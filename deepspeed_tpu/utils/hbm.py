"""HBM memory observatory (docs/hbm.md): attribute every HBM byte three ways
and reconcile them.

**measured** — the backend watermarks the compile watchdog already captures
(``memory_analysis()`` arg/out/temp per compiled program, ``memory_stats()``
in-use/peak per device, read through :func:`device_memory_stats`).

**parsed** — per-buffer attribution from the optimized program's entry layout
and donation tables (``utils/hlo.entry_buffer_table``). Each entry buffer is
classified into params / grads / optimizer state / comm error-feedback /
paged KV pool by matching its (dtype, per-device shape) against the multiset
of leaf signatures the engine declares via ``memory_manifest()`` — the memory
analogue of ``lint_programs()``. Classification is greedy in a fixed class
priority order; when two classes hold identical signatures (e.g. master and
Adam moments at ZeRO-2, all fp32 leaves scattered the same way) any
assignment swap moves identical byte counts, so per-class totals are
assignment-order independent.

**modeled** — a closed-form ZeRO-style predictor (PAPER.md's 2Ψ/2Ψ/12Ψ
accounting) parameterized by the manifest's geometry: (Ψ, dp, ZeRO stage,
sharded fraction, external-master shard, accumulation, remat policy, CE
chunking, serving pool geometry). Auxiliary buffers whose sizes are config
shapes rather than ZeRO formulas (comm EF buckets, KV pools) are modeled
from the declared shapes — still pre-compile configuration, so parsing the
compiled HLO against them remains a real cross-check.

The registry sweep (``ds-tpu hbm``) runs all three over every lint-registry
entry and gates parsed-vs-modeled within a pinned tolerance; ``--forecast``
is the pure-host feasibility predicate that re-derives the round-5 OOM
frontier (PERF.md) without executing anything — the prerequisite the
autotuner's config pruning needs (ROADMAP item 3).
"""

import argparse
import json
import sys
from collections import Counter
from typing import Any, Dict, List, Optional

HBM_REPORT_VERSION = 1
HBM_REPORT_KIND = "hbm_registry_sweep"

# parsed-vs-modeled reconciliation gate: relative slack for real divergence
# (layout padding, scalar optimizer fields), absolute slack so tiny classes
# aren't gated at sub-buffer granularity
HBM_REL_TOL = 0.02
HBM_ABS_TOL = 1024

# classification priority: persistent state first (params most recognizable),
# transient/auxiliary last. Order only matters when class signatures collide,
# and colliding assignments are byte-neutral (see module docstring).
CLASS_PRIORITY = ("params", "master", "optimizer", "grads", "comm_ef",
                  "kv_pool", "draft_params", "draft_pool")

# jnp dtype name -> HLO element type (mirrors lint/program_passes._HLO_DTYPE;
# kept local so utils does not import the lint package at module scope)
_HLO_DTYPE = {"float32": "f32", "float16": "f16", "bfloat16": "bf16",
              "float64": "f64", "int32": "s32", "int64": "s64", "int16": "s16",
              "int8": "s8", "uint32": "u32", "uint64": "u64", "uint16": "u16",
              "uint8": "u8", "bool": "pred"}
_DTYPE_ITEMSIZE = {"f64": 8, "s64": 8, "u64": 8, "f32": 4, "s32": 4, "u32": 4,
                   "f16": 2, "bf16": 2, "s16": 2, "u16": 2, "s8": 1, "u8": 1,
                   "pred": 1}

GIB = 2 ** 30


# --------------------------------------------------------------- measured
def device_memory_stats(device=None) -> Optional[Dict[str, int]]:
    """``memory_stats()`` of one device (default: local device 0), or None
    where the backend doesn't report them. Contract: CPU returns None; TPU and
    GPU report at least ``bytes_in_use`` / ``peak_bytes_in_use``. This is THE
    memory_stats read for the whole package — runtime/utils.see_memory_usage,
    utils/timer.memory_usage, telemetry.hbm_stats and the cluster heartbeat
    row all delegate here, so the None-on-CPU behavior is pinned once."""
    try:
        import jax
        if device is None:
            device = jax.local_devices()[0]
        stats = device.memory_stats()
    except Exception:
        return None
    return dict(stats) if stats else None


# ----------------------------------------------------------------- parsed
def leaf_signature(leaf):
    """(hlo_dtype, per-device shape, per-device bytes) of one manifest leaf.

    Entry parameters of a jitted SPMD program carry post-partitioning
    per-device shapes, so a sharded leaf must be signed by its shard shape
    (``sharding.shard_shape``), not its global shape."""
    import numpy as np
    dtype = np.dtype(leaf.dtype)
    shape = tuple(int(d) for d in leaf.shape)
    sharding = getattr(leaf, "sharding", None)
    if sharding is not None:
        try:
            shape = tuple(int(d) for d in sharding.shard_shape(shape))
        except Exception:
            pass
    n = 1
    for d in shape:
        n *= d
    hdt = _HLO_DTYPE.get(dtype.name, dtype.name)
    return (hdt, shape, n * _DTYPE_ITEMSIZE.get(hdt, dtype.itemsize))


def manifest_signatures(manifest):
    """(signatures, class_bytes) of a ``memory_manifest()`` dict:
    ``signatures[cls]`` is the Counter of (dtype, per-device shape) leaf
    signatures, ``class_bytes[cls]`` the class's total per-device bytes."""
    import jax
    signatures, class_bytes = {}, {}
    for cls, tree in (manifest.get("classes") or {}).items():
        counter = Counter()
        total = 0
        for leaf in jax.tree_util.tree_leaves(tree):
            dt, shape, b = leaf_signature(leaf)
            counter[(dt, shape)] += 1
            total += b
        signatures[cls] = counter
        class_bytes[cls] = total
    return signatures, class_bytes


def classify_program(hlo_text, signatures):
    """Attribute one optimized program's entry buffers against the manifest.

    Returns ``{"by_class": {cls: bytes}, "other_bytes", "parameter_bytes",
    "unaliased_result_bytes", "temp_estimate_bytes"}``. Each program gets a
    fresh copy of every class's signature multiset — the same resident buffer
    (params, pools) legitimately appears in several programs."""
    from . import hlo
    table = hlo.entry_buffer_table(hlo_text)
    remaining = {cls: Counter(c) for cls, c in signatures.items()}
    by_class = {cls: 0 for cls in signatures}
    other = 0
    for p in table["parameters"]:
        for dt, dims, b in p["leaves"]:
            key = (dt, tuple(dims))
            for cls in CLASS_PRIORITY:
                if remaining.get(cls, Counter()).get(key, 0) > 0:
                    remaining[cls][key] -= 1
                    by_class[cls] += b
                    break
            else:
                for cls in remaining:   # manifest classes outside the priority
                    if cls not in CLASS_PRIORITY and remaining[cls].get(key, 0) > 0:
                        remaining[cls][key] -= 1
                        by_class[cls] += b
                        break
                else:
                    other += b
    return {
        "by_class": {c: int(b) for c, b in by_class.items()},
        "other_bytes": int(other),
        "parameter_bytes": int(table["parameter_bytes"]),
        "unaliased_result_bytes": int(table["unaliased_result_bytes"]),
        "temp_estimate_bytes": int(hlo.temp_allocation_estimate(hlo_text)),
    }


def attribute_programs(program_reports):
    """Entry-level parsed attribution: per-class MAX over the entry's
    programs. The classes are resident state threaded through every program
    that touches it, so the live footprint of a class is the largest single
    appearance, not the sum."""
    parsed = {}
    for rep in program_reports:
        for cls, b in rep["by_class"].items():
            parsed[cls] = max(parsed.get(cls, 0), b)
    return parsed


# ---------------------------------------------------------------- modeled
def modeled_classes(geometry) -> Dict[str, int]:
    """Closed-form per-device byte prediction per class from a manifest's
    geometry dict — the ZeRO accounting (params Ψ·bytes, grads Ψ·bytes/dp at
    stage ≥ 2, master 4Ψ/dp + moments 8Ψ/dp at stage ≥ 1, i.e. the paper's
    2Ψ/2Ψ/12Ψ split) with the engine's measured sharded-coverage fraction in
    place of the ideal 1/dp, plus shape-derived sizes for auxiliary buffers
    (comm error-feedback, paged KV pools)."""
    kind = geometry.get("kind", "training")
    out: Dict[str, int] = {}
    if kind == "serving":
        psi = int(geometry["psi"])
        ib = int(geometry["param_itemsize"])
        pf = float(geometry.get("param_per_device_fraction", 1.0))
        out["params"] = int(round(psi * ib * pf))
        g = geometry.get("pool")
        if g:
            pool = (2 * g["n_layer"] * g["num_blocks"] * g["block_size"]
                    * g["n_head"] * g["head_dim"] * g["itemsize"])
            out["kv_pool"] = int(pool // max(int(g.get("shard_factor", 1)), 1))
        d = geometry.get("draft")
        if d:
            out["draft_params"] = int(d["psi"] * d["param_itemsize"])
            dp_ = d["pool"]
            out["draft_pool"] = int(2 * dp_["n_layer"] * dp_["num_blocks"]
                                    * dp_["block_size"] * dp_["n_head"]
                                    * dp_["head_dim"] * dp_["itemsize"])
        return out
    if kind == "decode":
        out["params"] = int(geometry["psi"]) * int(geometry["param_itemsize"])
        return out
    if kind == "pipeline_local":
        # instruction-executor pipeline: per-stage LOCAL programs — the live
        # param working set of any one program is the largest stage subtree
        out["params"] = int(geometry["stage_param_bytes_max"])
        return out

    psi = int(geometry["psi"])
    dp = max(int(geometry.get("dp", 1)), 1)
    stage = int(geometry.get("zero_stage", 0))
    zsf = geometry.get("zero_sharded_fraction")
    zsf = 1.0 if zsf is None else float(zsf)

    def frac(threshold):
        # sharded coverage zsf of the bytes scale 1/dp, the rest replicate
        if stage >= threshold and dp > 1:
            return 1.0 - zsf + zsf / dp
        return 1.0

    out["params"] = int(round(psi * int(geometry["param_itemsize"]) * frac(3)))
    if not geometry.get("fused", False) or geometry.get("offload", False):
        # two-jit / accumulation / offload paths hand grads between programs
        # as a resident buffer; the fused step keeps the grad tree internal so
        # XLA frees each leaf as the optimizer consumes it (PERF.md round 5)
        out["grads"] = int(round(psi * int(geometry["grad_itemsize"])
                                 * frac(2)))
    if geometry.get("offload", False):
        pass          # master + moments live in host DRAM: zero device bytes
    elif geometry.get("external_master", False):
        # client-owned flat shard: master + m1 + m2 fp32, replicated (client
        # state does not mirror the param tree, so ZeRO cannot scatter it)
        out["optimizer"] = int(3 * int(geometry["master_numel"]) * 4)
    else:
        out["master"] = int(round(4 * psi * frac(1)))
        out["optimizer"] = int(round(8 * psi * frac(1)))
    ef = int(geometry.get("comm_ef_bytes", 0))
    if ef:
        out["comm_ef"] = ef
    return out


def reconcile(parsed, modeled, class_bytes=None, rel_tol=HBM_REL_TOL,
              abs_tol=HBM_ABS_TOL):
    """Per-class reconciliation verdicts. A class is gated when the parsed
    attribution observed it (parsed > 0); a modeled-but-never-parsed class is
    ``unobserved`` (resident state outside the captured program set — e.g.
    the target pools of a spec-programs-only registry entry), which is not
    drift. Returns ``(classes, ok)``."""
    classes = {}
    ok = True
    for cls in sorted(set(parsed) | set(modeled)):
        p = int(parsed.get(cls, 0))
        m = int(modeled.get(cls, 0))
        row = {"parsed_bytes": p, "modeled_bytes": m}
        if class_bytes is not None:
            row["manifest_bytes"] = int(class_bytes.get(cls, 0))
        if p == 0 and m > 0:
            row["status"] = "unobserved"
        elif abs(p - m) <= max(abs_tol, rel_tol * max(p, m)):
            row["status"] = "ok"
        else:
            row["status"] = "drift"
            ok = False
        classes[cls] = row
    return classes, ok


# --------------------------------------------------------------- forecast
# Calibrated activation residency per remat policy, in units of
# n_embd-equivalents per token-layer (bf16). 'dots' = 8 is physically exact
# for the GPT-2 block: saved qkv (3E) + attention proj input (E) + mlp fc
# output (4E); policies saving more residuals sit above it, and XLA's own
# scheduler under 'none'/'flash' holds ~3E live. Calibrated against — and
# verified to binary-classify — every cell of the round-5 sweep (PERF.md).
REMAT_ACT_UNITS = {"none": 3, "flash": 3, "attn": 4, "dots": 8,
                   "dots+attn": 10, "dots+attn-lean": 12}

# fixed XLA workspace + fragmentation allowance at the 1.5B scale
FORECAST_WORKSPACE_BYTES = 1 * GIB


def gpt2_param_count(n_embd, n_layer, vocab_size, n_positions):
    """Exact GPT-2 Ψ: wte + wpe + per-block (12E² + 13E) + final LN (2E)."""
    e = int(n_embd)
    return (int(vocab_size) * e + int(n_positions) * e
            + int(n_layer) * (12 * e * e + 13 * e) + 2 * e)


def forecast(config) -> Dict[str, Any]:
    """Feasibility predicate for one training config — per-chip peak HBM
    prediction and fit/OOM verdict, without compiling or executing anything.

    ``config`` keys: ``model`` {n_embd, n_layer, vocab_size, n_positions,
    psi?}, ``remat`` (REMAT_ACT_UNITS key), ``batch_per_device``, ``seq_len``,
    ``ce_chunk`` (0 = unchunked), ``external_master_shards`` (0 = internal
    12Ψ/dp master+opt with ``dp``), ``dp``, ``budget_gib``.

    The prediction is BINARY by design: margins near the cliff are not
    comparable to XLA's real peak (scheduling is non-monotonic there —
    round 5 measured a policy that frees more yet peaks higher), but the
    fit/OOM frontier itself reproduces the round-5 sweep exactly."""
    m = config["model"]
    e, layers = int(m["n_embd"]), int(m["n_layer"])
    vocab, positions = int(m["vocab_size"]), int(m["n_positions"])
    psi = int(m.get("psi") or gpt2_param_count(e, layers, vocab, positions))
    remat = str(config.get("remat", "none"))
    if remat not in REMAT_ACT_UNITS:
        raise ValueError(f"unknown remat policy {remat!r}; expected one of "
                         f"{sorted(REMAT_ACT_UNITS)}")
    batch = int(config["batch_per_device"])
    seq = int(config.get("seq_len", positions))
    chunk = int(config.get("ce_chunk", 0)) or seq
    shards = int(config.get("external_master_shards", 0))
    dp = max(int(config.get("dp", 1)), 1)
    budget = int(round(float(config.get("budget_gib", 15.75)) * GIB))

    params_b = 2 * psi                                   # bf16 compute params
    opt_frac = (1.0 / shards) if shards else (1.0 / dp)
    master_opt_b = int(round(12 * psi * opt_frac))       # fp32 master + Adam
    acts_b = REMAT_ACT_UNITS[remat] * batch * seq * layers * e * 2
    logits_b = batch * chunk * vocab * 4                 # f32 CE chunk
    total = (params_b + master_opt_b + acts_b + logits_b
             + FORECAST_WORKSPACE_BYTES)
    return {
        "psi": psi,
        "classes": {"params": params_b, "master_opt": master_opt_b,
                    "activations": acts_b, "logits": logits_b,
                    "workspace": FORECAST_WORKSPACE_BYTES},
        "predicted_peak_bytes": int(total),
        "budget_bytes": budget,
        "fits": total <= budget,
        "headroom_bytes": int(budget - total),
    }


def smallest_fitting_delta(config) -> List[Dict[str, Any]]:
    """Single-knob config deltas predicted to fit, for an OOMed config —
    ordered cheapest-change first (chunk the CE loss, then a leaner remat
    policy, then smaller batch). Empty when the config already fits or no
    single knob rescues it."""
    base = forecast(config)
    if base["fits"]:
        return []
    out = []
    m = config["model"]
    seq = int(config.get("seq_len", int(m["n_positions"])))
    chunk = int(config.get("ce_chunk", 0)) or seq
    for cand in (256, 128, 64):
        if cand < chunk:
            trial = dict(config, ce_chunk=cand)
            f = forecast(trial)
            if f["fits"]:
                out.append({"change": "ce_chunk", "value": cand,
                            "predicted_peak_bytes": f["predicted_peak_bytes"]})
                break
    units = REMAT_ACT_UNITS[str(config.get("remat", "none"))]
    leaner = sorted(((u, p) for p, u in REMAT_ACT_UNITS.items() if u < units),
                    reverse=True)
    for _u, policy in leaner:
        f = forecast(dict(config, remat=policy))
        if f["fits"]:
            out.append({"change": "remat", "value": policy,
                        "predicted_peak_bytes": f["predicted_peak_bytes"]})
            break
    for b in range(int(config["batch_per_device"]) - 1, 0, -1):
        f = forecast(dict(config, batch_per_device=b))
        if f["fits"]:
            out.append({"change": "batch_per_device", "value": b,
                        "predicted_peak_bytes": f["predicted_peak_bytes"]})
            break
    return out


# The round-5 manual sweep (PERF.md): GPT-2 1.5B, T=1024, one 15.75 GiB v5e
# chip, external-master 1/32 fp32 shard, fused step. (remat, batch, ce_chunk,
# oomed). --forecast round5 re-derives this frontier offline and exits 1 on
# any misclassification — the acceptance gate for the predictor.
ROUND5_MODEL = {"n_embd": 1600, "n_layer": 48, "vocab_size": 50304,
                "n_positions": 1024}
ROUND5_BUDGET_GIB = 15.75
ROUND5_SHARDS = 32
ROUND5_WINNER = ("none", 3, 1024)
ROUND5_SWEEP = [
    ("dots", 8, 128, False),
    ("dots+attn", 8, 128, True),
    ("dots+attn", 8, 256, True),
    ("dots+attn", 8, 64, True),
    ("dots+attn-lean", 8, 128, True),
    ("flash", 8, 64, False),
    ("attn", 8, 128, False),
    ("none", 8, 128, False),
    ("none", 6, 128, False),
    ("none", 4, 128, False),
    ("none", 8, 1024, False),
    ("none", 6, 1024, False),
    ("none", 4, 256, False),
    ("none", 4, 512, False),
    ("none", 4, 1024, False),
    ("dots+attn", 4, 1024, False),
    ("none", 2, 1024, False),
    ("none", 3, 1024, False),
]


def forecast_round5() -> Dict[str, Any]:
    """Run the predictor over every round-5 sweep cell and diff the verdicts
    against the measured outcomes. ``ok`` iff every OOMed config is predicted
    infeasible AND every config that ran (the winner included) is predicted
    feasible — the frontier re-derived offline."""
    cells = []
    mismatches = []
    for remat, batch, chunk, oomed in ROUND5_SWEEP:
        cfg = {"model": dict(ROUND5_MODEL), "remat": remat,
               "batch_per_device": batch, "seq_len": 1024,
               "ce_chunk": 0 if chunk >= 1024 else chunk,
               "external_master_shards": ROUND5_SHARDS,
               "budget_gib": ROUND5_BUDGET_GIB}
        f = forecast(cfg)
        agree = f["fits"] == (not oomed)
        cells.append({"remat": remat, "batch": batch, "ce_chunk": chunk,
                      "measured_oom": oomed, "predicted_fits": f["fits"],
                      "predicted_peak_bytes": f["predicted_peak_bytes"],
                      "agree": agree})
        if not agree:
            mismatches.append(f"{remat}@{batch},c{chunk}: measured "
                              f"{'OOM' if oomed else 'fit'} but predicted "
                              f"{'fit' if f['fits'] else 'OOM'}")
    winner = next(c for c in cells
                  if (c["remat"], c["batch"], c["ce_chunk"]) == ROUND5_WINNER)
    return {
        "version": HBM_REPORT_VERSION,
        "kind": "hbm_forecast_round5",
        "budget_gib": ROUND5_BUDGET_GIB,
        "cells": cells,
        "winner": {"config": list(ROUND5_WINNER),
                   "predicted_fits": winner["predicted_fits"]},
        "mismatches": mismatches,
        "ok": not mismatches,
    }


# ----------------------------------------------------------- OOM forensics
def oom_forensics(snapshot) -> Dict[str, Any]:
    """Flight-recorder memory block: the per-class resident bytes largest
    first, the device watermarks, and — when the engine registered a
    forecastable config — the smallest single-knob deltas predicted to fit.
    Pure host dict-shuffling over an already-captured snapshot."""
    classes = dict(snapshot.get("classes") or {})
    out = {
        "classes": {c: int(b) for c, b in classes.items()},
        "largest_classes": [
            {"class": c, "bytes": int(b)}
            for c, b in sorted(classes.items(), key=lambda kv: (-kv[1], kv[0]))
        ],
    }
    measured = snapshot.get("measured")
    if measured:
        out["measured"] = {k: int(v) for k, v in measured.items()
                           if isinstance(v, (int, float))}
    if snapshot.get("temp_peak_bytes"):
        out["compiled_temp_bytes_peak"] = int(snapshot["temp_peak_bytes"])
    cfg = snapshot.get("forecast_config")
    if cfg:
        try:
            f = forecast(cfg)
            out["forecast"] = {"predicted_peak_bytes": f["predicted_peak_bytes"],
                               "budget_bytes": f["budget_bytes"],
                               "fits": f["fits"]}
            if not f["fits"]:
                out["fitting_deltas"] = smallest_fitting_delta(cfg)
        except Exception as e:           # forensics must never mask the crash
            out["forecast_error"] = repr(e)
    return out


# ------------------------------------------------------------ registry sweep
def sweep_entry(entry, builders=None, rel_tol=HBM_REL_TOL,
                abs_tol=HBM_ABS_TOL) -> Dict[str, Any]:
    """Measured + parsed + modeled attribution for one lint-registry entry.

    Builds the entry's engine, captures its step programs AOT (the same
    ``ProgramArtifact.capture`` path lint uses, so ``memory_analysis``
    watermarks ride along), classifies every program's entry buffers against
    the engine's ``memory_manifest()``, and reconciles the per-class maxima
    against the closed-form model."""
    from ..lint.program_passes import ProgramArtifact
    if builders is None:
        from ..lint.registry import BUILDERS as builders
    engine, batch = builders[entry]()
    manifest_fn = getattr(engine, "memory_manifest", None)
    manifest = manifest_fn() if manifest_fn is not None else {"classes": {},
                                                              "geometry": {}}
    signatures, class_bytes = manifest_signatures(manifest)
    programs = {}
    for name, jitted, args, man in engine.lint_programs(batch):
        artifact = ProgramArtifact.capture(f"{entry}:{name}", jitted, args,
                                           man)
        rep = classify_program(artifact.hlo_text, signatures)
        rep["measured"] = {k: int(v) for k, v in artifact.memory_stats.items()}
        programs[name] = rep
    parsed = attribute_programs(programs.values())
    geometry = dict(manifest.get("geometry") or {})
    modeled = modeled_classes(geometry) if geometry else {}
    classes, ok = reconcile(parsed, modeled, class_bytes,
                            rel_tol=rel_tol, abs_tol=abs_tol)
    return {
        "geometry": geometry,
        "classes": classes,
        "programs": programs,
        "activations": {
            "temp_estimate_bytes_max": max(
                (p["temp_estimate_bytes"] for p in programs.values()),
                default=0),
            "measured_temp_bytes_max": max(
                (p["measured"].get("temp_size_in_bytes", 0)
                 for p in programs.values()), default=0),
        },
        "reconciled": ok,
    }


def sweep_registry(entries=None, rel_tol=HBM_REL_TOL,
                   abs_tol=HBM_ABS_TOL) -> Dict[str, Any]:
    """The full sweep report over the lint registry (default: every entry)."""
    from ..lint.registry import BUILDERS
    names = sorted(BUILDERS) if not entries else list(entries)
    out_entries = {}
    errors = []
    for entry in names:
        try:
            out_entries[entry] = sweep_entry(entry, rel_tol=rel_tol,
                                             abs_tol=abs_tol)
        except Exception as e:
            errors.append(f"{entry}: sweep failed: {e}")
    drift = sorted(e for e, rep in out_entries.items()
                   if not rep["reconciled"])
    return {
        "version": HBM_REPORT_VERSION,
        "kind": HBM_REPORT_KIND,
        "tolerance": {"rel": rel_tol, "abs": abs_tol},
        "entries": out_entries,
        "drift_entries": drift,
        "errors": sorted(errors),
        "ok": not errors and not drift,
    }


def stable_projection(report) -> Dict[str, Any]:
    """The golden-pinnable slice of a sweep report: parsed/modeled per-class
    bytes, reconciliation verdicts, and entry-layout byte totals — all pure
    functions of the abstract manifests and the entry computation layout on
    the pinned 8-device CPU mesh. Measured watermarks and the temp-liveness
    estimate are excluded (they move with the XLA scheduler)."""
    entries = {}
    for entry, rep in report["entries"].items():
        entries[entry] = {
            "classes": rep["classes"],
            "reconciled": rep["reconciled"],
            "programs": {
                name: {"by_class": p["by_class"],
                       "other_bytes": p["other_bytes"],
                       "parameter_bytes": p["parameter_bytes"]}
                for name, p in rep["programs"].items()
            },
        }
    return {
        "version": report["version"],
        "kind": report["kind"] + "_golden",
        "tolerance": report["tolerance"],
        "entries": entries,
        "drift_entries": report["drift_entries"],
        "ok": report["ok"],
    }


def diff_reports(old, new, rel_tol=HBM_REL_TOL,
                 abs_tol=HBM_ABS_TOL) -> Dict[str, Any]:
    """Cross-run regression gate over two sweep reports (full or golden
    projection): any class whose parsed bytes GREW beyond tolerance, any
    entry that newly drifted, and any entry/class that disappeared."""
    regressions = []
    o_entries = old.get("entries", {})
    n_entries = new.get("entries", {})
    for entry in sorted(o_entries):
        if entry not in n_entries:
            regressions.append(f"{entry}: entry disappeared")
            continue
        o_rep, n_rep = o_entries[entry], n_entries[entry]
        if o_rep.get("reconciled", True) and not n_rep.get("reconciled", True):
            regressions.append(f"{entry}: newly drifted "
                               "(parsed vs modeled out of tolerance)")
        o_cls = o_rep.get("classes", {})
        n_cls = n_rep.get("classes", {})
        for cls in sorted(o_cls):
            ob = int(o_cls[cls].get("parsed_bytes", 0))
            nb = int(n_cls.get(cls, {}).get("parsed_bytes", 0))
            if nb > ob + max(abs_tol, rel_tol * ob):
                regressions.append(
                    f"{entry}/{cls}: parsed bytes grew {ob} -> {nb} "
                    f"(+{nb - ob})")
    return {"version": HBM_REPORT_VERSION, "kind": "hbm_diff",
            "regressions": regressions, "ok": not regressions}


# ------------------------------------------------------------------- CLI
def _load_json(path):
    with open(path) as f:
        return json.load(f)


def hbm_main(argv=None):
    """``ds-tpu hbm`` — the memory observatory CLI. Default: the registry
    sweep (per-program attribution + reconciliation gate, exit 1 on drift).
    ``--forecast round5|CONFIG.json`` and ``--diff A B`` are pure-host modes
    that never build an engine."""
    parser = argparse.ArgumentParser(
        prog="ds-tpu hbm",
        description="HBM attribution: measured vs parsed vs modeled over the "
                    "lint registry; offline OOM feasibility forecasts")
    parser.add_argument("--json", action="store_true",
                        help="emit the full report as JSON on stdout")
    parser.add_argument("--out", metavar="PATH",
                        help="also write the JSON report to PATH")
    parser.add_argument("--golden-out", metavar="PATH",
                        help="write the stable (golden-pinnable) projection "
                             "of the sweep to PATH")
    parser.add_argument("--entry", action="append", metavar="NAME",
                        help="limit the sweep to a lint-registry entry "
                             "(repeatable; default: every entry)")
    parser.add_argument("--tolerance", type=float, default=HBM_REL_TOL,
                        help="parsed-vs-modeled relative tolerance "
                             "(default: %(default)s)")
    parser.add_argument("--forecast", metavar="CONFIG",
                        help="feasibility forecast: 'round5' re-derives the "
                             "round-5 OOM frontier, else a JSON config path")
    parser.add_argument("--budget-gib", type=float, default=0.0,
                        help="override the forecast config's HBM budget")
    parser.add_argument("--diff", nargs=2, metavar=("OLD", "NEW"),
                        help="compare two sweep reports; exit 1 on parsed-"
                             "byte growth beyond tolerance")
    args = parser.parse_args(argv)

    # stdout belongs to the report (same contract as ds-tpu lint/anatomy)
    import logging
    for h in logging.getLogger("DeepSpeedTPU").handlers:
        if isinstance(h, logging.StreamHandler) and h.stream is sys.stdout:
            h.stream = sys.stderr

    if args.diff:
        report = diff_reports(_load_json(args.diff[0]),
                              _load_json(args.diff[1]),
                              rel_tol=args.tolerance)
    elif args.forecast == "round5":
        report = forecast_round5()
    elif args.forecast:
        cfg = _load_json(args.forecast)
        if args.budget_gib:
            cfg["budget_gib"] = args.budget_gib
        report = forecast(cfg)
        report.update({"version": HBM_REPORT_VERSION, "kind": "hbm_forecast",
                       "ok": True})
        if not report["fits"]:
            report["fitting_deltas"] = smallest_fitting_delta(cfg)
    else:
        report = sweep_registry(args.entry, rel_tol=args.tolerance)

    text = json.dumps(report, indent=2, sort_keys=True) + "\n"
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
    if args.golden_out and report.get("kind") == HBM_REPORT_KIND:
        with open(args.golden_out, "w") as f:
            f.write(json.dumps(stable_projection(report), indent=2,
                               sort_keys=True) + "\n")
    if args.json:
        sys.stdout.write(text)
    else:
        _print_report(report)
    return 0 if report.get("ok", True) else 1


def _print_report(report):
    kind = report.get("kind")
    if kind == HBM_REPORT_KIND:
        for entry in sorted(report["entries"]):
            rep = report["entries"][entry]
            verdict = "ok" if rep["reconciled"] else "DRIFT"
            print(f"{entry}: [{verdict}]")
            for cls, row in sorted(rep["classes"].items()):
                print(f"  {cls:<14} parsed {row['parsed_bytes']:>12,} B  "
                      f"modeled {row['modeled_bytes']:>12,} B  "
                      f"[{row['status']}]")
            act = rep["activations"]
            print(f"  {'activations':<14} temp est "
                  f"{act['temp_estimate_bytes_max']:>9,} B  measured temp "
                  f"{act['measured_temp_bytes_max']:>9,} B")
        for e in report["errors"]:
            print(f"ERROR {e}")
        print(f"{len(report['entries'])} entr(ies), "
              f"{len(report['drift_entries'])} drifted, "
              f"{len(report['errors'])} error(s)")
    elif kind == "hbm_forecast_round5":
        for c in report["cells"]:
            mark = "ok" if c["agree"] else "MISMATCH"
            print(f"{c['remat']}@{c['batch']},c{c['ce_chunk']}: predicted "
                  f"{'fit' if c['predicted_fits'] else 'OOM'} "
                  f"({c['predicted_peak_bytes'] / GIB:.2f} GiB), measured "
                  f"{'OOM' if c['measured_oom'] else 'fit'} [{mark}]")
        print(f"winner {report['winner']['config']}: predicted "
              f"{'fit' if report['winner']['predicted_fits'] else 'OOM'}; "
              f"{len(report['mismatches'])} mismatch(es)")
    elif kind == "hbm_forecast":
        for cls, b in sorted(report["classes"].items()):
            print(f"  {cls:<12} {b / GIB:>8.3f} GiB")
        print(f"predicted peak {report['predicted_peak_bytes'] / GIB:.3f} GiB "
              f"vs budget {report['budget_bytes'] / GIB:.2f} GiB -> "
              f"{'FITS' if report['fits'] else 'OOM'}")
        for d in report.get("fitting_deltas", []):
            print(f"  delta: {d['change']} -> {d['value']} "
                  f"({d['predicted_peak_bytes'] / GIB:.3f} GiB)")
    elif kind == "hbm_diff":
        for r in report["regressions"]:
            print(f"REGRESSION {r}")
        print(f"{len(report['regressions'])} regression(s)")


if __name__ == "__main__":
    sys.exit(hbm_main())
