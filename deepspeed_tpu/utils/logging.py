"""Rank-aware logging.

TPU-native analog of the reference's ``deepspeed/utils/logging.py`` (LoggerFactory l.7,
``log_dist`` l.40): a single named logger plus a rank-filtered helper. Ranks here are JAX
process indices (``jax.process_index``) instead of torch.distributed ranks.
"""

import logging
import os
import sys
from typing import Iterable, Optional

_FORMAT = "[%(asctime)s] [%(levelname)s] [%(name)s] %(message)s"


class LoggerFactory:

    @staticmethod
    def create_logger(name: str = "DeepSpeedTPU", level: int = logging.INFO) -> logging.Logger:
        if name is None:
            raise ValueError("name for logger cannot be None")
        logger_ = logging.getLogger(name)
        logger_.setLevel(level)
        logger_.propagate = False
        if not logger_.handlers:
            handler = logging.StreamHandler(stream=sys.stdout)
            handler.setFormatter(logging.Formatter(_FORMAT))
            handler.setLevel(level)
            logger_.addHandler(handler)
        return logger_


logger = LoggerFactory.create_logger(
    level=getattr(logging, os.environ.get("DSTPU_LOG_LEVEL", "INFO").upper(), logging.INFO))


def _process_index() -> int:
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


def log_dist(message: str, ranks: Optional[Iterable[int]] = None, level: int = logging.INFO) -> None:
    """Log ``message`` only on the given process ranks (None or [-1] = all ranks)."""
    my_rank = _process_index()
    ranks = list(ranks) if ranks is not None else []
    should_log = not ranks or (-1 in ranks) or (my_rank in ranks)
    if should_log:
        logger.log(level, f"[Rank {my_rank}] {message}")
