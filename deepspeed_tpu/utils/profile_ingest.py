"""Measured-time profile observatory: ingest profiler traces, reconcile them
against anatomy's predicted schedule (docs/profile.md).

The anatomy observatory (utils/anatomy.py) *predicts* step-time structure —
overlap windows, exposed ICI/DCN, roofline MFU ceilings — and telemetry's
trace windows (utils/telemetry.py) capture the *measured* device timeline that
nothing previously read back. This module closes the loop: a stdlib-pure
parser for the trace-viewer JSON ``jax.profiler`` writes
(``plugins/profile/*/…trace.json.gz``) that classifies device-timeline slices
into compute / collective (ICI vs DCN) / host-gap per named scope
(``ds_grad_bucket{k}``, ``ds_fwd_bwd``, ``ds_apply_update``, ``ring_rot{r}``,
``ds_offload_*`` — the scopes the engines already thread), computes measured
exposed ICI/DCN per bucket window, per-program measured MFU (trace durations
x the compile watchdog's recorded flops), and the step-wall decomposition.

``reconcile_profile`` then pins three views per class within a stated
tolerance — **measured** (the trace), **predicted** (the compile watchdog's
HLO catalog: anatomy bucket-window pricing, collective instruction counts,
wire bytes), **derived** (TelemetrySession's step counters) — with verdicts
ok / drift / unobserved exactly like ``ds-tpu hbm``. Seconds measured on the
CPU test mesh can never numerically match the cpu-test ChipSpec's fictional
pricing, so the gated pairs are machine-INDEPENDENT: collective slice
executions per step per device vs HLO instruction counts, predicted vs
derived flops and wire bytes. Wall-clock seconds are reported, never gated
(except step-wall at a generous sanity tolerance) and never golden-pinned.

Parsing is stdlib-only (``gzip``/``json``/``re``); the HLO-catalog and
reconcile-runner helpers lazily import ``.hlo`` / the engine stack, so a
post-mortem box can ingest and diff traces with no accelerator runtime.
"""

import argparse
import glob
import gzip
import json
import os
import re
import sys

PROFILE_REPORT_VERSION = 1
PROFILE_REPORT_KIND = "profile_report"
PROFILE_RECONCILE_KIND = "profile_reconcile"
PROFILE_DIFF_KIND = "profile_diff"

# measured-vs-predicted-vs-derived reconciliation slack for the gated
# machine-independent pairs (counts, flops, wire bytes)
PROFILE_REL_TOL = 0.05
# step-wall sanity gate: measured trace extent vs telemetry's host step wall.
# Generous on purpose — both are real seconds on the same host, but profiler
# overhead and the window's first/last step edges land inside it.
PROFILE_STEP_WALL_REL_TOL = 0.5

# the named scopes the engines thread (engine.py, comm/hierarchical.py,
# runtime/ring.py, runtime/offload.py, parallel/pipe engines) — kept textually
# in sync with the emitting sites by tests/unit/test_profile_ingest.py
SCOPE_RE = re.compile(
    r"(ds_grad_bucket\d+|ds_fwd_bwd|ds_accumulate|ds_apply_update"
    r"|ring_rot\d+|ds_offload_\w+|ds_pipe_\w+)")
_BUCKET_SCOPE_RE = re.compile(r"ds_grad_bucket(\d+)")

# collective HLO op-name prefixes, mirroring hlo.COLLECTIVE_OPS (kept local so
# trace ingestion stays stdlib-pure). Order matters: longest prefixes first so
# ``all-reduce-start.3`` doesn't half-match.
COLLECTIVE_PREFIXES = ("all-to-all", "all-gather", "all-reduce",
                      "reduce-scatter", "collective-permute")

# namespaced trace dirs (mirrors the flight-recorder dump naming,
# utils/numerics.py): trace_<run>_host<h>/ under the configured trace_dir.
# run ids are _sanitize_token'd (no underscores), so the split is unambiguous.
_TRACE_DIR_RE = re.compile(r"^trace_(?P<run>[^_]+)_host(?P<host>\d+)$")


class ProfileParseError(ValueError):
    """A trace file or directory that cannot be ingested — malformed JSON,
    truncated gzip, or a JSON object that is not a trace-viewer bundle. The
    parser refuses loudly instead of returning a silently-empty report."""


# ----------------------------------------------------------------- discovery
def scan_trace_dirs(trace_dir):
    """Enumerate the per-run trace directories under a configured
    ``telemetry.trace_dir``: ``[{"run", "host", "path"}]`` sorted by
    (run, host). Namespaced layout is ``trace_<run>_host<h>/``; a legacy
    un-namespaced layout (``trace_dir/plugins/profile`` directly — traces
    written before the namespacing, or sessions configured with
    ``run_id=""``) reports as ``{"run": "", "host": 0}``."""
    out = []
    if not os.path.isdir(trace_dir):
        return out
    if os.path.isdir(os.path.join(trace_dir, "plugins", "profile")):
        out.append({"run": "", "host": 0, "path": trace_dir})
    for name in sorted(os.listdir(trace_dir)):
        m = _TRACE_DIR_RE.match(name)
        path = os.path.join(trace_dir, name)
        if m and os.path.isdir(path):
            out.append({"run": m.group("run"), "host": int(m.group("host")),
                        "path": path})
    out.sort(key=lambda d: (d["run"], d["host"]))
    return out


def find_trace_files(path):
    """Trace-viewer JSON files under one trace directory (the
    ``plugins/profile/<timestamp>/*.trace.json.gz`` layout ``jax.profiler``
    writes), newest session last. Accepts a direct file path too."""
    if os.path.isfile(path):
        return [path]
    pats = [os.path.join(path, "plugins", "profile", "*", "*.trace.json.gz"),
            os.path.join(path, "plugins", "profile", "*", "*.trace.json")]
    files = []
    for pat in pats:
        files.extend(glob.glob(pat))
    return sorted(files)


def load_trace(path):
    """Parse one trace-viewer JSON (plain or gzipped). Returns the decoded
    dict; raises :class:`ProfileParseError` on truncated/undecodable input or
    when the payload is not a ``traceEvents`` bundle."""
    try:
        if path.endswith(".gz"):
            with gzip.open(path, "rt", encoding="utf-8", errors="replace") as f:
                data = json.load(f)
        else:
            with open(path, encoding="utf-8", errors="replace") as f:
                data = json.load(f)
    except (OSError, EOFError, ValueError) as e:
        raise ProfileParseError(f"unreadable trace {path!r}: {e}") from e
    if not isinstance(data, dict) or not isinstance(
            data.get("traceEvents"), list):
        raise ProfileParseError(
            f"{path!r} is not a trace-viewer bundle (no traceEvents list)")
    return data


def load_trace_dir(path):
    """Load every trace file of one trace dir (one file per profiled host
    process) and return ``(merged event list, [file paths])``. Raises
    :class:`ProfileParseError` when the directory holds no trace files."""
    files = find_trace_files(path)
    if not files:
        raise ProfileParseError(
            f"no trace files under {path!r} (expected "
            "plugins/profile/<session>/*.trace.json.gz)")
    events = []
    for f in files:
        events.extend(load_trace(f)["traceEvents"])
    return events, files


# ------------------------------------------------------------ classification
def device_slices(events):
    """The device-timeline slices of a trace: every complete ("X") event
    carrying an ``hlo_op`` arg — one slice per HLO instruction execution per
    device. Host-side python/runtime spans (no ``hlo_op``) are dropped here
    and accounted only through the host-gap class. Returns
    ``[{"module", "op", "ts", "dur"}]`` in timestamp order."""
    out = []
    for e in events:
        if e.get("ph") != "X":
            continue
        args = e.get("args")
        if not isinstance(args, dict) or "hlo_op" not in args:
            continue
        try:
            ts = float(e["ts"])
            dur = float(e.get("dur", 0.0))
        except (KeyError, TypeError, ValueError):
            continue
        out.append({"module": str(args.get("hlo_module", "")),
                    "op": str(args["hlo_op"]), "ts": ts, "dur": dur})
    out.sort(key=lambda s: (s["ts"], s["op"]))
    return out


def is_collective_op(op_name):
    """True when an ``hlo_op`` slice name is a collective instruction
    (``all-reduce.8``, ``reduce-scatter-start.2``, ...)."""
    return op_name.startswith(COLLECTIVE_PREFIXES)


def slice_scope(s, catalog=None):
    """Named scope of one device slice, or None. The per-program HLO catalog
    (``op_name`` metadata parsed at compile time) is authoritative — CPU
    traces carry bare instruction names. TPU traces prefix the scope path in
    the op name itself; the regex fallback covers those with no catalog."""
    if catalog:
        prog = catalog.get(s["module"])
        if prog:
            scope = prog.get("scopes", {}).get(s["op"])
            if scope:
                return scope
    m = SCOPE_RE.search(s["op"])
    return m.group(1) if m else None


def slice_level(s, catalog=None):
    """"ici" or "dcn" for a collective slice: the catalog's per-instruction
    replica-group classification when available (the same membership rule as
    ``hlo.collective_axis_bytes``), else ICI — the single-slice default the
    wire-byte ledger uses."""
    if catalog:
        prog = catalog.get(s["module"])
        if prog:
            row = prog.get("collectives", {}).get(s["op"])
            if row:
                return row["level"]
    return "ici"


# ------------------------------------------------------------- interval math
def _union(intervals):
    """Merge (start, end) intervals; returns the disjoint sorted union."""
    ivs = sorted((a, b) for a, b in intervals if b > a)
    out = []
    for a, b in ivs:
        if out and a <= out[-1][1]:
            if b > out[-1][1]:
                out[-1] = (out[-1][0], b)
        else:
            out.append((a, b))
    return out


def _union_len(merged):
    return sum(b - a for a, b in merged)


def _subtract_len(merged_a, merged_b):
    """Total length of ``A \\ B`` for two disjoint sorted interval unions —
    the measured-exposure primitive (collective wire time not covered by
    compute)."""
    total = 0.0
    j = 0
    for a, b in merged_a:
        cur = a
        while j < len(merged_b) and merged_b[j][1] <= cur:
            j += 1
        k = j
        while cur < b:
            if k >= len(merged_b) or merged_b[k][0] >= b:
                total += b - cur
                cur = b
            else:
                lo, hi = merged_b[k]
                if lo > cur:
                    total += lo - cur
                cur = max(cur, min(hi, b))
                k += 1
    return total


def _us(x):
    """Deterministic microsecond rounding (same contract as anatomy._us —
    report fields are pure functions of the input trace)."""
    return round(x, 3)


# --------------------------------------------------------------- HLO catalog
def program_profile_info(hlo_text, slice_sets=None):
    """Compact per-program catalog the measured-trace attribution joins
    against, computed once at compile time by the watchdog (lazily imports
    ``.hlo`` — trace ingestion itself never needs it)::

        {"module": HloModule header name (the trace's hlo_module key),
         "scopes": {instruction: named scope},          # op_name metadata
         "collectives": {instruction: {"level", "bytes", "bucket"}}}
    """
    from . import hlo
    sets = [frozenset(s) for s in slice_sets] if slice_sets else []

    def level(groups):
        if len(sets) <= 1:
            return "ici"
        if groups is None:
            return "dcn"
        return ("ici" if all(any(set(g) <= ss for ss in sets) for g in groups)
                else "dcn")

    scopes = {}
    for iname, op_name in hlo.instruction_op_names(hlo_text).items():
        m = SCOPE_RE.search(op_name)
        if m:
            scopes[iname] = m.group(1)
    collectives = {}
    for _line, iname, _op, _is_start, b, groups in hlo.collective_lines(
            hlo_text):
        scope = scopes.get(iname)
        bm = _BUCKET_SCOPE_RE.search(scope or "")
        collectives[iname] = {"level": level(groups), "bytes": int(b),
                              "bucket": int(bm.group(1)) if bm else None}
    return {"module": hlo.module_name(hlo_text), "scopes": scopes,
            "collectives": collectives}


def catalog_from_watchdog(watchdog):
    """{module name: catalog info + flops/wire/anatomy facts} over a
    CompileWatchdog's records — the predicted side of the reconciliation.
    Programs compiled without ``profile_scopes`` (or that failed analysis)
    are skipped."""
    catalog = {}
    for name, sigs in watchdog.records.items():
        for rec in sigs.values():
            info = getattr(rec, "profile_info", None)
            if not info or not info.get("module"):
                continue
            anat = rec.anatomy or {}
            exposed = anat.get("exposed_s", {})
            catalog[info["module"]] = {
                "program": name,
                "scopes": info["scopes"],
                "collectives": info["collectives"],
                "flops": float(rec.flops),
                "wire_ici": int(rec.wire_bytes_ici),
                "wire_dcn": int(rec.wire_bytes_dcn),
                "predicted_exposed_ici_us": _us(
                    float(exposed.get("ici", 0.0)) * 1e6),
                "predicted_exposed_dcn_us": _us(
                    float(exposed.get("dcn", 0.0)) * 1e6),
            }
    return catalog


# ------------------------------------------------------------- summarization
def summarize_slices(slices, catalog=None, devices=1, steps=1,
                     peak_tflops=None):
    """The measured profile report over one window's device slices.

    Interval math runs on the union timeline across all device threads (the
    CPU mesh runs 8 virtual devices on one host; per-device separation is not
    available in the trace, and the union is the quantity step wall actually
    pays). Exposure mirrors the anatomy pricing rule: exposed DCN is DCN wire
    time no compute covers; exposed ICI is ICI wire time covered by neither
    compute nor in-flight DCN (the cross-level overlap the bucketed exchange
    exists to create — docs/overlap.md)."""
    devices = max(int(devices), 1)
    steps = max(int(steps), 1)
    compute_iv, ici_iv, dcn_iv, all_iv = [], [], [], []
    bucket_iv = {}     # bucket -> {"ici": [...], "dcn": [...]}
    scope_rows = {}    # scope -> {"busy_us", "collective_us", "slices"}
    per_program = {}   # module -> {"slices", "intervals", "coll_counts"}
    for s in slices:
        iv = (s["ts"], s["ts"] + s["dur"])
        all_iv.append(iv)
        coll = is_collective_op(s["op"])
        if not coll and catalog:
            prog = catalog.get(s["module"])
            if prog and s["op"] in prog.get("collectives", {}):
                coll = True
        scope = slice_scope(s, catalog) or "unattributed"
        row = scope_rows.setdefault(scope, {"busy_us": 0.0,
                                            "collective_us": 0.0, "slices": 0})
        row["busy_us"] += s["dur"]
        row["slices"] += 1
        pp = per_program.setdefault(s["module"], {
            "slices": 0, "intervals": [], "collective_counts": {}})
        pp["slices"] += 1
        pp["intervals"].append(iv)
        if coll:
            row["collective_us"] += s["dur"]
            level = slice_level(s, catalog)
            (ici_iv if level == "ici" else dcn_iv).append(iv)
            pp["collective_counts"][s["op"]] = (
                pp["collective_counts"].get(s["op"], 0) + 1)
            m = _BUCKET_SCOPE_RE.search(scope)
            if m:
                bucket_iv.setdefault(int(m.group(1)),
                                     {"ici": [], "dcn": []})[level].append(iv)
        else:
            compute_iv.append(iv)
    comp_u, ici_u, dcn_u = _union(compute_iv), _union(ici_iv), _union(dcn_iv)
    all_u = _union(all_iv)
    extent = (all_u[-1][1] - all_u[0][0]) if all_u else 0.0
    comp_or_dcn = _union(compute_iv + dcn_iv)
    buckets = {}
    for k, ivs in sorted(bucket_iv.items()):
        b_ici, b_dcn = _union(ivs["ici"]), _union(ivs["dcn"])
        buckets[str(k)] = {
            "collective_ici_us": _us(_union_len(b_ici)),
            "collective_dcn_us": _us(_union_len(b_dcn)),
            "exposed_ici_us": _us(_subtract_len(b_ici, comp_or_dcn)),
            "exposed_dcn_us": _us(_subtract_len(b_dcn, comp_u)),
        }
    programs = {}
    collective_counts = {}
    for module, pp in sorted(per_program.items()):
        busy_us = _union_len(_union(pp["intervals"]))
        row = {"slices": pp["slices"], "busy_us": _us(busy_us)}
        info = (catalog or {}).get(module)
        if pp["collective_counts"]:
            collective_counts[module] = dict(sorted(
                pp["collective_counts"].items()))
        if info:
            row["program"] = info["program"]
            row["flops"] = info["flops"]
            if peak_tflops and busy_us > 0:
                # per-program measured MFU: the watchdog's per-device flops x
                # the window's executions over the program's busy wall on the
                # union timeline, against the stated peak. On the shared-host
                # CPU mesh this is an attribution metric, not a hardware
                # utilization claim — docs/profile.md spells the formula out.
                row["measured_mfu"] = round(
                    (info["flops"] * steps)
                    / (busy_us * 1e-6 * peak_tflops * 1e12), 12)
        programs[module] = row
    measured_mfu = None
    if peak_tflops and extent > 0 and catalog:
        # same convention as TelemetrySession's rolling MFU: one program
        # execution contributes its cost_analysis flops once, priced against
        # the stated peak over the window's wall extent
        window_flops = sum(catalog[m]["flops"] * steps
                           for m in per_program if m in catalog)
        if window_flops > 0:
            measured_mfu = round(
                window_flops / (extent * 1e-6 * peak_tflops * 1e12), 12)
    return {
        "version": PROFILE_REPORT_VERSION,
        "kind": PROFILE_REPORT_KIND,
        "devices": devices,
        "steps": steps,
        "classes": {
            "compute": {"busy_us": _us(_union_len(comp_u))},
            "collective_ici": {
                "busy_us": _us(_union_len(ici_u)),
                "exposed_us": _us(_subtract_len(ici_u, comp_or_dcn)),
            },
            "collective_dcn": {
                "busy_us": _us(_union_len(dcn_u)),
                "exposed_us": _us(_subtract_len(dcn_u, comp_u)),
            },
            "host_gap": {"gap_us": _us(extent - _union_len(all_u))},
        },
        "step_wall_us": _us(extent / steps),
        "extent_us": _us(extent),
        "measured_mfu": measured_mfu,
        "total_slices": len(slices),
        "scopes": {k: {"busy_us": _us(v["busy_us"]),
                       "collective_us": _us(v["collective_us"]),
                       "slices": v["slices"]}
                   for k, v in sorted(scope_rows.items())},
        "buckets": buckets,
        "programs": programs,
        "collective_counts": collective_counts,
    }


def measured_collective_counts(report, catalog):
    """Per-level measured collective executions per step per device —
    the machine-independent measured basis the reconciliation gates. Every
    HLO collective instruction executes exactly once per device per step, so
    the trace's slice count divided by (devices x steps) must equal the
    catalog's instruction count."""
    denom = report["devices"] * report["steps"]
    counts = {"ici": 0.0, "dcn": 0.0}
    for module, ops in report.get("collective_counts", {}).items():
        prog = catalog.get(module, {})
        for op, c in ops.items():
            row = prog.get("collectives", {}).get(op)
            level = row["level"] if row else "ici"
            counts[level] += c / denom
    return {k: round(v, 6) for k, v in counts.items()}


# ------------------------------------------------------------ reconciliation
def _within(a, b, rel_tol):
    return abs(a - b) <= rel_tol * max(abs(a), abs(b), 1e-12)


def reconcile_profile(measured, catalog, derived, rel_tol=PROFILE_REL_TOL,
                      entry=""):
    """Pin the three views against each other, per class, hbm-style.

    ``measured`` is a :func:`summarize_slices` report; ``catalog`` the
    watchdog catalog (:func:`catalog_from_watchdog`); ``derived`` the
    telemetry session's per-step counter view::

        {"flops_per_step", "wire_ici_per_step", "wire_dcn_per_step",
         "step_wall_ms" (optional)}

    Gated, machine-independent pairs per class:

    * ``compute`` — predicted flops/step (catalog, one execution per program
      per step) vs derived flops/step (the proxies' counters); measured
      compute busy time must be observed (>0) for the class to gate at all.
    * ``collective_ici`` / ``collective_dcn`` — measured slice executions per
      step per device vs the catalog's HLO instruction count, AND predicted
      vs derived wire bytes/step.
    * ``step_wall`` — measured trace extent per step vs telemetry's host step
      wall, at :data:`PROFILE_STEP_WALL_REL_TOL` (real seconds both, so gated
      only as a sanity check and excluded from the golden projection).

    Verdicts: ``ok`` / ``drift`` / ``unobserved`` (the measured side saw
    nothing a prediction exists for — e.g. a trace window that closed before
    the program ran)."""
    classes = {}
    ok = True
    pred_flops = sum(p["flops"] for p in catalog.values())
    pred_counts = {"ici": 0, "dcn": 0}
    pred_wire = {"ici": 0, "dcn": 0}
    pred_exposed = {"ici": 0.0, "dcn": 0.0}
    for p in catalog.values():
        for row in p["collectives"].values():
            pred_counts[row["level"]] += 1
        pred_wire["ici"] += p["wire_ici"]
        pred_wire["dcn"] += p["wire_dcn"]
        pred_exposed["ici"] += p["predicted_exposed_ici_us"]
        pred_exposed["dcn"] += p["predicted_exposed_dcn_us"]
    meas_counts = measured_collective_counts(measured, catalog)

    row = {
        "measured_busy_us": measured["classes"]["compute"]["busy_us"],
        "predicted_flops_per_step": round(pred_flops, 3),
        "derived_flops_per_step": round(float(derived["flops_per_step"]), 3),
    }
    if row["measured_busy_us"] <= 0 and pred_flops > 0:
        row["status"] = "unobserved"
    elif _within(pred_flops, derived["flops_per_step"], rel_tol):
        row["status"] = "ok"
    else:
        row["status"] = "drift"
        ok = False
    classes["compute"] = row

    for level in ("ici", "dcn"):
        mc = meas_counts[level]
        pc = pred_counts[level]
        dw = int(derived[f"wire_{level}_per_step"])
        pw = pred_wire[level]
        row = {
            "measured_count_per_step_per_device": mc,
            "predicted_instruction_count": pc,
            "predicted_wire_bytes_per_step": pw,
            "derived_wire_bytes_per_step": dw,
            "measured_busy_us":
                measured["classes"][f"collective_{level}"]["busy_us"],
            "measured_exposed_us":
                measured["classes"][f"collective_{level}"]["exposed_us"],
            "predicted_exposed_us": _us(pred_exposed[level]),
        }
        if mc == 0 and pc > 0:
            row["status"] = "unobserved"
        elif _within(mc, pc, rel_tol) and _within(pw, dw, rel_tol):
            row["status"] = "ok"
        else:
            row["status"] = "drift"
            ok = False
        classes[f"collective_{level}"] = row

    row = {"measured_step_wall_ms": round(measured["step_wall_us"] / 1e3, 6)}
    derived_wall = derived.get("step_wall_ms")
    if derived_wall:
        row["derived_step_wall_ms"] = round(float(derived_wall), 6)
        if _within(measured["step_wall_us"] / 1e3, derived_wall,
                   PROFILE_STEP_WALL_REL_TOL):
            row["status"] = "ok"
        else:
            row["status"] = "drift"
            ok = False
    else:
        row["status"] = "unobserved"
    classes["step_wall"] = row

    return {
        "version": PROFILE_REPORT_VERSION,
        "kind": PROFILE_RECONCILE_KIND,
        "entry": entry,
        "tolerance": {"rel": rel_tol,
                      "step_wall_rel": PROFILE_STEP_WALL_REL_TOL},
        "classes": classes,
        "scopes_observed": sorted(s for s in measured.get("scopes", {})
                                  if s != "unattributed"),
        "buckets_observed": sorted(measured.get("buckets", {}), key=int),
        "measured": measured,
        "ok": ok,
    }


def stable_projection(report):
    """The golden-pinnable slice of a reconcile report: verdicts, collective
    execution/instruction counts, wire bytes, flops, scope and bucket
    coverage — all pure functions of the compiled programs and the pinned
    8-device CPU mesh. Every wall-clock field (busy/exposed/step-wall
    microseconds) is excluded: those move with the machine; the structural
    facts must not."""
    classes = {}
    for cls, row in report["classes"].items():
        if cls == "step_wall":
            continue  # both sides are real seconds — never golden material
        keep = {k: v for k, v in row.items()
                if not k.endswith("_us") and not k.endswith("_ms")}
        classes[cls] = keep
    return {
        "version": report["version"],
        "kind": report["kind"] + "_golden",
        "entry": report["entry"],
        "tolerance": report["tolerance"],
        "classes": classes,
        "scopes_observed": report["scopes_observed"],
        "buckets_observed": report["buckets_observed"],
        "collective_counts": report["measured"]["collective_counts"],
        "ok": report["ok"],
    }


def diff_reports(old, new, rel_tol=PROFILE_REL_TOL):
    """Cross-run regression gate over two reconcile reports (full or golden
    projection): any class verdict that left ``ok``, any measured collective
    count or wire-byte growth beyond tolerance, any scope or bucket that
    disappeared from coverage."""
    regressions = []
    o_cls = old.get("classes", {})
    n_cls = new.get("classes", {})
    for cls in sorted(o_cls):
        o_row = o_cls[cls]
        n_row = n_cls.get(cls)
        if n_row is None:
            regressions.append(f"{cls}: class disappeared")
            continue
        if o_row.get("status") == "ok" and n_row.get("status") != "ok":
            regressions.append(
                f"{cls}: verdict regressed ok -> {n_row.get('status')}")
        for key in ("measured_count_per_step_per_device",
                    "predicted_wire_bytes_per_step"):
            ov, nv = o_row.get(key), n_row.get(key)
            if ov is None or nv is None:
                continue
            if nv > ov + rel_tol * max(abs(ov), 1e-12):
                regressions.append(f"{cls}/{key}: grew {ov} -> {nv}")
    for field in ("scopes_observed", "buckets_observed"):
        gone = sorted(set(old.get(field, [])) - set(new.get(field, [])))
        if gone:
            regressions.append(f"{field}: lost {gone}")
    return {"version": PROFILE_REPORT_VERSION, "kind": PROFILE_DIFF_KIND,
            "regressions": regressions, "ok": not regressions}


# -------------------------------------------------------------- merged timeline
def to_profile_trace_events(slices, catalog=None, predicted_reports=None):
    """Merged measured-vs-predicted Perfetto timeline: pid 0 carries the
    predicted schedule (one roofline-floor + exposed-comm thread pair per
    program, the same tracks ``ds-tpu anatomy`` draws), pinned ABOVE pid 1's
    measured device timeline (one thread per class) via process_sort_index.
    Measured slices are re-based to the window start so the two timebases
    align at 0."""
    from .trace_event import (complete_slice, process_name_event,
                              process_sort_index_event, thread_meta_events,
                              trace_envelope)
    events = [process_name_event(0, "predicted schedule"),
              process_sort_index_event(0, 0),
              process_name_event(1, "measured trace"),
              process_sort_index_event(1, 1)]
    if predicted_reports:
        from .anatomy import program_schedule_events
        for i, rep in enumerate(sorted(predicted_reports,
                                       key=lambda r: r["name"])):
            events += program_schedule_events(
                rep, pid=0, floor_tid=2 * i, comm_tid=2 * i + 1,
                sort_base=2 * i, label_prefix=rep["name"] + " ")
    class_tid = {"compute": 0, "collective_ici": 1, "collective_dcn": 2}
    for tid, name in ((0, "compute"), (1, "collective ici"),
                      (2, "collective dcn")):
        events += thread_meta_events(1, tid, name, sort_index=tid)
    t0 = min((s["ts"] for s in slices), default=0.0)
    for s in slices:
        coll = is_collective_op(s["op"])
        if not coll and catalog:
            prog = catalog.get(s["module"])
            coll = bool(prog and s["op"] in prog.get("collectives", {}))
        cls = (f"collective_{slice_level(s, catalog)}" if coll else "compute")
        args = {"module": s["module"]}
        scope = slice_scope(s, catalog)
        if scope:
            args["scope"] = scope
        events.append(complete_slice(
            1, class_tid[cls], _us(s["ts"] - t0), _us(s["dur"]), s["op"],
            cls.replace("_", "-"), args,
            cname="bad" if cls == "collective_dcn"
            else ("thread_state_iowait" if coll else None)))
    return trace_envelope(events, "ds-tpu profile",
                          measured_slices=len(slices),
                          trace_version=PROFILE_REPORT_VERSION)


# ---------------------------------------------------------- reconcile runner
RECONCILE_ENTRY = "comm_overlap"
RECONCILE_TRACE_STEPS = (3, 6)
RECONCILE_TOTAL_STEPS = 7


def run_reconcile(rel_tol=PROFILE_REL_TOL, trace_dir=None, keep_engine=False):
    """Build the lint registry's ``comm_overlap`` engine shape with a
    profile-enabled telemetry trace window, run it on the pinned 8-device CPU
    mesh, ingest the window's trace and reconcile measured vs predicted vs
    derived — the ``ds-tpu profile --reconcile`` lint gate. Heavy imports are
    local: only this runner needs jax/the engine stack."""
    import shutil
    import tempfile

    import jax

    import deepspeed_tpu
    from ..lint.registry import LintModel, _sample_batch

    own_dir = trace_dir is None
    trace_dir = trace_dir or tempfile.mkdtemp(prefix="ds_profile_reconcile_")
    model = LintModel()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.init(jax.random.PRNGKey(0)),
        config_params={
            "train_batch_size": 8, "steps_per_print": 1000,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "zero_optimization": {"stage": 2},
            "comm": {"mode": "hierarchical", "dcn_slices": 2,
                     "overlap": {"mode": "bucketed", "bucket_mb": 0.004}},
            "telemetry": {
                "enabled": True,
                "trace_dir": trace_dir,
                "trace_steps": list(RECONCILE_TRACE_STEPS),
                "anatomy": {"enabled": True, "chip": "cpu-test"},
                "profile": {"enabled": True, "reconcile_tolerance": rel_tol},
            },
        })
    try:
        session = engine.telemetry
        x, y = _sample_batch()
        a, b = RECONCILE_TRACE_STEPS

        def counters():
            return {"flops": session.flops_executed,
                    "wire_ici": session.wire_ici_executed,
                    "wire_dcn": session.wire_dcn_executed}
        base = end = {}
        walls = []
        for step in range(RECONCILE_TOTAL_STEPS):
            # `step` completed optimizer steps precede this iteration — the
            # same count on_step_begin keys the trace window off, so the
            # counter snapshots bracket exactly the traced steps [a, b)
            if step == a:
                base = counters()
            if step == b:
                end = counters()
            loss = engine(x, y)
            engine.backward(loss)
            engine.step()
            if a <= step < b:
                walls.append(session.last_step_ms)
        if not end:
            end = counters()
        if session._trace_failed:
            raise ProfileParseError(
                "profiler trace window failed to start (see telemetry "
                "warning); nothing to reconcile")
        steps = b - a
        derived = {
            "flops_per_step": (end["flops"] - base["flops"]) / steps,
            "wire_ici_per_step": (end["wire_ici"] - base["wire_ici"]) // steps,
            "wire_dcn_per_step": (end["wire_dcn"] - base["wire_dcn"]) // steps,
            "step_wall_ms": sum(walls) / len(walls) if walls else None,
        }
        catalog = catalog_from_watchdog(session.watchdog)
        events, _files = load_trace_dir(session.trace_output_dir)
        slices = device_slices(events)
        peak = session.peak_tflops
        measured = summarize_slices(slices, catalog=catalog,
                                    devices=jax.device_count(), steps=steps,
                                    peak_tflops=peak)
        report = reconcile_profile(measured, catalog, derived,
                                   rel_tol=rel_tol, entry=RECONCILE_ENTRY)
        anatomy_reports = [rec.anatomy
                           for sigs in session.watchdog.records.values()
                           for rec in sigs.values() if rec.anatomy]
        return report, slices, catalog, anatomy_reports
    finally:
        if not keep_engine:
            try:
                engine.telemetry.close()
            except Exception:
                pass
        if own_dir:
            shutil.rmtree(trace_dir, ignore_errors=True)


# ------------------------------------------------------------------- CLI
def _load_json(path):
    with open(path) as f:
        return json.load(f)


def _resolve_source(path):
    """A positional source can be a trace file, a trace dir, or a telemetry
    ``trace_dir`` holding namespaced per-run dirs — pick the newest run."""
    if os.path.isfile(path):
        return path
    if find_trace_files(path):
        return path
    runs = scan_trace_dirs(path)
    candidates = [r["path"] for r in runs if find_trace_files(r["path"])]
    if candidates:
        return candidates[-1]
    raise ProfileParseError(
        f"no trace files under {path!r} — expected a trace-viewer JSON, a "
        "profiler dir (plugins/profile/...) or a telemetry trace_dir with "
        "trace_<run>_host<h>/ subdirs")


def profile_main(argv=None):
    """``ds-tpu profile`` — the measured-time observatory CLI. Default mode
    ingests a trace (dir or file) into the deterministic ``--json`` report;
    ``--reconcile`` runs the traced CPU-mesh window and gates measured vs
    predicted vs derived (exit 1 on drift — the lint.sh gate); ``--diff A B``
    is the pure-host cross-run regression gate."""
    parser = argparse.ArgumentParser(
        prog="ds-tpu profile",
        description="profiler-trace ingestion: classify device slices per "
                    "scope, reconcile measured/predicted/derived step time")
    parser.add_argument("source", nargs="?", metavar="TRACE",
                        help="trace file, profiler dir, or telemetry "
                             "trace_dir (newest namespaced run wins)")
    parser.add_argument("--json", action="store_true",
                        help="emit the full report as JSON on stdout")
    parser.add_argument("--out", metavar="PATH",
                        help="also write the JSON report to PATH")
    parser.add_argument("--golden-out", metavar="PATH",
                        help="write the stable (golden-pinnable) projection "
                             "of a --reconcile report to PATH")
    parser.add_argument("--timeline", metavar="PATH",
                        help="write the merged measured-vs-predicted "
                             "Perfetto trace")
    parser.add_argument("--reconcile", action="store_true",
                        help="run the traced CPU-mesh lint window and gate "
                             "measured vs predicted vs derived (exit 1 on "
                             "drift)")
    parser.add_argument("--tolerance", type=float, default=PROFILE_REL_TOL,
                        help="reconciliation relative tolerance "
                             "(default: %(default)s)")
    parser.add_argument("--devices", type=int, default=1,
                        help="device count normalizing ingested slice counts "
                             "(default: 1; --reconcile derives it)")
    parser.add_argument("--steps", type=int, default=1,
                        help="optimizer steps the ingested window spans "
                             "(default: 1; --reconcile derives it)")
    parser.add_argument("--peak-tflops", type=float, default=0.0,
                        help="peak TFLOP/s pricing measured MFU (default: "
                             "off)")
    parser.add_argument("--diff", nargs=2, metavar=("OLD", "NEW"),
                        help="compare two reconcile reports; exit 1 on any "
                             "regression")
    args = parser.parse_args(argv)

    # stdout belongs to the report (same contract as ds-tpu lint/hbm)
    import logging
    for h in logging.getLogger("DeepSpeedTPU").handlers:
        if isinstance(h, logging.StreamHandler) and h.stream is sys.stdout:
            h.stream = sys.stderr

    slices = catalog = None
    anatomy_reports = []
    if args.diff:
        report = diff_reports(_load_json(args.diff[0]),
                              _load_json(args.diff[1]),
                              rel_tol=args.tolerance)
    elif args.reconcile:
        try:
            report, slices, catalog, anatomy_reports = run_reconcile(
                rel_tol=args.tolerance)
        except ProfileParseError as e:
            print(f"ERROR {e}", file=sys.stderr)
            return 1
    else:
        if not args.source:
            parser.error("a TRACE source is required unless --reconcile or "
                         "--diff is given")
        try:
            source = _resolve_source(args.source)
            events, files = load_trace_dir(source) \
                if not os.path.isfile(source) \
                else (load_trace(source)["traceEvents"], [source])
            slices = device_slices(events)
            report = summarize_slices(
                slices, devices=args.devices, steps=args.steps,
                peak_tflops=args.peak_tflops or None)
            report["source"] = sorted(os.path.relpath(f, args.source)
                                      if not os.path.isfile(args.source)
                                      else f for f in files)
        except ProfileParseError as e:
            print(f"ERROR {e}", file=sys.stderr)
            return 1

    text = json.dumps(report, indent=2, sort_keys=True) + "\n"
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
    if args.golden_out and report.get("kind") == PROFILE_RECONCILE_KIND:
        with open(args.golden_out, "w") as f:
            f.write(json.dumps(stable_projection(report), indent=2,
                               sort_keys=True) + "\n")
    if args.timeline and slices is not None:
        from .trace_event import serialize_trace
        with open(args.timeline, "w") as f:
            f.write(serialize_trace(to_profile_trace_events(
                slices, catalog=catalog,
                predicted_reports=anatomy_reports)))
    if args.json:
        sys.stdout.write(text)
    else:
        _print_report(report)
    return 0 if report.get("ok", True) else 1


def _print_report(report):
    kind = report.get("kind")
    if kind == PROFILE_RECONCILE_KIND:
        for cls, row in sorted(report["classes"].items()):
            print(f"{cls}: [{row['status']}]")
            for k, v in sorted(row.items()):
                if k != "status":
                    print(f"  {k:<36} {v}")
        print(f"scopes: {', '.join(report['scopes_observed']) or '(none)'}; "
              f"buckets: {', '.join(report['buckets_observed']) or '(none)'}")
        print("reconciled" if report["ok"] else "DRIFT")
    elif kind == PROFILE_DIFF_KIND:
        for r in report["regressions"]:
            print(f"REGRESSION {r}")
        print(f"{len(report['regressions'])} regression(s)")
    elif kind == PROFILE_REPORT_KIND:
        for cls, row in sorted(report["classes"].items()):
            facts = "  ".join(f"{k} {v}" for k, v in sorted(row.items())
                              if v is not None)
            print(f"{cls}: {facts}")
        print(f"step wall {report['step_wall_us']}us over "
              f"{report['steps']} step(s), {report['total_slices']} device "
              f"slice(s)")
        for scope, row in sorted(report["scopes"].items()):
            print(f"  scope {scope:<18} busy {row['busy_us']:>12}us  "
                  f"collective {row['collective_us']:>12}us")
        for k, row in sorted(report["buckets"].items(), key=lambda kv:
                             int(kv[0])):
            print(f"  bucket {k}: exposed ici {row['exposed_ici_us']}us / "
                  f"dcn {row['exposed_dcn_us']}us")


if __name__ == "__main__":
    sys.exit(profile_main())
