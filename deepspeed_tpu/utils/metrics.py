"""Unified metric catalog + per-host time-series ring (docs/metrics.md).

Every scalar name any observatory emits (``Telemetry/*``, ``Numerics/*``,
``Pipeline/*``, ``Serving/*`` including ``Serving/Fleet/*`` and
``Serving/Spec/*``, ``Cluster/*``, ``Run/Goodput/*``, ``Memory/*``,
``Profile/*``, ``Anatomy/*``, ``Train/*``, ``Alerts/*``) is declared ONCE
here with its unit, direction (lower/higher-is-better/neutral), class and a
one-line description. The catalog is the single source of truth for "which
way is worse" — bench.py derives its regression directions from it (no
private LOWER_IS_BETTER list survives anywhere else) and the alert plane
(utils/alerts.py) uses it to orient ``delta`` regression rules.

``MetricStore`` is the router: attached to a ``SummaryMonitor`` (monitor.py)
it sees every ``add_scalar`` on every rank, validates the name against the
catalog (warn-once on unknown names; a strict mode for tests turns drift
into an error), and keeps a bounded per-metric time-series ring. The ring
has FIXED geometry (``ring_len`` observations per metric), so per-host rings
are exactly mergeable across hosts through the existing flight-recorder /
cluster dump plane — same discipline as the PR 14 latency sketches: merging
is a lossless union keyed by (host, step), never a lossy reduction.

Everything here is pure host bookkeeping: no jax import, no device work, no
blocking primitives (pinned by tests/unit/test_no_sync_guard.py). The step
programs are HLO-instruction-identical with the router attached or not.

``ds-tpu metrics`` lists the catalog or exports the latest observations as
OpenMetrics text for external scrapers.
"""

import json
import os
import re
from collections import deque

from .logging import logger

# directions: which way is WORSE. "neutral" metrics carry no regression
# semantics (identifiers, configuration echoes, context gauges).
LOWER = "lower_is_better"
HIGHER = "higher_is_better"
NEUTRAL = "neutral"

CATALOG_VERSION = 1
DEFAULT_RING_LEN = 512


class UnknownMetricError(KeyError):
    """Raised in strict mode when a scalar is emitted under an undeclared
    name — the catalog drift guard (tests) turns schema bypass into a
    failure instead of a silently untyped metric."""


class MetricSpec:
    """One declared metric (exact name) or metric family (``Prefix/*``)."""

    __slots__ = ("pattern", "unit", "direction", "klass", "description")

    def __init__(self, pattern, unit, direction, klass, description):
        if direction not in (LOWER, HIGHER, NEUTRAL):
            raise ValueError(f"bad direction {direction!r} for {pattern!r}")
        self.pattern = pattern
        self.unit = unit
        self.direction = direction
        self.klass = klass
        self.description = description

    @property
    def is_family(self):
        return self.pattern.endswith("/*")

    def matches(self, name):
        if self.is_family:
            return name.startswith(self.pattern[:-1])
        return name == self.pattern

    def to_dict(self):
        return {"pattern": self.pattern, "unit": self.unit,
                "direction": self.direction, "class": self.klass,
                "description": self.description}


def _spec(pattern, unit, direction, klass, description):
    return MetricSpec(pattern, unit, direction, klass, description)


# The declarations. Exact names win over families; among families the
# LONGEST matching prefix wins (``Serving/Fleet/Latency/*`` over
# ``Serving/Fleet/*``). Units follow the scalar's own convention (ms, bytes,
# fraction in [0,1], count, 1/s). Classes group metrics for rendering and
# export: time / throughput / bytes / count / fraction / gauge.
_DECLARATIONS = (
    # -- engine training scalars (runtime/engine.py) -----------------------
    _spec("Train/Samples/train_loss", "loss", LOWER, "gauge",
          "training loss at the sample axis"),
    _spec("Train/Samples/lr", "1", NEUTRAL, "gauge",
          "learning rate of param group 0"),
    _spec("Train/Samples/loss_scale", "1", NEUTRAL, "gauge",
          "dynamic fp16 loss scale (host journal shadow)"),
    _spec("Train/Samples/grad_norm", "1", NEUTRAL, "gauge",
          "global gradient norm after clipping"),
    # -- telemetry step metrics (utils/telemetry.py end_step) --------------
    _spec("Telemetry/Samples/step_time_ms", "ms", LOWER, "time",
          "end-to-end optimizer step wall time"),
    _spec("Telemetry/Samples/samples_per_sec", "1/s", HIGHER, "throughput",
          "training throughput over the last step"),
    _spec("Telemetry/Samples/mfu", "fraction", HIGHER, "fraction",
          "rolling model FLOPS utilization over compile-free steps"),
    _spec("Telemetry/Samples/wire_bytes", "bytes", NEUTRAL, "bytes",
          "collective bytes moved by the last step (all links)"),
    _spec("Telemetry/Samples/wire_bytes_ici", "bytes", NEUTRAL, "bytes",
          "intra-slice (ICI) collective bytes of the last step"),
    _spec("Telemetry/Samples/wire_bytes_dcn", "bytes", NEUTRAL, "bytes",
          "cross-slice (DCN) collective bytes of the last step"),
    _spec("Telemetry/Samples/hbm_in_use_bytes", "bytes", LOWER, "bytes",
          "device HBM currently in use (backend watermark)"),
    _spec("Telemetry/Samples/hbm_peak_bytes", "bytes", LOWER, "bytes",
          "device HBM peak watermark"),
    _spec("Telemetry/Samples/compile_count", "count", LOWER, "count",
          "cumulative program compiles seen by the watchdog"),
    # -- HBM observatory (docs/hbm.md): per-class resident bytes -----------
    _spec("Memory/*", "bytes", LOWER, "bytes",
          "per-class resident HBM attribution from the engine manifest"),
    # -- step anatomy (docs/anatomy.md): roofline attribution --------------
    _spec("Anatomy/compute_ms", "ms", NEUTRAL, "time",
          "roofline compute floor of the measured step"),
    _spec("Anatomy/hbm_bound_ms", "ms", NEUTRAL, "time",
          "roofline HBM-bandwidth floor of the measured step"),
    _spec("Anatomy/exposed_ici_ms", "ms", LOWER, "time",
          "un-overlapped ICI collective time attributed to the step"),
    _spec("Anatomy/exposed_dcn_ms", "ms", LOWER, "time",
          "un-overlapped DCN collective time attributed to the step"),
    _spec("Anatomy/host_gap_ms", "ms", LOWER, "time",
          "measured wall minus every device-side floor (host stall)"),
    _spec("Anatomy/predicted_floor_ms", "ms", NEUTRAL, "time",
          "max of the roofline floors — the step's predicted best case"),
    _spec("Anatomy/mfu_ceiling", "fraction", NEUTRAL, "fraction",
          "MFU the roofline model admits for this step shape"),
    # -- pipeline schedule goodput (docs/pipeline-trace.md) ----------------
    _spec("Pipeline/Goodput/bubble_seconds", "s", LOWER, "time",
          "schedule bubble (idle) seconds within one pipeline step"),
    _spec("Pipeline/Goodput/bubble_fraction", "fraction", LOWER, "fraction",
          "bubble share of the pipeline step"),
    _spec("Pipeline/Goodput/*", "s", NEUTRAL, "time",
          "per-phase seconds of the pipeline schedule decomposition"),
    # -- run-lifecycle goodput ledger (docs/goodput.md) --------------------
    _spec("Run/Goodput/goodput_fraction", "fraction", HIGHER, "fraction",
          "productive share of the run's accounted wall-clock"),
    _spec("Run/Goodput/wall_seconds", "s", NEUTRAL, "time",
          "total accounted run wall-clock"),
    _spec("Run/Goodput/productive_step_seconds", "s", HIGHER, "time",
          "wall-clock billed to productive training steps"),
    _spec("Run/Goodput/checkpoint_stall_seconds", "s", LOWER, "time",
          "caller-thread wall-clock lost to checkpoint fences"),
    _spec("Run/Goodput/restart_replay_seconds", "s", LOWER, "time",
          "wall-clock re-paying steps lost to a restart"),
    _spec("Run/Goodput/hang_seconds", "s", LOWER, "time",
          "wall-clock inside watchdog-detected hangs"),
    _spec("Run/Goodput/straggler_skew_seconds", "s", LOWER, "time",
          "wall-clock this host spent above the fleet median dispatch"),
    _spec("Run/Goodput/host_gap_seconds", "s", LOWER, "time",
          "wall-clock in unattributed host gaps"),
    _spec("Run/Goodput/*", "s", NEUTRAL, "time",
          "remaining badput classes (init, compile, eval tag)"),
    # -- serving engine (docs/serving.md) ----------------------------------
    _spec("Serving/Latency/*", "ms", LOWER, "time",
          "request latency percentile summary (TTFT/TPOT/queue/e2e)"),
    _spec("Serving/PrefixCache/hit_rate", "fraction", HIGHER, "fraction",
          "prefix-cache token hit rate"),
    _spec("Serving/PrefixCache/hit_tokens", "count", HIGHER, "count",
          "prefill tokens served from the prefix cache"),
    _spec("Serving/PrefixCache/*", "count", NEUTRAL, "count",
          "prefix-cache occupancy counters (parked blocks, evictions)"),
    _spec("Serving/Spec/acceptance_rate", "fraction", HIGHER, "fraction",
          "speculative-draft token acceptance rate"),
    _spec("Serving/Spec/accepted_tokens", "count", HIGHER, "count",
          "draft tokens accepted by the target model"),
    _spec("Serving/Spec/wasted_draft_tokens", "count", LOWER, "count",
          "draft tokens rejected by the target model"),
    _spec("Serving/Spec/target_steps_per_token", "1", LOWER, "gauge",
          "target-model program executions per emitted token"),
    _spec("Serving/Spec/*", "count", NEUTRAL, "count",
          "speculative decoding counters (drafted tokens)"),
    _spec("Serving/Waste/replayed_tokens", "count", LOWER, "count",
          "scheduled tokens re-computed after preemption"),
    _spec("Serving/Waste/fraction", "fraction", LOWER, "fraction",
          "replayed share of all scheduled tokens"),
    _spec("Serving/Pool/fragmentation", "fraction", LOWER, "fraction",
          "paged KV pool fragmentation"),
    _spec("Serving/occupancy", "fraction", HIGHER, "fraction",
          "decode batch slot occupancy"),
    _spec("Serving/waiting", "count", LOWER, "count",
          "requests waiting for admission"),
    _spec("Serving/free_blocks", "count", HIGHER, "count",
          "free KV pool blocks"),
    _spec("Serving/tok_s", "1/s", HIGHER, "throughput",
          "sampled tokens per second"),
    _spec("Serving/goodput_tok_s", "1/s", HIGHER, "throughput",
          "tokens per second of requests that finished"),
    _spec("Serving/ttft_ms", "ms", LOWER, "time",
          "per-request time to first token"),
    _spec("Serving/ttft_iters", "count", LOWER, "count",
          "per-request engine iterations to first token"),
    # -- fleet router (docs/serving.md): merged across replicas ------------
    _spec("Serving/Fleet/Latency/*", "ms", LOWER, "time",
          "fleet-merged latency percentiles"),
    _spec("Serving/Fleet/Goodput/fraction", "fraction", HIGHER, "fraction",
          "fleet-merged serving goodput fraction"),
    _spec("Serving/Fleet/shed", "count", LOWER, "count",
          "requests shed by admission control (cumulative)"),
    _spec("Serving/Fleet/finished", "count", HIGHER, "count",
          "requests finished fleet-wide (cumulative)"),
    _spec("Serving/Fleet/waiting", "count", LOWER, "count",
          "requests waiting fleet-wide"),
    _spec("Serving/Fleet/running", "count", NEUTRAL, "count",
          "requests running fleet-wide"),
    _spec("Serving/Fleet/free_blocks", "count", HIGHER, "count",
          "free KV pool blocks fleet-wide"),
    _spec("Serving/Fleet/Spec/*", "count", NEUTRAL, "count",
          "fleet-merged speculative decoding counters"),
    _spec("Serving/*", "1", NEUTRAL, "gauge",
          "remaining serving gauges"),
    # -- cluster observatory (docs/cluster.md) -----------------------------
    _spec("Cluster/hosts", "count", NEUTRAL, "count",
          "hosts present in the heartbeat matrix"),
    _spec("Cluster/step_ms_max", "ms", LOWER, "time",
          "slowest host's step wall this heartbeat"),
    _spec("Cluster/step_ms_median", "ms", LOWER, "time",
          "fleet median step wall this heartbeat"),
    _spec("Cluster/step_skew", "ratio", LOWER, "gauge",
          "max/median step-wall skew across hosts"),
    _spec("Cluster/wire_bytes_ici_total", "bytes", NEUTRAL, "bytes",
          "fleet-total ICI bytes this heartbeat"),
    _spec("Cluster/wire_bytes_dcn_total", "bytes", NEUTRAL, "bytes",
          "fleet-total DCN bytes this heartbeat"),
    _spec("Cluster/hbm_peak_bytes_max", "bytes", LOWER, "bytes",
          "worst host HBM peak this heartbeat"),
    _spec("Cluster/straggler_host", "host", NEUTRAL, "gauge",
          "host id named straggler (-1 = none)"),
    # -- measured-time profile observatory (docs/profile.md) ---------------
    _spec("Profile/exposed_ici_ms", "ms", LOWER, "time",
          "measured un-overlapped ICI time per step"),
    _spec("Profile/exposed_dcn_ms", "ms", LOWER, "time",
          "measured un-overlapped DCN time per step"),
    _spec("Profile/host_gap_ms", "ms", LOWER, "time",
          "measured device-idle host gap per step"),
    _spec("Profile/step_wall_ms", "ms", LOWER, "time",
          "measured step wall from the trace window"),
    _spec("Profile/mfu", "fraction", HIGHER, "fraction",
          "measured-window MFU"),
    _spec("Profile/*", "ms", NEUTRAL, "time",
          "measured per-class busy time per step"),
    # -- numerics observatory (docs/numerics.md): per-subtree stats --------
    _spec("Numerics/grad_norm/*", "1", NEUTRAL, "gauge",
          "per-subtree gradient norm from the in-graph sentinel"),
    _spec("Numerics/weight_norm/*", "1", NEUTRAL, "gauge",
          "per-subtree weight norm from the in-graph sentinel"),
    _spec("Numerics/update_ratio/*", "1", NEUTRAL, "gauge",
          "per-subtree update/weight norm ratio"),
    # -- alert plane (docs/alerts.md): 1 while a rule is firing ------------
    _spec("Alerts/*", "bool", NEUTRAL, "gauge",
          "1 while the named alert rule is firing, 0 once it clears"),
)


class MetricCatalog:
    """Declared metric schema with exact-then-longest-prefix resolution."""

    def __init__(self, specs=_DECLARATIONS):
        self.specs = tuple(specs)
        self._exact = {}
        self._families = []
        for s in self.specs:
            if s.is_family:
                self._families.append(s)
            else:
                if s.pattern in self._exact:
                    raise ValueError(f"duplicate declaration {s.pattern!r}")
                self._exact[s.pattern] = s
        # longest prefix first, so Serving/Fleet/Latency/* shadows Serving/*
        self._families.sort(key=lambda s: len(s.pattern), reverse=True)

    def resolve(self, name):
        """The declaration covering ``name``, or None when undeclared."""
        spec = self._exact.get(name)
        if spec is not None:
            return spec
        for fam in self._families:
            if fam.matches(name):
                return fam
        return None

    def direction(self, name):
        """lower_is_better / higher_is_better / neutral, or None when the
        name is undeclared (callers treat that as an error, not neutral)."""
        spec = self.resolve(name)
        return spec.direction if spec is not None else None

    def to_dict(self):
        return {"version": CATALOG_VERSION,
                "metrics": [s.to_dict() for s in self.specs]}


_DEFAULT_CATALOG = None


def default_catalog():
    """The shipped catalog singleton (cheap to rebuild, cached anyway)."""
    global _DEFAULT_CATALOG
    if _DEFAULT_CATALOG is None:
        _DEFAULT_CATALOG = MetricCatalog()
    return _DEFAULT_CATALOG


# ------------------------------------------------------------- metric store


class MetricStore:
    """Per-host bounded time-series ring, fed by SummaryMonitor.add_scalar.

    Fixed geometry: every metric keeps at most ``ring_len`` observations
    (step, value). ``to_dict`` snapshots are exactly mergeable across hosts
    (``merge_host_rings``) because merging is a union keyed by (host, step)
    — no reduction, no loss, no geometry negotiation beyond the equality
    check. Recording happens on EVERY rank (the SummaryMonitor hook runs
    before its rank-0 early return) so each host's flight-recorder dump
    carries its own ring."""

    def __init__(self, catalog=None, ring_len=DEFAULT_RING_LEN, strict=False,
                 host=0):
        self.catalog = catalog if catalog is not None else default_catalog()
        self.ring_len = int(ring_len)
        if self.ring_len <= 0:
            raise ValueError(f"ring_len must be > 0, got {ring_len!r}")
        self.strict = bool(strict)
        self.host = int(host)
        self.series_by_name = {}
        self.observations = 0
        self._warned = set()

    def observe(self, name, value, step):
        spec = self.catalog.resolve(name)
        if spec is None:
            if self.strict:
                raise UnknownMetricError(
                    f"scalar {name!r} is not declared in the MetricCatalog "
                    "(utils/metrics.py) — declare it with a unit/direction/"
                    "class or fix the emitter")
            if name not in self._warned:
                self._warned.add(name)
                logger.warning(
                    f"[deepspeed_tpu] metrics: scalar {name!r} is not in the "
                    "MetricCatalog — recording it untyped (warn-once; add a "
                    "declaration in utils/metrics.py)")
        ring = self.series_by_name.get(name)
        if ring is None:
            ring = self.series_by_name[name] = deque(maxlen=self.ring_len)
        ring.append((int(step), float(value)))
        self.observations += 1

    # -- reads -------------------------------------------------------------
    def series(self, name):
        """Observations [(step, value), ...] oldest-first (possibly empty)."""
        return list(self.series_by_name.get(name, ()))

    def last(self, name):
        ring = self.series_by_name.get(name)
        return ring[-1] if ring else None

    def to_dict(self):
        return {
            "version": CATALOG_VERSION,
            "host": self.host,
            "ring_len": self.ring_len,
            "observations": self.observations,
            "series": {name: [[s, v] for s, v in ring]
                       for name, ring in sorted(self.series_by_name.items())},
        }


def merge_host_rings(rings_by_host):
    """Exact fleet merge of per-host ring snapshots (``MetricStore.to_dict``
    payloads keyed by host id, as the cluster dump plane delivers them).
    Geometry must match — mismatched ``ring_len`` raises, the same contract
    the PR 14 latency sketches enforce for their bin edges."""
    hosts = sorted(rings_by_host)
    if not hosts:
        return {"version": CATALOG_VERSION, "hosts": [], "ring_len": None,
                "series": {}}
    lens = {int(rings_by_host[h].get("ring_len", 0)) for h in hosts}
    if len(lens) != 1:
        raise ValueError(
            f"metric rings disagree on geometry (ring_len {sorted(lens)}) — "
            "refusing a lossy merge")
    series = {}
    for h in hosts:
        for name, obs in (rings_by_host[h].get("series") or {}).items():
            series.setdefault(name, {})[int(h)] = [[int(s), float(v)]
                                                   for s, v in obs]
    return {"version": CATALOG_VERSION, "hosts": [int(h) for h in hosts],
            "ring_len": lens.pop(),
            "series": {k: series[k] for k in sorted(series)}}


# -------------------------------------------------------- OpenMetrics export

_OM_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def openmetrics_name(name):
    """Catalog scalar name -> a valid OpenMetrics metric name."""
    out = _OM_BAD.sub("_", name.strip("/")).lower()
    if out and out[0].isdigit():
        out = "_" + out
    return out


def openmetrics_text(store_dict, catalog=None):
    """OpenMetrics text exposition of a ring snapshot's LATEST observation
    per metric (scrapers want the current value; the full ring travels in
    the dump plane, not the scrape). Deterministic: sorted by metric name."""
    catalog = catalog if catalog is not None else default_catalog()
    host = store_dict.get("host", 0)
    lines = []
    for name in sorted(store_dict.get("series") or {}):
        obs = store_dict["series"][name]
        if not obs:
            continue
        step, value = obs[-1]
        om = openmetrics_name(name)
        spec = catalog.resolve(name)
        if spec is not None:
            lines.append(f"# HELP {om} {spec.description}")
            lines.append(f"# UNIT {om} {spec.unit}")
        lines.append(f"# TYPE {om} gauge")
        lines.append(f'{om}{{host="{host}",step="{int(step)}"}} {value:g}')
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def export_store(store, path, catalog=None):
    """Write the OpenMetrics exposition of a live MetricStore to ``path``."""
    text = openmetrics_text(store.to_dict(), catalog=catalog)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        f.write(text)
    return path


# ------------------------------------------------------------------ the CLI


def _ring_from_source(path):
    """Ring snapshot from a scalars.jsonl ledger OR a flight-recorder dump
    (its ``alerts.ring`` block). Pure host JSON reading."""
    if path.endswith(".jsonl"):
        store = MetricStore(strict=False)
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                store.observe(rec["tag"], rec["value"], rec.get("step", 0))
        return store.to_dict()
    with open(path) as f:
        data = json.load(f)
    ring = (data.get("alerts") or {}).get("ring") or data.get("ring")
    if ring is None:
        raise ValueError(f"{path}: no metric ring (expected a scalars.jsonl "
                         "ledger or a flight-recorder dump with an alerts "
                         "block)")
    if "host" not in ring:
        ring = dict(ring, host=data.get("host", 0))
    return ring


def metrics_main(argv=None):
    """``ds-tpu metrics`` — catalog listing + OpenMetrics export."""
    import argparse
    ap = argparse.ArgumentParser(
        prog="ds-tpu metrics",
        description="metric catalog listing and OpenMetrics export")
    ap.add_argument("--json", action="store_true",
                    help="emit the catalog as JSON instead of a table")
    ap.add_argument("--export", metavar="SOURCE",
                    help="export the latest observations of SOURCE (a "
                         "scalars.jsonl ledger or a flight-recorder dump) "
                         "as OpenMetrics text")
    ap.add_argument("--out", metavar="PATH",
                    help="write the export/listing to PATH instead of stdout")
    args = ap.parse_args(argv)
    catalog = default_catalog()
    if args.export:
        try:
            ring = _ring_from_source(args.export)
        except (OSError, ValueError, KeyError) as e:
            print(f"metrics: {e}", flush=True)
            return 1
        text = openmetrics_text(ring, catalog=catalog)
    elif args.json:
        text = json.dumps(catalog.to_dict(), indent=2, sort_keys=True) + "\n"
    else:
        rows = [(s.pattern, s.unit, s.direction, s.klass, s.description)
                for s in catalog.specs]
        w0 = max(len(r[0]) for r in rows)
        w1 = max(len(r[1]) for r in rows)
        w2 = max(len(r[2]) for r in rows)
        lines = [f"{'METRIC':<{w0}}  {'UNIT':<{w1}}  {'DIRECTION':<{w2}}  "
                 f"CLASS       DESCRIPTION"]
        for p, u, d, k, desc in rows:
            lines.append(f"{p:<{w0}}  {u:<{w1}}  {d:<{w2}}  {k:<10}  {desc}")
        text = "\n".join(lines) + "\n"
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
    else:
        print(text, end="", flush=True)
    return 0
