"""FLOPs / memory profiler from compiled-program cost analysis.

The reference line of this framework later shipped a module-walking flops profiler
that recursively estimated per-layer multiply-adds from torch module types. On TPU
the compiler already knows the answer exactly: every jitted program carries XLA's
cost analysis (flops, bytes accessed) and memory stats (argument/output/temp
bytes). ``profile`` lowers + compiles a jittable fn and reads them; the numbers
are for the OPTIMIZED program — post-fusion, post-remat — so rematerialized
backward flops are counted, constant-folded work is not. That makes this the right
denominator for honest MFU accounting (``mfu`` divides by what the chip actually
executes... for model-quality MFU pass analytic ``6 * params * tokens`` instead).

Works for any jittable fn, including the engine's compiled train step
(``DeepSpeedEngine.flops_profile``).
"""

from typing import Any, Dict, Optional


def profile(fn, *args, peak_tflops: Optional[float] = None,
            static_argnums=()) -> Dict[str, Any]:
    """Compile ``fn(*args)`` and report its executed cost.

    ``args`` may be concrete arrays or ``jax.ShapeDtypeStruct``s (no data needed —
    profiling a 100B-param step does not require materializing it). Returns a dict:

    For SPMD programs (inputs sharded over a mesh) every figure is PER DEVICE —
    the cost analysis describes the partitioned program each device executes.
    That is the right denominator for per-chip MFU; multiply by the mesh size for
    whole-job totals.

    - ``flops``: total executed FLOPs of the optimized program
    - ``bytes_accessed``: HBM traffic the cost model charges (post-fusion)
    - ``arithmetic_intensity``: flops / bytes_accessed — below the chip's
      flops:bandwidth ratio the program is memory-bound
    - ``argument_bytes`` / ``output_bytes`` / ``temp_bytes``: compiled buffer
      footprint (temp = XLA's scratch high-water estimate)
    - ``optimal_seconds``: flops / peak (when ``peak_tflops`` given) — the
      roofline-compute lower bound on step time
    """
    import jax

    # a jit object, or anything lowerable like it (e.g. the telemetry
    # watchdog's _WatchedJit proxy) passes through unchanged
    if isinstance(fn, jax.stages.Wrapped) or hasattr(fn, "lower"):
        jitted = fn
    else:
        jitted = jax.jit(fn, static_argnums=static_argnums)
    compiled = jitted.lower(*args).compile()
    ca = compiled.cost_analysis()
    if not isinstance(ca, dict):  # older jax returned [dict]
        ca = ca[0] if ca else {}
    flops = float(ca.get("flops", 0.0))
    bytes_accessed = float(ca.get("bytes accessed", 0.0))
    mem = compiled.memory_analysis()
    report = {
        "flops": flops,
        "bytes_accessed": bytes_accessed,
        "arithmetic_intensity": flops / bytes_accessed if bytes_accessed else 0.0,
        "argument_bytes": int(getattr(mem, "argument_size_in_bytes", 0)),
        "output_bytes": int(getattr(mem, "output_size_in_bytes", 0)),
        "temp_bytes": int(getattr(mem, "temp_size_in_bytes", 0)),
    }
    if peak_tflops:
        report["optimal_seconds"] = flops / (peak_tflops * 1e12)
    return report


def mfu(report: Dict[str, Any], seconds: float, peak_tflops: float) -> float:
    """Model-flops utilization of a measured run: executed flops / (time * peak)."""
    return report["flops"] / (seconds * peak_tflops * 1e12)


def format_report(report: Dict[str, Any], title: str = "profile") -> str:
    def eng(v):
        for unit in ("", "K", "M", "G", "T", "P"):
            if abs(v) < 1000:
                return f"{v:7.2f} {unit}"
            v /= 1000.0
        return f"{v:7.2f} E"

    lines = [f"--- {title} ---",
             f"flops                : {eng(report['flops'])}",
             f"bytes accessed       : {eng(report['bytes_accessed'])}B",
             f"arithmetic intensity : {report['arithmetic_intensity']:.1f} flops/B",
             f"argument bytes       : {eng(float(report['argument_bytes']))}B",
             f"output bytes         : {eng(float(report['output_bytes']))}B",
             f"temp bytes           : {eng(float(report['temp_bytes']))}B"]
    if "optimal_seconds" in report:
        lines.append(f"optimal step time    : {report['optimal_seconds'] * 1e3:.2f} ms")
    if "params" in report:
        lines.append(f"params               : {eng(float(report['params']))}")
    return "\n".join(lines)
