"""Optimized-HLO inspection: collectives, aliasing, entry layout.

The framework's multi-chip claims are of the form "XLA emits the collective the
reference called NCCL/MPI for" (zero/sharding.py, pipeline_spmd.py, ring_attention.py,
custom_collectives.py). This module is the shared audit surface for that claim: it
parses a compiled program's text for collective instructions so tests
(tests/unit/test_collectives_hlo.py), the driver dry-run (__graft_entry__.py), the
program lint passes (deepspeed_tpu/lint/program_passes.py) and users debugging
shardings can count them and account wire bytes from ONE parser. The lint suite
additionally needs the module-header facts — ``input_output_alias`` (which donations
XLA actually honored) and ``entry_computation_layout`` (parameter/result types) —
parsed here for the same single-parser reason.
"""

import re
from collections import Counter

import numpy as np

COLLECTIVE_OPS = ("all-to-all", "all-gather", "all-reduce", "reduce-scatter",
                  "collective-permute")

# `%name = TYPE op(...)` where TYPE is a shaped type or a tuple of them
# (all-to-all returns a tuple). The optional ``-start`` suffix folds the async
# variants into their base op: ``all-gather-start`` IS the program's all-gather
# (the paired ``-done`` carries no transfer of its own and is never matched —
# counting both would double-book the wire).
_OP_RE = re.compile(r"= (\([^)]*\)|\S+) (" + "|".join(COLLECTIVE_OPS) +
                    r")(-start)?\(")

_DTYPE_BYTES = {"s4": 1, "u4": 1, "s8": 1, "u8": 1, "pred": 1,
                "f8e4m3": 1, "f8e4m3fn": 1, "f8e4m3b11fnz": 1, "f8e4m3fnuz": 1,
                "f8e5m2": 1, "f8e5m2fnuz": 1, "f8e3m4": 1,
                "s16": 2, "u16": 2, "bf16": 2, "f16": 2,
                "s32": 4, "u32": 4, "f32": 4,
                "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16}

_SHAPED_RE = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")


def dtype_bytes(dt):
    """Bytes per element of an HLO element-type string, or None if unknown."""
    return _DTYPE_BYTES.get(dt)


def _shaped_types(type_str):
    """[(dtype, (dims...))] for every shaped type inside ``type_str`` (tuples
    flattened; scalars yield empty dims)."""
    out = []
    for dt, dims in _SHAPED_RE.findall(type_str):
        out.append((dt, tuple(int(d) for d in dims.split(",") if d)))
    return out


def _elements(dims):
    n = 1
    for d in dims:
        n *= d
    return n


# one HLO instruction per `name = type op(...)` line (ROOT-prefixed or not);
# computation headers / ENTRY lines carry no ` = ` and don't match
_INSTR_RE = re.compile(r"^\s*(?:ROOT\s+)?%?[\w.-]+ = ", re.M)


def instruction_count(hlo_text):
    """Total HLO instructions across all computations of the optimized program.
    The telemetry HLO-identity guarantee is stated in these terms: default-mode
    telemetry (named_scope metadata + AOT watchdog) must not change this count."""
    return len(_INSTR_RE.findall(hlo_text))


def optimized_hlo(jitted, *args):
    """Optimized (post-SPMD-partitioner) HLO text of ``jitted`` on ``args``."""
    return jitted.lower(*args).compile().as_text()


def _collective_matches(hlo_text):
    """(result_type, base_op, is_start) per collective instruction."""
    return [(ty, op, bool(start)) for ty, op, start in _OP_RE.findall(hlo_text)]


def collective_counts(hlo_text):
    """{collective op name -> instruction count} over the optimized HLO.
    Async ``-start`` variants count under their base op name."""
    counts = Counter()
    for _result_ty, op, _start in _collective_matches(hlo_text):
        counts[op] += 1
    return dict(counts)


def _result_shapes(result_ty, op, is_start):
    """Shaped result types of one collective, skipping the bookkeeping an async
    ``-start`` carries. ``all-gather-start`` / ``collective-permute-start``
    return ``(operands..., results...[, u32 context scalars])`` — only the
    produced half is the transfer; ``all-reduce-start`` (and any untupled
    start) returns its results directly."""
    shaped = _shaped_types(result_ty)
    if (is_start and result_ty.startswith("(") and len(shaped) > 1
            and op in ("all-gather", "collective-permute")):
        shaped = [s for s in shaped
                  if not (s[1] == () and s[0] in ("u32", "s32"))]
        return shaped[len(shaped) // 2:]
    return shaped


def collective_results(hlo_text, op=None):
    """[(op, dtype, dims tuple)] of every collective instruction's produced
    results (tuples flattened, async operand echoes skipped). ``op`` filters to
    one base op name."""
    out = []
    for result_ty, found, is_start in _collective_matches(hlo_text):
        if op is not None and found != op:
            continue
        for dt, dims in _result_shapes(result_ty, found, is_start):
            out.append((found, dt, dims))
    return out


def collective_result_types(hlo_text, op):
    """Element-type strings of every ``op`` instruction's results (tuples
    flattened; async ``-start`` variants report their produced buffers only)."""
    return [dt for _op, dt, _dims in collective_results(hlo_text, op)]


def collective_bytes(hlo_text):
    """Approximate per-device collective wire bytes: for each collective
    instruction, bytes = result size (what each participant receives). The basis
    for the 1-bit Adam comm-volume accounting in PERF.md."""
    total = 0
    for _op, dt, dims in collective_results(hlo_text):
        if dt not in _DTYPE_BYTES:
            continue
        total += _elements(dims) * _DTYPE_BYTES[dt]
    return total


# ----------------------------------------------------------------- per-axis ledger
# A collective instruction names its participant grouping inline:
#   replica_groups={{0,1,2,3},{4,5,6,7}}        explicit groups
#   replica_groups=[4,2]<=[2,4]T(1,0)           iota form: reshape/transpose of
#                                               iota(N) into [groups, group_size]
#   replica_groups={}                           every participant, one group
#   source_target_pairs={{0,1},{1,2}}           collective-permute's equivalent
# Ids are the program's logical device numbers (device-assignment order == the
# flattened mesh.devices order, which on every mesh this repo builds equals the
# global device id — the same convention CommTopology.slice_device_sets uses).
_RG_EXPLICIT_RE = re.compile(r"replica_groups=\{((?:\{[^}]*\},?)*)\}")
_RG_IOTA_RE = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?")
_STP_RE = re.compile(r"source_target_pairs=\{((?:\{[^}]*\},?)*)\}")


def parse_replica_groups(line):
    """Participant groups of one collective instruction line: a list of int
    tuples, or None when the instruction names no grouping (or the empty
    ``{}`` grouping) — i.e. every participating device is one group."""
    m = _RG_IOTA_RE.search(line)
    if m:
        g, s = int(m.group(1)), int(m.group(2))
        dims = [int(d) for d in m.group(3).split(",")]
        arr = np.arange(int(np.prod(dims))).reshape(dims)
        if m.group(4):
            arr = arr.transpose([int(p) for p in m.group(4).split(",")])
        return [tuple(int(v) for v in row) for row in arr.reshape(g, s)]
    m = _RG_EXPLICIT_RE.search(line) or _STP_RE.search(line)
    if m is None or not m.group(1):
        return None
    return [tuple(int(v) for v in grp.split(",") if v)
            for grp in re.findall(r"\{([^}]*)\}", m.group(1))]


def collective_instructions(hlo_text):
    """[(base op, [(dtype, dims)...] produced results, groups-or-None)] for
    every collective instruction, line by line (async ``-start`` folded into
    the base op exactly as in ``collective_counts``)."""
    out = []
    for line in hlo_text.splitlines():
        m = _OP_RE.search(line)
        if not m:
            continue
        ty, op, start = m.groups()
        out.append((op, _result_shapes(ty, op, bool(start)),
                    parse_replica_groups(line)))
    return out


def collective_axis_bytes(hlo_text, slice_sets):
    """Split ``collective_bytes`` per network level against a slice
    factorization: ``{"ici": bytes, "dcn": bytes}``.

    ``slice_sets`` is a list of device-id sets (one per slice — see
    ``CommTopology.slice_device_sets``). An instruction accounts as ICI iff
    every one of its replica groups stays inside a single slice; any group
    spanning two slices rides the DCN. Ungrouped instructions (all devices)
    are ICI only on a single-slice factorization. The two buckets sum exactly
    to ``collective_bytes`` on the same program.
    """
    sets = [frozenset(s) for s in slice_sets]
    totals = {"ici": 0, "dcn": 0}
    for _op, shaped, groups in collective_instructions(hlo_text):
        b = sum(_elements(dims) * _DTYPE_BYTES[dt]
                for dt, dims in shaped if dt in _DTYPE_BYTES)
        if groups is None:
            intra = len(sets) <= 1
        else:
            intra = all(any(set(g) <= ss for ss in sets) for g in groups)
        totals["ici" if intra else "dcn"] += b
    return totals


def collective_axis_breakdown(hlo_text, slice_sets):
    """Per-op refinement of ``collective_axis_bytes``:
    ``{op: {"ici": {"count": n, "bytes": b}, "dcn": {...}}}`` with the same
    group-membership rule, so summing the leaves reproduces the two-bucket
    split exactly (the comm-sim CLI report is built from this)."""
    sets = [frozenset(s) for s in slice_sets]
    out = {}
    for op, shaped, groups in collective_instructions(hlo_text):
        b = sum(_elements(dims) * _DTYPE_BYTES[dt]
                for dt, dims in shaped if dt in _DTYPE_BYTES)
        if groups is None:
            intra = len(sets) <= 1
        else:
            intra = all(any(set(g) <= ss for ss in sets) for g in groups)
        lvl = out.setdefault(op, {"ici": {"count": 0, "bytes": 0},
                                  "dcn": {"count": 0, "bytes": 0}})
        lvl["ici" if intra else "dcn"]["count"] += 1
        lvl["ici" if intra else "dcn"]["bytes"] += b
    return out


# ------------------------------------------------------- async start/done pairs
# Post-scheduling HLO splits an overlappable collective into a `-start` that
# launches the transfer and a `-done` that blocks on it; every instruction the
# scheduler placed between the two runs concurrently with the wire. The step-
# anatomy analyzer (utils/anatomy.py) prices that window to split each
# collective into overlapped vs exposed time. Two syntactic forms exist:
# dedicated start/done ops (`all-reduce-start` / `all-reduce-done`) and the
# generic wrapper (`async-start(...), calls=%comp` holding the collective
# inside the called computation, optionally chained through `async-update`).

_DEF_NAME_RE = re.compile(r"^\s*(?:ROOT\s+)?%?([\w.-]+) = ")
_ASYNC_DONE_RE = re.compile(
    r"= .*?(" + "|".join(COLLECTIVE_OPS) + r"|async)-done\(([^)]*)\)")
_ASYNC_UPDATE_RE = re.compile(r"= .*?async-update\(([^)]*)\)")
_ASYNC_WRAPPER_RE = re.compile(r"= .*? async-start\(")
_CALLS_RE = re.compile(r"calls=%?([\w.-]+)")
_COMP_HEADER_RE = re.compile(r"^\s*(?:ENTRY\s+)?%?([\w.-]+)\s+(?:\([^{]*\))?\s*"
                             r"(?:->\s*[^{]*)?\{\s*$")


def _operand_name(operand_text):
    """Instruction name from a (possibly type-annotated) operand: both
    ``f32[1024]{0} %ars`` and ``%ars``/``ars`` yield ``ars``."""
    toks = operand_text.strip().split()
    return toks[-1].lstrip("%") if toks else ""


def _called_computation_window(lines, comp_name):
    """Line-index range (start, stop) of computation ``comp_name``'s body."""
    for i, line in enumerate(lines):
        m = _COMP_HEADER_RE.match(line)
        if m and m.group(1) == comp_name:
            for j in range(i + 1, len(lines)):
                if lines[j].strip().startswith("}"):
                    return i + 1, j
            return i + 1, len(lines)
    return None


def parse_async_pairs(hlo_text):
    """Pair every async collective ``-start`` with its ``-done`` across the
    program text. Returns one dict per pair, in done order::

        {"op": base op, "name": start instruction name, "done": done name,
         "start_line": int, "done_line": int,   # indices into splitlines()
         "bytes": per-device transfer bytes, "groups": replica groups or None}

    Dedicated forms (``all-reduce-start`` ...) read bytes/groups off the start
    line with the same tuple conventions as ``collective_results``; generic
    ``async-start`` wrappers resolve ``calls=`` to the inner collective, and
    ``async-update`` chains forward to the original start. A ``-done`` whose
    operand resolves to no known start raises ``ValueError`` — a malformed
    program must fail loudly, not silently drop a collective from the ledger.
    """
    lines = hlo_text.splitlines()
    starts = {}   # start name -> pair dict (without done fields yet)
    alias = {}    # async-update result name -> upstream operand name
    pairs = []
    for i, line in enumerate(lines):
        m_op = _OP_RE.search(line)
        if m_op and m_op.group(3):  # dedicated `<op>-start`
            name_m = _DEF_NAME_RE.match(line)
            if not name_m:
                continue
            ty, op, _ = m_op.groups()
            b = sum(_elements(dims) * _DTYPE_BYTES[dt]
                    for dt, dims in _result_shapes(ty, op, True)
                    if dt in _DTYPE_BYTES)
            starts[name_m.group(1)] = {
                "op": op, "name": name_m.group(1), "start_line": i,
                "bytes": b, "groups": parse_replica_groups(line),
                "inner_line": None}
            continue
        if _ASYNC_WRAPPER_RE.search(line):  # generic wrapper form
            name_m = _DEF_NAME_RE.match(line)
            calls_m = _CALLS_RE.search(line)
            if not name_m:
                continue
            op, b, groups, inner_line = None, 0, None, None
            if calls_m:
                window = _called_computation_window(lines, calls_m.group(1))
                if window:
                    for k in range(window[0], window[1]):
                        m_in = _OP_RE.search(lines[k])
                        if m_in:
                            ty, op, is_start = m_in.groups()
                            b = sum(_elements(dims) * _DTYPE_BYTES[dt]
                                    for dt, dims in
                                    _result_shapes(ty, op, bool(is_start))
                                    if dt in _DTYPE_BYTES)
                            groups = parse_replica_groups(lines[k])
                            inner_line = k
                            break
            if op is not None:
                starts[name_m.group(1)] = {
                    "op": op, "name": name_m.group(1), "start_line": i,
                    "bytes": b, "groups": groups, "inner_line": inner_line}
            continue
        m_upd = _ASYNC_UPDATE_RE.search(line)
        if m_upd:
            name_m = _DEF_NAME_RE.match(line)
            if name_m:
                alias[name_m.group(1)] = _operand_name(m_upd.group(1))
            continue
        m_done = _ASYNC_DONE_RE.search(line)
        if m_done:
            done_m = _DEF_NAME_RE.match(line)
            operand = _operand_name(m_done.group(2))
            seen = set()
            while operand in alias and operand not in seen:  # update chains
                seen.add(operand)
                operand = alias[operand]
            pair = starts.pop(operand, None)
            if pair is None:
                raise ValueError(
                    f"async {m_done.group(1)}-done "
                    f"{done_m.group(1) if done_m else '<unnamed>'!r} has no "
                    f"matching -start for operand {operand!r}")
            pair["done"] = done_m.group(1) if done_m else ""
            pair["done_line"] = i
            pairs.append(pair)
    return pairs


def collective_lines(hlo_text):
    """[(line index, instruction name, base op, is_start, produced bytes,
    groups-or-None)] per collective instruction, in program order — the
    line-indexed refinement of ``collective_instructions`` the anatomy
    analyzer needs to tell paired async starts from synchronous collectives."""
    out = []
    for i, line in enumerate(hlo_text.splitlines()):
        m = _OP_RE.search(line)
        if not m:
            continue
        ty, op, start = m.groups()
        name_m = _DEF_NAME_RE.match(line)
        b = sum(_elements(dims) * _DTYPE_BYTES[dt]
                for dt, dims in _result_shapes(ty, op, bool(start))
                if dt in _DTYPE_BYTES)
        out.append((i, name_m.group(1) if name_m else "", op, bool(start), b,
                    parse_replica_groups(line)))
    return out


# ------------------------------------------------------- metadata / identity
# The profiler's device timeline names slices by (hlo_module, hlo_op); mapping
# them back to the engine's named scopes needs two more module facts: the
# HloModule header name (the trace's ``hlo_module`` key) and each entry
# instruction's ``metadata={op_name="jit(f)/.../ds_grad_bucket0/mul"}`` — the
# jaxpr scope path ``jax.named_scope`` threads through compilation. CPU traces
# carry bare instruction names, so the metadata map is the only scope source
# there; TPU traces prefix scopes in the op name itself and use this map as a
# cross-check.
_MODULE_NAME_RE = re.compile(r"^HloModule\s+([\w.-]+)")
_METADATA_OP_NAME_RE = re.compile(r'metadata=\{[^{}]*op_name="([^"]*)"')


def module_name(hlo_text):
    """The ``HloModule`` header name (e.g. ``jit_loss_and_grad``) — the same
    string the profiler's trace events carry as ``args.hlo_module``. Empty
    when the text has no module header."""
    m = _MODULE_NAME_RE.match(hlo_text)
    return m.group(1) if m else ""


def instruction_op_names(hlo_text):
    """{instruction name: metadata op_name} over every definition line that
    carries ``op_name`` metadata, across all computations. The op_name is the
    traced scope path (``jit(fn)/jit(main)/<named scopes>/<primitive>``);
    callers regex their scope tokens out of it."""
    out = {}
    for line in hlo_text.splitlines():
        d = _DEF_NAME_RE.match(line)
        if not d:
            continue
        m = _METADATA_OP_NAME_RE.search(line)
        if m:
            out[d.group(1)] = m.group(1)
    return out


# per-instruction cost estimates for the overlap-window pricing: a window's
# compute capacity is what the scheduler placed between -start and -done,
# priced as max(dot flops / peak, result bytes / HBM bandwidth)
_DOT_LINE_RE = re.compile(r"= (\S+) dot\(([^)]*)\)")
_LHS_CDIMS_RE = re.compile(r"lhs_contracting_dims=\{([\d,]*)\}")
_RESULT_TY_RE = re.compile(r"^\s*(?:ROOT\s+)?%?[\w.-]+ = (\([^)]*\)|\S+) ")


def dot_flops_estimate(line):
    """2 * result_elements * contraction_size for one ``dot`` instruction line,
    reading the contraction off the lhs operand's inline type annotation
    (optimized HLO always annotates). 0 when the line is not an annotated dot
    — the overlap estimate stays conservative (no phantom compute credit)."""
    m = _DOT_LINE_RE.search(line)
    if not m:
        return 0
    result = _shaped_types(m.group(1))
    cd = _LHS_CDIMS_RE.search(line)
    if not result or not cd:
        return 0
    operands = _split_top_level(m.group(2))
    lhs = _shaped_types(operands[0]) if operands else []
    if not lhs:
        return 0
    cdims = [int(d) for d in cd.group(1).split(",") if d]
    contraction = 1
    for d in cdims:
        if d >= len(lhs[0][1]):
            return 0
        contraction *= lhs[0][1][d]
    return 2 * _elements(result[0][1]) * contraction


def result_bytes(line):
    """Bytes of one instruction line's produced result(s) — the HBM-write
    proxy the overlap-window pricing charges per scheduled instruction."""
    m = _RESULT_TY_RE.match(line)
    if not m:
        return 0
    return sum(_elements(dims) * _DTYPE_BYTES[dt]
               for dt, dims in _shaped_types(m.group(1))
               if dt in _DTYPE_BYTES)


# --------------------------------------------------------------------- lint surface
# The module header of an optimized program names which donations XLA actually
# honored: `input_output_alias={ {out_idx}: (param_number, {param_idx}, kind) }`.
_ALIAS_HEADER_RE = re.compile(r"input_output_alias=\{((?:[^{}]|\{[^}]*\})*)\}")
_ALIAS_ENTRY_RE = re.compile(r"\{([0-9, ]*)\}:\s*\((\d+),\s*\{([0-9, ]*)\},\s*([\w-]+)\)")


def input_output_aliases(hlo_text):
    """{param_number -> [(output_index, param_index, kind)]} from the module
    header; empty when the program aliases nothing (the header is then absent)."""
    m = _ALIAS_HEADER_RE.search(hlo_text)
    if not m:
        return {}
    out = {}

    def idx(s):
        return tuple(int(x) for x in s.replace(" ", "").split(",") if x)

    for out_idx, param, param_idx, kind in _ALIAS_ENTRY_RE.findall(m.group(1)):
        out.setdefault(int(param), []).append((idx(out_idx), idx(param_idx), kind))
    return out


def _entry_layout_body(hlo_text):
    """'(params...)->result' body of the entry_computation_layout header, via a
    balanced-brace scan (layout annotations like ``{1,0}`` nest braces)."""
    marker = "entry_computation_layout={"
    start = hlo_text.find(marker)
    if start < 0:
        return None
    i, depth = start + len(marker), 1
    while i < len(hlo_text) and depth:
        if hlo_text[i] == "{":
            depth += 1
        elif hlo_text[i] == "}":
            depth -= 1
        i += 1
    return hlo_text[start + len(marker):i - 1]


def _split_top_level(s):
    """Split a type-tuple body on top-level commas (layout braces `{1,0}` and
    nested tuples carry commas of their own)."""
    parts, depth, cur = [], 0, []
    for ch in s:
        if ch in "({[":
            depth += 1
        elif ch in ")}]":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        parts.append("".join(cur))
    return [p.strip() for p in parts if p.strip()]


def entry_parameter_types(hlo_text):
    """[(dtype, dims)] per entry parameter (one entry per parameter, in param-
    number order; a tuple-typed parameter reports its first shaped leaf)."""
    body = _entry_layout_body(hlo_text)
    if body is None or "->" not in body:
        return []
    params = body.split("->", 1)[0].strip()
    if params.startswith("(") and params.endswith(")"):
        params = params[1:-1]
    out = []
    for part in _split_top_level(params):
        shaped = _shaped_types(part)
        out.append(shaped[0] if shaped else (part, ()))
    return out


def entry_result_types(hlo_text):
    """[(dtype, dims)] of the entry computation's results (tuple flattened)."""
    body = _entry_layout_body(hlo_text)
    if body is None or "->" not in body:
        return []
    return _shaped_types(body.split("->", 1)[1])


# ------------------------------------------------------------- buffer table
# The module header's buffer_donor set names parameters the caller donated but
# XLA left unaliased (they are still freed, just not reused in place):
#   buffer_donor={ (1, {}), (3, {}) }
_BUFFER_DONOR_RE = re.compile(r"buffer_donor=\{((?:[^{}]|\{[^}]*\})*)\}")
_BUFFER_DONOR_ENTRY_RE = re.compile(r"\((\d+),\s*\{[0-9, ]*\}\)")


def _type_bytes(shaped):
    return sum(_elements(dims) * _DTYPE_BYTES.get(dt, 0) for dt, dims in shaped)


def entry_buffer_table(hlo_text):
    """Per-buffer view of an optimized program's entry interface — the HBM
    observatory's parsing surface (utils/hbm.py classifies these rows against
    the engine's memory manifest).

    Returns::

        {"parameters": [{"param": i, "leaves": [(dtype, dims, bytes)],
                         "bytes": total, "donated": bool,
                         "aliased_outputs": [output_index tuples]}],
         "results": [{"index": j, "dtype": dt, "dims": dims, "bytes": b,
                      "aliased": bool}],
         "parameter_bytes": int, "result_bytes": int,
         "aliased_result_bytes": int, "unaliased_result_bytes": int}

    Shapes are the post-SPMD per-device shapes of the compiled module (one
    entry parameter per flattened pytree leaf under jit). ``donated`` is true
    when the parameter appears in either donation header (``input_output_alias``
    — donation honored in place — or ``buffer_donor`` — donated, freed, but not
    aliased to an output). A result leaf is ``aliased`` when an input buffer
    backs it, i.e. it occupies no HBM beyond its parameter's bytes."""
    body = _entry_layout_body(hlo_text)
    if body is None or "->" not in body:
        return {"parameters": [], "results": [], "parameter_bytes": 0,
                "result_bytes": 0, "aliased_result_bytes": 0,
                "unaliased_result_bytes": 0}
    params_str, result_str = body.split("->", 1)
    params_str = params_str.strip()
    if params_str.startswith("(") and params_str.endswith(")"):
        params_str = params_str[1:-1]
    aliases = input_output_aliases(hlo_text)
    donors = set()
    m = _BUFFER_DONOR_RE.search(hlo_text)
    if m:
        donors = {int(p) for p in _BUFFER_DONOR_ENTRY_RE.findall(m.group(1))}
    aliased_outputs = {tuple(out_idx)
                       for rows in aliases.values()
                       for out_idx, _param_idx, _kind in rows}
    parameters = []
    for i, part in enumerate(_split_top_level(params_str)):
        shaped = _shaped_types(part)
        leaves = [(dt, dims, _elements(dims) * _DTYPE_BYTES.get(dt, 0))
                  for dt, dims in shaped]
        parameters.append({
            "param": i,
            "leaves": leaves,
            "bytes": sum(b for _dt, _dims, b in leaves),
            "donated": i in aliases or i in donors,
            "aliased_outputs": sorted(out_idx for out_idx, _pi, _k in
                                      aliases.get(i, [])),
        })
    result_str = result_str.strip()
    if result_str.startswith("(") and result_str.endswith(")"):
        result_str = result_str[1:-1]
        result_parts = _split_top_level(result_str)
    else:
        result_parts = [result_str]
    results = []
    for j, part in enumerate(result_parts):
        shaped = _shaped_types(part)
        if not shaped:
            continue
        dt, dims = shaped[0]
        results.append({
            "index": j, "dtype": dt, "dims": dims,
            "bytes": _type_bytes(shaped),
            "aliased": (j,) in aliased_outputs or (() in aliased_outputs
                                                   and len(result_parts) == 1),
        })
    parameter_bytes = sum(p["bytes"] for p in parameters)
    result_bytes = sum(r["bytes"] for r in results)
    aliased_result_bytes = sum(r["bytes"] for r in results if r["aliased"])
    return {
        "parameters": parameters,
        "results": results,
        "parameter_bytes": parameter_bytes,
        "result_bytes": result_bytes,
        "aliased_result_bytes": aliased_result_bytes,
        "unaliased_result_bytes": result_bytes - aliased_result_bytes,
    }


_USE_RE = re.compile(r"%([\w.-]+)")


def temp_allocation_estimate(hlo_text):
    """Analytic peak-temp estimate: a def-to-last-use liveness scan over the
    ENTRY computation's instruction lines. Each non-parameter instruction's
    result bytes go live at its definition line and die after the last line
    referencing it; the estimate is the peak of concurrently-live bytes,
    excluding parameters (argument bytes) and the ROOT tuple (output bytes) —
    i.e. the same bucket ``memory_analysis().temp_size_in_bytes`` measures.

    Fusion-internal buffers are invisible at this granularity (a fusion's
    temp is its result), so the estimate is a scheduling-free LOWER-bound
    companion to the measured temp watermark, good for attribution and
    cross-run comparison rather than exact byte parity."""
    lines = hlo_text.splitlines()
    entry_start = None
    for i in range(len(lines) - 1, -1, -1):
        if lines[i].startswith("ENTRY "):
            entry_start = i
            break
    if entry_start is None:
        return 0
    entry_end = len(lines)
    for i in range(entry_start + 1, len(lines)):
        if lines[i].startswith("}"):
            entry_end = i
            break
    defs = {}       # name -> (def line, bytes)
    last_use = {}   # name -> last line referencing it as an operand
    for i in range(entry_start + 1, entry_end):
        line = lines[i]
        name_m = _DEF_NAME_RE.match(line)
        if not name_m:
            continue
        name = name_m.group(1)
        is_param = " parameter(" in line
        is_root = line.lstrip().startswith("ROOT ")
        if not is_param and not is_root:
            defs[name] = (i, result_bytes(line))
        for used in _USE_RE.findall(line.split("=", 1)[1]):
            if used != name:
                last_use[used] = i
    deaths = {}
    for name, (_def_line, b) in defs.items():
        deaths.setdefault(last_use.get(name, entry_end), []).append(name)
    live = peak = 0
    for i in range(entry_start + 1, entry_end):
        for name, (def_line, b) in defs.items():
            if def_line == i:
                live += b
        peak = max(peak, live)
        for name in deaths.get(i, ()):
            live -= defs[name][1]
    return peak


_F32_DOT_RE = re.compile(r"%?([\w.-]+) = f32\[[^\]]*\][^ ]* dot\(([^)]*)\)")
# optimized HLO annotates operands inline (`convert(bf16[8]{0} %x)`); the
# pre-backend module the dtype lint reads writes bare names (`convert(x.4)`),
# so the operand's source dtype comes from the inline annotation when present
# and the defining instruction otherwise.
_CONVERT_RE = re.compile(
    r"%?([\w.-]+) = ([a-z0-9]+)\[[^\]]*\][^ ]* convert\("
    r"(?:([a-z0-9]+)\[[^\]]*\][^ ]* )?%?([\w.-]+)\)")
_DEF_RE = re.compile(r"^\s*(?:ROOT )?%?([\w.-]+) = ([a-z0-9]+)\[", re.M)


def _definition_dtypes(hlo_text):
    """{instruction name: result element type} over every definition line."""
    return dict(_DEF_RE.findall(hlo_text))


def _convert_table(hlo_text):
    """{result name: (src dtype, dst dtype, operand name)} for every convert."""
    defs = None
    out = {}
    for name, dst, src, operand in _CONVERT_RE.findall(hlo_text):
        if not src:
            if defs is None:
                defs = _definition_dtypes(hlo_text)
            src = defs.get(operand, "")
        if src:
            out[name] = (src, dst, operand)
    return out


def f32_dots_with_lowp_operands(hlo_text, lowp=("bf16", "f16")):
    """[(dot name, [operand names converted from a low-precision dtype])] for
    every f32 dot at least one of whose operands is the direct result of a
    convert from ``lowp``. The dtype-promotion lint's primary probe: inside a
    declared low-precision compute region, such a dot means XLA (or the traced
    program) silently promoted a matmul the author believed ran on the
    low-precision MXU path."""
    lowp_converts = {name for name, (src, _dst, _op) in
                     _convert_table(hlo_text).items() if src in lowp}
    hits = []
    for dot_name, operands in _F32_DOT_RE.findall(hlo_text):
        names = [tok.split()[-1].lstrip("%")
                 for tok in operands.split(",") if tok.strip()]
        promoted = [n for n in names if n in lowp_converts]
        if promoted:
            hits.append((dot_name, promoted))
    return hits


def lossy_convert_roundtrips(hlo_text):
    """[(first convert name, dtype chain)] for convert pairs d1 -> d2 -> d1
    where the intermediate d2 is NARROWER than d1: a value made a lossy round
    trip (each such pair silently truncates mantissa and usually marks a dtype
    boundary drawn in the wrong place)."""
    converts = _convert_table(hlo_text)
    hits = []
    for name, (src, dst, operand) in sorted(converts.items()):
        up = converts.get(operand)
        if up is None:
            continue
        src0, dst0, _ = up
        if src0 == dst and dst0 == src:  # d1 -> d2 (=src) -> d1 (=dst)
            b_mid = _DTYPE_BYTES.get(src, 0) or 0
            b_end = _DTYPE_BYTES.get(dst, 0) or 0
            if b_mid and b_end and b_mid < b_end:
                hits.append((operand, (dst, src, dst)))
    return hits
