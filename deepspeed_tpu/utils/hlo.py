"""Optimized-HLO collective inspection.

The framework's multi-chip claims are of the form "XLA emits the collective the
reference called NCCL/MPI for" (zero/sharding.py, pipeline_spmd.py, ring_attention.py,
custom_collectives.py). This module is the shared audit surface for that claim: it
parses a compiled program's text for collective instructions so tests
(tests/unit/test_collectives_hlo.py), the driver dry-run (__graft_entry__.py), and
users debugging shardings can count them and account wire bytes from ONE parser.
"""

import re
from collections import Counter

COLLECTIVE_OPS = ("all-to-all", "all-gather", "all-reduce", "reduce-scatter",
                  "collective-permute")

# `%name = TYPE op(...)` where TYPE is a shaped type or a tuple of them
# (all-to-all returns a tuple). Matches the -start variants' base names too.
_OP_RE = re.compile(r"= (\([^)]*\)|\S+) (" + "|".join(COLLECTIVE_OPS) + r")\(")

_DTYPE_BYTES = {"s8": 1, "u8": 1, "pred": 1, "s16": 2, "u16": 2, "bf16": 2,
                "f16": 2, "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8,
                "f64": 8}


# one HLO instruction per `name = type op(...)` line (ROOT-prefixed or not);
# computation headers / ENTRY lines carry no ` = ` and don't match
_INSTR_RE = re.compile(r"^\s*(?:ROOT\s+)?%?[\w.-]+ = ", re.M)


def instruction_count(hlo_text):
    """Total HLO instructions across all computations of the optimized program.
    The telemetry HLO-identity guarantee is stated in these terms: default-mode
    telemetry (named_scope metadata + AOT watchdog) must not change this count."""
    return len(_INSTR_RE.findall(hlo_text))


def optimized_hlo(jitted, *args):
    """Optimized (post-SPMD-partitioner) HLO text of ``jitted`` on ``args``."""
    return jitted.lower(*args).compile().as_text()


def collective_counts(hlo_text):
    """{collective op name -> instruction count} over the optimized HLO."""
    counts = Counter()
    for _result_ty, op in _OP_RE.findall(hlo_text):
        counts[op] += 1
    return dict(counts)


def collective_result_types(hlo_text, op):
    """Element-type strings of every ``op`` instruction's results (tuples flattened)."""
    out = []
    for result_ty, found in _OP_RE.findall(hlo_text):
        if found == op:
            out.extend(re.findall(r"([a-z0-9]+)\[", result_ty))
    return out


def collective_bytes(hlo_text):
    """Approximate per-device collective wire bytes: for each collective
    instruction, bytes = result size (what each participant receives). The basis
    for the 1-bit Adam comm-volume accounting in PERF.md."""
    total = 0
    for result_ty, _op in _OP_RE.findall(hlo_text):
        for dt, dims in re.findall(r"([a-z0-9]+)\[([0-9,]*)\]", result_ty):
            if dt not in _DTYPE_BYTES:
                continue
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            total += n * _DTYPE_BYTES[dt]
    return total
