"""Step-time anatomy: roofline ledger + async-overlap analysis per program.

Everything here is static analysis over artifacts the compile watchdog (or the
lint registry) already captures — optimized HLO text, ``cost_analysis`` flops,
``bytes accessed`` — so the analyzer adds zero device syncs and runs identically
on a laptop reading a saved artifact or inside ``TelemetrySession.end_step``.

Per program the analysis has two halves:

* **Overlap**: post-scheduling HLO splits each overlappable collective into a
  ``-start``/``-done`` pair (``hlo.parse_async_pairs``); every instruction the
  scheduler placed inside the window runs concurrently with the wire. The
  window's hiding capacity is priced as ``max(window flops / peak, window
  bytes / HBM bw)`` and whatever the wire time exceeds it by is **exposed**.
  A synchronous collective (the only kind the CPU backend emits) hides
  nothing — fully exposed, flagged ``zero_overlap`` — with one exception:
  collectives tagged with the bucketed-exchange scope
  (``comm.hierarchical.GRAD_BUCKET_SCOPE``, ``ds_grad_bucket{k}``) are priced
  by the bucket-pipeline model below even when the backend serialized them.
* **Roofline** (utils/roofline.py): compute and HBM floors from the cost
  analysis, plus the exposed-comm seconds split ICI/DCN by the same
  slice-membership rule as ``hlo.collective_axis_bytes`` — together the
  predicted step floor and the MFU ceiling the program structure permits.

``ds-tpu anatomy`` runs the analysis over the full lint registry on the
8-virtual-device CPU mesh, emits a deterministic ``--json`` report, an
optional predicted-schedule Perfetto timeline, named zero-overlap
optimization opportunities, and the golden-pinned flat-vs-hierarchical
comm comparison (exposed-DCN must drop under the two-level exchange).
"""

import argparse
import json
import re
import sys

from . import hlo
from .roofline import resolve_spec, roofline
from .trace_event import (complete_slice, process_name_event, serialize_trace,
                          thread_meta_events, trace_envelope)

ANATOMY_REPORT_VERSION = 1
ANATOMY_REPORT_KIND = "anatomy_report"

# zero-overlap collectives below this wire size are noise (scalar loss pmeans,
# norm all-reduces), not optimization opportunities
DEFAULT_OPPORTUNITY_MIN_BYTES = 1024

# the named_scope the bucketed grad exchange wraps each bucket's chain in
# (kept textually in sync with comm.hierarchical.GRAD_BUCKET_SCOPE — pinned by
# tests/unit/test_anatomy.py — so parsing HLO text never imports jax)
_BUCKET_RE = re.compile(r"ds_grad_bucket(\d+)/")


def _bucket_windows(lines):
    """Per-bucket issue windows of a bucketed grad exchange, from the
    scheduled entry computation: bucket ``k``'s window runs from the first
    entry line carrying its ``ds_grad_bucket{k}/`` scope (its producer
    fusion — the backward compute that completes the bucket's subtree) to the
    next bucket's first tagged line, and the last bucket's to the entry ROOT
    (bucketed grad outputs feed nothing but the ROOT tuple, so the wire may
    stay in flight until the step's end). Returns ``{bucket: (start, end)}``
    line-index pairs, empty when no bucket scope appears."""
    entry_start = 0
    for i in range(len(lines) - 1, -1, -1):
        if lines[i].startswith("ENTRY "):
            entry_start = i
            break
    firsts = {}
    root = len(lines) - 1
    for i in range(entry_start, len(lines)):
        m = _BUCKET_RE.search(lines[i])
        if m:
            firsts.setdefault(int(m.group(1)), i)
        if lines[i].lstrip().startswith("ROOT "):
            root = i
            break
    order = sorted(firsts.items(), key=lambda kv: kv[1])
    windows = {}
    for idx, (k, start) in enumerate(order):
        end = order[idx + 1][1] if idx + 1 < len(order) else root
        windows[k] = (start, end)
    return windows


def _price_bucketed(rows, lines, spec):
    """Overlap pricing for bucket-tagged synchronous collectives — the
    eager-issue model of the bucketed exchange (docs/overlap.md).

    Buckets are mutually independent chains (bucket k's reduce-scatter
    consumes only bucket k's producer fusion; its all-gather feeds only the
    ROOT tuple), so even though the linearized schedule serializes them, an
    async runtime keeps every bucket's phases in flight simultaneously.
    Per row the hiding credit is:

    * compute scheduled in the bucket's own issue window (disjoint windows,
      ``_bucket_windows`` — no compute is double-counted across buckets), and
    * for **ICI** rows only: the DCN wire seconds of every *other* bucket —
      the cross-level overlap the split two-level exchange exists to create
      (bucket k's reduce-scatter/all-gather ride under bucket j's in-flight
      cross-slice psum on the independent, slower DCN link).

    DCN rows are never credited with ICI wire: the slow link is the
    exchange's drain and hides only behind real compute. Like the async
    window model above, this is per-row ceiling accounting — rows do not
    contend for shared hiding capacity."""
    tagged = [r for r in rows if not r["async"] and r["bucket"] is not None]
    if not tagged:
        return
    windows = _bucket_windows(lines)
    dcn_wire = {}
    for r in tagged:
        if r["level"] == "dcn":
            dcn_wire[r["bucket"]] = dcn_wire.get(r["bucket"], 0.0) + r["comm_s"]
    for r in tagged:
        win = windows.get(r["bucket"])
        if win is None:
            continue
        hide = _window_hiding_seconds(lines, win[0], win[1], spec)
        if r["level"] == "ici":
            hide += sum(s for j, s in dcn_wire.items() if j != r["bucket"])
        overlap_s = min(r["comm_s"], hide)
        r["zero_overlap"] = overlap_s <= 0.0
        r["overlap_s"] = overlap_s
        r["exposed_s"] = r["comm_s"] - overlap_s


def _us(seconds):
    """Deterministic microsecond rounding for report/timeline fields."""
    return round(seconds * 1e6, 3)


def _level(groups, slice_sets):
    """"ici" iff every replica group stays inside one slice set — the same
    membership rule as ``hlo.collective_axis_bytes``."""
    sets = slice_sets or []
    if len(sets) <= 1:
        return "ici"  # single-slice (or unset) factorization: no DCN exists
    if groups is None:
        return "dcn"  # every device participates, spanning the slices
    return ("ici" if all(any(set(g) <= set(ss) for ss in sets) for g in groups)
            else "dcn")


def _window_hiding_seconds(lines, start_line, done_line, spec):
    """Seconds of wire time the compute scheduled inside one ``-start`` →
    ``-done`` window can hide: max(window dot flops / peak, window result
    bytes / HBM bw) over the strictly-between instruction lines. Other
    collective lines in the window contribute no hiding credit — their own
    wire time is accounted on their own ledger rows."""
    win_flops = 0
    win_bytes = 0
    for k in range(start_line + 1, done_line):
        line = lines[k]
        if hlo._OP_RE.search(line):
            continue
        win_flops += hlo.dot_flops_estimate(line)
        win_bytes += hlo.result_bytes(line)
    return max(win_flops / spec.peak_flops,
               win_bytes / (spec.hbm_gbps * 1e9))


def analyze_program(hlo_text, flops, hbm_bytes, spec, slice_sets=None,
                    name=""):
    """The full anatomy of one compiled program.

    Returns ``{"name", "flops", "hbm_bytes", "collectives": [...],
    "wire_bytes": {"ici", "dcn"}, "exposed_s": {"ici", "dcn"},
    "roofline": {...}}`` where each collective row carries ``{"instruction",
    "op", "line", "level", "bytes", "async", "zero_overlap", "bucket",
    "comm_s", "overlap_s", "exposed_s"}`` (``bucket`` is the
    ``ds_grad_bucket{k}`` id for bucketed-exchange collectives, else None —
    tagged synchronous rows are priced by ``_price_bucketed`` instead of the
    fully-exposed rule). Raises ``ValueError`` on malformed async pairing
    (propagated from ``hlo.parse_async_pairs``) — an unparseable exposed-comm
    report must fail loudly.
    """
    lines = hlo_text.splitlines()
    pairs = hlo.parse_async_pairs(hlo_text)
    paired_start_lines = {p["start_line"] for p in pairs}
    inner_lines = {p["inner_line"] for p in pairs
                   if p["inner_line"] is not None}
    rows = []
    for pair in pairs:
        comm_s = pair["bytes"] / (spec.link_gbps(
            _level(pair["groups"], slice_sets)) * 1e9)
        hide_s = _window_hiding_seconds(lines, pair["start_line"],
                                        pair["done_line"], spec)
        overlap_s = min(comm_s, hide_s)
        m = _BUCKET_RE.search(lines[pair["start_line"]])
        rows.append({
            "instruction": pair["name"], "op": pair["op"],
            "line": pair["start_line"],
            "level": _level(pair["groups"], slice_sets),
            "bytes": pair["bytes"], "async": True,
            "zero_overlap": overlap_s <= 0.0,
            "bucket": int(m.group(1)) if m else None,
            "comm_s": comm_s, "overlap_s": overlap_s,
            "exposed_s": comm_s - overlap_s,
        })
    for line_no, iname, op, _is_start, b, groups in hlo.collective_lines(
            hlo_text):
        if line_no in paired_start_lines or line_no in inner_lines:
            continue
        # synchronous (or unpaired-start, conservatively): fully exposed,
        # unless bucket-tagged — _price_bucketed reprices those below
        level = _level(groups, slice_sets)
        comm_s = b / (spec.link_gbps(level) * 1e9)
        m = _BUCKET_RE.search(lines[line_no])
        rows.append({
            "instruction": iname, "op": op, "line": line_no, "level": level,
            "bytes": b, "async": False, "zero_overlap": True,
            "bucket": int(m.group(1)) if m else None,
            "comm_s": comm_s, "overlap_s": 0.0, "exposed_s": comm_s,
        })
    _price_bucketed(rows, lines, spec)
    rows.sort(key=lambda r: r["line"])
    wire = {"ici": 0, "dcn": 0}
    exposed = {"ici": 0.0, "dcn": 0.0}
    for r in rows:
        wire[r["level"]] += r["bytes"]
        exposed[r["level"]] += r["exposed_s"]
    return {
        "name": name,
        "flops": float(flops),
        "hbm_bytes": float(hbm_bytes),
        "collectives": rows,
        "wire_bytes": wire,
        "exposed_s": exposed,
        "roofline": roofline(flops, hbm_bytes, exposed["ici"], exposed["dcn"],
                             spec),
    }


def analyze_artifact(artifact, spec, slice_sets=None):
    """``analyze_program`` over one lint ``ProgramArtifact`` (optimized HLO +
    cost_analysis stats). The report carries the memory cross-link — the
    entry-layout byte attribution from utils/hbm's parsers — so one sweep
    answers both where the step's *time* and where its *HBM* go."""
    cost = getattr(artifact, "cost_stats", {}) or {}
    report = analyze_program(artifact.hlo_text, cost.get("flops", 0.0),
                             cost.get("bytes_accessed", 0.0), spec,
                             slice_sets=slice_sets, name=artifact.name)
    try:
        table = hlo.entry_buffer_table(artifact.hlo_text)
        report["memory"] = {
            "parameter_bytes": table["parameter_bytes"],
            "aliased_result_bytes": table["aliased_result_bytes"],
            "unaliased_result_bytes": table["unaliased_result_bytes"],
            "temp_estimate_bytes":
                hlo.temp_allocation_estimate(artifact.hlo_text),
        }
    except Exception:  # anatomy must not die on an unparsable entry layout
        report["memory"] = None
    return report


def opportunities(reports, min_bytes=DEFAULT_OPPORTUNITY_MIN_BYTES):
    """Named optimization opportunities: every zero-overlap collective moving
    at least ``min_bytes``, sorted largest wire first. Each row names the
    program and instruction so the reader can find the site in the HLO."""
    out = []
    for report in reports:
        for r in report["collectives"]:
            if not r["zero_overlap"] or r["bytes"] < min_bytes:
                continue
            hint = ("synchronous collective — no -start/-done window exists; "
                    "restructure so independent compute can overlap the wire"
                    if not r["async"] else
                    "async window hides nothing — schedule independent "
                    "compute between -start and -done")
            out.append({
                "program": report["name"], "instruction": r["instruction"],
                "op": r["op"], "level": r["level"], "bytes": r["bytes"],
                "exposed_us": _us(r["exposed_s"]), "hint": hint,
            })
    out.sort(key=lambda o: (-o["bytes"], o["program"], o["instruction"]))
    return out


def _program_json(report):
    """The deterministic per-program report block (seconds -> rounded µs)."""
    rf = report["roofline"]
    return {
        "name": report["name"],
        "flops": report["flops"],
        "hbm_bytes": report["hbm_bytes"],
        "wire_bytes": dict(report["wire_bytes"]),
        "collectives": [{
            "instruction": r["instruction"], "op": r["op"],
            "level": r["level"], "bytes": r["bytes"], "async": r["async"],
            "zero_overlap": r["zero_overlap"], "bucket": r["bucket"],
            "comm_us": _us(r["comm_s"]),
            "overlap_us": _us(r["overlap_s"]),
            "exposed_us": _us(r["exposed_s"]),
        } for r in report["collectives"]],
        "roofline": {
            "compute_floor_us": _us(rf["compute_floor_s"]),
            "hbm_floor_us": _us(rf["hbm_floor_s"]),
            "exposed_ici_us": _us(rf["exposed_ici_s"]),
            "exposed_dcn_us": _us(rf["exposed_dcn_s"]),
            "predicted_floor_us": _us(rf["predicted_floor_s"]),
            "mfu_ceiling": round(rf["mfu_ceiling"], 4),
        },
        "memory": report.get("memory"),
    }


def program_schedule_events(report, pid, floor_tid=0, comm_tid=1,
                            sort_base=0, label_prefix=""):
    """The predicted-schedule track pair of ONE program report: the binding
    compute/HBM floor slice on ``floor_tid``, the exposed collectives laid end
    to end after it on ``comm_tid``. Shared between ``ds-tpu anatomy``'s
    per-program processes and ``ds-tpu profile``'s merged
    measured-vs-predicted timeline (which stacks every program's pair inside
    one "predicted schedule" process, hence the tid/label knobs)."""
    rf = report["roofline"]
    events = []
    events += thread_meta_events(pid, floor_tid,
                                 label_prefix + "roofline floor",
                                 sort_index=sort_base)
    events += thread_meta_events(pid, comm_tid, label_prefix + "exposed comm",
                                 sort_index=sort_base + 1)
    bound_s = max(rf["compute_floor_s"], rf["hbm_floor_s"])
    binding = ("compute floor"
               if rf["compute_floor_s"] >= rf["hbm_floor_s"]
               else "hbm floor")
    events.append(complete_slice(
        pid, floor_tid, 0, _us(bound_s), binding, "roofline",
        {"compute_floor_us": _us(rf["compute_floor_s"]),
         "hbm_floor_us": _us(rf["hbm_floor_s"]),
         "mfu_ceiling": round(rf["mfu_ceiling"], 4)}))
    ts = _us(bound_s)
    for r in report["collectives"]:
        if r["exposed_s"] <= 0:
            continue
        dur = _us(r["exposed_s"])
        events.append(complete_slice(
            pid, comm_tid, ts, dur, f"{r['op']} ({r['level']})",
            "exposed-comm",
            {"instruction": r["instruction"], "bytes": r["bytes"],
             "zero_overlap": r["zero_overlap"],
             "overlap_us": _us(r["overlap_s"])},
            cname="terrible" if r["zero_overlap"] else "bad"))
        ts += dur
    return events


def to_anatomy_trace_events(reports):
    """Predicted-schedule Perfetto timeline: one process per program (sorted),
    thread 0 carrying the binding compute/HBM floor slice, thread 1 the
    exposed collectives laid end to end after it — the picture of where the
    model says the step time must go. Zero-overlap collectives render in the
    alert color."""
    events = []
    for pid, report in enumerate(sorted(reports, key=lambda r: r["name"])):
        events.append(process_name_event(pid, report["name"]))
        events += program_schedule_events(report, pid)
    return trace_envelope(events, "ds-tpu anatomy",
                          programs=len(reports),
                          trace_version=ANATOMY_REPORT_VERSION)


def comm_compare(entry_reports):
    """The flat-vs-hierarchical-vs-compressed-vs-overlap exchange comparison:
    summed exposed-DCN and wire bytes per registry entry, plus the reduction
    each mode achieves over the flat exchange. ``ok`` iff every two-level
    mode exposes strictly less DCN time than flat, AND the bucketed overlap
    mode exposes strictly less DCN than the monolithic hierarchical exchange
    with exactly zero exposed-ICI on its tagged grad collectives."""
    modes = {"flat": "standard", "hierarchical": "comm_hierarchical",
             "compressed": "comm_compressed", "overlap": "comm_overlap"}
    if not all(entry in entry_reports for entry in modes.values()):
        return None
    out = {}
    for mode, entry in modes.items():
        reports = entry_reports[entry]
        out[mode] = {
            "entry": entry,
            "exposed_dcn_us": _us(sum(r["exposed_s"]["dcn"] for r in reports)),
            "exposed_ici_us": _us(sum(r["exposed_s"]["ici"] for r in reports)),
            "wire_dcn_bytes": sum(r["wire_bytes"]["dcn"] for r in reports),
            "wire_ici_bytes": sum(r["wire_bytes"]["ici"] for r in reports),
        }
    out["overlap"]["grad_ici_exposed_us"] = _us(sum(
        c["exposed_s"] for r in entry_reports["comm_overlap"]
        for c in r["collectives"]
        if c["bucket"] is not None and c["level"] == "ici"))
    flat_dcn = out["flat"]["exposed_dcn_us"]
    reductions = {}
    for mode in ("hierarchical", "compressed", "overlap"):
        reductions[mode] = (round(1.0 - out[mode]["exposed_dcn_us"] / flat_dcn,
                                  4) if flat_dcn > 0 else 0.0)
    out["exposed_dcn_reduction_vs_flat"] = reductions
    out["ok"] = (flat_dcn > out["hierarchical"]["exposed_dcn_us"]
                 and flat_dcn > out["compressed"]["exposed_dcn_us"]
                 and (out["hierarchical"]["exposed_dcn_us"]
                      > out["overlap"]["exposed_dcn_us"])
                 and out["overlap"]["grad_ici_exposed_us"] == 0.0)
    return out


def _registry_slice_sets():
    """Device-id slice sets of the CLI mesh: the same 2-slice factorization
    the comm_hierarchical registry entry trains on (``dcn_slices: 2``)."""
    import jax

    from ..comm.topology import CommTopology, derive_num_slices
    n = jax.device_count()
    topo = CommTopology(n, derive_num_slices(n))
    return [frozenset(g) for g in topo.ici_groups]


def anatomy_main(argv=None):
    """``ds-tpu anatomy`` — the step-time anatomy report over the lint
    registry's AOT artifacts. Deterministic ``--json``, optional Perfetto
    timeline of the predicted schedule, optional golden-pinnable comm
    comparison file. Exit 1 when any entry fails to capture or a program's
    exposed-comm report is unparseable."""
    parser = argparse.ArgumentParser(
        prog="ds-tpu anatomy",
        description="roofline ledger + async-overlap analysis over the lint "
                    "registry's AOT-compiled programs")
    parser.add_argument("--json", action="store_true",
                        help="emit the full report as JSON on stdout")
    parser.add_argument("--out", metavar="PATH",
                        help="also write the JSON report to PATH")
    parser.add_argument("--entry", action="append", metavar="NAME",
                        help="limit to a lint-registry entry (repeatable; "
                             "default: every entry)")
    parser.add_argument("--chip", default="cpu-test",
                        help="chip spec to price against (default: cpu-test, "
                             "the CI mesh bound; '' auto-detects)")
    parser.add_argument("--peak-tflops", type=float, default=0.0,
                        help="override the spec's dense peak TFLOP/s")
    parser.add_argument("--hbm-gbps", type=float, default=0.0,
                        help="override the spec's HBM GB/s")
    parser.add_argument("--ici-gbps", type=float, default=0.0,
                        help="override the spec's ICI GB/s")
    parser.add_argument("--dcn-gbps", type=float, default=0.0,
                        help="override the spec's DCN GB/s")
    parser.add_argument("--timeline", metavar="PATH",
                        help="write the predicted-schedule Perfetto trace")
    parser.add_argument("--comm-compare-out", metavar="PATH",
                        help="write the flat-vs-hierarchical comparison JSON "
                             "(the golden-pinned file)")
    parser.add_argument("--opportunity-min-bytes", type=int,
                        default=DEFAULT_OPPORTUNITY_MIN_BYTES,
                        help="ignore zero-overlap collectives below this wire "
                             "size (default: %(default)s)")
    args = parser.parse_args(argv)

    # stdout belongs to the report (same contract as ds-tpu lint)
    import logging
    for h in logging.getLogger("DeepSpeedTPU").handlers:
        if isinstance(h, logging.StreamHandler) and h.stream is sys.stdout:
            h.stream = sys.stderr

    from ..lint import registry
    spec = resolve_spec(args.chip, args.peak_tflops, args.hbm_gbps,
                        args.ici_gbps, args.dcn_gbps)
    slice_sets = _registry_slice_sets()
    entries = sorted(registry.BUILDERS) if not args.entry else list(args.entry)
    entry_reports = {}
    errors = []
    for entry in entries:
        try:
            artifacts = registry.capture_entry(entry)
        except Exception as e:
            errors.append(f"{entry}: capture failed: {e}")
            continue
        reports = []
        for artifact in artifacts:
            try:
                reports.append(analyze_artifact(artifact, spec,
                                                slice_sets=slice_sets))
            except ValueError as e:
                errors.append(f"{artifact.name}: exposed-comm report "
                              f"unparseable: {e}")
        entry_reports[entry] = reports

    all_reports = sorted((r for reports in entry_reports.values()
                          for r in reports), key=lambda r: r["name"])
    # overlap gate: a bucket-tagged grad collective with zero overlap means
    # the bucketed exchange failed to create the window it exists for
    for r in all_reports:
        for c in r["collectives"]:
            if c["bucket"] is not None and c["zero_overlap"]:
                errors.append(
                    f"{r['name']}#{c['instruction']}: overlap gate: bucketed "
                    f"grad collective (bucket {c['bucket']}, {c['level']}) "
                    "has zero overlap")
    compare = comm_compare(entry_reports)
    report = {
        "version": ANATOMY_REPORT_VERSION,
        "kind": ANATOMY_REPORT_KIND,
        "chip": spec.to_dict(),
        "slice_sets": [sorted(s) for s in slice_sets],
        "programs": [_program_json(r) for r in all_reports],
        "opportunities": opportunities(all_reports,
                                       min_bytes=args.opportunity_min_bytes),
        "comm_compare": compare,
        "errors": sorted(errors),
        "ok": not errors and (compare is None or compare["ok"]),
    }
    text = json.dumps(report, indent=2, sort_keys=True) + "\n"
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
    if args.comm_compare_out:
        with open(args.comm_compare_out, "w") as f:
            f.write(json.dumps(compare, indent=2, sort_keys=True) + "\n")
    if args.timeline:
        with open(args.timeline, "w") as f:
            f.write(serialize_trace(to_anatomy_trace_events(all_reports)))
    if args.json:
        sys.stdout.write(text)
    else:
        for r in report["programs"]:
            rf = r["roofline"]
            print(f"{r['name']}: floor {rf['predicted_floor_us']}us "
                  f"(compute {rf['compute_floor_us']}us, hbm "
                  f"{rf['hbm_floor_us']}us, exposed ici "
                  f"{rf['exposed_ici_us']}us / dcn {rf['exposed_dcn_us']}us) "
                  f"mfu ceiling {rf['mfu_ceiling']}")
        for o in report["opportunities"]:
            print(f"OPPORTUNITY {o['program']}#{o['instruction']}: {o['op']} "
                  f"({o['level']}, {o['bytes']} B, {o['exposed_us']}us "
                  f"exposed) — {o['hint']}")
        if compare is not None:
            red = compare["exposed_dcn_reduction_vs_flat"]
            print(f"comm compare: flat {compare['flat']['exposed_dcn_us']}us "
                  f"exposed DCN; hierarchical "
                  f"-{round(red['hierarchical'] * 100, 2)}%, compressed "
                  f"-{round(red['compressed'] * 100, 2)}%, overlap "
                  f"-{round(red['overlap'] * 100, 2)}% (grad ICI exposed "
                  f"{compare['overlap']['grad_ici_exposed_us']}us)"
                  + ("" if compare["ok"] else "  [NOT LOWER — FAIL]"))
        for e in report["errors"]:
            print(f"ERROR {e}")
        print(f"{len(report['programs'])} program(s), "
              f"{len(report['opportunities'])} opportunity(ies), "
              f"{len(report['errors'])} error(s)")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(anatomy_main())
