"""Run-lifecycle goodput observatory: the badput ledger.

Every other observatory in the repo accounts for one subsystem — step anatomy
(Anatomy/*), pipeline bubbles (Pipeline/Goodput/*), serving requests
(Serving/*), cluster hangs/stragglers (Cluster/*), resilience events. None of
them answers the run-level question: of the wall-clock between engine
construction and exit, what fraction was productive training, and where did
the rest go? That fraction — *goodput* — is the metric that decides whether a
fleet can run on preemptible capacity, and both Google's ML Goodput
methodology and the MegaScale production-diagnostics work converged on the
same shape for it: one goodput number plus an exhaustive, mutually-exclusive
badput decomposition.

:class:`RunLedger` is that decomposition. It opens at engine construction and
classifies every wall-clock interval of the run into exactly one of a closed
taxonomy:

==================  ===========================================================
class               source of truth
==================  ===========================================================
``init``            engine construction -> first train step (minus compile)
``compile``         compile-watchdog record seconds (CompileWatchdog)
``productive_step`` step wall remaining after all carve-outs
``checkpoint_stall``AsyncCheckpointer snapshot-fence time (``last_stall_ms``)
``restart_replay``  steps re-run between the restore point and the pre-crash
                    step (flight-recorder ``first_bad_step``)
``hang``            steps during which the cluster hang watchdog fired
``straggler_skew``  this host's dispatch time above the fleet median
                    (cluster heartbeat dispatch column)
``eval``            forward-only evaluation intervals
``host_gap``        residual — wall not claimed by any other class
==================  ===========================================================

The partition invariant — asserted in tests/unit/test_goodput.py — is that
the class seconds sum to the run wall-clock exactly (to float tolerance) with
no interval double-counted. It holds *by construction*: the ledger keeps a
single monotonic cursor; every boundary event classifies the span since the
cursor, carve-outs are clamped to the span, and the remainder goes to the
interval's base class. There is no second clock to disagree with.

Everything here is host-side arithmetic over timestamps other layers already
took: no jax import, no device fetch, nothing under the AST no-host-sync
guard. With ``telemetry.goodput`` enabled the lowered step program is
HLO-instruction-identical to a build without it.

Surfaces: per-run JSON beside the flight-recorder dumps
(``goodput_<run>_host<h>.json``), ``Run/Goodput/*`` scalars through
``TelemetrySession.end_step``, the ``ds-tpu goodput`` CLI (render one run,
fleet-merge a directory, ``--diff`` two runs with a per-class delta table and
a ``--tolerance`` exit-code contract), and a Perfetto run-timeline track via
utils/trace_event.py. See docs/goodput.md.
"""

import argparse
import json
import os
import re
import time

from .trace_event import (serialize_trace, trace_envelope, load_bundle,
                          process_name_event, thread_meta_events,
                          complete_slice, counter_event)

GOODPUT_LEDGER_VERSION = 1

# The closed badput taxonomy. Order is the render/report order: lifecycle
# first, then the step-time carve-outs, then the residual.
BADPUT_CLASSES = (
    "init",
    "compile",
    "productive_step",
    "checkpoint_stall",
    "restart_replay",
    "hang",
    "straggler_skew",
    "eval",
    "host_gap",
)

# Matches numerics._sanitize_token: the run token never contains '_' because
# '_' is the ledger-name field separator.
_TOKEN_RE = re.compile(r"[^A-Za-z0-9.-]+")

# Both the legacy anonymous name (goodput__host0.json, empty run token) and
# the run-namespaced name parse; anonymous ledgers group under run key "".
LEDGER_NAME_RE = re.compile(
    r"goodput_(?P<run>[^_]*)_host(?P<host>\d+)\.json$")


def _sanitize_token(s):
    return _TOKEN_RE.sub("-", str(s)).strip("-")


class RunLedger:
    """Single-host run-lifecycle ledger with an exact wall-clock partition.

    One monotonic cursor walks the run; :meth:`close` classifies the span
    since the cursor into a base class minus clamped carve-outs. The engine
    drives it (construction -> ``close("init", ...)``; each
    ``_finish_step`` -> :meth:`close_step`; eval -> :meth:`close` pairs;
    shutdown -> :meth:`finalize`), but the ledger itself never reads a clock
    source other than ``clock()`` — tests inject a fake clock and the
    partition invariant must hold for any event stream.
    """

    def __init__(self, run_id="", host=0, ledger_dir=None, eval_tag="eval",
                 interval_capacity=4096, persist_every=16, clock=None,
                 wall=None):
        self._clock = clock if clock is not None else time.perf_counter
        self._wall = wall if wall is not None else time.time
        self.run_id = _sanitize_token(run_id)
        self.host = int(host)
        self.ledger_dir = ledger_dir or None
        self.eval_tag = str(eval_tag) or "eval"
        self.interval_capacity = max(int(interval_capacity), 16)
        self.persist_every = max(int(persist_every), 1)
        self.t0 = self._clock()
        self.wall_start = self._wall()
        self._cursor = self.t0
        self.class_seconds = {c: 0.0 for c in BADPUT_CLASSES}
        self.intervals = []          # [t0_rel, t1_rel, cls] contiguous spans
        self.intervals_dropped = 0
        self.steps = 0
        self.replay_steps = 0
        self.hang_steps = 0
        self.checkpoint_stalls = 0
        self.replay_until = -1       # steps <= this are restart replay
        self.finalized = False

    # ------------------------------------------------------------ recording

    def _append_interval(self, t0_rel, t1_rel, cls):
        if t1_rel <= t0_rel:
            return
        # merge with the previous interval when contiguous and same-class so
        # carve-heavy runs don't fragment the timeline
        if self.intervals and self.intervals[-1][2] == cls \
                and abs(self.intervals[-1][1] - t0_rel) < 1e-9:
            self.intervals[-1][1] = t1_rel
            return
        if len(self.intervals) >= self.interval_capacity:
            self.intervals.pop(0)
            self.intervals_dropped += 1
        self.intervals.append([t0_rel, t1_rel, cls])

    def close(self, base_cls, carve=None):
        """Classify the span since the cursor: each ``carve`` entry
        (class -> seconds) is clamped to what remains of the span, the
        remainder goes to ``base_cls``. Returns the span length. The span is
        consumed exactly once — this is the partition invariant's engine."""
        if base_cls not in self.class_seconds:
            raise ValueError(f"unknown badput class {base_cls!r}")
        now = self._clock()
        span = max(now - self._cursor, 0.0)
        start = self._cursor - self.t0
        remaining = span
        # carve-outs are laid down in taxonomy order so the interval list is
        # deterministic for a given event stream
        if carve:
            for cls in carve:
                if cls not in self.class_seconds:
                    raise ValueError(f"unknown badput class {cls!r}")
            for cls in BADPUT_CLASSES:
                want = float(carve.get(cls, 0.0) or 0.0)
                if want <= 0.0 or cls == base_cls:
                    continue
                got = min(want, remaining)
                if got <= 0.0:
                    continue
                self.class_seconds[cls] += got
                self._append_interval(start, start + got, cls)
                start += got
                remaining -= got
        if remaining > 0.0:
            self.class_seconds[base_cls] += remaining
            self._append_interval(start, start + remaining, base_cls)
        self._cursor = now
        return span

    def close_step(self, global_step, carve=None, hang=False):
        """Close one train-step interval. Replay steps (``global_step`` at or
        below :meth:`set_replay_until`'s bound) bill their remainder to
        ``restart_replay``; a step during which the hang watchdog fired bills
        its remainder to ``hang`` — a stalled step produced nothing, so none
        of its wall is productive."""
        if hang:
            base = "hang"
            self.hang_steps += 1
        elif global_step <= self.replay_until:
            base = "restart_replay"
            self.replay_steps += 1
        else:
            base = "productive_step"
        had_stall = bool(carve and carve.get("checkpoint_stall", 0.0) > 0.0)
        if had_stall:
            self.checkpoint_stalls += 1
        self.steps += 1
        span = self.close(base, carve)
        # the engine has no shutdown hook, so the on-disk ledger refreshes
        # itself: every Nth step, plus every step that paid a checkpoint fence
        # (those are the steps a post-mortem asks about)
        if self.ledger_dir and (had_stall
                                or self.steps % self.persist_every == 0):
            self.persist()
        return span

    def close_eval(self):
        """Close a forward-only evaluation interval (the caller closed the
        preceding span as ``host_gap`` when eval began)."""
        return self.close("eval")

    def set_replay_until(self, step):
        """Arm restart-replay billing: steps re-run at or below ``step`` are
        badput — work the run already paid for once before the crash."""
        self.replay_until = int(step)

    def finalize(self, persist=True):
        """Close the residual span as ``host_gap``, optionally persist, and
        return the summary. Idempotent."""
        if not self.finalized:
            self.close("host_gap")
            self.finalized = True
        if persist:
            self.persist()
        return self.summary()

    # ------------------------------------------------------------ reporting

    def wall_seconds(self):
        return max(self._clock() - self.t0, 0.0)

    def accounted_seconds(self):
        return sum(self.class_seconds.values())

    def goodput_fraction(self):
        acct = self.accounted_seconds()
        if acct <= 0.0:
            return 0.0
        return self.class_seconds["productive_step"] / acct

    def summary(self):
        """The ledger header without the interval list — what scalars, the
        fleet merge, and embedded dump copies carry."""
        return {
            "version": GOODPUT_LEDGER_VERSION,
            "kind": "goodput",
            "run": self.run_id,
            "host": self.host,
            "eval_tag": self.eval_tag,
            "wall_start": self.wall_start,
            "wall_s": self.accounted_seconds(),
            "steps": self.steps,
            "replay_steps": self.replay_steps,
            "hang_steps": self.hang_steps,
            "checkpoint_stalls": self.checkpoint_stalls,
            "class_seconds": dict(self.class_seconds),
            "goodput_fraction": self.goodput_fraction(),
        }

    def to_dict(self):
        d = self.summary()
        d["intervals"] = [list(iv) for iv in self.intervals]
        d["intervals_dropped"] = self.intervals_dropped
        return d

    def scalar_items(self):
        """``Run/Goodput/*`` scalar (name, value) pairs for end_step. The
        ``eval`` class is surfaced under the configured tag so an eval-heavy
        consumer can rename it without forking the taxonomy."""
        items = [("Run/Goodput/goodput_fraction", self.goodput_fraction()),
                 ("Run/Goodput/wall_seconds", self.accounted_seconds())]
        for cls in BADPUT_CLASSES:
            name = self.eval_tag if cls == "eval" else cls
            items.append((f"Run/Goodput/{name}_seconds",
                          self.class_seconds[cls]))
        return items

    def ledger_path(self):
        if not self.ledger_dir:
            return None
        return os.path.join(
            self.ledger_dir, f"goodput_{self.run_id}_host{self.host}.json")

    def persist(self):
        """Write the per-run ledger JSON beside the flight-recorder dumps.
        Atomic rename so a reader (or a crash) never sees a torn file."""
        path = self.ledger_path()
        if path is None:
            return None
        os.makedirs(self.ledger_dir, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_dict(), f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
        return path


# ------------------------------------------------------------ fleet merge


def scan_ledger_dir(ledger_dir, run=None):
    """Map run key -> {host: ledger dict} for every parseable ledger file in
    ``ledger_dir``. ``run`` filters to one run key."""
    runs = {}
    if not ledger_dir or not os.path.isdir(ledger_dir):
        return runs
    for name in sorted(os.listdir(ledger_dir)):
        m = LEDGER_NAME_RE.match(name)
        if not m:
            continue
        if run is not None and m.group("run") != run:
            continue
        try:
            with open(os.path.join(ledger_dir, name)) as f:
                data = json.load(f)
        except (OSError, ValueError):
            continue
        if data.get("kind") != "goodput":
            continue
        runs.setdefault(m.group("run"), {})[int(m.group("host"))] = data
    return runs


def fleet_goodput(by_host):
    """Merge per-host ledgers into the rank-0 fleet view: class seconds and
    step counts sum across hosts (host-seconds, the unit fleet capacity is
    bought in), the fleet goodput fraction is productive host-seconds over
    total host-seconds, and the per-host breakdown rides along so a single
    bad host stays attributable."""
    hosts = sorted(by_host)
    class_seconds = {c: 0.0 for c in BADPUT_CLASSES}
    per_host = {}
    steps = replay = hangs = stalls = 0
    for h in hosts:
        led = by_host[h]
        for cls in BADPUT_CLASSES:
            class_seconds[cls] += float(
                led.get("class_seconds", {}).get(cls, 0.0))
        steps += int(led.get("steps", 0))
        replay += int(led.get("replay_steps", 0))
        hangs += int(led.get("hang_steps", 0))
        stalls += int(led.get("checkpoint_stalls", 0))
        per_host[str(h)] = {
            "wall_s": led.get("wall_s", 0.0),
            "goodput_fraction": led.get("goodput_fraction", 0.0),
            "class_seconds": dict(led.get("class_seconds", {})),
        }
    total = sum(class_seconds.values())
    frac = class_seconds["productive_step"] / total if total > 0 else 0.0
    run_keys = {led.get("run", "") for led in by_host.values()}
    return {
        "version": GOODPUT_LEDGER_VERSION,
        "kind": "goodput_fleet",
        "run": sorted(run_keys)[0] if run_keys else "",
        "n_hosts": len(hosts),
        "hosts": hosts,
        "wall_s": total,
        "steps": steps,
        "replay_steps": replay,
        "hang_steps": hangs,
        "checkpoint_stalls": stalls,
        "class_seconds": class_seconds,
        "goodput_fraction": frac,
        "per_host": per_host,
    }


def _median_step_seconds(records):
    """Median per-step cost from the dump's per-record monotonic stamps —
    robust to the occasional outlier interval (a mid-run recompile, a fence)
    that would skew the span-wide mean. None when fewer than two stamped
    records exist."""
    gaps = []
    prev_mono = prev_step = None
    for rec in records:
        mono, step = rec.get("mono"), rec.get("step")
        if mono is None or step is None:
            continue
        if prev_mono is not None and int(step) > int(prev_step):
            gaps.append((float(mono) - float(prev_mono))
                        / (int(step) - int(prev_step)))
        prev_mono, prev_step = mono, step
    if not gaps:
        return None
    gaps.sort()
    return gaps[(len(gaps) - 1) // 2]


def estimate_replay_seconds(bundle, resume_step):
    """Price restart-replay badput from a flight-recorder dump alone: the
    dump's monotonic step stamps give seconds-per-step (median inter-record
    gap when per-step stamps exist, span-wide mean otherwise); the replay
    runs from the restore point to the first bad step (or, absent one, the
    last recorded step). Returns (replay_steps, replay_seconds) or (0, 0.0)
    for legacy dumps without span fields."""
    span = bundle.get("span") if isinstance(bundle, dict) else None
    if not isinstance(span, dict):
        return 0, 0.0
    steps_spanned = int(span.get("steps_spanned", 0) or 0)
    mono = float(span.get("mono_end", 0.0)) - float(span.get("mono_start", 0.0))
    if steps_spanned <= 0 or mono <= 0.0:
        return 0, 0.0
    per_step = _median_step_seconds(bundle.get("steps", []))
    if per_step is None:
        per_step = mono / steps_spanned
    first_bad = bundle.get("first_bad_step")
    last_step = int(span.get("last_step", 0) or 0)
    stop = int(first_bad) if first_bad is not None else last_step
    replay_steps = max(stop - int(resume_step), 0)
    return replay_steps, replay_steps * per_step


# ------------------------------------------------------------ Perfetto


def goodput_trace_events(ledger):
    """One Perfetto track per host: a complete slice per ledger interval named
    by its badput class, plus a cumulative goodput-fraction counter sampled at
    every interval edge. Timebase is microseconds since the ledger opened."""
    host = int(ledger.get("host", 0))
    pid = 1000 + host
    run = ledger.get("run", "")
    events = [process_name_event(pid, f"Run goodput host{host}"
                                       + (f" [{run}]" if run else ""))]
    events.extend(thread_meta_events(pid, 0, "run lifecycle", sort_index=0))
    productive = 0.0
    total = 0.0
    for t0_rel, t1_rel, cls in ledger.get("intervals", []):
        ts = int(round(t0_rel * 1e6))
        dur = int(round((t1_rel - t0_rel) * 1e6))
        events.append(complete_slice(
            pid, 0, ts, dur, cls, "goodput", {"class": cls},
            cname="good" if cls == "productive_step" else None))
        total += t1_rel - t0_rel
        if cls == "productive_step":
            productive += t1_rel - t0_rel
        events.append(counter_event(
            pid, 0, int(round(t1_rel * 1e6)), "goodput_fraction",
            {"fraction": round(productive / total, 6) if total > 0 else 0.0}))
    return events


def goodput_timeline(ledger, out_path):
    trace = trace_envelope(goodput_trace_events(ledger),
                           "ds-tpu goodput",
                           run=ledger.get("run", ""),
                           host=ledger.get("host", 0))
    payload = serialize_trace(trace)
    with open(out_path, "w") as f:
        f.write(payload)
    return len(payload)


# ------------------------------------------------------------ CLI


def _load_goodput(path, run=None):
    """Resolve a CLI path operand to a goodput view: a ledger file, a
    flight-recorder dump embedding one, or a directory of per-host ledgers
    (fleet-merged when more than one host is present)."""
    if os.path.isdir(path):
        runs = scan_ledger_dir(path, run=run)
        if not runs:
            raise FileNotFoundError(
                f"no goodput ledgers (goodput_<run>_host<h>.json) in {path}")
        if run is None and len(runs) > 1:
            raise ValueError(
                "multiple runs in directory: "
                + ", ".join(repr(k) for k in sorted(runs))
                + " — pick one with --run")
        by_host = runs[run if run is not None else next(iter(runs))]
        if len(by_host) == 1:
            return next(iter(by_host.values()))
        return fleet_goodput(by_host)
    led = load_bundle(path, "goodput")
    if led is None:
        raise ValueError(f"{path} is not a goodput ledger "
                         "(and embeds none under its 'goodput' key)")
    return led


def _fmt_row(cls, sec, total):
    pct = 100.0 * sec / total if total > 0 else 0.0
    return f"  {cls:<18} {sec:>12.3f} s {pct:>7.2f}%"


def render_goodput(led):
    """Human-readable single-run (or fleet) report."""
    lines = []
    kind = led.get("kind", "goodput")
    head = f"run={led.get('run', '')!r}"
    if kind == "goodput_fleet":
        head += f" hosts={led.get('n_hosts', 0)}"
    else:
        head += f" host={led.get('host', 0)}"
    total = float(led.get("wall_s", 0.0))
    lines.append(f"goodput ledger: {head}")
    lines.append(f"  wall {total:.3f} s over {led.get('steps', 0)} steps "
                 f"({led.get('replay_steps', 0)} replayed, "
                 f"{led.get('hang_steps', 0)} hung, "
                 f"{led.get('checkpoint_stalls', 0)} checkpoint stalls)")
    cs = led.get("class_seconds", {})
    for cls in BADPUT_CLASSES:
        lines.append(_fmt_row(cls, float(cs.get(cls, 0.0)), total))
    lines.append(f"  goodput_fraction   {led.get('goodput_fraction', 0.0):.4f}")
    return "\n".join(lines)


def diff_goodput(a, b, tolerance=0.0):
    """Per-class delta between two ledgers (b relative to a). The regressing
    class is the badput class whose share of wall grew the most; ``regressed``
    is True when b's goodput fraction fell more than ``tolerance`` below
    a's — the CI exit-code contract."""
    a_total = float(a.get("wall_s", 0.0)) or 1.0
    b_total = float(b.get("wall_s", 0.0)) or 1.0
    deltas = {}
    worst_cls, worst_delta = None, 0.0
    for cls in BADPUT_CLASSES:
        a_pct = float(a.get("class_seconds", {}).get(cls, 0.0)) / a_total
        b_pct = float(b.get("class_seconds", {}).get(cls, 0.0)) / b_total
        deltas[cls] = {
            "a_seconds": float(a.get("class_seconds", {}).get(cls, 0.0)),
            "b_seconds": float(b.get("class_seconds", {}).get(cls, 0.0)),
            "a_share": a_pct,
            "b_share": b_pct,
            "share_delta": b_pct - a_pct,
        }
        if cls != "productive_step" and b_pct - a_pct > worst_delta:
            worst_cls, worst_delta = cls, b_pct - a_pct
    a_frac = float(a.get("goodput_fraction", 0.0))
    b_frac = float(b.get("goodput_fraction", 0.0))
    return {
        "version": GOODPUT_LEDGER_VERSION,
        "kind": "goodput_diff",
        "a_goodput_fraction": a_frac,
        "b_goodput_fraction": b_frac,
        "fraction_delta": b_frac - a_frac,
        "tolerance": float(tolerance),
        "regressed": b_frac < a_frac - float(tolerance),
        "regressing_class": worst_cls,
        "classes": deltas,
    }


def render_diff(diff):
    lines = ["goodput diff (b vs a):",
             f"  {'class':<18} {'a (s)':>10} {'b (s)':>10} {'Δshare':>9}"]
    for cls in BADPUT_CLASSES:
        d = diff["classes"][cls]
        mark = "  <-- regressing" if cls == diff["regressing_class"] else ""
        lines.append(f"  {cls:<18} {d['a_seconds']:>10.3f} "
                     f"{d['b_seconds']:>10.3f} "
                     f"{100.0 * d['share_delta']:>+8.2f}%{mark}")
    lines.append(f"  goodput_fraction   {diff['a_goodput_fraction']:>10.4f} "
                 f"{diff['b_goodput_fraction']:>10.4f} "
                 f"{100.0 * diff['fraction_delta']:>+8.2f}%")
    verdict = "REGRESSED" if diff["regressed"] else "ok"
    lines.append(f"  verdict: {verdict} "
                 f"(tolerance {diff['tolerance']:.4f})")
    return "\n".join(lines)


def goodput_main(argv=None):
    """``ds-tpu goodput`` — render one run's badput ledger (file, embedding
    dump, or per-host directory with fleet merge), export its Perfetto
    run-timeline, or diff two runs. Exit code: 0 clean; 1 when ``--diff``
    finds the goodput fraction regressed beyond ``--tolerance`` (so external
    CI can gate on run efficiency without parsing JSON); 2 on bad operands."""
    p = argparse.ArgumentParser(
        prog="ds-tpu goodput",
        description="Render, export, or diff run-lifecycle goodput ledgers.")
    p.add_argument("path", nargs="?", default=None,
                   help="ledger JSON, flight-recorder dump embedding one, or "
                        "a directory of per-host ledgers (fleet merge)")
    p.add_argument("--run", default=None,
                   help="run key when the directory holds several runs")
    p.add_argument("--diff", nargs=2, metavar=("A", "B"), default=None,
                   help="diff two ledgers/directories: per-class delta table "
                        "naming the regressing class")
    p.add_argument("--tolerance", type=float, default=0.0,
                   help="allowed goodput-fraction drop before --diff exits "
                        "nonzero (absolute, e.g. 0.02)")
    p.add_argument("--json", default=None, metavar="OUT",
                   help="also write the rendered view (or diff) as JSON")
    p.add_argument("--timeline", default=None, metavar="OUT",
                   help="write the Perfetto run-timeline trace JSON")
    args = p.parse_args(argv)

    try:
        if args.diff is not None:
            a = _load_goodput(args.diff[0], run=args.run)
            b = _load_goodput(args.diff[1], run=args.run)
            diff = diff_goodput(a, b, tolerance=args.tolerance)
            print(render_diff(diff))
            if args.json:
                with open(args.json, "w") as f:
                    json.dump(diff, f, indent=2, sort_keys=True)
                    f.write("\n")
            return 1 if diff["regressed"] else 0
        if args.path is None:
            p.error("a ledger path is required unless --diff is given")
        led = _load_goodput(args.path, run=args.run)
    except (OSError, ValueError) as e:
        print(f"ds-tpu goodput: {e}")
        return 2
    print(render_goodput(led))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(led, f, indent=2, sort_keys=True)
            f.write("\n")
    if args.timeline:
        if "intervals" not in led:
            print("ds-tpu goodput: --timeline needs a single-host ledger "
                  "with its interval list (fleet merges carry none)")
            return 2
        goodput_timeline(led, args.timeline)
        print(f"wrote {args.timeline}")
    return 0
