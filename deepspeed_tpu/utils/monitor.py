"""Training-scalar monitor (TensorBoard + JSONL).

TPU-native analog of the reference's tensorboardX wiring
(``deepspeed/runtime/engine.py:151-152, 246-261`` creates a SummaryWriter behind the
``tensorboard`` config block; scalars emitted at engine.py:779-790, 920-936,
950-974). Differences: scalars are ALWAYS mirrored to a newline-delimited JSON file
(cheap, dependency-free, machine-parseable) and TensorBoard events are written
additionally when a writer implementation is importable. Only process 0 writes.
"""

import atexit
import json
import os
import time
from typing import Optional

from .logging import logger


class SummaryMonitor:
    """Scalar sink: JSONL always, TensorBoard when available."""

    def __init__(self, output_path: Optional[str] = None, job_name: Optional[str] = None,
                 enabled: bool = True):
        import jax
        self.enabled = enabled and jax.process_index() == 0
        self._tb = None
        self._jsonl = None
        self._events = None
        # MetricStore hook (utils/metrics.py), set by
        # TelemetrySession.configure_metrics. Lives on EVERY rank and is fed
        # before the rank-0 early return so each host's metric ring is
        # populated even though only process 0 writes files.
        self.metrics = None
        # log_dir is part of the public surface on EVERY rank (rank-agnostic
        # callers read it), so it must be set before the disabled early-return.
        output_path = output_path or os.path.join(os.environ.get("DLWS_JOB_ID", "."),
                                                  "deepspeed_monitor")
        job_name = job_name or "DeepSpeedJobName"
        self.log_dir = os.path.join(output_path, job_name)
        if not self.enabled:
            return
        os.makedirs(self.log_dir, exist_ok=True)
        # block-buffered: one write syscall per flush() (telemetry flushes at
        # every end_step), not one per scalar. The flight recorder flushes
        # this stream before dumping so a crash loses nothing (numerics.py).
        self._jsonl = open(os.path.join(self.log_dir, "scalars.jsonl"), "a")
        atexit.register(self.close)  # flush TB events on normal interpreter exit
        try:
            from torch.utils.tensorboard import SummaryWriter
            self._tb = SummaryWriter(log_dir=self.log_dir)
        except Exception as e:  # tensorboard package missing etc. — JSONL still works
            logger.info(f"[deepspeed_tpu] tensorboard writer unavailable ({e!r}); "
                        f"scalars go to {self.log_dir}/scalars.jsonl only")

    def add_scalar(self, name: str, value, global_step: int):
        if self.metrics is not None:
            # catalog routing + ring recording happens on every rank and for
            # every emitter (engine, serving, router, cluster, numerics all
            # share this monitor object) — strict mode may raise here, which
            # is the drift guard doing its job.
            self.metrics.observe(name, value, global_step)
        if not self.enabled:
            return
        value = float(value)
        self._jsonl.write(json.dumps({"tag": name, "value": value, "step": int(global_step),
                                      "time": time.time()}) + "\n")
        if self._tb is not None:
            self._tb.add_scalar(name, value, global_step)

    def event(self, name: str, payload, step: Optional[int] = None):
        """Structured (non-scalar) event sink — loss-scale journal entries,
        desync-audit results, etc. Written to events.jsonl beside scalars.jsonl;
        the file is created lazily so scalar-only jobs keep a clean log dir."""
        if not self.enabled:
            return
        if self._events is None:
            self._events = open(os.path.join(self.log_dir, "events.jsonl"), "a")
        self._events.write(json.dumps(
            {"event": name, "step": None if step is None else int(step),
             "payload": payload, "time": time.time()}, default=repr) + "\n")

    def flush(self):
        if self._jsonl is not None:
            self._jsonl.flush()
        if self._events is not None:
            self._events.flush()
        if self._tb is not None:
            self._tb.flush()

    def close(self):
        self.enabled = False  # a late add_scalar (e.g. one more step) becomes a no-op
        if self._jsonl is not None:
            self._jsonl.close()
            self._jsonl = None
        if self._events is not None:
            self._events.close()
            self._events = None
        if self._tb is not None:
            self._tb.close()
            self._tb = None
