"""Non-perturbing telemetry: step metrics, trace windows, compile watchdog, ledger.

The reference line of this framework observed training with host-blocking
wall-clock timers (deepspeed/utils/timer.py) plus ad-hoc TensorBoard scalars —
every timed section drained the device queue, so turning on observability
CHANGED the thing being observed (it serializes exactly the async dispatch the
offload pipeline and ring schedules exploit). This module is the TPU-native
replacement: instrumentation that rides on XLA's own machinery, in four pillars.

1. **Step metrics** (``TelemetrySession.end_step``): the default path blocks
   once per step — on a loss scalar the engine fetches anyway — and derives step
   time, samples/sec and a rolling MFU from the compiled programs' own cost
   analysis. Zero extra barriers; the barrier-per-section breakdown timers
   survive only behind ``telemetry.perturbing_breakdown`` with a loud warning.
2. **Trace windows** (``on_step_begin``): config-driven
   ``jax.profiler.start_trace``/``stop_trace`` around a chosen step range, with
   ``jax.named_scope`` annotations threaded through the engines so the captured
   trace is readable. named_scope adds HLO metadata only — zero instructions
   (asserted by tests/unit/test_telemetry.py against utils/hlo.py counts).
3. **Compile watchdog** (``CompileWatchdog`` + ``_WatchedJit``): every engine
   jit runs through an AOT-caching proxy keyed by the abstract input signature,
   so each compile is observed exactly — wall time, ``memory_analysis()``
   argument/output/temp bytes, ``cost_analysis()`` flops, and the program's
   collective wire bytes (utils/hlo.py) — and recompile storms (the classic
   silent TPU perf killer) warn by name.
4. **Resource ledger**: per-step ``device.memory_stats()`` HBM in-use/peak
   watermarks and collective wire bytes actually executed, emitted as scalars
   through ``SummaryMonitor`` (JSONL always, TensorBoard when available).
"""

import atexit
import os
import time
from collections import deque
from typing import Any, Dict, Optional

import jax
import numpy as np

from .logging import logger


def _abstract_signature(args) -> tuple:
    """Per-leaf (shape, dtype, sharding) signature of a call's inputs — the
    compile-cache key jit itself retraces on. Shardings are hashable jax objects;
    host arrays carry ``None`` (they adopt the compiled program's layout)."""
    sig = []
    for leaf in jax.tree_util.tree_leaves(args):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            a = np.asarray(leaf)
            shape, dtype = a.shape, a.dtype
        sig.append((tuple(shape), dtype, getattr(leaf, "sharding", None)))
    return tuple(sig)


_mem_unavailable_warned = set()   # backends already named in a warning


def _analyze_compiled(compiled, slice_sets=None, anatomy_spec=None,
                      profile_scopes=False):
    """(flops, argument/output/temp bytes, collective wire bytes, wire bytes
    split (ici, dcn), HBM bytes accessed, anatomy report, profile_info,
    mem_unavailable) of a compiled executable, each 0/None when the backend
    doesn't report it. With no slice factorization every wire byte accounts
    as ICI. The anatomy report (utils/anatomy.analyze_program) is computed
    only when ``anatomy_spec`` names a chip spec — pure host-side text
    analysis of the same artifact. ``profile_scopes`` additionally parses the
    program's scope/collective identity catalog
    (utils/profile_ingest.program_profile_info) so a measured trace window
    can be joined back to this compile. ``mem_unavailable`` is True when
    ``memory_analysis()`` raised or returned nothing — recorded so its zeros
    are distinguishable from a genuinely zero-byte program, with one warning
    per backend per session instead of a silent pass."""
    flops = hbm_b = 0.0
    arg_b = out_b = tmp_b = wire = wire_ici = wire_dcn = 0
    anatomy = profile_info = None
    mem_unavailable = False
    try:
        ca = compiled.cost_analysis()
        if not isinstance(ca, dict):  # older jax returned [dict]
            ca = ca[0] if ca else {}
        flops = max(float(ca.get("flops", 0.0)), 0.0)
        hbm_b = max(float(ca.get("bytes accessed", 0.0)), 0.0)
    except Exception:
        pass
    try:
        mem = compiled.memory_analysis()
        if mem is None:
            raise RuntimeError("memory_analysis() returned None")
        arg_b = int(getattr(mem, "argument_size_in_bytes", 0) or 0)
        out_b = int(getattr(mem, "output_size_in_bytes", 0) or 0)
        tmp_b = int(getattr(mem, "temp_size_in_bytes", 0) or 0)
    except Exception as e:
        mem_unavailable = True
        backend = "unknown"
        try:
            backend = jax.default_backend()
        except Exception:
            pass
        if backend not in _mem_unavailable_warned:
            _mem_unavailable_warned.add(backend)
            logger.warning(
                f"[deepspeed_tpu] telemetry: compiled memory_analysis is "
                f"unavailable on the {backend!r} backend ({e!r}); compile "
                f"records carry mem_unavailable=True and zero arg/out/temp "
                f"bytes (watermark-based HBM attribution is off)")
    try:
        from .hlo import collective_bytes, collective_axis_bytes
        text = compiled.as_text()
        wire = collective_bytes(text)
        wire_ici = wire
        if slice_sets and len(slice_sets) > 1:
            split = collective_axis_bytes(text, slice_sets)
            wire_ici, wire_dcn = split["ici"], split["dcn"]
        if anatomy_spec is not None:
            from .anatomy import analyze_program
            anatomy = analyze_program(text, flops, hbm_b, anatomy_spec,
                                      slice_sets=slice_sets)
        if profile_scopes:
            from .profile_ingest import program_profile_info
            profile_info = program_profile_info(text, slice_sets=slice_sets)
    except Exception:
        pass
    return (flops, arg_b, out_b, tmp_b, wire, wire_ici, wire_dcn, hbm_b,
            anatomy, profile_info, mem_unavailable)


class CompileRecord:
    """One observed compile of one program signature."""

    __slots__ = ("signature", "compile_seconds", "flops", "argument_bytes",
                 "output_bytes", "temp_bytes", "wire_bytes", "wire_bytes_ici",
                 "wire_bytes_dcn", "hbm_bytes", "anatomy", "profile_info",
                 "mem_unavailable", "count")

    def __init__(self, signature, compile_seconds, flops=0.0, argument_bytes=0,
                 output_bytes=0, temp_bytes=0, wire_bytes=0, wire_bytes_ici=0,
                 wire_bytes_dcn=0, hbm_bytes=0.0, anatomy=None,
                 profile_info=None, mem_unavailable=False):
        self.signature = signature
        self.compile_seconds = compile_seconds
        self.flops = flops
        self.argument_bytes = argument_bytes
        self.output_bytes = output_bytes
        self.temp_bytes = temp_bytes
        self.wire_bytes = wire_bytes
        self.wire_bytes_ici = wire_bytes_ici
        self.wire_bytes_dcn = wire_bytes_dcn
        self.hbm_bytes = hbm_bytes          # cost_analysis "bytes accessed"
        self.anatomy = anatomy              # utils/anatomy report or None
        self.profile_info = profile_info    # utils/profile_ingest catalog row
        self.mem_unavailable = mem_unavailable  # memory_analysis absent: the
        # zero arg/out/temp bytes above mean "not reported", not "zero bytes"
        self.count = 1


class CompileWatchdog:
    """Registry of every observed jit compile, keyed (program name, abstract
    input signature). A program accumulating ``recompile_warn`` distinct
    signatures warns once by name — recompiles are silent on TPU and can
    dominate wall-clock without ever surfacing in step timings."""

    def __init__(self, recompile_warn: int = 3):
        self.recompile_warn = max(int(recompile_warn), 2)
        self.records: Dict[str, Dict[tuple, CompileRecord]] = {}
        self._storm_warned = set()
        # slice factorization for the per-axis (ICI vs DCN) wire-byte split;
        # None means single-slice — every collective byte accounts as ICI
        self.slice_sets = None
        # roofline ChipSpec: when set, every analyzed compile also gets the
        # step-anatomy report (utils/anatomy) — still pure host text analysis
        self.anatomy_spec = None
        # profile observatory: when True, every analyzed compile also parses
        # the scope/collective identity catalog the trace ingester joins on
        self.profile_scopes = False

    def record(self, name: str, sig, seconds: float, compiled=None) -> CompileRecord:
        per = self.records.setdefault(name, {})
        rec = per.get(sig)
        if rec is not None:  # same-signature recompile (e.g. fallback jit cache miss)
            rec.count += 1
            rec.compile_seconds += seconds
        else:
            if compiled is not None:
                (flops, arg_b, out_b, tmp_b, wire, wire_ici, wire_dcn,
                 hbm_b, anatomy, profile_info, mem_unavail) = \
                    _analyze_compiled(compiled, self.slice_sets,
                                      self.anatomy_spec, self.profile_scopes)
            else:
                flops = arg_b = out_b = tmp_b = wire = wire_ici = wire_dcn = 0
                hbm_b, anatomy, profile_info, mem_unavail = 0.0, None, None, \
                    False
            rec = per[sig] = CompileRecord(sig, seconds, flops, arg_b, out_b,
                                           tmp_b, wire, wire_ici, wire_dcn,
                                           hbm_b, anatomy, profile_info,
                                           mem_unavail)
        n = sum(r.count for r in per.values())
        if len(per) >= self.recompile_warn and name not in self._storm_warned:
            self._storm_warned.add(name)
            logger.warning(
                f"[deepspeed_tpu] telemetry: recompile storm — program {name!r} has "
                f"compiled {n} times ({len(per)} distinct input signatures, "
                f"{self.compile_seconds(name):.1f} s total). Varying shapes/dtypes/"
                f"shardings are reaching the jitted step; pad or bucket them.")
        return rec

    def compiles(self, name: Optional[str] = None) -> int:
        per = ([self.records.get(name, {})] if name is not None
               else self.records.values())
        return sum(r.count for d in per for r in d.values())

    def recompiles(self, name: Optional[str] = None) -> int:
        """Compiles beyond each program's first — the waste the watchdog hunts."""
        names = [name] if name is not None else list(self.records)
        return sum(max(self.compiles(n) - 1, 0) for n in names)

    def compile_seconds(self, name: Optional[str] = None) -> float:
        per = ([self.records.get(name, {})] if name is not None
               else self.records.values())
        return sum(r.compile_seconds for d in per for r in d.values())

    def peak_temp_bytes(self) -> int:
        return max((r.temp_bytes for d in self.records.values()
                    for r in d.values()), default=0)


class _WatchedJit:
    """Watchdog proxy around one jitted program: executes through per-signature
    AOT-compiled executables so every compile is timed and analyzed exactly, and
    every execution feeds the session's flops / wire-bytes counters. Adds no
    device work — the executable is the same one jit would run. If AOT
    lowering/execution is unsupported for this program (host callbacks etc.) the
    proxy falls back permanently to the raw jit, keeping signature tracking."""

    def __init__(self, name: str, jitted, session: "TelemetrySession"):
        self._name = name
        self._jit = jitted
        self._session = session
        self._cache: Dict[tuple, tuple] = {}
        self._fallback = False

    def lower(self, *args, **kwargs):  # flops_profiler / hlo audits delegate
        return self._jit.lower(*args, **kwargs)

    def _call_fallback(self, sig, *args):
        per = self._session.watchdog.records.get(self._name, {})
        if sig in per:
            return self._jit(*args)
        # first call on a new signature pays the compile inside the dispatch;
        # the timed wall includes one execution (upper bound, noted as opaque)
        t0 = time.perf_counter()
        out = self._jit(*args)
        self._session.watchdog.record(self._name, sig,
                                      time.perf_counter() - t0)
        return out

    def __call__(self, *args):
        sig = _abstract_signature(args)
        if self._fallback:
            return self._call_fallback(sig, *args)
        entry = self._cache.get(sig)
        if entry is None:
            t0 = time.perf_counter()
            try:
                compiled = self._jit.lower(*args).compile()
            except Exception as e:
                self._fallback = True
                logger.warning(f"[deepspeed_tpu] telemetry: AOT compile unavailable "
                               f"for program {self._name!r} ({e!r}); falling back to "
                               "the raw jit (signature tracking only)")
                return self._call_fallback(sig, *args)
            rec = self._session.watchdog.record(
                self._name, sig, time.perf_counter() - t0, compiled)
            anat = rec.anatomy or {}
            exposed = anat.get("exposed_s", {})
            entry = self._cache[sig] = (compiled, rec.flops, rec.wire_bytes,
                                        rec.wire_bytes_ici, rec.wire_bytes_dcn,
                                        rec.hbm_bytes,
                                        exposed.get("ici", 0.0),
                                        exposed.get("dcn", 0.0))
        (compiled, flops, wire, wire_ici, wire_dcn, hbm_b, exp_ici,
         exp_dcn) = entry
        try:
            out = compiled(*args)
        except Exception as e:
            self._fallback = True
            self._cache.clear()
            logger.warning(f"[deepspeed_tpu] telemetry: AOT execution failed for "
                           f"program {self._name!r} ({e!r}); falling back to the "
                           "raw jit (signature tracking only)")
            return self._jit(*args)
        self._session.note_execution(flops, wire, wire_ici, wire_dcn,
                                     hbm_bytes=hbm_b, exposed_ici_s=exp_ici,
                                     exposed_dcn_s=exp_dcn)
        return out


def hbm_stats() -> Optional[Dict[str, int]]:
    """device 0's memory_stats dict, or None where the backend doesn't report
    them (CPU returns None; TPU/GPU report bytes_in_use / peak_bytes_in_use).
    Thin alias of utils/hbm.device_memory_stats — the package's single
    memory_stats read."""
    from .hbm import device_memory_stats
    return device_memory_stats()


class TelemetrySession:
    """One engine's telemetry: watchdog-wrapped programs, per-step scalars
    through a SummaryMonitor, and the configured profiler trace window.

    ``monitor``: an existing SummaryMonitor to emit through; when None, the
    session opens its own at ``output_path``/``job_name`` (scalars.jsonl always;
    TensorBoard when importable)."""

    def __init__(self, monitor=None, peak_tflops: Optional[float] = None,
                 trace_dir: Optional[str] = None, trace_steps=None,
                 mfu_window: int = 20, recompile_warn: int = 3,
                 output_path: Optional[str] = None, job_name: Optional[str] = None,
                 anatomy_spec=None, run_id: Optional[str] = None,
                 host_id: Optional[int] = None):
        self.watchdog = CompileWatchdog(recompile_warn=recompile_warn)
        # step-anatomy: a roofline ChipSpec (utils/roofline.resolve_spec)
        # switches on the per-compile overlap/roofline analysis and the
        # Anatomy/* end_step scalars; None keeps the analyzer fully off
        self.watchdog.anatomy_spec = anatomy_spec
        self.anatomy_spec = anatomy_spec
        self.last_anatomy = None
        self.peak_tflops = float(peak_tflops) if peak_tflops else None
        self.trace_dir = trace_dir or "deepspeed_telemetry_trace"
        # namespaced trace output (mirrors the flight-recorder dump naming):
        # trace_<run>_host<h>/ under trace_dir, so two engines sharing one
        # trace_dir never interleave profiler sessions. run_id="" opts back
        # into the legacy layout (the trace lands in trace_dir itself);
        # run_id=None derives the same default id the flight recorder uses.
        if run_id is None:
            from .numerics import default_run_id
            run_id = default_run_id()
        self.run_id = run_id
        if host_id is None:
            try:
                host_id = jax.process_index()
            except Exception:
                host_id = 0
        self.host_id = int(host_id)
        self.trace_output_dir = (
            os.path.join(self.trace_dir,
                         f"trace_{self.run_id}_host{self.host_id}")
            if self.run_id else self.trace_dir)
        self.trace_steps = tuple(trace_steps) if trace_steps is not None else None
        # profile observatory (docs/profile.md): off until configure_profile
        self.profile_enabled = False
        self.profile_rel_tol = None
        self.profile_emit_scalars = True
        self.last_profile = None
        # metric catalog + alert plane (docs/metrics.md, docs/alerts.md):
        # off until configure_metrics / configure_alerts
        self.metric_store = None
        self.alert_engine = None
        self.metrics_export_path = None
        self._owns_monitor = monitor is None
        if monitor is None:
            from .monitor import SummaryMonitor
            monitor = SummaryMonitor(output_path or None,
                                     job_name or "DeepSpeedTelemetry")
        self.monitor = monitor

        # step-metric state: everything is a host counter fed by the proxies;
        # end_step differences them — no device work, no barriers
        self.flops_executed = 0.0
        self.wire_bytes_executed = 0
        self.wire_ici_executed = 0
        self.wire_dcn_executed = 0
        self.hbm_bytes_executed = 0.0
        self.exposed_ici_executed = 0.0
        self.exposed_dcn_executed = 0.0
        self.steps_recorded = 0
        self.last_mfu = None
        self.last_step_ms = None
        self.last_dispatch_ms = None
        self._dispatch_base = None
        self.last_wire_bytes = 0
        self.last_wire_bytes_ici = 0
        self.last_wire_bytes_dcn = 0
        self._dispatch_mark = None
        self._window = deque(maxlen=max(int(mfu_window), 1))  # (dt, flops)
        self._last_end = time.perf_counter()
        self._last_flops = 0.0
        self._last_wire = 0
        self._last_wire_ici = 0
        self._last_wire_dcn = 0
        self._last_hbm = 0.0
        self._last_exp_ici = 0.0
        self._last_exp_dcn = 0.0
        self._last_compiles = 0

        # HBM observatory (docs/hbm.md): per-class resident bytes from the
        # engine's memory_manifest — host dicts only, set once at wiring time,
        # emitted as Memory/* scalars in end_step (no device work ever)
        self._memory_class_bytes = None
        self._memory_geometry = None
        self._forecast_config = None

        self._trace_active = False
        self._trace_done = False
        self._trace_failed = False
        self._warned_perturbing = False
        self._noted_suppressed = False
        self._closed = False
        atexit.register(self.close)

    # ------------------------------------------------------------- watchdog
    def watch(self, name: str, jitted):
        """Wrap a jitted program in the compile watchdog (None passes through)."""
        if jitted is None:
            return None
        return _WatchedJit(name, jitted, self)

    def note_execution(self, flops: float, wire_bytes: int,
                       wire_ici: int = 0, wire_dcn: int = 0,
                       hbm_bytes: float = 0.0, exposed_ici_s: float = 0.0,
                       exposed_dcn_s: float = 0.0):
        self.flops_executed += flops
        self.wire_bytes_executed += wire_bytes
        self.wire_ici_executed += wire_ici
        self.wire_dcn_executed += wire_dcn
        self.hbm_bytes_executed += hbm_bytes
        self.exposed_ici_executed += exposed_ici_s
        self.exposed_dcn_executed += exposed_dcn_s

    def set_memory_manifest(self, class_bytes, geometry=None,
                            forecast_config=None):
        """Install the engine's per-class resident-byte attribution
        (utils/hbm.manifest_signatures over engine.memory_manifest()).
        ``class_bytes`` is a host dict {class: per-device bytes}; ``geometry``
        the manifest's predictor geometry; ``forecast_config`` an optional
        utils/hbm.forecast config enabling fitting-delta suggestions in the
        flight recorder's OOM forensics. Pure host state — end_step emits the
        classes as ``Memory/*`` scalars and nothing about the compiled step
        changes (HLO-instruction-identity is pinned in tests)."""
        self._memory_class_bytes = dict(class_bytes) if class_bytes else None
        self._memory_geometry = dict(geometry) if geometry else None
        self._forecast_config = forecast_config

    def memory_snapshot(self) -> Optional[Dict[str, Any]]:
        """The OOM-forensics input: manifest classes + geometry + the device
        watermarks + the watchdog's compiled-temp peak. None when no manifest
        was installed (telemetry.hbm off)."""
        if self._memory_class_bytes is None:
            return None
        return {
            "classes": dict(self._memory_class_bytes),
            "geometry": dict(self._memory_geometry or {}),
            "measured": hbm_stats(),
            "temp_peak_bytes": self.watchdog.peak_temp_bytes(),
            "forecast_config": self._forecast_config,
        }

    def configure_profile(self, enabled: bool, reconcile_tolerance=None,
                          emit_scalars: bool = True):
        """Switch the measured-time profile observatory on for this session:
        every subsequently compiled program also records its scope/collective
        identity catalog (utils/profile_ingest.program_profile_info — pure
        host text analysis, the compiled step is untouched), and when a trace
        window closes end_step ingests the written trace into ``Profile/*``
        scalars and ``last_profile``. Call before the step programs compile,
        like set_comm_topology."""
        self.profile_enabled = bool(enabled)
        self.profile_rel_tol = reconcile_tolerance
        self.profile_emit_scalars = bool(emit_scalars)
        if self.profile_enabled:
            self.watchdog.profile_scopes = True

    def profile_snapshot(self) -> Optional[Dict[str, Any]]:
        """Flight-recorder embedding: the last closed trace window's measured
        profile report (utils/profile_ingest.summarize_slices) plus the
        window disposition. None when no window was ever ingested AND the
        trace never failed — i.e. when there is nothing worth embedding."""
        if self.last_profile is None and not self._trace_failed:
            return None
        return {
            "trace_dir": self.trace_output_dir,
            "trace_failed": self._trace_failed,
            "report": self.last_profile,
        }

    def configure_metrics(self, enabled: bool = True, ring_len: int = 512,
                          strict: bool = False,
                          export_path: Optional[str] = None):
        """Switch the metric catalog router on: every scalar any observatory
        emits through this session's SummaryMonitor is resolved against the
        MetricCatalog (unknown names warn-once; ``strict`` raises — the test
        drift guard) and recorded into a bounded per-host time-series ring.
        Pure host bookkeeping — the step programs are untouched
        (HLO-instruction-identity pinned in tests). ``export_path`` writes an
        OpenMetrics text exposition of the ring's latest values on close."""
        if not enabled:
            return
        from .metrics import MetricStore, default_catalog
        self.metric_store = MetricStore(catalog=default_catalog(),
                                        ring_len=ring_len, strict=strict,
                                        host=self.host_id)
        if self.monitor is not None:
            self.monitor.metrics = self.metric_store
        self.metrics_export_path = export_path or None

    def configure_alerts(self, rules=None, recorder=None,
                         ring_len: int = 512):
        """Arm the alert plane: deterministic host-side rules (utils/alerts)
        evaluated once per end_step against the metric ring — zero new
        device syncs, zero step-program changes. ``rules=None`` arms the
        shipped default ruleset. The flight recorder can be attached later
        (engine wiring builds it after the session)."""
        if self.metric_store is None:
            self.configure_metrics(ring_len=ring_len)
        from .alerts import AlertEngine
        self.alert_engine = AlertEngine(rules=rules, store=self.metric_store,
                                        monitor=self.monitor,
                                        recorder=recorder)

    def alerts_snapshot(self) -> Optional[Dict[str, Any]]:
        """Flight-recorder embedding: alert rules/fired/active state plus the
        full metric ring, so a page-triggered post-mortem carries the
        evidence the rule fired on. None when the plane is off."""
        if self.metric_store is None and self.alert_engine is None:
            return None
        out: Dict[str, Any] = {}
        if self.alert_engine is not None:
            out.update(self.alert_engine.snapshot())
        if self.metric_store is not None:
            out["ring"] = self.metric_store.to_dict()
        return out

    def set_comm_topology(self, slice_sets):
        """Install the slice factorization (list of per-slice device-id sets,
        CommTopology.slice_device_sets) that splits every subsequently compiled
        program's wire bytes into the ICI vs DCN ledger. Call before the step
        programs compile — already-analyzed records keep their old split."""
        self.watchdog.slice_sets = (
            [frozenset(s) for s in slice_sets] if slice_sets else None)

    # ------------------------------------------------------------- trace window
    def on_step_begin(self, global_step: int):
        """Trace-window bookkeeping; called at the first micro-step of a window
        with the number of COMPLETED optimizer steps (captures steps a..b-1 for
        ``trace_steps = [a, b]``)."""
        if self.trace_steps is None or self._trace_failed:
            return
        a, b = self.trace_steps
        if self._trace_active and global_step >= b:
            self._stop_trace()
        if not self._trace_active and not self._trace_done and a <= global_step < b:
            self._start_trace()

    def _start_trace(self):
        a, b = self.trace_steps
        try:
            os.makedirs(self.trace_output_dir, exist_ok=True)
            jax.profiler.start_trace(self.trace_output_dir)
        except Exception as e:
            self._trace_failed = True
            logger.warning(f"[deepspeed_tpu] telemetry: profiler trace unavailable "
                           f"({e!r}); trace window [{a}, {b}) skipped")
            return
        self._trace_active = True
        logger.info(f"[deepspeed_tpu] telemetry: profiler trace started for steps "
                    f"{a}..{b - 1} -> {self.trace_output_dir}")

    def _stop_trace(self):
        try:
            jax.profiler.stop_trace()
            logger.info(f"[deepspeed_tpu] telemetry: profiler trace written to "
                        f"{self.trace_output_dir}")
        except Exception as e:
            self._trace_failed = True
            logger.warning(f"[deepspeed_tpu] telemetry: stop_trace failed ({e!r})")
        self._trace_active = False
        self._trace_done = True

    def _ingest_profile(self):
        """Read the just-closed trace window back into the measured profile
        report (utils/profile_ingest) — pure host file parsing after
        stop_trace flushed, no device work. Failures warn once and leave
        ``last_profile`` None; the training loop is never at risk from a
        malformed trace."""
        from .profile_ingest import (ProfileParseError, catalog_from_watchdog,
                                     device_slices, load_trace_dir,
                                     summarize_slices)
        a, b = self.trace_steps
        try:
            events, _files = load_trace_dir(self.trace_output_dir)
            self.last_profile = summarize_slices(
                device_slices(events),
                catalog=catalog_from_watchdog(self.watchdog),
                devices=jax.device_count(), steps=max(b - a, 1),
                peak_tflops=self.peak_tflops)
        except (ProfileParseError, OSError) as e:
            logger.warning(f"[deepspeed_tpu] telemetry: profile ingest of "
                           f"{self.trace_output_dir} failed ({e}); Profile/* "
                           "scalars skipped")
        return self.last_profile

    # ------------------------------------------------------------- step metrics
    def mark_step_dispatched(self):
        """Host-local step boundary: the engine calls this when every
        host-side phase of the step is done and it is about to dispatch the
        final update program — i.e. when this host ARRIVES at the step's
        barrier. end_step turns it into ``last_dispatch_ms``. The cluster
        observatory attributes stragglers from this window: collectives (and
        the fetches behind them) equalise the end-to-end step wall across
        hosts, so only how LATE a host reached the barrier shows which host
        was actually slow."""
        self._dispatch_mark = time.perf_counter()

    def rebase_dispatch_window(self):
        """Restart the host-local dispatch window NOW. The cluster observatory
        calls this right after its heartbeat allgather: the allgather is
        itself a cross-host rendezvous, so time spent waiting in it belongs to
        the slow peer — charging it to THIS host's next dispatch window would
        re-equalise exactly the signal the window exists to separate."""
        self._dispatch_base = time.perf_counter()

    def end_step(self, global_step: int, samples_per_step: int, pending=None,
                 numerics=None, goodput=None, serving=None,
                 schedule_goodput=None, run_goodput=None):
        """Close one optimizer step's metrics. The ONLY blocking operation is a
        device_get of ``pending``'s last loss scalar (already computed; the
        engine fetches it for its monitor anyway) — the step boundary rides that
        fetch instead of a queue-draining barrier, so the offload/ring pipelines
        stay fully async. ``global_step`` is the count of completed steps.

        ``numerics`` (optional) is the step's in-graph sentinel output (a small
        pytree of per-subtree stat vectors); it is fetched JOINTLY with the loss
        in the same device_get, so enabling the numerics sentinel adds no host
        sync point. Returns the host-side numerics stats (or None).

        ``schedule_goodput`` (optional) is the pipeline tracer's per-step
        schedule decomposition (utils/pipeline_trace.goodput_decomposition) —
        already computed from host timestamps, so emitting it here adds
        ``Pipeline/Goodput/*`` scalars only. ``goodput`` is its deprecated
        alias (one release; the bare name collided with the run-level ledger).

        ``run_goodput`` (optional) is the run-lifecycle ledger's scalar dict
        (utils/goodput.RunLedger.scalar_items) — emitted verbatim as
        ``Run/Goodput/*`` scalars. The two fractions measure different
        things: Pipeline/Goodput is schedule efficiency within one step,
        Run/Goodput is productive wall over the whole run (docs/goodput.md).

        ``serving`` (optional) is the serving request tracer's flat latency
        summary (serve/request_trace.RequestTracer.latency_summary — e.g.
        ``ttft_ms_p99``); emitted as ``Serving/Latency/*`` scalars, again
        host-computed so scalars only."""
        if schedule_goodput is None:
            schedule_goodput = goodput
        # dispatch boundary: set by mark_step_dispatched (engine, pre-fetch);
        # a caller that never marks gets "now", i.e. dispatch wall == step wall
        fetch_start = self._dispatch_mark
        if fetch_start is None:
            fetch_start = time.perf_counter()
        self._dispatch_mark = None
        numerics_host = None
        try:
            if pending:
                _, numerics_host = jax.device_get((pending[-1], numerics))
            elif numerics is not None:
                numerics_host = jax.device_get(numerics)
        except Exception:
            pass
        now = time.perf_counter()
        compiles = self.watchdog.compiles()
        dt = now - self._last_end
        dispatch_base = (self._dispatch_base if self._dispatch_base is not None
                         else self._last_end)
        dispatch_dt = fetch_start - dispatch_base
        self._dispatch_base = None
        flops_d = self.flops_executed - self._last_flops
        wire_d = self.wire_bytes_executed - self._last_wire
        wire_ici_d = self.wire_ici_executed - self._last_wire_ici
        wire_dcn_d = self.wire_dcn_executed - self._last_wire_dcn
        hbm_d = self.hbm_bytes_executed - self._last_hbm
        exp_ici_d = self.exposed_ici_executed - self._last_exp_ici
        exp_dcn_d = self.exposed_dcn_executed - self._last_exp_dcn
        had_compile = compiles != self._last_compiles
        self._last_end = now
        self._last_flops = self.flops_executed
        self._last_wire = self.wire_bytes_executed
        self._last_wire_ici = self.wire_ici_executed
        self._last_wire_dcn = self.wire_dcn_executed
        self._last_hbm = self.hbm_bytes_executed
        self._last_exp_ici = self.exposed_ici_executed
        self._last_exp_dcn = self.exposed_dcn_executed
        self._last_compiles = compiles

        samples = global_step * samples_per_step
        mon = self.monitor
        self.last_step_ms = dt * 1000.0
        self.last_dispatch_ms = max(dispatch_dt, 0.0) * 1000.0
        self.last_wire_bytes = wire_d
        self.last_wire_bytes_ici = wire_ici_d
        self.last_wire_bytes_dcn = wire_dcn_d
        self.steps_recorded += 1
        mon.add_scalar("Telemetry/Samples/step_time_ms", dt * 1000.0, samples)
        if dt > 0:
            mon.add_scalar("Telemetry/Samples/samples_per_sec",
                           samples_per_step / dt, samples)
        mon.add_scalar("Telemetry/Samples/wire_bytes", wire_d, samples)
        mon.add_scalar("Telemetry/Samples/wire_bytes_ici", wire_ici_d, samples)
        mon.add_scalar("Telemetry/Samples/wire_bytes_dcn", wire_dcn_d, samples)
        # rolling MFU over compile-free steps: a step that paid a compile would
        # poison the window with compile wall-time that is not execution
        if not had_compile and flops_d > 0 and dt > 0:
            self._window.append((dt, flops_d))
        if self.peak_tflops and self._window:
            from .flops_profiler import mfu as _mfu
            tot_dt = sum(d for d, _ in self._window)
            tot_f = sum(f for _, f in self._window)
            self.last_mfu = _mfu({"flops": tot_f}, tot_dt, self.peak_tflops)
            mon.add_scalar("Telemetry/Samples/mfu", self.last_mfu, samples)
        stats = hbm_stats()
        if stats is not None:
            mon.add_scalar("Telemetry/Samples/hbm_in_use_bytes",
                           stats.get("bytes_in_use", 0), samples)
            mon.add_scalar("Telemetry/Samples/hbm_peak_bytes",
                           stats.get("peak_bytes_in_use", 0), samples)
        mon.add_scalar("Telemetry/Samples/compile_count", compiles, samples)
        # per-class resident-HBM attribution: host constants installed once by
        # the engine via set_memory_manifest — no device syncs, and the
        # compiled step is untouched (HLO-instruction-identity pinned in
        # tests). Scalars appear/disappear with telemetry.hbm only.
        if self._memory_class_bytes is not None:
            for cls, nbytes in sorted(self._memory_class_bytes.items()):
                mon.add_scalar(f"Memory/{cls}_bytes", nbytes, samples)
            mon.add_scalar("Memory/compiled_temp_peak_bytes",
                           self.watchdog.peak_temp_bytes(), samples)
        # step anatomy: the roofline attribution of this step's measured wall
        # time. Pure arithmetic over counters the proxies already fed — the
        # scalars appear or disappear with telemetry.anatomy, nothing else
        # about the step path changes (asserted HLO-identical in tests).
        if self.anatomy_spec is not None and dt > 0 and not had_compile:
            from .roofline import roofline
            rf = roofline(flops_d, hbm_d, exp_ici_d, exp_dcn_d,
                          self.anatomy_spec, measured_seconds=dt)
            self.last_anatomy = rf
            mon.add_scalar("Anatomy/compute_ms",
                           rf["compute_s"] * 1000.0, samples)
            mon.add_scalar("Anatomy/hbm_bound_ms",
                           rf["hbm_bound_s"] * 1000.0, samples)
            mon.add_scalar("Anatomy/exposed_ici_ms",
                           rf["exposed_ici_s"] * 1000.0, samples)
            mon.add_scalar("Anatomy/exposed_dcn_ms",
                           rf["exposed_dcn_s"] * 1000.0, samples)
            mon.add_scalar("Anatomy/host_gap_ms",
                           rf["host_gap_s"] * 1000.0, samples)
            mon.add_scalar("Anatomy/predicted_floor_ms",
                           rf["predicted_floor_s"] * 1000.0, samples)
            mon.add_scalar("Anatomy/mfu_ceiling", rf["mfu_ceiling"], samples)
        if schedule_goodput:
            for key in ("fwd_seconds", "bwd_seconds", "p2p_seconds", "load_seconds",
                        "reduce_seconds", "opt_seconds", "bubble_seconds",
                        "pipeline_seconds"):
                if key in schedule_goodput:
                    mon.add_scalar(f"Pipeline/Goodput/{key}",
                                   schedule_goodput[key], samples)
            if schedule_goodput.get("bubble_fraction") is not None:
                mon.add_scalar("Pipeline/Goodput/bubble_fraction",
                               schedule_goodput["bubble_fraction"], samples)
        if run_goodput:
            for key in sorted(run_goodput):   # sorted: deterministic order
                mon.add_scalar(key, run_goodput[key], samples)
        if serving:
            for key in sorted(serving):   # sorted: deterministic scalar order
                mon.add_scalar(f"Serving/Latency/{key}", serving[key], samples)
        mon.flush()
        if self._trace_active and self.trace_steps is not None \
                and global_step >= self.trace_steps[1]:
            self._stop_trace()
            # measured-time observatory: the window just flushed to disk —
            # read it back (host-side file parsing only; the step programs
            # are untouched and HLO-instruction-identical, pinned in tests)
            if self.profile_enabled and not self._trace_failed \
                    and self._ingest_profile() is not None \
                    and self.profile_emit_scalars:
                prof = self.last_profile
                steps = max(prof["steps"], 1)
                cls = prof["classes"]
                mon.add_scalar("Profile/compute_ms",
                               cls["compute"]["busy_us"] / steps / 1e3,
                               samples)
                mon.add_scalar("Profile/collective_ici_ms",
                               cls["collective_ici"]["busy_us"] / steps / 1e3,
                               samples)
                mon.add_scalar("Profile/collective_dcn_ms",
                               cls["collective_dcn"]["busy_us"] / steps / 1e3,
                               samples)
                mon.add_scalar("Profile/exposed_ici_ms",
                               cls["collective_ici"]["exposed_us"] / steps
                               / 1e3, samples)
                mon.add_scalar("Profile/exposed_dcn_ms",
                               cls["collective_dcn"]["exposed_us"] / steps
                               / 1e3, samples)
                mon.add_scalar("Profile/host_gap_ms",
                               cls["host_gap"]["gap_us"] / steps / 1e3,
                               samples)
                mon.add_scalar("Profile/step_wall_ms",
                               prof["step_wall_us"] / 1e3, samples)
                if prof.get("measured_mfu") is not None:
                    mon.add_scalar("Profile/mfu", prof["measured_mfu"],
                                   samples)
                mon.flush()
        if self.alert_engine is not None:
            # alert rules run on the end_step boundary, on the same axis the
            # scalars above were recorded at — pure reads of the host-side
            # metric ring, no device work (pinned by the no-sync guard)
            self.alert_engine.evaluate(samples)
        return numerics_host

    # ------------------------------------------------------------- breakdown gate
    def warn_perturbing_once(self):
        if not self._warned_perturbing:
            self._warned_perturbing = True
            logger.warning(
                "[deepspeed_tpu] telemetry.perturbing_breakdown=true: barrier-per-"
                "section timers are ACTIVE — every section boundary drains the "
                "device queue (jax.effects_barrier), serializing async dispatch and "
                "the offload/ring pipelines. The numbers are for debugging section "
                "attribution only; disable for performance runs.")

    def note_breakdown_suppressed_once(self):
        if not self._noted_suppressed:
            self._noted_suppressed = True
            logger.info(
                "[deepspeed_tpu] telemetry: wall_clock_breakdown=true is suppressed "
                "while telemetry is enabled (its per-section barriers would perturb "
                "the run being measured); set telemetry.perturbing_breakdown=true "
                "to force the breakdown timers anyway.")

    # ------------------------------------------------------------- reporting
    def summary(self) -> Dict[str, Any]:
        """One-shot digest for benches/reports: rolling MFU, HBM watermarks,
        wire bytes of the last step, and the watchdog's compile accounting."""
        stats = hbm_stats() or {}
        anatomy = None
        if self.last_anatomy is not None:
            rf = self.last_anatomy
            anatomy = {
                "predicted_floor_ms": round(rf["predicted_floor_s"] * 1e3, 6),
                "compute_ms": round(rf["compute_s"] * 1e3, 6),
                "hbm_bound_ms": round(rf["hbm_bound_s"] * 1e3, 6),
                "exposed_ici_ms": round(rf["exposed_ici_s"] * 1e3, 6),
                "exposed_dcn_ms": round(rf["exposed_dcn_s"] * 1e3, 6),
                "host_gap_ms": round(rf["host_gap_s"] * 1e3, 6),
                "mfu_ceiling": round(rf["mfu_ceiling"], 4),
            }
        profile = None
        if self.last_profile is not None:
            prof = self.last_profile
            steps = max(prof["steps"], 1)
            cls = prof["classes"]
            profile = {
                "compute_ms": round(cls["compute"]["busy_us"] / steps / 1e3, 6),
                "collective_ici_ms": round(
                    cls["collective_ici"]["busy_us"] / steps / 1e3, 6),
                "collective_dcn_ms": round(
                    cls["collective_dcn"]["busy_us"] / steps / 1e3, 6),
                "exposed_ici_ms": round(
                    cls["collective_ici"]["exposed_us"] / steps / 1e3, 6),
                "exposed_dcn_ms": round(
                    cls["collective_dcn"]["exposed_us"] / steps / 1e3, 6),
                "host_gap_ms": round(
                    cls["host_gap"]["gap_us"] / steps / 1e3, 6),
                "step_wall_ms": round(prof["step_wall_us"] / 1e3, 6),
                "measured_mfu": prof.get("measured_mfu"),
                "scopes": sorted(prof.get("scopes", {})),
                "steps": prof["steps"],
            }
        # trace-window disposition, with the _trace_failed latch surfaced so
        # a "profiler unavailable" run is visible in every bench/report
        # digest instead of only in one early warning line
        trace = None
        if self.trace_steps is not None:
            trace = {
                "trace_dir": self.trace_output_dir,
                "steps": list(self.trace_steps),
                "active": self._trace_active,
                "done": self._trace_done,
                "failed": self._trace_failed,
            }
        return {
            "mfu": self.last_mfu,
            "step_time_ms": self.last_step_ms,
            "steps_recorded": self.steps_recorded,
            "anatomy": anatomy,
            "trace": trace,
            "profile": profile,
            "wire_bytes_per_step": self.last_wire_bytes,
            "wire_bytes_per_step_ici": self.last_wire_bytes_ici,
            "wire_bytes_per_step_dcn": self.last_wire_bytes_dcn,
            "hbm_in_use_bytes": int(stats.get("bytes_in_use", 0)),
            "hbm_peak_bytes": int(stats.get("peak_bytes_in_use", 0)),
            "compile_count": self.watchdog.compiles(),
            "recompile_count": self.watchdog.recompiles(),
            "compile_seconds": round(self.watchdog.compile_seconds(), 3),
            "compiled_temp_bytes_peak": self.watchdog.peak_temp_bytes(),
        }

    def close(self):
        if self._closed:
            return
        self._closed = True
        if self._trace_active:
            self._stop_trace()
        if self.metrics_export_path and self.metric_store is not None:
            try:
                from .metrics import export_store
                export_store(self.metric_store, self.metrics_export_path)
            except OSError as e:  # export failure must never kill shutdown
                logger.warning(f"[deepspeed_tpu] metrics export failed: {e}")
        if self._owns_monitor and self.monitor is not None:
            self.monitor.close()
