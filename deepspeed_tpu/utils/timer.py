"""Wall-clock + throughput timers.

TPU-native analog of ``deepspeed/utils/timer.py`` (SynchronizedWallClockTimer l.20,
ThroughputTimer l.100). CUDA-stream synchronization is replaced with
``jax.block_until_ready``-style barriers: callers hand the timer a "sync" callable (usually a
no-op on CPU, ``jax.effects_barrier``/block on TPU) or rely on the engine to time around
already-blocked step functions.
"""

import time
from typing import Callable, Dict, List, Optional

from .logging import logger


_sync_failure_warned = False


def _default_sync() -> None:
    # Dispatch is async in JAX; timing boundaries must drain the device queue.
    global _sync_failure_warned
    try:
        import jax
        jax.effects_barrier()
    except Exception as e:
        if not _sync_failure_warned:
            _sync_failure_warned = True
            logger.warning(
                f"[deepspeed_tpu] timer sync failed ({e!r}): jax.effects_barrier "
                "is unavailable, so timers are measuring DISPATCH, not device "
                "compute — treat wall-clock breakdown numbers as unreliable")


class SynchronizedWallClockTimer:
    """Group of named timers whose start/stop drain the device work queue."""

    class Timer:

        def __init__(self, name: str, sync_fn: Callable[[], None]):
            self.name_ = name
            self.elapsed_ = 0.0
            self.started_ = False
            self.start_time = 0.0
            self._sync = sync_fn

        def start(self):
            assert not self.started_, f"timer {self.name_} has already been started"
            self._sync()
            self.start_time = time.time()
            self.started_ = True

        def stop(self, reset=False):
            assert self.started_, f"timer {self.name_} is not started"
            self._sync()
            if reset:
                self.elapsed_ = time.time() - self.start_time
            else:
                self.elapsed_ += time.time() - self.start_time
            self.started_ = False

        def reset(self):
            self.elapsed_ = 0.0
            self.started_ = False

        def elapsed(self, reset=True):
            started_ = self.started_
            if self.started_:
                self.stop()
            elapsed_ = self.elapsed_
            if reset:
                self.reset()
            if started_:
                self.start()
            return elapsed_

    def __init__(self, sync_fn: Optional[Callable[[], None]] = None):
        self.timers: Dict[str, SynchronizedWallClockTimer.Timer] = {}
        self._sync = sync_fn or _default_sync

    def __call__(self, name: str) -> "SynchronizedWallClockTimer.Timer":
        if name not in self.timers:
            self.timers[name] = self.Timer(name, self._sync)
        return self.timers[name]

    @staticmethod
    def memory_usage() -> str:
        from .hbm import device_memory_stats
        stats = device_memory_stats()
        if stats is None:
            return "Mem stats unavailable"
        in_use = stats.get("bytes_in_use", 0) / (1024**3)
        peak = stats.get("peak_bytes_in_use", 0) / (1024**3)
        return f"Mem in-use {round(in_use, 2)} GB | peak {round(peak, 2)} GB"

    def log(self, names: List[str], normalizer: float = 1.0, reset: bool = True, memory_breakdown: bool = False):
        assert normalizer > 0.0
        string = "time (ms)"
        for name in names:
            if name in self.timers:
                elapsed_time = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                string += " | {}: {:.2f}".format(name, elapsed_time)
        if memory_breakdown:
            string += " | " + self.memory_usage()
        logger.info(string)


class ThroughputTimer:

    def __init__(self,
                 batch_size: int,
                 num_workers: int,
                 start_step: int = 2,
                 steps_per_output: int = 50,
                 monitor_memory: bool = False,
                 logging_fn=None):
        self.start_time = 0.0
        self.end_time = 0.0
        self.started = False
        self.batch_size = max(1, batch_size)
        self.num_workers = num_workers
        self.start_step = start_step
        self.epoch_count = 0
        self.local_step_count = 0
        self.total_step_count = 0
        self.total_elapsed_time = 0.0
        self.steps_per_output = steps_per_output
        self.monitor_memory = monitor_memory
        self.logging = logging_fn or logger.info

    def update_epoch_count(self):
        self.epoch_count += 1
        self.local_step_count = 0

    def start(self):
        self.started = True
        if self.total_step_count >= self.start_step:
            _default_sync()
            self.start_time = time.time()

    def stop(self, report_speed=True):
        if not self.started:
            return
        self.started = False
        self.total_step_count += 1
        self.local_step_count += 1
        if self.total_step_count > self.start_step:
            _default_sync()
            self.end_time = time.time()
            duration = self.end_time - self.start_time
            self.total_elapsed_time += duration
            if report_speed and self.local_step_count % self.steps_per_output == 0:
                self.logging("{}/{}, SamplesPerSec={:.4f}".format(self.epoch_count, self.local_step_count,
                                                                  self.avg_samples_per_sec()))
                if self.monitor_memory:
                    self.logging(SynchronizedWallClockTimer.memory_usage())

    def avg_samples_per_sec(self):
        if self.total_step_count > self.start_step and self.total_elapsed_time > 0:
            samples_per_step = self.batch_size * self.num_workers
            avg_time_per_step = self.total_elapsed_time / (self.total_step_count - self.start_step)
            return samples_per_step / avg_time_per_step
        return float("-inf")
