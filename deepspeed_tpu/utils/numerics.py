"""Numerics observatory: in-graph anomaly sentinel + training flight recorder.

Four cooperating pieces (docs/numerics.md):

1. **Sentinel bucketing** — pure in-graph helpers (`bucket_sumsq`,
   `bucket_nonfinite`) that fold per-leaf statistics into per-parameter-subtree
   vectors with `jax.ops.segment_sum`. The engine computes these inside the
   already-jitted step; they leave the device through the telemetry session's
   existing loss fetch, never through an extra host sync.

2. **Cross-rank desync audit** — `leaf_checksum` produces a uint32 bitwise
   checksum per leaf (exact integer addition: reduction order cannot make
   in-sync replicas disagree); `compare_audit_rows` is the host-side
   comparator over the `[replicas, n_subtrees]` matrix an audit-step
   all-gather returns.

3. **Flight recorder** — `FlightRecorder` keeps a bounded per-host ring of
   step records and structured events, and dumps a JSON post-mortem bundle on
   trigger (nonfinite loss, consecutive overflow skips, desync, signal/atexit).

4. **Inspector** — `inspect_dump_main` backs `bin/ds-tpu inspect-dump`,
   printing first-bad-step, the offending subtree, and the loss-scale
   trajectory from a dump bundle.

Invariant enforced by tests/unit/test_no_sync_guard.py: this module performs
NO host synchronisation itself — no ``jax.device_get``, no
``block_until_ready``, no ``np.asarray`` of device values. Everything
host-side here operates on values the engine already fetched.
"""

import argparse
import atexit
import json
import math
import os
import re
import signal
import socket
import time
from collections import deque

import jax
import jax.numpy as jnp

from .logging import logger

NUMERICS_DUMP_VERSION = 1

# ------------------------------------------------------------------ subtrees


def subtree_name(path, depth=1):
    """Join the first `depth` components of a tree_util key path."""
    parts = []
    for p in path[:depth]:
        key = getattr(p, "key", None)
        if key is None:
            key = getattr(p, "idx", None)
        if key is None:
            key = getattr(p, "name", None)
        if key is None:
            key = p
        parts.append(str(key))
    return "/".join(parts) if parts else "<root>"


class SubtreeIndex:
    """Static mapping of tree leaves to named parameter subtrees.

    Built once at init from the parameter pytree structure; the per-leaf
    bucket ids are closure constants inside the jitted step, so bucketing
    compiles to a single segment_sum with no dynamic indexing.
    """

    __slots__ = ("names", "leaf_buckets")

    def __init__(self, names, leaf_buckets):
        self.names = list(names)
        self.leaf_buckets = list(leaf_buckets)

    @property
    def n(self):
        return len(self.names)


def build_subtree_index(tree, depth=1):
    leaves_with_path = jax.tree_util.tree_flatten_with_path(tree)[0]
    names = []
    name_to_id = {}
    buckets = []
    for path, _ in leaves_with_path:
        name = subtree_name(path, depth)
        if name not in name_to_id:
            name_to_id[name] = len(names)
            names.append(name)
        buckets.append(name_to_id[name])
    return SubtreeIndex(names, buckets)


# ------------------------------------------------------------- in-graph math


def bucket_sumsq(tree, index):
    """Per-subtree sum of squares (fp32) -> f32[index.n]. In-graph only."""
    leaves = jax.tree_util.tree_leaves(tree)
    vals = jnp.stack([jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves])
    seg = jnp.asarray(index.leaf_buckets, dtype=jnp.int32)
    return jax.ops.segment_sum(vals, seg, num_segments=index.n)


def bucket_nonfinite(tree, index):
    """Per-subtree nonfinite element count -> i32[index.n]. In-graph only."""
    leaves = jax.tree_util.tree_leaves(tree)
    vals = jnp.stack([
        jnp.sum((~jnp.isfinite(l.astype(jnp.float32))).astype(jnp.int32))
        for l in leaves
    ])
    seg = jnp.asarray(index.leaf_buckets, dtype=jnp.int32)
    return jax.ops.segment_sum(vals, seg, num_segments=index.n)


def leaf_checksum(leaf):
    """uint32 bitwise checksum of one array. Exact (integer addition), so the
    reduction order chosen by XLA cannot make identical replicas disagree —
    a float-sum checksum would false-positive on benign reassociation."""
    x = leaf
    if x.dtype == jnp.bool_:
        bits = x.astype(jnp.uint32)
    else:
        itemsize = x.dtype.itemsize
        if itemsize == 8:  # fold 64-bit leaves to 32-bit before bitcasting
            x = x.astype(jnp.float32) if jnp.issubdtype(x.dtype, jnp.floating) \
                else x.astype(jnp.int32)
            itemsize = 4
        target = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32}[itemsize]
        bits = jax.lax.bitcast_convert_type(x, target).astype(jnp.uint32)
    return jnp.sum(bits, dtype=jnp.uint32)


# --------------------------------------------------------- host-side compare


def compare_audit_rows(matrix, names, slice_rows=None):
    """Host comparator for the audit all-gather result.

    `matrix` is a [replicas, n_subtrees] array of uint32 checksums (already
    fetched by the engine). Returns None when every replica agrees, else a
    dict naming the FIRST diverging subtree and which replicas disagree with
    replica 0.

    `slice_rows` (optional) is the comm topology's per-slice replica grouping
    (CommTopology.slice_rows): when given, the divergence is classified per
    network LEVEL — "intra_slice" when some slice's members disagree among
    themselves (the ICI exchange or the local compute went wrong), else
    "cross_slice" (each slice internally consistent but the slices disagree:
    the DCN hop is the culprit). The payload then also carries
    `diverging_slices` (slices whose consensus differs from slice 0's).
    """
    rows = [[int(v) for v in row] for row in matrix]
    if len(rows) <= 1:
        return None
    n = len(rows[0])
    for j in range(n):
        col = [row[j] for row in rows]
        if any(c != col[0] for c in col):
            div = {
                "subtree": names[j] if j < len(names) else f"<{j}>",
                "index": j,
                "checksums": col,
                "diverging_replicas": [i for i, c in enumerate(col) if c != col[0]],
            }
            if slice_rows and len(slice_rows) > 1:
                intra = any(
                    any(col[r] != col[grp[0]] for r in grp if r < len(col))
                    for grp in slice_rows if grp and grp[0] < len(col))
                div["level"] = "intra_slice" if intra else "cross_slice"
                ref = col[slice_rows[0][0]] if slice_rows[0][0] < len(col) else col[0]
                div["diverging_slices"] = [
                    s for s, grp in enumerate(slice_rows)
                    if grp and grp[0] < len(col) and col[grp[0]] != ref]
            return div
    return None


# ------------------------------------------------------------ flight recorder


def _sanitize_token(s):
    """Filename-safe token: anything outside [A-Za-z0-9.-] collapses to '-'.
    Underscores are excluded on purpose — they are the dump-name field
    separator, so a run id containing one would break the scan regex."""
    return re.sub(r"[^A-Za-z0-9.-]+", "-", str(s)).strip("-")


def default_run_id():
    """Run identity for dump namespacing when several hosts (or several
    launches) share one dump_dir. All ranks of one `ds-tpu` launch derive the
    same id (from the coordinator address the launcher exports), so their
    dumps group into one run; unrelated launches get distinct ids."""
    rid = os.environ.get("DS_RUN_ID")
    if rid:
        return _sanitize_token(rid)
    coord = os.environ.get("DS_COORDINATOR_ADDRESS")
    if coord:
        return "run-" + _sanitize_token(coord)
    node = _sanitize_token(socket.gethostname()) or "node"
    return f"{node}-p{os.getpid()}"


class FlightRecorder:
    """Bounded per-host ring buffer of step records + structured events that
    dumps a JSON post-mortem bundle when triggered."""

    def __init__(self, capacity=256, dump_dir=None, telemetry=None, host_id=0,
                 pipeline_trace=None, request_trace=None, run_id=None,
                 cluster=None):
        self.capacity = int(capacity)
        self.dump_dir = dump_dir
        self.telemetry = telemetry
        # optional PipelineTracer: its span bundle rides along in every dump so
        # ``ds-tpu timeline`` can reconstruct the schedule of a dead run
        self.pipeline_trace = pipeline_trace
        # optional serving RequestTracer (serve/request_trace.py): same deal,
        # for ``ds-tpu serve-timeline`` on a dead serving host's dump
        self.request_trace = request_trace
        # optional ClusterMonitor (utils/cluster.py): heartbeat history +
        # clock-offset estimates ride along so ``ds-tpu cluster-dump`` and
        # ``ds-tpu timeline --cluster`` can merge per-host dumps coherently
        self.cluster = cluster
        # run_id="" keeps the legacy un-namespaced dump names (tests and the
        # crash-sim write those directly); None picks the launch-wide default
        self.run_id = _sanitize_token(run_id) if run_id is not None \
            else default_run_id()
        self.host_id = int(host_id)
        # wall/monotonic anchor pair taken once: every per-step monotonic
        # stamp converts to wall-clock as wall0 + (mono - mono0), so the
        # dump's span fields stay consistent even across NTP slews
        self._wall0 = time.time()
        self._mono0 = time.perf_counter()
        self.steps = deque(maxlen=self.capacity)
        self.events = deque(maxlen=max(self.capacity * 4, 64))
        self.dump_count = 0
        self.last_dump_path = None
        self._pending_anomaly = False
        self._installed = False

    # -- recording ---------------------------------------------------------
    def record_step(self, record):
        # monotonic stamp per record: the dump's "span" header prices
        # seconds-per-step for restart-replay badput (utils/goodput.py)
        record.setdefault("mono", time.perf_counter())
        self.steps.append(record)

    def record_event(self, name, payload, step=None):
        self.events.append({"event": name, "step": step, "payload": payload,
                            "time": time.time()})

    def note_anomaly(self):
        self._pending_anomaly = True

    # -- bundle assembly ---------------------------------------------------
    def first_bad_step(self):
        for rec in self.steps:
            if rec.get("anomaly") or rec.get("overflow"):
                return rec
        return None

    def bundle(self, reason, detail=None):
        bad = self.first_bad_step()
        compile_records = []
        if self.telemetry is not None and getattr(self.telemetry, "watchdog", None):
            for prog, sigs in self.telemetry.watchdog.records.items():
                for rec in sigs.values():
                    compile_records.append({
                        "program": prog,
                        "compile_seconds": rec.compile_seconds,
                        "count": rec.count,
                    })
        out = {
            "version": NUMERICS_DUMP_VERSION,
            "reason": reason,
            "detail": detail,
            "host": self.host_id,
            "time": time.time(),
            "first_bad_step": bad.get("step") if bad else None,
            "offending_subtree": (bad.get("anomaly") or {}).get("subtree")
                                 if bad else None,
            "loss_scale_trajectory": [[r.get("step"), r.get("loss_scale")]
                                      for r in self.steps
                                      if r.get("loss_scale") is not None],
            "steps": list(self.steps),
            "events": list(self.events),
            "compile_records": compile_records,
        }
        span = self._span()
        if span is not None:
            out["span"] = span
        if self.run_id:
            out["run"] = self.run_id
        if self.pipeline_trace is not None:
            out["pipeline_trace"] = self.pipeline_trace.bundle()
        if self.request_trace is not None:
            out["serving_request_trace"] = self.request_trace.bundle()
        if self.cluster is not None:
            out["cluster"] = self.cluster.bundle()
        snap = None
        if self.telemetry is not None:
            snapper = getattr(self.telemetry, "memory_snapshot", None)
            if snapper is not None:
                try:
                    snap = snapper()
                except Exception:  # forensics must never block the dump
                    snap = None
        if snap is not None:
            try:
                from .hbm import oom_forensics
                out["hbm"] = oom_forensics(snap)
            except Exception:
                out["hbm"] = {"error": "oom_forensics failed", "snapshot": snap}
        if self.telemetry is not None:
            # measured-time observatory: the last closed trace window's
            # summary rides along so a post-mortem sees what the device
            # timeline actually did (guarded like hbm — forensics must never
            # block the dump)
            prof_snapper = getattr(self.telemetry, "profile_snapshot", None)
            if prof_snapper is not None:
                try:
                    prof = prof_snapper()
                except Exception:
                    prof = None
                if prof is not None:
                    out["profile"] = prof
            # alert plane: rules/fired/active state + the full metric ring
            # (utils/alerts.py) — a page-severity alert triggers this dump,
            # so the bundle must carry the evidence it fired on
            alert_snapper = getattr(self.telemetry, "alerts_snapshot", None)
            if alert_snapper is not None:
                try:
                    alerts = alert_snapper()
                except Exception:
                    alerts = None
                if alerts is not None:
                    out["alerts"] = alerts
        return out

    def _span(self):
        """Monotonic + wall-clock extent of the recorded step ring, or None
        when no step carries a stamp (records fed in by hand, old callers).
        ``steps_spanned`` counts step *intervals* — the step-number delta when
        both ends know their step, else stamped records minus one — so
        (mono_end - mono_start) / steps_spanned is seconds-per-step; that is
        how ``goodput.estimate_replay_seconds`` prices restart-replay badput
        from a dump alone."""
        stamped = [r for r in self.steps if r.get("mono") is not None]
        if not stamped:
            return None
        first, last = stamped[0], stamped[-1]
        first_step, last_step = first.get("step"), last.get("step")
        if first_step is not None and last_step is not None:
            spanned = int(last_step) - int(first_step)
        else:
            spanned = len(stamped) - 1
        return {
            "mono_start": float(first["mono"]),
            "mono_end": float(last["mono"]),
            "wall_start": self._wall0 + (float(first["mono"]) - self._mono0),
            "wall_end": self._wall0 + (float(last["mono"]) - self._mono0),
            "first_step": first_step,
            "last_step": last_step,
            "steps_spanned": spanned,
        }

    # -- triggering --------------------------------------------------------
    def trigger(self, reason, detail=None, quiet=False):
        # the SummaryMonitor's JSONL streams are block-buffered; a crash
        # post-mortem is exactly when the last pre-crash scalars/events
        # matter, so force them to disk before (and regardless of) the dump
        mon = getattr(self.telemetry, "monitor", None) \
            if self.telemetry is not None else None
        if mon is not None:
            try:
                mon.flush()
            except Exception:  # dump/flush failure must never kill the job
                pass
        if not self.dump_dir:
            return None
        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            prefix = f"numerics_dump_{self.run_id}_" if self.run_id \
                else "numerics_dump_"
            path = os.path.join(
                self.dump_dir,
                f"{prefix}host{self.host_id}_{self.dump_count}.json")
            with open(path, "w") as f:
                json.dump(self.bundle(reason, detail), f, default=float)
            self.dump_count += 1
            self.last_dump_path = path
            self._pending_anomaly = False
            if not quiet:
                logger.warning("numerics: flight recorder dumped post-mortem "
                               f"({reason}) -> {path}")
            return path
        except OSError as e:  # dump failure must never kill the training job
            if not quiet:
                logger.warning(f"numerics: dump failed: {e}")
            return None

    def install(self, install_signal_handlers=False):
        if self._installed:
            return
        self._installed = True
        atexit.register(self._atexit_dump)
        if install_signal_handlers:
            for sig in (signal.SIGTERM, signal.SIGINT):
                try:
                    prev = signal.getsignal(sig)

                    def _handler(signum, frame, _prev=prev):
                        self.trigger("signal", {"signum": signum})
                        if callable(_prev):
                            _prev(signum, frame)
                        else:
                            signal.signal(signum, signal.SIG_DFL)
                            signal.raise_signal(signum)

                    signal.signal(sig, _handler)
                except (ValueError, OSError):
                    pass  # not the main thread / unsupported platform

    def _atexit_dump(self):
        # Only dump at exit when an anomaly was seen but never dumped — a
        # healthy run must leave the dump dir untouched. quiet: log streams
        # may already be closed this late in interpreter shutdown.
        if self._pending_anomaly and self.dump_count == 0:
            self.trigger("atexit", quiet=True)


# --------------------------------------------------------- numerics monitor


class NumericsMonitor:
    """Host-side coordinator: consumes the per-step sentinel stats (already
    fetched through the telemetry loss ride-along), feeds the journal,
    monitor scalars/events, and the flight recorder, and decides triggers."""

    def __init__(self, index, *, monitor=None, telemetry=None, journal=None,
                 recorder=None, audit_interval=0, consecutive_skip_trigger=8,
                 trigger_on_nonfinite_loss=True):
        self.index = index
        self.monitor = monitor
        self.telemetry = telemetry
        self.journal = journal
        self.recorder = recorder
        self.audit_interval = int(audit_interval)
        self.consecutive_skip_trigger = int(consecutive_skip_trigger)
        self.trigger_on_nonfinite_loss = bool(trigger_on_nonfinite_loss)
        self.anomaly_count = 0
        self.audit_runs = 0
        self.audit_seconds = 0.0
        self.desync = None
        self.last_record = None
        self._warned = 0
        if journal is not None:
            journal.emit = self._on_journal_event

    # -- plumbing ----------------------------------------------------------
    def _on_journal_event(self, ev, step):
        if self.monitor is not None:
            self.monitor.event("loss_scale", ev, step)
        if self.recorder is not None:
            self.recorder.record_event("loss_scale", ev, step)

    def _scalar(self, tag, value, step):
        if self.monitor is not None:
            self.monitor.add_scalar(tag, value, step)

    # -- per-step commit ---------------------------------------------------
    def commit_step(self, step, stats, *, loss=None, overflowed=False,
                    grad_norm=None):
        """All inputs are HOST values (the engine fetched them alongside the
        loss). `stats` maps sentinel keys to per-subtree vectors, or is None
        on paths that produce no sentinel (e.g. a pure-eval step)."""
        if self.journal is not None:
            self.journal.record(step, overflowed)
        loss_scale = self.journal.cur_scale if self.journal is not None else None

        names = self.index.names
        anomaly = None
        record = {"step": step, "overflow": bool(overflowed), "loss": loss,
                  "loss_scale": loss_scale, "grad_norm": grad_norm,
                  "subtrees": names}

        if stats is not None:
            gss = [float(v) for v in stats.get("grad_sumsq", [])]
            wss = [float(v) for v in stats.get("weight_sumsq", [])]
            uss = [float(v) for v in stats.get("update_sumsq", [])]
            nonfinite = [int(v) for v in stats.get("grad_nonfinite", [])]

            record["grad_norm_per_subtree"] = [
                math.sqrt(max(v, 0.0)) for v in gss]
            if wss:
                record["weight_norm_per_subtree"] = [
                    math.sqrt(max(v, 0.0)) for v in wss]
            if uss and wss:
                record["update_ratio_per_subtree"] = [
                    (math.sqrt(max(u, 0.0)) / math.sqrt(w))
                    if w > 0.0 else 0.0
                    for u, w in zip(uss, wss)]
            record["nonfinite_total"] = sum(nonfinite)
            record["nonfinite_per_subtree"] = nonfinite

            for j, name in enumerate(names):
                if j < len(gss):
                    self._scalar(f"Numerics/grad_norm/{name}",
                                 record["grad_norm_per_subtree"][j], step)
                if j < len(wss):
                    self._scalar(f"Numerics/weight_norm/{name}",
                                 record["weight_norm_per_subtree"][j], step)
                if "update_ratio_per_subtree" in record and j < len(uss):
                    self._scalar(f"Numerics/update_ratio/{name}",
                                 record["update_ratio_per_subtree"][j], step)

            bad = [j for j, c in enumerate(nonfinite) if c > 0]
            if bad:
                anomaly = {"kind": "nonfinite_grad",
                           "subtree": names[bad[0]],
                           "count": nonfinite[bad[0]],
                           "per_subtree": {names[j]: nonfinite[j] for j in bad}}

        if loss is not None and not math.isfinite(loss):
            if anomaly is None:
                anomaly = {"kind": "nonfinite_loss", "subtree": None}
            anomaly["nonfinite_loss"] = True

        record["anomaly"] = anomaly
        self.last_record = record
        if self.recorder is not None:
            self.recorder.record_step(record)

        if anomaly is not None:
            self.anomaly_count += 1
            if self.recorder is not None:
                self.recorder.note_anomaly()
            if self._warned < 3:
                self._warned += 1
                logger.warning(
                    f"numerics: anomaly at step {step}: {anomaly['kind']}"
                    + (f" in subtree '{anomaly['subtree']}'"
                       if anomaly.get("subtree") else ""))

        # triggers
        if self.recorder is not None:
            if (self.trigger_on_nonfinite_loss and loss is not None
                    and not math.isfinite(loss)):
                self.recorder.trigger("nonfinite_loss", {"step": step})
            elif (self.journal is not None and self.consecutive_skip_trigger > 0
                  and self.journal.skip_streak == self.consecutive_skip_trigger):
                self.recorder.trigger(
                    "consecutive_overflow_skips",
                    {"step": step, "streak": self.journal.skip_streak})
        return record

    # -- audit -------------------------------------------------------------
    def audit_due(self, step):
        return self.audit_interval > 0 and step > 0 \
            and step % self.audit_interval == 0

    def commit_audit(self, step, matrix, names, seconds=0.0, slice_rows=None):
        """`matrix` is the host-fetched [replicas, n] checksum matrix;
        `slice_rows` (optional, CommTopology.slice_rows) classifies any
        divergence per network level (intra_slice vs cross_slice)."""
        self.audit_runs += 1
        self.audit_seconds += float(seconds)
        divergence = compare_audit_rows(matrix, names, slice_rows=slice_rows)
        payload = {"replicas": len(matrix), "subtrees": len(names),
                   "seconds": seconds,
                   "divergence": divergence}
        if self.monitor is not None:
            self.monitor.event("desync_audit", payload, step)
        if self.recorder is not None:
            self.recorder.record_event("desync_audit", payload, step)
        if divergence is not None:
            self.desync = dict(divergence, step=step)
            level = divergence.get("level")
            logger.error(
                f"numerics: CROSS-RANK DESYNC at step {step}: subtree "
                f"'{divergence['subtree']}' disagrees on replicas "
                f"{divergence['diverging_replicas']}"
                + (f" (level: {level})" if level else ""))
            if self.recorder is not None:
                self.recorder.note_anomaly()
                self.recorder.trigger("desync", dict(divergence, step=step))
        return divergence

    # -- reporting ---------------------------------------------------------
    def summary(self):
        return {
            "anomaly_count": self.anomaly_count,
            "journal_events": len(self.journal.events)
            if self.journal is not None else 0,
            "audit_runs": self.audit_runs,
            "audit_seconds": self.audit_seconds,
            "desync": self.desync is not None,
            "dumps": self.recorder.dump_count if self.recorder is not None else 0,
        }


# ---------------------------------------------------------------- inspector


# Both the legacy name (numerics_dump_host0_0.json) and the run-namespaced
# name (numerics_dump_<run>_host0_0.json) parse; legacy dumps group under the
# empty run key "". The run token never contains '_' (see _sanitize_token).
DUMP_NAME_RE = re.compile(
    r"numerics_dump_(?:(?P<run>[^_]+)_)?host(?P<host>\d+)_(?P<idx>\d+)\.json$")


def scan_dump_dir_runs(dump_dir):
    """Group the flight-recorder dumps in ``dump_dir`` by run.

    Returns ``{run_key: [entry, ...]}`` where each entry is
    ``{"host", "index", "path", "mtime"}`` and each run's entries are sorted
    by (index, host). Legacy un-namespaced dumps land under run key ``""``.
    Pure host file I/O."""
    runs = {}
    if not dump_dir or not os.path.isdir(dump_dir):
        return runs
    for name in os.listdir(dump_dir):
        m = DUMP_NAME_RE.match(name)
        if not m:
            continue
        path = os.path.join(dump_dir, name)
        try:
            mtime = os.stat(path).st_mtime
        except OSError:
            continue
        runs.setdefault(m.group("run") or "", []).append({
            "host": int(m.group("host")),
            "index": int(m.group("idx")),
            "path": path,
            "mtime": mtime,
        })
    for entries in runs.values():
        entries.sort(key=lambda e: (e["index"], e["host"]))
    return runs


def load_run_bundles(dump_dir, run=None):
    """Load the newest bundle per host for one run of a shared dump_dir.

    Picks the most recently written run when ``run`` is None. Returns
    ``(run_key, {host: bundle})``; torn dumps are skipped (an older intact
    dump from the same host wins, if any)."""
    runs = scan_dump_dir_runs(dump_dir)
    if not runs:
        return run, {}
    if run is None:
        run = max(runs, key=lambda k: max(e["mtime"] for e in runs[k]))
    elif run not in runs:
        return run, {}
    by_host = {}
    for entry in runs[run]:  # ascending (index, host): last intact one wins
        try:
            with open(entry["path"]) as f:
                by_host[entry["host"]] = json.load(f)
        except (OSError, ValueError):
            continue
    return run, by_host


def merge_first_bad(bundles_by_host):
    """Merged (first_bad_step, first_bad_host) over per-host bundles: the
    minimum first bad step across the fleet, ties broken by lowest host.
    Returns (None, None) when no host recorded a bad step."""
    best = None
    for host in sorted(bundles_by_host):
        s = summarize_dump(bundles_by_host[host])
        step = s.get("first_bad_step")
        if step is None:
            continue
        key = (step, host)
        if best is None or key < best:
            best = key
    return best if best is not None else (None, None)


def scan_dump_dir(dump_dir):
    """Newest flight-recorder bundle in ``dump_dir``, or None when the dir
    holds none. Dumps are grouped by run (see scan_dump_dir_runs); the most
    recently written run wins, then the highest (dump index, host) within it —
    the recorder numbers dumps monotonically per host. Pure host file I/O —
    the auto-resume path (resilience/auto_resume.py) calls this before any
    engine exists."""
    runs = scan_dump_dir_runs(dump_dir)
    if not runs:
        return None
    run = max(runs, key=lambda k: max(e["mtime"] for e in runs[k]))
    best = runs[run][-1]  # entries sorted by (index, host)
    try:
        with open(best["path"]) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None  # a torn dump must not block resume


def summarize_dump(bundle):
    """Derive the headline facts from a dump bundle, recomputing anything a
    partial/old bundle is missing."""
    steps = bundle.get("steps", [])
    first_bad = bundle.get("first_bad_step")
    offending = bundle.get("offending_subtree")
    if first_bad is None:
        for rec in steps:
            if rec.get("anomaly") or rec.get("overflow"):
                first_bad = rec.get("step")
                offending = (rec.get("anomaly") or {}).get("subtree")
                break
    return {
        "reason": bundle.get("reason"),
        "detail": bundle.get("detail"),
        "host": bundle.get("host"),
        "first_bad_step": first_bad,
        "offending_subtree": offending,
        "steps_recorded": len(steps),
        "events_recorded": len(bundle.get("events", [])),
        # None for legacy dumps written before the span header existed
        "span": bundle.get("span"),
        "loss_scale_trajectory": bundle.get("loss_scale_trajectory", []),
        "desync": next((e["payload"]["divergence"]
                        for e in bundle.get("events", [])
                        if e.get("event") == "desync_audit"
                        and (e.get("payload") or {}).get("divergence")), None),
        "compile_records": bundle.get("compile_records", []),
    }


def _inspect_dump_dir(dump_dir, run, as_json):
    """Directory mode: merge the newest run's per-host dumps into one view."""
    run_key, by_host = load_run_bundles(dump_dir, run=run)
    if not by_host:
        print(f"no flight-recorder dumps in {dump_dir}"
              + (f" for run '{run}'" if run else ""))
        return 2
    fb_step, fb_host = merge_first_bad(by_host)
    summaries = {h: summarize_dump(by_host[h]) for h in sorted(by_host)}
    if as_json:
        print(json.dumps({
            "run": run_key,
            "hosts": {str(h): summaries[h] for h in summaries},
            "first_bad_step": fb_step,
            "first_bad_host": fb_host,
        }, indent=2, default=float))
        return 0
    print(f"numerics post-mortem: {dump_dir} "
          f"(run '{run_key}', {len(by_host)} host(s))")
    print(f"  first bad step : {fb_step}")
    print(f"  first bad host : {fb_host}")
    for h in sorted(summaries):
        s = summaries[h]
        print(f"  host {h:<4}: reason={s['reason']} "
              f"first_bad_step={s['first_bad_step']} "
              f"subtree={s['offending_subtree']} "
              f"steps={s['steps_recorded']} events={s['events_recorded']}")
    return 0


def inspect_dump_main(argv=None):
    """Entry point for `ds-tpu inspect-dump <dump.json | dump_dir>`."""
    parser = argparse.ArgumentParser(
        prog="ds-tpu inspect-dump",
        description="Summarize a numerics flight-recorder post-mortem bundle, "
                    "or merge a directory of per-host dumps.")
    parser.add_argument("dump", help="path to a numerics_dump_*.json bundle, "
                                     "or a dump directory of per-host bundles")
    parser.add_argument("--run", default=None,
                        help="directory mode: inspect this run instead of the "
                             "newest one")
    parser.add_argument("--json", action="store_true",
                        help="print the machine-readable summary instead")
    args = parser.parse_args(argv)

    if os.path.isdir(args.dump):
        return _inspect_dump_dir(args.dump, args.run, args.json)

    with open(args.dump) as f:
        bundle = json.load(f)
    s = summarize_dump(bundle)

    if args.json:
        print(json.dumps(s, indent=2, default=float))
        return 0

    print(f"numerics post-mortem: {args.dump}")
    print(f"  trigger reason    : {s['reason']}")
    if s["detail"]:
        print(f"  trigger detail    : {s['detail']}")
    print(f"  host              : {s['host']}")
    print(f"  first bad step    : {s['first_bad_step']}")
    print(f"  offending subtree : {s['offending_subtree']}")
    print(f"  steps recorded    : {s['steps_recorded']}")
    print(f"  events recorded   : {s['events_recorded']}")
    if s.get("span"):
        sp = s["span"]
        mono = float(sp.get("mono_end", 0.0)) - float(sp.get("mono_start", 0.0))
        print(f"  step span         : steps {sp.get('first_step')}"
              f"..{sp.get('last_step')} over {mono:.3f}s "
              f"({sp.get('steps_spanned')} interval(s))")
    if s["desync"]:
        d = s["desync"]
        print(f"  DESYNC            : subtree '{d.get('subtree')}' on replicas "
              f"{d.get('diverging_replicas')}")
    traj = s["loss_scale_trajectory"]
    if traj:
        print("  loss-scale trajectory (step, scale):")
        shown = traj if len(traj) <= 16 else traj[:8] + traj[-8:]
        for step, scale in shown:
            print(f"    {step:>8}  {scale}")
        if len(traj) > 16:
            print(f"    ... ({len(traj)} points total)")
    if s["compile_records"]:
        print("  compile records:")
        for rec in s["compile_records"]:
            print(f"    {rec['program']}: {rec['count']} run(s), "
                  f"{rec['compile_seconds']:.3f}s compile")
    return 0


if __name__ == "__main__":
    raise SystemExit(inspect_dump_main())
