"""Roofline chip-spec model: peak-rate floors for the step-time anatomy.

The roofline method (Williams et al., 2009) bounds a program's runtime from
below by each hardware resource it must saturate: executed flops can go no
faster than peak matrix throughput, touched bytes no faster than HBM
bandwidth, and collective bytes no faster than the link level they ride
(ICI within a slice, DCN across slices). utils/anatomy.py combines these
floors with the async-overlap analysis into a predicted step floor and an
MFU ceiling; this module owns the per-chip peak-rate table and the floor
arithmetic, so the numbers live in exactly one place.

The table entries are approximate public figures on a deliberately simple
convention — dense bf16 peak per chip, aggregate HBM bandwidth per chip, and
an effective per-chip collective bandwidth per link level (not per-link
signaling rates). Every number is overridable through the
``telemetry.anatomy`` config block or the ``ds-tpu anatomy`` CLI; the
``cpu-test`` spec is a generous upper bound for the 8-virtual-device CI mesh,
chosen so predicted floors always sit below measured CPU step times (the
sanity invariant tests pin).
"""

from typing import Dict, Optional

__all__ = ["ChipSpec", "CHIP_SPECS", "detect_chip", "resolve_spec"]


class ChipSpec:
    """Peak rates of one chip generation. ``peak_tflops`` is dense bf16;
    bandwidths are GB/s (1e9 bytes per second) per chip."""

    __slots__ = ("name", "peak_tflops", "hbm_gbps", "ici_gbps", "dcn_gbps")

    def __init__(self, name: str, peak_tflops: float, hbm_gbps: float,
                 ici_gbps: float, dcn_gbps: float):
        self.name = name
        self.peak_tflops = float(peak_tflops)
        self.hbm_gbps = float(hbm_gbps)
        self.ici_gbps = float(ici_gbps)
        self.dcn_gbps = float(dcn_gbps)

    @property
    def peak_flops(self) -> float:
        return self.peak_tflops * 1e12

    def link_gbps(self, level: str) -> float:
        return self.dcn_gbps if level == "dcn" else self.ici_gbps

    def to_dict(self) -> Dict[str, float]:
        return {"name": self.name, "peak_tflops": self.peak_tflops,
                "hbm_gbps": self.hbm_gbps, "ici_gbps": self.ici_gbps,
                "dcn_gbps": self.dcn_gbps}

    def __repr__(self):
        return (f"ChipSpec({self.name!r}, peak_tflops={self.peak_tflops}, "
                f"hbm_gbps={self.hbm_gbps}, ici_gbps={self.ici_gbps}, "
                f"dcn_gbps={self.dcn_gbps})")


CHIP_SPECS = {
    "tpu-v4": ChipSpec("tpu-v4", 275.0, 1228.0, 270.0, 25.0),
    "tpu-v5e": ChipSpec("tpu-v5e", 197.0, 819.0, 200.0, 25.0),
    "tpu-v5p": ChipSpec("tpu-v5p", 459.0, 2765.0, 600.0, 25.0),
    "tpu-v6e": ChipSpec("tpu-v6e", 918.0, 1640.0, 448.0, 25.0),
    # CI mesh: 8 virtual devices on one CPU. Rates are a deliberate UPPER
    # bound on any CI machine, so floor <= measured holds everywhere.
    "cpu-test": ChipSpec("cpu-test", 100.0, 1000.0, 100.0, 25.0),
}

# jax device_kind substrings -> spec table key, most specific first
_KIND_PATTERNS = (("v6", "tpu-v6e"), ("v5p", "tpu-v5p"), ("v5 lite", "tpu-v5e"),
                  ("v5e", "tpu-v5e"), ("v4", "tpu-v4"))


def detect_chip() -> str:
    """Spec-table key for the local accelerator (``cpu-test`` for anything
    the table doesn't know, including the CPU backend)."""
    try:
        import jax
        kind = jax.local_devices()[0].device_kind.lower()
    except Exception:
        return "cpu-test"
    for pattern, name in _KIND_PATTERNS:
        if pattern in kind:
            return name
    return "cpu-test"


def resolve_spec(chip: str = "", peak_tflops: float = 0.0,
                 hbm_gbps: float = 0.0, ici_gbps: float = 0.0,
                 dcn_gbps: float = 0.0) -> ChipSpec:
    """Spec for ``chip`` ("" = auto-detect) with per-field overrides (0 keeps
    the table value). Unknown chip names raise — a typo'd chip must not
    silently price the roofline off the CPU fallback."""
    name = chip or detect_chip()
    base = CHIP_SPECS.get(name)
    if base is None:
        raise ValueError(f"unknown chip {name!r}; known: "
                         f"{', '.join(sorted(CHIP_SPECS))}")
    return ChipSpec(base.name,
                    peak_tflops or base.peak_tflops,
                    hbm_gbps or base.hbm_gbps,
                    ici_gbps or base.ici_gbps,
                    dcn_gbps or base.dcn_gbps)


def compute_floor_seconds(flops: float, spec: ChipSpec) -> float:
    """Time the executed flops need at peak matrix throughput."""
    return max(float(flops), 0.0) / spec.peak_flops


def hbm_floor_seconds(hbm_bytes: float, spec: ChipSpec) -> float:
    """Time the touched bytes need at full HBM bandwidth."""
    return max(float(hbm_bytes), 0.0) / (spec.hbm_gbps * 1e9)


def comm_seconds(wire_bytes: float, level: str, spec: ChipSpec) -> float:
    """Time ``wire_bytes`` need on the ``level`` ("ici"/"dcn") link."""
    return max(float(wire_bytes), 0.0) / (spec.link_gbps(level) * 1e9)


def roofline(flops: float, hbm_bytes: float, exposed_ici_s: float,
             exposed_dcn_s: float, spec: ChipSpec,
             measured_seconds: Optional[float] = None) -> Dict[str, float]:
    """The roofline decomposition: per-resource floors, the predicted step
    floor (the binding compute/HBM floor plus all exposed communication —
    overlapped comm hides under compute by construction) and the MFU ceiling
    the program structure permits. With ``measured_seconds``, also attributes
    the measured wall time into compute / HBM-bound / exposed-ICI /
    exposed-DCN / host-gap residual."""
    compute_s = compute_floor_seconds(flops, spec)
    hbm_s = hbm_floor_seconds(hbm_bytes, spec)
    bound_s = max(compute_s, hbm_s)
    floor_s = bound_s + max(exposed_ici_s, 0.0) + max(exposed_dcn_s, 0.0)
    out = {
        "compute_floor_s": compute_s,
        "hbm_floor_s": hbm_s,
        "exposed_ici_s": max(exposed_ici_s, 0.0),
        "exposed_dcn_s": max(exposed_dcn_s, 0.0),
        "predicted_floor_s": floor_s,
        "mfu_ceiling": (compute_s / floor_s) if floor_s > 0 else 0.0,
    }
    if measured_seconds is not None:
        measured = max(float(measured_seconds), 0.0)
        out["measured_s"] = measured
        out["compute_s"] = compute_s
        out["hbm_bound_s"] = bound_s - compute_s
        out["host_gap_s"] = max(measured - floor_s, 0.0)
    return out
