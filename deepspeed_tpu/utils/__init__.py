from .logging import logger, log_dist, LoggerFactory
from .timer import SynchronizedWallClockTimer, ThroughputTimer
from .telemetry import TelemetrySession, CompileWatchdog
