"""Alert plane over the metric catalog (docs/alerts.md).

Deterministic host-side rules evaluated once per ``TelemetrySession.end_step``
on the metric ring (utils/metrics.py) — ZERO new device syncs: every input is
a scalar the observatories already fetched. Four rule kinds:

- ``threshold``  — absolute bound (above/below) held for N consecutive
                   observations.
- ``delta``      — rolling-window mean vs the immediately preceding baseline
                   window; "worse" is oriented by the catalog direction, so a
                   rule on an MFU-like metric fires on a DROP and one on a
                   latency-like metric fires on a RISE. Neutral metrics are
                   rejected at validation — a regression rule needs a
                   direction.
- ``stuck``      — metric unchanged for N observations (optionally pinned to
                   a specific value, e.g. the loss-scale min floor), or
                   observed before but absent for N steps.
- ``slo_burn``   — Google-SRE multi-window burn rate over an error budget:
                   fires only when BOTH the fast and the slow window burn
                   above their thresholds, so a single bad step can't page
                   but a sustained budget fire does, fast. ``fraction`` mode
                   reads bad-fraction gauges (``good: true`` inverts a
                   goodput gauge like ``Run/Goodput/goodput_fraction``);
                   ``counter`` mode diffs a cumulative counter like
                   ``Serving/Fleet/shed`` into per-step events against a
                   budget of allowed events/step.

A rule firing is a False->True transition: it emits an ``Alerts/<rule>``
scalar (1.0), appends a structured record to the SummaryMonitor event stream,
and — severity ``page`` — triggers a flight-recorder dump so the post-mortem
bundle carries the full metric ring. Clearing emits the 0.0 scalar and an
``alert_clear`` event. Per-host alert state merges fleet-wide through
``assemble_cluster_report`` (utils/cluster.py), which names the first-firing
host + rule.

``ds-tpu alerts`` renders fired/active alerts from a live events ledger or a
dump; ``ds-tpu alert-sim`` is the attribution harness: four injected
ground-truth regressions, each asserted to fire exactly its own rule in the
shipped default ruleset and no other (golden-pinned, gated in lint.sh).

Pure host code: no jax import, no blocking primitives (pinned by
tests/unit/test_no_sync_guard.py).
"""

import json
import os

from .logging import logger
from .metrics import (HIGHER, LOWER, NEUTRAL, MetricStore, default_catalog,
                      merge_host_rings)

ALERTS_VERSION = 1
RULE_KINDS = ("threshold", "delta", "stuck", "slo_burn")
SEVERITIES = ("warn", "page")

# allowed keys per rule kind (beyond the common name/kind/metric/severity)
_COMMON_KEYS = {"name", "kind", "metric", "severity"}
_KIND_KEYS = {
    "threshold": {"above", "below", "for_steps"},
    "delta": {"window", "baseline", "drop_pct"},
    "stuck": {"steps", "at"},
    "slo_burn": {"mode", "budget", "fast_window", "slow_window",
                 "fast_burn", "slow_burn", "good"},
}


def _bad(rule, msg):
    name = rule.get("name", "<unnamed>") if isinstance(rule, dict) else rule
    raise ValueError(f"alert rule {name!r}: {msg}")


def _num(rule, key, lo=None):
    v = rule[key]
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        _bad(rule, f"{key} must be a number, got {v!r}")
    if lo is not None and not v > lo:
        _bad(rule, f"{key} must be > {lo}, got {v!r}")
    return float(v)


def _count(rule, key, lo=1):
    v = rule[key]
    if isinstance(v, bool) or not isinstance(v, int):
        _bad(rule, f"{key} must be an int, got {v!r}")
    if v < lo:
        _bad(rule, f"{key} must be >= {lo}, got {v!r}")
    return int(v)


def validate_rules(rules, catalog=None):
    """Validate + normalize a rules list (fill kind defaults). Raises
    ValueError on any malformed rule; returns the normalized copies. With a
    catalog, also enforces that every rule targets a DECLARED metric and
    that ``delta`` rules target a direction-bearing (non-neutral) one."""
    if not isinstance(rules, (list, tuple)):
        raise ValueError(f"alert rules must be a list, got {type(rules).__name__}")
    out, names = [], set()
    for rule in rules:
        if not isinstance(rule, dict):
            raise ValueError(f"alert rule must be a dict, got {rule!r}")
        name = rule.get("name")
        if not isinstance(name, str) or not name:
            _bad(rule, "needs a non-empty string 'name'")
        if name in names:
            _bad(rule, "duplicate rule name")
        names.add(name)
        kind = rule.get("kind")
        if kind not in RULE_KINDS:
            _bad(rule, f"kind must be one of {RULE_KINDS}, got {kind!r}")
        metric = rule.get("metric")
        if not isinstance(metric, str) or not metric:
            _bad(rule, "needs a non-empty string 'metric'")
        severity = rule.get("severity", "warn")
        if severity not in SEVERITIES:
            _bad(rule, f"severity must be one of {SEVERITIES}, got {severity!r}")
        unknown = set(rule) - _COMMON_KEYS - _KIND_KEYS[kind]
        if unknown:
            _bad(rule, f"unknown key(s) for kind {kind!r}: {sorted(unknown)}")
        if catalog is not None and catalog.resolve(metric) is None:
            _bad(rule, f"metric {metric!r} is not declared in the "
                       "MetricCatalog (utils/metrics.py)")
        norm = {"name": name, "kind": kind, "metric": metric,
                "severity": severity}
        if kind == "threshold":
            above, below = rule.get("above"), rule.get("below")
            if above is None and below is None:
                _bad(rule, "threshold needs 'above' and/or 'below'")
            if above is not None:
                norm["above"] = _num(rule, "above")
            if below is not None:
                norm["below"] = _num(rule, "below")
            norm["for_steps"] = _count(rule, "for_steps") \
                if "for_steps" in rule else 1
        elif kind == "delta":
            norm["window"] = _count(rule, "window") if "window" in rule else 8
            norm["baseline"] = _count(rule, "baseline") \
                if "baseline" in rule else 16
            norm["drop_pct"] = _num(rule, "drop_pct", lo=0.0) \
                if "drop_pct" in rule else 20.0
            if catalog is not None and catalog.direction(metric) == NEUTRAL:
                _bad(rule, f"delta rule needs a direction-bearing metric; "
                           f"{metric!r} is declared neutral")
        elif kind == "stuck":
            norm["steps"] = _count(rule, "steps", lo=2) \
                if "steps" in rule else 8
            if "at" in rule and rule["at"] is not None:
                norm["at"] = _num(rule, "at")
        else:  # slo_burn
            mode = rule.get("mode", "fraction")
            if mode not in ("fraction", "counter"):
                _bad(rule, f"slo_burn mode must be 'fraction' or 'counter', "
                           f"got {mode!r}")
            norm["mode"] = mode
            if "budget" not in rule:
                _bad(rule, "slo_burn needs a 'budget' (error budget: bad "
                           "fraction in fraction mode, allowed events/step "
                           "in counter mode)")
            norm["budget"] = _num(rule, "budget", lo=0.0)
            norm["fast_window"] = _count(rule, "fast_window") \
                if "fast_window" in rule else 8
            norm["slow_window"] = _count(rule, "slow_window") \
                if "slow_window" in rule else 32
            if norm["slow_window"] < norm["fast_window"]:
                _bad(rule, "slow_window must be >= fast_window")
            norm["fast_burn"] = _num(rule, "fast_burn", lo=0.0) \
                if "fast_burn" in rule else 14.4
            norm["slow_burn"] = _num(rule, "slow_burn", lo=0.0) \
                if "slow_burn" in rule else 6.0
            good = rule.get("good", False)
            if not isinstance(good, bool):
                _bad(rule, f"good must be a bool, got {good!r}")
            norm["good"] = good
            if good and mode == "counter":
                _bad(rule, "'good' only applies to fraction mode")
        out.append(norm)
    return out


def default_rules():
    """The shipped ruleset — one rule per kind, one per failure class the
    attribution harness injects (PERF.md arms exactly these on TPU runs):
    MFU regression, fleet shed-rate SLO burn, loss-scale death spiral
    (stuck at the min-scale floor), cross-host dispatch skew."""
    return validate_rules([
        {"name": "mfu_drop", "kind": "delta",
         "metric": "Telemetry/Samples/mfu",
         "window": 8, "baseline": 16, "drop_pct": 20.0, "severity": "page"},
        {"name": "fleet_shed_burn", "kind": "slo_burn",
         "metric": "Serving/Fleet/shed", "mode": "counter", "budget": 0.1,
         "fast_window": 8, "slow_window": 16, "fast_burn": 14.4,
         "slow_burn": 6.0, "severity": "page"},
        {"name": "loss_scale_stuck", "kind": "stuck",
         "metric": "Train/Samples/loss_scale", "steps": 8, "at": 1.0,
         "severity": "warn"},
        {"name": "dispatch_skew", "kind": "threshold",
         "metric": "Cluster/step_skew", "above": 3.0, "for_steps": 2,
         "severity": "warn"},
    ], default_catalog())


def _mean(vals):
    return sum(vals) / len(vals)


def _r6(x):
    return round(float(x), 6)


class AlertEngine:
    """Evaluates the rules against a MetricStore once per end_step.

    Stateful per rule (active flag + fire count); a rule fires on its
    False->True transition and clears on True->False, so a sustained
    violation produces exactly one record, not one per step."""

    def __init__(self, rules=None, store=None, catalog=None, monitor=None,
                 recorder=None):
        self.catalog = catalog if catalog is not None else default_catalog()
        self.store = store if store is not None \
            else MetricStore(catalog=self.catalog)
        self.rules = default_rules() if rules is None \
            else validate_rules(rules, self.catalog)
        self.monitor = monitor
        self.recorder = recorder  # FlightRecorder, attached late by engine.py
        self.fired = []
        self.evaluations = 0
        self._state = {r["name"]: {"active": False, "fired": 0}
                       for r in self.rules}

    # -- predicates (pure reads of the ring, deterministic) ----------------
    def _eval_threshold(self, rule):
        series = self.store.series(rule["metric"])
        n = rule["for_steps"]
        if len(series) < n:
            return False, None, None
        tail = [v for _, v in series[-n:]]
        above, below = rule.get("above"), rule.get("below")

        def viol(v):
            return (above is not None and v > above) or \
                   (below is not None and v < below)

        if not all(viol(v) for v in tail):
            return False, None, None
        detail = {"for_steps": n, "last": _r6(tail[-1])}
        if above is not None:
            detail["above"] = _r6(above)
        if below is not None:
            detail["below"] = _r6(below)
        return True, tail[-1], detail

    def _eval_delta(self, rule):
        series = self.store.series(rule["metric"])
        w, b = rule["window"], rule["baseline"]
        if len(series) < w + b:
            return False, None, None
        vals = [v for _, v in series]
        recent = _mean(vals[-w:])
        base = _mean(vals[-(w + b):-w])
        if base == 0.0:
            return False, None, None
        direction = self.catalog.direction(rule["metric"])
        if direction == HIGHER:
            frac = (base - recent) / abs(base)
        elif direction == LOWER:
            frac = (recent - base) / abs(base)
        else:  # undeclared metric in a catalog-less validation path: no fire
            return False, None, None
        if frac * 100.0 < rule["drop_pct"]:
            return False, None, None
        return True, recent, {"recent_mean": _r6(recent),
                              "baseline_mean": _r6(base),
                              "regression_pct": _r6(frac * 100.0),
                              "drop_pct": _r6(rule["drop_pct"])}

    def _eval_stuck(self, rule, step):
        series = self.store.series(rule["metric"])
        if not series:
            return False, None, None
        n = rule["steps"]
        at = rule.get("at")
        last_step, last_val = series[-1]
        if step - last_step >= n:
            # observed before, silent since: only the un-pinned form treats
            # absence as stuck (a pinned rule watches for a specific value)
            if at is None:
                return True, last_val, {"mode": "absent",
                                        "last_seen_step": int(last_step),
                                        "silent_steps": int(step - last_step)}
            return False, None, None
        if len(series) < n:
            return False, None, None
        tail = [v for _, v in series[-n:]]
        if any(v != tail[0] for v in tail):
            return False, None, None
        if at is not None and tail[0] != at:
            return False, None, None
        detail = {"mode": "unchanged", "steps": n, "value": _r6(tail[0])}
        if at is not None:
            detail["at"] = _r6(at)
        return True, tail[0], detail

    def _eval_slo_burn(self, rule, active):
        series = self.store.series(rule["metric"])
        vals = [v for _, v in series]
        if rule["mode"] == "counter":
            # cumulative counter -> per-step events (clamped: a counter
            # reset after restart must not register as negative burn)
            bad = [max(0.0, vals[i] - vals[i - 1])
                   for i in range(1, len(vals))]
        else:
            bad = [(1.0 - v) if rule["good"] else v for v in vals]
        sw, fw = rule["slow_window"], rule["fast_window"]
        if len(bad) < sw:
            return False, None, None
        budget = rule["budget"]
        burn_fast = _mean(bad[-fw:]) / budget
        burn_slow = _mean(bad[-sw:]) / budget
        if active:
            # hysteresis: an active burn alert clears only when BOTH windows
            # drop back within budget (burn < 1), not merely below the fire
            # threshold — anything else flaps on a bursty error stream
            firing = burn_fast >= 1.0 or burn_slow >= 1.0
        else:
            firing = burn_fast >= rule["fast_burn"] \
                and burn_slow >= rule["slow_burn"]
        if not firing:
            return False, None, None
        return True, vals[-1], {"burn_fast": _r6(burn_fast),
                                "burn_slow": _r6(burn_slow),
                                "budget": _r6(budget),
                                "fast_burn": _r6(rule["fast_burn"]),
                                "slow_burn": _r6(rule["slow_burn"])}

    def _predicate(self, rule, step, active):
        kind = rule["kind"]
        if kind == "threshold":
            return self._eval_threshold(rule)
        if kind == "delta":
            return self._eval_delta(rule)
        if kind == "stuck":
            return self._eval_stuck(rule, step)
        return self._eval_slo_burn(rule, active)

    # -- evaluation --------------------------------------------------------
    def evaluate(self, step):
        """Evaluate every rule at the end_step boundary; returns the newly
        fired records (empty most steps). Host-only: reads the ring, writes
        the monitor/recorder — never touches a device value."""
        step = int(step)
        self.evaluations += 1
        newly = []
        for rule in self.rules:
            st = self._state[rule["name"]]
            firing, value, detail = self._predicate(rule, step,
                                                    st["active"])
            if firing and not st["active"]:
                st["active"] = True
                st["fired"] += 1
                rec = {"rule": rule["name"], "kind": rule["kind"],
                       "metric": rule["metric"],
                       "severity": rule["severity"], "step": step,
                       "value": _r6(value), "detail": detail}
                self.fired.append(rec)
                newly.append(rec)
                self._emit_fire(rec)
            elif not firing and st["active"]:
                st["active"] = False
                self._emit_clear(rule, step)
        return newly

    def _emit_fire(self, rec):
        logger.warning(f"[deepspeed_tpu] ALERT {rec['severity']}: "
                       f"{rec['rule']} ({rec['kind']} on {rec['metric']}) "
                       f"at step {rec['step']}: {rec['detail']}")
        if self.monitor is not None:
            self.monitor.add_scalar(f"Alerts/{rec['rule']}", 1.0, rec["step"])
            self.monitor.event("alert", rec, rec["step"])
        if self.recorder is not None:
            self.recorder.record_event("alert", rec, rec["step"])
            if rec["severity"] == "page":
                # post-mortem bundle carries the full metric ring (the
                # recorder's bundle embeds alerts_snapshot) — dump AFTER
                # recording so the bundle contains this firing
                self.recorder.trigger(f"alert:{rec['rule']}", rec)

    def _emit_clear(self, rule, step):
        if self.monitor is not None:
            self.monitor.add_scalar(f"Alerts/{rule['name']}", 0.0, step)
            self.monitor.event("alert_clear",
                               {"rule": rule["name"], "step": step}, step)
        if self.recorder is not None:
            self.recorder.record_event("alert_clear",
                                       {"rule": rule["name"]}, step)

    # -- state export ------------------------------------------------------
    def active(self):
        return sorted(n for n, st in self._state.items() if st["active"])

    def snapshot(self):
        """Deterministic alert-state block for dumps and the fleet plane
        (no wall-clock stamps — fleet merges must be byte-stable)."""
        return {
            "version": ALERTS_VERSION,
            "rules": [{"name": r["name"], "kind": r["kind"],
                       "metric": r["metric"], "severity": r["severity"]}
                      for r in self.rules],
            "active": self.active(),
            "fired": list(self.fired),
            "evaluations": self.evaluations,
        }


# ------------------------------------------------------------- fleet merge


def merge_fleet_alerts(by_host):
    """Fleet alert state from per-host dump bundles ({host: bundle} with an
    ``alerts`` block each, as ``assemble_cluster_report`` receives them).
    Deterministic: firings ordered by (step, host, rule); the first entry
    names the first-firing host + rule — where the incident started."""
    hosts = sorted(int(h) for h in by_host)
    fired, active = [], {}
    for h in hosts:
        bundle = by_host[h]
        blk = bundle.get("alerts") if isinstance(bundle, dict) else None
        if not isinstance(blk, dict):
            continue
        for rec in blk.get("fired") or ():
            fired.append(dict(rec, host=int(h)))
        for name in blk.get("active") or ():
            active.setdefault(name, []).append(int(h))
    fired.sort(key=lambda r: (r.get("step", 0), r.get("host", 0),
                              r.get("rule", "")))
    first = fired[0] if fired else None
    return {
        "hosts": hosts,
        "fired_total": len(fired),
        "fired_rules": sorted({r.get("rule") for r in fired}),
        "by_host": {str(h): sum(1 for r in fired if r["host"] == h)
                    for h in hosts},
        "active": {k: sorted(v) for k, v in sorted(active.items())},
        "first_firing": ({"host": first["host"], "rule": first["rule"],
                          "step": first.get("step"),
                          "severity": first.get("severity")}
                         if first else None),
    }


# --------------------------------------------------------------- ds-tpu CLI


def _load_alert_state(path):
    """Alert state from an events.jsonl ledger (live run) or a
    flight-recorder dump's ``alerts`` block."""
    if path.endswith(".jsonl"):
        fired, active = [], []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                if rec.get("event") == "alert":
                    p = rec.get("payload") or {}
                    fired.append(p)
                    if p.get("rule") not in active:
                        active.append(p.get("rule"))
                elif rec.get("event") == "alert_clear":
                    rule = (rec.get("payload") or {}).get("rule")
                    if rule in active:
                        active.remove(rule)
        return {"fired": fired, "active": sorted(a for a in active if a)}
    with open(path) as f:
        data = json.load(f)
    blk = data.get("alerts") if isinstance(data, dict) else None
    if not isinstance(blk, dict):
        raise ValueError(f"{path}: no alert state (expected an events.jsonl "
                         "ledger or a flight-recorder dump with an alerts "
                         "block)")
    return {"fired": list(blk.get("fired") or []),
            "active": list(blk.get("active") or [])}


def alerts_main(argv=None):
    """``ds-tpu alerts`` — render fired/active alerts; ``--diff`` compares
    two states (what's new, what resolved)."""
    import argparse
    ap = argparse.ArgumentParser(
        prog="ds-tpu alerts",
        description="fired/active alerts from a live ledger or dump")
    ap.add_argument("source", help="events.jsonl ledger or flight-recorder "
                                   "dump JSON")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--diff", metavar="BASELINE",
                    help="compare against BASELINE's alert state")
    args = ap.parse_args(argv)
    try:
        state = _load_alert_state(args.source)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"alerts: {e}", flush=True)
        return 1
    if args.diff:
        try:
            base = _load_alert_state(args.diff)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"alerts: {e}", flush=True)
            return 1
        mine = {r.get("rule") for r in state["fired"]}
        theirs = {r.get("rule") for r in base["fired"]}
        diff = {"new": sorted(mine - theirs),
                "resolved": sorted(theirs - mine),
                "common": sorted(mine & theirs)}
        if args.json:
            print(json.dumps(diff, indent=2, sort_keys=True), flush=True)
        else:
            for k in ("new", "resolved", "common"):
                print(f"{k:>9}: {', '.join(diff[k]) or '-'}", flush=True)
        return 0
    if args.json:
        print(json.dumps(state, indent=2, sort_keys=True), flush=True)
        return 0
    if not state["fired"]:
        print("no alerts fired", flush=True)
        return 0
    print(f"{len(state['fired'])} firing(s), "
          f"{len(state['active'])} active: "
          f"{', '.join(state['active']) or '-'}", flush=True)
    for r in state["fired"]:
        print(f"  step {r.get('step'):>6}  {r.get('severity', '?'):<4}  "
              f"{r.get('rule')}  ({r.get('kind')} on {r.get('metric')})  "
              f"value={r.get('value')}", flush=True)
    return 0


# ------------------------------------------------- attribution harness (sim)


class _SimTelemetry:
    """Minimal telemetry stand-in for the sim's FlightRecorder: provides the
    alerts_snapshot hook the dump bundle embeds (utils/numerics.py)."""

    def __init__(self):
        self.monitor = None
        self.watchdog = None
        self._snapper = None

    def alerts_snapshot(self):
        return self._snapper() if self._snapper is not None else None


def _sim_scenario(name, expected_rule, feed, steps, inject_step, dump_dir,
                  host=0, inject_shift=0):
    """Drive one injected-regression scenario through the DEFAULT ruleset.
    ``feed(store, step, injected)`` must emit ALL four watched metric
    families — healthy except the scenario's own injected stream — so the
    no-cross-fire assertion means something."""
    from .numerics import FlightRecorder

    store = MetricStore(catalog=default_catalog(), ring_len=256, strict=True,
                        host=host)
    tel = _SimTelemetry()
    recorder = FlightRecorder(capacity=64, dump_dir=dump_dir, telemetry=tel,
                              host_id=host, run_id="alertsim")
    engine = AlertEngine(rules=default_rules(), store=store,
                         recorder=recorder)
    tel._snapper = lambda: dict(engine.snapshot(), ring=store.to_dict())
    inject_at = inject_step + inject_shift
    for step in range(steps):
        feed(store, step, step >= inject_at)
        engine.evaluate(step)
    fired_rules = [r["rule"] for r in engine.fired]
    return {
        "name": name,
        "expected_rule": expected_rule,
        "inject_step": inject_at,
        "steps": steps,
        "fired": list(engine.fired),
        "unexpected": sorted(r for r in fired_rules if r != expected_rule),
        "missed": expected_rule not in fired_rules,
        "dumps": recorder.dump_count,
        "ok": fired_rules == [expected_rule],
    }, engine.snapshot()


def _feed_healthy(store, step, *, mfu=True, shed=None, journal=None,
                  skew=True):
    """The healthy baselines each scenario shares. Returns nothing; streams
    straight into the ring like SummaryMonitor.add_scalar would."""
    if mfu:
        step_ms = 100.0 + 0.5 * (step % 3)
        store.observe("Telemetry/Samples/step_time_ms", step_ms, step)
        store.observe("Telemetry/Samples/mfu", 0.40 * 100.0 / step_ms, step)
    if shed is not None:
        store.observe("Serving/Fleet/shed", float(shed), step)
    if journal is not None:
        journal.record(step, False)
        store.observe("Train/Samples/loss_scale", journal.cur_scale, step)
    if skew:
        from .cluster import derive_cluster_stats
        matrix = [[step, 0.0, 100.0 + 0.5 * h + 0.3 * (step % 2),
                   95.0 + 0.5 * h, 0.0, 0.0, 1 << 30] for h in range(4)]
        stats = derive_cluster_stats(matrix)
        store.observe("Cluster/step_skew", stats["step_skew"], step)


def _make_journal():
    from ..runtime.fp16.loss_scaler import LossScaleJournal
    # scale_window 4 < the stuck rule's 8-step run: a HEALTHY journal ramps
    # every 4 clean steps, so its longest unchanged run can never trip the
    # rule — only the min-scale death spiral holds one value 8 steps
    return LossScaleJournal(True, 256.0, scale_window=4, scale_factor=2.0,
                            min_scale=1.0, hysteresis=1)


def _scenario_mfu(seed):
    journal = _make_journal()

    def feed(store, step, injected):
        step_ms = (160.0 if injected else 100.0) + 0.5 * (step % 3)
        store.observe("Telemetry/Samples/step_time_ms", step_ms, step)
        store.observe("Telemetry/Samples/mfu", 0.40 * 100.0 / step_ms, step)
        _feed_healthy(store, step, mfu=False, shed=0.0, journal=journal)

    return feed


def _scenario_shed(seed, steps, inject_step):
    """Fleet shed-rate spike: Poisson arrivals at 2x the service capacity
    (the serve-sim trace generator's own arrival knob) through a bounded
    admission queue — the shed counter is CUMULATIVE like the router's."""
    from ..serve.sim import synth_trace

    reqs = synth_trace(16 * steps, vocab_size=64, max_model_len=32,
                       seed=seed, beam_every=0,
                       arrival_process=("poisson", 4.0))
    arrivals = [0] * (16 * steps)
    for r in reqs:
        if r.arrival < len(arrivals):
            arrivals[r.arrival] += 1
    state = {"queue": 0, "shed": 0, "iter": 0}
    capacity, queue_bound = 2, 8
    journal = _make_journal()

    def feed(store, step, injected):
        if injected:
            # 2x-capacity Poisson arrival burst (seeded trace, iteration
            # domain offset so each injected step consumes fresh arrivals)
            state["queue"] += arrivals[state["iter"]]
            state["iter"] += 1
        else:
            state["queue"] += step % 2  # 0.5 req/step, well under capacity
        over = max(0, state["queue"] - queue_bound)
        state["shed"] += over
        state["queue"] -= over + min(state["queue"] - over, capacity)
        _feed_healthy(store, step, shed=state["shed"], journal=journal)

    return feed


def _scenario_loss_scale(seed):
    journal = _make_journal()

    def feed(store, step, injected):
        # forced-NaN overflow streak: hysteresis-1 journal halves every
        # step, hits the min_scale floor and pins there — the death spiral
        journal.record(step, injected)
        store.observe("Train/Samples/loss_scale", journal.cur_scale, step)
        _feed_healthy(store, step, shed=0.0, journal=None)

    return feed


def _scenario_skew(seed):
    from .cluster import derive_cluster_stats

    journal = _make_journal()

    def feed(store, step, injected):
        matrix = []
        for h in range(4):
            step_ms = 100.0 + 0.5 * h + 0.3 * (step % 2)
            dispatch = 95.0 + 0.5 * h
            if injected and h == 2:
                step_ms *= 6.0   # one host's dispatch stalls the fleet
                dispatch *= 6.0
            matrix.append([step, 0.0, step_ms, dispatch, 0.0, 0.0, 1 << 30])
        stats = derive_cluster_stats(matrix)
        store.observe("Cluster/step_skew", stats["step_skew"], step)
        _feed_healthy(store, step, shed=0.0, journal=journal, skew=False)

    return feed


def run_alert_attribution(seed=20, steps=64, inject_step=32, dump_dir=None):
    """The four ground-truth regressions, each against the shipped default
    ruleset; plus a two-host fleet merge of the shed scenario (host 1's
    injection shifted +4 steps) pinning first-firing attribution.
    Deterministic transcript — golden-pinned in lint.sh."""
    scenarios = [
        ("mfu_step_wall_inflation", "mfu_drop",
         lambda shift: _scenario_mfu(seed)),
        ("fleet_shed_poisson_2x", "fleet_shed_burn",
         lambda shift: _scenario_shed(seed, steps, inject_step + shift)),
        ("loss_scale_forced_nan", "loss_scale_stuck",
         lambda shift: _scenario_loss_scale(seed)),
        ("heartbeat_dispatch_skew", "dispatch_skew",
         lambda shift: _scenario_skew(seed)),
    ]
    results, rules = [], default_rules()
    for name, expected, make_feed in scenarios:
        res, _snap = _sim_scenario(name, expected, make_feed(0), steps,
                                   inject_step, dump_dir)
        results.append(res)
    # fleet plane: the shed regression on two hosts, host 1 injected later —
    # the merged state must name host 0 / fleet_shed_burn as first firing
    by_host = {}
    for host, shift in ((0, 0), (1, 4)):
        _res, snap = _sim_scenario("fleet", "fleet_shed_burn",
                                   _scenario_shed(seed, steps,
                                                  inject_step + shift),
                                   steps, inject_step, dump_dir, host=host,
                                   inject_shift=shift)
        by_host[host] = {"alerts": snap}
    fleet = merge_fleet_alerts(by_host)
    ok = all(r["ok"] for r in results) and \
        fleet["first_firing"] is not None and \
        fleet["first_firing"]["host"] == 0 and \
        fleet["first_firing"]["rule"] == "fleet_shed_burn"
    return {
        "version": ALERTS_VERSION,
        "kind": "alert_attribution",
        "seed": seed,
        "steps": steps,
        "rules": [r["name"] for r in rules],
        "scenarios": results,
        "fleet": fleet,
        "ok": ok,
    }


def alert_sim_main(argv=None):
    """``ds-tpu alert-sim`` — run the attribution harness; exit nonzero
    unless every injected regression fired exactly its own rule."""
    import argparse
    import shutil
    import tempfile
    ap = argparse.ArgumentParser(
        prog="ds-tpu alert-sim",
        description="alert attribution harness: four injected regressions "
                    "against the default ruleset")
    ap.add_argument("--seed", type=int, default=20)
    ap.add_argument("--steps", type=int, default=64)
    ap.add_argument("--inject-step", type=int, default=32)
    ap.add_argument("--json", metavar="PATH",
                    help="write the (golden-pinned) transcript to PATH")
    ap.add_argument("--dump-dir", metavar="DIR",
                    help="keep page-severity flight-recorder dumps in DIR "
                         "(default: a temp dir, removed after the run)")
    args = ap.parse_args(argv)
    dump_dir = args.dump_dir or tempfile.mkdtemp(prefix="alert_sim_")
    try:
        transcript = run_alert_attribution(seed=args.seed, steps=args.steps,
                                           inject_step=args.inject_step,
                                           dump_dir=dump_dir)
    finally:
        if not args.dump_dir:
            shutil.rmtree(dump_dir, ignore_errors=True)
    for s in transcript["scenarios"]:
        fired = [r["rule"] for r in s["fired"]]
        status = "OK " if s["ok"] else "FAIL"
        print(f"[{status}] {s['name']:<28} expected={s['expected_rule']:<18} "
              f"fired={','.join(fired) or '-'}", flush=True)
    ff = transcript["fleet"]["first_firing"]
    print(f"fleet: {transcript['fleet']['fired_total']} firing(s), first = "
          f"host {ff['host']} / {ff['rule']} @ step {ff['step']}"
          if ff else "fleet: no firings", flush=True)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(transcript, f, indent=2, sort_keys=True)
        print(f"transcript -> {args.json}", flush=True)
    print(f"alert-sim: {'OK' if transcript['ok'] else 'FAILED'}", flush=True)
    return 0 if transcript["ok"] else 1
