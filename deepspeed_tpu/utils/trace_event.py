"""Shared Chrome/Perfetto ``trace_event`` writer.

Three CLIs export timelines in the Chrome trace_event JSON format —
``ds-tpu timeline`` (pipeline instruction spans, utils/pipeline_trace.py),
``ds-tpu serve-timeline`` (serving request lifecycles, serve/request_trace.py)
and ``ds-tpu anatomy`` (predicted roofline schedules, utils/anatomy.py). They
grew three private copies of the same event constructors and the byte-stable
serializer; this module is the single copy all of them build on.

The golden-file contract lives in :func:`serialize_trace`: sorted keys, no
whitespace, so the emitted bytes are a pure function of the event dicts'
key/value sets — construction order never matters. The helpers below build
exactly the dict shapes the pre-dedup writers emitted, which is what keeps
``pipeline_timeline_2x4.trace.json`` and ``serve_timeline_64.trace.json``
byte-identical across the refactor.
"""

import json

__all__ = ["serialize_trace", "trace_envelope", "load_bundle",
           "process_name_event", "process_sort_index_event",
           "thread_meta_events", "complete_slice", "counter_event",
           "instant_event"]


def serialize_trace(trace):
    """Byte-stable serialization (sorted keys, no whitespace) — the golden-file
    contract of the timeline exporter tests."""
    return json.dumps(trace, sort_keys=True, separators=(",", ":"))


def trace_envelope(events, generator, **other_data):
    """The top-level trace_event JSON object: ``traceEvents`` plus an
    ``otherData`` block naming the generator (and any exporter-specific
    facts, e.g. stage count or the iteration timebase)."""
    other = {"generator": generator}
    other.update(other_data)
    return {"traceEvents": events, "displayTimeUnit": "ms", "otherData": other}


def load_bundle(path, kind):
    """Read a dump JSON and return the bundle of ``kind`` — either the file
    itself (``data["kind"] == kind``) or a bundle embedded under the ``kind``
    key of a flight-recorder dump. None when neither form is present."""
    with open(path) as f:
        data = json.load(f)
    if data.get("kind") == kind:
        return data
    embedded = data.get(kind)
    if isinstance(embedded, dict) and embedded.get("kind") == kind:
        return embedded
    return None


def process_name_event(pid, name, tid=0):
    return {"ph": "M", "pid": pid, "tid": tid, "name": "process_name",
            "args": {"name": name}}


def process_sort_index_event(pid, sort_index, tid=0):
    """Pin a process track's vertical position in the Perfetto UI — the merged
    measured-vs-predicted profile timeline uses it to keep the predicted
    schedule above the measured one regardless of pid numbering."""
    return {"ph": "M", "pid": pid, "tid": tid, "name": "process_sort_index",
            "args": {"sort_index": sort_index}}


def thread_meta_events(pid, tid, name, sort_index=None):
    """The (thread_name, thread_sort_index) metadata pair for one track;
    the sort_index event is omitted when ``sort_index`` is None."""
    events = [{"ph": "M", "pid": pid, "tid": tid, "name": "thread_name",
               "args": {"name": name}}]
    if sort_index is not None:
        events.append({"ph": "M", "pid": pid, "tid": tid,
                       "name": "thread_sort_index",
                       "args": {"sort_index": sort_index}})
    return events


def complete_slice(pid, tid, ts, dur, name, cat, args, cname=None):
    """A complete ("X") slice; zero-length spans render as 1 us so they stay
    visible in the Perfetto UI. ``cname`` picks a reserved color name."""
    ev = {"ph": "X", "pid": pid, "tid": tid, "ts": ts, "dur": max(dur, 1),
          "cat": cat, "name": name, "args": args}
    if cname:
        ev["cname"] = cname
    return ev


def counter_event(pid, tid, ts, name, args):
    return {"ph": "C", "pid": pid, "tid": tid, "ts": ts, "name": name,
            "args": args}


def instant_event(pid, tid, ts, name, args):
    """A thread-scoped ("s": "t") instant marker."""
    return {"ph": "i", "pid": pid, "tid": tid, "ts": ts, "s": "t",
            "name": name, "args": args}
