"""Cluster observatory: cross-host aggregation, hang/straggler detection,
merged fleet timelines.

Every observatory before this one (telemetry scalars, the numerics flight
recorder, step-time anatomy, the serving request ledger) is strictly
per-host. This module is the cross-host plane that rides the gloo CPU world
`runtime/dist.py` already initialises (docs/cluster.md):

1. **Heartbeat aggregation** — each host contributes its end_step record
   (step wall ms, host-local dispatch wall ms, wire bytes per level, HBM
   watermark) through a small allgather on the host CPU backend. Every host
   derives the same global view from the identical matrix; host 0 emits the
   `Cluster/*` scalars: step skew, the straggler host (named by the same
   median-ratio divergence rule the pipeline observatory uses, with the
   LOWER-middle median so a two-host world can still name one), fleet wire
   totals, HBM peak. The straggler rule runs on the DISPATCH wall: blocking
   collectives equalise the end-to-end step wall across hosts (everyone
   waits for the slowest), so only the host-local window before the first
   blocking fetch attributes the skew to the host that caused it.

2. **Hang watchdog** — a per-host daemon thread arms a deadline around each
   step. On expiry it captures all-thread Python stacks plus the
   last-entered named scope (``ds_grad_bucket{k}``, ``ds_fwd_bwd``, …),
   writes a flight-recorder-format dump through the host's FlightRecorder,
   and best-effort signals peers by dropping an epoch marker file in the
   shared dump_dir — so every host dumps a coherent epoch and a silent hang
   becomes a cross-host post-mortem.

3. **Post-mortem assembly** — ``ds-tpu cluster-dump`` merges the per-host
   dumps of one run into a single report naming the first host to stall and
   the scope it died in; ``ds-tpu timeline --cluster`` merges per-host
   pipeline trace bundles onto per-host track groups, aligned with
   heartbeat-estimated clock offsets.

4. **Fleet serving rollups** — per-replica latency histograms are mergeable
   fixed-bin sketches (serve/request_trace.HistogramSketch), so
   ``fleet_latency_summary`` combines N replicas' distributions exactly and
   deterministically into fleet-level percentiles.

Everything here is host-side: with ``telemetry.cluster`` enabled the
compiled step stays HLO-instruction-identical (tested). Scope entries for
in-graph scopes are recorded when the scope is entered on the host — i.e. at
trace time — so a hang names the program region most recently traced; a hang
inside compilation points at the exact scope being built.

Invariant shared with utils/numerics.py and enforced by
tests/unit/test_no_sync_guard.py: this module performs NO host
synchronisation of device values.
"""

import argparse
import contextlib
import json
import os
import re
import sys
import threading
import time
import traceback
from collections import deque

import jax

from .logging import logger
from .numerics import _sanitize_token, default_run_id
from .trace_event import serialize_trace, trace_envelope

CLUSTER_BUNDLE_VERSION = 1
CLUSTER_KIND = "cluster"

# Heartbeat row layout: one row per host, allgathered every
# heartbeat_interval steps. Columns are plain host floats. ``step_ms`` is the
# end-to-end step wall — in a multi-host world the blocking collectives
# equalise it across hosts (everyone waits for the slowest), so it carries
# the global skew but cannot ATTRIBUTE it. ``dispatch_ms`` is the host-local
# wall from the previous step boundary to this host's first blocking fetch
# (telemetry.mark_step_dispatched): a slow host shows up there asymmetrically,
# so the straggler rule runs on that column.
HEARTBEAT_FIELDS = ("step", "wall_s", "step_ms", "dispatch_ms",
                    "wire_bytes_ici", "wire_bytes_dcn", "hbm_peak_bytes")
(COL_STEP, COL_WALL, COL_STEP_MS, COL_DISPATCH_MS, COL_WIRE_ICI,
 COL_WIRE_DCN, COL_HBM) = range(len(HEARTBEAT_FIELDS))

# Peer hang markers: cluster_hang_<run>_e<epoch>_host<h>.json in the shared
# dump_dir. The run token never contains '_' (numerics._sanitize_token).
MARKER_RE = re.compile(
    r"cluster_hang_(?P<run>[^_]+)_e(?P<epoch>\d+)_host(?P<host>\d+)\.json$")


# ------------------------------------------------------------- scope tracker


class ScopeTracker:
    """Host-side ledger of the last-entered named scope. Thread-safe: the
    training thread enters scopes, the watchdog thread reads them."""

    def __init__(self):
        self._lock = threading.Lock()
        self._last = None  # (name, monotonic entry time)

    def enter(self, name):
        with self._lock:
            self._last = (str(name), time.monotonic())

    def last_scope(self):
        """{"name", "age_s"} of the most recently entered scope, or None."""
        with self._lock:
            if self._last is None:
                return None
            name, t0 = self._last
        return {"name": name, "age_s": max(time.monotonic() - t0, 0.0)}


_DEFAULT_TRACKER = ScopeTracker()


def default_tracker():
    return _DEFAULT_TRACKER


@contextlib.contextmanager
def named_scope(name, tracker=None):
    """Drop-in ``jax.named_scope`` that also records the entry host-side, so
    a hang dump can name the scope. Inside jitted code the record happens at
    trace time (the scope most recently traced/compiled); on host-side code
    it happens per entry."""
    (tracker if tracker is not None else _DEFAULT_TRACKER).enter(name)
    with jax.named_scope(name):
        yield


# --------------------------------------------------------------- stack dumps


def all_thread_stacks(limit=40):
    """{thread label: [frames]} for every live Python thread. Pure host
    introspection — safe to call from the watchdog thread mid-hang."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for ident, frame in sys._current_frames().items():
        label = f"{names.get(ident, 'thread')}-{ident}"
        stack = [f"{fs.filename}:{fs.lineno}:{fs.name}"
                 for fs in traceback.extract_stack(frame)]
        out[label] = stack[-limit:]
    return out


# --------------------------------------------------------- heartbeat algebra


_ALLGATHER_WARNED = [False]


def host_allgather(row):
    """Allgather one heartbeat row across hosts on the CPU backend.

    Returns [n_hosts][len(row)] of host floats (row h = host h's
    contribution, identical on every host). Single-process worlds shortcut
    to [row]; a failed allgather degrades to the local row with a one-shot
    warning — the cluster view collapses to local-only rather than killing
    the step loop."""
    row = [float(v) for v in row]
    try:
        n = jax.process_count()
    except Exception:
        n = 1
    if n <= 1:
        return [row]
    try:
        import numpy as np
        from jax.experimental import multihost_utils
        mat = np.array(multihost_utils.process_allgather(
            np.array(row, dtype=np.float64)))
        return [[float(v) for v in r] for r in mat]
    except Exception as e:
        if not _ALLGATHER_WARNED[0]:
            _ALLGATHER_WARNED[0] = True
            logger.warning(
                f"cluster: heartbeat allgather failed ({e!r}); falling back "
                "to local-only view")
        return [row]


def _median_low(vals):
    """Lower-middle median: an actually-observed value, and — unlike the
    upper-middle median the pipeline observatory uses per stage — it lets a
    2-host world name a straggler (upper-middle would pick the straggler
    itself as the baseline, so the ratio could never exceed 1)."""
    ordered = sorted(vals)
    return ordered[(len(ordered) - 1) // 2]


def find_straggler_host(per_host_ms, threshold=3.0):
    """Median-ratio divergence rule over per-host walls (callers feed the
    host-local dispatch column): the slowest host is the straggler when its
    time exceeds ``threshold`` x the (lower-middle) median. Returns
    {"host", "ratio"} or None."""
    vals = [float(v) for v in per_host_ms]
    if len(vals) < 2:
        return None
    med = _median_low(vals)
    if med <= 0.0:
        return None
    worst = max(range(len(vals)), key=lambda i: (vals[i], i))
    ratio = vals[worst] / med
    if ratio > float(threshold):
        return {"host": worst, "ratio": ratio}
    return None


def derive_cluster_stats(matrix, threshold=3.0):
    """Global per-step view from one allgathered heartbeat matrix. Skew
    scalars come from the end-to-end step wall; straggler attribution comes
    from the host-local dispatch wall (see HEARTBEAT_FIELDS)."""
    step_ms = [float(r[COL_STEP_MS]) for r in matrix]
    dispatch_ms = [float(r[COL_DISPATCH_MS]) for r in matrix]
    med = _median_low(step_ms)
    return {
        "step": int(matrix[0][COL_STEP]),
        "hosts": len(matrix),
        "step_ms_max": max(step_ms),
        "step_ms_min": min(step_ms),
        "step_ms_median": med,
        "step_skew": (max(step_ms) / med) if med > 0 else 1.0,
        "dispatch_ms_max": max(dispatch_ms),
        "wire_bytes_ici_total": sum(float(r[COL_WIRE_ICI]) for r in matrix),
        "wire_bytes_dcn_total": sum(float(r[COL_WIRE_DCN]) for r in matrix),
        "hbm_peak_bytes_max": max(float(r[COL_HBM]) for r in matrix),
        "straggler": find_straggler_host(dispatch_ms, threshold),
    }


def estimate_clock_offsets(heartbeats):
    """Per-host wall-clock offset (seconds, relative to host 0) from the
    heartbeat history: every host snapshots time.time() at the same
    heartbeat, so the median over heartbeats of (wall_h - wall_0) estimates
    host h's clock skew, robust to the odd delayed snapshot. Returns a list
    indexed by host; offsets[0] == 0.0."""
    deltas = {}
    for mat in heartbeats:
        if not mat:
            continue
        w0 = float(mat[0][COL_WALL])
        for h, row in enumerate(mat):
            deltas.setdefault(h, []).append(float(row[COL_WALL]) - w0)
    return [_median_low(deltas[h]) if deltas.get(h) else 0.0
            for h in range(len(deltas))]


# ------------------------------------------------------------- hang watchdog


class HangWatchdog:
    """Per-host hang detector. ``arm(step)`` before dispatching a step,
    ``disarm()`` when it completes; a daemon thread fires when an armed
    deadline expires — capturing all-thread stacks plus the last-entered
    named scope, dumping through the host's FlightRecorder, and dropping an
    epoch marker in the shared dump_dir so peers dump the same epoch. Peer
    markers are polled by the same thread; a peer-signalled fire dumps but
    writes no marker of its own (no marker ping-pong). Fires at most once
    per epoch (= armed step) per host."""

    def __init__(self, recorder=None, deadline_s=60.0, dump_dir=None,
                 host_id=0, run_id=None, signal_peers=True, tracker=None,
                 poll_s=None):
        self.recorder = recorder
        self.deadline_s = float(deadline_s)
        self.dump_dir = dump_dir or (recorder.dump_dir
                                     if recorder is not None else None)
        self.host_id = int(host_id)
        if run_id is None:
            run_id = recorder.run_id if recorder is not None \
                else default_run_id()
        self.run_id = _sanitize_token(run_id) or "norun"
        self.signal_peers = bool(signal_peers)
        self.tracker = tracker if tracker is not None else _DEFAULT_TRACKER
        self.poll_s = float(poll_s) if poll_s else \
            min(max(self.deadline_s / 5.0, 0.02), 0.5)
        self.fired = []  # fire payloads, for summaries and the hang-sim
        self._lock = threading.Lock()
        self._armed_at = None
        self._step = None
        self._fired_epochs = set()
        self._seen_markers = set()
        self._stop = threading.Event()
        self._thread = None

    # -- arming ------------------------------------------------------------
    def arm(self, step):
        with self._lock:
            self._armed_at = time.monotonic()
            self._step = int(step)
        self._ensure_thread()

    def disarm(self):
        with self._lock:
            self._armed_at = None

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=2.0)

    def _ensure_thread(self):
        if self._thread is not None and self._thread.is_alive():
            return
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"ds-hang-watchdog-h{self.host_id}")
        self._thread.start()

    # -- the watchdog thread -----------------------------------------------
    def _loop(self):
        while not self._stop.wait(self.poll_s):
            with self._lock:
                armed_at, step = self._armed_at, self._step
            if armed_at is not None:
                waited = time.monotonic() - armed_at
                if waited > self.deadline_s:
                    self._fire("deadline", epoch=step, step=step,
                               waited_s=waited)
            if self.signal_peers and self.dump_dir:
                self._scan_peer_markers()

    def _scan_peer_markers(self):
        try:
            names = os.listdir(self.dump_dir)
        except OSError:
            return
        for name in sorted(names):
            m = MARKER_RE.match(name)
            if not m or name in self._seen_markers:
                continue
            if m.group("run") != self.run_id:
                continue
            host = int(m.group("host"))
            if host == self.host_id:
                continue
            self._seen_markers.add(name)
            try:
                with open(os.path.join(self.dump_dir, name)) as f:
                    marker = json.load(f)
            except (OSError, ValueError):
                marker = {}
            epoch = int(m.group("epoch"))
            self._fire("peer_signal", epoch=epoch,
                       step=marker.get("step", epoch), peer=host,
                       peer_scope=marker.get("last_scope"))

    def _fire(self, origin, epoch, step, waited_s=None, peer=None,
              peer_scope=None):
        key = int(epoch) if epoch is not None else -1
        with self._lock:
            if key in self._fired_epochs:
                return
            self._fired_epochs.add(key)
        scope = self.tracker.last_scope() if self.tracker is not None else None
        payload = {
            "origin": origin,
            "epoch": key,
            "step": step,
            "host": self.host_id,
            "deadline_s": self.deadline_s,
            "waited_s": waited_s,
            "last_scope": scope["name"] if scope else None,
            "scope_age_s": scope["age_s"] if scope else None,
            "peer": peer,
            "peer_scope": peer_scope,
            "threads": all_thread_stacks(),
        }
        self.fired.append(payload)
        logger.error(
            f"cluster: HANG detected on host {self.host_id} at step {step} "
            f"({origin}), last scope: {payload['last_scope']}")
        if self.recorder is not None:
            self.recorder.record_event("hang", payload, step)
            self.recorder.note_anomaly()
            self.recorder.trigger("hang", {
                "origin": origin, "epoch": key, "step": step,
                "host": self.host_id, "last_scope": payload["last_scope"]})
        if origin != "peer_signal":
            self._write_marker(key, step, payload["last_scope"])

    def _write_marker(self, epoch, step, last_scope):
        if not (self.signal_peers and self.dump_dir):
            return
        name = f"cluster_hang_{self.run_id}_e{epoch}_host{self.host_id}.json"
        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            tmp = os.path.join(self.dump_dir, name + ".tmp")
            with open(tmp, "w") as f:
                json.dump({"epoch": epoch, "step": step,
                           "host": self.host_id, "last_scope": last_scope,
                           "time": time.time()}, f)
            os.replace(tmp, os.path.join(self.dump_dir, name))
        except OSError as e:  # best-effort: peers just won't be signalled
            logger.warning(f"cluster: peer hang marker failed: {e}")


# ------------------------------------------------------------ cluster monitor


class ClusterMonitor:
    """Per-host coordinator of the cluster plane: heartbeats every
    ``heartbeat_interval`` steps, ``Cluster/*`` scalars from host 0, the
    hang watchdog armed around each step, and the bundle that rides along in
    flight-recorder dumps. All host-side."""

    def __init__(self, telemetry=None, monitor=None, recorder=None,
                 heartbeat_interval=1, hang_deadline_s=0.0,
                 straggler_threshold=3.0, signal_peers=True, dump_dir=None,
                 run_id=None, host_id=None, n_hosts=None, tracker=None,
                 heartbeat_capacity=512, allgather=None, warmup_steps=1):
        self.telemetry = telemetry
        self.monitor = monitor if monitor is not None else \
            (telemetry.monitor if telemetry is not None else None)
        self.recorder = recorder
        self.heartbeat_interval = max(int(heartbeat_interval), 1)
        self.straggler_threshold = float(straggler_threshold)
        # the first step(s) pay multi-second compiles: arming a deadline or
        # naming a straggler there would only ever flag compile-time jitter
        self.warmup_steps = max(int(warmup_steps), 0)
        self.host_id = int(host_id) if host_id is not None \
            else _process_index()
        self.n_hosts = int(n_hosts) if n_hosts is not None \
            else _process_count()
        self.tracker = tracker if tracker is not None else _DEFAULT_TRACKER
        self._allgather = allgather if allgather is not None else host_allgather
        self.heartbeats = deque(maxlen=max(int(heartbeat_capacity), 8))
        self.stragglers = deque(maxlen=64)
        self.last_stats = None
        # dispatch-skew integral for the goodput ledger: seconds THIS host's
        # dispatch wall sat above the fleet lower-middle median, sampled at
        # heartbeat steps (utils/goodput.py bills them as straggler_skew)
        self.last_local_skew_s = 0.0
        self.skew_integral_s = 0.0
        # when the engine's run ledger is attached, every flight-recorder
        # dump's cluster bundle carries this host's goodput summary, so the
        # cluster plane can merge a fleet goodput view post-mortem
        self.goodput = None
        self.watchdog = None
        if hang_deadline_s and float(hang_deadline_s) > 0:
            self.watchdog = HangWatchdog(
                recorder=recorder, deadline_s=float(hang_deadline_s),
                dump_dir=dump_dir or (recorder.dump_dir
                                      if recorder is not None else None),
                host_id=self.host_id, run_id=run_id,
                signal_peers=signal_peers, tracker=self.tracker)

    # -- step hooks (called by the engine around each optimizer step) -------
    def on_step_begin(self, step):
        if self.watchdog is not None and int(step) >= self.warmup_steps:
            self.watchdog.arm(step)

    def on_step_end(self, step):
        if self.watchdog is not None:
            self.watchdog.disarm()
        if int(step) % self.heartbeat_interval != 0:
            return None
        stats = self.heartbeat(step)
        if self.telemetry is not None:
            # the allgather above is a cross-host rendezvous: restart the
            # dispatch window after it, so waiting for a slow peer's heartbeat
            # is not charged to this host's next step (telemetry docstring)
            self.telemetry.rebase_dispatch_window()
        return stats

    # -- heartbeats ---------------------------------------------------------
    def local_row(self, step):
        t = self.telemetry
        step_ms = float(t.last_step_ms or 0.0) if t is not None else 0.0
        # host-local dispatch wall; falls back to the step wall when the
        # engine never marked a dispatch boundary (older call sites)
        dispatch_ms = step_ms
        if t is not None and getattr(t, "last_dispatch_ms", None) is not None:
            dispatch_ms = float(t.last_dispatch_ms)
        wire_ici = float(t.last_wire_bytes_ici) if t is not None else 0.0
        wire_dcn = float(t.last_wire_bytes_dcn) if t is not None else 0.0
        from .hbm import device_memory_stats
        stats = device_memory_stats()
        hbm = float((stats or {}).get("peak_bytes_in_use", 0))
        return [float(step), time.time(), step_ms, dispatch_ms,
                wire_ici, wire_dcn, hbm]

    def heartbeat(self, step):
        return self.ingest(self._allgather(self.local_row(step)), step)

    def ingest(self, matrix, step):
        """Fold one allgathered heartbeat matrix into the history and derive
        the global view. Every host computes the same stats from the same
        matrix; only host 0 emits scalars (the "rank 0 derives" contract)."""
        matrix = [[float(v) for v in row] for row in matrix]
        self.heartbeats.append(matrix)
        stats = derive_cluster_stats(matrix, self.straggler_threshold)
        if int(step) < self.warmup_steps:
            # compile steps: dispatch walls are dominated by per-host compile
            # jitter — naming a straggler from them would be noise
            stats["straggler"] = None
        self.last_stats = stats
        # goodput's straggler_skew source: this host's dispatch wall above the
        # fleet lower-middle median (same column and median rule the straggler
        # namer uses). Warmup steps are excluded for the same reason.
        self.last_local_skew_s = 0.0
        if int(step) >= self.warmup_steps and 0 <= self.host_id < len(matrix):
            dispatch = [row[3] for row in matrix]
            skew_ms = dispatch[self.host_id] - _median_low(dispatch)
            if skew_ms > 0:
                self.last_local_skew_s = skew_ms / 1000.0
                self.skew_integral_s += self.last_local_skew_s
        strag = stats["straggler"]
        if strag is not None:
            event = {"step": int(step), "host": int(strag["host"]),
                     "ratio": float(strag["ratio"])}
            self.stragglers.append(event)
            if self.recorder is not None:
                self.recorder.record_event("cluster_straggler", event,
                                           int(step))
        if self.monitor is not None and self.host_id == 0:
            self._emit(stats, int(step))
        return stats

    def _emit(self, stats, step):
        mon = self.monitor
        mon.add_scalar("Cluster/hosts", stats["hosts"], step)
        mon.add_scalar("Cluster/step_ms_max", stats["step_ms_max"], step)
        mon.add_scalar("Cluster/step_ms_median", stats["step_ms_median"], step)
        mon.add_scalar("Cluster/step_skew", stats["step_skew"], step)
        mon.add_scalar("Cluster/wire_bytes_ici_total",
                       stats["wire_bytes_ici_total"], step)
        mon.add_scalar("Cluster/wire_bytes_dcn_total",
                       stats["wire_bytes_dcn_total"], step)
        mon.add_scalar("Cluster/hbm_peak_bytes_max",
                       stats["hbm_peak_bytes_max"], step)
        strag = stats["straggler"]
        mon.add_scalar("Cluster/straggler_host",
                       strag["host"] if strag else -1, step)
        if strag is not None:
            mon.event("cluster_straggler", dict(strag, step=step), step)

    # -- reporting ----------------------------------------------------------
    def clock_offsets(self):
        return estimate_clock_offsets(list(self.heartbeats))

    def bundle(self):
        out = {
            "version": CLUSTER_BUNDLE_VERSION,
            "kind": CLUSTER_KIND,
            "host": self.host_id,
            "n_hosts": self.n_hosts,
            "fields": list(HEARTBEAT_FIELDS),
            "heartbeat_interval": self.heartbeat_interval,
            "heartbeats": [[list(row) for row in m] for m in self.heartbeats],
            "stragglers": list(self.stragglers),
            "clock_offsets_s": self.clock_offsets(),
            "skew_integral_s": self.skew_integral_s,
        }
        if self.goodput is not None:
            out["goodput"] = self.goodput.summary()
        return out

    def summary(self):
        last = self.last_stats or {}
        return {
            "hosts": self.n_hosts,
            "heartbeats": len(self.heartbeats),
            "step_skew": last.get("step_skew"),
            "straggler_host": (self.stragglers[-1]["host"]
                               if self.stragglers else None),
            "straggler_events": len(self.stragglers),
            "watchdog_fired": len(self.watchdog.fired)
            if self.watchdog is not None else 0,
            "dumps": self.recorder.dump_count
            if self.recorder is not None else 0,
        }

    def stop(self):
        if self.watchdog is not None:
            self.watchdog.stop()


def _process_index():
    try:
        return jax.process_index()
    except Exception:
        return 0


def _process_count():
    try:
        return jax.process_count()
    except Exception:
        return 1


# ------------------------------------------------------- fleet serving rollup


def fleet_latency_sketches(bundles):
    """Merge the ``latency_sketches`` of N replica request-trace bundles into
    one HistogramSketch per metric. Identical fixed-bin geometry on every
    replica makes the merge exact: fleet percentiles equal the percentiles
    of the concatenated request stream."""
    from ..serve.request_trace import HistogramSketch
    merged = {}
    for b in bundles:
        for metric, d in ((b or {}).get("latency_sketches") or {}).items():
            sk = HistogramSketch.from_dict(d)
            if metric in merged:
                merged[metric].merge_from(sk)
            else:
                merged[metric] = sk
    return merged


def fleet_latency_summary(bundles, ps=(50, 95, 99)):
    """Fleet-level latency percentiles from N replica bundles, in the same
    flat shape RequestTracer.latency_summary emits for one replica — the
    metrics substrate a fleet router's SLO gate reads."""
    out = {}
    merged = fleet_latency_sketches(bundles)
    for metric in sorted(merged):
        sk = merged[metric]
        if not sk.count:
            continue
        for p in ps:
            out[f"{metric}_p{p:g}"] = sk.percentile(p)
    return out


def fleet_serving_totals(bundles):
    """Sum the scheduled-work ``totals`` and lifecycle ``counts`` of N replica
    request-trace bundles into one fleet rollup. Integer-exact (token and
    request counters, no floats), so the speculation economics
    (drafted/accepted/wasted_draft_tokens) survive the fleet fold instead of
    being silently dropped next to the latency-sketch merge."""
    totals = {}
    counts = {}
    for b in bundles:
        for k, v in ((b or {}).get("totals") or {}).items():
            totals[k] = totals.get(k, 0) + int(v)
        for k, v in ((b or {}).get("counts") or {}).items():
            counts[k] = counts.get(k, 0) + int(v)
    return {"totals": totals, "counts": counts}


# ----------------------------------------------------------- merged timeline


def merged_cluster_trace(pipe_bundles, offsets_s=None):
    """Merge per-host pipeline_trace bundles into one Perfetto trace: host h's
    events land in process (track group) h, timestamps shifted by -offset_s[h]
    so every host renders on host 0's clock."""
    from .pipeline_trace import to_trace_events
    offsets_s = offsets_s or {}
    events = []
    offsets_us = {}
    for h in sorted(pipe_bundles):
        sub = to_trace_events(pipe_bundles[h])
        shift_us = int(round(-float(offsets_s.get(h, 0.0)) * 1e6))
        offsets_us[str(h)] = -shift_us
        for ev in sub["traceEvents"]:
            ev = dict(ev)
            ev["pid"] = h
            if "ts" in ev:
                ev["ts"] = int(ev["ts"]) + shift_us
            events.append(ev)
    return trace_envelope(events, "ds-tpu timeline --cluster",
                          hosts=sorted(pipe_bundles),
                          clock_offsets_us=offsets_us)


def cluster_timeline(dump_dir, output, run=None):
    """Back end of ``ds-tpu timeline --cluster <dump_dir>``: load one run's
    per-host flight-recorder dumps, estimate clock offsets from the embedded
    heartbeat history, and write the merged trace."""
    from .numerics import load_run_bundles
    run_key, by_host = load_run_bundles(dump_dir, run=run)
    if not by_host:
        print(f"ds-tpu timeline --cluster: no flight-recorder dumps in "
              f"{dump_dir}" + (f" for run '{run}'" if run else ""),
              file=sys.stderr)
        return 2
    pipe = {}
    heartbeats = []
    for h in sorted(by_host):
        pt = by_host[h].get("pipeline_trace")
        if pt:
            pipe[h] = pt
        hb = (by_host[h].get("cluster") or {}).get("heartbeats") or []
        if len(hb) > len(heartbeats):
            heartbeats = hb
    if not pipe:
        print(f"ds-tpu timeline --cluster: no pipeline_trace bundles in the "
              f"dumps of run '{run_key}' (enable telemetry.pipeline_trace)",
              file=sys.stderr)
        return 2
    offs = estimate_clock_offsets(heartbeats)
    offsets = {h: (offs[h] if h < len(offs) else 0.0) for h in pipe}
    trace = merged_cluster_trace(pipe, offsets)
    with open(output, "w") as f:
        f.write(serialize_trace(trace))
    print(f"wrote {len(trace['traceEvents'])} trace events "
          f"({len(pipe)} host track group(s), run '{run_key}', clock offsets "
          f"{[round(offsets[h] * 1e3, 3) for h in sorted(offsets)]} ms) "
          f"-> {output}")
    return 0


# -------------------------------------------------------------- cluster-dump


def assemble_cluster_report(by_host, run_key=""):
    """Merge one run's per-host dump bundles into a single post-mortem:
    which host stalled first (deadline-origin hang events ordered by epoch,
    then clock-offset-corrected wall time, then host id), the scope it died
    in, the merged first-bad-step, and the straggler history."""
    from .numerics import merge_first_bad
    hosts = sorted(by_host)
    heartbeats = []
    stragglers = []
    for h in hosts:
        cb = by_host[h].get("cluster") or {}
        if len(cb.get("heartbeats") or []) > len(heartbeats):
            heartbeats = cb["heartbeats"]
        if not stragglers and cb.get("stragglers"):
            stragglers = cb["stragglers"]
    offs = estimate_clock_offsets(heartbeats)
    hangs = []
    for h in hosts:
        for ev in by_host[h].get("events", []):
            if ev.get("event") != "hang":
                continue
            p = ev.get("payload") or {}
            hangs.append({
                "host": h,
                "origin": p.get("origin"),
                "epoch": p.get("epoch"),
                "step": p.get("step"),
                "scope": p.get("last_scope"),
                "_t": float(ev.get("time") or 0.0)
                - (offs[h] if h < len(offs) else 0.0),
            })
    primaries = [g for g in hangs if g["origin"] == "deadline"] or hangs
    first = min(primaries, key=lambda g: (
        g["epoch"] if g["epoch"] is not None else 1 << 60, g["_t"],
        g["host"])) if primaries else None
    for g in hangs:
        g.pop("_t", None)
    fb_step, fb_host = merge_first_bad(by_host)
    # rank-0 fleet goodput: when the per-host cluster bundles (or the dumps
    # themselves) carry run-ledger summaries, fold them into one fleet view
    # with the per-host breakdown (utils/goodput.fleet_goodput)
    goodput_by_host = {}
    for h in hosts:
        led = (by_host[h].get("goodput")
               or (by_host[h].get("cluster") or {}).get("goodput"))
        if isinstance(led, dict) and led.get("kind") == "goodput":
            goodput_by_host[h] = led
    fleet_gp = None
    if goodput_by_host:
        from .goodput import fleet_goodput
        fleet_gp = fleet_goodput(goodput_by_host)
    # fleet alert plane (utils/alerts.py): when any host's dump carries an
    # alerts block, merge them — the report names the first-firing host +
    # rule, i.e. where the incident started
    alerts_fleet = None
    if any(isinstance(by_host[h].get("alerts"), dict) for h in hosts):
        from .alerts import merge_fleet_alerts
        alerts_fleet = merge_fleet_alerts(by_host)
    return {
        "version": 1,
        "kind": "cluster_report",
        "run": run_key,
        "hosts": hosts,
        "n_dumps": len(by_host),
        "hangs": hangs,
        "first_stall": ({"host": first["host"], "step": first["step"],
                         "scope": first["scope"], "origin": first["origin"]}
                        if first else None),
        "first_bad_step": fb_step,
        "first_bad_host": fb_host,
        "stragglers": stragglers,
        "goodput": fleet_gp,
        "alerts_fleet": alerts_fleet,
    }


def cluster_dump_main(argv=None):
    """Entry point for ``ds-tpu cluster-dump <dump_dir>``."""
    parser = argparse.ArgumentParser(
        prog="ds-tpu cluster-dump",
        description="Assemble one run's per-host flight-recorder dumps into "
                    "a single cluster post-mortem naming the first host to "
                    "stall and the scope it died in.")
    parser.add_argument("dump_dir", help="shared dump directory")
    parser.add_argument("--run", default=None,
                        help="assemble this run instead of the newest one")
    parser.add_argument("--json", action="store_true",
                        help="print the machine-readable report instead")
    args = parser.parse_args(argv)

    from .numerics import load_run_bundles
    run_key, by_host = load_run_bundles(args.dump_dir, run=args.run)
    if not by_host:
        print(f"no flight-recorder dumps in {args.dump_dir}"
              + (f" for run '{args.run}'" if args.run else ""),
              file=sys.stderr)
        return 2
    report = assemble_cluster_report(by_host, run_key=run_key or "")

    if args.json:
        print(json.dumps(report, indent=2, default=float))
        return 0

    print(f"cluster post-mortem: {args.dump_dir} "
          f"(run '{report['run']}', {len(report['hosts'])} host(s), "
          f"{report['n_dumps']} dump(s))")
    fs = report["first_stall"]
    if fs:
        print(f"  first stall    : host {fs['host']} at step {fs['step']} "
              f"in scope '{fs['scope']}' ({fs['origin']})")
    else:
        print("  first stall    : none recorded")
    for g in report["hangs"]:
        print(f"  host {g['host']:<4}: hang ({g['origin']}) at step "
              f"{g['step']}, last scope '{g['scope']}'")
    print(f"  first bad step : {report['first_bad_step']}"
          + (f" (host {report['first_bad_host']})"
             if report["first_bad_host"] is not None else ""))
    if report["stragglers"]:
        last = report["stragglers"][-1]
        print(f"  stragglers     : {len(report['stragglers'])} event(s), "
              f"last: host {last['host']} at step {last['step']} "
              f"({last['ratio']:.2f}x median)")
    return 0


# ------------------------------------------------------------------ hang-sim


def hang_sim_main(argv=None):
    """``ds-tpu hang-sim``: deterministic two-host hang rehearsal, fully
    in-process. Host 1 stalls inside ``ds_grad_bucket1`` with a short
    deadline; host 0 idles in ``ds_fwd_bwd`` with a deadline that cannot
    expire, so only the peer marker can make it dump — exercising detection,
    the cross-host signal, both dumps, and the cluster-dump report. The
    transcript contains no wall-clock values, so its bytes are pinned as a
    golden in scripts/lint.sh."""
    parser = argparse.ArgumentParser(
        prog="ds-tpu hang-sim",
        description="Deterministic two-host hang/watchdog rehearsal.")
    parser.add_argument("--json", default=None, metavar="PATH",
                        help="write the transcript JSON here")
    parser.add_argument("--dump-dir", default="/tmp/ds_tpu_hang_sim_dumps",
                        help="scratch dump directory (cleaned first)")
    parser.add_argument("--deadline", type=float, default=0.25,
                        help="host 1's hang deadline in seconds")
    args = parser.parse_args(argv)

    from .numerics import FlightRecorder, load_run_bundles
    from .pipeline_trace import simulated_bundle

    run = "hangsim"
    dump_dir = args.dump_dir
    os.makedirs(dump_dir, exist_ok=True)
    for name in os.listdir(dump_dir):  # stale state would corrupt the replay
        if name.startswith(("numerics_dump_", "cluster_hang_")):
            try:
                os.unlink(os.path.join(dump_dir, name))
            except OSError:
                pass

    class _StaticBundle:
        def __init__(self, b):
            self._b = b

        def bundle(self):
            return self._b

    stall_step = 3
    hosts = (0, 1)
    trackers, monitors, recorders, watchdogs = {}, {}, {}, {}
    for h in hosts:
        trackers[h] = ScopeTracker()
        pipe = simulated_bundle(4, 2, step=stall_step)
        pipe["host"] = h
        monitors[h] = ClusterMonitor(
            heartbeat_interval=1, straggler_threshold=3.0,
            host_id=h, n_hosts=2, tracker=trackers[h],
            allgather=lambda row: [row])
        recorders[h] = FlightRecorder(
            capacity=16, dump_dir=dump_dir, host_id=h, run_id=run,
            pipeline_trace=_StaticBundle(pipe), cluster=monitors[h])
        monitors[h].recorder = recorders[h]

    # synthetic heartbeat history: host 1's wall clock runs 1.5 ms behind
    # host 0's, so the merged timeline must shift its track group forward
    for s in range(stall_step + 1):
        wall0 = 1000.0 + float(s)
        matrix = [[float(s), wall0, 12.0, 9.0, 1024.0, 2048.0, 0.0],
                  [float(s), wall0 - 0.0015, 13.5, 10.0, 1024.0, 2048.0, 0.0]]
        for h in hosts:
            monitors[h].ingest(matrix, s)

    # per-host goodput ledgers on a FAKE clock (utils/goodput.py): 1s of
    # init then four 1s steps, host 1's stall step billed to ``hang``. The
    # ledgers ride the cluster bundles into both dumps, so the merged report
    # must carry the rank-0 fleet goodput view — with deterministic seconds,
    # keeping the transcript byte-pinnable.
    from .goodput import RunLedger
    ledgers = {}
    for h in hosts:
        cell = [0.0]

        def _clock(cell=cell):
            return cell[0]

        led = RunLedger(run_id=run, host=h, clock=_clock,
                        wall=lambda: 1000.0)
        cell[0] = 1.0
        led.close("init")
        for s in range(stall_step + 1):
            cell[0] += 1.0
            led.close_step(s, hang=(h == 1 and s == stall_step))
        led.finalize(persist=False)
        ledgers[h] = led
        monitors[h].goodput = led

    # host 1: short deadline, stalled inside a grad-bucket collective.
    # host 0: un-expirable deadline — only the peer signal can fire it.
    trackers[0].enter("ds_fwd_bwd")
    trackers[1].enter("ds_grad_bucket1")
    watchdogs[1] = HangWatchdog(
        recorder=recorders[1], deadline_s=args.deadline, dump_dir=dump_dir,
        host_id=1, run_id=run, tracker=trackers[1], poll_s=0.05)
    watchdogs[0] = HangWatchdog(
        recorder=recorders[0], deadline_s=3600.0, dump_dir=dump_dir,
        host_id=0, run_id=run, tracker=trackers[0], poll_s=0.05)
    t_armed = time.monotonic()
    for h in hosts:
        watchdogs[h].arm(stall_step)

    deadline_wall = t_armed + max(args.deadline * 40.0, 15.0)
    while time.monotonic() < deadline_wall:
        if all(recorders[h].dump_count >= 1 for h in hosts):
            break
        time.sleep(0.02)
    for h in hosts:
        watchdogs[h].stop()

    run_key, by_host = load_run_bundles(dump_dir, run=run)
    report = assemble_cluster_report(by_host, run_key=run_key or "")

    fired = sorted((p for h in hosts for p in watchdogs[h].fired),
                   key=lambda p: p["host"])
    dumps = [{"host": p["host"], "origin": p["origin"], "epoch": p["epoch"],
              "step": p["step"], "last_scope": p["last_scope"]}
             for p in fired]
    detected = any(
        p["origin"] == "deadline" and p["host"] == 1
        and p["waited_s"] is not None
        and p["waited_s"] <= args.deadline + 2.0
        for p in watchdogs[1].fired)
    # the fleet goodput view must survive the dump -> merge round trip with
    # the stalled host's hang second attributed (7 productive host-seconds
    # of 10 total -> 0.7)
    gp = report.get("goodput")
    goodput_attributed = bool(
        gp is not None and gp.get("kind") == "goodput_fleet"
        and gp.get("n_hosts") == 2 and gp.get("hang_steps") == 1
        and abs(gp["class_seconds"]["hang"] - 1.0) < 1e-9
        and abs(gp["goodput_fraction"] - 0.7) < 1e-9)
    ok = (detected
          and len(dumps) == 2
          and all(recorders[h].dump_count >= 1 for h in hosts)
          and goodput_attributed
          and report["first_stall"] == {"host": 1, "step": stall_step,
                                        "scope": "ds_grad_bucket1",
                                        "origin": "deadline"})
    transcript = {
        "version": 1,
        "kind": "hang_sim",
        "scenario": "two-host stalled-collective rehearsal",
        "deadline_s": args.deadline,
        "stalled_host": 1,
        "stall_step": stall_step,
        "detected_within_deadline": bool(detected),
        "goodput_attributed": goodput_attributed,
        "dumps": dumps,
        "report": report,
        "ok": bool(ok),
    }

    print(f"hang-sim: stall injected on host 1 at step {stall_step} "
          f"(deadline {args.deadline}s)")
    for d in dumps:
        print(f"  host {d['host']}: dumped ({d['origin']}), last scope "
              f"'{d['last_scope']}'")
    fs = report["first_stall"]
    if fs:
        print(f"  cluster-dump: first stall host {fs['host']} in scope "
              f"'{fs['scope']}'")
    if gp is not None:
        print(f"  fleet goodput: {gp['goodput_fraction']:.2f} over "
              f"{gp['n_hosts']} hosts ({gp['hang_steps']} hung step(s))")
    print(f"hang-sim: {'OK' if ok else 'FAILED'}")

    if args.json:
        with open(args.json, "w") as f:
            json.dump(transcript, f, indent=2, sort_keys=True)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(cluster_dump_main())
