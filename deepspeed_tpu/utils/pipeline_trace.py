"""Pipeline schedule observatory: per-instruction span timeline, bubble/goodput
accounting, an analytic schedule simulator, and a Perfetto trace exporter.

The PipelineEngine's instruction executor runs merged per-stage streams on a
single controller, so the only honest measurement surface is the host-side
interval around each executed ``PipeInstruction`` — boundaries the executor
already crosses. ``PipelineTracer`` records exactly those spans (stage id,
schedule step index, micro-batch id, buffer id, wall interval in µs) and keeps
them in a bounded per-step ring. No device fetch, no barrier, no added HLO:
with ``telemetry.pipeline_trace`` disabled the engine holds ``None`` instead of
a tracer and the executor path is byte-identical (see
tests/unit/test_pipeline_trace.py::test_pipeline_hlo_identical_when_disabled
and the AST no-sync guard pinning this module to zero blocking primitives).

Three consumers sit on the span stream:

* ``goodput_decomposition`` — per optimizer step, seconds spent in
  fwd / bwd / p2p / load / reduce / opt, plus the bubble the schedule would
  have on a real per-stage deployment, reconstructed by replaying the spans
  on a lockstep timeline (step wall = slowest stage at that schedule step).
* ``simulate_schedule`` / ``lint_schedule`` — offline symbolic replay of
  ``TrainSchedule``/``InferenceSchedule`` streams: expected bubble fraction
  (``(p-1)/(m+p-1)`` at uniform cost), per-stage idle slots, peak buffer
  occupancy, and a static validator for send/recv rendezvous and buffer
  lifetime invariants (tests/unit/test_schedule_lint.py).
* ``to_trace_events`` / ``timeline_main`` — Perfetto/Chrome ``trace_event``
  JSON: one track per stage, microbatch-colored slices, counter tracks for
  buffer occupancy and bubble fraction. ``bin/ds-tpu timeline`` dispatches
  here, accepting either a live span bundle or a flight-recorder dump that
  embeds one (docs/pipeline-trace.md).
"""

import argparse
import atexit
import json
import os
import time
from collections import deque

from .logging import logger
from .trace_event import (complete_slice, counter_event, load_bundle,
                          process_name_event, serialize_trace,  # noqa: F401
                          thread_meta_events, trace_envelope)

PIPELINE_TRACE_VERSION = 1
PIPELINE_TRACE_KIND = "pipeline_trace"

# instruction name -> goodput category
CATEGORY = {
    "LoadMicroBatch": "load",
    "ForwardPass": "fwd",
    "BackwardPass": "bwd",
    "SendActivation": "p2p",
    "RecvActivation": "p2p",
    "SendGrad": "p2p",
    "RecvGrad": "p2p",
    "ReduceGrads": "reduce",
    "ReduceTiedGrads": "reduce",
    "OptimizerStep": "opt",
}
_COMPUTE = ("ForwardPass", "BackwardPass")
# mirror of engine._SEND_CMDS: within one merged step all Sends/Loads run
# before any Recv (the rendezvous invariant the symbolic replay re-checks)
_SEND_NAMES = ("SendActivation", "SendGrad", "LoadMicroBatch")

# span tuple layout: [stage, sched_step, name, micro_batch, buffer_id, rel_us, dur_us]
SPAN_STAGE, SPAN_STEP, SPAN_NAME, SPAN_MB, SPAN_BUF, SPAN_T0, SPAN_DUR = range(7)


class ScheduleLintError(Exception):
    """A TrainSchedule/InferenceSchedule instruction stream violated a
    rendezvous or buffer-lifetime invariant."""


# --------------------------------------------------------------- span recorder


class PipelineTracer:
    """Host-side span recorder for the instruction-stream pipeline executor.

    One ``begin_step``/``record``*/``end_step`` cycle per ``train_batch`` (or
    ``eval_batch``). Only stdlib calls on the hot path: two ``perf_counter``
    reads and a list append per executed instruction.
    """

    def __init__(self, stages, capacity=64, dump_dir=None, host_id=0):
        self.stages = int(stages)
        self.capacity = int(capacity)
        self.dump_dir = dump_dir or None
        self.host_id = int(host_id)
        self.steps = deque(maxlen=self.capacity)
        # the per-step SCHEDULE decomposition (bubble accounting) — distinct
        # from the run-level Run/Goodput ledger (utils/goodput.py), which is
        # why this is the schedule_-prefixed name, never bare "goodput"
        self.last_schedule_goodput = None
        self._epoch = time.perf_counter()
        self._cur = None
        self._straggler_warned = 0
        if self.dump_dir:
            atexit.register(self._atexit_dump)

    # -- recording ---------------------------------------------------------
    def begin_step(self, step, schedule_name, micro_batches, kind="train"):
        now = time.perf_counter()
        self._cur = {
            "step": int(step),
            "kind": kind,
            "schedule": schedule_name,
            "micro_batches": int(micro_batches),
            "t0_us": int((now - self._epoch) * 1e6),
            "_t0": now,
            "spans": [],
        }

    def record(self, stage, sched_step, name, micro_batch, buffer_id, t0, t1):
        cur = self._cur
        if cur is None:
            return
        cur["spans"].append([
            int(stage), int(sched_step), name,
            None if micro_batch is None else int(micro_batch),
            None if buffer_id is None else int(buffer_id),
            int((t0 - cur["_t0"]) * 1e6),
            max(int((t1 - t0) * 1e6), 0),
        ])

    def end_step(self):
        cur, self._cur = self._cur, None
        if cur is None:
            return None
        t0 = cur.pop("_t0")
        cur["wall_seconds"] = time.perf_counter() - t0
        goodput = goodput_decomposition(cur["spans"], self.stages)
        cur["schedule_goodput"] = goodput
        self.steps.append(cur)
        self.last_schedule_goodput = goodput
        straggler = goodput.get("straggler")
        if straggler is not None and self._straggler_warned < 3:
            self._straggler_warned += 1
            logger.warning(
                "[deepspeed_tpu] pipeline_trace: stage %d is a straggler — "
                "%.1fx the median stage busy time (step %d)",
                straggler["stage"], straggler["ratio"], cur["step"])
        return goodput

    # -- divergence --------------------------------------------------------
    def divergence(self, threshold=3.0):
        """Measured-vs-ideal check on the most recent step: the ideal schedule
        gives every stage the same busy time, so a stage whose measured busy
        seconds exceed ``threshold`` x the median is named as the straggler."""
        if not self.steps:
            return None
        last = self.steps[-1]
        decomp = last.get("schedule_goodput") or {}
        return _find_straggler(decomp["per_stage_busy_seconds"], threshold)

    # -- bundle / dump -----------------------------------------------------
    def bundle(self, last_n=None):
        steps = list(self.steps)
        if last_n is not None:
            steps = steps[-int(last_n):]
        return {
            "version": PIPELINE_TRACE_VERSION,
            "kind": "pipeline_trace",
            "host": self.host_id,
            "stages": self.stages,
            "steps": steps,
        }

    def dump(self, path=None):
        if path is None:
            if not self.dump_dir:
                return None
            os.makedirs(self.dump_dir, exist_ok=True)
            path = os.path.join(self.dump_dir,
                                f"pipeline_trace_host{self.host_id}.json")
        with open(path, "w") as f:
            json.dump(self.bundle(), f)
        return path

    def _atexit_dump(self):
        if self.dump_dir and self.steps:
            try:
                self.dump()
            except OSError:
                pass  # trace dump failure must never mask the real exit


# ------------------------------------------------------------ goodput accounting


def _find_straggler(per_stage_busy, threshold):
    if len(per_stage_busy) < 2:
        return None
    ordered = sorted(per_stage_busy)
    median = ordered[len(ordered) // 2]
    worst = max(range(len(per_stage_busy)), key=lambda s: per_stage_busy[s])
    if median > 0 and per_stage_busy[worst] > threshold * median:
        return {"stage": worst, "ratio": per_stage_busy[worst] / median}
    return None


def goodput_decomposition(spans, stages, straggler_threshold=3.0):
    """Decompose one step's span stream into category seconds plus the bubble
    the schedule would exhibit on a real per-stage deployment.

    The single-controller executor serializes all stages on one host, so wall
    clock alone cannot show a bubble. Instead the spans are replayed on a
    lockstep timeline: schedule step ``k`` costs ``max`` over stages of their
    compute (fwd/bwd) span durations at ``k`` — the slowest stage gates every
    peer exactly as in a synchronous pipeline. ``bubble_seconds`` is then the
    idle stage-time of that reconstructed timeline and ``bubble_fraction``
    its share; at uniform compute cost this converges to the PipeDream-flush
    closed form ``(p-1)/(m+p-1)``.
    """
    cat_seconds = {"fwd": 0.0, "bwd": 0.0, "p2p": 0.0, "load": 0.0,
                   "reduce": 0.0, "opt": 0.0}
    busy = {}          # (stage, sched_step) -> compute seconds
    per_stage = [0.0] * stages
    for sp in spans:
        dur = sp[SPAN_DUR] / 1e6
        cat = CATEGORY.get(sp[SPAN_NAME])
        if cat is not None:
            cat_seconds[cat] += dur
        if sp[SPAN_NAME] in _COMPUTE:
            key = (sp[SPAN_STAGE], sp[SPAN_STEP])
            busy[key] = busy.get(key, 0.0) + dur
            per_stage[sp[SPAN_STAGE]] += dur
    wall_by_step = {}
    for (_, k), dur in busy.items():
        wall_by_step[k] = max(wall_by_step.get(k, 0.0), dur)
    pipeline_seconds = sum(wall_by_step.values())
    compute_seconds = sum(per_stage)
    slot_time = stages * pipeline_seconds
    bubble_seconds = max(slot_time - compute_seconds, 0.0)
    out = dict(cat_seconds)
    out.update({
        "compute_seconds": compute_seconds,
        "pipeline_seconds": pipeline_seconds,
        "bubble_seconds": bubble_seconds,
        "bubble_fraction": (bubble_seconds / slot_time) if slot_time > 0 else 0.0,
        "per_stage_busy_seconds": per_stage,
        "spans": len(spans),
        "straggler": _find_straggler(per_stage, straggler_threshold),
    })
    # keep the *_seconds suffix for the monitor scalar names
    for cat in ("fwd", "bwd", "p2p", "load", "reduce", "opt"):
        out[f"{cat}_seconds"] = out.pop(cat)
    return out


def measured_costs(step_record):
    """Mean fwd/bwd span duration (seconds) of a recorded step — feed these to
    ``simulate_schedule`` to get the expected bubble at the measured costs."""
    sums = {"ForwardPass": [0.0, 0], "BackwardPass": [0.0, 0]}
    for sp in step_record["spans"]:
        if sp[SPAN_NAME] in sums:
            acc = sums[sp[SPAN_NAME]]
            acc[0] += sp[SPAN_DUR] / 1e6
            acc[1] += 1
    t_fwd = sums["ForwardPass"][0] / max(sums["ForwardPass"][1], 1)
    t_bwd = sums["BackwardPass"][0] / max(sums["BackwardPass"][1], 1)
    return t_fwd, t_bwd


# ----------------------------------------------------- symbolic schedule replay


def _instruction_streams(micro_batches, stages, schedule="train"):
    # lazy: keeps this module importable without pulling the runtime package
    from ..runtime.pipe import schedule as sched_mod
    cls = {"train": sched_mod.TrainSchedule,
           "inference": sched_mod.InferenceSchedule}[schedule]
    scheds = [cls(micro_batches=micro_batches, stages=stages, stage_id=s)
              for s in range(stages)]
    return ([list(iter(sc)) for sc in scheds],
            [sc.num_pipe_buffers() for sc in scheds])


def _replay(streams, rings, micro_batches, schedule="train"):
    """Symbolically execute merged per-stage streams, mirroring the engine's
    buffer dicts and send-before-recv merged-step ordering. Raises
    ``ScheduleLintError`` on any rendezvous or buffer-lifetime violation;
    returns the executed event list and per-stage occupancy stats."""
    S = len(streams)
    m = micro_batches
    train = schedule == "train"
    act_in = [dict() for _ in range(S)]    # buffer -> mb, input awaiting fwd
    saved = [dict() for _ in range(S)]     # buffer -> mb, activation awaiting bwd
    act_out = [dict() for _ in range(S)]   # buffer -> mb, output awaiting send
    grad_in = [dict() for _ in range(S)]   # buffer -> mb, grad awaiting bwd
    dx_buf = [dict() for _ in range(S)]    # buffer -> mb, input-grad awaiting send
    chan_act = {}                          # (src stage, mb) -> send step
    chan_grad = {}
    fwd_count = [0] * S
    bwd_count = [0] * S
    recv_act = [0] * S
    recv_grad = [0] * S
    load_count = [0] * S
    loaded = set()                         # micro-batches stage 0 has loaded
    peak_live = [0] * S
    events = []

    def fail(s, k, cmd, why):
        raise ScheduleLintError(
            f"stage {s} step {k}: {cmd!r}: {why} "
            f"(micro_batches={m}, stages={S}, schedule={schedule})")

    def note_live(s):
        # distinct buffer slots holding an activation: saved and act_out share
        # the slot their ForwardPass used, exactly as in the engine's ring
        live = set(act_in[s]) | set(saved[s]) | set(act_out[s])
        peak_live[s] = max(peak_live[s], len(live))

    def exec_cmd(s, k, cmd):
        name, buf = cmd.name, getattr(cmd, "buffer_id", None)
        mb_id = None
        if name == "LoadMicroBatch":
            mb_id = load_count[s]
            load_count[s] += 1
            if s == 0:
                if buf in act_in[0]:
                    fail(s, k, cmd, f"load clobbers unconsumed input buffer {buf}")
                act_in[0][buf] = mb_id
                loaded.add(mb_id)
            elif s != S - 1:
                fail(s, k, cmd, "LoadMicroBatch on an interior stage")
            elif mb_id >= m:
                fail(s, k, cmd, "more label loads than micro-batches")
        elif name == "ForwardPass":
            if buf not in act_in[s]:
                fail(s, k, cmd, f"buffer {buf} used before load/recv")
            mb_id = act_in[s].pop(buf)
            if mb_id != fwd_count[s]:
                fail(s, k, cmd, f"out-of-order forward: mb {mb_id} before {fwd_count[s]}")
            fwd_count[s] += 1
            if train:
                if buf in saved[s]:
                    fail(s, k, cmd, f"forward clobbers saved activation in buffer {buf}")
                saved[s][buf] = mb_id
            if s < S - 1:
                if buf in act_out[s]:
                    fail(s, k, cmd, f"forward clobbers unsent output in buffer {buf}")
                act_out[s][buf] = mb_id
        elif name == "SendActivation":
            if s >= S - 1:
                fail(s, k, cmd, "SendActivation on the last stage")
            if buf not in act_out[s]:
                fail(s, k, cmd, f"send of never-produced output buffer {buf}")
            mb_id = act_out[s].pop(buf)
            if (s, mb_id) in chan_act:
                fail(s, k, cmd, f"duplicate in-flight activation for mb {mb_id}")
            chan_act[(s, mb_id)] = k
            in_flight = sum(1 for (src, _) in chan_act if src == s)
            if in_flight > rings[s + 1]:
                fail(s, k, cmd, f"{in_flight} activations in flight > receiver "
                                f"num_pipe_buffers()={rings[s + 1]}")
        elif name == "RecvActivation":
            mb_id = recv_act[s]
            recv_act[s] += 1
            if (s - 1, mb_id) not in chan_act:
                fail(s, k, cmd, f"no matching SendActivation on stage {s - 1} "
                                f"for mb {mb_id}")
            sent_at = chan_act.pop((s - 1, mb_id))
            if sent_at != k:
                fail(s, k, cmd, f"rendezvous step mismatch: sent at step {sent_at}")
            if buf in act_in[s]:
                fail(s, k, cmd, f"recv clobbers unconsumed input buffer {buf}")
            act_in[s][buf] = mb_id
        elif name == "BackwardPass":
            if buf not in saved[s]:
                fail(s, k, cmd, f"backward without saved activation in buffer {buf}")
            mb_id = saved[s].pop(buf)
            if mb_id != bwd_count[s]:
                fail(s, k, cmd, f"out-of-order backward: mb {mb_id} before {bwd_count[s]}")
            bwd_count[s] += 1
            if s == S - 1:
                if mb_id not in loaded:
                    fail(s, k, cmd, f"labels for mb {mb_id} were never loaded")
            else:
                if buf not in grad_in[s]:
                    fail(s, k, cmd, f"backward without received grad in buffer {buf}")
                grad_in[s].pop(buf)
            if s > 0:
                if buf in dx_buf[s]:
                    fail(s, k, cmd, f"backward clobbers unsent grad in buffer {buf}")
                dx_buf[s][buf] = mb_id
        elif name == "SendGrad":
            if s == 0:
                fail(s, k, cmd, "SendGrad on the first stage")
            if buf not in dx_buf[s]:
                fail(s, k, cmd, f"send of never-produced grad buffer {buf}")
            mb_id = dx_buf[s].pop(buf)
            if (s, mb_id) in chan_grad:
                fail(s, k, cmd, f"duplicate in-flight grad for mb {mb_id}")
            chan_grad[(s, mb_id)] = k
            in_flight = sum(1 for (src, _) in chan_grad if src == s)
            if in_flight > rings[s - 1]:
                fail(s, k, cmd, f"{in_flight} grads in flight > receiver "
                                f"num_pipe_buffers()={rings[s - 1]}")
        elif name == "RecvGrad":
            mb_id = recv_grad[s]
            recv_grad[s] += 1
            if (s + 1, mb_id) not in chan_grad:
                fail(s, k, cmd, f"no matching SendGrad on stage {s + 1} for mb {mb_id}")
            sent_at = chan_grad.pop((s + 1, mb_id))
            if sent_at != k:
                fail(s, k, cmd, f"rendezvous step mismatch: sent at step {sent_at}")
            if buf in grad_in[s]:
                fail(s, k, cmd, f"recv clobbers unconsumed grad buffer {buf}")
            grad_in[s][buf] = mb_id
        elif name in ("ReduceGrads", "ReduceTiedGrads", "OptimizerStep"):
            pass
        else:
            fail(s, k, cmd, "unknown instruction")
        note_live(s)
        events.append((s, k, name, mb_id, buf))

    total_steps = len(streams[0])
    for k in range(total_steps):
        for s in range(S):
            for cmd in streams[s][k]:
                if cmd.name in _SEND_NAMES:
                    exec_cmd(s, k, cmd)
        for s in range(S):
            for cmd in streams[s][k]:
                if cmd.name not in _SEND_NAMES:
                    exec_cmd(s, k, cmd)

    if chan_act or chan_grad:
        raise ScheduleLintError(
            f"payloads left in flight at end of schedule: act={chan_act} "
            f"grad={chan_grad} (micro_batches={m}, stages={S})")
    for s in range(S):
        if fwd_count[s] != m or (train and bwd_count[s] != m):
            raise ScheduleLintError(
                f"stage {s} retired fwd={fwd_count[s]} bwd={bwd_count[s]} "
                f"of {m} micro-batches")
        leftover = (len(act_in[s]) + len(saved[s]) + len(act_out[s])
                    + len(grad_in[s]) + len(dx_buf[s]))
        if leftover:
            raise ScheduleLintError(f"stage {s} ends with {leftover} live buffers")
    return {"events": events, "peak_live": peak_live, "total_steps": total_steps}


def lint_schedule(micro_batches, stages, schedule="train"):
    """Static validator for one (micro_batches, stages) schedule instance across
    ALL stage ids: every send has a same-step recv on the adjacent stage, every
    buffer is loaded before use, and live buffers never exceed the stage's
    ``num_pipe_buffers()``. Raises ``ScheduleLintError`` on violation."""
    streams, rings = _instruction_streams(micro_batches, stages, schedule)
    stats = _replay(streams, rings, micro_batches, schedule)
    for s, (peak, ring) in enumerate(zip(stats["peak_live"], rings)):
        if peak > ring:
            raise ScheduleLintError(
                f"stage {s} peak live buffers {peak} > num_pipe_buffers()={ring}")
    return stats


# ------------------------------------------------------------ analytic simulator


def simulate_schedule(micro_batches, stages, schedule="train", t_fwd=1.0, t_bwd=None):
    """Replay a schedule offline on the lockstep timeline: expected bubble
    fraction, per-stage busy/idle slots, and peak buffer occupancy for any
    ``(micro_batches, stages)``. At uniform cost (``t_bwd == t_fwd``) the
    train-schedule bubble equals the closed form ``(p-1)/(m+p-1)``."""
    if t_bwd is None:
        t_bwd = t_fwd
    streams, rings = _instruction_streams(micro_batches, stages, schedule)
    stats = _replay(streams, rings, micro_batches, schedule)
    cost = {"ForwardPass": t_fwd, "BackwardPass": t_bwd}
    busy = {}
    per_stage = [0.0] * stages
    busy_slots = []
    for s, k, name, _, _ in stats["events"]:
        c = cost.get(name)
        if c is None:
            continue
        busy[(s, k)] = busy.get((s, k), 0.0) + c
        per_stage[s] += c
        busy_slots.append([s, k])
    wall_by_step = {}
    for (_, k), c in busy.items():
        wall_by_step[k] = max(wall_by_step.get(k, 0.0), c)
    pipeline_seconds = sum(wall_by_step.values())
    slot_time = stages * pipeline_seconds
    compute = sum(per_stage)
    active_steps = sorted(wall_by_step)
    idle_slots = [sum(1 for k in active_steps if (s, k) not in busy)
                  for s in range(stages)]
    return {
        "schedule": schedule,
        "micro_batches": micro_batches,
        "stages": stages,
        "total_steps": stats["total_steps"],
        "bubble_fraction": ((slot_time - compute) / slot_time) if slot_time else 0.0,
        "pipeline_seconds": pipeline_seconds,
        "per_stage_busy_seconds": per_stage,
        "per_stage_idle_slots": idle_slots,
        "busy_slots": sorted(map(tuple, busy_slots)),
        "peak_buffer_occupancy": stats["peak_live"],
        "num_pipe_buffers": rings,
    }


def simulated_bundle(micro_batches, stages, schedule="train",
                     t_fwd_us=100, t_bwd_us=200, step=0):
    """Deterministic synthetic span bundle from the lockstep replay: compute
    spans get the given integer costs, everything else is a zero-length marker.
    Used by the exporter golden test and as a docs-friendly demo input."""
    streams, rings = _instruction_streams(micro_batches, stages, schedule)
    stats = _replay(streams, rings, micro_batches, schedule)
    cost = {"ForwardPass": int(t_fwd_us), "BackwardPass": int(t_bwd_us)}
    step_wall = {}
    for s, k, name, _, _ in stats["events"]:
        c = cost.get(name, 0)
        step_wall[k] = max(step_wall.get(k, 0), c)
    start = {}
    t = 0
    for k in range(stats["total_steps"]):
        start[k] = t
        t += step_wall.get(k, 0)
    spans = [[s, k, name, mb, buf, start[k], cost.get(name, 0)]
             for s, k, name, mb, buf in stats["events"]]
    rec = {
        "step": int(step),
        "kind": "train" if schedule == "train" else "eval",
        "schedule": "TrainSchedule" if schedule == "train" else "InferenceSchedule",
        "micro_batches": int(micro_batches),
        "t0_us": 0,
        "spans": spans,
        "wall_seconds": t / 1e6,
    }
    rec["schedule_goodput"] = goodput_decomposition(spans, stages)
    return {
        "version": PIPELINE_TRACE_VERSION,
        "kind": "pipeline_trace",
        "host": 0,
        "stages": int(stages),
        "steps": [rec],
    }


# ------------------------------------------------------------- Perfetto export

# Chrome trace_event reserved color names, cycled per micro-batch so adjacent
# microbatches get visually distinct slices in Perfetto
_MB_COLORS = ("thread_state_running", "thread_state_runnable", "rail_response",
              "rail_animation", "rail_idle", "rail_load", "cq_build_passed",
              "cq_build_failed")


def to_trace_events(bundle):
    """Convert a span bundle into a Chrome/Perfetto ``trace_event`` JSON object:
    one thread (track) per stage, complete ("X") events per instruction span,
    counter ("C") tracks for per-stage buffer occupancy and per-step bubble
    fraction. Deterministic for a given bundle."""
    stages = int(bundle["stages"])
    events = [process_name_event(0, f"pipeline host {bundle.get('host', 0)}")]
    for s in range(stages):
        events += thread_meta_events(0, s, f"stage {s}", sort_index=s)
    for rec in bundle.get("steps", []):
        base = int(rec.get("t0_us", 0))
        train = rec.get("schedule") != "InferenceSchedule"
        occupancy = [0] * stages
        # legacy bundles predate the schedule_goodput rename
        goodput = rec.get("schedule_goodput") or rec.get("goodput") or {}
        if goodput.get("bubble_fraction") is not None:
            events.append(counter_event(
                0, 0, base, "bubble_fraction",
                {"bubble": round(goodput["bubble_fraction"], 6)}))
        for sp in rec["spans"]:
            s, k, name, mb, buf, rel, dur = sp
            cname = (_MB_COLORS[mb % len(_MB_COLORS)]
                     if mb is not None and name in _COMPUTE else None)
            events.append(complete_slice(
                0, s, base + rel, dur,
                name if mb is None else f"{name} mb{mb}",
                CATEGORY.get(name, "other"),
                {"sched_step": k, "micro_batch": mb, "buffer": buf,
                 "step": rec.get("step")}, cname=cname))
            delta = 0
            if name == "RecvActivation" or (name == "LoadMicroBatch" and s == 0):
                delta = 1
            elif train and name == "BackwardPass":
                delta = -1
            elif not train and name == "ForwardPass":
                delta = -1
            if delta:
                occupancy[s] += delta
                events.append(counter_event(
                    0, s, base + rel + dur, f"stage {s} buffers",
                    {"buffers": occupancy[s]}))
    return trace_envelope(events, "ds-tpu timeline", stages=stages,
                          trace_version=bundle.get("version"))


# serialize_trace lives in utils/trace_event.py (shared with the serve and
# anatomy exporters) and stays re-exported here for its historical importers.


# --------------------------------------------------------------------- the CLI


def _load_bundle(path):
    # flight-recorder dumps (numerics.FlightRecorder) embed the span bundle
    return load_bundle(path, PIPELINE_TRACE_KIND)


def timeline_main(argv=None):
    """``ds-tpu timeline`` entry point: span bundle (or flight-recorder dump
    embedding one) -> Perfetto/Chrome trace_event JSON."""
    parser = argparse.ArgumentParser(
        prog="ds-tpu timeline",
        description="Convert a pipeline_trace span bundle (or a flight-recorder "
                    "dump that embeds one) into Perfetto/Chrome trace_event JSON "
                    "viewable at ui.perfetto.dev or chrome://tracing.")
    parser.add_argument("bundle", help="path to the span bundle / dump JSON "
                                       "(with --cluster: a shared dump dir)")
    parser.add_argument("-o", "--output", default=None,
                        help="output path (default: <bundle>.trace.json)")
    parser.add_argument("--cluster", action="store_true",
                        help="treat BUNDLE as a shared dump directory and "
                             "merge one run's per-host bundles onto per-host "
                             "track groups, aligned by heartbeat-estimated "
                             "clock offsets")
    parser.add_argument("--run", default=None,
                        help="with --cluster: merge this run instead of the "
                             "newest one")
    args = parser.parse_args(argv)

    if args.cluster:
        from .cluster import cluster_timeline
        out = args.output
        if out is None:
            out = os.path.join(args.bundle, "cluster.trace.json")
        return cluster_timeline(args.bundle, out, run=args.run)

    try:
        bundle = _load_bundle(args.bundle)
    except (OSError, ValueError) as e:
        print(f"ds-tpu timeline: cannot read {args.bundle}: {e}")
        return 2
    if bundle is None:
        print(f"ds-tpu timeline: {args.bundle} holds no pipeline_trace bundle "
              "(enable telemetry.pipeline_trace and re-dump)")
        return 2

    trace = to_trace_events(bundle)
    out = args.output
    if out is None:
        stem = args.bundle[:-5] if args.bundle.endswith(".json") else args.bundle
        out = stem + ".trace.json"
    with open(out, "w") as f:
        f.write(serialize_trace(trace))
    n_spans = sum(len(rec["spans"]) for rec in bundle.get("steps", []))
    print(f"wrote {len(trace['traceEvents'])} trace events "
          f"({n_spans} spans, {len(bundle.get('steps', []))} steps, "
          f"{bundle['stages']} stages) -> {out}")
    return 0
