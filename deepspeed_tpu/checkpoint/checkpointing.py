"""Checkpoint save/load with DeepSpeed's tag/dir layout semantics.

Analog of ``deepspeed/runtime/engine.py:1149-1416``: a checkpoint directory contains a
``<tag>/`` subdir with ``mp_rank_00_model_states`` (module params + counters + lr/scaler
state) and, under ZeRO, per-DP-shard optimizer state files
``zero_pp_rank_{dp}_mp_rank_{mp}_optim_states`` whose shards can be merged and
re-partitioned when reloading under a different DP world size (elastic checkpoint,
reference stage2.py:1713-1779 / stage1.py:836-947). Arrays are stored as .npz; metadata as
JSON. ``latest`` file tracks the most recent tag (engine.py:1351-1353).

In the single-controller JAX runtime one process owns every shard, so "per-rank files"
are written by slicing the global arrays — the on-disk layout (one optim file per DP rank)
is preserved so multi-host loaders and the elastic merge path work identically.

Durability (docs/resilience.md): a save is a two-phase operation. Phase 1
(``snapshot_checkpoint``) materializes every payload as host data — it runs the
device→host copies and the multi-host collective gathers but touches no files,
so phase 2 (``write_snapshot``) can run on a background thread while training
continues. Phase 2 commits through ``<tag>.tmp/`` + per-file sha256 manifest +
fsync + atomic rename, and ``latest`` is updated via tmp + ``os.replace`` — a
crash at any point leaves either the previous committed state or a ``.tmp``
dir/mismatched manifest that ``verify_checkpoint`` detects and restore skips,
never loads.
"""

import hashlib
import json
import os
import shutil
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import logger


def _path_key(path) -> str:
    """Canonical '/'-joined key for a tree path — the single definition every
    save/load layout (tree npz, per-rank shards, offload regions) keys by."""
    return "/".join(str(getattr(p, "key", getattr(p, "idx", getattr(p, "name", p))))
                    for p in path)


def _needs_allgather(leaf) -> bool:
    """Whether materializing ``leaf`` requires the collective gather. The decision
    derives from the sharding's PROCESS SPAN — a globally consistent property —
    not per-process addressability: an array placed on a subset of processes is
    fully addressable on its owner but not elsewhere, and an addressability-based
    rule would have the owner skip the allgather other processes join (deadlock)."""
    if not isinstance(leaf, jax.Array) or leaf.is_fully_replicated:
        return False
    span = {d.process_index for d in leaf.sharding.device_set}
    return len(span) > 1


def _leaf_to_host(leaf) -> np.ndarray:
    """Host copy of a (possibly multi-host sharded) array. Cross-process sharded
    leaves are gathered collectively — EVERY process must call this on the same
    leaves in the same order (save_checkpoint guarantees it)."""
    if _needs_allgather(leaf):
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(leaf, tiled=True))
    return np.asarray(jax.device_get(leaf))


def _flatten_with_paths(tree, materialize: bool = True) -> Dict[str, np.ndarray]:
    """``materialize=False`` (non-writer processes): join only the collective
    gathers that cross-process sharded leaves require — skip the redundant D2H of
    every addressable/replicated leaf (N-1 wasted full-model copies otherwise)."""
    flat = {}
    leaves_with_paths = jax.tree_util.tree_flatten_with_path(tree)[0]
    for path, leaf in leaves_with_paths:
        key = _path_key(path)
        if not materialize:
            if _needs_allgather(leaf):
                _leaf_to_host(leaf)  # collective participation only
            continue
        arr = _leaf_to_host(leaf)
        if arr.dtype not in (np.float32, np.float64, np.int32, np.int64, np.bool_,
                             np.uint32, np.uint8, np.int8, np.float16):
            # npz can't natively store ml_dtypes (bfloat16 et al.); widen losslessly.
            arr = arr.astype(np.float32)
        flat[key] = arr
    return flat


def _unflatten_like(template, flat: Dict[str, np.ndarray], numpy: bool = False):
    """``numpy=True`` keeps leaves as host arrays — required for the offload path,
    whose fp32 master+moments may not fit on device at all."""
    paths, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, leaf in paths:
        key = _path_key(path)
        if key not in flat:
            raise KeyError(f"checkpoint missing array {key!r}")
        arr = flat[key]
        if numpy:
            leaves.append(np.asarray(arr, dtype=np.dtype(leaf.dtype)).reshape(leaf.shape))
        else:
            leaves.append(jnp.asarray(arr, dtype=leaf.dtype).reshape(leaf.shape))
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _load_tree_npz(path: str, template):
    with np.load(path) as data:
        flat = {k: data[k] for k in data.files}
    return _unflatten_like(template, flat)


def _ckpt_dir(save_dir: str, tag: str) -> str:
    return os.path.join(save_dir, str(tag))


# --------------------------------------------------------- commit protocol
# Manifest name is distinct from the offload_manifest_* region manifests: this
# one is the integrity record of the WHOLE tag dir (per-file sha256), written
# last so its presence certifies every other file landed completely.
MANIFEST_NAME = "ds_ckpt_manifest.json"
TMP_SUFFIX = ".tmp"


def _file_sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _fsync_dir(path: str) -> None:
    """fsync the directory entry so renames/creates inside it are durable.
    Best-effort: not every filesystem (or platform) supports dir fds."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _atomic_write_text(path: str, text: str) -> None:
    """tmp-file + fsync + os.replace: readers see the old content or the new
    content, never a torn prefix."""
    tmp = path + TMP_SUFFIX
    with open(tmp, "w") as f:
        f.write(text)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path) or ".")


def write_latest(save_dir: str, tag: str) -> None:
    """Atomically point ``latest`` at ``tag`` — a preemption mid-write must
    never leave a torn ``latest`` that fails every future restore."""
    _atomic_write_text(os.path.join(save_dir, "latest"), str(tag))


def write_manifest(ckpt_dir: str, extra: Optional[Dict] = None) -> Dict:
    """Checksum every file in ``ckpt_dir`` into the integrity manifest. Written
    LAST in the commit sequence: a save killed before this point leaves no (or
    a stale) manifest, which verify_checkpoint reports as torn."""
    entries = {}
    for name in sorted(os.listdir(ckpt_dir)):
        path = os.path.join(ckpt_dir, name)
        if name == MANIFEST_NAME or name.endswith(TMP_SUFFIX) \
                or not os.path.isfile(path):
            continue
        entries[name] = {"sha256": _file_sha256(path),
                         "bytes": os.path.getsize(path)}
    manifest = {"version": 1, "files": entries}
    if extra:
        manifest.update(extra)
    _atomic_write_text(os.path.join(ckpt_dir, MANIFEST_NAME),
                       json.dumps(manifest, sort_keys=True))
    return manifest


def verify_checkpoint(ckpt_dir: str):
    """(ok, reason) integrity verdict for one tag dir. A checkpoint whose
    manifest is missing a file, or whose bytes/sha256 disagree with the
    manifest, is TORN — restore must skip it, never load it. Pre-manifest
    (legacy) checkpoints pass with a reason noting the weaker guarantee."""
    if not os.path.isdir(ckpt_dir):
        return False, "missing checkpoint directory"
    mpath = os.path.join(ckpt_dir, MANIFEST_NAME)
    if not os.path.isfile(mpath):
        return True, "legacy (no integrity manifest)"
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (ValueError, OSError) as e:
        return False, f"unreadable manifest ({e})"
    for name, ent in manifest.get("files", {}).items():
        path = os.path.join(ckpt_dir, name)
        if not os.path.isfile(path):
            return False, f"missing file {name}"
        if os.path.getsize(path) != ent.get("bytes"):
            return False, (f"size mismatch in {name}: "
                           f"{os.path.getsize(path)} != {ent.get('bytes')}")
        if _file_sha256(path) != ent.get("sha256"):
            return False, f"checksum mismatch in {name}"
    return True, "ok"


def model_states_name(mp_rank: int = 0) -> str:
    return f"mp_rank_{mp_rank:02d}_model_states"


def optim_states_name(dp_rank: int, mp_rank: int = 0) -> str:
    return f"zero_pp_rank_{dp_rank}_mp_rank_{mp_rank:02d}_optim_states"


def offload_states_name(proc: int) -> str:
    return f"zero_offload_proc_{proc}_optim_states"


def _offload_leaf_keys(off):
    """Leaf path keys in tree_flatten order for the offload class's param tree."""
    skeleton = jax.tree_util.tree_unflatten(off._treedef,
                                            [np.zeros(0)] * len(off._shapes))
    return [_path_key(path)
            for path, _ in jax.tree_util.tree_flatten_with_path(skeleton)[0]]


def _snapshot_offload_regions(engine):
    """Per-PROCESS region payloads for the host-tier state (multi-host safe).

    Each process snapshots only the master/moment regions its devices own
    (``zero_offload_proc_N``); a region manifest records leaf shapes and every
    region's slice so any topology can reassemble full leaves on load — the
    region-wise analog of the reference's per-rank ``zero_pp_rank_N`` files.
    Buffer regions are COPIED: the async writer thread must not observe the
    next step's in-place host updates."""
    off = engine._offload
    keys = _offload_leaf_keys(off)
    shard = {}
    regions_meta = []
    for li, regions in enumerate(off._leaf_regions):
        for r in regions:
            tag = f"r{li}_{r.offset}"
            for prefix, buf in (("master", off.fp32), ("exp_avg", off.exp_avg),
                                ("exp_avg_sq", off.exp_avg_sq)):
                shard[f"{prefix}/{tag}"] = np.array(
                    buf[r.offset:r.offset + r.size])
            regions_meta.append({"tag": tag, "leaf": li,
                                 "starts": [sl.start for sl in r.slices],
                                 "stops": [sl.stop for sl in r.slices]})
    manifest = {"n_procs": jax.process_count(), "proc": jax.process_index(),
                "leaves": [{"key": k, "shape": list(shp)}
                           for k, shp in zip(keys, off._shapes)],
                "regions": regions_meta}
    return shard, manifest


def _save_barrier():
    """Rendezvous across hosts: save_checkpoint returns only after EVERY process's
    files are on disk (an immediate load may otherwise race another host's writes)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("deepspeed_tpu_checkpoint_save")


def _offload_manifests(ckpt_dir: str):
    import glob
    return sorted(glob.glob(os.path.join(ckpt_dir, "offload_manifest_*.json")))


def _load_offload_regions(ckpt_dir: str):
    """Reassemble full master/exp_avg/exp_avg_sq flat dicts (key -> full array) from
    the per-process region files. Topology-agnostic: works for any current dp."""
    out = None
    seen_procs = set()
    n_procs_seen = set()
    for mpath in _offload_manifests(ckpt_dir):
        with open(mpath) as f:
            manifest = json.load(f)
        leaves = manifest["leaves"]
        seen_procs.add(manifest["proc"])
        n_procs_seen.add(manifest["n_procs"])
        if out is None:
            out = {prefix: {l["key"]: np.zeros(l["shape"], np.float32) for l in leaves}
                   for prefix in ("master", "exp_avg", "exp_avg_sq")}
        path = os.path.join(ckpt_dir, offload_states_name(manifest["proc"]) + ".npz")
        with np.load(path) as data:
            for r in manifest["regions"]:
                leaf = leaves[r["leaf"]]
                slices = tuple(slice(a, b) for a, b in zip(r["starts"], r["stops"]))
                shape = tuple(b - a for a, b in zip(r["starts"], r["stops"]))
                for prefix in ("master", "exp_avg", "exp_avg_sq"):
                    out[prefix][leaf["key"]][slices] = \
                        data[f"{prefix}/{r['tag']}"].reshape(shape)
    assert out is not None, "no offload manifests found"
    if len(n_procs_seen) != 1 or seen_procs != set(range(next(iter(n_procs_seen)))):
        # partial saves AND stale manifests from an older topology in a reused tag
        # dir must fail loud, not merge into (or zero out) the restored state
        raise RuntimeError(
            f"offload checkpoint is inconsistent: manifests for processes "
            f"{sorted(seen_procs)} with recorded world sizes {sorted(n_procs_seen)}")
    return out["master"], out["exp_avg"], out["exp_avg_sq"]


def _scatter_offload_regions(ckpt_dir: str, off) -> bool:
    """Same-topology fast path: copy saved regions straight into the LOCAL offload
    buffers without materializing full trees (each host allocates only its partition
    — full-tree reassembly of a multi-B model would 3x-overshoot a host sized for the
    partitioned steady state). Returns False when the topology changed (any local
    region unmatched) — caller falls back to full reassembly."""
    local = {}
    for li, regions in enumerate(off._leaf_regions):
        for r in regions:
            key = (li, tuple(sl.start for sl in r.slices),
                   tuple(sl.stop for sl in r.slices))
            local[key] = r
    bufs = {"master": off.fp32, "exp_avg": off.exp_avg, "exp_avg_sq": off.exp_avg_sq}
    matched = set()
    for mpath in _offload_manifests(ckpt_dir):
        with open(mpath) as f:
            manifest = json.load(f)
        if len(manifest["leaves"]) != len(off._shapes) or any(
                tuple(l["shape"]) != tuple(shp)
                for l, shp in zip(manifest["leaves"], off._shapes)):
            return False  # different model/tree
        hits = []
        for r in manifest["regions"]:
            key = (r["leaf"], tuple(r["starts"]), tuple(r["stops"]))
            if key in local:
                hits.append((r, local[key]))
        if not hits:
            continue
        path = os.path.join(ckpt_dir, offload_states_name(manifest["proc"]) + ".npz")
        with np.load(path) as data:
            for saved, lr in hits:
                for prefix, buf in bufs.items():
                    buf[lr.offset:lr.offset + lr.size] = \
                        data[f"{prefix}/{saved['tag']}"]
                matched.add((saved["leaf"], tuple(saved["starts"]),
                             tuple(saved["stops"])))
    return matched == set(local.keys())


def comm_ef_geometry(engine):
    """Geometry descriptor of the engine-held compressed-exchange error-feedback
    buffers (``_comm_we``/``_comm_se``), or None when the engine holds none.
    This is what save records next to the buffers and what restore validates
    (resilience/elastic.py) — the chunk→global-offset map is a function of
    (dp, slice_size) and, under bucketed overlap, of the per-bucket leaf
    partition, so a restore must prove it can replay the same layout (or a
    remappable resize of it) before touching the buffers."""
    if getattr(engine, "_comm_we", None) is None:
        return None
    topo = engine._comm_topo
    plan = getattr(engine, "_overlap_plan", None)
    geo = {"dp": int(engine.dp_size), "slice_size": int(topo.slice_size)}
    if plan is not None:
        geo["layout"] = "bucketed"
        geo["buckets"] = [{"sizes": [int(s) for s in b["sizes"]],
                           "n": int(b["n"]), "n_pad": int(b["n_pad"])}
                          for b in plan]
    else:
        from ..comm.hierarchical import padded_size, tree_size
        n_total = tree_size(engine.params)
        geo["layout"] = "monolithic"
        geo["n"] = int(n_total)
        geo["n_pad"] = int(padded_size(n_total, engine.dp_size))
    return geo


def snapshot_checkpoint(engine, tag: Optional[str] = None, client_state: Dict = {}):
    """Phase 1 of a save: materialize every checkpoint payload as HOST data.

    Runs the device→host copies (and the multi-host collective gathers every
    process must join) but touches NO files — the returned snapshot is
    self-contained host state, so phase 2 (``write_snapshot``) can run on a
    background writer thread while training keeps stepping
    (resilience/async_ckpt.py). The step programs donate their state buffers,
    but device_get copies to host before the next step runs, so the snapshot
    can never observe a half-updated tree."""
    if tag is None:
        tag = f"global_step{engine.global_steps}"
    offload = getattr(engine, "_offload", None)
    # Multi-host: the model-states/scaler/optim-shard/latest files are shared paths —
    # exactly one WRITER (process 0), or concurrent identical-path np.savez calls
    # corrupt the archives. But cross-process sharded state (ZeRO masters, a
    # pipe-sharded wte) needs a collective gather that EVERY process participates in,
    # so ALL processes run every flatten below (offload included — no early return
    # before the last flatten) and only the payload retention is gated.
    writer = jax.process_index() == 0
    files: Dict[str, Any] = {}  # filename -> ("npz", flat dict) | ("json", obj)

    if offload is not None:
        # host-tier state: each process snapshots its own regions (multi-host safe)
        shard, off_manifest = _snapshot_offload_regions(engine)
        proc = jax.process_index()
        files[offload_states_name(proc) + ".npz"] = ("npz", shard)
        files[f"offload_manifest_{proc}.json"] = ("json", off_manifest)

    # --- model states (replicated compute params + host-side counters) ---
    # _ckpt_export: engines with a non-canonical runtime layout (SPMD pipeline's
    # pipe-stacked stages) serialize in the layer-keyed form so checkpoints stay
    # portable across stage counts / executor modes
    params_flat = _flatten_with_paths(engine._ckpt_export(engine.params, "params"),
                                      materialize=writer)
    if writer:
        files[model_states_name() + ".npz"] = ("npz", params_flat)
    meta = {
        "external_master": bool(getattr(engine, "_external_master", False)),
        "global_steps": engine.global_steps,
        "micro_steps": engine.micro_steps,
        "skipped_steps": engine.skipped_steps,
        "dp_world_size": engine.dp_size,
        "zero_stage": engine.zero_optimization_stage(),
        "optimizer_name": engine.optimizer.name,
        "param_groups": [
            {k: (list(v) if isinstance(v, tuple) else v) for k, v in g.items()}
            for g in engine.optimizer.param_groups
        ],
        "lr_scheduler": engine.lr_scheduler.state_dict() if engine.lr_scheduler is not None else None,
        "client_state": client_state,
    }
    if writer:
        files[model_states_name() + ".json"] = ("json", meta)

    # --- scaler state ---
    scaler_flat = _flatten_with_paths(engine.scaler_state, materialize=writer)
    if writer:
        files["loss_scaler.npz"] = ("npz", scaler_flat)

    if offload is None:
        # --- optimizer + master states, one file per DP rank (elastic layout) ---
        # external-master engines hold no master (it is byte-for-byte derivable as
        # the fp32 upcast of the saved params — writing it would triple the
        # checkpoint and materialize a full fp32 tree on device for nothing)
        from ..runtime.zero.sharding import elastic_split
        dp = engine.dp_size
        if getattr(engine, "_external_master", False):
            master_flat = {}
        else:
            master_flat = _flatten_with_paths(
                engine._ckpt_export(engine.master_params, "master"), materialize=writer)
        opt_flat = _flatten_with_paths(engine._ckpt_export(engine.opt_state, "opt"),
                                       materialize=writer)
        if writer:
            split = {f"{prefix}/{key}": elastic_split(arr, dp)
                     for prefix, flat in (("master", master_flat), ("opt", opt_flat))
                     for key, arr in flat.items()}
            for dp_rank in range(dp):
                files[optim_states_name(dp_rank) + ".npz"] = (
                    "npz", {key: parts[dp_rank] for key, parts in split.items()})
            # shape manifest for elastic restore
            shapes = {f"master/{k}": list(v.shape) for k, v in master_flat.items()}
            shapes.update({f"opt/{k}": list(v.shape) for k, v in opt_flat.items()})
            files["optim_shapes.json"] = ("json", {"dp_world_size": dp,
                                                  "shapes": shapes})

    # --- engine-held compressed-comm error feedback (docs/resilience.md) ---
    ef_geo = comm_ef_geometry(engine)
    if ef_geo is not None:
        ef_flat = _flatten_with_paths({"server_error": engine._comm_se,
                                       "worker_error": engine._comm_we},
                                      materialize=writer)
        if writer:
            files["comm_ef.npz"] = ("npz", ef_flat)
            files["comm_ef.json"] = ("json", ef_geo)

    return {"tag": str(tag), "writer": writer,
            "single_process": jax.process_count() == 1,
            "offload": offload is not None,
            "n_procs": jax.process_count(),
            "manifest_meta": {"tag": str(tag),
                              "global_steps": int(engine.global_steps),
                              "dp_world_size": int(engine.dp_size)},
            "files": files}


def _write_payloads(dirpath: str, files: Dict[str, Any]) -> None:
    for name in sorted(files):
        kind, payload = files[name]
        path = os.path.join(dirpath, name)
        if kind == "npz":
            with open(path, "wb") as f:
                np.savez(f, **payload)
                f.flush()
                os.fsync(f.fileno())
        else:
            with open(path, "w") as f:
                json.dump(payload, f)
                f.flush()
                os.fsync(f.fileno())


def write_snapshot(snapshot: Dict, save_dir: str, save_latest: bool = True) -> str:
    """Phase 2 of a save: the commit protocol. Pure host file I/O — no device
    access — so it is safe on a background writer thread.

    Single-process: every file lands in ``<tag>.tmp/``, the integrity manifest
    is written (itself via tmp + replace), everything is fsynced, and the tmp
    dir is atomically renamed to ``<tag>/``. A crash at ANY point leaves
    either the previous committed state or a ``.tmp`` dir restore ignores.

    Multi-process: each process writes its own files straight into the final
    dir (a cross-host dir rename cannot be made atomic without another
    rendezvous); after the barrier, process 0 writes the manifest LAST, so a
    torn multi-host save still presents as missing/mismatched manifest and is
    skipped at restore. ``latest`` always updates via tmp + os.replace."""
    tag = snapshot["tag"]
    files = snapshot["files"]
    final_dir = _ckpt_dir(save_dir, tag)
    os.makedirs(save_dir, exist_ok=True)

    if snapshot["single_process"]:
        tmp_dir = final_dir + TMP_SUFFIX
        if os.path.isdir(tmp_dir):
            shutil.rmtree(tmp_dir)
        os.makedirs(tmp_dir)
        _write_payloads(tmp_dir, files)
        write_manifest(tmp_dir, extra=snapshot["manifest_meta"])
        _fsync_dir(tmp_dir)
        if os.path.isdir(final_dir):
            # re-saving an existing tag: the old dir must vacate the name. The
            # crash window between rmtree and rename can lose THIS tag, but
            # ``latest`` still points at a committed tag until the final step.
            shutil.rmtree(final_dir)
        os.rename(tmp_dir, final_dir)
        _fsync_dir(save_dir)
    else:
        os.makedirs(final_dir, exist_ok=True)
        if snapshot["offload"] and snapshot["writer"]:
            # a reused tag dir may hold files from an older, larger topology;
            # current writers only touch indices < n_procs, so this is safe
            import glob as _glob
            for stale in _glob.glob(os.path.join(final_dir, "offload_manifest_*.json")):
                idx = int(stale.rsplit("_", 1)[1].split(".")[0])
                if idx >= snapshot["n_procs"]:
                    os.remove(stale)
                    npz = os.path.join(final_dir, offload_states_name(idx) + ".npz")
                    if os.path.isfile(npz):
                        os.remove(npz)
        _write_payloads(final_dir, files)
        _save_barrier()
        if snapshot["writer"]:
            write_manifest(final_dir, extra=snapshot["manifest_meta"])

    if save_latest and snapshot["writer"]:
        write_latest(save_dir, tag)
    return final_dir


def save_checkpoint(engine, save_dir: str, tag: Optional[str] = None, client_state: Dict = {},
                    save_latest: bool = True):
    snapshot = snapshot_checkpoint(engine, tag=tag, client_state=client_state)
    write_snapshot(snapshot, save_dir, save_latest=save_latest)
    _save_barrier()
    logger.info(f"[deepspeed_tpu] saved checkpoint {snapshot['tag']} to {save_dir}")
    return True


def _merge_elastic(ckpt_dir: str) -> Dict[str, np.ndarray]:
    """Merge per-DP-rank optim shards back into full flat arrays (any saved dp size)."""
    with open(os.path.join(ckpt_dir, "optim_shapes.json")) as f:
        manifest = json.load(f)
    saved_dp = manifest["dp_world_size"]
    shapes = manifest["shapes"]
    merged: Dict[str, List[np.ndarray]] = {k: [None] * saved_dp for k in shapes}
    for dp_rank in range(saved_dp):
        path = os.path.join(ckpt_dir, optim_states_name(dp_rank) + ".npz")
        with np.load(path) as data:
            for key in data.files:
                merged[key][dp_rank] = data[key]
    out = {}
    for key, chunks in merged.items():
        flat = np.concatenate(chunks)
        out[key] = flat.reshape(shapes[key])
    return out


def load_checkpoint(engine, load_dir: str, tag: Optional[str] = None,
                    load_optimizer_states: bool = True, load_lr_scheduler_states: bool = True):
    if tag is None:
        latest_path = os.path.join(load_dir, "latest")
        if os.path.isfile(latest_path):
            with open(latest_path) as f:
                tag = f.read().strip()
        else:
            logger.warning(f"Unable to find latest file at {latest_path}, "
                           "if trying to load latest checkpoint please pass a valid tag.")
            return None, {}
    ckpt_dir = _ckpt_dir(load_dir, tag)
    if not os.path.isdir(ckpt_dir):
        logger.warning(f"Client provided checkpoint tag {tag} does not exist in {load_dir}")
        return None, {}
    ok, reason = verify_checkpoint(ckpt_dir)
    if not ok:
        # torn / partially-written save (a crash mid-write) — refuse it rather
        # than load silently-corrupt state; auto-resume falls back to an older
        # committed tag (resilience/auto_resume.py)
        logger.warning(f"[deepspeed_tpu] REFUSING to load checkpoint {tag}: {reason}")
        return None, {}

    with open(os.path.join(ckpt_dir, model_states_name() + ".json")) as f:
        meta = json.load(f)

    params = _load_tree_npz(os.path.join(ckpt_dir, model_states_name() + ".npz"),
                            engine._ckpt_export(engine.params, "params"))
    engine.params = jax.device_put(engine._ckpt_import(params, "params"),
                                   engine._param_shardings)

    engine.global_steps = meta["global_steps"]
    engine.micro_steps = meta["micro_steps"]
    engine.skipped_steps = meta["skipped_steps"]
    for g, src in zip(engine.optimizer.param_groups, meta.get("param_groups", [])):
        src = dict(src)
        if "betas" in src and isinstance(src["betas"], list):
            src["betas"] = tuple(src["betas"])
        g.update(src)
    if load_lr_scheduler_states and engine.lr_scheduler is not None and meta.get("lr_scheduler"):
        engine.lr_scheduler.load_state_dict(meta["lr_scheduler"])

    engine.scaler_state = _load_tree_npz(os.path.join(ckpt_dir, "loss_scaler.npz"), engine.scaler_state)

    if load_optimizer_states:
        offload = getattr(engine, "_offload", None)
        has_region_layout = bool(_offload_manifests(ckpt_dir))

        def offload_template():
            # leaf-shaped numpy skeleton: avoids assembling engine.master_params
            # (impossible on a multi-host offload engine, whose buffers are partial)
            return jax.tree_util.tree_unflatten(
                offload._treedef, [np.zeros(shp, np.float32) for shp in offload._shapes])

        if has_region_layout:
            if offload is not None and _scatter_offload_regions(ckpt_dir, offload):
                pass  # same topology: regions copied straight into the local buffers
            elif offload is not None:
                # topology changed: reassemble full leaves, then scatter locally
                master_flat, ea_flat, eas_flat = _load_offload_regions(ckpt_dir)
                t = offload_template()
                offload.load_trees(_unflatten_like(t, master_flat, numpy=True),
                                   _unflatten_like(t, ea_flat, numpy=True),
                                   _unflatten_like(t, eas_flat, numpy=True))
            else:
                master_flat, ea_flat, eas_flat = _load_offload_regions(ckpt_dir)
                if not getattr(engine, "_external_master", False):
                    master = _unflatten_like(
                        engine._ckpt_export(engine.master_params, "master"), master_flat)
                    engine.master_params = engine._place_master(
                        engine._ckpt_import(master, "master"))
                opt_flat = {f"exp_avg/{k}": v for k, v in ea_flat.items()}
                opt_flat.update({f"exp_avg_sq/{k}": v for k, v in eas_flat.items()})
                opt = _unflatten_like(engine._ckpt_export(engine.opt_state, "opt"), opt_flat)
                engine.opt_state = jax.device_put(
                    engine._ckpt_import(opt, "opt"), engine._opt_shardings)
        else:
            merged = _merge_elastic(ckpt_dir)
            master_flat = {k[len("master/"):]: v for k, v in merged.items() if k.startswith("master/")}
            opt_flat = {k[len("opt/"):]: v for k, v in merged.items() if k.startswith("opt/")}
            if hasattr(engine, "_onebit") and meta["dp_world_size"] != engine.dp_size:
                # OneBitAdam state sizes are dp-dependent (padded moments, per-worker
                # error buffers); adapt them instead of failing the reshape below.
                # (1-bit Adam requires replicated params, so no _ckpt_export needed.)
                opt_flat = engine._onebit.elastic_adapt(opt_flat, _flatten_with_paths(engine.opt_state))
            if offload is not None:
                # host-tier state: unflatten on the host and copy into the flat offload
                # buffers — never materialize master/moments on device
                t = offload_template()
                ea = {k[len("exp_avg/"):]: v for k, v in opt_flat.items()
                      if k.startswith("exp_avg/")}
                eas = {k[len("exp_avg_sq/"):]: v for k, v in opt_flat.items()
                       if k.startswith("exp_avg_sq/")}
                offload.load_trees(_unflatten_like(t, master_flat, numpy=True),
                                   _unflatten_like(t, ea, numpy=True),
                                   _unflatten_like(t, eas, numpy=True))
            else:
                if getattr(engine, "_external_master", False):
                    pass  # no master storage; the view re-derives from params
                elif master_flat:
                    master = _unflatten_like(
                        engine._ckpt_export(engine.master_params, "master"), master_flat)
                    engine.master_params = engine._place_master(
                        engine._ckpt_import(master, "master"))
                else:
                    # an external-master checkpoint loaded into a standard engine:
                    # the master is BY DEFINITION the fp32 upcast of the restored
                    # params (that is why it was not written)
                    engine.master_params = jax.device_put(
                        jax.tree_util.tree_map(lambda p: jnp.asarray(p, jnp.float32),
                                               engine.params),
                        engine._master_shardings)
                opt = _unflatten_like(engine._ckpt_export(engine.opt_state, "opt"), opt_flat)
                engine.opt_state = jax.device_put(
                    engine._ckpt_import(opt, "opt"), engine._opt_shardings)
    else:
        # re-derive master from loaded params (fp16-derived restore, stage2.py:1781-1836)
        if getattr(engine, "_offload", None) is not None:
            engine._offload.load_trees(master_tree=engine.params)
        else:
            engine.master_params = engine._place_master(
                jax.tree_util.tree_map(lambda p: jnp.asarray(p, jnp.float32), engine.params))

    if getattr(engine, "_comm_we", None) is not None:
        # engine-held compressed-comm error feedback: restore (with elastic
        # remap on a dp change) or, for pre-resilience checkpoints that never
        # saved it, keep the zero-initialized buffers
        from ..resilience.elastic import restore_comm_ef
        restore_comm_ef(engine, ckpt_dir)

    logger.info(f"[deepspeed_tpu] loaded checkpoint {tag} from {load_dir} "
                f"(saved dp={meta['dp_world_size']}, current dp={engine.dp_size})")
    return ckpt_dir, meta.get("client_state", {})
