"""Checkpoint save/load with DeepSpeed's tag/dir layout semantics.

Analog of ``deepspeed/runtime/engine.py:1149-1416``: a checkpoint directory contains a
``<tag>/`` subdir with ``mp_rank_00_model_states`` (module params + counters + lr/scaler
state) and, under ZeRO, per-DP-shard optimizer state files
``zero_pp_rank_{dp}_mp_rank_{mp}_optim_states`` whose shards can be merged and
re-partitioned when reloading under a different DP world size (elastic checkpoint,
reference stage2.py:1713-1779 / stage1.py:836-947). Arrays are stored as .npz; metadata as
JSON. ``latest`` file tracks the most recent tag (engine.py:1351-1353).

In the single-controller JAX runtime one process owns every shard, so "per-rank files"
are written by slicing the global arrays — the on-disk layout (one optim file per DP rank)
is preserved so multi-host loaders and the elastic merge path work identically.
"""

import json
import os
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import logger


def _path_key(path) -> str:
    """Canonical '/'-joined key for a tree path — the single definition every
    save/load layout (tree npz, per-rank shards, offload regions) keys by."""
    return "/".join(str(getattr(p, "key", getattr(p, "idx", getattr(p, "name", p))))
                    for p in path)


def _needs_allgather(leaf) -> bool:
    """Whether materializing ``leaf`` requires the collective gather. The decision
    derives from the sharding's PROCESS SPAN — a globally consistent property —
    not per-process addressability: an array placed on a subset of processes is
    fully addressable on its owner but not elsewhere, and an addressability-based
    rule would have the owner skip the allgather other processes join (deadlock)."""
    if not isinstance(leaf, jax.Array) or leaf.is_fully_replicated:
        return False
    span = {d.process_index for d in leaf.sharding.device_set}
    return len(span) > 1


def _leaf_to_host(leaf) -> np.ndarray:
    """Host copy of a (possibly multi-host sharded) array. Cross-process sharded
    leaves are gathered collectively — EVERY process must call this on the same
    leaves in the same order (save_checkpoint guarantees it)."""
    if _needs_allgather(leaf):
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(leaf, tiled=True))
    return np.asarray(jax.device_get(leaf))


def _flatten_with_paths(tree, materialize: bool = True) -> Dict[str, np.ndarray]:
    """``materialize=False`` (non-writer processes): join only the collective
    gathers that cross-process sharded leaves require — skip the redundant D2H of
    every addressable/replicated leaf (N-1 wasted full-model copies otherwise)."""
    flat = {}
    leaves_with_paths = jax.tree_util.tree_flatten_with_path(tree)[0]
    for path, leaf in leaves_with_paths:
        key = _path_key(path)
        if not materialize:
            if _needs_allgather(leaf):
                _leaf_to_host(leaf)  # collective participation only
            continue
        arr = _leaf_to_host(leaf)
        if arr.dtype not in (np.float32, np.float64, np.int32, np.int64, np.bool_,
                             np.uint32, np.uint8, np.int8, np.float16):
            # npz can't natively store ml_dtypes (bfloat16 et al.); widen losslessly.
            arr = arr.astype(np.float32)
        flat[key] = arr
    return flat


def _unflatten_like(template, flat: Dict[str, np.ndarray], numpy: bool = False):
    """``numpy=True`` keeps leaves as host arrays — required for the offload path,
    whose fp32 master+moments may not fit on device at all."""
    paths, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, leaf in paths:
        key = _path_key(path)
        if key not in flat:
            raise KeyError(f"checkpoint missing array {key!r}")
        arr = flat[key]
        if numpy:
            leaves.append(np.asarray(arr, dtype=np.dtype(leaf.dtype)).reshape(leaf.shape))
        else:
            leaves.append(jnp.asarray(arr, dtype=leaf.dtype).reshape(leaf.shape))
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _load_tree_npz(path: str, template):
    with np.load(path) as data:
        flat = {k: data[k] for k in data.files}
    return _unflatten_like(template, flat)


def _ckpt_dir(save_dir: str, tag: str) -> str:
    return os.path.join(save_dir, str(tag))


def model_states_name(mp_rank: int = 0) -> str:
    return f"mp_rank_{mp_rank:02d}_model_states"


def optim_states_name(dp_rank: int, mp_rank: int = 0) -> str:
    return f"zero_pp_rank_{dp_rank}_mp_rank_{mp_rank:02d}_optim_states"


def offload_states_name(proc: int) -> str:
    return f"zero_offload_proc_{proc}_optim_states"


def _offload_leaf_keys(off):
    """Leaf path keys in tree_flatten order for the offload class's param tree."""
    skeleton = jax.tree_util.tree_unflatten(off._treedef,
                                            [np.zeros(0)] * len(off._shapes))
    return [_path_key(path)
            for path, _ in jax.tree_util.tree_flatten_with_path(skeleton)[0]]


def _save_offload_regions(engine, ckpt_dir: str):
    """Per-PROCESS region files for the host-tier state (multi-host safe).

    Each process writes only the master/moment regions its devices own
    (``zero_offload_proc_N``); a manifest records leaf shapes and every region's
    slice so any topology can reassemble full leaves on load — the region-wise
    analog of the reference's per-rank ``zero_pp_rank_N`` files."""
    off = engine._offload
    proc = jax.process_index()
    keys = _offload_leaf_keys(off)
    shard = {}
    regions_meta = []
    for li, regions in enumerate(off._leaf_regions):
        for r in regions:
            tag = f"r{li}_{r.offset}"
            for prefix, buf in (("master", off.fp32), ("exp_avg", off.exp_avg),
                                ("exp_avg_sq", off.exp_avg_sq)):
                shard[f"{prefix}/{tag}"] = buf[r.offset:r.offset + r.size]
            regions_meta.append({"tag": tag, "leaf": li,
                                 "starts": [sl.start for sl in r.slices],
                                 "stops": [sl.stop for sl in r.slices]})
    np.savez(os.path.join(ckpt_dir, offload_states_name(proc) + ".npz"), **shard)
    # one manifest per process: concurrent writers never touch the same file
    with open(os.path.join(ckpt_dir, f"offload_manifest_{proc}.json"), "w") as f:
        json.dump({"n_procs": jax.process_count(), "proc": proc,
                   "leaves": [{"key": k, "shape": list(shp)}
                              for k, shp in zip(keys, off._shapes)],
                   "regions": regions_meta}, f)


def _save_barrier():
    """Rendezvous across hosts: save_checkpoint returns only after EVERY process's
    files are on disk (an immediate load may otherwise race another host's writes)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("deepspeed_tpu_checkpoint_save")


def _offload_manifests(ckpt_dir: str):
    import glob
    return sorted(glob.glob(os.path.join(ckpt_dir, "offload_manifest_*.json")))


def _load_offload_regions(ckpt_dir: str):
    """Reassemble full master/exp_avg/exp_avg_sq flat dicts (key -> full array) from
    the per-process region files. Topology-agnostic: works for any current dp."""
    out = None
    seen_procs = set()
    n_procs_seen = set()
    for mpath in _offload_manifests(ckpt_dir):
        with open(mpath) as f:
            manifest = json.load(f)
        leaves = manifest["leaves"]
        seen_procs.add(manifest["proc"])
        n_procs_seen.add(manifest["n_procs"])
        if out is None:
            out = {prefix: {l["key"]: np.zeros(l["shape"], np.float32) for l in leaves}
                   for prefix in ("master", "exp_avg", "exp_avg_sq")}
        path = os.path.join(ckpt_dir, offload_states_name(manifest["proc"]) + ".npz")
        with np.load(path) as data:
            for r in manifest["regions"]:
                leaf = leaves[r["leaf"]]
                slices = tuple(slice(a, b) for a, b in zip(r["starts"], r["stops"]))
                shape = tuple(b - a for a, b in zip(r["starts"], r["stops"]))
                for prefix in ("master", "exp_avg", "exp_avg_sq"):
                    out[prefix][leaf["key"]][slices] = \
                        data[f"{prefix}/{r['tag']}"].reshape(shape)
    assert out is not None, "no offload manifests found"
    if len(n_procs_seen) != 1 or seen_procs != set(range(next(iter(n_procs_seen)))):
        # partial saves AND stale manifests from an older topology in a reused tag
        # dir must fail loud, not merge into (or zero out) the restored state
        raise RuntimeError(
            f"offload checkpoint is inconsistent: manifests for processes "
            f"{sorted(seen_procs)} with recorded world sizes {sorted(n_procs_seen)}")
    return out["master"], out["exp_avg"], out["exp_avg_sq"]


def _scatter_offload_regions(ckpt_dir: str, off) -> bool:
    """Same-topology fast path: copy saved regions straight into the LOCAL offload
    buffers without materializing full trees (each host allocates only its partition
    — full-tree reassembly of a multi-B model would 3x-overshoot a host sized for the
    partitioned steady state). Returns False when the topology changed (any local
    region unmatched) — caller falls back to full reassembly."""
    local = {}
    for li, regions in enumerate(off._leaf_regions):
        for r in regions:
            key = (li, tuple(sl.start for sl in r.slices),
                   tuple(sl.stop for sl in r.slices))
            local[key] = r
    bufs = {"master": off.fp32, "exp_avg": off.exp_avg, "exp_avg_sq": off.exp_avg_sq}
    matched = set()
    for mpath in _offload_manifests(ckpt_dir):
        with open(mpath) as f:
            manifest = json.load(f)
        if len(manifest["leaves"]) != len(off._shapes) or any(
                tuple(l["shape"]) != tuple(shp)
                for l, shp in zip(manifest["leaves"], off._shapes)):
            return False  # different model/tree
        hits = []
        for r in manifest["regions"]:
            key = (r["leaf"], tuple(r["starts"]), tuple(r["stops"]))
            if key in local:
                hits.append((r, local[key]))
        if not hits:
            continue
        path = os.path.join(ckpt_dir, offload_states_name(manifest["proc"]) + ".npz")
        with np.load(path) as data:
            for saved, lr in hits:
                for prefix, buf in bufs.items():
                    buf[lr.offset:lr.offset + lr.size] = \
                        data[f"{prefix}/{saved['tag']}"]
                matched.add((saved["leaf"], tuple(saved["starts"]),
                             tuple(saved["stops"])))
    return matched == set(local.keys())


def save_checkpoint(engine, save_dir: str, tag: Optional[str] = None, client_state: Dict = {},
                    save_latest: bool = True):
    if tag is None:
        tag = f"global_step{engine.global_steps}"
    ckpt_dir = _ckpt_dir(save_dir, tag)
    os.makedirs(ckpt_dir, exist_ok=True)
    offload = getattr(engine, "_offload", None)

    if offload is not None:
        # host-tier state: each process writes its own regions (multi-host safe)
        _save_offload_regions(engine, ckpt_dir)
        if jax.process_index() == 0:
            # a reused tag dir may hold files from an older, larger topology;
            # current writers only touch indices < process_count, so this is safe
            import glob as _glob
            for stale in _glob.glob(os.path.join(ckpt_dir, "offload_manifest_*.json")):
                idx = int(stale.rsplit("_", 1)[1].split(".")[0])
                if idx >= jax.process_count():
                    os.remove(stale)
                    npz = os.path.join(ckpt_dir, offload_states_name(idx) + ".npz")
                    if os.path.isfile(npz):
                        os.remove(npz)
    # Multi-host: the model-states/scaler/optim-shard/latest files are shared paths —
    # exactly one WRITER (process 0), or concurrent identical-path np.savez calls
    # corrupt the archives. But cross-process sharded state (ZeRO masters, a
    # pipe-sharded wte) needs a collective gather that EVERY process participates in,
    # so ALL processes run every flatten below (offload included — no early return
    # before the last flatten) and only the file writes are gated.
    writer = jax.process_index() == 0

    # --- model states (replicated compute params + host-side counters) ---
    # _ckpt_export: engines with a non-canonical runtime layout (SPMD pipeline's
    # pipe-stacked stages) serialize in the layer-keyed form so checkpoints stay
    # portable across stage counts / executor modes
    params_flat = _flatten_with_paths(engine._ckpt_export(engine.params, "params"),
                                      materialize=writer)
    if writer:
        np.savez(os.path.join(ckpt_dir, model_states_name() + ".npz"), **params_flat)
    meta = {
        "external_master": bool(getattr(engine, "_external_master", False)),
        "global_steps": engine.global_steps,
        "micro_steps": engine.micro_steps,
        "skipped_steps": engine.skipped_steps,
        "dp_world_size": engine.dp_size,
        "zero_stage": engine.zero_optimization_stage(),
        "optimizer_name": engine.optimizer.name,
        "param_groups": [
            {k: (list(v) if isinstance(v, tuple) else v) for k, v in g.items()}
            for g in engine.optimizer.param_groups
        ],
        "lr_scheduler": engine.lr_scheduler.state_dict() if engine.lr_scheduler is not None else None,
        "client_state": client_state,
    }
    if writer:
        with open(os.path.join(ckpt_dir, model_states_name() + ".json"), "w") as f:
            json.dump(meta, f)

    # --- scaler state ---
    scaler_flat = _flatten_with_paths(engine.scaler_state, materialize=writer)
    if writer:
        np.savez(os.path.join(ckpt_dir, "loss_scaler.npz"), **scaler_flat)

    if offload is None:
        # --- optimizer + master states, one file per DP rank (elastic layout) ---
        # external-master engines hold no master (it is byte-for-byte derivable as
        # the fp32 upcast of the saved params — writing it would triple the
        # checkpoint and materialize a full fp32 tree on device for nothing)
        dp = engine.dp_size
        if getattr(engine, "_external_master", False):
            master_flat = {}
        else:
            master_flat = _flatten_with_paths(
                engine._ckpt_export(engine.master_params, "master"), materialize=writer)
        opt_flat = _flatten_with_paths(engine._ckpt_export(engine.opt_state, "opt"),
                                       materialize=writer)
        if writer:
            for dp_rank in range(dp):
                shard = {}
                for prefix, flat in (("master", master_flat), ("opt", opt_flat)):
                    for key, arr in flat.items():
                        parts = np.array_split(arr.reshape(-1), dp)
                        shard[f"{prefix}/{key}"] = parts[dp_rank]
                np.savez(os.path.join(ckpt_dir, optim_states_name(dp_rank) + ".npz"),
                         **shard)
            # shape manifest for elastic restore
            shapes = {f"master/{k}": list(v.shape) for k, v in master_flat.items()}
            shapes.update({f"opt/{k}": list(v.shape) for k, v in opt_flat.items()})
            with open(os.path.join(ckpt_dir, "optim_shapes.json"), "w") as f:
                json.dump({"dp_world_size": dp, "shapes": shapes}, f)

    if save_latest and writer:
        with open(os.path.join(save_dir, "latest"), "w") as f:
            f.write(tag)
    _save_barrier()
    logger.info(f"[deepspeed_tpu] saved checkpoint {tag} to {save_dir}")
    return True


def _merge_elastic(ckpt_dir: str) -> Dict[str, np.ndarray]:
    """Merge per-DP-rank optim shards back into full flat arrays (any saved dp size)."""
    with open(os.path.join(ckpt_dir, "optim_shapes.json")) as f:
        manifest = json.load(f)
    saved_dp = manifest["dp_world_size"]
    shapes = manifest["shapes"]
    merged: Dict[str, List[np.ndarray]] = {k: [None] * saved_dp for k in shapes}
    for dp_rank in range(saved_dp):
        path = os.path.join(ckpt_dir, optim_states_name(dp_rank) + ".npz")
        with np.load(path) as data:
            for key in data.files:
                merged[key][dp_rank] = data[key]
    out = {}
    for key, chunks in merged.items():
        flat = np.concatenate(chunks)
        out[key] = flat.reshape(shapes[key])
    return out


def load_checkpoint(engine, load_dir: str, tag: Optional[str] = None,
                    load_optimizer_states: bool = True, load_lr_scheduler_states: bool = True):
    if tag is None:
        latest_path = os.path.join(load_dir, "latest")
        if os.path.isfile(latest_path):
            with open(latest_path) as f:
                tag = f.read().strip()
        else:
            logger.warning(f"Unable to find latest file at {latest_path}, "
                           "if trying to load latest checkpoint please pass a valid tag.")
            return None, {}
    ckpt_dir = _ckpt_dir(load_dir, tag)
    if not os.path.isdir(ckpt_dir):
        logger.warning(f"Client provided checkpoint tag {tag} does not exist in {load_dir}")
        return None, {}

    with open(os.path.join(ckpt_dir, model_states_name() + ".json")) as f:
        meta = json.load(f)

    params = _load_tree_npz(os.path.join(ckpt_dir, model_states_name() + ".npz"),
                            engine._ckpt_export(engine.params, "params"))
    engine.params = jax.device_put(engine._ckpt_import(params, "params"),
                                   engine._param_shardings)

    engine.global_steps = meta["global_steps"]
    engine.micro_steps = meta["micro_steps"]
    engine.skipped_steps = meta["skipped_steps"]
    for g, src in zip(engine.optimizer.param_groups, meta.get("param_groups", [])):
        src = dict(src)
        if "betas" in src and isinstance(src["betas"], list):
            src["betas"] = tuple(src["betas"])
        g.update(src)
    if load_lr_scheduler_states and engine.lr_scheduler is not None and meta.get("lr_scheduler"):
        engine.lr_scheduler.load_state_dict(meta["lr_scheduler"])

    engine.scaler_state = _load_tree_npz(os.path.join(ckpt_dir, "loss_scaler.npz"), engine.scaler_state)

    if load_optimizer_states:
        offload = getattr(engine, "_offload", None)
        has_region_layout = bool(_offload_manifests(ckpt_dir))

        def offload_template():
            # leaf-shaped numpy skeleton: avoids assembling engine.master_params
            # (impossible on a multi-host offload engine, whose buffers are partial)
            return jax.tree_util.tree_unflatten(
                offload._treedef, [np.zeros(shp, np.float32) for shp in offload._shapes])

        if has_region_layout:
            if offload is not None and _scatter_offload_regions(ckpt_dir, offload):
                pass  # same topology: regions copied straight into the local buffers
            elif offload is not None:
                # topology changed: reassemble full leaves, then scatter locally
                master_flat, ea_flat, eas_flat = _load_offload_regions(ckpt_dir)
                t = offload_template()
                offload.load_trees(_unflatten_like(t, master_flat, numpy=True),
                                   _unflatten_like(t, ea_flat, numpy=True),
                                   _unflatten_like(t, eas_flat, numpy=True))
            else:
                master_flat, ea_flat, eas_flat = _load_offload_regions(ckpt_dir)
                if not getattr(engine, "_external_master", False):
                    master = _unflatten_like(
                        engine._ckpt_export(engine.master_params, "master"), master_flat)
                    engine.master_params = engine._place_master(
                        engine._ckpt_import(master, "master"))
                opt_flat = {f"exp_avg/{k}": v for k, v in ea_flat.items()}
                opt_flat.update({f"exp_avg_sq/{k}": v for k, v in eas_flat.items()})
                opt = _unflatten_like(engine._ckpt_export(engine.opt_state, "opt"), opt_flat)
                engine.opt_state = jax.device_put(
                    engine._ckpt_import(opt, "opt"), engine._opt_shardings)
        else:
            merged = _merge_elastic(ckpt_dir)
            master_flat = {k[len("master/"):]: v for k, v in merged.items() if k.startswith("master/")}
            opt_flat = {k[len("opt/"):]: v for k, v in merged.items() if k.startswith("opt/")}
            if hasattr(engine, "_onebit") and meta["dp_world_size"] != engine.dp_size:
                # OneBitAdam state sizes are dp-dependent (padded moments, per-worker
                # error buffers); adapt them instead of failing the reshape below.
                # (1-bit Adam requires replicated params, so no _ckpt_export needed.)
                opt_flat = engine._onebit.elastic_adapt(opt_flat, _flatten_with_paths(engine.opt_state))
            if offload is not None:
                # host-tier state: unflatten on the host and copy into the flat offload
                # buffers — never materialize master/moments on device
                t = offload_template()
                ea = {k[len("exp_avg/"):]: v for k, v in opt_flat.items()
                      if k.startswith("exp_avg/")}
                eas = {k[len("exp_avg_sq/"):]: v for k, v in opt_flat.items()
                       if k.startswith("exp_avg_sq/")}
                offload.load_trees(_unflatten_like(t, master_flat, numpy=True),
                                   _unflatten_like(t, ea, numpy=True),
                                   _unflatten_like(t, eas, numpy=True))
            else:
                if getattr(engine, "_external_master", False):
                    pass  # no master storage; the view re-derives from params
                elif master_flat:
                    master = _unflatten_like(
                        engine._ckpt_export(engine.master_params, "master"), master_flat)
                    engine.master_params = engine._place_master(
                        engine._ckpt_import(master, "master"))
                else:
                    # an external-master checkpoint loaded into a standard engine:
                    # the master is BY DEFINITION the fp32 upcast of the restored
                    # params (that is why it was not written)
                    engine.master_params = jax.device_put(
                        jax.tree_util.tree_map(lambda p: jnp.asarray(p, jnp.float32),
                                               engine.params),
                        engine._master_shardings)
                opt = _unflatten_like(engine._ckpt_export(engine.opt_state, "opt"), opt_flat)
                engine.opt_state = jax.device_put(
                    engine._ckpt_import(opt, "opt"), engine._opt_shardings)
    else:
        # re-derive master from loaded params (fp16-derived restore, stage2.py:1781-1836)
        if getattr(engine, "_offload", None) is not None:
            engine._offload.load_trees(master_tree=engine.params)
        else:
            engine.master_params = engine._place_master(
                jax.tree_util.tree_map(lambda p: jnp.asarray(p, jnp.float32), engine.params))

    logger.info(f"[deepspeed_tpu] loaded checkpoint {tag} from {load_dir} "
                f"(saved dp={meta['dp_world_size']}, current dp={engine.dp_size})")
    return ckpt_dir, meta.get("client_state", {})
