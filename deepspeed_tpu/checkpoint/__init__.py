from .checkpointing import save_checkpoint, load_checkpoint
