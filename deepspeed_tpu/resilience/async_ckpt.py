"""Async sharded checkpointing: snapshot on the step thread, commit off it.

The save is split exactly along the device/host boundary
(checkpoint/checkpointing.py):

- **snapshot** (``snapshot_checkpoint``) runs on the caller's thread — it is
  the device→host copy plus any multi-host collective gathers, and it is the
  ONLY part that must see a consistent device state. The step programs donate
  their buffers, but ``jax.device_get`` materializes host copies before the
  next step's donation can retire them, so the snapshot needs no fence: the
  exposed cost is the D2H transfer, not a step-long stall.
- **commit** (``write_snapshot``) is pure host file I/O and runs on a
  background writer thread. The commit protocol (tmp dir → fsync → atomic
  rename → ``latest`` via ``os.replace``) means a crash at any point — the
  trainer's or the writer thread's — leaves either the previous committed
  checkpoint or an ignorable ``.tmp`` dir, never a loadable torn state.

One save may be in flight at a time: a new ``save()`` first joins the
previous writer (re-raising its failure rather than dropping it), so the
steady state is "training overlaps one background commit". Multi-host runs
degrade the COMMIT to the caller thread — ``write_snapshot``'s cross-process
barrier must not rendezvous from per-host daemon threads — while keeping the
same two-phase structure and crash-safety via the manifest-last ordering.
"""

import threading
import time

import jax

from ..checkpoint.checkpointing import snapshot_checkpoint, write_snapshot
from ..utils import logger


class AsyncCheckpointer:
    """Owns the background writer for one engine. ``last_stall_ms`` is the
    caller-visible cost of the most recent ``save()`` (snapshot + join of the
    previous writer) — the number bench.py reports as ``checkpoint_stall_ms``."""

    def __init__(self, engine, save_dir: str, save_latest: bool = True,
                 fence_delay_s: float = 0.0):
        self.engine = engine
        self.save_dir = save_dir
        self.save_latest = save_latest
        self._thread = None
        self._error = None
        self.last_stall_ms = 0.0
        self.saves_started = 0
        self.saves_committed = 0
        # fault-injection hook (ds-tpu crash-sim goodput attribution): a known
        # extra stall inside the snapshot fence, so the run ledger's
        # checkpoint_stall attribution can be checked against ground truth
        self.fence_delay_s = float(fence_delay_s)

    def _commit(self, snapshot):
        try:
            write_snapshot(snapshot, self.save_dir,
                           save_latest=self.save_latest)
            self.saves_committed += 1
            logger.info(f"[deepspeed_tpu] async checkpoint {snapshot['tag']} "
                        f"committed to {self.save_dir}")
        except BaseException as e:   # surfaced by the next save()/wait()
            self._error = e

    def save(self, tag=None, client_state={}):
        """Snapshot now, commit in the background. Blocks only for the
        device→host copy (and any previous still-running commit)."""
        t0 = time.perf_counter()
        self.wait()
        if self.fence_delay_s > 0.0:
            time.sleep(self.fence_delay_s)
        snapshot = snapshot_checkpoint(self.engine, tag=tag,
                                       client_state=client_state)
        self.saves_started += 1
        if snapshot["single_process"]:
            self._thread = threading.Thread(
                target=self._commit, args=(snapshot,),
                name="ds-tpu-ckpt-writer", daemon=True)
            self._thread.start()
        else:
            # multi-host: the commit's cross-process barrier must run on the
            # thread every process drives in lockstep
            self._commit(snapshot)
        self.last_stall_ms = (time.perf_counter() - t0) * 1000.0
        return snapshot["tag"]

    def wait(self):
        """Join the in-flight commit (if any); re-raise its failure."""
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise err
