"""Serving warm restart: checkpoint/restore of a paged-serving replica.

A preempted serving replica loses host state (scheduler ledger, allocator
free list, prefix-cache index) and device state (the paged KV pool). The
expensive part to rebuild is the pool: every cached prompt page holds KV a
cold replica must re-prefill. This module persists BOTH halves with the same
commit protocol the trainer checkpoints use (tmp dir → fsync → atomic rename
+ integrity manifest — checkpoint/checkpointing.py), so a warm restart:

- verifies the manifest and REFUSES a torn snapshot (never loads it);
- validates the engine geometry (``InferenceEngine.geometry``) and refuses a
  snapshot from a differently-shaped replica (page indices and pool bytes
  are meaningless under another layout);
- restores the pool bytes, allocator ledger (free-list/cached-tier ORDER
  included — allocation determinism depends on it), prefix-cache index, and
  requeued in-flight requests — which then rejoin through the PR 12 prefix
  machinery: parked prompt pages remap into their new tables instead of
  re-prefilling, which is exactly what makes the restart *warm* (crash-sim
  asserts strictly fewer prefill chunks than a cold start, token-identical
  outputs).

The snapshot itself quiesces the scheduler (``Scheduler.quiesce``): every
running group is preempted — its prefill frontier registers in the prefix
cache and its requests requeue at their original positions — leaving a
ledger with no live Group objects to serialize.
"""

import json
import os
import shutil

import numpy as np

from ..checkpoint.checkpointing import (TMP_SUFFIX, _fsync_dir,
                                        verify_checkpoint, write_manifest)
from ..utils import logger

STATE_JSON = "serve_state.json"
POOL_NPZ = "serve_pool.npz"


def server_state_dict(engine) -> dict:
    """Snapshot a serving engine (quiesces it). Alias for
    ``InferenceEngine.state_dict`` so callers can stay serve-agnostic."""
    return engine.state_dict()


def save_server(engine, save_dir: str, tag: str = "serve") -> str:
    """Snapshot ``engine`` and commit it under ``save_dir/tag/`` atomically.
    Returns the committed directory path."""
    state = server_state_dict(engine)
    # npz can't round-trip ml_dtypes pools (bfloat16 loads back as raw V2);
    # store widened to float32 — exact for every serving compute dtype, a
    # no-op for float32 pools — and let load_state_dict cast back down
    k_pool = np.asarray(state.pop("k_pool"), np.float32)
    v_pool = np.asarray(state.pop("v_pool"), np.float32)
    final_dir = os.path.join(save_dir, tag)
    tmp_dir = final_dir + TMP_SUFFIX
    if os.path.isdir(tmp_dir):
        shutil.rmtree(tmp_dir)
    os.makedirs(tmp_dir)
    with open(os.path.join(tmp_dir, POOL_NPZ), "wb") as f:
        np.savez(f, k_pool=k_pool, v_pool=v_pool)
        f.flush()
        os.fsync(f.fileno())
    with open(os.path.join(tmp_dir, STATE_JSON), "w") as f:
        json.dump(state, f)
        f.flush()
        os.fsync(f.fileno())
    write_manifest(tmp_dir, extra={"kind": "serve", "tag": tag,
                                   "it": int(state["it"])})
    _fsync_dir(tmp_dir)
    if os.path.isdir(final_dir):
        shutil.rmtree(final_dir)
    os.rename(tmp_dir, final_dir)
    _fsync_dir(save_dir)
    logger.info(f"[deepspeed_tpu] serving snapshot committed to {final_dir}")
    return final_dir


def load_server_state(ckpt_dir: str):
    """Read a committed serving snapshot back into a ``state_dict``-shaped
    dict, or None for a missing/torn snapshot (refused, never loaded)."""
    ok, reason = verify_checkpoint(ckpt_dir)
    if not ok:
        logger.warning(f"[deepspeed_tpu] REFUSING serving snapshot "
                       f"{ckpt_dir}: {reason}")
        return None
    try:
        with open(os.path.join(ckpt_dir, STATE_JSON)) as f:
            state = json.load(f)
        with np.load(os.path.join(ckpt_dir, POOL_NPZ)) as data:
            state["k_pool"] = data["k_pool"]
            state["v_pool"] = data["v_pool"]
    except (OSError, ValueError, KeyError) as e:
        logger.warning(f"[deepspeed_tpu] REFUSING serving snapshot "
                       f"{ckpt_dir}: unreadable ({e})")
        return None
    return state


def restore_server(engine, ckpt_dir: str) -> bool:
    """Load a committed snapshot into ``engine``. Returns False when the
    snapshot is missing/torn (caller starts cold); raises ValueError on a
    geometry mismatch (restarting into the wrong shape is a config bug, not
    a recoverable condition)."""
    state = load_server_state(ckpt_dir)
    if state is None:
        return False
    engine.load_state_dict(state)
    logger.info(f"[deepspeed_tpu] serving replica rejoined warm from "
                f"{ckpt_dir} (it={engine._it}, "
                f"{len(engine.scheduler.waiting)} requests requeued)")
    return True


def failover_server(engine, build_replacement, save_dir: str,
                    tag: str = "serve"):
    """Fleet warm failover: snapshot ``engine`` (quiescing it — in-flight
    prefill frontiers park in the prefix cache), build a replacement replica
    via ``build_replacement()``, and restore the snapshot into it, so the
    successor rejoins with the KV pool and requeued requests intact. Returns
    the restored replacement. Raises RuntimeError if the just-written
    snapshot is refused (torn mid-failover means the host is failing, not
    the request stream — the router must not silently drop work)."""
    ckpt_dir = save_server(engine, save_dir, tag=tag)
    replacement = build_replacement()
    if not restore_server(replacement, ckpt_dir):
        raise RuntimeError(
            f"fleet failover: snapshot {ckpt_dir} refused immediately after "
            "commit — aborting instead of dropping in-flight requests")
    return replacement
