"""Topology-changing restore of the engine-held compressed-comm EF buffers.

The engine's bucketed-overlap compressed exchange (runtime/engine.py) holds
its error feedback as two ``(dp, cols)`` buffers whose column layout is the
per-bucket chunks laid back to back — a function of the bucket plan (leaf
partition), dp, and the topology's slice factor. A restore under a different
dp cannot just reshape: each bucket's chunk→global-offset map changes with
(dp, slice_size), exactly the problem ``OneBitAdam.elastic_adapt`` already
solves for the monolithic optimizer-held buffers. This module generalizes
that remap to the per-bucket layout:

- the saved geometry (``comm_ef.json``, written by
  ``checkpoint.comm_ef_geometry``) is validated against a replay of the LIVE
  engine's bucket plan — same layout kind, same bucket count, same per-bucket
  leaf ``sizes``/``n``. Anything else (different bucket_bytes, different
  model, a monolithic↔bucketed flip) is REFUSED with ``ValueError`` instead
  of silently corrupting the residuals;
- a validated geometry with a different (dp, slice_size) is remapped
  bucket-by-bucket with OneBitAdam's math: ``server_error`` by exact index
  permutation (bit-identical on every real-data element), ``worker_error``
  by the f64 slice-mean re-placement (mean-preserving — the strongest
  invariant a topology change admits, see ops/onebit_adam.py:207).
"""

import json
import os

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..ops.onebit_adam import OneBitAdam
from ..parallel.mesh import DATA_AXIS
from ..utils import logger


def _geometry_blocks(geo):
    """Per-bucket ``(n_pad, we_cols, se_cols)`` column spans of a saved/live
    EF geometry — one block for the monolithic layout."""
    dp, L = geo["dp"], geo["slice_size"]
    if geo["layout"] == "bucketed":
        return [(b["n_pad"], b["n_pad"] // L, b["n_pad"] // dp)
                for b in geo["buckets"]]
    return [(geo["n_pad"], geo["n_pad"] // L, geo["n_pad"] // dp)]


def _validate_remappable(saved, live):
    """Raise ValueError unless ``saved`` EF state can be carried into the
    ``live`` layout. The per-bucket leaf sizes pin the chunk boundaries the
    residuals were accumulated under — only (dp, slice_size) may differ."""
    if saved["layout"] != live["layout"]:
        raise ValueError(
            f"checkpointed comm EF layout {saved['layout']!r} cannot restore "
            f"into a {live['layout']!r} engine — the residual chunking "
            f"differs structurally; refusing rather than corrupting")
    if saved["layout"] == "bucketed":
        s_b, l_b = saved["buckets"], live["buckets"]
        if len(s_b) != len(l_b) or any(
                tuple(a["sizes"]) != tuple(b["sizes"]) or a["n"] != b["n"]
                for a, b in zip(s_b, l_b)):
            raise ValueError(
                f"checkpointed comm EF bucket plan ({len(s_b)} buckets) does "
                f"not replay under the live engine ({len(l_b)} buckets) — "
                f"bucket_bytes or the parameter tree changed; refusing "
                f"rather than corrupting")
    elif saved["n"] != live["n"]:
        raise ValueError(
            f"checkpointed comm EF covers {saved['n']} elements but the live "
            f"parameter tree has {live['n']} — refusing rather than "
            f"corrupting")


def remap_ef_block(we, se, dp_o, L_o, np_o, dp_n, L_n, np_n):
    """Remap one contiguous EF block (one bucket, or the monolithic whole)
    from geometry (dp_o, L_o, np_o) to (dp_n, L_n, np_n). Same math as
    ``OneBitAdam.elastic_adapt``'s per-kind branches."""
    keep = min(np_o, np_n)
    # server: the dp sub-chunks tile the padded vector exactly — permutation
    g = np.zeros(np_o, np.float32)
    cs_o = np_o // dp_o
    for d, off in enumerate(OneBitAdam._server_offsets(dp_o, L_o, np_o)):
        g[off:off + cs_o] = np.asarray(se)[d]
    g_new = np.zeros(np_n, np.float32)
    g_new[:keep] = g[:keep]
    cs_n = np_n // dp_n
    se_new = np.stack([g_new[off:off + cs_n]
                       for off in OneBitAdam._server_offsets(dp_n, L_n, np_n)])
    # worker: slice-sharers hold independent residuals; re-place their mean
    C_o = np_o // L_o
    gw = np.zeros(np_o, np.float64)
    w64 = np.asarray(we, np.float64)
    for l in range(L_o):
        gw[l * C_o:(l + 1) * C_o] = w64[l::L_o].mean(axis=0)
    gw_new = np.zeros(np_n, np.float64)
    gw_new[:keep] = gw[:keep]
    C_n = np_n // L_n
    we_new = np.stack([gw_new[(d % L_n) * C_n:(d % L_n + 1) * C_n]
                       for d in range(dp_n)]).astype(np.float32)
    return we_new, se_new


def restore_comm_ef(engine, ckpt_dir: str) -> bool:
    """Restore (or elastically remap) the engine's ``_comm_we``/``_comm_se``
    from a checkpoint dir. Returns True when the buffers were restored; False
    for a pre-resilience checkpoint that never saved them (the engine keeps
    its zero-initialized buffers — the reference's lazy-reallocation trade)."""
    from ..checkpoint.checkpointing import comm_ef_geometry
    live = comm_ef_geometry(engine)
    if live is None:
        return False
    npz_path = os.path.join(ckpt_dir, "comm_ef.npz")
    json_path = os.path.join(ckpt_dir, "comm_ef.json")
    if not (os.path.isfile(npz_path) and os.path.isfile(json_path)):
        logger.warning("[deepspeed_tpu] checkpoint holds no comm EF state "
                       "(pre-resilience save) — compression restarts with "
                       "zero residuals")
        return False
    with open(json_path) as f:
        saved = json.load(f)
    with np.load(npz_path) as data:
        we_s = data["worker_error"]
        se_s = data["server_error"]

    sharding = NamedSharding(engine.mesh, P(DATA_AXIS, None))
    if saved == live:
        # identical geometry: bit-identical passthrough
        engine._comm_we = jax.device_put(jnp.asarray(we_s, jnp.float32), sharding)
        engine._comm_se = jax.device_put(jnp.asarray(se_s, jnp.float32), sharding)
        return True

    _validate_remappable(saved, live)
    dp_o, L_o = saved["dp"], saved["slice_size"]
    dp_n, L_n = live["dp"], live["slice_size"]
    we_parts, se_parts = [], []
    wo = so = 0
    for (np_o, wc_o, sc_o), (np_n, _, _) in zip(_geometry_blocks(saved),
                                                _geometry_blocks(live)):
        we_b, se_b = remap_ef_block(we_s[:, wo:wo + wc_o],
                                    se_s[:, so:so + sc_o],
                                    dp_o, L_o, np_o, dp_n, L_n, np_n)
        we_parts.append(we_b)
        se_parts.append(se_b)
        wo += wc_o
        so += sc_o
    engine._comm_we = jax.device_put(
        jnp.asarray(np.concatenate(we_parts, axis=1), jnp.float32), sharding)
    engine._comm_se = jax.device_put(
        jnp.asarray(np.concatenate(se_parts, axis=1), jnp.float32), sharding)
    logger.info(f"[deepspeed_tpu] remapped comm EF state dp={dp_o} "
                f"slice={L_o} -> dp={dp_n} slice={L_n}")
    return True
