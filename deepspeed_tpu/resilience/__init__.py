"""Resilience layer: async sharded checkpointing, elastic restart, auto-resume,
serving warm restart, and the crash-sim fault-injection harness.

The pieces this wires together already exist in-repo — the donation lint
proves no shadow copies race a snapshot, ``OneBitAdam.elastic_adapt`` remaps
error-feedback buffers across a dp resize, and the flight recorder knows the
first bad step and the journaled loss scale. This package turns them into one
survivability story (docs/resilience.md):

- ``async_ckpt``:  two-phase save — device→host snapshot on the step thread,
  commit-protocol file writes on a background writer thread.
- ``elastic``:     topology-changing restore of the engine-held compressed-comm
  error-feedback buffers (monolithic AND PR 11 bucketed layouts), with a
  geometry-validation pass that refuses mismatched layouts.
- ``auto_resume``: pick the newest committed checkpoint *before* the flight
  recorder's first bad step; restore the journaled loss scale.
- ``serve_restart``: checkpoint/restore a serving replica's paged KV pool,
  allocator, prefix-cache index, and scheduler ledger for warm rejoin.
- ``crash_sim``:   kill/restart trainer and serve-sim runs at adversarial
  points and assert bit-exact or documented-tolerance recovery.
"""

from .async_ckpt import AsyncCheckpointer
from .auto_resume import auto_resume, find_resume_point
from .elastic import restore_comm_ef
from .serve_restart import (restore_server, save_server, server_state_dict,
                            load_server_state, failover_server)

__all__ = ["AsyncCheckpointer", "auto_resume", "find_resume_point",
           "restore_comm_ef", "restore_server", "save_server",
           "server_state_dict", "load_server_state", "failover_server"]
