"""Auto-resume: restart from the newest good state, not the newest state.

On a crash the flight recorder (utils/numerics.py) dumped a post-mortem that
knows two things a naive "load latest" restart does not:

- **the first bad step** — a checkpoint taken at or after it has already
  absorbed the anomaly (a nonfinite subtree, a desync), so resuming from it
  replays the failure. ``find_resume_point`` selects the newest COMMITTED
  checkpoint strictly before the first bad step (manifest-verified — torn
  saves are skipped, never loaded).
- **the journaled loss scale** — the scale trajectory around an overflow
  spiral ends far below the scale the pre-crash checkpoint recorded. Resuming
  with the checkpoint's (higher) scale re-runs the same overflow/backoff
  spiral, wasting the same steps again. ``auto_resume`` clamps the restored
  scale to the journal's final value, so recovery continues from where the
  backoff had actually converged.

With no dump present (clean preemption, not a numerics crash) every committed
checkpoint is eligible and the newest wins — plain warm restart.
"""

import json
import os

import jax.numpy as jnp

from ..checkpoint.checkpointing import (MANIFEST_NAME, TMP_SUFFIX,
                                        model_states_name, verify_checkpoint)
from ..utils import logger
from ..utils.numerics import scan_dump_dir


def _tag_step(ckpt_dir):
    """global_steps of a checkpoint dir, from the manifest meta (resilience
    saves) or the model-states meta (legacy saves). None when unreadable."""
    try:
        with open(os.path.join(ckpt_dir, MANIFEST_NAME)) as f:
            meta = json.load(f).get("meta", {})
        if "global_steps" in meta:
            return int(meta["global_steps"])
    except (OSError, ValueError):
        pass
    try:
        with open(os.path.join(ckpt_dir, model_states_name() + ".json")) as f:
            return int(json.load(f)["global_steps"])
    except (OSError, ValueError, KeyError):
        return None


def find_resume_point(save_dir, dump_dir=None):
    """Select the checkpoint a restart should load.

    Returns ``{"tag", "global_steps", "first_bad_step", "journal_scale"}`` or
    None when no committed checkpoint qualifies. ``journal_scale`` is the last
    loss scale the flight recorder journaled before the crash (None without a
    dump or an fp16 journal)."""
    first_bad = None
    journal_scale = None
    bundle = scan_dump_dir(dump_dir)
    if bundle is not None:
        first_bad = bundle.get("first_bad_step")
        if first_bad is None:
            for rec in bundle.get("steps", []):
                if rec.get("anomaly") or rec.get("overflow"):
                    first_bad = rec.get("step")
                    break
        traj = bundle.get("loss_scale_trajectory") or []
        if traj:
            journal_scale = float(traj[-1][1])

    best = None
    if os.path.isdir(save_dir):
        for name in sorted(os.listdir(save_dir)):
            ckpt_dir = os.path.join(save_dir, name)
            if name.endswith(TMP_SUFFIX) or not os.path.isdir(ckpt_dir):
                continue
            ok, reason = verify_checkpoint(ckpt_dir)
            if not ok:
                logger.warning(f"[deepspeed_tpu] auto-resume skipping torn "
                               f"checkpoint {name}: {reason}")
                continue
            step = _tag_step(ckpt_dir)
            if step is None:
                continue
            if first_bad is not None and step >= first_bad:
                continue  # taken at/after the anomaly: replays the failure
            if best is None or step > best["global_steps"]:
                best = {"tag": name, "global_steps": step}
    if best is None:
        return None
    best["first_bad_step"] = first_bad
    best["journal_scale"] = journal_scale
    return best


def auto_resume(engine, save_dir, dump_dir=None):
    """Load the resume point into ``engine``. Returns ``(ckpt_path,
    client_state, info)`` — ``(None, {}, None)`` when nothing qualifies (cold
    start). ``dump_dir`` defaults to the engine's flight-recorder dir."""
    if dump_dir is None and getattr(engine, "_numerics", None) is not None \
            and engine._numerics.recorder is not None:
        dump_dir = engine._numerics.recorder.dump_dir
    info = find_resume_point(save_dir, dump_dir)
    if info is None:
        logger.info(f"[deepspeed_tpu] auto-resume: no committed checkpoint "
                    f"before the first bad step in {save_dir}; cold start")
        return None, {}, None
    path, client_state = engine.load_checkpoint(save_dir, tag=info["tag"])
    if path is None:
        return None, {}, None
    scale = info["journal_scale"]
    if scale is not None and hasattr(engine, "scaler_state") \
            and engine.scaler_state is not None:
        ckpt_scale = float(engine.scaler_state.cur_scale)
        new_scale = min(ckpt_scale, scale)
        if new_scale != ckpt_scale:
            # don't replay the overflow spiral: continue from the backed-off
            # scale the journal had converged to when the run died
            engine.scaler_state = engine.scaler_state._replace(
                cur_scale=jnp.asarray(new_scale, jnp.float32))
            logger.info(f"[deepspeed_tpu] auto-resume: loss scale clamped "
                        f"{ckpt_scale} -> {new_scale} (journaled)")
        if getattr(engine, "_numerics", None) is not None \
                and engine._numerics.journal is not None:
            engine._numerics.journal.cur_scale = new_scale
    logger.info(f"[deepspeed_tpu] auto-resume: restored {info['tag']} "
                f"(step {info['global_steps']}, first bad step "
                f"{info['first_bad_step']})")
    return path, client_state, info
