"""``ds-tpu crash-sim`` — fault-injection harness for the resilience layer.

Kills and restarts trainer and serve-sim runs at adversarial points and
asserts recovery, in-process and deterministically (the transcript is a pure
function of the seed — ints/bools/strings only, no wall-clock, no floats —
so CI golden-pins it byte-identically, scripts/lint.sh):

- ``trainer_mid_save``      — die during a checkpoint COMMIT: the tmp dir is
  fully written but never renamed. Restart must ignore the ``.tmp`` carcass,
  resume from the previous committed tag, and retrain BIT-EQUAL to an
  uninterrupted oracle.
- ``trainer_between_shards`` — die between shard writes (simulated as a
  committed tag with one optimizer shard torn afterwards): the manifest
  checksum pass must refuse the tag, and auto-resume falls back to the older
  committed one. Bit-equal retrain again.
- ``trainer_auto_resume``   — a flight-recorder dump names the first bad
  step; auto-resume must select the newest checkpoint strictly BEFORE it,
  not the newest overall.
- ``serve_mid_decode``      — kill a serving replica mid-decode-step, warm
  restart from the serving snapshot: strictly fewer prefill chunks than a
  cold restart, token-identical outputs vs the uninterrupted oracle, and the
  request-trace waste identity (useful + replayed == scheduled) intact.
- ``serve_post_preempt``    — same assertions with the kill landing right
  after a pool-pressure preemption (the snapshot then carries parked prefix
  pages AND requeued carry state at once).
"""

import argparse
import json
import os
import shutil
import sys
import tempfile

import numpy as np

HIDDEN = 16
BATCH = 8
TRAIN_STEPS = 8
SAVE_STEP = 3
KILL_STEP = 5


class _MLP:
    """Two-layer MLP returning MSE loss (the unit-test fixture model)."""

    def __init__(self, hidden=HIDDEN):
        self.hidden = hidden

    def init(self, rng):
        import jax
        import jax.numpy as jnp
        k1, k2 = jax.random.split(rng)
        h = self.hidden
        return {"w1": jax.random.normal(k1, (h, h), jnp.float32) * 0.1,
                "b1": jnp.zeros((h,), jnp.float32),
                "w2": jax.random.normal(k2, (h, h), jnp.float32) * 0.1,
                "b2": jnp.zeros((h,), jnp.float32)}

    def apply(self, params, x, y):
        import jax.numpy as jnp
        h = jnp.tanh(x @ params["w1"] + params["b1"])
        out = h @ params["w2"] + params["b2"]
        return jnp.mean(jnp.square(out - y))


def _train_batches(n, seed):
    rng = np.random.default_rng(seed)
    w_true = np.random.default_rng(1234).normal(
        size=(HIDDEN, HIDDEN)).astype(np.float32) * 0.3
    out = []
    for _ in range(n):
        x = rng.normal(size=(BATCH, HIDDEN)).astype(np.float32)
        out.append((x, np.tanh(x @ w_true)))
    return out


def _make_trainer(init_seed):
    import jax

    import deepspeed_tpu
    model = _MLP()
    params = model.init(jax.random.PRNGKey(init_seed))
    cfg = {"train_batch_size": BATCH, "steps_per_print": 1 << 30,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, config_params=cfg)
    return engine


def _train(engine, batches):
    for x, y in batches:
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()


def _masters_bit_equal(a, b):
    import jax
    la = jax.tree_util.tree_leaves(jax.device_get(a))
    lb = jax.tree_util.tree_leaves(jax.device_get(b))
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb))


def _trainer_scenario(kill_point, seed, workdir):
    """One trainer kill/recover cycle. Returns the transcript record."""
    from ..checkpoint.checkpointing import (_write_payloads,
                                            snapshot_checkpoint)
    from .async_ckpt import AsyncCheckpointer
    from .auto_resume import auto_resume

    save_dir = os.path.join(workdir, f"trainer_{kill_point}")
    os.makedirs(save_dir, exist_ok=True)
    batches = _train_batches(TRAIN_STEPS, seed)

    oracle = _make_trainer(seed)
    _train(oracle, batches)

    victim = _make_trainer(seed)
    ck = AsyncCheckpointer(victim, save_dir)
    _train(victim, batches[:SAVE_STEP])
    ck.save(tag=f"step{SAVE_STEP}")
    _train(victim, batches[SAVE_STEP:KILL_STEP])

    if kill_point == "mid_save":
        # the commit dies after every payload is written but BEFORE the
        # atomic rename: a fully-populated .tmp carcass restore must ignore
        snap = snapshot_checkpoint(victim, tag=f"step{KILL_STEP}")
        tmp = os.path.join(save_dir, f"step{KILL_STEP}.tmp")
        os.makedirs(tmp, exist_ok=True)
        _write_payloads(tmp, snap["files"])
    else:  # between_shards: a committed tag torn afterwards (one shard
        # truncated) — the manifest checksum pass must refuse the whole tag
        ck.save(tag=f"step{KILL_STEP}")
        ck.wait()
        shard = os.path.join(save_dir, f"step{KILL_STEP}",
                             "zero_pp_rank_0_mp_rank_00_optim_states.npz")
        with open(shard, "r+b") as f:
            f.truncate(max(os.path.getsize(shard) // 2, 1))
    # the dead run's in-memory state is gone from here on

    restarted = _make_trainer(seed + 1000)  # different init: restore must win
    path, _, info = auto_resume(restarted, save_dir)
    resumed = path is not None and info is not None
    resumed_at_save_step = bool(
        resumed and info["global_steps"] == SAVE_STEP
        and restarted.global_steps == SAVE_STEP)
    _train(restarted, batches[SAVE_STEP:])
    bit_equal = _masters_bit_equal(oracle.master_params,
                                   restarted.master_params)
    return {"kill_point": kill_point, "resumed": bool(resumed),
            "resumed_at_step": int(info["global_steps"]) if resumed else -1,
            "resumed_at_save_step": resumed_at_save_step,
            "retrained_bit_equal": bool(bit_equal),
            "ok": bool(resumed_at_save_step and bit_equal)}


def _auto_resume_scenario(seed, workdir):
    """A flight-recorder dump pins the first bad step between two committed
    checkpoints: selection must take the OLDER one."""
    from .auto_resume import find_resume_point

    save_dir = os.path.join(workdir, "trainer_auto_resume")
    dump_dir = os.path.join(workdir, "dumps")
    os.makedirs(dump_dir, exist_ok=True)
    batches = _train_batches(TRAIN_STEPS, seed)
    engine = _make_trainer(seed)
    _train(engine, batches[:SAVE_STEP])
    engine.save_checkpoint(save_dir, tag=f"step{SAVE_STEP}")
    _train(engine, batches[SAVE_STEP:KILL_STEP])
    engine.save_checkpoint(save_dir, tag=f"step{KILL_STEP}")
    with open(os.path.join(dump_dir, "numerics_dump_host0_0.json"), "w") as f:
        json.dump({"first_bad_step": SAVE_STEP + 1,
                   "loss_scale_trajectory": [[SAVE_STEP, 1024.0],
                                             [SAVE_STEP + 1, 512.0]]}, f)
    info = find_resume_point(save_dir, dump_dir)
    picked_before_bad = bool(info is not None
                             and info["tag"] == f"step{SAVE_STEP}"
                             and info["first_bad_step"] == SAVE_STEP + 1)
    no_dump = find_resume_point(save_dir, None)
    newest_without_dump = bool(no_dump is not None
                               and no_dump["tag"] == f"step{KILL_STEP}")
    return {"picked_before_bad_step": picked_before_bad,
            "journal_scale_seen": bool(info is not None
                                       and info["journal_scale"] == 512.0),
            "newest_without_dump": newest_without_dump,
            "ok": bool(picked_before_bad and newest_without_dump)}


# ------------------------------------------------------------------ serving
SERVE_GEOM = dict(num_slots=4, block_size=8, max_model_len=64,
                  prefill_chunk=8)


def _make_server(seed, num_blocks):
    import jax
    import jax.numpy as jnp

    from ..models.gpt2 import GPT2Config, GPT2Model
    from ..serve.engine import InferenceEngine
    cfg = GPT2Config(vocab_size=64, n_positions=SERVE_GEOM["max_model_len"],
                     n_embd=32, n_layer=2, n_head=2,
                     compute_dtype=jnp.float32, loss_chunk=0)
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(seed))
    return InferenceEngine(
        model, params, num_blocks=num_blocks, prefix_cache=True,
        request_trace={"enabled": True, "capacity": 512}, **SERVE_GEOM)


def _serve_trace(seed, gen_lo=4, gen_hi=10):
    """Seeded greedy trace: shared 16-token system prefix (two full blocks —
    prefix-cache food), no EOS, so the schedule is independent of token
    VALUES and chunk counts are machine-independent."""
    from ..serve.scheduler import Request
    rng = np.random.RandomState(seed)
    shared = rng.randint(0, 64, size=16).tolist()
    reqs = []
    for i in range(6):
        tail = rng.randint(0, 64, size=int(rng.randint(6, 20))).tolist()
        reqs.append(Request(f"req{i:02d}", shared + tail,
                            int(rng.randint(gen_lo, gen_hi)), arrival=i))
    return reqs


def _drain(engine):
    logs = []
    guard = 0
    while not engine.scheduler.idle:
        if not engine.scheduler.running:
            na = engine.scheduler.next_arrival()
            if na is not None and na > engine._it:
                engine._it = na
        logs.append(engine.step())
        guard += 1
        if guard > 100000:
            raise RuntimeError("crash-sim serving loop failed to drain")
    return logs


def _prefill_chunks(logs):
    return sum(1 for l in logs if l.get("prefill") is not None)


def _serve_scenario(kill_point, seed, workdir):
    from ..serve.scheduler import pack_request, unpack_request
    from .serve_restart import restore_server, save_server

    # post_preempt needs pool pressure (tight pool + long generations so
    # concurrent decode demand outruns the free list); mid_decode wants a
    # roomy pool so the kill lands on plain decode progress
    if kill_point == "post_preempt":
        num_blocks, trace = 13, _serve_trace(seed, gen_lo=12, gen_hi=24)
    else:
        num_blocks, trace = 129, _serve_trace(seed)
    save_dir = os.path.join(workdir, f"serve_{kill_point}")

    oracle = _make_server(seed, num_blocks)
    oracle_out, _ = oracle.run([unpack_request(pack_request(r))
                                for r in trace])
    oracle_tokens = {o.req_id: list(o.tokens) for o in oracle_out
                     if o.status == "finished"}

    victim = _make_server(seed, num_blocks)
    for r in trace:
        victim.submit(unpack_request(pack_request(r)))
    # drive to the adversarial kill point (a pure function of the schedule)
    armed = False
    kill_it = -1
    guard = 0
    while not victim.scheduler.idle:
        log = victim.step()
        if kill_point == "mid_decode":
            armed = armed or bool(log["decode"])
        else:
            armed = armed or bool(log["preempted"])
        if armed:
            kill_it = log["it"]
            break
        guard += 1
        if guard > 100000:
            raise RuntimeError(f"crash-sim never reached {kill_point}")
    if not armed:  # trace drained before the adversarial point fired —
        # a silent pass here would test nothing, so refuse loudly
        raise RuntimeError(
            f"crash-sim trace drained without reaching {kill_point}")
    finished_at_kill = set(victim.outputs)
    snap_dir = save_server(victim, save_dir)
    # the dead replica's in-memory state is gone from here on

    warm = _make_server(seed, num_blocks)
    warm_ok = restore_server(warm, snap_dir)
    warm_logs = _drain(warm)
    warm_chunks = _prefill_chunks(warm_logs)
    warm_tokens = {o.req_id: list(o.tokens) for o in warm.outputs.values()
                   if o.status == "finished"}

    cold = _make_server(seed, num_blocks)
    pending = [r for r in trace if r.req_id not in finished_at_kill]
    cold_out, cold_logs = cold.run([unpack_request(pack_request(r))
                                    for r in pending])
    cold_chunks = _prefill_chunks(cold_logs)
    cold_tokens = {o.req_id: list(o.tokens) for o in cold_out
                   if o.status == "finished"}

    tokens_match_oracle = warm_tokens == oracle_tokens
    cold_match = all(cold_tokens.get(r.req_id) == oracle_tokens.get(r.req_id)
                     for r in pending)
    fewer_chunks = warm_chunks < cold_chunks
    ws = warm.tracer.waste_summary()
    waste_identity = (ws["useful_tokens"] + ws["replayed_tokens"]
                      == ws["scheduled_tokens"])
    return {"kill_point": kill_point, "kill_iteration": int(kill_it),
            "restored_warm": bool(warm_ok),
            "finished_before_kill": int(len(finished_at_kill)),
            "warm_prefill_chunks": int(warm_chunks),
            "cold_prefill_chunks": int(cold_chunks),
            "warm_fewer_chunks_than_cold": bool(fewer_chunks),
            "tokens_match_oracle": bool(tokens_match_oracle),
            "cold_tokens_match_oracle": bool(cold_match),
            "waste_identity_intact": bool(waste_identity),
            "ok": bool(warm_ok and fewer_chunks and tokens_match_oracle
                       and cold_match and waste_identity)}


# --------------------------------------------------- goodput attribution
# ``ds-tpu crash-sim --goodput``: every injected stall carries a known
# ground-truth duration, and the run-lifecycle goodput ledger
# (utils/goodput.py) must attribute it to the correct badput class within
# GOODPUT_REL_TOL relative tolerance. The transcript holds only booleans,
# ints, and the injected constants — never measured wall-clock — so CI
# byte-pins it (tests/unit/golden/goodput_attribution.json, scripts/lint.sh).

GOODPUT_REL_TOL = 0.10
FENCE_DELAY_S = 0.8     # injected checkpoint snapshot-fence stall, per save
REPLAY_STEP_S = 0.4     # injected per-step cost, so replay badput is known
HANG_STALL_S = 0.6      # injected stall under an armed hang watchdog
SKEW_MS = 80.0          # injected dispatch lag above the fleet median


def _within(attributed, truth, rel=GOODPUT_REL_TOL):
    """Ground-truth check: the ledger may bill the real (small) overhead on
    top of the injection, but never less than the injection and never more
    than ``rel`` above it."""
    return bool(truth <= attributed <= truth * (1.0 + rel))


def _partition_exact(ledger):
    """The taxonomy partition invariant: class seconds sum to the run wall."""
    return bool(abs(ledger.accounted_seconds() - ledger.wall_seconds()) < 0.01)


def _goodput_trainer(seed, ledger_dir, resilience, numerics=None):
    import jax

    import deepspeed_tpu
    model = _MLP()
    params = model.init(jax.random.PRNGKey(seed))
    cfg = {"train_batch_size": BATCH, "steps_per_print": 1 << 30,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
           "telemetry": {"enabled": True,
                         "goodput": {"enabled": True,
                                     "ledger_dir": ledger_dir}},
           "resilience": resilience}
    if numerics is not None:
        cfg["numerics"] = numerics
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, config_params=cfg)
    return engine


def _train_slow(engine, batches, sleep_s):
    """Drive steps whose wall-clock is dominated by a known injected sleep,
    so per-step badput has a ground truth independent of machine speed. The
    sleep sits AFTER the forward: the ledger's first step interval opens at
    the first forward dispatch (everything before it is init), so a sleep
    ahead of it would be billed to init, not the step."""
    import time as _time
    for x, y in batches:
        loss = engine(x, y)
        _time.sleep(sleep_s)
        engine.backward(loss)
        engine.step()


def _goodput_fence_scenario(seed, workdir):
    """Injected checkpoint fence: every periodic save sleeps FENCE_DELAY_S
    inside the snapshot fence (AsyncCheckpointer.fence_delay_s); the ledger
    must bill each to ``checkpoint_stall``, not the productive step."""
    save_dir = os.path.join(workdir, "gp_fence_ckpt")
    ledger_dir = os.path.join(workdir, "gp_fence_ledger")
    engine = _goodput_trainer(
        seed, ledger_dir,
        {"enabled": True, "save_dir": save_dir, "save_interval": SAVE_STEP})
    engine._resilience.fence_delay_s = FENCE_DELAY_S  # the fault injection
    _train(engine, _train_batches(TRAIN_STEPS, seed))
    engine._resilience.wait()
    led = engine._goodput
    led.finalize(persist=True)
    saves = int(engine._resilience.saves_started)   # steps 3 and 6 of 8
    truth = saves * FENCE_DELAY_S
    attributed = led.class_seconds["checkpoint_stall"]
    within = _within(attributed, truth)
    counted = led.checkpoint_stalls == saves
    return {"injected_class": "checkpoint_stall",
            "injected_s": truth, "saves": saves,
            "stalls_counted": bool(counted),
            "attributed_within_tolerance": within,
            "partition_exact": _partition_exact(led),
            "ok": bool(saves == 2 and counted and within
                       and _partition_exact(led))}


def _goodput_replay_scenario(seed, workdir):
    """Kill/restore replay: the victim dies after KILL_STEP with a committed
    checkpoint at SAVE_STEP and a flight-recorder dump whose span header
    prices its steps. The restarted engine re-runs steps SAVE_STEP+1..
    KILL_STEP — each carrying a known injected cost — and the ledger must
    bill exactly those to ``restart_replay``."""
    save_dir = os.path.join(workdir, "gp_replay_ckpt")
    dump_dir = os.path.join(workdir, "gp_replay_dumps")
    ledger_dir = os.path.join(workdir, "gp_replay_ledger")
    # async saves: the commit rides a background thread, so the victim's
    # dump span prices the steps themselves, not checkpoint file I/O
    resilience = {"enabled": True, "save_dir": save_dir,
                  "save_interval": SAVE_STEP, "auto_resume": True}
    numerics = {"enabled": True, "dump_dir": dump_dir}
    batches = _train_batches(TRAIN_STEPS, seed)

    victim = _goodput_trainer(seed, ledger_dir, resilience, numerics)
    _train_slow(victim, batches[:KILL_STEP], REPLAY_STEP_S)
    victim._resilience.wait()   # the kill must land AFTER the commit
    # clean preemption: dump the post-mortem (span header included), die
    victim._numerics.recorder.trigger("preempt", {"sim": "goodput"},
                                      quiet=True)

    restarted = _goodput_trainer(seed + 1000, ledger_dir, resilience,
                                 numerics)
    _train_slow(restarted, batches[SAVE_STEP:], REPLAY_STEP_S)
    led = restarted._goodput
    led.finalize(persist=True)

    expected_replay = KILL_STEP - SAVE_STEP
    truth = expected_replay * REPLAY_STEP_S
    attributed = led.class_seconds["restart_replay"]
    within = _within(attributed, truth)
    steps_match = led.replay_steps == expected_replay

    # offline pricing from the dump alone (satellite of the same taxonomy):
    # the victim's span header must reproduce the replay cost
    from ..utils.goodput import estimate_replay_seconds
    from ..utils.numerics import scan_dump_dir
    est_steps, est_s = estimate_replay_seconds(
        scan_dump_dir(dump_dir) or {}, SAVE_STEP)
    est_close = bool(truth > 0
                     and abs(est_s - truth) / truth <= 0.25)
    return {"injected_class": "restart_replay",
            "injected_s": truth, "replay_steps": expected_replay,
            "replay_steps_match": bool(steps_match),
            "attributed_within_tolerance": within,
            "offline_estimate_steps": int(est_steps),
            "offline_estimate_close": est_close,
            "partition_exact": _partition_exact(led),
            "ok": bool(steps_match and within
                       and est_steps == expected_replay and est_close
                       and _partition_exact(led))}


def _goodput_hang_scenario():
    """Watchdog hang: a step stalls HANG_STALL_S under an armed HangWatchdog
    with a much shorter deadline. The engine's billing rule — a step during
    which the watchdog fired bills its whole remainder to ``hang`` (a stalled
    step produced nothing) — must attribute the stall."""
    import time as _time

    from ..utils.cluster import HangWatchdog
    from ..utils.goodput import RunLedger

    led = RunLedger(run_id="gpattr", host=0)
    led.close("init")
    _time.sleep(0.05)
    led.close_step(1)                      # a healthy step first
    wd = HangWatchdog(deadline_s=0.2, signal_peers=False, poll_s=0.05,
                      run_id="gpattr")
    wd.arm(2)
    _time.sleep(HANG_STALL_S)              # the injected stall
    wd.disarm()
    fired = len(wd.fired) > 0
    led.close_step(2, hang=fired)          # the engine's rule, verbatim
    wd.stop()
    led.finalize(persist=False)
    attributed = led.class_seconds["hang"]
    within = _within(attributed, HANG_STALL_S)
    return {"injected_class": "hang", "injected_s": HANG_STALL_S,
            "watchdog_fired": bool(fired),
            "hang_steps": int(led.hang_steps),
            "attributed_within_tolerance": within,
            "partition_exact": _partition_exact(led),
            "ok": bool(fired and led.hang_steps == 1 and within
                       and _partition_exact(led))}


def _goodput_skew_scenario():
    """Rank sleep: this host really sleeps through its step while the
    injected heartbeat matrix shows its dispatch SKEW_MS above the fleet
    median — the amount the ledger must carve to ``straggler_skew``."""
    import time as _time

    from ..utils.cluster import ClusterMonitor
    from ..utils.goodput import RunLedger

    mon = ClusterMonitor(heartbeat_interval=1, host_id=1, n_hosts=2,
                         hang_deadline_s=0, warmup_steps=0,
                         allgather=lambda row: [row])
    led = RunLedger(run_id="gpattr", host=1)
    led.close("init")
    _time.sleep(SKEW_MS / 1000.0 + 0.05)   # the rank's real lag + step work
    mon.ingest([[1.0, 1000.0, 12.0, 9.0, 1024.0, 2048.0, 0.0],
                [1.0, 1000.0, 12.0, 9.0 + SKEW_MS, 1024.0, 2048.0, 0.0]], 1)
    truth = SKEW_MS / 1000.0
    led.close_step(1, {"straggler_skew": mon.last_local_skew_s})
    led.finalize(persist=False)
    attributed = led.class_seconds["straggler_skew"]
    within = _within(attributed, truth)
    integral_seen = abs(mon.skew_integral_s - truth) < 1e-9
    return {"injected_class": "straggler_skew", "injected_s": truth,
            "skew_integral_seen": bool(integral_seen),
            "attributed_within_tolerance": within,
            "partition_exact": _partition_exact(led),
            "ok": bool(integral_seen and within and _partition_exact(led))}


def run_goodput_attribution(seed=0, workdir=None):
    """All four injected-stall attributions. Deterministic transcript."""
    own_dir = workdir is None
    if own_dir:
        workdir = tempfile.mkdtemp(prefix="ds_tpu_goodput_attr_")
    try:
        scenarios = {
            "checkpoint_fence": _goodput_fence_scenario(seed, workdir),
            "restart_replay": _goodput_replay_scenario(seed, workdir),
            "watchdog_hang": _goodput_hang_scenario(),
            "rank_sleep_skew": _goodput_skew_scenario(),
        }
        return {"version": 1, "kind": "goodput_attribution",
                "seed": int(seed), "tolerance_rel": GOODPUT_REL_TOL,
                "scenarios": scenarios,
                "ok": all(s["ok"] for s in scenarios.values())}
    finally:
        if own_dir:
            shutil.rmtree(workdir, ignore_errors=True)


KILL_POINTS = ("mid_save", "between_shards", "auto_resume", "mid_decode",
               "post_preempt")


def run_crash_sim(seed=0, kill_points=KILL_POINTS, workdir=None):
    own_dir = workdir is None
    if own_dir:
        workdir = tempfile.mkdtemp(prefix="ds_tpu_crash_sim_")
    try:
        scenarios = {}
        for kp in kill_points:
            if kp in ("mid_save", "between_shards"):
                scenarios[f"trainer_{kp}"] = _trainer_scenario(
                    kp, seed, workdir)
            elif kp == "auto_resume":
                scenarios["trainer_auto_resume"] = _auto_resume_scenario(
                    seed, workdir)
            else:
                scenarios[f"serve_{kp}"] = _serve_scenario(kp, seed, workdir)
        return {"version": 1, "seed": int(seed), "scenarios": scenarios,
                "ok": all(s["ok"] for s in scenarios.values())}
    finally:
        if own_dir:
            shutil.rmtree(workdir, ignore_errors=True)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="ds-tpu crash-sim",
        description="Kill/restart trainer and serve-sim runs at adversarial "
                    "points; assert bit-exact or documented-tolerance "
                    "recovery.")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--kill-points", default="all",
                        help="comma list of "
                             f"{','.join(KILL_POINTS)} (default: all)")
    parser.add_argument("--json", metavar="PATH",
                        help="write the deterministic recovery transcript")
    parser.add_argument("--workdir", default=None,
                        help="keep checkpoints here instead of a tmp dir")
    parser.add_argument("--goodput", action="store_true",
                        help="run the goodput-attribution sweep instead: "
                             "every injected stall (checkpoint fence, "
                             "kill/restore replay, watchdog hang, rank "
                             "sleep) must land in the correct badput class "
                             "within tolerance")
    args = parser.parse_args(argv)

    if args.goodput:
        transcript = run_goodput_attribution(seed=args.seed,
                                             workdir=args.workdir)
        if args.json:
            with open(args.json, "w") as f:
                json.dump(transcript, f, indent=2, sort_keys=True)
                f.write("\n")
        print(f"crash-sim --goodput seed={args.seed} "
              f"(rel tolerance {transcript['tolerance_rel']})")
        for name, s in transcript["scenarios"].items():
            status = "PASS" if s["ok"] else "FAIL"
            print(f"  {status} {name}: {s['injected_s']:.2f}s injected -> "
                  f"{s['injected_class']}")
        print("crash-sim: every injected stall attributed"
              if transcript["ok"]
              else "crash-sim: GOODPUT MISATTRIBUTION", flush=True)
        return 0 if transcript["ok"] else 1

    kps = (KILL_POINTS if args.kill_points == "all"
           else tuple(args.kill_points.split(",")))
    bad = [k for k in kps if k not in KILL_POINTS]
    if bad:
        print(f"crash-sim: unknown kill point(s): {bad}", file=sys.stderr)
        return 2
    transcript = run_crash_sim(seed=args.seed, kill_points=kps,
                               workdir=args.workdir)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(transcript, f, indent=2, sort_keys=True)
            f.write("\n")
    print(f"crash-sim seed={args.seed}")
    for name, s in transcript["scenarios"].items():
        status = "PASS" if s["ok"] else "FAIL"
        extra = ""
        if "warm_prefill_chunks" in s:
            extra = (f" (warm {s['warm_prefill_chunks']} vs cold "
                     f"{s['cold_prefill_chunks']} prefill chunks)")
        elif "retrained_bit_equal" in s:
            extra = (f" (resumed at step {s['resumed_at_step']}, "
                     f"bit-equal={s['retrained_bit_equal']})")
        print(f"  {status} {name}{extra}")
    print("crash-sim: all kill points recovered" if transcript["ok"]
          else "crash-sim: RECOVERY FAILURES", flush=True)
    return 0 if transcript["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
