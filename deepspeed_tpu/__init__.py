"""deepspeed_tpu: a TPU-native training framework with the capabilities of DeepSpeed v0.3.0.

Public API mirrors the reference's ``deepspeed/__init__.py``: ``initialize()`` returns
``(engine, optimizer, dataloader, lr_scheduler)``; ``add_config_arguments()`` wires argparse.
The implementation is idiomatic JAX/XLA/Pallas/pjit — see SURVEY.md for the mapping.
"""

from . import git_version_info as _gvi


def __getattr__(name):
    # lazy provenance (PEP 562): no git subprocess at import time
    _map = {"__version__": "version", "__git_hash__": "git_hash",
            "__git_branch__": "git_branch", "installed_ops": "installed_ops"}
    if name in _map:
        return getattr(_gvi, _map[name])
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

from .runtime.config import DeepSpeedConfig  # noqa: F401
from .runtime.activation_checkpointing import checkpointing  # noqa: F401


def initialize(args=None,
               model=None,
               optimizer=None,
               model_parameters=None,
               training_data=None,
               lr_scheduler=None,
               mpu=None,
               dist_init_required=None,
               collate_fn=None,
               config_params=None):
    """Initialize the DeepSpeed-TPU engine (reference deepspeed/__init__.py:52-141).

    Returns a tuple of ``(engine, optimizer, training_dataloader, lr_scheduler)``.
    """
    from .runtime.engine import make_engine

    engine = make_engine(args=args,
                         model=model,
                         optimizer=optimizer,
                         model_parameters=model_parameters,
                         training_data=training_data,
                         lr_scheduler=lr_scheduler,
                         mpu=mpu,
                         dist_init_required=dist_init_required,
                         collate_fn=collate_fn,
                         config_params=config_params)
    return_items = [engine, engine.optimizer, engine.training_dataloader, engine.lr_scheduler]
    return tuple(return_items)


def init_inference(model=None,
                   model_parameters=None,
                   config_params=None,
                   telemetry=None,
                   mirror=False,
                   draft_model=None,
                   draft_parameters=None):
    """Initialize the TPU serving engine (``deepspeed.init_inference``-shaped).

    ``model`` is a ``models.gpt2.GPT2Model`` (dense), ``model_parameters`` its
    param pytree, ``config_params`` a DeepSpeed config dict/path whose
    ``"serving"`` block (runtime/constants.py) sizes the paged KV pool and the
    continuous-batching scheduler. Returns a ``serve.InferenceEngine``:
    ``submit()`` requests, drive ``step()`` (or ``run()``) to completion.
    ``telemetry`` is an optional ``utils.telemetry.TelemetrySession`` (compile
    watchdog + Serving/* scalars); ``mirror=True`` runs the dense-cache oracle
    in bitwise lockstep (tests/serve-sim only — it doubles the work).
    ``serving.speculation.enabled`` additionally needs the live draft here:
    ``draft_model`` / ``draft_parameters`` (a config file cannot carry a
    parameter tree; the config's ``draft_model`` string is a report label)."""
    from .serve.engine import InferenceEngine

    config_params = config_params if config_params is not None else {}
    if isinstance(config_params, dict):
        config_params = dict(config_params)
        # serving is batch-free; satisfy the training config's batch check
        if not any(k in config_params for k in
                   ("train_batch_size", "train_micro_batch_size_per_gpu")):
            config_params["train_batch_size"] = 1
    ds_config = DeepSpeedConfig(config_params, world_size=1)
    return InferenceEngine(
        model, model_parameters,
        num_slots=ds_config.serving_max_seqs,
        block_size=ds_config.serving_block_size,
        num_blocks=ds_config.serving_num_blocks,
        max_model_len=ds_config.serving_max_model_len,
        prefill_chunk=ds_config.serving_prefill_chunk,
        use_pallas=ds_config.serving_use_pallas_decode,
        telemetry=telemetry, mirror=mirror,
        prefix_cache=ds_config.serving_prefix_cache_enabled,
        sharding={"model": ds_config.serving_sharding_model}
        if ds_config.serving_sharding_model > 1 else None,
        request_trace={
            "enabled": ds_config.serving_request_trace_enabled,
            "capacity": ds_config.serving_request_trace_capacity,
            "iteration_capacity":
                ds_config.serving_request_trace_iteration_capacity,
            "dump_dir": ds_config.serving_request_trace_dump_dir,
            "slo": {"ttft_ms": ds_config.serving_slo_ttft_ms,
                    "tpot_ms": ds_config.serving_slo_tpot_ms},
        },
        speculation={
            "enabled": True,
            "draft_model": draft_model,
            "draft_params": draft_parameters,
            "label": ds_config.serving_speculation_draft_model,
            "max_draft_tokens":
                ds_config.serving_speculation_max_draft_tokens,
            "draft_pool_blocks":
                ds_config.serving_speculation_draft_pool_blocks,
        } if ds_config.serving_speculation_enabled else None)


def _add_core_arguments(parser):
    """Core DeepSpeed arguments (reference deepspeed/__init__.py:144-192)."""
    group = parser.add_argument_group("DeepSpeed", "DeepSpeed configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed (helper flag for user code, no impact on engine)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="Path to DeepSpeed json configuration.")
    group.add_argument("--deepspeed_mpi", default=False, action="store_true",
                       help="Run via MPI; this flag will force multi-host distributed init.")
    return parser


def add_config_arguments(parser):
    """Update an argument parser to enable the DeepSpeed config block."""
    parser = _add_core_arguments(parser)
    return parser


# ---- legacy `deepspeed.pt` module-structure shim (reference deepspeed/__init__.py:41-49)
import sys as _sys
import types as _types

from .runtime import config as _rt_config, utils as _rt_utils

pt = _types.ModuleType("pt", "legacy pt module alias for backwards compatibility")
pt.deepspeed_utils = _rt_utils
pt.deepspeed_config = _rt_config
_sys.modules[__name__ + ".pt"] = pt
_sys.modules[__name__ + ".pt.deepspeed_utils"] = _rt_utils
_sys.modules[__name__ + ".pt.deepspeed_config"] = _rt_config
