"""Slice topology: factorize the data axis into ('dcn', 'ici') levels.

A TPU multi-slice pod is two networks, not one: within a slice, chips see the
full ICI torus bandwidth; across slices, traffic rides the datacenter network
(DCN) at roughly an order of magnitude less bandwidth per chip. The reference
hit the same asymmetry on GPU clusters (NVLink within a node, Ethernet/IB
across) and answered with 1-bit Adam's compressed MPI allreduce; here the
factorization is explicit: the ``data`` axis of the mesh is split into
``num_slices`` contiguous blocks of ``slice_size`` devices, and every
two-level collective in :mod:`deepspeed_tpu.comm.hierarchical` runs over the
``axis_index_groups`` this module derives.

The factorization is geometric only — compression policy (flat vs hierarchical
vs hierarchical+compressed, warmup step) lives in the ``"comm"`` config block
(runtime/constants.py) and is interpreted by the engine.

Derivation rule (``derive_num_slices``): an explicit ``dcn_slices`` from the
config wins; otherwise each ``jax.distributed`` process is one slice (the
launcher starts one process per host/slice, so process boundaries ARE the DCN
boundaries); otherwise a single-process 8-device mesh — the tier-1 CPU test
mesh — factorizes virtually as 2 slices x 4 devices so every two-level
schedule is exercised without real DCN hardware. Anything else stays at one
slice (purely-ICI mesh: the two-level schedule degenerates gracefully).
"""

from typing import List, Optional

import numpy as np

from ..parallel.mesh import DATA_AXIS

__all__ = ["CommTopology", "derive_num_slices", "derive_topology"]


class CommTopology:
    """Geometric factorization of a ``dp``-way data axis into contiguous slices.

    Device at data-axis position ``d`` sits in slice ``d // slice_size`` at
    local position ``d % slice_size``. Contiguity matches both the multi-host
    reality (``jax.devices()`` orders a process's local devices contiguously)
    and the mesh builder's (pipe, data, model) reshape.
    """

    def __init__(self, dp: int, num_slices: int):
        dp, num_slices = int(dp), int(num_slices)
        if num_slices < 1:
            raise ValueError(f"num_slices must be >= 1, got {num_slices}")
        if dp % num_slices != 0:
            raise ValueError(
                f"data-parallel size {dp} is not divisible by {num_slices} slices")
        self.dp = dp
        self.num_slices = num_slices
        self.slice_size = dp // num_slices

    # ---------------------------------------------------------------- groups
    @property
    def ici_groups(self) -> List[List[int]]:
        """axis_index_groups for intra-slice collectives: one group per slice,
        members in local-position order."""
        L = self.slice_size
        return [[s * L + i for i in range(L)] for s in range(self.num_slices)]

    @property
    def dcn_groups(self) -> List[List[int]]:
        """axis_index_groups for cross-slice collectives: one group per local
        position, members in slice order (device d's group position is its
        slice index d // slice_size)."""
        L = self.slice_size
        return [[s * L + i for s in range(self.num_slices)] for i in range(L)]

    @property
    def slice_rows(self) -> List[List[int]]:
        """Data-axis ranks grouped by slice — the per-level desync audit's and
        the checkpoint remapper's view of the same factorization."""
        return self.ici_groups

    def slice_of(self, rank: int) -> int:
        return int(rank) // self.slice_size

    @property
    def is_hierarchical(self) -> bool:
        return self.num_slices > 1

    # ---------------------------------------------------------- device sets
    def slice_device_sets(self, mesh) -> List[frozenset]:
        """Per-slice sets of global device ids on ``mesh`` — the HLO wire-byte
        classifier's ground truth (utils/hlo.py:collective_axis_bytes). A data
        rank's whole (pipe, model) fiber joins its slice, so model/pipe
        collectives inside one data shard classify as ICI."""
        axes = list(mesh.axis_names)
        dev = np.asarray(mesh.devices)
        data_pos = axes.index(DATA_AXIS)
        dev = np.moveaxis(dev, data_pos, 0).reshape(mesh.shape[DATA_AXIS], -1)
        L = self.slice_size
        out = []
        for s in range(self.num_slices):
            ids = {int(d.id) for d in dev[s * L:(s + 1) * L].ravel()}
            out.append(frozenset(ids))
        return out

    def __repr__(self):
        return (f"CommTopology(dp={self.dp}, num_slices={self.num_slices}, "
                f"slice_size={self.slice_size})")

    def __eq__(self, other):
        return (isinstance(other, CommTopology) and other.dp == self.dp
                and other.num_slices == self.num_slices)


def derive_num_slices(dp: int, requested: int = 0,
                      process_count: Optional[int] = None) -> int:
    """Resolve the slice count for a ``dp``-way data axis.

    ``requested`` (the config's ``comm.dcn_slices``) wins when positive;
    ``0`` means auto: one slice per ``jax.distributed`` process when the world
    is multi-process (and the processes tile the axis evenly), else the
    virtual 2-slice factorization of the canonical 8-device test mesh, else 1.
    """
    dp = int(dp)
    requested = int(requested)
    if requested > 0:
        if dp % requested != 0:
            raise ValueError(
                f"comm.dcn_slices={requested} does not divide the data-parallel "
                f"size {dp}")
        return requested
    if process_count is None:
        import jax
        process_count = jax.process_count()
    if process_count > 1 and dp % process_count == 0:
        return int(process_count)
    if dp == 8:
        return 2  # virtual 2 x 4: the tier-1 CPU mesh's test factorization
    return 1


def derive_topology(dp: int, requested: int = 0,
                    process_count: Optional[int] = None) -> CommTopology:
    """``CommTopology`` from the ``derive_num_slices`` rule."""
    return CommTopology(dp, derive_num_slices(dp, requested, process_count))
