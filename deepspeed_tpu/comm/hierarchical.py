"""Two-level ICI+DCN collectives: the topology-aware gradient-exchange schedule.

Generalizes ``runtime/custom_collectives.compressed_allreduce`` from its flat
single-axis form to the two-network reality a :class:`~.topology.CommTopology`
describes. Every data-parallel exchange becomes three steps:

1. **ICI reduce-scatter** within each slice (exact, full-precision): device
   ``(s, l)`` ends up owning chunk ``l`` of its slice's local sum — the cheap
   network does the high-bandwidth work.
2. **DCN exchange** across slices, one group per chunk position. Uncompressed
   mode runs a plain ``psum``; compressed mode runs the reference's
   error-feedback two-phase sign compression (1 bit/element bit-packed into
   uint8 + per-segment fp32 RMS scales) among the ``num_slices`` peers — the
   slow network ships ~n/16 bytes instead of 4n.
3. **ICI all-gather** within each slice reassembles the full vector.

With ``slice_size == 1`` the schedule degenerates to exactly the flat
compressed allreduce (every device is its own slice; the DCN group is the
whole axis); with ``num_slices == 1`` it degenerates to a flat psum.

Numerics contract: the two-level UNCOMPRESSED mean reassociates the reduction
(slice-sums first), so on generic fp32 data it is bit-equal to XLA's flat
all-reduce only when every partial sum is exact (integer-valued grids, data
with shared exponents) — tests pin bit-equality on such data and tolerance
parity on real training (docs/multislice.md). Error-feedback state for the
compressed mode: ``worker_error`` is per-device over its ICI chunk
``(dp, n / slice_size)`` and ``server_error`` per-device over its DCN
sub-chunk ``(dp, n / dp)`` — the flat layout with ``slice_size == 1`` keeps
the historical ``(dp, n)`` shape.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel.mesh import DATA_AXIS, shard_map
from ..utils.cluster import named_scope as ds_named_scope
from ..runtime.custom_collectives import _signs_collective, padded_size
from .topology import CommTopology

__all__ = [
    "flatten_tree", "unflatten_tree", "tree_size", "grad_segment_ids",
    "two_level_sum", "two_level_compressed",
    "two_level_allreduce", "two_level_compressed_allreduce",
    "error_state_shapes", "padded_size",
    "bucket_partition", "bucket_plan", "bucketed_error_state_shapes",
    "bucketed_two_level_mean", "bucketed_two_level_compressed",
    "GRAD_BUCKET_SCOPE",
]

# named_scope prefix stamped on every bucketed exchange: it survives into the
# optimized HLO as instruction metadata (op_name), which is how the anatomy
# pass recognizes an eagerly-issued bucket collective and prices its real
# issue-to-use window instead of treating the sync instruction as fully
# exposed (utils/anatomy.py, docs/overlap.md)
GRAD_BUCKET_SCOPE = "ds_grad_bucket"


# ---------------------------------------------------------------- tree plumbing
def tree_size(tree) -> int:
    return sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(tree))


def flatten_tree(tree):
    """Tree -> (n,) vector plus the restore recipe (leaf order = tree order)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    sizes = [int(np.prod(l.shape)) for l in leaves]
    flat = jnp.concatenate([l.reshape(-1) for l in leaves])
    return flat, (treedef, sizes, [l.shape for l in leaves])


def unflatten_tree(vec, recipe):
    treedef, sizes, shapes = recipe
    offsets = np.cumsum([0] + sizes)
    leaves = [vec[offsets[i]:offsets[i + 1]].reshape(shapes[i])
              for i in range(len(sizes))]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def grad_segment_ids(tree, n_pad: int) -> np.ndarray:
    """Element -> leaf-index segment map over the flattened padded vector, the
    padded tail in its own segment (its zeros must not drag a real tensor's
    RMS scale down — same per-tensor semantics as 1-bit Adam's state)."""
    sizes = [int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(tree)]
    ids = np.repeat(np.arange(len(sizes), dtype=np.int32), sizes)
    if n_pad > ids.shape[0]:
        ids = np.concatenate([ids, np.full(n_pad - ids.shape[0], len(sizes),
                                           np.int32)])
    assert ids.shape[0] == n_pad, f"tree has {ids.shape[0]} elements > n_pad={n_pad}"
    return ids


def error_state_shapes(n_pad: int, topo: CommTopology):
    """((dp, worker_cols), (dp, server_cols)) for the compressed exchange's
    persistent error-feedback buffers on an ``n_pad``-element vector."""
    dp = topo.dp
    assert n_pad % dp == 0
    return (dp, n_pad // topo.slice_size), (dp, n_pad // dp)


# --------------------------------------------------------------- bucketing
def bucket_partition(tree, bucket_bytes: int):
    """Greedy deterministic partition of the tree's leaves (tree order) into
    contiguous size-bounded buckets: a leaf opens a new bucket when appending
    it would push the current bucket past ``bucket_bytes``. Sizes are priced
    at 4 bytes/element (the fp32 wire width) so the partition depends only on
    the parameter SHAPES and ``bucket_bytes`` — never on dtype or data. A
    single leaf larger than the bound gets its own (oversized) bucket.
    Returns a list of leaf-index lists covering every leaf exactly once."""
    leaves = jax.tree_util.tree_leaves(tree)
    buckets, cur, cur_bytes = [], [], 0
    for i, leaf in enumerate(leaves):
        nbytes = int(np.prod(leaf.shape)) * 4
        if cur and cur_bytes + nbytes > bucket_bytes:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes
    if cur:
        buckets.append(cur)
    return buckets


def bucket_plan(tree, bucket_bytes: int, dp: int):
    """``bucket_partition`` plus the static per-bucket exchange geometry:
    ``[{"leaf_indices", "sizes", "n", "n_pad"}]`` where ``n_pad`` rounds each
    bucket up to a multiple of ``dp`` (the two-level schedule's scatter
    granularity). Deterministic for a given tree / bucket_bytes / dp."""
    leaves = jax.tree_util.tree_leaves(tree)
    plan = []
    for idxs in bucket_partition(tree, bucket_bytes):
        sizes = tuple(int(np.prod(leaves[i].shape)) for i in idxs)
        n = sum(sizes)
        plan.append({"leaf_indices": tuple(idxs), "sizes": sizes,
                     "n": n, "n_pad": padded_size(n, dp)})
    return plan


def bucketed_error_state_shapes(plan, topo: CommTopology):
    """((dp, worker_cols), (dp, server_cols)) for the bucketed compressed
    exchange's persistent error-feedback buffers: the per-bucket chunks laid
    out back to back in plan order. The total exceeds the monolithic
    ``error_state_shapes`` by the per-bucket padding — bucketed EF state is a
    different (per-bucket) layout, not a re-slicing of the monolithic one."""
    dp = topo.dp
    we_cols = sum(b["n_pad"] // topo.slice_size for b in plan)
    se_cols = sum(b["n_pad"] // dp for b in plan)
    return (dp, we_cols), (dp, se_cols)


def _bucket_vec(leaves, bucket):
    """One bucket's padded flat vector (in the leaves' own dtype)."""
    parts = [leaves[i].reshape(-1) for i in bucket["leaf_indices"]]
    vec = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
    return jnp.pad(vec, (0, bucket["n_pad"] - bucket["n"]))


def _bucket_unpack(mean, bucket, leaves, out):
    """Scatter one bucket's exchanged vector back onto its leaves."""
    off = 0
    for i, sz in zip(bucket["leaf_indices"], bucket["sizes"]):
        out[i] = mean[off:off + sz].reshape(leaves[i].shape) \
            .astype(leaves[i].dtype)
        off += sz


def bucketed_two_level_mean(leaves, plan, topo: CommTopology,
                            axis_name: str = DATA_AXIS):
    """Per-bucket exact two-level MEAN of a flat leaf list (inside shard_map).

    Each bucket runs the same reduce-scatter -> DCN psum -> all-gather
    schedule as the monolithic ``two_level_sum`` (plain psum on a flat
    topology), under its own ``ds_grad_bucket{k}`` named_scope, and depends
    only on its OWN leaves — so the compiler is free to issue bucket k's
    exchange while the backward producing bucket k-1's leaves is still
    running, and the DCN hop of bucket k runs concurrently with the ICI
    phase of bucket k+1. Per element the reduction tree is identical to the
    monolithic exchange, so the result is bit-equal to it for any fixed
    bucket assignment (bucketing reorders issue, not math)."""
    dp = topo.dp
    out = [None] * len(leaves)
    for k, bucket in enumerate(plan):
        with ds_named_scope(f"{GRAD_BUCKET_SCOPE}{k}"):
            mean = two_level_sum(_bucket_vec(leaves, bucket), topo,
                                 axis_name) / dp
            _bucket_unpack(mean, bucket, leaves, out)
    return out


def bucketed_two_level_compressed(leaves, we_local, se_local, plan,
                                  topo: CommTopology, seg_consts, n_segs,
                                  axis_name: str = DATA_AXIS):
    """Per-bucket error-feedback compressed MEAN of a flat leaf list (inside
    shard_map): ``two_level_compressed`` over each bucket's padded vector,
    with the persistent worker/server error buffers laid out per bucket
    (``bucketed_error_state_shapes``). ``seg_consts``/``n_segs`` are the
    static per-bucket scale-segment maps (one per plan entry). NOT bit-equal
    to the monolithic compressed exchange — per-segment RMS scales are
    chunked per bucket — but the EF telescoping contract holds per bucket.
    Returns (out leaves, new_we, new_se)."""
    L, dp = topo.slice_size, topo.dp
    out = [None] * len(leaves)
    new_we, new_se = [], []
    we_off = se_off = 0
    for k, bucket in enumerate(plan):
        n_pad = bucket["n_pad"]
        wcols, scols = n_pad // L, n_pad // dp
        with ds_named_scope(f"{GRAD_BUCKET_SCOPE}{k}"):
            vec = _bucket_vec(leaves, bucket).astype(jnp.float32)
            mean, we_k, se_k = two_level_compressed(
                vec, we_local[we_off:we_off + wcols],
                se_local[se_off:se_off + scols], topo, seg_consts[k],
                n_segs[k], axis_name)
            _bucket_unpack(mean, bucket, leaves, out)
        new_we.append(we_k)
        new_se.append(se_k)
        we_off += wcols
        se_off += scols
    return (out, jnp.concatenate(new_we) if len(new_we) > 1 else new_we[0],
            jnp.concatenate(new_se) if len(new_se) > 1 else new_se[0])


# ------------------------------------------------------------ in-context bodies
# These run INSIDE an existing shard_map over the data axis (the engine's grad
# scaffold); the wrappers below add the shard_map for standalone callers.

def two_level_sum(x_local, topo: CommTopology, axis_name: str = DATA_AXIS):
    """Exact two-level SUM of per-device vectors: reduce-scatter over ICI,
    psum over DCN, all-gather over ICI. ``x_local`` length must divide by
    ``slice_size``. Caller divides for a mean (one division, same placement
    as XLA's flat pmean)."""
    if not topo.is_hierarchical:
        return jax.lax.psum(x_local, axis_name)
    part = jax.lax.psum_scatter(x_local, axis_name, scatter_dimension=0,
                                axis_index_groups=topo.ici_groups, tiled=True)
    part = jax.lax.psum(part, axis_name, axis_index_groups=topo.dcn_groups)
    return jax.lax.all_gather(part, axis_name,
                              axis_index_groups=topo.ici_groups, tiled=True)


def two_level_compressed(x_local, we_local, se_local, topo: CommTopology,
                         seg_const, n_segs: int, axis_name: str = DATA_AXIS):
    """Two-level error-feedback sign-compressed MEAN of per-device vectors.

    Args (per-device, inside shard_map):
      x_local: (n,) — this device's local contribution.
      we_local: (n / slice_size,) worker error over this device's ICI chunk.
      se_local: (n / dp,) server error over this device's DCN sub-chunk.
      seg_const: (n,) int32 scale-segment map (static).
      n_segs: static segment count (max id + 1).

    Returns (out (n,) ~= mean over dp of x_local, new_we, new_se).
    """
    n = x_local.shape[0]
    S, L = topo.num_slices, topo.slice_size
    assert n % (S * L) == 0, f"vector size {n} must divide by dp={S * L} (pad first)"
    C = n // L          # my ICI chunk after the reduce-scatter
    csize = C // S      # my DCN server sub-chunk
    idx = jax.lax.axis_index(axis_name)
    l = idx % L         # position within my slice == which chunk of n I own
    s = idx // L        # my slice == my position within my DCN group

    def seg_rms(buf, ids):
        counts = jnp.maximum(
            jax.ops.segment_sum(jnp.ones(buf.shape, jnp.float32), ids,
                                num_segments=n_segs), 1.0)
        ss = jax.ops.segment_sum(jnp.square(buf), ids, num_segments=n_segs)
        return jnp.sqrt(ss / counts)

    # Level 1 (ICI, exact): slice-local reduce-scatter, then the slice mean so
    # the DCN server mean over slices composes to the grand mean — the same
    # magnitude the flat schedule compresses, keeping error-feedback residual
    # scales comparable across topologies.
    chunk = jax.lax.psum_scatter(
        x_local.astype(jnp.float32), axis_name, scatter_dimension=0,
        axis_index_groups=topo.ici_groups, tiled=True) / L          # (C,)

    # Level 2 phase 1 (DCN): compress my chunk, ship sub-chunk j to slice j.
    seg_chunk = jax.lax.dynamic_slice(seg_const, (l * C,), (C,))
    corrected = chunk + we_local
    wscale = seg_rms(corrected, seg_chunk)                           # (n_segs,)
    signs = jnp.where(corrected >= 0, 1, -1).astype(jnp.int8)
    new_we = corrected - wscale[seg_chunk] * signs.astype(jnp.float32)

    packed = csize % 8 == 0
    recv = _signs_collective(
        lambda t: jax.lax.all_to_all(t, axis_name, split_axis=0, concat_axis=0,
                                     tiled=False,
                                     axis_index_groups=topo.dcn_groups),
        signs.reshape(S, csize), packed)                             # (S, csize)
    wscales = jax.lax.all_gather(wscale, axis_name,
                                 axis_index_groups=topo.dcn_groups)  # (S, n_segs)

    # Server reduction over the S slice peers, with my persistent server error.
    seg_server = jax.lax.dynamic_slice(seg_const, (l * C + s * csize,), (csize,))
    per_elem_wscale = jnp.take_along_axis(
        wscales, seg_server[None, :].repeat(S, 0), axis=1)           # (S, csize)
    server_m = jnp.mean(recv.astype(jnp.float32) * per_elem_wscale, axis=0)
    corrected_s = server_m + se_local
    sscale = seg_rms(corrected_s, seg_server)
    s_signs = jnp.where(corrected_s >= 0, 1, -1).astype(jnp.int8)
    new_se = corrected_s - sscale[seg_server] * s_signs.astype(jnp.float32)

    # Level 2 phase 2 (DCN): gather the S compressed server sub-chunks back.
    all_signs = _signs_collective(
        lambda t: jax.lax.all_gather(t, axis_name,
                                     axis_index_groups=topo.dcn_groups),
        s_signs, packed)                                             # (S, csize)
    sscales = jax.lax.all_gather(sscale, axis_name,
                                 axis_index_groups=topo.dcn_groups)  # (S, n_segs)
    per_elem_sscale = jnp.take_along_axis(sscales, seg_chunk.reshape(S, csize),
                                          axis=1)
    my_chunk = (all_signs.astype(jnp.float32) * per_elem_sscale).reshape(C)

    # Level 3 (ICI): reassemble the full mean from the L slice chunks.
    out = jax.lax.all_gather(my_chunk, axis_name,
                             axis_index_groups=topo.ici_groups, tiled=True)
    return out, new_we, new_se


# --------------------------------------------------------- standalone wrappers
def two_level_allreduce(mesh: Mesh, x, topo: CommTopology,
                        axis_name: str = DATA_AXIS):
    """Uncompressed two-level MEAN of per-worker rows: (dp, n) sharded
    ``P(data, None)`` -> (n,) replicated."""
    dp = topo.dp
    assert mesh.shape[axis_name] == dp

    def body(x_row):
        total = two_level_sum(x_row[0], topo, axis_name)
        return total / dp

    fn = shard_map(body, mesh=mesh, in_specs=(P(axis_name, None),),
                   out_specs=P(), check_vma=False)
    return fn(x)


def two_level_compressed_allreduce(mesh: Mesh, x, worker_error, server_error,
                                   topo: CommTopology,
                                   axis_name: str = DATA_AXIS, seg_ids=None):
    """Two-level generalization of ``custom_collectives.compressed_allreduce``.

    Args:
      x: (dp, n) fp32 per-worker rows, sharded ``P(data, None)``.
      worker_error: (dp, n / slice_size) fp32 persistent, same sharding.
      server_error: (dp, n / dp) fp32 persistent, same sharding.
      topo: the slice factorization (flat ``slice_size == 1`` reproduces the
        historical flat layout and math exactly).
      seg_ids: optional STATIC (n,) int segment map (per-tensor scales).

    Returns (out (n,) replicated compressed mean, new_worker_error,
    new_server_error).
    """
    dp = topo.dp
    assert mesh.shape[axis_name] == dp
    n = x.shape[-1]
    seg_np = (np.zeros((n,), np.int32) if seg_ids is None
              else np.asarray(seg_ids, np.int32))
    assert seg_np.shape == (n,), f"seg_ids must be ({n},), got {seg_np.shape}"
    n_segs = int(seg_np.max()) + 1
    seg_const = jnp.asarray(seg_np)

    def body(x_row, we_row, se_row):
        out, new_we, new_se = two_level_compressed(
            x_row[0], we_row[0], se_row[0], topo, seg_const, n_segs, axis_name)
        return out, new_we[None], new_se[None]

    fn = shard_map(body, mesh=mesh,
                   in_specs=(P(axis_name, None),) * 3,
                   out_specs=(P(), P(axis_name, None), P(axis_name, None)),
                   check_vma=False)
    return fn(x, worker_error, server_error)
