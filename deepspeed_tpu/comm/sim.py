"""Two-level comm schedule simulator: ``ds-tpu comm-sim``.

AOT-compiles the engine's data-parallel gradient-exchange programs for all
three comm modes on the pinned 8-virtual-device CPU mesh factorized as
2 slices x 4 devices, classifies every collective instruction against the
slice device sets (utils/hlo.collective_axis_breakdown), and emits a
deterministic JSON report of per-level (ici/dcn) collective counts and wire
bytes. Nothing executes — the report is a pure function of the lowered HLO,
so two runs on any machine produce byte-identical JSON (CI diffs it).

An embedded manifest pins the schedule's shape:

- flat mode ships its full fp32 exchange cross-"slice" (the factorization is
  virtual — XLA knows nothing of it, so the flat all-reduce's single group
  spans both slices);
- hierarchical mode moves the bulk onto ICI (reduce-scatter + all-gather
  inside slices) leaving one fp32 psum on the DCN;
- compressed mode replaces that psum with the 1-bit exchange and must cut
  cross-slice bytes by >= MIN_DCN_REDUCTION vs flat (the PR's acceptance
  floor).

Any violation exits nonzero — this is the CI gate ``scripts/lint.sh`` runs
after the lint surface.
"""

import argparse
import json
import sys

MIN_DCN_REDUCTION = 8.0     # acceptance floor: compressed dcn bytes vs flat

# Expected per-level schedule shape, pinned per program. "ops" maps HLO op ->
# level -> (min_count, max_count); "dcn_bytes_max_frac" bounds that program's
# cross-slice bytes as a fraction of the flat baseline's.
MANIFEST = {
    "flat:loss_and_grad": {
        "ops": {"all-reduce": {"dcn": (1, None)}},
        "ici_bytes_max": 0,      # flat mode may not touch the ICI-only level
    },
    "hierarchical:loss_and_grad": {
        "ops": {
            "reduce-scatter": {"ici": (1, None)},
            "all-reduce": {"dcn": (1, None)},
            "all-gather": {"ici": (1, None)},
        },
        "dcn_bytes_max_frac": 0.5,   # only 1/slice_size of the vector crosses
    },
    "compressed:loss_and_grad_comm": {
        "ops": {
            "reduce-scatter": {"ici": (1, None)},
            "all-to-all": {"dcn": (1, None)},    # 1-bit worker->server phase
            "all-gather": {"ici": (1, None), "dcn": (1, None)},
        },
        "dcn_bytes_max_frac": 1.0 / MIN_DCN_REDUCTION,
    },
}


def _build_engine(comm_cfg):
    import jax
    import deepspeed_tpu
    from ..lint.registry import LintModel, _config, _sample_batch

    model = LintModel()
    overrides = {"zero_optimization": {"stage": 1}}
    if comm_cfg:
        overrides["comm"] = comm_cfg
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.init(jax.random.PRNGKey(0)),
        config_params=_config(**overrides))
    return eng, _sample_batch()


def _capture(jitted, args):
    return jitted.lower(*args).compile().as_text()


def build_report(num_slices=2):
    """The comm-sim report dict (deterministic given the pinned mesh)."""
    from ..utils.hlo import collective_axis_breakdown
    from .topology import derive_topology

    modes = [
        ("flat", None, "loss_and_grad"),
        ("hierarchical", {"mode": "hierarchical", "dcn_slices": num_slices},
         "loss_and_grad"),
        ("compressed", {"mode": "hierarchical_compressed",
                        "dcn_slices": num_slices}, "loss_and_grad_comm"),
    ]
    programs = {}
    topo = None
    for mode, comm_cfg, prog_name in modes:
        eng, batch = _build_engine(comm_cfg)
        if topo is None:
            # the flat engine's mesh carries the same 8 devices; derive the
            # factorization once so flat is judged against the SAME slice sets
            topo = derive_topology(eng.dp_size, num_slices)
            slice_sets = [sorted(s) for s in topo.slice_device_sets(eng.mesh)]
        progs = {n: (j, a) for n, j, a, _m in eng.lint_programs(batch)}
        if prog_name not in progs:
            raise RuntimeError(f"{mode}: program {prog_name!r} not on the "
                               f"step path ({sorted(progs)})")
        jitted, args = progs[prog_name]
        breakdown = collective_axis_breakdown(_capture(jitted, args),
                                              slice_sets)
        totals = {lvl: sum(ops[lvl]["bytes"] for ops in breakdown.values())
                  for lvl in ("ici", "dcn")}
        programs[f"{mode}:{prog_name}"] = {
            "collectives": {op: breakdown[op] for op in sorted(breakdown)},
            "ici_bytes": totals["ici"],
            "dcn_bytes": totals["dcn"],
        }
    flat_dcn = programs["flat:loss_and_grad"]["dcn_bytes"]
    comp_dcn = programs["compressed:loss_and_grad_comm"]["dcn_bytes"]
    report = {
        "mesh": {"devices": topo.dp, "dp": topo.dp,
                 "num_slices": topo.num_slices,
                 "slice_size": topo.slice_size,
                 "slice_device_sets": slice_sets},
        "programs": programs,
        "dcn_reduction_vs_flat": (round(flat_dcn / comp_dcn, 3)
                                  if comp_dcn else None),
        "min_dcn_reduction": MIN_DCN_REDUCTION,
    }
    report["violations"] = _check(report)
    report["ok"] = not report["violations"]
    return report


def _check(report):
    """Manifest violations for a report (empty list = schedule shape holds)."""
    out = []
    flat_dcn = report["programs"]["flat:loss_and_grad"]["dcn_bytes"]
    for name, man in MANIFEST.items():
        prog = report["programs"].get(name)
        if prog is None:
            out.append(f"{name}: program missing from report")
            continue
        for op, levels in man.get("ops", {}).items():
            got = prog["collectives"].get(op, {})
            for lvl, (lo, hi) in levels.items():
                n = got.get(lvl, {}).get("count", 0)
                if lo is not None and n < lo:
                    out.append(f"{name}: {op}[{lvl}] count {n} < min {lo}")
                if hi is not None and n > hi:
                    out.append(f"{name}: {op}[{lvl}] count {n} > max {hi}")
        if "ici_bytes_max" in man and prog["ici_bytes"] > man["ici_bytes_max"]:
            out.append(f"{name}: ici bytes {prog['ici_bytes']} > "
                       f"{man['ici_bytes_max']}")
        frac = man.get("dcn_bytes_max_frac")
        if frac is not None and flat_dcn and prog["dcn_bytes"] > flat_dcn * frac:
            out.append(f"{name}: dcn bytes {prog['dcn_bytes']} > "
                       f"{frac} * flat {flat_dcn}")
    red = report["dcn_reduction_vs_flat"]
    if red is None or red < MIN_DCN_REDUCTION:
        out.append(f"compressed dcn reduction {red} < floor "
                   f"{MIN_DCN_REDUCTION}x vs flat")
    return out


def render(report):
    """Deterministic bytes: sorted keys, no floats beyond the rounded ratio."""
    return json.dumps(report, indent=2, sort_keys=True) + "\n"


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="ds-tpu comm-sim",
        description="Replay the two-level ICI+DCN schedule on the pinned "
                    "8-device CPU mesh and check the per-level byte manifest.")
    ap.add_argument("--json", action="store_true",
                    help="print the full JSON report (default: summary line)")
    ap.add_argument("--out", default=None,
                    help="also write the JSON report to this path")
    ap.add_argument("--num-slices", type=int, default=2,
                    help="slice factorization of the 8-device mesh (default 2)")
    args = ap.parse_args(argv)

    # stdout belongs to the report (same contract as ds-tpu lint): route the
    # framework logger's engine-build INFO lines to stderr
    import logging

    import deepspeed_tpu  # noqa: F401 — installs the logger handlers
    for h in logging.getLogger("DeepSpeedTPU").handlers:
        if isinstance(h, logging.StreamHandler) and h.stream is sys.stdout:
            h.stream = sys.stderr

    report = build_report(num_slices=args.num_slices)
    text = render(report)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
    if args.json:
        sys.stdout.write(text)
    else:
        red = report["dcn_reduction_vs_flat"]
        print(f"comm-sim: dcn_reduction_vs_flat={red}x "
              f"(floor {MIN_DCN_REDUCTION}x), "
              f"{'OK' if report['ok'] else 'VIOLATIONS'}")
    for v in report["violations"]:
        print(f"comm-sim violation: {v}", file=sys.stderr)
    return 0 if report["ok"] else 1
