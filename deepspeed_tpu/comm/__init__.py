"""Topology-aware communication subsystem: two-level ICI+DCN collectives.

- :mod:`.topology` — slice factorization of the mesh's data axis.
- :mod:`.hierarchical` — two-level (reduce-scatter / compressed-allreduce /
  all-gather) schedules generalizing ``runtime/custom_collectives``.
- :mod:`.sim` — ``ds-tpu comm-sim``: deterministic replay + per-level
  collective manifest gate on the 8-device CPU mesh.
"""

from .topology import CommTopology, derive_num_slices, derive_topology
from .hierarchical import (two_level_allreduce, two_level_compressed_allreduce,
                           two_level_sum, two_level_compressed,
                           error_state_shapes)

__all__ = [
    "CommTopology", "derive_num_slices", "derive_topology",
    "two_level_allreduce", "two_level_compressed_allreduce",
    "two_level_sum", "two_level_compressed", "error_state_shapes",
]
