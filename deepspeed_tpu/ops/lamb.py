"""Fused LAMB for TPU.

Replaces ``csrc/lamb/fused_lamb_cuda_kernel.cu`` (N3) + ``deepspeed/ops/lamb/fused_lamb.py``:
Adam-style update with a per-tensor trust ratio ||p|| / ||update||, clamped to
[min_coeff, max_coeff] (reference fused_lamb.py:48-49). The two-pass norm reduction the CUDA
kernel hand-rolls is a pair of XLA reductions that fuse into the update.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp


class LambState(NamedTuple):
    exp_avg: object
    exp_avg_sq: object


def init(master_params) -> LambState:
    z = lambda: jax.tree_util.tree_map(lambda p: jnp.zeros(p.shape, jnp.float32), master_params)
    return LambState(exp_avg=z(), exp_avg_sq=z())


def apply(grads, state: LambState, master_params, step, hyper,
          max_coeff: float = 10.0, min_coeff: float = 0.01, groups=None):
    from .adam import flat_group_ids, hyper_for_group

    def leaf(g, m, v, p, gi):
        h = hyper_for_group(hyper, gi)
        lr, b1, b2, eps, wd = h["lr"], h["beta1"], h["beta2"], h["eps"], h["weight_decay"]
        g = g.astype(jnp.float32)
        m = b1 * m + (1.0 - b1) * g
        v = b2 * v + (1.0 - b2) * jnp.square(g)
        update = m / (jnp.sqrt(v) + eps) + wd * p
        w_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
        u_norm = jnp.sqrt(jnp.sum(jnp.square(update)))
        trust = jnp.where(u_norm > 0, jnp.where(w_norm > 0, w_norm / u_norm, 1.0), 1.0)
        trust = jnp.clip(trust, min_coeff, max_coeff)
        new_p = p - lr * trust * update
        return new_p, m, v

    flat_g, treedef = jax.tree_util.tree_flatten(grads)
    flat_m = jax.tree_util.tree_leaves(state.exp_avg)
    flat_v = jax.tree_util.tree_leaves(state.exp_avg_sq)
    flat_p = jax.tree_util.tree_leaves(master_params)
    flat_gi = flat_group_ids(groups, len(flat_g))
    new_p, new_m, new_v = [], [], []
    for g, m, v, p, gi in zip(flat_g, flat_m, flat_v, flat_p, flat_gi):
        np_, nm, nv = leaf(g, m, v, p, gi)
        new_p.append(np_)
        new_m.append(nm)
        new_v.append(nv)
    unflat = jax.tree_util.tree_unflatten
    return unflat(treedef, new_p), LambState(exp_avg=unflat(treedef, new_m),
                                             exp_avg_sq=unflat(treedef, new_v))
