"""Paged decode attention Pallas kernel (single-query, block-table gather).

One grid cell per (slot, page): the scalar-prefetched block table steers each
cell's k/v ``BlockSpec`` index map straight at the slot's page in the HBM pool
``[n_layer, num_blocks, block_size, n_head, head_dim]`` — the pages are DMA'd
by table indirection, never gathered into a contiguous [slots, max_len, ...]
buffer (that gather is exactly what the XLA fallback in serve/paged.py pays
for). Online-softmax (m, l, acc) scratch carries the reduction across a slot's
pages, vLLM's PagedAttention shape specialized to decode (query length 1).

Numerics: scores and the softmax accumulate in f32 regardless of pool dtype;
the result matches the dense path to float tolerance, NOT bitwise (the dense
path computes one flat softmax over max_len, this kernel reduces page by
page). Hence the engine default is the bitwise XLA gather path; this kernel
is opt-in via ``serving.use_pallas_decode`` and pinned by an allclose parity
test (tests/unit/test_paged_attention.py).

``interpret=True`` (automatic off-TPU) runs the same grid sequentially on
CPU — scratch persistence across the page dimension matches TPU semantics.

Head sharding: under ``serving.sharding.model`` the engine invokes this kernel
inside ``shard_map`` with the pool's head axis already split, so ``n_head``
here is the PER-SHARD head count and the pool refs are the shard-local pages.
Nothing in the kernel is head-global — the softmax reduces over each head's
own pages independently — so the same kernel body serves both layouts; the
cross-shard f32 psum lives in the caller's projection, not here.
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    _HAS_PLTPU = False

_NEG_INF = -1e30  # python float: a jnp scalar would be a captured constant


def _decode_kernel(tables_ref, lengths_ref, q_ref, k_ref, v_ref, o_ref,
                   m_ref, l_ref, acc_ref, *, block_size, head_dim):
    """Grid (slots, pages): accumulate one page of one slot's KV history into
    the slot's online-softmax state; finalize on the last page."""
    b = pl.program_id(1)
    s = pl.program_id(0)
    num_pages = pl.num_programs(1)

    @pl.when(b == 0)
    def _init():
        m_ref[...] = jnp.full(m_ref.shape, _NEG_INF, m_ref.dtype)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q = q_ref[:, 0, :].astype(jnp.float32)                 # [nh, hd]
    k = k_ref[...].astype(jnp.float32)                     # [BS, nh, hd]
    v = v_ref[...].astype(jnp.float32)

    # scores [nh, BS]: batch over heads, contract head_dim
    scores = jax.lax.dot_general(
        q, k, (((1,), (2,)), ((0,), (1,))),
        preferred_element_type=jnp.float32) / math.sqrt(head_dim)

    # causal frontier: token index within the whole history
    idx = b * block_size + jax.lax.broadcasted_iota(
        jnp.int32, (1, block_size), 1)                     # [1, BS]
    scores = jnp.where(idx < lengths_ref[s], scores, _NEG_INF)

    m_prev = m_ref[...]                                    # [nh, 1]
    m_new = jnp.maximum(m_prev, jnp.max(scores, axis=1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(scores - m_new)                            # [nh, BS]
    l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=1, keepdims=True)
    # pv [nh, hd]: batch over heads, contract the page dimension
    pv = jax.lax.dot_general(p, v, (((1,), (0,)), ((0,), (1,))),
                             preferred_element_type=jnp.float32)
    acc_ref[...] = acc_ref[...] * alpha + pv
    m_ref[...] = m_new

    @pl.when(b == num_pages - 1)
    def _finalize():
        o_ref[:, 0, :] = (acc_ref[...] / l_ref[...]).astype(o_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("li", "block_size", "interpret"))
def _paged_decode(q, k_pool, v_pool, tables, lengths, *, li, block_size,
                  interpret):
    S, nh, _, hd = q.shape
    MB = tables.shape[1]
    BS = block_size            # static argname; already an int (see wrapper)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,                 # tables, lengths steer the DMA
        grid=(S, MB),
        in_specs=[
            pl.BlockSpec((None, nh, 1, hd), lambda s, b, t, ln: (s, 0, 0, 0)),
            # the paged gather: page (li, tables[s, b]) of the pool
            pl.BlockSpec((None, None, BS, nh, hd),
                         lambda s, b, t, ln: (li, t[s, b], 0, 0, 0)),
            pl.BlockSpec((None, None, BS, nh, hd),
                         lambda s, b, t, ln: (li, t[s, b], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, nh, 1, hd),
                               lambda s, b, t, ln: (s, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((nh, 1), jnp.float32),   # running max
            pltpu.VMEM((nh, 1), jnp.float32),   # running denominator
            pltpu.VMEM((nh, hd), jnp.float32),  # running numerator
        ],
    )
    kernel = functools.partial(_decode_kernel, block_size=BS, head_dim=hd)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, nh, 1, hd), q.dtype),
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(tables, lengths, q, k_pool, v_pool)


def paged_decode_attention(q, k_pool, v_pool, li, tables, lengths, *,
                           block_size, interpret=None):
    """Decode attention through the block table.

    q [slots, n_head, 1, head_dim]; k_pool/v_pool the layer-major page pools
    [n_layer, num_blocks, block_size, n_head, head_dim]; ``li`` the (static)
    layer; tables [slots, max_blocks] int32 page ids; lengths [slots] valid
    history lengths (pos + 1). Returns [slots, n_head, 1, head_dim] in
    q.dtype. ``interpret`` defaults to True off-TPU."""
    if not _HAS_PLTPU:  # pragma: no cover
        raise RuntimeError("pallas tpu backend unavailable")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _paged_decode(q, k_pool, v_pool,
                         tables.astype(jnp.int32), lengths.astype(jnp.int32),
                         li=int(li), block_size=int(block_size),
                         interpret=bool(interpret))
