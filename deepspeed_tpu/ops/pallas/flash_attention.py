"""Flash attention Pallas kernel (fwd + bwd) for TPU.

TPU-native replacement for the reference's fused attention-softmax CUDA kernels
(``csrc/transformer/softmax_kernels.cu``, ``general_kernels.cu`` attention-score path of
N1): a blocked online-softmax attention that never materializes the [T, T] score matrix.

Design (v5e):
- grid over (batch*heads, q-blocks); the k/v stream is a ``lax.fori_loop`` over k-blocks
  with running (m, l, acc) online-softmax state — classic FlashAttention-2 structure.
- blocks default to 256x512 (tuned on v5e: ~1.7x over 128x128); head_dim <= 256 in VMEM.
- causal masking prunes whole k-blocks above the diagonal (loop bound), and applies the
  triangular mask only on the single diagonal block.
- backward is the standard two-pass flash backward (dq pass over k-blocks; dk/dv pass
  over q-blocks) using the saved LSE; residuals are (q, k, v, out, lse) — O(T) memory.
- ``interpret=True`` fallback keeps CPU tests honest; a dense reference implementation
  (``dense_attention``) is the numerics oracle.
"""

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu is importable on CPU too (interpret mode), but guard anyway
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    _HAS_PLTPU = False

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


def dense_attention(q, k, v, causal=False, sm_scale=None):
    """Reference dense attention ([B,H,T,D] inputs), fp32 softmax."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * sm_scale
    if causal:
        T = q.shape[2]
        mask = jnp.tril(jnp.ones((T, T), jnp.bool_))
        scores = jnp.where(mask, scores, DEFAULT_MASK_VALUE)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(q.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, sm_scale, causal, block_k, seq_len):
    bq = q_ref.shape[0]
    d = q_ref.shape[1]
    q_blk_idx = pl.program_id(1)
    # keep MXU operands in the input dtype (bf16): bf16-in/fp32-accumulate is the MXU's
    # native mode — upcasting to fp32 before the dot ran the matmuls many times slower
    q = q_ref[...]

    num_k_blocks = pl.cdiv(seq_len, block_k)
    if causal:
        # process k blocks up to and including the diagonal block
        last_blk = jnp.minimum(num_k_blocks, (q_blk_idx * bq + bq + block_k - 1) // block_k)
    else:
        last_blk = num_k_blocks

    m0 = jnp.full((bq, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[pl.ds(kb * block_k, block_k), :]
        v_blk = v_ref[pl.ds(kb * block_k, block_k), :]
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * sm_scale  # [bq, bk]
        if causal:
            q_pos = q_blk_idx * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, DEFAULT_MASK_VALUE)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.dot(p.astype(v_blk.dtype), v_blk,
                                        preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, last_blk, body, (m0, l0, acc0))
    l = jnp.maximum(l, 1e-30)
    o_ref[...] = (acc / l).astype(o_ref.dtype)
    lse_ref[...] = (m + jnp.log(l)).reshape(1, bq)


def _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    B, H, T, D = q.shape
    grid = (B * H, pl.cdiv(T, block_q))
    q3 = q.reshape(B * H, T, D)
    k3 = k.reshape(B * H, T, D)
    v3 = v.reshape(B * H, T, D)

    kernel = functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                               block_k=block_k, seq_len=T)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, 1, block_q), lambda b, i: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
            # LSE carried as [B*H, 1, T]: TPU block shapes need the trailing two dims
            # tileable, so the per-row scalar rides in a (1, block_q) lane layout
            jax.ShapeDtypeStruct((B * H, 1, T), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3)
    return out.reshape(B, H, T, D), lse.reshape(B, H, T)


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
                   sm_scale, causal, block_k, seq_len):
    bq, d = q_ref.shape
    q_blk_idx = pl.program_id(1)
    q = q_ref[...]      # input dtype: bf16-in/fp32-out MXU dots (see _fwd_kernel note)
    do = do_ref[...]
    lse = lse_ref[...].reshape(bq, 1)
    delta = delta_ref[...].reshape(bq, 1)

    num_k_blocks = pl.cdiv(seq_len, block_k)
    if causal:
        last_blk = jnp.minimum(num_k_blocks, (q_blk_idx * bq + bq + block_k - 1) // block_k)
    else:
        last_blk = num_k_blocks

    def body(kb, dq):
        k_blk = k_ref[pl.ds(kb * block_k, block_k), :]
        v_blk = v_ref[pl.ds(kb * block_k, block_k), :]
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_pos = q_blk_idx * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, DEFAULT_MASK_VALUE)
        p = jnp.exp(s - lse)
        dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        return dq + jnp.dot(ds.astype(k_blk.dtype), k_blk, preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, last_blk, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[...] = (dq * sm_scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, *,
                    sm_scale, causal, block_q, seq_len):
    bk, d = k_ref.shape
    k_blk_idx = pl.program_id(1)
    k = k_ref[...]      # input dtype: bf16-in/fp32-out MXU dots (see _fwd_kernel note)
    v = v_ref[...]

    num_q_blocks = pl.cdiv(seq_len, block_q)
    if causal:
        first_blk = (k_blk_idx * bk) // block_q
    else:
        first_blk = 0

    def body(qb, carry):
        dk, dv = carry
        q_blk = q_ref[pl.ds(qb * block_q, block_q), :]
        do_blk = do_ref[pl.ds(qb * block_q, block_q), :]
        lse_blk = lse_ref[0, pl.ds(qb * block_q, block_q)].reshape(block_q, 1)
        delta_blk = delta_ref[0, pl.ds(qb * block_q, block_q)].reshape(block_q, 1)
        s = jnp.dot(q_blk, k.T, preferred_element_type=jnp.float32) * sm_scale  # [bq, bk]
        if causal:
            q_pos = qb * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 0)
            k_pos = k_blk_idx * bk + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 1)
            s = jnp.where(q_pos >= k_pos, s, DEFAULT_MASK_VALUE)
        p = jnp.exp(s - lse_blk)
        dv_new = dv + jnp.dot(p.T.astype(do_blk.dtype), do_blk,
                              preferred_element_type=jnp.float32)
        dp = jnp.dot(do_blk, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta_blk)
        dk_new = dk + jnp.dot(ds.T.astype(q_blk.dtype), q_blk,
                              preferred_element_type=jnp.float32)
        return dk_new, dv_new

    dk, dv = jax.lax.fori_loop(first_blk, num_q_blocks, body,
                               (jnp.zeros((bk, d), jnp.float32), jnp.zeros((bk, d), jnp.float32)))
    dk_ref[...] = (dk * sm_scale).astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


def _flash_bwd(res, g, sm_scale, causal, block_q, block_k, interpret):
    q, k, v, out, lse = res
    B, H, T, D = q.shape
    do = g
    # delta = rowsum(do * o): the softmax-normalization correction term
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)  # [B,H,T]

    q3 = q.reshape(B * H, T, D)
    k3 = k.reshape(B * H, T, D)
    v3 = v.reshape(B * H, T, D)
    do3 = do.reshape(B * H, T, D)
    lse3 = lse.reshape(B * H, 1, T)
    delta3 = delta.reshape(B * H, 1, T)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block_k=block_k, seq_len=T),
        grid=(B * H, pl.cdiv(T, block_q)),
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, 1, block_q), lambda b, i: (b, 0, i)),
            pl.BlockSpec((None, 1, block_q), lambda b, i: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
        interpret=interpret,
    )(q3, k3, v3, do3, lse3, delta3)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, seq_len=T),
        grid=(B * H, pl.cdiv(T, block_k)),
        in_specs=[
            pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, 1, T), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, 1, T), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
        ],
        interpret=interpret,
    )(q3, k3, v3, do3, lse3, delta3)

    return dq.reshape(B, H, T, D), dk.reshape(B, H, T, D), dv.reshape(B, H, T, D)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal: bool = False, sm_scale: Optional[float] = None,
                    block_q: int = 256, block_k: int = 512, interpret: Optional[bool] = None):
    """Blocked flash attention on [B, H, T, D] tensors. Differentiable."""
    out, _ = _flash_attention_fwd_rule(q, k, v, causal, sm_scale, block_q, block_k, interpret)
    return out


def _resolve(q, sm_scale, block_q, block_k, interpret):
    T = q.shape[2]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])

    def fit(b):
        # largest power-of-two-reduced block that divides the sequence length
        b = min(b, T)
        while T % b != 0:
            b //= 2
        return max(b, 1)

    block_q = fit(block_q)
    block_k = fit(block_k)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return sm_scale, block_q, block_k, interpret


def _flash_attention_fwd_rule(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    sm_scale_, bq, bk, interp = _resolve(q, sm_scale, block_q, block_k, interpret)
    assert q.shape[2] % bq == 0 and q.shape[2] % bk == 0, \
        f"seq_len {q.shape[2]} must be divisible by block sizes ({bq}, {bk})"
    out, lse = _flash_fwd(q, k, v, sm_scale_, causal, bq, bk, interp)
    return out, (q, k, v, out, lse)


def _flash_attention_bwd_rule(causal, sm_scale, block_q, block_k, interpret, res, g):
    q = res[0]
    sm_scale_, bq, bk, interp = _resolve(q, sm_scale, block_q, block_k, interpret)
    dq, dk, dv = _flash_bwd(res, g, sm_scale_, causal, bq, bk, interp)
    return dq, dk, dv


flash_attention.defvjp(_flash_attention_fwd_rule, _flash_attention_bwd_rule)
