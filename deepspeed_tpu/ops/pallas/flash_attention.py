"""Flash attention Pallas kernel (fwd + bwd) for TPU.

TPU-native replacement for the reference's fused attention-softmax CUDA kernels
(``csrc/transformer/softmax_kernels.cu``, ``general_kernels.cu`` attention-score path of
N1): a blocked online-softmax attention that never materializes the [T, T] score matrix.

Design (v5e):
- grid over (batch*heads, q-blocks); the k/v stream is a ``lax.fori_loop`` over k-blocks
  with running (m, l, acc) online-softmax state — classic FlashAttention-2 structure.
- blocks default to 256x512 (tuned on v5e: ~1.7x over 128x128); head_dim <= 256 in VMEM.
- causal masking prunes whole k-blocks above the diagonal (loop bound), and applies the
  triangular mask only on the single diagonal block.
- backward is the standard two-pass flash backward (dq pass over k-blocks; dk/dv pass
  over q-blocks) using the saved LSE; residuals are (q, k, v, out, lse) — O(T) memory.
- ``interpret=True`` fallback keeps CPU tests honest; a dense reference implementation
  (``dense_attention``) is the numerics oracle.
"""

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # pltpu is importable on CPU too (interpret mode), but guard anyway
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    _HAS_PLTPU = False

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


def dense_attention(q, k, v, causal=False, sm_scale=None, bias=None, dropout_keep=None):
    """Reference dense attention ([B,H,T,D] inputs), fp32 softmax.

    ``bias``: additive key bias [B, 1, T_k] (the BERT padding mask).
    ``dropout_keep``: pre-scaled multiplicative mask on the post-softmax probs
    (e.g. from ``dropout_keep_reference``) — the numerics oracle for the kernel.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * sm_scale
    if bias is not None:
        scores = scores + bias.astype(jnp.float32)[:, :, None, :]  # [B,1,1,Tk]
    if causal:
        T = q.shape[2]
        mask = jnp.tril(jnp.ones((T, T), jnp.bool_))
        scores = jnp.where(mask, scores, DEFAULT_MASK_VALUE)
    probs = jax.nn.softmax(scores, axis=-1)
    if dropout_keep is not None:
        probs = probs * dropout_keep.astype(probs.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(q.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


# ---------------------------------------------------------------------------
# in-kernel attention dropout
# ---------------------------------------------------------------------------
# Stateless counter-based dropout: a lowbias32-style integer avalanche over the
# ABSOLUTE coordinate (batch*head, q position, k position) plus the step seed. Because
# the bits depend only on coordinates — never on block shapes or grid order — the
# forward kernel and both backward kernels regenerate bit-identical masks, remat
# replays them exactly (the seed is a traced operand), and a pure-jnp oracle
# (``dropout_keep_reference``) exists for parity tests. This replaces the reference's
# CUDA RNG state tracker + curand path (csrc/transformer/dropout_kernels.cu).

def _dropout_bits(seed_u32, bh_u32, q_pos, k_pos):
    """uint32 hash; inputs broadcast, q_pos/k_pos int32 arrays."""
    x = (q_pos.astype(jnp.uint32) * jnp.uint32(0x9E3779B1)
         + k_pos.astype(jnp.uint32) * jnp.uint32(0x85EBCA77)
         + bh_u32 * jnp.uint32(0xC2B2AE3D)
         + seed_u32)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    return x


def _keep_threshold(rate: float) -> int:
    return min(int(rate * 4294967296.0), 4294967295)


def dropout_keep_reference(seed, B, H, T_q, T_k, rate: float):
    """[B, H, T_q, T_k] pre-scaled keep mask identical to the in-kernel stream."""
    seed_u32 = jnp.asarray(seed, jnp.int32).reshape(()).astype(jnp.uint32)
    bh = jnp.arange(B * H, dtype=jnp.uint32)[:, None, None]
    qp = jnp.arange(T_q, dtype=jnp.int32)[None, :, None]
    kp = jnp.arange(T_k, dtype=jnp.int32)[None, None, :]
    bits = _dropout_bits(seed_u32, bh, qp, kp)
    keep = (bits >= jnp.uint32(_keep_threshold(rate))).astype(jnp.float32)
    return (keep / (1.0 - rate)).reshape(B, H, T_q, T_k)


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------

LOG2E = 1.4426950408889634  # 1/ln(2): softmax runs in base 2 (exp2 is the cheaper
# VPU transcendental, and folding sm_scale*log2e into q kills a per-tile scale pass)


def _read_seed_ref(seed_ref, seg):
    """Unpack the SMEM seed/offset operand.

    Contiguous form (3,): ``[seed, q_off, k_off]`` — global position is local
    position plus the scalar offset.
    Segmented form (7,): ``[seed, q_off0, k_off0, q_half, q_off1, k_half, k_off1]``
    — the local sequence is two concatenated global segments (zigzag ring layout):
    local positions ``< *_half`` start at ``*_off0``, the rest at ``*_off1``.
    Returns ``(seed_u32, map_q, map_k)`` where the maps take local int32 position
    arrays to global coordinates.
    """
    seed_u32 = seed_ref[0].astype(jnp.uint32)
    q_off, k_off = seed_ref[1], seed_ref[2]
    if seg:
        q_half, q_off1 = seed_ref[3], seed_ref[4]
        k_half, k_off1 = seed_ref[5], seed_ref[6]
        map_q = lambda p: p + jnp.where(p < q_half, q_off, q_off1 - q_half)
        map_k = lambda p: p + jnp.where(p < k_half, k_off, k_off1 - k_half)
    else:
        map_q = lambda p: p + q_off
        map_k = lambda p: p + k_off
    return seed_u32, map_q, map_k


def _fwd_kernel(*refs, sm_scale, causal, block_k, seq_len, has_bias, rate, threshold,
                has_seed, seg):
    i = 0
    seed_ref = None
    bias_ref = None
    if has_seed:
        seed_ref = refs[i]
        i += 1
    if has_bias:
        bias_ref = refs[i]
        i += 1
    q_ref, k_ref, v_ref, o_ref, lse_ref = refs[i:]
    bq = q_ref.shape[0]
    d = q_ref.shape[1]
    q_blk_idx = pl.program_id(1)
    # keep MXU operands in the input dtype (bf16): bf16-in/fp32-accumulate is the MXU's
    # native mode — upcasting to fp32 before the dot ran the matmuls many times slower.
    # sm_scale*log2e is pre-folded into q: scores come out of the MXU in base-2 units.
    q = (q_ref[...].astype(jnp.float32) * (sm_scale * LOG2E)).astype(q_ref.dtype)
    if has_seed:
        # see _read_seed_ref: the operand translates this call's LOCAL positions into
        # GLOBAL sequence coordinates for the dropout hash (and, in the segmented
        # zigzag layout, the causal mask), so chunked long-context tiles and
        # ring-attention shards regenerate the same bit stream / mask a single
        # whole-sequence kernel would.
        seed_u32, map_q, map_k = _read_seed_ref(seed_ref, seg)
        bh_u32 = pl.program_id(0).astype(jnp.uint32)
    if rate > 0:
        inv_keep = 1.0 / (1.0 - rate)

    num_k_blocks = pl.cdiv(seq_len, block_k)
    if causal:
        # process k blocks up to and including the diagonal block. The bounds use
        # LOCAL indices — exact for segmented layouts too, because causal segmented
        # calls require identical, monotone q/k segment maps (zigzag: both sides are
        # the same [chunk i, chunk 2n-1-i] interleave), under which local order
        # equals global order.
        last_blk = jnp.minimum(num_k_blocks, (q_blk_idx * bq + bq + block_k - 1) // block_k)
        # blocks strictly below the diagonal need no mask: max k_pos <= min q_pos
        n_full = jnp.minimum(last_blk, (q_blk_idx * bq + 1) // block_k)
    else:
        last_blk = num_k_blocks
        n_full = num_k_blocks

    m0 = jnp.full((bq, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)

    def make_body(masked):
        def body(kb, carry):
            m, l, acc = carry
            k_blk = k_ref[pl.ds(kb * block_k, block_k), :]
            v_blk = v_ref[pl.ds(kb * block_k, block_k), :]
            s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)  # [bq, bk] base-2
            if has_bias:
                s = s + bias_ref[:, pl.ds(kb * block_k, block_k)] * LOG2E
            if masked or rate > 0:
                q_pos = q_blk_idx * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
                k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
                if has_seed:
                    q_glob, k_glob = map_q(q_pos), map_k(k_pos)
                else:
                    q_glob, k_glob = q_pos, k_pos
            if masked:
                s = jnp.where(q_glob >= k_glob, s, DEFAULT_MASK_VALUE)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp2(s - m_new)
            alpha = jnp.exp2(m - m_new)
            # the normalizer uses the UNdropped probabilities (torch dropout(softmax(s)))
            l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
            if rate > 0:
                bits = _dropout_bits(seed_u32, bh_u32, q_glob, k_glob)
                keep = (bits >= jnp.uint32(threshold)).astype(jnp.float32) * inv_keep
                p_eff = p * keep
            else:
                p_eff = p
            acc_new = acc * alpha + jnp.dot(p_eff.astype(v_blk.dtype), v_blk,
                                            preferred_element_type=jnp.float32)
            return m_new, l_new, acc_new
        return body

    carry = jax.lax.fori_loop(0, n_full, make_body(False), (m0, l0, acc0))
    if causal:
        carry = jax.lax.fori_loop(n_full, last_blk, make_body(True), carry)
    m, l, acc = carry
    l = jnp.maximum(l, 1e-30)
    o_ref[...] = (acc / l).astype(o_ref.dtype)
    # stored LSE stays in natural-log units (m is base-2)
    lse_ref[...] = (m / LOG2E + jnp.log(l)).reshape(1, bq)


def _is_segmented(seed) -> bool:
    """Whether a packed seed/offset operand carries the (7,) segmented layout."""
    return seed is not None and np.shape(seed)[-1] == 7


def _aux_operands(seed, bias, B, H, T, rate, block_k_map=None):
    """(operands, in_specs) for the optional seed/bias inputs shared by all kernels.

    ``block_k_map``: None -> each grid cell sees the full [1, T] bias row; otherwise a
    (block, index_map) pair for k-blocked bias tiles.
    """
    operands, specs = [], []
    if seed is not None:
        # packed (3,) or (7,) offset operand — see _read_seed_ref on the
        # global-coordinate contract for the dropout hash and segmented causal mask
        operands.append(jnp.asarray(seed, jnp.int32))
        specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
    if bias is not None:
        operands.append(jnp.asarray(bias, jnp.float32).reshape(B, 1, T))
        if block_k_map is None:
            specs.append(pl.BlockSpec((None, 1, T), lambda b, i, H=H: (b // H, 0, 0)))
        else:
            blk, imap = block_k_map
            specs.append(pl.BlockSpec((None, 1, blk), imap))
    return operands, specs


def _flash_fwd(q, k, v, seed, bias, sm_scale, causal, rate, block_q, block_k, interpret):
    B, H, T, D = q.shape
    grid = (B * H, pl.cdiv(T, block_q))
    q3 = q.reshape(B * H, T, D)
    k3 = k.reshape(B * H, T, D)
    v3 = v.reshape(B * H, T, D)

    kernel = functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                               block_k=block_k, seq_len=T, has_bias=bias is not None,
                               rate=rate, threshold=_keep_threshold(rate),
                               has_seed=seed is not None, seg=_is_segmented(seed))
    aux, aux_specs = _aux_operands(seed, bias, B, H, T, rate)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=aux_specs + [
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, 1, block_q), lambda b, i: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
            # LSE carried as [B*H, 1, T]: TPU block shapes need the trailing two dims
            # tileable, so the per-row scalar rides in a (1, block_q) lane layout
            jax.ShapeDtypeStruct((B * H, 1, T), jnp.float32),
        ],
        interpret=interpret,
    )(*aux, q3, k3, v3)
    return out.reshape(B, H, T, D), lse.reshape(B, H, T)


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(*refs, sm_scale, causal, block_k, seq_len, has_bias, rate, threshold,
                   has_seed, seg):
    i = 0
    seed_ref = bias_ref = None
    if has_seed:
        seed_ref = refs[i]
        i += 1
    if has_bias:
        bias_ref = refs[i]
        i += 1
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref = refs[i:]
    bq, d = q_ref.shape
    q_blk_idx = pl.program_id(1)
    # base-2 softmax with sm_scale*log2e folded into q (see _fwd_kernel)
    q = (q_ref[...].astype(jnp.float32) * (sm_scale * LOG2E)).astype(q_ref.dtype)
    do = do_ref[...]
    lse2 = lse_ref[...].reshape(bq, 1) * LOG2E  # natural -> base-2
    delta = delta_ref[...].reshape(bq, 1)
    if has_seed:
        seed_u32, map_q, map_k = _read_seed_ref(seed_ref, seg)
        bh_u32 = pl.program_id(0).astype(jnp.uint32)
    if rate > 0:
        inv_keep = 1.0 / (1.0 - rate)

    num_k_blocks = pl.cdiv(seq_len, block_k)
    if causal:
        last_blk = jnp.minimum(num_k_blocks, (q_blk_idx * bq + bq + block_k - 1) // block_k)
        n_full = jnp.minimum(last_blk, (q_blk_idx * bq + 1) // block_k)
    else:
        last_blk = num_k_blocks
        n_full = num_k_blocks

    def make_body(masked):
        def body(kb, dq):
            k_blk = k_ref[pl.ds(kb * block_k, block_k), :]
            v_blk = v_ref[pl.ds(kb * block_k, block_k), :]
            s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
            if has_bias:
                s = s + bias_ref[:, pl.ds(kb * block_k, block_k)] * LOG2E
            if masked or rate > 0:
                q_pos = q_blk_idx * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
                k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
                if has_seed:
                    q_glob, k_glob = map_q(q_pos), map_k(k_pos)
                else:
                    q_glob, k_glob = q_pos, k_pos
            if masked:
                s = jnp.where(q_glob >= k_glob, s, DEFAULT_MASK_VALUE)
            p = jnp.exp2(s - lse2)
            dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
            if rate > 0:
                bits = _dropout_bits(seed_u32, bh_u32, q_glob, k_glob)
                dp = dp * ((bits >= jnp.uint32(threshold)).astype(jnp.float32) * inv_keep)
            ds = p * (dp - delta)
            return dq + jnp.dot(ds.astype(k_blk.dtype), k_blk, preferred_element_type=jnp.float32)
        return body

    dq = jax.lax.fori_loop(0, n_full, make_body(False), jnp.zeros((bq, d), jnp.float32))
    if causal:
        dq = jax.lax.fori_loop(n_full, last_blk, make_body(True), dq)
    dq_ref[...] = (dq * sm_scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, sm_scale, causal, block_q, seq_len, has_bias, rate, threshold,
                    has_seed, seg):
    i = 0
    seed_ref = bias_ref = None
    if has_seed:
        seed_ref = refs[i]
        i += 1
    if has_bias:
        bias_ref = refs[i]
        i += 1
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref = refs[i:]
    bk, d = k_ref.shape
    k_blk_idx = pl.program_id(1)
    # base-2 softmax: fold sm_scale*log2e into K here (q stays raw in this kernel)
    k = (k_ref[...].astype(jnp.float32) * (sm_scale * LOG2E)).astype(k_ref.dtype)
    v = v_ref[...]
    if has_seed:
        seed_u32, map_q, map_k = _read_seed_ref(seed_ref, seg)
        bh_u32 = pl.program_id(0).astype(jnp.uint32)
    if rate > 0:
        inv_keep = 1.0 / (1.0 - rate)

    num_q_blocks = pl.cdiv(seq_len, block_q)
    if causal:
        first_blk = (k_blk_idx * bk) // block_q
        # q blocks whose min q_pos covers this k block's max k_pos need no mask
        full_from = jnp.minimum(num_q_blocks,
                                ((k_blk_idx + 1) * bk - 1 + block_q - 1) // block_q)
    else:
        first_blk = 0
        full_from = 0

    def make_body(masked):
        def body(qb, carry):
            dk, dv = carry
            q_blk = q_ref[pl.ds(qb * block_q, block_q), :]
            do_blk = do_ref[pl.ds(qb * block_q, block_q), :]
            lse2_blk = lse_ref[0, pl.ds(qb * block_q, block_q)].reshape(block_q, 1) * LOG2E
            delta_blk = delta_ref[0, pl.ds(qb * block_q, block_q)].reshape(block_q, 1)
            s = jnp.dot(q_blk, k.T, preferred_element_type=jnp.float32)  # [bq, bk] base-2
            if has_bias:
                s = s + bias_ref[...] * LOG2E  # [1, bk]: this k-block's bias tile
            if masked or rate > 0:
                q_pos = qb * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 0)
                k_pos = k_blk_idx * bk + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 1)
                if has_seed:
                    q_glob, k_glob = map_q(q_pos), map_k(k_pos)
                else:
                    q_glob, k_glob = q_pos, k_pos
            if masked:
                s = jnp.where(q_glob >= k_glob, s, DEFAULT_MASK_VALUE)
            p = jnp.exp2(s - lse2_blk)
            if rate > 0:
                bits = _dropout_bits(seed_u32, bh_u32, q_glob, k_glob)
                keep = (bits >= jnp.uint32(threshold)).astype(jnp.float32) * inv_keep
                p_drop = p * keep
            else:
                p_drop = p
            dv_new = dv + jnp.dot(p_drop.T.astype(do_blk.dtype), do_blk,
                                  preferred_element_type=jnp.float32)
            dp = jnp.dot(do_blk, v.T, preferred_element_type=jnp.float32)
            if rate > 0:
                dp = dp * keep
            ds = p * (dp - delta_blk)
            dk_new = dk + jnp.dot(ds.T.astype(q_blk.dtype), q_blk,
                                  preferred_element_type=jnp.float32)
            return dk_new, dv_new
        return body

    init = (jnp.zeros((bk, d), jnp.float32), jnp.zeros((bk, d), jnp.float32))
    if causal:
        carry = jax.lax.fori_loop(first_blk, full_from, make_body(True), init)
        dk, dv = jax.lax.fori_loop(full_from, num_q_blocks, make_body(False), carry)
    else:
        dk, dv = jax.lax.fori_loop(0, num_q_blocks, make_body(False), init)
    dk_ref[...] = (dk * sm_scale).astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


def _flash_bwd(res, g, seed, bias, sm_scale, causal, rate, block_q, block_k, interpret,
               g_lse=None):
    q, k, v, out, lse = res
    B, H, T, D = q.shape
    do = g
    # delta = rowsum(do * o): the softmax-normalization correction term (valid under
    # dropout too: do.o = sum_j probs_j * keep_j * (do.v_j) = sum_j probs_j * dprobs_j)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)  # [B,H,T]
    if g_lse is not None:
        # An LSE cotangent folds into delta: dL/ds_ij gains g_lse_i * p_ij (softmax
        # jacobian of logsumexp), so ds = p*(dp - (delta - g_lse)) — the whole lse
        # gradient costs one subtraction. dv is untouched (lse doesn't read V).
        delta = delta - g_lse.astype(jnp.float32)

    q3 = q.reshape(B * H, T, D)
    k3 = k.reshape(B * H, T, D)
    v3 = v.reshape(B * H, T, D)
    do3 = do.reshape(B * H, T, D)
    lse3 = lse.reshape(B * H, 1, T)
    delta3 = delta.reshape(B * H, 1, T)
    has_bias = bias is not None

    aux, aux_specs = _aux_operands(seed, bias, B, H, T, rate)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block_k=block_k, seq_len=T, has_bias=has_bias, rate=rate,
                          threshold=_keep_threshold(rate),
                          has_seed=seed is not None, seg=_is_segmented(seed)),
        grid=(B * H, pl.cdiv(T, block_q)),
        in_specs=aux_specs + [
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, 1, block_q), lambda b, i: (b, 0, i)),
            pl.BlockSpec((None, 1, block_q), lambda b, i: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
        interpret=interpret,
    )(*aux, q3, k3, v3, do3, lse3, delta3)

    # the dkv grid iterates k-blocks, so its bias operand is tiled per k-block
    aux2, aux2_specs = _aux_operands(
        seed, bias, B, H, T, rate,
        block_k_map=(block_k, lambda b, i, H=H: (b // H, 0, i)))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, seq_len=T, has_bias=has_bias, rate=rate,
                          threshold=_keep_threshold(rate),
                          has_seed=seed is not None, seg=_is_segmented(seed)),
        grid=(B * H, pl.cdiv(T, block_k)),
        in_specs=aux2_specs + [
            pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, 1, T), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, 1, T), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
        ],
        interpret=interpret,
    )(*aux2, q3, k3, v3, do3, lse3, delta3)

    return dq.reshape(B, H, T, D), dk.reshape(B, H, T, D), dv.reshape(B, H, T, D)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _flash_attention_core(q, k, v, bias, seed, causal, sm_scale, rate, block_q, block_k,
                          interpret):
    out, _ = _core_fwd_rule(q, k, v, bias, seed, causal, sm_scale, rate, block_q, block_k,
                            interpret)
    return out


def _resolve(q, sm_scale, block_q, block_k, causal, interpret):
    T = q.shape[2]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if block_q is None or block_k is None:
        # Measured on v5e (slope-timed, relay fence cancelled; tests/perf/flash_sweep):
        # non-causal T=4096: (1024,1024) 101.6 TF/s vs (256,512) 56.2 — the bigger
        # q tile amortizes per-cell K/V residency; T=8192: (512,1024) 67.6 vs 58.9.
        # Causal prefers small q blocks (diagonal work balance): (256,512).
        if causal or T < 4096:
            dq_, dk_ = 256, 512
        elif T < 8192:
            dq_, dk_ = 1024, 1024
        else:
            dq_, dk_ = 512, 1024
        block_q = block_q or dq_
        block_k = block_k or dk_

    def fit(b):
        # largest power-of-two-reduced block that divides the sequence length
        b = min(b, T)
        while T % b != 0:
            b //= 2
        return max(b, 1)

    block_q = fit(block_q)
    block_k = fit(block_k)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return sm_scale, block_q, block_k, interpret


def _core_fwd_rule(q, k, v, bias, seed, causal, sm_scale, rate, block_q, block_k,
                   interpret):
    sm_scale_, bq, bk, interp = _resolve(q, sm_scale, block_q, block_k, causal,
                                         interpret)
    assert q.shape[2] % bq == 0 and q.shape[2] % bk == 0, \
        f"seq_len {q.shape[2]} must be divisible by block sizes ({bq}, {bk})"
    out, lse = _flash_fwd(q, k, v, seed, bias, sm_scale_, causal, rate, bq, bk, interp)
    # Tag the RESIDUALS (not just downstream values): under jax.checkpoint a
    # name applied by the caller to the kernel's output cannot mark the
    # custom_vjp's own residual vars as saveable, so every remat policy would
    # re-run this forward kernel in backward just to regenerate (out, lse) —
    # measured: tests/perf/remat_flash_probe.py showed fwd_replayed == n_layers
    # for 'dots', 'attn' AND 'dots+attn' before this tag. Naming them here lets
    # save_only_these_names("attn_out", "attn_lse") keep the flash bwd kernels
    # replay-free (fwd_replayed == 0, same probe).
    from jax.ad_checkpoint import checkpoint_name
    return out, (q, k, v, checkpoint_name(out, "attn_out"),
                 checkpoint_name(lse, "attn_lse"), bias, seed)


def _core_bwd_rule(causal, sm_scale, rate, block_q, block_k, interpret, res, g):
    q, k, v, out, lse, bias, seed = res
    sm_scale_, bq, bk, interp = _resolve(q, sm_scale, block_q, block_k, causal,
                                         interpret)
    dq, dk, dv = _flash_bwd((q, k, v, out, lse), g, seed, bias, sm_scale_, causal, rate,
                            bq, bk, interp)
    # bias is the (non-trainable) padding mask: cotangent is zero by contract; seed is
    # integer-valued, whose tangent space is float0
    dbias = None if bias is None else jnp.zeros_like(bias)
    dseed = None if seed is None else np.zeros(np.shape(seed), jax.dtypes.float0)
    return dq, dk, dv, dbias, dseed


_flash_attention_core.defvjp(_core_fwd_rule, _core_bwd_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _flash_attention_core_lse(q, k, v, bias, seed, causal, sm_scale, rate, block_q,
                              block_k, interpret):
    out, res = _core_fwd_rule(q, k, v, bias, seed, causal, sm_scale, rate, block_q,
                              block_k, interpret)
    return out, res[4]


def _core_lse_fwd(q, k, v, bias, seed, causal, sm_scale, rate, block_q, block_k,
                  interpret):
    out, res = _core_fwd_rule(q, k, v, bias, seed, causal, sm_scale, rate, block_q,
                              block_k, interpret)
    return (out, res[4]), res


def _core_lse_bwd(causal, sm_scale, rate, block_q, block_k, interpret, res, g):
    g_out, g_lse = g
    q, k, v, out, lse, bias, seed = res
    sm_scale_, bq, bk, interp = _resolve(q, sm_scale, block_q, block_k, causal,
                                         interpret)
    dq, dk, dv = _flash_bwd((q, k, v, out, lse), g_out, seed, bias, sm_scale_, causal,
                            rate, bq, bk, interp, g_lse=g_lse)
    dbias = None if bias is None else jnp.zeros_like(bias)
    dseed = None if seed is None else np.zeros(np.shape(seed), jax.dtypes.float0)
    return dq, dk, dv, dbias, dseed


_flash_attention_core_lse.defvjp(_core_lse_fwd, _core_lse_bwd)


def _seed_vec(seed, q_offset, k_offset):
    """Pack (seed, global q offset, global k offset) into the (3,) int32 operand the
    kernels read from SMEM. Offsets may be traced (ring attention derives them from
    ``axis_index``)."""
    return jnp.stack([jnp.asarray(seed, jnp.int32).reshape(()),
                      jnp.asarray(q_offset, jnp.int32).reshape(()),
                      jnp.asarray(k_offset, jnp.int32).reshape(())])


def _seed_vec_seg(seed, q_segments, k_segments, T_q, T_k,
                  q_offset=0, k_offset=0):
    """Pack the (7,) segmented operand ``[seed, q_off0, k_off0, q_half, q_off1,
    k_half, k_off1]`` (see ``_read_seed_ref``). A ``*_segments`` pair gives the
    global start offsets of the two equal halves of that side's local sequence;
    ``None`` means the side is contiguous at the plain scalar offset (its half
    boundary is pushed past the end so the first branch always wins)."""
    if q_segments is not None:
        q0, q1, qh = q_segments[0], q_segments[1], T_q // 2
    else:
        q0, q1, qh = q_offset, 0, T_q
    if k_segments is not None:
        k0, k1, kh = k_segments[0], k_segments[1], T_k // 2
    else:
        k0, k1, kh = k_offset, 0, T_k
    return jnp.stack([jnp.asarray(x, jnp.int32).reshape(())
                      for x in (seed, q0, k0, qh, q1, kh, k1)])


def flash_attention_with_lse(q, k, v, causal: bool = False,
                             sm_scale: Optional[float] = None,
                             block_q: Optional[int] = None,
                             block_k: Optional[int] = None,
                             interpret: Optional[bool] = None,
                             dropout_rate: float = 0.0, dropout_seed=None,
                             dropout_q_offset=0, dropout_k_offset=0,
                             q_segments=None, k_segments=None):
    """Flash attention returning ``(out, lse)``, BOTH differentiable.

    ``lse`` is the per-row log-sum-exp of the scaled scores ([B, H, T_q], natural
    log) — the quantity sequence-parallel/ring attention combines across k/v chunks
    (parallel/ring_attention.py). The lse cotangent folds into the standard flash
    backward's delta term, so the extra gradient is effectively free.

    ``dropout_q_offset``/``dropout_k_offset`` translate this call's local positions
    into global sequence coordinates for the dropout PRNG, so chunk/ring callers
    sample the same mask a whole-sequence kernel would (they may be traced values).

    ``q_segments``/``k_segments``: optional ``(off0, off1)`` pairs declaring that
    side's local sequence to be TWO concatenated global segments of equal length
    (the zigzag ring's [chunk i, chunk 2n-1-i] interleave): local position ``p``
    maps to global ``off0 + p`` in the first half and ``off1 + (p - half)`` in the
    second. Both the causal mask and the dropout hash then run in global
    coordinates. A causal segmented call requires q_segments == k_segments with
    ``off0 < off1`` (identical monotone maps keep the kernel's local block-pruning
    bounds exact); offsets may be traced. Overrides ``dropout_*_offset`` for the
    segmented side.
    """
    rate = float(dropout_rate)
    if rate > 0:
        assert dropout_seed is not None, "dropout_rate > 0 requires a dropout_seed"
    segmented = q_segments is not None or k_segments is not None
    if segmented and causal:
        assert q_segments is not None and k_segments is not None, (
            "causal segmented attention requires BOTH q_segments and k_segments "
            "(identical maps keep local block pruning exact)")
    if segmented and (causal or rate > 0):
        seed = _seed_vec_seg(dropout_seed if dropout_seed is not None else 0,
                             q_segments, k_segments, q.shape[2], k.shape[2],
                             dropout_q_offset, dropout_k_offset)
    elif rate > 0:
        seed = _seed_vec(dropout_seed, dropout_q_offset, dropout_k_offset)
    else:
        seed = None
    return _flash_attention_core_lse(q, k, v, None, seed, bool(causal), sm_scale,
                                     rate, block_q, block_k, interpret)


def _merge_partial(o, lse, o_new, lse_new):
    """Online-softmax merge of normalized partials (fp32 accumulator)."""
    lse_out = jnp.logaddexp(lse, lse_new)
    o_out = (o * jnp.exp(lse - lse_out)[..., None]
             + o_new.astype(jnp.float32) * jnp.exp(lse_new - lse_out)[..., None])
    return o_out, lse_out


# The whole-K/V-resident kernel exceeds scoped VMEM (16 MB) past this sequence
# length at d=64 (measured: T=16384 needs 16.16 MB); longer single-chip sequences
# stream K/V in chunks below.
_RESIDENT_T_LIMIT = 8192


def _flash_attention_chunked(q, k, v, causal, sm_scale, interpret, chunk,
                             rate=0.0, seed=None, block_q=None, block_k=None):
    """Single-chip long-context flash: decompose the [T, T] attention into equal
    ``chunk x chunk`` tiles, run the resident kernel per (q-chunk, k-chunk) pair
    and merge each q-chunk's (out, lse) partials — the sequential analog of ring
    attention's combine (same `flash_attention_with_lse` + online merge, so fully
    differentiable; one compiled kernel shape reused for every pair). Causal is
    EXACT with no wasted compute: a q-chunk visits only its <= k-chunks, the
    diagonal pair with the in-kernel triangular mask. Attention dropout works at
    any length: each tile hashes GLOBAL (q, k) coordinates via the per-tile
    offsets, so the sampled mask equals the whole-sequence kernel's
    (``dropout_keep_reference`` at full T is the oracle)."""
    B, H, T, D = q.shape
    n = T // chunk
    rows = []
    for i in range(n):
        qi = q[:, :, i * chunk:(i + 1) * chunk]
        o = lse = None
        for c in range(i + 1 if causal else n):
            ks = k[:, :, c * chunk:(c + 1) * chunk]
            vs = v[:, :, c * chunk:(c + 1) * chunk]
            oc, lc = flash_attention_with_lse(qi, ks, vs, causal=(causal and c == i),
                                              sm_scale=sm_scale, interpret=interpret,
                                              block_q=block_q, block_k=block_k,
                                              dropout_rate=rate, dropout_seed=seed,
                                              dropout_q_offset=i * chunk,
                                              dropout_k_offset=c * chunk)
            if o is None:  # adopt the first partial; no merge against -inf init
                o, lse = oc.astype(jnp.float32), lc
            else:
                o, lse = _merge_partial(o, lse, oc, lc)
        rows.append(o)
    return jnp.concatenate(rows, axis=2).astype(q.dtype)


def _chunk_for(T: int) -> int:
    """Largest divisor of T not exceeding the resident VMEM ceiling (halving from
    the limit keeps chunks 128-aligned for any even T)."""
    c = _RESIDENT_T_LIMIT
    while c > 1 and T % c != 0:
        c //= 2
    return c


def flash_attention(q, k, v, causal: bool = False, sm_scale: Optional[float] = None,
                    block_q: Optional[int] = None, block_k: Optional[int] = None,
                    interpret: Optional[bool] = None,
                    bias=None, dropout_rate: float = 0.0, dropout_seed=None):
    """Blocked flash attention on [B, H, T, D] tensors. Differentiable in q/k/v.

    ``bias``: optional additive key bias, any shape squeezable to [B, T_k] (the BERT
    padding mask [B,1,1,T] included) — fused into the in-kernel softmax, replacing the
    reference's scale+mask softmax kernel (csrc/transformer/softmax_kernels.cu).
    ``bias`` receives NO gradient (it is stop_gradient'ed here): it is a padding/attention
    mask, not a learnable table. Route learnable additive biases (ALiBi slopes, relative
    position tables) through q/k instead.
    ``dropout_rate``/``dropout_seed``: in-kernel attention dropout over the post-softmax
    probabilities (csrc/transformer/dropout_kernels.cu); the seed is a traced operand so
    remat replays identical masks. ``dropout_keep_reference`` reproduces the exact mask
    for parity tests.
    """
    rate = float(dropout_rate)
    if rate > 0:
        assert dropout_seed is not None, "dropout_rate > 0 requires a dropout_seed"
    T_k = k.shape[2]
    if T_k > _RESIDENT_T_LIMIT and not (interpret or jax.default_backend() != "tpu"):
        # Past the resident kernel's scoped-VMEM ceiling (the K/V operands are
        # whole-sequence-resident regardless of block sizes): decompose into chunk
        # tiles. Dropout works at any length (tiles hash global coordinates); an
        # additive bias or non-square attention cannot take the chunked path, and
        # silently compiling the resident kernel would fail deep inside Mosaic —
        # raise the constraint instead.
        chunk = _chunk_for(T_k)
        if q.shape[2] == T_k and bias is None and chunk >= 1024:
            return _flash_attention_chunked(q, k, v, bool(causal), sm_scale, interpret,
                                            chunk=chunk, rate=rate, seed=dropout_seed,
                                            block_q=block_q, block_k=block_k)
        reasons = []
        if q.shape[2] != T_k:
            reasons.append(f"q_len ({q.shape[2]}) != k_len ({T_k}) — chunking assumes "
                           "square self-attention")
        if bias is not None:
            reasons.append("an additive bias is not supported on the chunked path "
                           "(fold padding into shorter sequences or segment masks)")
        if chunk < 1024:
            reasons.append(f"seq_len {T_k} has no divisor chunk >= 1024 (largest: "
                           f"{chunk}) — pad the sequence to a multiple of 1024")
        raise ValueError(
            f"flash_attention: seq_len {T_k} exceeds the whole-K/V-resident kernel's "
            f"scoped-VMEM ceiling (T <= {_RESIDENT_T_LIMIT}) and the chunked "
            f"long-context path is ineligible: {'; '.join(reasons)}.")
    seed = _seed_vec(dropout_seed, 0, 0) if rate > 0 else None
    if bias is not None:
        B, T_k = q.shape[0], k.shape[2]
        # no-grad contract made explicit in the jaxpr: a learnable bias passed here
        # would otherwise silently train with zero gradient (see docstring)
        bias = jax.lax.stop_gradient(jnp.asarray(bias, jnp.float32).reshape(B, 1, T_k))
    return _flash_attention_core(q, k, v, bias, seed, bool(causal), sm_scale, rate,
                                 block_q, block_k, interpret)
