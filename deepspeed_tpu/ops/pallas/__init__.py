from .flash_attention import flash_attention, dense_attention
from .fused_block import fused_transformer_block, fused_block_reference
