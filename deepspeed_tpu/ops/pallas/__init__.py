from .flash_attention import flash_attention, dense_attention
