"""Fused transformer-block attention half (LN + qkv + attention + residual).

Pallas counterpart of the reference's fused CUDA transformer op
(``csrc/transformer/transform_kernels.cu`` + the fused softmax path): one kernel
computes ``x + proj(attn(qkv(layernorm(x))))`` per q-tile, so the normalized
hidden states, the qkv activations, the [T, T] score matrix and the pre-residual
attention output never round-trip through HBM. The roofline ledger
(``ds-tpu anatomy``) prices exactly this path as HBM-bound: at GPT-2 shapes the
unfused forward writes ~7 intermediate [B, T, E]-class tensors per block; the
fused kernel writes one.

Design:
- grid ``(B, T // block_q)``; the second dimension is sequential, so the kernel
  primes whole-row K and V into VMEM scratch once per batch row (at q-block 0:
  full-row LN + the k/v thirds of the fused qkv matmul) and every q-tile
  iteration reads them back from VMEM — the sequential-grid analog of flash
  attention's streamed k/v, with the projection fused in front.
- per-head attention runs over the resident K/V with an fp32 softmax; the
  [block_q, T] score tile lives only in registers/VMEM.
- the whole block's weights (w_qkv [E, 3E], w_proj [E, E]) are VMEM-resident,
  which caps the kernel at moderate widths: bf16 GPT-2 base (E=768, T=1024)
  uses ~10 MB of the ~16 MB scope; past that, keep the unfused path.
- backward: ``custom_vjp`` whose bwd differentiates the pure-jnp reference
  (``fused_block_reference``) at the saved primals — fused forward, XLA
  backward. Gradients are exactly the reference's; the forward values differ
  from the reference only by kernel rounding (one fewer dtype round-trip).
- ``interpret=True`` (auto on CPU) keeps the parity tests honest off-TPU.

Constraints: no attention dropout (route ``config.dropout > 0`` through the
unfused path), self-attention only, E divisible by n_head, T divisible by the
resolved block_q. On real TPUs prefer E a multiple of 128 (lane alignment).
"""

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # importable on CPU too (interpret mode), but guard anyway
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    _HAS_PLTPU = False

_MASK_VALUE = -1e9  # matches the model's dense causal mask (python scalar:
# a jnp constant would be captured by the kernel closure, which pallas rejects)


def fused_block_reference(x, ln_scale, ln_bias, w_qkv, b_qkv, w_proj, b_proj,
                          n_head: int, causal: bool = True,
                          sm_scale: Optional[float] = None, eps: float = 1e-5):
    """Pure-jnp oracle, mirroring ``GPT2Model._layer_norm`` + ``_attention``'s
    dense path + the residual add (models/gpt2.py). Differentiable; the fused
    kernel's custom_vjp backward runs ``jax.vjp`` of this function."""
    B, T, E = x.shape
    D = E // n_head
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    h = ((xf - mean) * jax.lax.rsqrt(var + eps)
         * ln_scale + ln_bias).astype(x.dtype)
    qkv = (jnp.dot(h, w_qkv.astype(x.dtype), preferred_element_type=jnp.float32)
           .astype(x.dtype) + b_qkv.astype(x.dtype))
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, T, n_head, D).transpose(0, 2, 1, 3)
    k = k.reshape(B, T, n_head, D).transpose(0, 2, 1, 3)
    v = v.reshape(B, T, n_head, D).transpose(0, 2, 1, 3)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * sm_scale
    if causal:
        mask = jnp.tril(jnp.ones((T, T), jnp.bool_))
        s = jnp.where(mask, s, _MASK_VALUE)
    p = jax.nn.softmax(s, axis=-1).astype(x.dtype)
    y = jnp.einsum("bhqk,bhkd->bhqd", p, v,
                   preferred_element_type=jnp.float32).astype(x.dtype)
    y = y.transpose(0, 2, 1, 3).reshape(B, T, E)
    out = jnp.dot(y, w_proj.astype(x.dtype), preferred_element_type=jnp.float32)
    return x + (out.astype(x.dtype) + b_proj.astype(x.dtype))


def _fused_block_kernel(x_full_ref, x_tile_ref, scale_ref, bias_ref, wqkv_ref,
                        bqkv_ref, wproj_ref, bproj_ref, o_ref, k_s, v_s, *,
                        n_head, sm_scale, eps, causal, block_q):
    E = x_tile_ref.shape[-1]
    D = E // n_head
    T = x_full_ref.shape[0]
    qb = pl.program_id(1)

    def ln(xf):  # fp32 in, fp32 out
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
        return ((xf - mean) * jax.lax.rsqrt(var + eps)
                * scale_ref[0, :] + bias_ref[0, :])

    # prime whole-row K/V once per batch row: the grid's second dimension is
    # sequential, so the scratch persists across this row's q-tiles
    @pl.when(qb == 0)
    def _prime_kv():
        h = ln(x_full_ref[...].astype(jnp.float32)).astype(x_full_ref.dtype)
        k_s[...] = (jnp.dot(h, wqkv_ref[:, E:2 * E],
                            preferred_element_type=jnp.float32)
                    + bqkv_ref[0, E:2 * E]).astype(k_s.dtype)
        v_s[...] = (jnp.dot(h, wqkv_ref[:, 2 * E:],
                            preferred_element_type=jnp.float32)
                    + bqkv_ref[0, 2 * E:]).astype(v_s.dtype)

    xt = x_tile_ref[...]                                        # [bq, E]
    hq = ln(xt.astype(jnp.float32)).astype(xt.dtype)
    q_all = (jnp.dot(hq, wqkv_ref[:, :E], preferred_element_type=jnp.float32)
             + bqkv_ref[0, :E]).astype(xt.dtype)                # [bq, E]

    if causal:
        q_pos = qb * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, T), 0)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, (block_q, T), 1)
        keep = q_pos >= k_pos
    heads = []
    for hd in range(n_head):
        qh = q_all[:, hd * D:(hd + 1) * D]
        kh = k_s[:, hd * D:(hd + 1) * D]
        vh = v_s[:, hd * D:(hd + 1) * D]
        s = jnp.dot(qh, kh.T, preferred_element_type=jnp.float32) * sm_scale
        if causal:
            s = jnp.where(keep, s, _MASK_VALUE)
        p = jax.nn.softmax(s, axis=-1).astype(vh.dtype)
        heads.append(jnp.dot(p, vh, preferred_element_type=jnp.float32)
                     .astype(xt.dtype))
    y = jnp.concatenate(heads, axis=-1)                         # [bq, E]
    out = jnp.dot(y, wproj_ref[...], preferred_element_type=jnp.float32)
    o_ref[...] = (xt.astype(jnp.float32) + out.astype(jnp.float32)
                  + bproj_ref[0, :]).astype(o_ref.dtype)


def _fused_block_impl(x, ln_scale, ln_bias, w_qkv, b_qkv, w_proj, b_proj,
                      n_head, causal, sm_scale, eps, block_q, interpret):
    B, T, E = x.shape
    grid = (B, T // block_q)
    kernel = functools.partial(_fused_block_kernel, n_head=n_head,
                               sm_scale=sm_scale, eps=eps, causal=causal,
                               block_q=block_q)
    dt = x.dtype
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, T, E), lambda b, i: (b, 0, 0)),        # full row
            pl.BlockSpec((None, block_q, E), lambda b, i: (b, i, 0)),  # q tile
            pl.BlockSpec((1, E), lambda b, i: (0, 0)),
            pl.BlockSpec((1, E), lambda b, i: (0, 0)),
            pl.BlockSpec((E, 3 * E), lambda b, i: (0, 0)),
            pl.BlockSpec((1, 3 * E), lambda b, i: (0, 0)),
            pl.BlockSpec((E, E), lambda b, i: (0, 0)),
            pl.BlockSpec((1, E), lambda b, i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, E), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B, T, E), dt),
        scratch_shapes=[pltpu.VMEM((T, E), dt), pltpu.VMEM((T, E), dt)],
        interpret=interpret,
    )(x, x,
      jnp.asarray(ln_scale, jnp.float32).reshape(1, E),
      jnp.asarray(ln_bias, jnp.float32).reshape(1, E),
      w_qkv.astype(dt), jnp.asarray(b_qkv, jnp.float32).reshape(1, 3 * E),
      w_proj.astype(dt), jnp.asarray(b_proj, jnp.float32).reshape(1, E))


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10, 11, 12))
def _fused_block_core(x, ln_scale, ln_bias, w_qkv, b_qkv, w_proj, b_proj,
                      n_head, causal, sm_scale, eps, block_q, interpret):
    return _fused_block_impl(x, ln_scale, ln_bias, w_qkv, b_qkv, w_proj, b_proj,
                             n_head, causal, sm_scale, eps, block_q, interpret)


def _core_fwd(x, ln_scale, ln_bias, w_qkv, b_qkv, w_proj, b_proj,
              n_head, causal, sm_scale, eps, block_q, interpret):
    out = _fused_block_impl(x, ln_scale, ln_bias, w_qkv, b_qkv, w_proj, b_proj,
                            n_head, causal, sm_scale, eps, block_q, interpret)
    return out, (x, ln_scale, ln_bias, w_qkv, b_qkv, w_proj, b_proj)


def _core_bwd(n_head, causal, sm_scale, eps, block_q, interpret, res, g):
    # fused forward, reference backward: differentiate the jnp oracle at the
    # saved primals — XLA fuses this fine, and the gradients are exactly the
    # unfused block's (the kernel only reorders forward rounding)
    ref = functools.partial(fused_block_reference, n_head=n_head, causal=causal,
                            sm_scale=sm_scale, eps=eps)
    _, vjp = jax.vjp(ref, *res)
    return vjp(g)


_fused_block_core.defvjp(_core_fwd, _core_bwd)


def fused_transformer_block(x, ln_scale, ln_bias, w_qkv, b_qkv, w_proj, b_proj,
                            n_head: int, causal: bool = True,
                            sm_scale: Optional[float] = None, eps: float = 1e-5,
                            block_q: Optional[int] = None,
                            interpret: Optional[bool] = None):
    """``x + proj(attention(qkv(layernorm(x))))`` in one Pallas kernel.

    Inputs: ``x`` [B, T, E]; ``ln_scale``/``ln_bias`` [E]; ``w_qkv`` [E, 3E]
    (fused ``[q | k | v]`` layout, the GPT-2 ``c_attn_w``); ``b_qkv`` [3E];
    ``w_proj`` [E, E]; ``b_proj`` [E]. Differentiable in all array arguments
    (see module docstring for the fused-fwd/reference-bwd contract). No
    attention dropout — keep such configs on the unfused path.
    """
    B, T, E = x.shape
    assert E % n_head == 0, f"n_embd {E} must divide by n_head {n_head}"
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(E // n_head)
    if block_q is None:
        block_q = 256
    # largest power-of-two reduction of block_q that divides T
    block_q = min(block_q, T)
    while T % block_q != 0:
        block_q //= 2
    block_q = max(block_q, 1)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _fused_block_core(x, ln_scale, ln_bias, w_qkv, b_qkv, w_proj, b_proj,
                             int(n_head), bool(causal), float(sm_scale),
                             float(eps), int(block_q), bool(interpret))
