"""Block-sparse flash attention driven by SparsityConfig layouts.

TPU-native replacement for the reference's Triton block-sparse stack
(``deepspeed/ops/sparse_attention/{matmul,softmax}.py`` + ``trsrc/*.tr`` + the C++
``sdd_segment`` LUT builder, N4): instead of three kernels (SDD matmul → sparse softmax →
DSD matmul) materializing block-sparse score tensors, a single flash-style kernel streams
only the *active* k-blocks per q-row — the layout's LUT plays the role the reference's
``make_sdd_lut``/``sdd_segment`` played, and the online softmax replaces the sparse
softmax kernel. Backward mirrors the flash backward with a transposed LUT for dk/dv.

Layouts are [heads, seq/block, seq/block] 0/1 arrays (SparsityConfig.make_layout).
Causal=True applies token-level triangular masking inside diagonal blocks (the reference
applies block-granular causality only via the layout; token-level is strictly correct for
unidirectional attention).
"""

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


# ---------------------------------------------------------------------------
# LUT construction (host-side, static per layout)
# ---------------------------------------------------------------------------

def build_luts(layout: np.ndarray) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """From [H, nb, nb] layout build forward and transposed LUTs.

    Returns (counts [H*nb], cols [H*nb, A], counts_t [H*nb], rows_t [H*nb, A_t]):
    cols[h*nb+i, :counts[...]] are the active k-block indices of q-row i (sorted);
    rows_t the active q-block indices of k-column j.
    """
    layout = np.asarray(layout) != 0
    H, nb, _ = layout.shape
    max_a = max(1, int(layout.sum(-1).max()))
    max_at = max(1, int(layout.sum(-2).max()))
    counts = np.zeros((H * nb,), np.int32)
    cols = np.zeros((H * nb, max_a), np.int32)
    counts_t = np.zeros((H * nb,), np.int32)
    rows_t = np.zeros((H * nb, max_at), np.int32)
    for h in range(H):
        for i in range(nb):
            act = np.nonzero(layout[h, i])[0]
            counts[h * nb + i] = len(act)
            cols[h * nb + i, :len(act)] = act
            act_t = np.nonzero(layout[h, :, i])[0]
            counts_t[h * nb + i] = len(act_t)
            rows_t[h * nb + i, :len(act_t)] = act_t
    return counts, cols, counts_t, rows_t


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------

def _bs_fwd_kernel(counts_ref, cols_ref, q_ref, k_hbm, v_hbm, o_ref, lse_ref,
                   kbuf, vbuf, sems, *, sm_scale, causal, block, num_heads, nb):
    """K/V stay in HBM; only the layout's active blocks are DMA'd in, double-buffered —
    HBM traffic scales with density, not seq_len^2 (splash-attention structure)."""
    b = pl.program_id(0)
    i = pl.program_id(1)
    h = b % num_heads
    row = h * nb + i
    bq = q_ref.shape[0]
    d = q_ref.shape[1]
    # bf16-in/fp32-accumulate is the MXU's native mode (see flash_attention._fwd_kernel)
    q = q_ref[...]

    n_active = counts_ref[row]

    # K/V arrive as [BH, nb, block, D]: DMA slices index only leading dims so the
    # trailing (block, D) tile stays whole (Mosaic requires lane-aligned slices)
    def start_dma(j, slot):
        kb = cols_ref[row, j]
        pltpu.make_async_copy(k_hbm.at[b, kb], kbuf.at[slot], sems.at[0, slot]).start()
        pltpu.make_async_copy(v_hbm.at[b, kb], vbuf.at[slot], sems.at[1, slot]).start()

    def wait_dma(j, slot):
        kb = cols_ref[row, j]
        pltpu.make_async_copy(k_hbm.at[b, kb], kbuf.at[slot], sems.at[0, slot]).wait()
        pltpu.make_async_copy(v_hbm.at[b, kb], vbuf.at[slot], sems.at[1, slot]).wait()

    # Launch EVERY active block's DMA up front (one VMEM slot per LUT entry) so the
    # per-copy latencies overlap; the compute loop drains them in order. This keeps
    # low-density layouts compute-bound instead of serial-DMA-latency-bound.
    jax.lax.fori_loop(0, n_active, lambda j, c: (start_dma(j, j), c)[1], 0)

    m0 = jnp.full((bq, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)

    def body(j, carry):
        m, l, acc = carry
        slot = j

        wait_dma(j, slot)
        kb = cols_ref[row, j]
        # buffers hold K/V blocks TRANSPOSED [D, block] (lane dim = block, 128-aligned)
        kt_blk = kbuf[slot]
        vt_blk = vbuf[slot]
        s = jnp.dot(q, kt_blk, preferred_element_type=jnp.float32) * sm_scale  # [bq, block]
        if causal:
            q_pos = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block), 0)
            k_pos = kb * block + jax.lax.broadcasted_iota(jnp.int32, (bq, block), 1)
            s = jnp.where(q_pos >= k_pos, s, DEFAULT_MASK_VALUE)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        # p @ v with v stored [D, block]: contract p's block dim with vt's block dim
        pv = jax.lax.dot_general(p.astype(vt_blk.dtype), vt_blk,
                                 dimension_numbers=(((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_new = acc * alpha + pv
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, n_active, body, (m0, l0, acc0))
    l = jnp.maximum(l, 1e-30)
    o_ref[...] = jnp.where(n_active > 0, acc / l, 0.0).astype(o_ref.dtype)
    lse_ref[...] = (m + jnp.log(l)).reshape(1, bq)


def _bs_dq_kernel(counts_ref, cols_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                  dq_ref, *, sm_scale, causal, block, num_heads, nb):
    b = pl.program_id(0)
    i = pl.program_id(1)
    h = b % num_heads
    row = h * nb + i
    bq, d = q_ref.shape
    q = q_ref[...]
    do = do_ref[...]
    lse = lse_ref[...].reshape(bq, 1)
    delta = delta_ref[...].reshape(bq, 1)

    def body(j, dq):
        kb = cols_ref[row, j]
        k_blk = k_ref[pl.ds(kb * block, block), :]
        v_blk = v_ref[pl.ds(kb * block, block), :]
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_pos = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block), 0)
            k_pos = kb * block + jax.lax.broadcasted_iota(jnp.int32, (bq, block), 1)
            s = jnp.where(q_pos >= k_pos, s, DEFAULT_MASK_VALUE)
        p = jnp.exp(s - lse)
        dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        return dq + jnp.dot(ds.astype(k_blk.dtype), k_blk, preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, counts_ref[row], body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[...] = (dq * sm_scale).astype(dq_ref.dtype)


def _bs_dkv_kernel(counts_t_ref, rows_t_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dk_ref, dv_ref, *, sm_scale, causal, block, num_heads, nb):
    b = pl.program_id(0)
    i = pl.program_id(1)  # k-block index
    h = b % num_heads
    col = h * nb + i
    bk, d = k_ref.shape
    k = k_ref[...]
    v = v_ref[...]

    def body(j, carry):
        dk, dv = carry
        qb = rows_t_ref[col, j]
        q_blk = q_ref[pl.ds(qb * block, block), :]
        do_blk = do_ref[pl.ds(qb * block, block), :]
        lse_blk = lse_ref[0, pl.ds(qb * block, block)].reshape(block, 1)
        delta_blk = delta_ref[0, pl.ds(qb * block, block)].reshape(block, 1)
        s = jnp.dot(q_blk, k.T, preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_pos = qb * block + jax.lax.broadcasted_iota(jnp.int32, (block, bk), 0)
            k_pos = i * bk + jax.lax.broadcasted_iota(jnp.int32, (block, bk), 1)
            s = jnp.where(q_pos >= k_pos, s, DEFAULT_MASK_VALUE)
        p = jnp.exp(s - lse_blk)
        dv_new = dv + jnp.dot(p.T.astype(do_blk.dtype), do_blk,
                              preferred_element_type=jnp.float32)
        dp = jnp.dot(do_blk, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta_blk)
        dk_new = dk + jnp.dot(ds.T.astype(q_blk.dtype), q_blk,
                              preferred_element_type=jnp.float32)
        return dk_new, dv_new

    dk, dv = jax.lax.fori_loop(0, counts_t_ref[col], body,
                               (jnp.zeros((bk, d), jnp.float32), jnp.zeros((bk, d), jnp.float32)))
    dk_ref[...] = (dk * sm_scale).astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call plumbing
# ---------------------------------------------------------------------------

def _grid_spec(num_prefetch, grid, in_specs, out_specs):
    return pltpu.PrefetchScalarGridSpec(num_scalar_prefetch=num_prefetch, grid=grid,
                                        in_specs=in_specs, out_specs=out_specs)


def _bs_fwd(q, k, v, counts, cols, sm_scale, causal, block, interpret):
    B, H, T, D = q.shape
    nb = T // block
    q3 = q.reshape(B * H, T, D)
    # K/V blocks stored transposed [BH, nb, D, block]: the DMA'd tile's lane dim is the
    # 128-aligned block size, and the kernel's matmuls consume [D, block] directly
    if not interpret:
        assert block % 128 == 0, f"sparse block size {block} must be a multiple of 128 on TPU " \
                                 f"(smaller layouts: use interpret mode or a bigger block)"
    k3 = k.reshape(B * H, nb, block, D).transpose(0, 1, 3, 2)
    v3 = v.reshape(B * H, nb, block, D).transpose(0, 1, 3, 2)
    max_active = int(cols.shape[1])
    # VMEM budget: 2 buffers x max_active x D x block x itemsize must fit ~16MB
    vmem_need = 2 * max_active * D * block * q.dtype.itemsize
    assert vmem_need < 12 * 1024 * 1024, \
        f"layout too dense for all-upfront DMA ({vmem_need} B of VMEM); reduce max row density"
    kernel = functools.partial(_bs_fwd_kernel, sm_scale=sm_scale, causal=causal, block=block,
                               num_heads=H, nb=nb)
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B * H, nb),
            in_specs=[
                pl.BlockSpec((None, block, D), lambda b, i, c0, c1: (b, i, 0)),
                pl.BlockSpec(memory_space=pl.ANY),  # K stays in HBM
                pl.BlockSpec(memory_space=pl.ANY),  # V stays in HBM
            ],
            out_specs=[
                pl.BlockSpec((None, block, D), lambda b, i, c0, c1: (b, i, 0)),
                pl.BlockSpec((None, 1, block), lambda b, i, c0, c1: (b, 0, i)),
            ],
            scratch_shapes=[
                pltpu.VMEM((max_active, D, block), q.dtype),
                pltpu.VMEM((max_active, D, block), q.dtype),
                pltpu.SemaphoreType.DMA((2, max_active)),
            ]),
        out_shape=[
            jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, 1, T), jnp.float32),
        ],
        interpret=interpret,
    )(counts, cols, q3, k3, v3)
    return out.reshape(B, H, T, D), lse.reshape(B, H, T)


def _bs_bwd(res, g, sm_scale, causal, block, interpret):
    q, k, v, out, lse, counts, cols, counts_t, rows_t = res
    B, H, T, D = q.shape
    nb = T // block
    do = g
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    q3, k3, v3, do3 = (x.reshape(B * H, T, D) for x in (q, k, v, do))
    lse3 = lse.reshape(B * H, 1, T)
    delta3 = delta.reshape(B * H, 1, T)

    dq = pl.pallas_call(
        functools.partial(_bs_dq_kernel, sm_scale=sm_scale, causal=causal, block=block,
                          num_heads=H, nb=nb),
        grid_spec=_grid_spec(
            2, (B * H, nb),
            in_specs=[
                pl.BlockSpec((None, block, D), lambda b, i, c0, c1: (b, i, 0)),
                pl.BlockSpec((None, T, D), lambda b, i, c0, c1: (b, 0, 0)),
                pl.BlockSpec((None, T, D), lambda b, i, c0, c1: (b, 0, 0)),
                pl.BlockSpec((None, block, D), lambda b, i, c0, c1: (b, i, 0)),
                pl.BlockSpec((None, 1, block), lambda b, i, c0, c1: (b, 0, i)),
                pl.BlockSpec((None, 1, block), lambda b, i, c0, c1: (b, 0, i)),
            ],
            out_specs=pl.BlockSpec((None, block, D), lambda b, i, c0, c1: (b, i, 0))),
        out_shape=jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
        interpret=interpret,
    )(counts, cols, q3, k3, v3, do3, lse3, delta3)

    dk, dv = pl.pallas_call(
        functools.partial(_bs_dkv_kernel, sm_scale=sm_scale, causal=causal, block=block,
                          num_heads=H, nb=nb),
        grid_spec=_grid_spec(
            2, (B * H, nb),
            in_specs=[
                pl.BlockSpec((None, T, D), lambda b, i, c0, c1: (b, 0, 0)),
                pl.BlockSpec((None, block, D), lambda b, i, c0, c1: (b, i, 0)),
                pl.BlockSpec((None, block, D), lambda b, i, c0, c1: (b, i, 0)),
                pl.BlockSpec((None, T, D), lambda b, i, c0, c1: (b, 0, 0)),
                pl.BlockSpec((None, 1, T), lambda b, i, c0, c1: (b, 0, 0)),
                pl.BlockSpec((None, 1, T), lambda b, i, c0, c1: (b, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((None, block, D), lambda b, i, c0, c1: (b, i, 0)),
                pl.BlockSpec((None, block, D), lambda b, i, c0, c1: (b, i, 0)),
            ]),
        out_shape=[
            jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
        ],
        interpret=interpret,
    )(counts_t, rows_t, q3, k3, v3, do3, lse3, delta3)
    return dq.reshape(B, H, T, D), dk.reshape(B, H, T, D), dv.reshape(B, H, T, D)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10))
def _bs_attention_core(q, k, v, counts, cols, counts_t, rows_t,
                       block, causal, sm_scale, interpret):
    out, _ = _bs_core_fwd(q, k, v, counts, cols, counts_t, rows_t, block, causal, sm_scale,
                          interpret)
    return out


def _bs_core_fwd(q, k, v, counts, cols, counts_t, rows_t, block, causal, sm_scale, interpret):
    out, lse = _bs_fwd(q, k, v, counts, cols, sm_scale, causal, block, interpret)
    return out, (q, k, v, out, lse, counts, cols, counts_t, rows_t)


def _bs_core_bwd(block, causal, sm_scale, interpret, res, g):
    dq, dk, dv = _bs_bwd(res, g, sm_scale, causal, block, interpret)
    return dq, dk, dv, None, None, None, None


_bs_attention_core.defvjp(_bs_core_fwd, _bs_core_bwd)


def block_sparse_attention(q, k, v, layout, block: int, causal: bool = False,
                           sm_scale: Optional[float] = None, interpret: Optional[bool] = None):
    """Block-sparse attention on [B, H, T, D] with a [H, T/block, T/block] layout."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    assert q.shape[2] % block == 0, f"seq len {q.shape[2]} must be divisible by block {block}"
    assert layout.shape[1] == q.shape[2] // block, "layout block-count mismatch with seq len"
    counts, cols, counts_t, rows_t = build_luts(np.asarray(layout))
    return _bs_attention_core(q, k, v, jnp.asarray(counts), jnp.asarray(cols),
                              jnp.asarray(counts_t), jnp.asarray(rows_t),
                              block, causal, sm_scale, interpret)


def dense_blocksparse_attention(q, k, v, layout, block: int, causal: bool = False,
                                sm_scale: Optional[float] = None):
    """Dense-masked reference (numerics oracle; O(T^2) memory)."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    B, H, T, D = q.shape
    mask = np.kron(np.asarray(layout) != 0, np.ones((block, block), bool))  # [H, T, T]
    if causal:
        mask = mask & np.tril(np.ones((T, T), bool))[None]
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * sm_scale
    scores = jnp.where(jnp.asarray(mask)[None], scores, DEFAULT_MASK_VALUE)
    probs = jax.nn.softmax(scores, axis=-1)
    # rows with no active blocks: all-masked softmax is uniform garbage; zero them
    any_active = jnp.asarray(mask.any(-1))[None, :, :, None]
    probs = jnp.where(any_active, probs, 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(q.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)
