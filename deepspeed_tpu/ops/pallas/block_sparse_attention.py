"""Block-sparse flash attention driven by SparsityConfig layouts.

TPU-native replacement for the reference's Triton block-sparse stack
(``deepspeed/ops/sparse_attention/{matmul,softmax}.py`` + ``trsrc/*.tr`` + the C++
``sdd_segment`` LUT builder, N4): instead of three kernels (SDD matmul → sparse softmax →
DSD matmul) materializing block-sparse score tensors, a single flash-style kernel streams
only the *active* k-blocks per q-row — the layout's LUT plays the role the reference's
``make_sdd_lut``/``sdd_segment`` played, and the online softmax replaces the sparse
softmax kernel. Backward mirrors the flash backward with a transposed LUT for dk/dv.

Layouts are [heads, seq/block, seq/block] 0/1 arrays (SparsityConfig.make_layout).
Causal=True applies token-level triangular masking inside diagonal blocks (the reference
applies block-granular causality only via the layout; token-level is strictly correct for
unidirectional attention).
"""

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


def _row_band_masks(rows, block, group):
    """Per-sub-band boolean predicates ([rows, block] each), precomputed once per
    kernel so the per-slot membership mask is scalar selects, not a per-element
    variable shift (which measurably regressed the VPU path)."""
    if group == 1:
        return None
    iota = jax.lax.broadcasted_iota(jnp.int32, (rows, block), 0) // block
    return [iota == g for g in range(group)]


def _memb_mask(bits, band, group, rows, block):
    """[rows, block] membership mask from a slot's bitmask scalar: band predicates
    AND'd with their scalar bit. group == 1 degenerates to one scalar broadcast."""
    if group == 1:
        return jnp.broadcast_to(bits > 0, (rows, block))
    ok = band[0] & (bits & 1 == 1)
    for g in range(1, group):
        ok = ok | (band[g] & ((bits >> g) & 1 == 1))
    return ok


# ---------------------------------------------------------------------------
# LUT construction (host-side, static per layout)
# ---------------------------------------------------------------------------

_MEMB_SHIFT = 24  # block index in bits 0..23, membership bitmask in bits 24..30


def build_luts(layout: np.ndarray) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """From [H, nb, nb] layout build forward and transposed LUTs.

    Returns (counts [H*nb], cols [H*nb, A], counts_t [H*nb], rows_t [H*nb, A_t]):
    cols[h*nb+i, :counts[...]] are the active k-block indices of q-row i (sorted);
    rows_t the active q-block indices of k-column j.
    """
    counts, packed = build_grouped_luts(layout, 1)
    counts_t, packed_t = build_grouped_luts(np.transpose(np.asarray(layout), (0, 2, 1)), 1)
    return counts, packed & ((1 << _MEMB_SHIFT) - 1), counts_t, \
        packed_t & ((1 << _MEMB_SHIFT) - 1)


def build_grouped_luts(layout: np.ndarray, group: int):
    """LUT over GROUPS of ``group`` consecutive q-rows: each group's list is the
    UNION of its rows' active k-blocks, with a per-slot membership bitmask (bit g
    set iff sub-row g of the group attends that k-block) PACKED into the entry's
    high bits — one prefetch array, because the LUTs live in scoped SMEM and a
    BigBird global row makes the LUT width = nb (a second array blew the SMEM
    budget at T=8192). Grouping packs several low-count layout rows into one
    [group*block, ...] grid cell — bigger MXU tiles and 1/group the per-row fixed
    cost, the lever that closes the gap to the density-ideal speedup.

    Returns (counts [H*ng], packed [H*ng, A]) with packed = kb | memb << 24; padded
    slots have memb == 0 so their lanes mask to zero regardless of the count check.
    """
    layout = np.asarray(layout) != 0
    H, nb, _ = layout.shape
    assert nb % group == 0, f"layout rows {nb} not divisible by group {group}"
    assert nb < (1 << _MEMB_SHIFT) and group <= 7, "packed LUT limits: nb < 2^24, group <= 7"
    ng = nb // group
    per_group = []
    max_a = 1
    for h in range(H):
        for gi in range(ng):
            rows = layout[h, gi * group:(gi + 1) * group]  # [group, nb]
            act = np.nonzero(rows.any(axis=0))[0]
            max_a = max(max_a, len(act))
            per_group.append((h, gi, rows, act))
    counts = np.zeros((H * ng,), np.int32)
    packed = np.zeros((H * ng, max_a), np.int32)
    for h, gi, rows, act in per_group:
        r = h * ng + gi
        counts[r] = len(act)
        for idx, kb in enumerate(act):
            memb = int(sum(1 << g for g in range(group) if rows[g, kb]))
            packed[r, idx] = int(kb) | (memb << _MEMB_SHIFT)
    return counts, packed


# ---------------------------------------------------------------------------
# kernels — resident variants (K/V or Q/dO live whole in VMEM; the pallas
# pipeline fetches them once per batch*head and the compute loop slices active
# blocks directly). Measured 2x faster than the manual-DMA variants at T=8192
# (slope-timed r3); the DMA variants below remain the path for sequences whose
# operands exceed the VMEM budget (_resident_fits).
# ---------------------------------------------------------------------------

def _slot_tiles(lut_ref, row, t, kwidth, block, src_refs, lane_iota, band, group,
                rows):
    """Gather one compute tile's active blocks from each resident ``src_refs``
    array: returns ([W*block, D] tile per src, positions [rows, W*block],
    membership mask [rows, W*block])."""
    tiles = [[] for _ in src_refs]
    pos, oks = [], []
    for w in range(kwidth):
        j = jnp.minimum(t * kwidth + w, lut_ref.shape[1] - 1)
        entry = lut_ref[row, j]
        kb = entry & ((1 << _MEMB_SHIFT) - 1)
        for parts, ref in zip(tiles, src_refs):
            parts.append(ref[pl.ds(kb * block, block), :])
        pos.append(kb * block + lane_iota)
        oks.append(_memb_mask(entry >> _MEMB_SHIFT, band, group, rows, block))
    return ([jnp.concatenate(parts, axis=0) for parts in tiles],
            jnp.concatenate(pos, axis=1), jnp.concatenate(oks, axis=1))


def _bs_fwd_kernel_res(counts_ref, cols_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                       sm_scale, causal, block, num_heads, ng, kwidth, group):
    i = pl.program_id(1)
    row = (pl.program_id(0) % num_heads) * ng + i
    bq, d = q_ref.shape  # group * block
    q = q_ref[...]
    n_active = counts_ref[row]
    lane_iota = jax.lax.broadcasted_iota(jnp.int32, (bq, block), 1)
    band = _row_band_masks(bq, block, group)
    m0 = jnp.full((bq, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)

    def body(t, carry):
        m, l, acc = carry
        (kt, vt), k_pos, ok = _slot_tiles(cols_ref, row, t, kwidth, block,
                                          (k_ref, v_ref), lane_iota, band, group, bq)
        s = jax.lax.dot_general(q, kt, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_pos = i * bq + jax.lax.broadcasted_iota(jnp.int32,
                                                      (bq, kwidth * block), 0)
            ok = jnp.logical_and(ok, q_pos >= k_pos)
        s = jnp.where(ok, s, DEFAULT_MASK_VALUE)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(ok, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.dot(p.astype(vt.dtype), vt,
                                        preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    n_tiles = (n_active + kwidth - 1) // kwidth
    m, l, acc = jax.lax.fori_loop(0, n_tiles, body, (m0, l0, acc0))
    l = jnp.maximum(l, 1e-30)
    o_ref[...] = jnp.where(n_active > 0, acc / l, 0.0).astype(o_ref.dtype)
    lse_ref[...] = (m + jnp.log(l)).reshape(1, bq)


def _bs_dq_kernel_res(counts_ref, cols_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                      delta_ref, dq_ref, *, sm_scale, causal, block, num_heads, ng,
                      kwidth, group):
    i = pl.program_id(1)
    row = (pl.program_id(0) % num_heads) * ng + i
    bq, d = q_ref.shape
    q = q_ref[...]
    do = do_ref[...]
    lse = lse_ref[...].reshape(bq, 1)
    delta = delta_ref[...].reshape(bq, 1)
    n_active = counts_ref[row]
    lane_iota = jax.lax.broadcasted_iota(jnp.int32, (bq, block), 1)
    band = _row_band_masks(bq, block, group)

    def body(t, dq):
        (kt, vt), k_pos, ok = _slot_tiles(cols_ref, row, t, kwidth, block,
                                          (k_ref, v_ref), lane_iota, band, group, bq)
        s = jax.lax.dot_general(q, kt, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_pos = i * bq + jax.lax.broadcasted_iota(jnp.int32,
                                                      (bq, kwidth * block), 0)
            ok = jnp.logical_and(ok, q_pos >= k_pos)
        s = jnp.where(ok, s, DEFAULT_MASK_VALUE)
        p = jnp.where(ok, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(do, vt, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)  # [bq, Wb]
        ds = p * (dp - delta)
        return dq + jnp.dot(ds.astype(kt.dtype), kt,
                            preferred_element_type=jnp.float32)

    n_tiles = (n_active + kwidth - 1) // kwidth
    dq = jax.lax.fori_loop(0, n_tiles, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[...] = (dq * sm_scale).astype(dq_ref.dtype)


def _bs_dkv_kernel_res(counts_t_ref, rows_t_ref, q_ref, k_ref, v_ref, do_ref,
                       lse_ref, delta_ref, dk_ref, dv_ref, *, sm_scale, causal,
                       block, num_heads, ng, kwidth, group):
    i = pl.program_id(1)  # k-column-group index
    col = (pl.program_id(0) % num_heads) * ng + i
    bk, d = k_ref.shape  # group * block
    k = k_ref[...]
    v = v_ref[...]
    n_active = counts_t_ref[col]
    row_iota = jax.lax.broadcasted_iota(jnp.int32, (block, bk), 0)
    if group == 1:
        band = None
    else:
        lane_sub = jax.lax.broadcasted_iota(jnp.int32, (block, bk), 1) // block
        band = [lane_sub == g for g in range(group)]

    def body(t, carry):
        dk, dv = carry
        qs_parts, dot_parts, lse_parts, delta_parts, pos_parts, ok_parts = \
            [], [], [], [], [], []
        for w in range(kwidth):
            j = jnp.minimum(t * kwidth + w, rows_t_ref.shape[1] - 1)
            entry = rows_t_ref[col, j]
            qb = entry & ((1 << _MEMB_SHIFT) - 1)
            sl = pl.ds(qb * block, block)
            qs_parts.append(q_ref[sl, :])
            dot_parts.append(do_ref[sl, :])
            lse_parts.append(lse_ref[0, sl].reshape(block, 1))
            delta_parts.append(delta_ref[0, sl].reshape(block, 1))
            pos_parts.append(qb * block + row_iota)
            ok_parts.append(_memb_mask(entry >> _MEMB_SHIFT, band, group, block, bk))
        qt = jnp.concatenate(qs_parts, axis=0)      # [W*block, D]
        dot = jnp.concatenate(dot_parts, axis=0)
        lse_tile = jnp.concatenate(lse_parts, axis=0)
        delta_tile = jnp.concatenate(delta_parts, axis=0)
        q_pos = jnp.concatenate(pos_parts, axis=0)
        ok = jnp.concatenate(ok_parts, axis=0)
        s = jax.lax.dot_general(qt, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            k_pos = i * bk + jax.lax.broadcasted_iota(jnp.int32,
                                                      (kwidth * block, bk), 1)
            ok = jnp.logical_and(ok, q_pos >= k_pos)
        s = jnp.where(ok, s, DEFAULT_MASK_VALUE)
        p = jnp.where(ok, jnp.exp(s - lse_tile), 0.0)
        dv_new = dv + jax.lax.dot_general(p.astype(dot.dtype), dot,
                                          (((0,), (0,)), ((), ())),
                                          preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(dot, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)  # [Wb, bk]
        ds = p * (dp - delta_tile)
        dk_new = dk + jax.lax.dot_general(ds.astype(qt.dtype), qt,
                                          (((0,), (0,)), ((), ())),
                                          preferred_element_type=jnp.float32)
        return dk_new, dv_new

    n_tiles = (n_active + kwidth - 1) // kwidth
    dk, dv = jax.lax.fori_loop(0, n_tiles, body,
                               (jnp.zeros((bk, d), jnp.float32),
                                jnp.zeros((bk, d), jnp.float32)))
    dk_ref[...] = jnp.where(n_active > 0, dk * sm_scale, 0.0).astype(dk_ref.dtype)
    dv_ref[...] = jnp.where(n_active > 0, dv, 0.0).astype(dv_ref.dtype)


def _resident_fits(T: int, D: int, itemsize: int, n_operands: int = 2) -> bool:
    """Whole-[T, D] operand residency budget: leave room for the double-buffered
    pipeline + score tiles inside the ~16 MB of VMEM."""
    return n_operands * T * D * itemsize <= 6 * 1024 * 1024


# ---------------------------------------------------------------------------
# kernels — manual-DMA variants (K/V stay in HBM; active blocks are DMA'd).
# Used when the resident operands don't fit VMEM (very long sequences).
# ---------------------------------------------------------------------------

def _bs_fwd_kernel(counts_ref, cols_ref, q_ref, k_hbm, v_hbm, o_ref, lse_ref,
                   kbuf, vbuf, sems, *, sm_scale, causal, block, num_heads, ng, kwidth,
                   group):
    """K/V stay in HBM; only the layout's active blocks are DMA'd in — HBM traffic
    scales with density, not seq_len^2 (splash-attention structure).

    Blocks land LANE-CONCATENATED in VMEM ([D, A_pad*block] scratch), so the compute
    loop consumes ``kwidth`` blocks per iteration as one [group*block, kwidth*block]
    score tile. ``group`` q-rows share a grid cell via the union LUT: each sub-row's
    actual membership is a per-slot bitmask in the entry's high bits, masked per
    128-row band —
    bigger MXU tiles and 1/group the per-row fixed cost at low density."""
    b = pl.program_id(0)
    i = pl.program_id(1)
    h = b % num_heads
    row = h * ng + i
    bq = q_ref.shape[0]  # group * block
    d = q_ref.shape[1]
    # bf16-in/fp32-accumulate is the MXU's native mode (see flash_attention._fwd_kernel)
    q = q_ref[...]

    n_active = counts_ref[row]
    n_slots = ((n_active + kwidth - 1) // kwidth) * kwidth  # padded slots DMA block 0

    def start_dma(j):
        kb = cols_ref[row, j] & ((1 << _MEMB_SHIFT) - 1)
        dst = pl.ds(j * block, block)
        pltpu.make_async_copy(k_hbm.at[b, kb], kbuf.at[:, dst], sems.at[0, j]).start()
        pltpu.make_async_copy(v_hbm.at[b, kb], vbuf.at[:, dst], sems.at[1, j]).start()

    def wait_dma(j):
        kb = cols_ref[row, j] & ((1 << _MEMB_SHIFT) - 1)
        dst = pl.ds(j * block, block)
        pltpu.make_async_copy(k_hbm.at[b, kb], kbuf.at[:, dst], sems.at[0, j]).wait()
        pltpu.make_async_copy(v_hbm.at[b, kb], vbuf.at[:, dst], sems.at[1, j]).wait()

    # Launch EVERY slot's DMA up front (one VMEM region per LUT entry) so the
    # per-copy latencies overlap; the compute loop drains them tile by tile.
    jax.lax.fori_loop(0, n_slots, lambda j, c: (start_dma(j), c)[1], 0)

    m0 = jnp.full((bq, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    lane_iota = jax.lax.broadcasted_iota(jnp.int32, (bq, block), 1)
    band = _row_band_masks(bq, block, group)

    def body(t, carry):
        m, l, acc = carry
        jax.lax.fori_loop(t * kwidth, (t + 1) * kwidth,
                          lambda j, c: (wait_dma(j), c)[1], 0)
        tile = pl.ds(t * (kwidth * block), kwidth * block)
        kt = kbuf[:, tile]               # [D, kwidth*block]
        vt = vbuf[:, tile]
        s = jnp.dot(q, kt, preferred_element_type=jnp.float32) * sm_scale  # [bq, W*blk]
        # per-sub-block k positions + per-sub-row membership (padded slots: memb 0)
        parts_pos, parts_ok = [], []
        for w in range(kwidth):
            j = jnp.minimum(t * kwidth + w, cols_ref.shape[1] - 1)
            entry = cols_ref[row, j]
            kb = entry & ((1 << _MEMB_SHIFT) - 1)
            parts_pos.append(kb * block + lane_iota)
            parts_ok.append(_memb_mask(entry >> _MEMB_SHIFT, band, group, bq, block))
        k_pos = jnp.concatenate(parts_pos, axis=1)
        ok = jnp.concatenate(parts_ok, axis=1)
        if causal:
            q_pos = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, kwidth * block), 0)
            ok = jnp.logical_and(ok, q_pos >= k_pos)
        s = jnp.where(ok, s, DEFAULT_MASK_VALUE)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(ok, p, 0.0)  # exact zero for padded/non-member lanes
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        # p @ v with v stored [D, W*block]: contract the lane dims
        pv = jax.lax.dot_general(p.astype(vt.dtype), vt,
                                 dimension_numbers=(((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_new = acc * alpha + pv
        return m_new, l_new, acc_new

    n_tiles = (n_active + kwidth - 1) // kwidth
    m, l, acc = jax.lax.fori_loop(0, n_tiles, body, (m0, l0, acc0))
    l = jnp.maximum(l, 1e-30)
    o_ref[...] = jnp.where(n_active > 0, acc / l, 0.0).astype(o_ref.dtype)
    lse_ref[...] = (m + jnp.log(l)).reshape(1, bq)


def _bs_dq_kernel(counts_ref, cols_ref, q_ref, k_hbm, v_hbm, do_ref, lse_ref,
                  delta_ref, dq_ref, kbuf, vbuf, sems, *, sm_scale, causal, block,
                  num_heads, ng, kwidth, group):
    """dq over this q-row-GROUP's union of active k-blocks, kwidth blocks per
    iteration (same HBM-resident K/V + lane-concatenated VMEM scratch + membership
    bitmask structure as the forward)."""
    b = pl.program_id(0)
    i = pl.program_id(1)
    h = b % num_heads
    row = h * ng + i
    bq, d = q_ref.shape  # bq = group * block
    q = q_ref[...]
    do = do_ref[...]
    lse = lse_ref[...].reshape(bq, 1)
    delta = delta_ref[...].reshape(bq, 1)

    n_active = counts_ref[row]
    n_slots = ((n_active + kwidth - 1) // kwidth) * kwidth

    def start_dma(j):
        kb = cols_ref[row, j] & ((1 << _MEMB_SHIFT) - 1)
        dst = pl.ds(j * block, block)
        pltpu.make_async_copy(k_hbm.at[b, kb], kbuf.at[:, dst], sems.at[0, j]).start()
        pltpu.make_async_copy(v_hbm.at[b, kb], vbuf.at[:, dst], sems.at[1, j]).start()

    def wait_dma(j):
        kb = cols_ref[row, j] & ((1 << _MEMB_SHIFT) - 1)
        dst = pl.ds(j * block, block)
        pltpu.make_async_copy(k_hbm.at[b, kb], kbuf.at[:, dst], sems.at[0, j]).wait()
        pltpu.make_async_copy(v_hbm.at[b, kb], vbuf.at[:, dst], sems.at[1, j]).wait()

    jax.lax.fori_loop(0, n_slots, lambda j, c: (start_dma(j), c)[1], 0)
    lane_iota = jax.lax.broadcasted_iota(jnp.int32, (bq, block), 1)
    band = _row_band_masks(bq, block, group)

    def body(t, dq):
        jax.lax.fori_loop(t * kwidth, (t + 1) * kwidth,
                          lambda j, c: (wait_dma(j), c)[1], 0)
        tile = pl.ds(t * (kwidth * block), kwidth * block)
        kt = kbuf[:, tile]               # [D, W*block]
        vt = vbuf[:, tile]
        s = jnp.dot(q, kt, preferred_element_type=jnp.float32) * sm_scale
        parts_pos, parts_ok = [], []
        for w in range(kwidth):
            j = jnp.minimum(t * kwidth + w, cols_ref.shape[1] - 1)
            entry = cols_ref[row, j]
            kb = entry & ((1 << _MEMB_SHIFT) - 1)
            parts_pos.append(kb * block + lane_iota)
            parts_ok.append(_memb_mask(entry >> _MEMB_SHIFT, band, group, bq, block))
        k_pos = jnp.concatenate(parts_pos, axis=1)
        ok = jnp.concatenate(parts_ok, axis=1)
        if causal:
            q_pos = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, kwidth * block), 0)
            ok = jnp.logical_and(ok, q_pos >= k_pos)
        s = jnp.where(ok, s, DEFAULT_MASK_VALUE)
        p = jnp.exp(s - lse)
        p = jnp.where(ok, p, 0.0)
        dp = jax.lax.dot_general(do, vt, dimension_numbers=(((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)  # [bq, W*block]
        ds = p * (dp - delta)
        # ds @ K with K stored [D, W*block]: contract the lane dims
        return dq + jax.lax.dot_general(ds.astype(kt.dtype), kt,
                                        dimension_numbers=(((1,), (1,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    n_tiles = (n_active + kwidth - 1) // kwidth
    dq = jax.lax.fori_loop(0, n_tiles, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[...] = (dq * sm_scale).astype(dq_ref.dtype)


def _bs_dkv_kernel(counts_t_ref, rows_t_ref, q_hbm, k_ref, v_ref, do_hbm,
                   lse_ref, delta_ref, dk_ref, dv_ref, qbuf, dobuf, sems, *, sm_scale,
                   causal, block, num_heads, ng, kwidth, group):
    """dk/dv over this k-column-GROUP's union of active q-blocks, kwidth blocks per
    iteration. Q/dO stay in HBM stored TRANSPOSED [BH, nb, D, block] (lane dim = the
    128-aligned block size — [block, D<128] tiles trip Mosaic's memref_slice);
    active q-blocks are DMA'd lane-concatenated into [D, A_pad*block] scratch and all
    matmuls contract via dimension_numbers instead of VMEM transposes. Membership
    bitmasks select which of the ``group`` k-column bands each q-block attends."""
    b = pl.program_id(0)
    i = pl.program_id(1)  # k-column-group index
    h = b % num_heads
    col = h * ng + i
    bk, d = k_ref.shape  # bk = group * block
    k = k_ref[...]
    v = v_ref[...]

    n_active = counts_t_ref[col]
    n_slots = ((n_active + kwidth - 1) // kwidth) * kwidth

    def start_dma(j):
        qb = rows_t_ref[col, j] & ((1 << _MEMB_SHIFT) - 1)
        dst = pl.ds(j * block, block)
        pltpu.make_async_copy(q_hbm.at[b, qb], qbuf.at[:, dst], sems.at[0, j]).start()
        pltpu.make_async_copy(do_hbm.at[b, qb], dobuf.at[:, dst], sems.at[1, j]).start()

    def wait_dma(j):
        qb = rows_t_ref[col, j] & ((1 << _MEMB_SHIFT) - 1)
        dst = pl.ds(j * block, block)
        pltpu.make_async_copy(q_hbm.at[b, qb], qbuf.at[:, dst], sems.at[0, j]).wait()
        pltpu.make_async_copy(do_hbm.at[b, qb], dobuf.at[:, dst], sems.at[1, j]).wait()

    jax.lax.fori_loop(0, n_slots, lambda j, c: (start_dma(j), c)[1], 0)
    row_iota = jax.lax.broadcasted_iota(jnp.int32, (block, bk), 0)
    # which of the group's 128-column bands a lane belongs to (transposed band masks)
    if group == 1:
        band = None
    else:
        lane_sub = jax.lax.broadcasted_iota(jnp.int32, (block, bk), 1) // block
        band = [lane_sub == g for g in range(group)]

    def body(t, carry):
        dk, dv = carry
        jax.lax.fori_loop(t * kwidth, (t + 1) * kwidth,
                          lambda j, c: (wait_dma(j), c)[1], 0)
        tile = pl.ds(t * (kwidth * block), kwidth * block)
        qt = qbuf[:, tile]               # [D, W*block]
        dot = dobuf[:, tile]             # [D, W*block]
        parts_pos, parts_ok, parts_lse, parts_delta = [], [], [], []
        for w in range(kwidth):
            j = jnp.minimum(t * kwidth + w, rows_t_ref.shape[1] - 1)
            entry = rows_t_ref[col, j]
            qb = entry & ((1 << _MEMB_SHIFT) - 1)
            qs = pl.ds(qb * block, block)
            parts_pos.append(qb * block + row_iota)
            parts_ok.append(_memb_mask(entry >> _MEMB_SHIFT, band, group, block, bk))
            parts_lse.append(lse_ref[0, qs].reshape(block, 1))
            parts_delta.append(delta_ref[0, qs].reshape(block, 1))
        q_pos = jnp.concatenate(parts_pos, axis=0)
        ok = jnp.concatenate(parts_ok, axis=0)
        lse_tile = jnp.concatenate(parts_lse, axis=0)
        delta_tile = jnp.concatenate(parts_delta, axis=0)
        # s[Wb, bk] = (q @ k^T) with q stored [D, Wb]: contract the D dims
        s = jax.lax.dot_general(qt, k, dimension_numbers=(((0,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            k_pos = i * bk + jax.lax.broadcasted_iota(jnp.int32,
                                                      (kwidth * block, bk), 1)
            ok = jnp.logical_and(ok, q_pos >= k_pos)
        s = jnp.where(ok, s, DEFAULT_MASK_VALUE)
        p = jnp.exp(s - lse_tile)
        p = jnp.where(ok, p, 0.0)
        # dv[bk, D] += p^T @ do with do stored [D, Wb]: contract the Wb dims
        dv_new = dv + jax.lax.dot_general(p.astype(dot.dtype), dot,
                                          dimension_numbers=(((0,), (1,)), ((), ())),
                                          preferred_element_type=jnp.float32)
        # dp[Wb, bk] = do^T @ v^T: contract the D dims
        dp = jax.lax.dot_general(dot, v, dimension_numbers=(((0,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_tile)
        # dk[bk, D] += ds^T @ q^T: contract the Wb dims
        dk_new = dk + jax.lax.dot_general(ds.astype(qt.dtype), qt,
                                          dimension_numbers=(((0,), (1,)), ((), ())),
                                          preferred_element_type=jnp.float32)
        return dk_new, dv_new

    n_tiles = (n_active + kwidth - 1) // kwidth
    dk, dv = jax.lax.fori_loop(0, n_tiles, body,
                               (jnp.zeros((bk, d), jnp.float32), jnp.zeros((bk, d), jnp.float32)))
    dk_ref[...] = jnp.where(n_active > 0, dk * sm_scale, 0.0).astype(dk_ref.dtype)
    dv_ref[...] = jnp.where(n_active > 0, dv, 0.0).astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call plumbing
# ---------------------------------------------------------------------------

_KWIDTH = 4  # k-blocks consumed per compute iteration (one [bq, KW*block] score tile)


def _pad_lut(lut, max_width=_KWIDTH):
    """Clamp the tile width to the LUT and pad its width to a tile multiple
    (padded slots DMA block 0; their lanes mask out via the zero membership bits
    in the entries' high bits).
    Returns (padded_lut, padded_width, kwidth)."""
    width = int(lut.shape[1])
    kwidth = max(1, min(max_width, width))
    a_pad = (width + kwidth - 1) // kwidth * kwidth
    if a_pad != width:
        lut = jnp.pad(lut, ((0, 0), (0, a_pad - width)))
    return jnp.asarray(lut), a_pad, kwidth


def _pick_group(nb: int, block: int) -> int:
    """Rows per grid cell: target 256-row score tiles (two 128 blocks), capped at 4
    (the membership select chain grows with group), falling back to 1 when the
    layout height doesn't divide."""
    g = min(4, max(1, 256 // block))
    while g > 1 and nb % g != 0:
        g //= 2
    return g


def _bs_fwd(q, k, v, counts, cols, group, sm_scale, causal, block, interpret):
    B, H, T, D = q.shape
    nb = T // block
    ng = nb // group
    if not interpret:
        assert block % 128 == 0, f"sparse block size {block} must be a multiple of 128 on TPU " \
                                 f"(smaller layouts: use interpret mode or a bigger block)"
    if _resident_fits(T, D, q.dtype.itemsize):
        q3, k3, v3 = (x.reshape(B * H, T, D) for x in (q, k, v))
        cols_p, _, kwidth = _pad_lut(cols)
        out, lse = pl.pallas_call(
            functools.partial(_bs_fwd_kernel_res, sm_scale=sm_scale, causal=causal,
                              block=block, num_heads=H, ng=ng, kwidth=kwidth,
                              group=group),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(B * H, ng),
                in_specs=[
                    pl.BlockSpec((None, group * block, D), lambda b, i, *_: (b, i, 0)),
                    pl.BlockSpec((None, T, D), lambda b, i, *_: (b, 0, 0)),
                    pl.BlockSpec((None, T, D), lambda b, i, *_: (b, 0, 0)),
                ],
                out_specs=[
                    pl.BlockSpec((None, group * block, D), lambda b, i, *_: (b, i, 0)),
                    pl.BlockSpec((None, 1, group * block), lambda b, i, *_: (b, 0, i)),
                ]),
            out_shape=[
                jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
                jax.ShapeDtypeStruct((B * H, 1, T), jnp.float32),
            ],
            interpret=interpret,
        )(counts, cols_p, q3, k3, v3)
        return out.reshape(B, H, T, D), lse.reshape(B, H, T)
    q3 = q.reshape(B * H, T, D)
    # K/V blocks stored transposed [BH, nb, D, block]: the DMA'd tile's lane dim is the
    # 128-aligned block size, and the kernel's matmuls consume [D, block] directly
    k3 = k.reshape(B * H, nb, block, D).transpose(0, 1, 3, 2)
    v3 = v.reshape(B * H, nb, block, D).transpose(0, 1, 3, 2)
    cols, a_pad, kwidth = _pad_lut(cols)
    # VMEM budget: 2 buffers x a_pad x D x block x itemsize must fit ~16MB
    vmem_need = 2 * a_pad * D * block * q.dtype.itemsize
    assert vmem_need < 12 * 1024 * 1024, \
        f"layout too dense for all-upfront DMA ({vmem_need} B of VMEM); reduce max row density"
    kernel = functools.partial(_bs_fwd_kernel, sm_scale=sm_scale, causal=causal, block=block,
                               num_heads=H, ng=ng, kwidth=kwidth, group=group)
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B * H, ng),
            in_specs=[
                pl.BlockSpec((None, group * block, D), lambda b, i, *_: (b, i, 0)),
                pl.BlockSpec(memory_space=pl.ANY),  # K stays in HBM
                pl.BlockSpec(memory_space=pl.ANY),  # V stays in HBM
            ],
            out_specs=[
                pl.BlockSpec((None, group * block, D), lambda b, i, *_: (b, i, 0)),
                pl.BlockSpec((None, 1, group * block), lambda b, i, *_: (b, 0, i)),
            ],
            scratch_shapes=[
                pltpu.VMEM((D, a_pad * block), q.dtype),
                pltpu.VMEM((D, a_pad * block), q.dtype),
                pltpu.SemaphoreType.DMA((2, a_pad)),
            ]),
        out_shape=[
            jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, 1, T), jnp.float32),
        ],
        interpret=interpret,
    )(counts, cols, q3, k3, v3)
    return out.reshape(B, H, T, D), lse.reshape(B, H, T)


def _bs_bwd(res, g, sm_scale, causal, block, group, interpret):
    (q, k, v, out, lse, counts, cols, counts_t, rows_t) = res
    B, H, T, D = q.shape
    nb = T // block
    ng = nb // group
    do = g
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    lse3 = lse.reshape(B * H, 1, T)
    delta3 = delta.reshape(B * H, 1, T)
    q3, do3 = (x.reshape(B * H, T, D) for x in (q, do))
    if _resident_fits(T, D, q.dtype.itemsize):
        k3, v3 = (x.reshape(B * H, T, D) for x in (k, v))
        cols_p, _, kwidth = _pad_lut(cols)
        dq = pl.pallas_call(
            functools.partial(_bs_dq_kernel_res, sm_scale=sm_scale, causal=causal,
                              block=block, num_heads=H, ng=ng, kwidth=kwidth,
                              group=group),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(B * H, ng),
                in_specs=[
                    pl.BlockSpec((None, group * block, D), lambda b, i, *_: (b, i, 0)),
                    pl.BlockSpec((None, T, D), lambda b, i, *_: (b, 0, 0)),
                    pl.BlockSpec((None, T, D), lambda b, i, *_: (b, 0, 0)),
                    pl.BlockSpec((None, group * block, D), lambda b, i, *_: (b, i, 0)),
                    pl.BlockSpec((None, 1, group * block), lambda b, i, *_: (b, 0, i)),
                    pl.BlockSpec((None, 1, group * block), lambda b, i, *_: (b, 0, i)),
                ],
                out_specs=pl.BlockSpec((None, group * block, D),
                                       lambda b, i, *_: (b, i, 0))),
            out_shape=jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
            interpret=interpret,
        )(counts, cols_p, q3, k3, v3, do3, lse3, delta3)

        rows_p, _, kwidth_t = _pad_lut(rows_t)
        dk, dv = pl.pallas_call(
            functools.partial(_bs_dkv_kernel_res, sm_scale=sm_scale, causal=causal,
                              block=block, num_heads=H, ng=ng, kwidth=kwidth_t,
                              group=group),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(B * H, ng),
                in_specs=[
                    pl.BlockSpec((None, T, D), lambda b, i, *_: (b, 0, 0)),
                    pl.BlockSpec((None, group * block, D), lambda b, i, *_: (b, i, 0)),
                    pl.BlockSpec((None, group * block, D), lambda b, i, *_: (b, i, 0)),
                    pl.BlockSpec((None, T, D), lambda b, i, *_: (b, 0, 0)),
                    pl.BlockSpec((None, 1, T), lambda b, i, *_: (b, 0, 0)),
                    pl.BlockSpec((None, 1, T), lambda b, i, *_: (b, 0, 0)),
                ],
                out_specs=[
                    pl.BlockSpec((None, group * block, D), lambda b, i, *_: (b, i, 0)),
                    pl.BlockSpec((None, group * block, D), lambda b, i, *_: (b, i, 0)),
                ]),
            out_shape=[
                jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
                jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
            ],
            interpret=interpret,
        )(counts_t, rows_p, q3, k3, v3, do3, lse3, delta3)
        return (dq.reshape(B, H, T, D), dk.reshape(B, H, T, D),
                dv.reshape(B, H, T, D))

    cols_p, a_pad, kwidth = _pad_lut(cols)
    assert 2 * a_pad * D * block * q.dtype.itemsize < 12 * 1024 * 1024, \
        "layout too dense for all-upfront DMA in dq (reduce max row density)"
    # K/V blocked + transposed [BH, nb, D, block] for the lane-concat DMA (as in fwd)
    k3 = k.reshape(B * H, nb, block, D).transpose(0, 1, 3, 2)
    v3 = v.reshape(B * H, nb, block, D).transpose(0, 1, 3, 2)
    dq = pl.pallas_call(
        functools.partial(_bs_dq_kernel, sm_scale=sm_scale, causal=causal, block=block,
                          num_heads=H, ng=ng, kwidth=kwidth, group=group),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B * H, ng),
            in_specs=[
                pl.BlockSpec((None, group * block, D), lambda b, i, *_: (b, i, 0)),
                pl.BlockSpec(memory_space=pl.ANY),  # K stays in HBM
                pl.BlockSpec(memory_space=pl.ANY),  # V stays in HBM
                pl.BlockSpec((None, group * block, D), lambda b, i, *_: (b, i, 0)),
                pl.BlockSpec((None, 1, group * block), lambda b, i, *_: (b, 0, i)),
                pl.BlockSpec((None, 1, group * block), lambda b, i, *_: (b, 0, i)),
            ],
            out_specs=pl.BlockSpec((None, group * block, D), lambda b, i, *_: (b, i, 0)),
            scratch_shapes=[
                pltpu.VMEM((D, a_pad * block), q.dtype),
                pltpu.VMEM((D, a_pad * block), q.dtype),
                pltpu.SemaphoreType.DMA((2, a_pad)),
            ]),
        out_shape=jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
        interpret=interpret,
    )(counts, cols_p, q3, k3, v3, do3, lse3, delta3)

    rows_p, at_pad, kwidth_t = _pad_lut(rows_t)
    assert 2 * at_pad * D * block * q.dtype.itemsize < 12 * 1024 * 1024, \
        "layout too dense for all-upfront DMA in dkv (a k-column with too many " \
        "active q-blocks; reduce max column density)"
    # Q/dO blocked + transposed [BH, nb, D, block] for the lane-concat DMA
    q4 = q.reshape(B * H, nb, block, D).transpose(0, 1, 3, 2)
    do4 = do.reshape(B * H, nb, block, D).transpose(0, 1, 3, 2)
    k3f = k.reshape(B * H, T, D)
    v3f = v.reshape(B * H, T, D)
    dk, dv = pl.pallas_call(
        functools.partial(_bs_dkv_kernel, sm_scale=sm_scale, causal=causal, block=block,
                          num_heads=H, ng=ng, kwidth=kwidth_t, group=group),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B * H, ng),
            in_specs=[
                pl.BlockSpec(memory_space=pl.ANY),  # Q stays in HBM
                pl.BlockSpec((None, group * block, D), lambda b, i, *_: (b, i, 0)),
                pl.BlockSpec((None, group * block, D), lambda b, i, *_: (b, i, 0)),
                pl.BlockSpec(memory_space=pl.ANY),  # dO stays in HBM
                pl.BlockSpec((None, 1, T), lambda b, i, *_: (b, 0, 0)),
                pl.BlockSpec((None, 1, T), lambda b, i, *_: (b, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((None, group * block, D), lambda b, i, *_: (b, i, 0)),
                pl.BlockSpec((None, group * block, D), lambda b, i, *_: (b, i, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((D, at_pad * block), q.dtype),
                pltpu.VMEM((D, at_pad * block), q.dtype),
                pltpu.SemaphoreType.DMA((2, at_pad)),
            ]),
        out_shape=[
            jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
        ],
        interpret=interpret,
    )(counts_t, rows_p, q4, k3f, v3f, do4, lse3, delta3)
    return dq.reshape(B, H, T, D), dk.reshape(B, H, T, D), dv.reshape(B, H, T, D)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10, 11))
def _bs_attention_core(q, k, v, counts, cols, counts_t, rows_t,
                       block, causal, sm_scale, group, interpret):
    out, _ = _bs_core_fwd(q, k, v, counts, cols, counts_t, rows_t,
                          block, causal, sm_scale, group, interpret)
    return out


def _bs_core_fwd(q, k, v, counts, cols, counts_t, rows_t,
                 block, causal, sm_scale, group, interpret):
    out, lse = _bs_fwd(q, k, v, counts, cols, group, sm_scale, causal, block,
                       interpret)
    return out, (q, k, v, out, lse, counts, cols, counts_t, rows_t)


def _bs_core_bwd(block, causal, sm_scale, group, interpret, res, g):
    dq, dk, dv = _bs_bwd(res, g, sm_scale, causal, block, group, interpret)
    return dq, dk, dv, None, None, None, None


_bs_attention_core.defvjp(_bs_core_fwd, _bs_core_bwd)


def block_sparse_attention(q, k, v, layout, block: int, causal: bool = False,
                           sm_scale: Optional[float] = None, interpret: Optional[bool] = None,
                           group: Optional[int] = None):
    """Block-sparse attention on [B, H, T, D] with a [H, T/block, T/block] layout.

    ``group``: layout q-rows (and, transposed, k-columns) packed per grid cell via a
    union LUT + membership bitmasks; default targets 256-wide score tiles."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    assert q.shape[2] % block == 0, f"seq len {q.shape[2]} must be divisible by block {block}"
    assert layout.shape[1] == q.shape[2] // block, "layout block-count mismatch with seq len"
    layout = np.asarray(layout)
    nb = q.shape[2] // block
    if group is None:
        group = _pick_group(nb, block)
    while nb % group != 0:
        group //= 2
    group = max(1, group)
    counts, cols, counts_t, rows_t = _cached_luts(layout, group)
    return _bs_attention_core(q, k, v, jnp.asarray(counts), jnp.asarray(cols),
                              jnp.asarray(counts_t), jnp.asarray(rows_t),
                              block, causal, sm_scale, group, interpret)


# LUT build is pure host work on a static layout: a deep model calls
# block_sparse_attention once PER LAYER with the same layout, and without this
# cache each trace would re-run build_grouped_luts (Python loops over H*ng
# groups, twice — forward + transposed). Keyed by layout bytes, bounded LRU.
# The cache holds NUMPY arrays only: jnp.asarray inside an active jit trace
# stages a device_put and returns a tracer, which must never outlive its trace.
_LUT_CACHE = {}
_LUT_CACHE_MAX = 32


def _cached_luts(layout: np.ndarray, group: int):
    key = (layout.shape, layout.tobytes(), group)
    hit = _LUT_CACHE.pop(key, None)
    if hit is None:
        counts, cols = build_grouped_luts(layout, group)
        counts_t, rows_t = build_grouped_luts(np.transpose(layout, (0, 2, 1)), group)
        hit = (counts, cols, counts_t, rows_t)
        while len(_LUT_CACHE) >= _LUT_CACHE_MAX:
            _LUT_CACHE.pop(next(iter(_LUT_CACHE)))
    _LUT_CACHE[key] = hit  # re-insert = move to MRU position
    return hit


def dense_blocksparse_attention(q, k, v, layout, block: int, causal: bool = False,
                                sm_scale: Optional[float] = None):
    """Dense-masked reference (numerics oracle; O(T^2) memory)."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    B, H, T, D = q.shape
    mask = np.kron(np.asarray(layout) != 0, np.ones((block, block), bool))  # [H, T, T]
    if causal:
        mask = mask & np.tril(np.ones((T, T), bool))[None]
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * sm_scale
    scores = jnp.where(jnp.asarray(mask)[None], scores, DEFAULT_MASK_VALUE)
    probs = jax.nn.softmax(scores, axis=-1)
    # rows with no active blocks: all-masked softmax is uniform garbage; zero them
    any_active = jnp.asarray(mask.any(-1))[None, :, :, None]
    probs = jnp.where(any_active, probs, 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(q.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)
