"""Fused Adam/AdamW for TPU.

Replaces the reference's apex ``FusedAdam`` (used via ``runtime/engine.py:544-556``) and the
update math of ``csrc/adam/cpu_adam.cpp`` (N2). On TPU a jitted elementwise update IS the
fused kernel — XLA emits a single fused loop over each parameter buffer; there is nothing
to hand-write. State and master weights are fp32; under ZeRO they carry sharded layouts and
GSPMD partitions this update automatically.

The functional contract (init/apply) is shared by all optimizers in this package:
  init(master_params) -> opt_state
  apply(grads, opt_state, master_params, step, hyper) -> (new_master_params, new_opt_state)
where ``hyper`` is a dict of *device scalars* {lr, beta1, beta2, eps, weight_decay} so
schedule changes never recompile.

Per-group hyperparameters (the reference's torch param_groups with per-group
lr/weight_decay, engine.py:503-650): pass ``groups`` — a pytree of STATIC ints mirroring
the params — and make each ``hyper`` value a [n_groups] device array; every leaf then
indexes its group's scalars at trace time (no gather in the compiled update).
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp


class AdamState(NamedTuple):
    exp_avg: object   # pytree like params (fp32)
    exp_avg_sq: object


def init(master_params) -> AdamState:
    zeros = jax.tree_util.tree_map(lambda p: jnp.zeros(p.shape, jnp.float32), master_params)
    zeros2 = jax.tree_util.tree_map(lambda p: jnp.zeros(p.shape, jnp.float32), master_params)
    return AdamState(exp_avg=zeros, exp_avg_sq=zeros2)


def hyper_for_group(hyper: dict, gi: int) -> dict:
    """Per-leaf view of ``hyper``: index [n_groups] arrays by the leaf's static group
    id; pass 0-d scalars through (single-group mode)."""
    out = {}
    for k, h in hyper.items():
        arr = jnp.asarray(h)
        out[k] = arr[gi] if arr.ndim else arr
    return out


def flat_group_ids(groups, n_leaves: int):
    """[static int per leaf] from a groups pytree (all-zeros when groups is None)."""
    if groups is None:
        return [0] * n_leaves
    ids = [int(g) for g in jax.tree_util.tree_leaves(groups)]
    assert len(ids) == n_leaves, f"groups tree has {len(ids)} leaves, params {n_leaves}"
    return ids


def apply(grads, state: AdamState, master_params, step, hyper, adamw: bool = True,
          groups=None):
    """One Adam step. ``step`` is the 1-based update count (device int32)."""
    stepf = step.astype(jnp.float32)

    def leaf(g, m, v, p, gi):
        h = hyper_for_group(hyper, gi)
        lr, b1, b2, eps, wd = h["lr"], h["beta1"], h["beta2"], h["eps"], h["weight_decay"]
        bc1 = 1.0 - jnp.power(b1, stepf)
        bc2 = 1.0 - jnp.power(b2, stepf)
        g = g.astype(jnp.float32)
        if not adamw:
            # classic L2 Adam (torch.optim.Adam / reference apex FusedAdam): the decay
            # term enters the gradient BEFORE the moment updates
            g = g + wd * p
        m = b1 * m + (1.0 - b1) * g
        v = b2 * v + (1.0 - b2) * jnp.square(g)
        m_hat = m / bc1
        v_hat = v / bc2
        update = m_hat / (jnp.sqrt(v_hat) + eps)
        if adamw:
            new_p = p - lr * (update + wd * p)
        else:
            new_p = p - lr * update
        return new_p, m, v

    flat_g, treedef = jax.tree_util.tree_flatten(grads)
    flat_m = jax.tree_util.tree_leaves(state.exp_avg)
    flat_v = jax.tree_util.tree_leaves(state.exp_avg_sq)
    flat_p = jax.tree_util.tree_leaves(master_params)
    flat_gi = flat_group_ids(groups, len(flat_g))
    new_p, new_m, new_v = [], [], []
    for g, m, v, p, gi in zip(flat_g, flat_m, flat_v, flat_p, flat_gi):
        np_, nm, nv = leaf(g, m, v, p, gi)
        new_p.append(np_)
        new_m.append(nm)
        new_v.append(nv)
    unflat = jax.tree_util.tree_unflatten
    return unflat(treedef, new_p), AdamState(exp_avg=unflat(treedef, new_m),
                                             exp_avg_sq=unflat(treedef, new_v))


DEFAULT_HYPER = dict(lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8, weight_decay=0.0)


def hyper_from_params(params: dict) -> dict:
    """Translate a DeepSpeed optimizer-params dict into our hyper dict."""
    betas = params.get("betas", (0.9, 0.999))
    return dict(lr=params.get("lr", 1e-3),
                beta1=betas[0],
                beta2=betas[1],
                eps=params.get("eps", 1e-8),
                weight_decay=params.get("weight_decay", 0.0))
