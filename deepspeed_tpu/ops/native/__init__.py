"""Native (C++) op loading for host-side kernels.

Replaces the reference's torch cpp_extension build system (``setup.py:138-303``,
``DS_BUILD_CPU_ADAM``): sources live in ``deepspeed_tpu/csrc/`` and are compiled on
first use with the system toolchain into a shared library next to the source, then
bound via ctypes (no pybind11 in this environment). A content-hash in the library name
invalidates stale builds. Failure to build degrades gracefully: callers fall back to a
vectorized numpy implementation.

Set ``DS_SKIP_NATIVE=1`` to force the numpy fallbacks (same spirit as the reference's
``DS_BUILD_*`` masks).
"""

import ctypes
import hashlib
import os
import subprocess

from ...utils.logging import logger

_CSRC = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
                     "csrc")
_LOADED = {}


def _build(source_path: str, tag: str):
    import platform
    with open(source_path, "rb") as f:
        # Key the cache on source AND host ISA: -march=native binaries must never be
        # reused on a machine with different CPU features (SIGILL instead of fallback).
        hasher = hashlib.sha256(f.read())
        hasher.update(platform.machine().encode())
        try:
            with open("/proc/cpuinfo") as cpu:
                for line in cpu:
                    if line.startswith("flags") or line.startswith("Features"):
                        hasher.update(line.encode())
                        break
        except OSError:
            pass
        digest = hasher.hexdigest()[:12]
    lib_path = os.path.join(_CSRC, f"_{tag}_{digest}.so")
    if os.path.exists(lib_path):
        return lib_path
    flag_sets = [
        ["-O3", "-march=native", "-fopenmp"],
        ["-O3", "-march=native"],   # toolchains without libgomp
        ["-O2"],                    # last resort: portable scalar build
    ]
    # Compile to a process-private temp path and rename into place: rename is atomic,
    # so a killed/timed-out compile can never leave a truncated .so at the cache path,
    # and concurrent builders on one host race harmlessly.
    tmp_path = f"{lib_path}.tmp.{os.getpid()}"
    for flags in flag_sets:
        cmd = ["g++", "-shared", "-fPIC", "-std=c++17", *flags, "-o", tmp_path, source_path]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(tmp_path, lib_path)
            logger.info(f"[deepspeed_tpu] built native op {tag}: {' '.join(cmd)}")
            return lib_path
        except (subprocess.CalledProcessError, subprocess.TimeoutExpired, FileNotFoundError) as e:
            err = getattr(e, "stderr", b"")
            logger.warning(f"[deepspeed_tpu] native build of {tag} failed with {flags}: "
                           f"{err.decode(errors='replace')[:500] if err else e}")
            try:
                os.remove(tmp_path)
            except OSError:
                pass
    return None


def load_cpu_adam():
    """Load (building if needed) the native CPU Adam; returns None on any failure."""
    if "cpu_adam" in _LOADED:
        return _LOADED["cpu_adam"]
    lib = None
    if os.environ.get("DS_SKIP_NATIVE", "0") != "1":
        src = os.path.join(_CSRC, "cpu_adam.cpp")
        path = _build(src, "cpu_adam")
        if path is not None:
            try:
                lib = ctypes.CDLL(path)
                f32p = ctypes.POINTER(ctypes.c_float)
                u16p = ctypes.POINTER(ctypes.c_uint16)
                common = [ctypes.c_int64, ctypes.c_int32, ctypes.c_float, ctypes.c_float,
                          ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
                          ctypes.c_int32, ctypes.c_int32]
                lib.ds_adam_step.argtypes = [f32p, f32p, f32p, f32p] + common
                lib.ds_adam_step.restype = None
                lib.ds_adam_step_copy.argtypes = [f32p, f32p, f32p, f32p, u16p] + common
                lib.ds_adam_step_copy.restype = None
            except OSError as e:
                logger.warning(f"[deepspeed_tpu] failed to load native cpu_adam: {e}")
                lib = None
    _LOADED["cpu_adam"] = lib
    return lib
