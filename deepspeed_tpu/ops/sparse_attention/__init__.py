from .sparsity_config import (SparsityConfig, DenseSparsityConfig, FixedSparsityConfig,
                              VariableSparsityConfig, BigBirdSparsityConfig,
                              BSLongformerSparsityConfig)
from .sparse_self_attention import SparseSelfAttention, BertSparseSelfAttention
from .sparse_attention_utils import SparseAttentionUtils
from .matmul import MatMul, dense_to_sparse, sparse_to_dense
from .softmax import Softmax
