"""Fused block-sparse softmax over layout-active blocks.

TPU-native rebuild of the reference's Triton sparse softmax
(``deepspeed/ops/sparse_attention/softmax.py:207-292`` + ``trsrc/softmax_fwd.tr`` /
``softmax_bwd.tr``): numerically-stable softmax across each logical row of a block-sparse
score matrix, fused with optional scale, relative position embedding, key-padding mask and
attention mask. Rows are distributed across blocks, so the row reductions are scatter-max /
scatter-add over a row-segment LUT; XLA lowers these to efficient segmented reductions and
the surrounding elementwise work fuses into one kernel.

Sparse input/output format matches ``matmul.MatMul``: ``[batch, nnz, block, block]`` in
row-major ``(head, row_block, col_block)`` layout order.
"""

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .matmul import _lut

__all__ = ["Softmax"]


class Softmax:
    """softmax(scale*x + rpe + masks) across logical rows of the sparse matrix
    (reference softmax.py:207 ``Softmax``; mask semantics l.244-292)."""

    def __init__(self, layout: np.ndarray, block: int):
        self.layout = np.asarray(layout)
        self.block = int(block)
        self.lut_h, self.lut_i, self.lut_j = _lut(self.layout)
        H, Mb, Nb = self.layout.shape
        # segment id of each nonzero block = its logical (head, row-block) pair
        self.row_seg = (self.lut_h.astype(np.int64) * Mb + self.lut_i).astype(np.int32)
        self.num_segs = H * Mb

    def __call__(self, x: jnp.ndarray, scale: float = 1.0,
                 rpe: Optional[jnp.ndarray] = None,
                 key_padding_mask: Optional[jnp.ndarray] = None,
                 attn_mask: Optional[jnp.ndarray] = None,
                 key_padding_mask_mode: str = "add",
                 attn_mask_mode: str = "mul") -> jnp.ndarray:
        blk = self.block
        B, nnz, _, _ = x.shape
        assert nnz == len(self.lut_h), \
            f"values nnz={nnz} does not match layout nnz={len(self.lut_h)}"
        dtype = x.dtype
        x = x.astype(jnp.float32) * scale

        if rpe is not None:
            # [H, T, T] / [1, T, T] relative position bias, or [B, H, T, T] for a
            # per-batch bias (the reference kernel strides RPE by batch: pidz *
            # stride_zrpe in softmax_fwd.tr), gathered blockwise either way
            rpe = jnp.asarray(rpe, jnp.float32)
            H = self.layout.shape[0]
            T = rpe.shape[-1]
            if rpe.ndim == 4:
                if rpe.shape[1] == 1 and H > 1:
                    rpe = jnp.broadcast_to(rpe, (rpe.shape[0], H) + rpe.shape[2:])
                rpe_blocks = (rpe.reshape(rpe.shape[0], H, T // blk, blk, T // blk, blk)
                              .transpose(0, 1, 2, 4, 3, 5))
                x = x + rpe_blocks[:, self.lut_h, self.lut_i, self.lut_j]
            else:
                if rpe.shape[0] == 1 and H > 1:
                    rpe = jnp.broadcast_to(rpe, (H,) + rpe.shape[1:])
                rpe_blocks = rpe.reshape(H, T // blk, blk, T // blk, blk).transpose(0, 1, 3, 2, 4)
                x = x + rpe_blocks[self.lut_h, self.lut_i, self.lut_j][None]

        if attn_mask is not None:
            # [T, T] mask over (query, key) positions. "mul" semantics follow the
            # reference kernel (softmax_fwd.tr ATTN_MASK_MUL): zero mask lanes become
            # -inf before the row reduction; nonzero lanes leave the score UNCHANGED
            # (the kernel adds +0 there — it never scales by the mask value).
            attn_mask = jnp.asarray(attn_mask, jnp.float32)
            T = attn_mask.shape[-1]
            am_blocks = attn_mask.reshape(T // blk, blk, T // blk, blk).transpose(0, 2, 1, 3)
            am = am_blocks[self.lut_i, self.lut_j][None]
            if attn_mask_mode == "mul":
                x = jnp.where(am == 0.0, -jnp.inf, x)
            else:
                x = x + am

        if key_padding_mask is not None:
            # [B, T] mask over key positions (broadcast down each block row)
            key_padding_mask = jnp.asarray(key_padding_mask, jnp.float32)
            kp_blocks = key_padding_mask.reshape(B, -1, blk)        # [B, Nb, blk]
            kp = kp_blocks[:, self.lut_j][:, :, None, :]            # [B, nnz, 1, blk]
            if key_padding_mask_mode == "mul":
                # KP_MASK_MUL: zero -> -inf, nonzero -> score unchanged
                x = jnp.where(kp == 0.0, -jnp.inf, x)
            else:
                x = x + kp

        # --- segmented stable softmax across each logical row ---
        neg_inf = jnp.float32(-jnp.inf)
        block_rowmax = x.max(axis=-1)                                # [B, nnz, blk]
        rowmax = jnp.full((B, self.num_segs, blk), neg_inf)
        rowmax = rowmax.at[:, self.row_seg].max(block_rowmax)
        rowmax = jax.lax.stop_gradient(rowmax)
        shifted = x - rowmax[:, self.row_seg][..., None]
        # fully-masked rows: exp(-inf - -inf) = nan -> force 0
        ex = jnp.where(jnp.isnan(shifted), 0.0, jnp.exp(shifted))
        block_rowsum = ex.sum(axis=-1)                               # [B, nnz, blk]
        rowsum = jnp.zeros((B, self.num_segs, blk))
        rowsum = rowsum.at[:, self.row_seg].add(block_rowsum)
        denom = rowsum[:, self.row_seg][..., None]
        out = jnp.where(denom > 0, ex / jnp.where(denom > 0, denom, 1.0), 0.0)
        return out.astype(dtype)
