"""Block-sparse matrix multiply ops (SDD / DSD / DDS modes).

TPU-native rebuild of the reference's Triton-backed ``MatMul``
(``deepspeed/ops/sparse_attention/matmul.py:595-729``; LUT builders l.90-320; the CUDA
``sdd_segment`` LUT segmenter ``csrc/sparse_attention/utils.cpp:14-119``). The reference
launches hand-written Triton kernels over a lookup table of nonzero blocks; here the same
semantics are expressed as XLA gather → nnz-batched ``einsum`` → scatter-add, which the
TPU compiler maps onto batched MXU matmuls. The LUT is just the row-major nonzero list of
the layout — no greedy segmentation pass is needed because XLA tiles the batched matmul
itself.

Sparse operands/results use a flat block format: ``[batch, nnz, block, block]`` where
``nnz`` enumerates ``layout.nonzero()`` in row-major ``(head, row_block, col_block)``
order (the same canonical order as ``block_sparse_attention.build_luts``).

Performance (measured, tests/perf/sparse_ops_perf.py, BigBird block 128 at seq
4096/8192 bf16): the composed sdd→softmax→dsd attention runs at ~2.3–2.6× the fused
``block_sparse_attention`` Pallas kernel's time, and 6×/149× FASTER than dense
unfused XLA attention — these ops are a usable building block for custom sparse
patterns, but route hot attention paths through the fused kernel.

Modes (dense operands are ``[batch, heads, rows, cols]``):
- ``sdd``: dense @ dense -> sparse (only layout-active output blocks are computed)
- ``dsd``: sparse @ dense -> dense
- ``dds``: dense @ sparse -> dense
``trans_a`` / ``trans_b`` transpose the corresponding operand logically (for a sparse
operand this swaps its row/col LUTs and transposes each block), matching the reference's
use in backward passes.
"""

import jax.numpy as jnp
import numpy as np

__all__ = ["MatMul", "dense_to_sparse", "sparse_to_dense"]


def _lut(layout: np.ndarray):
    """Row-major nonzero list of a [heads, Mb, Nb] layout -> (h, i, j) index arrays."""
    layout = np.asarray(layout)
    assert layout.ndim == 3, f"layout must be [heads, blocks, blocks], got {layout.shape}"
    h, i, j = layout.nonzero()
    return h.astype(np.int32), i.astype(np.int32), j.astype(np.int32)


def dense_to_sparse(dense: jnp.ndarray, layout: np.ndarray, block: int) -> jnp.ndarray:
    """[B, H, M, N] dense -> [B, nnz, block, block] values of the layout-active blocks."""
    B, H, M, N = dense.shape
    hh, ii, jj = _lut(layout)
    blocked = dense.reshape(B, H, M // block, block, N // block, block)
    blocked = blocked.transpose(0, 1, 2, 4, 3, 5)  # [B, H, Mb, Nb, block, block]
    return blocked[:, hh, ii, jj]


def sparse_to_dense(vals: jnp.ndarray, layout: np.ndarray, block: int,
                    fill: float = 0.0) -> jnp.ndarray:
    """[B, nnz, block, block] values -> [B, H, M, N] dense with `fill` in inactive blocks."""
    layout = np.asarray(layout)
    H, Mb, Nb = layout.shape
    B = vals.shape[0]
    hh, ii, jj = _lut(layout)
    out = jnp.full((B, H, Mb, Nb, block, block), fill, vals.dtype)
    out = out.at[:, hh, ii, jj].set(vals)
    return out.transpose(0, 1, 2, 4, 3, 5).reshape(B, H, Mb * block, Nb * block)


class MatMul:
    """Block-sparse matmul with a fixed layout (reference matmul.py:595 ``MatMul``)."""

    def __init__(self, layout: np.ndarray, block: int, mode: str,
                 trans_a: bool = False, trans_b: bool = False):
        if mode not in ("sdd", "dsd", "dds"):
            raise NotImplementedError(f"Supported modes are: sdd, dsd, dds — got {mode!r}")
        self.layout = np.asarray(layout)
        self.block = int(block)
        self.mode = mode
        self.trans_a = trans_a
        self.trans_b = trans_b
        self.lut_h, self.lut_i, self.lut_j = _lut(self.layout)
        self.nnz = len(self.lut_h)

    # ---------------------------------------------------------------- helpers
    def _sparse_luts(self, transposed: bool):
        """(row, col) LUTs of the sparse operand, honoring a logical transpose."""
        if transposed:
            return self.lut_j, self.lut_i
        return self.lut_i, self.lut_j

    def _check_blocks(self, name, nblocks, axis_len):
        """JAX clamps out-of-bounds gather indices, which would silently duplicate the
        last block — validate dense operand extents against the layout instead."""
        if axis_len != nblocks * self.block:
            raise ValueError(
                f"{name} extent {axis_len} does not match layout: expected "
                f"{nblocks} blocks x block={self.block} = {nblocks * self.block}")

    def __call__(self, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        H, Mb, Nb = self.layout.shape
        if self.mode == "sdd":
            self._check_blocks("a rows", Nb if self.trans_a else Mb,
                               a.shape[-1] if self.trans_a else a.shape[-2])
            self._check_blocks("b cols", Mb if self.trans_b else Nb,
                               b.shape[-2] if self.trans_b else b.shape[-1])
        elif self.mode == "dsd":
            if a.shape[1] != self.nnz:
                raise ValueError(f"sparse operand nnz={a.shape[1]} != layout nnz={self.nnz}")
            self._check_blocks("b rows", Mb if self.trans_a else Nb,
                               b.shape[-1] if self.trans_b else b.shape[-2])
        else:  # dds
            if b.shape[1] != self.nnz:
                raise ValueError(f"sparse operand nnz={b.shape[1]} != layout nnz={self.nnz}")
            self._check_blocks("a cols", Nb if self.trans_b else Mb,
                               a.shape[-2] if self.trans_a else a.shape[-1])
        return getattr(self, f"_{self.mode}")(a, b)

    # ---------------------------------------------------------------- modes
    def _sdd(self, a, b):
        """dense [B,H,M,K] @ dense [B,H,K,N] -> sparse [B,nnz,block,block]."""
        blk = self.block
        if self.trans_a:
            a = a.swapaxes(-1, -2)
        if not self.trans_b:
            b = b.swapaxes(-1, -2)          # -> [B, H, N, K] (row-gatherable)
        B, H, M, K = a.shape
        a_blocks = a.reshape(B, H, M // blk, blk, K)[:, self.lut_h, self.lut_i]
        b_blocks = b.reshape(B, H, b.shape[2] // blk, blk, K)[:, self.lut_h, self.lut_j]
        # [B, nnz, blk, K] x [B, nnz, blk, K] -> [B, nnz, blk, blk]
        return jnp.einsum("bnik,bnjk->bnij", a_blocks, b_blocks,
                          preferred_element_type=jnp.float32).astype(a.dtype)

    def _dsd(self, a, b):
        """sparse [B,nnz,blk,blk] @ dense [B,H,K,N] -> dense [B,H,M,N]."""
        blk = self.block
        rows, cols = self._sparse_luts(self.trans_a)
        vals = a.swapaxes(-1, -2) if self.trans_a else a
        if self.trans_b:
            b = b.swapaxes(-1, -2)
        B, H, K, N = b.shape
        Mb = self.layout.shape[2] if self.trans_a else self.layout.shape[1]
        b_blocks = b.reshape(B, H, K // blk, blk, N)[:, self.lut_h, cols]  # [B,nnz,blk,N]
        prod = jnp.einsum("bnij,bnjk->bnik", vals, b_blocks,
                          preferred_element_type=jnp.float32).astype(b.dtype)
        out = jnp.zeros((B, H, Mb, blk, N), prod.dtype)
        out = out.at[:, self.lut_h, rows].add(prod)
        return out.reshape(B, H, Mb * blk, N)

    def _dds(self, a, b):
        """dense [B,H,M,K] @ sparse [B,nnz,blk,blk] -> dense [B,H,M,N]."""
        blk = self.block
        rows, cols = self._sparse_luts(self.trans_b)
        vals = b.swapaxes(-1, -2) if self.trans_b else b
        if self.trans_a:
            a = a.swapaxes(-1, -2)
        B, H, M, K = a.shape
        Nb = self.layout.shape[1] if self.trans_b else self.layout.shape[2]
        # gather a's K-blocks (the sparse operand's row dim): [B,H,Kb,M,blk]
        a_blocks = a.reshape(B, H, M, K // blk, blk).transpose(0, 1, 3, 2, 4)
        a_strips = a_blocks[:, self.lut_h, rows]                 # [B, nnz, M, blk]
        prod = jnp.einsum("bnmi,bnij->bnmj", a_strips, vals,
                          preferred_element_type=jnp.float32).astype(a.dtype)
        out = jnp.zeros((B, H, Nb, M, blk), prod.dtype)
        out = out.at[:, self.lut_h, cols].add(prod)
        return out.transpose(0, 1, 3, 2, 4).reshape(B, H, M, Nb * blk)
