"""Helpers for using sparse attention with transformer models.

Mirrors ``deepspeed/ops/sparse_attention/sparse_attention_utils.py`` (SparseAttentionUtils
l.13-225): pad inputs to the block size, unpad outputs, extend position embeddings. The
reference's HF-torch model-surgery helpers (replace_model_self_attention_...) translate
here to swapping the attention callable on our in-tree BERT/GPT models.
"""

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np


class SparseAttentionUtils:

    @staticmethod
    def extend_position_embedding(position_embedding, max_position: int):
        """Tile an existing [P, H] position embedding out to max_position rows
        (reference l.36-84 extends HF model embeddings the same way)."""
        P, H = position_embedding.shape
        if max_position <= P:
            return position_embedding[:max_position]
        reps = -(-max_position // P)
        extended = jnp.concatenate([position_embedding] * reps, axis=0)[:max_position]
        return extended

    @staticmethod
    def update_tokenizer_model_max_length(tokenizer, max_position: int):
        tokenizer.model_max_length = max_position
        if hasattr(tokenizer, "init_kwargs"):
            tokenizer.init_kwargs["model_max_length"] = max_position
        return tokenizer

    @staticmethod
    def pad_to_block_size(block_size: int,
                          input_ids,
                          attention_mask=None,
                          token_type_ids=None,
                          position_ids=None,
                          inputs_embeds=None,
                          pad_token_id: int = 0,
                          model_embeddings=None) -> Tuple:
        """Pad sequence dim up to a multiple of block_size (reference l.85-174).

        Returns (pad_len, input_ids, attention_mask, token_type_ids, position_ids,
        inputs_embeds).
        """
        ref = input_ids if input_ids is not None else inputs_embeds
        seq_len = ref.shape[1]
        pad_len = (block_size - seq_len % block_size) % block_size
        if pad_len == 0:
            return (0, input_ids, attention_mask, token_type_ids, position_ids, inputs_embeds)

        def pad2d(x, value=0):
            if x is None:
                return None
            return jnp.pad(jnp.asarray(x), ((0, 0), (0, pad_len)), constant_values=value)

        input_ids = pad2d(input_ids, pad_token_id)
        attention_mask = pad2d(attention_mask, 0)
        token_type_ids = pad2d(token_type_ids, 0)
        position_ids = pad2d(position_ids, 0)
        if inputs_embeds is not None:
            pad_block = jnp.zeros((inputs_embeds.shape[0], pad_len, inputs_embeds.shape[2]),
                                  inputs_embeds.dtype)
            if model_embeddings is not None and input_ids is None:
                pad_ids = jnp.full((inputs_embeds.shape[0], pad_len), pad_token_id, jnp.int32)
                pad_block = jnp.asarray(model_embeddings)[pad_ids].astype(inputs_embeds.dtype)
            inputs_embeds = jnp.concatenate([inputs_embeds, pad_block], axis=1)
        return (pad_len, input_ids, attention_mask, token_type_ids, position_ids, inputs_embeds)

    @staticmethod
    def unpad_sequence_output(pad_len: int, sequence_output):
        """Drop padded positions from the model output (reference l.176-193)."""
        if pad_len > 0:
            return sequence_output[:, :-pad_len]
        return sequence_output
