"""Block-sparse attention layout configurations.

Same semantic surface as ``deepspeed/ops/sparse_attention/sparsity_config.py`` (663 LoC):
Dense / Fixed / Variable / BigBird / BSLongformer patterns produce boolean layouts of
shape [num_heads, seq_blocks, seq_blocks] at ``block`` granularity. Layouts here are
numpy bool arrays (host-side, static per seq_len) — they drive both the Pallas
block-sparse kernel's LUTs and the dense-masked fallback.

Pattern definitions (local windows, global representative blocks, sliding windows,
random blocks, uni/bidirectional) follow the cited papers exactly as the reference does:
Sparse Transformers (Fixed), BigBird, Longformer.
"""

import random
from typing import List, Optional

import numpy as np


class SparsityConfig:
    """Base: holds head count, block size, per-head-layout flag.

    Layout construction is DETERMINISTIC: patterns with random blocks
    (BigBird, Variable) draw from ``random.Random(layout_seed)``, so every
    process — multi-host data-parallel ranks, or a later eval run reloading a
    checkpoint — realizes the identical layout. (The reference sampled the
    unseeded global RNG; per-process layouts would bake different LUT
    constants into each host's compiled program and silently diverge.)"""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 layout_seed=709):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head
        self.num_layout_heads = num_heads if different_layout_per_head else 1
        self.layout_seed = layout_seed

    def layout_rng(self) -> "random.Random":
        """Fresh seeded RNG per make_layout call, so repeated builds (and
        different sequence lengths) are themselves reproducible."""
        return random.Random(self.layout_seed)

    def set_random_layout(self, h, layout, rng=None):
        """Per-row random blocks for patterns with ``num_random_blocks``
        (Variable, BigBird); shared here so the sampling logic has one home."""
        num_blocks = layout.shape[1]
        if num_blocks < self.num_random_blocks:
            raise ValueError(f"sparse layout: num_random_blocks={self.num_random_blocks} "
                             f"exceeds the {num_blocks} blocks per row")
        rng = rng or self.layout_rng()
        for row in range(num_blocks):
            rnd_cols = rng.sample(range(num_blocks), self.num_random_blocks)
            layout[h, row, rnd_cols] = 1
        return layout

    def setup_layout(self, seq_len) -> np.ndarray:
        if seq_len % self.block != 0:
            raise ValueError(f"sparse layout: seq_len={seq_len} is not a multiple of block={self.block}")
        num_blocks = seq_len // self.block
        return np.zeros((self.num_heads, num_blocks, num_blocks), dtype=np.int64)

    def check_and_propagate_first_head_layout(self, layout: np.ndarray) -> np.ndarray:
        if not self.different_layout_per_head:
            layout[1:] = layout[0]
        return layout


class DenseSparsityConfig(SparsityConfig):
    """All blocks active (dense attention expressed in the block-sparse machinery)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False):
        super().__init__(num_heads, block, different_layout_per_head=False)

    def make_layout(self, seq_len) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        layout[:] = 1
        return layout


class FixedSparsityConfig(SparsityConfig):
    """Sparse-Transformers 'fixed' pattern: local windows + fixed global representative
    blocks per window, uni- or bidirectional."""

    def __init__(self,
                 num_heads,
                 block=16,
                 different_layout_per_head=False,
                 num_local_blocks=4,
                 num_global_blocks=1,
                 attention="bidirectional",
                 horizontal_global_attention=False,
                 num_different_global_patterns=1):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_local_blocks = num_local_blocks
        if num_local_blocks % num_global_blocks != 0:
            raise ValueError(f"sparse layout: num_local_blocks={num_local_blocks} is not a "
                             f"multiple of num_global_blocks={num_global_blocks}")
        self.num_global_blocks = num_global_blocks
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError(f"sparse layout: unknown attention mode {attention!r} "
                                      "(expected 'unidirectional' or 'bidirectional')")
        self.attention = attention
        if attention != "bidirectional" and horizontal_global_attention:
            raise ValueError("sparse layout: horizontal_global_attention requires "
                             "attention='bidirectional'")
        self.horizontal_global_attention = horizontal_global_attention
        if num_different_global_patterns > 1 and not different_layout_per_head:
            raise ValueError("sparse layout: num_different_global_patterns > 1 requires "
                             "different_layout_per_head=True")
        if num_different_global_patterns > (num_local_blocks // num_global_blocks):
            raise ValueError(f"sparse layout: num_different_global_patterns="
                             f"{num_different_global_patterns} exceeds the "
                             f"{num_local_blocks // num_global_blocks} distinct patterns available")
        self.num_different_global_patterns = num_different_global_patterns

    def set_local_layout(self, h, layout):
        num_blocks = layout.shape[1]
        for win_start in range(0, num_blocks, self.num_local_blocks):
            end = min(win_start + self.num_local_blocks, num_blocks)
            for row in range(win_start, end):
                last_col = row + 1 if self.attention == "unidirectional" else end
                layout[h, row, win_start:last_col] = 1
        return layout

    def set_global_layout(self, h, layout):
        num_blocks = layout.shape[1]
        first_global = self.num_local_blocks - (
            1 + h % self.num_different_global_patterns) * self.num_global_blocks

        end = num_blocks - (num_blocks % self.num_local_blocks)
        for i in range(first_global, end, self.num_local_blocks):
            first_row = 0 if self.attention == "bidirectional" else i
            layout[h, first_row:, i:i + self.num_global_blocks] = 1
            if self.horizontal_global_attention:
                layout[h, i:i + self.num_global_blocks, :] = 1
        if end < num_blocks:
            start = min(end + first_global, num_blocks - self.num_global_blocks)
            stop = start + self.num_global_blocks
            first_row = 0 if self.attention == "bidirectional" else start
            layout[h, first_row:, start:stop] = 1
            if self.horizontal_global_attention:
                layout[h, start:stop, :] = 1
        return layout

    def make_layout(self, seq_len) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            layout = self.set_local_layout(h, layout)
            layout = self.set_global_layout(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


class VariableSparsityConfig(SparsityConfig):
    """Variable-size local windows + explicit global block (ranges) + random blocks."""

    def __init__(self,
                 num_heads,
                 block=16,
                 different_layout_per_head=False,
                 num_random_blocks=0,
                 local_window_blocks: Optional[List[int]] = None,
                 global_block_indices: Optional[List[int]] = None,
                 global_block_end_indices: Optional[List[int]] = None,
                 attention="bidirectional",
                 horizontal_global_attention=False,
                 layout_seed=709):
        super().__init__(num_heads, block, different_layout_per_head,
                         layout_seed=layout_seed)
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = local_window_blocks or [4]
        self.global_block_indices = global_block_indices if global_block_indices is not None else [0]
        if global_block_end_indices is not None:
            if len(self.global_block_indices) != len(global_block_end_indices):
                raise ValueError("sparse layout: global_block_indices and "
                                 "global_block_end_indices differ in length")
            for start_idx, end_idx in zip(self.global_block_indices, global_block_end_indices):
                if start_idx >= end_idx:
                    raise ValueError(f"sparse layout: global block range [{start_idx}, {end_idx}) is empty")
        self.global_block_end_indices = global_block_end_indices
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError(f"sparse layout: unknown attention mode {attention!r} "
                                      "(expected 'unidirectional' or 'bidirectional')")
        self.attention = attention
        if attention != "bidirectional" and horizontal_global_attention:
            raise ValueError("sparse layout: horizontal_global_attention requires "
                             "attention='bidirectional'")
        self.horizontal_global_attention = horizontal_global_attention

    def set_local_layout(self, h, layout):
        num_blocks = layout.shape[1]
        start = 0
        end = 0
        block_size = self.local_window_blocks[-1]
        for block_size in self.local_window_blocks:
            end = min(end + block_size, num_blocks)
            for row in range(start, end):
                last_col = row + 1 if self.attention == "unidirectional" else end
                layout[h, row, start:last_col] = 1
            start += block_size
        for i in range(start, num_blocks, block_size):
            end = min(i + block_size, num_blocks)
            for row in range(i, end):
                last_col = row + 1 if self.attention == "unidirectional" else end
                layout[h, row, i:last_col] = 1
        return layout

    def set_global_layout(self, h, layout):
        num_blocks = layout.shape[1]
        if self.global_block_end_indices is None:
            for idx in self.global_block_indices:
                if idx < num_blocks:
                    if self.horizontal_global_attention:
                        layout[h, idx, :] = 1
                    first_row = 0 if self.attention == "bidirectional" else idx
                    layout[h, first_row:, idx] = 1
        else:
            for start_idx, end_idx in zip(self.global_block_indices, self.global_block_end_indices):
                if start_idx < num_blocks:
                    end_idx = min(end_idx, num_blocks)
                    if self.horizontal_global_attention:
                        layout[h, start_idx:end_idx, :] = 1
                    first_row = 0 if self.attention == "bidirectional" else start_idx
                    layout[h, first_row:, start_idx:end_idx] = 1
        return layout

    def make_layout(self, seq_len) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        rng = self.layout_rng()  # one seeded stream; heads draw sequentially
        for h in range(self.num_layout_heads):
            layout = self.set_random_layout(h, layout, rng)
            layout = self.set_local_layout(h, layout)
            layout = self.set_global_layout(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


class BigBirdSparsityConfig(SparsityConfig):
    """BigBird ITC: random + sliding window + leading global blocks."""

    def __init__(self,
                 num_heads,
                 block=16,
                 different_layout_per_head=False,
                 num_random_blocks=1,
                 num_sliding_window_blocks=3,
                 num_global_blocks=1,
                 layout_seed=709):
        super().__init__(num_heads, block, different_layout_per_head,
                         layout_seed=layout_seed)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks

    def set_sliding_window_layout(self, h, layout):
        num_blocks = layout.shape[1]
        if num_blocks < self.num_sliding_window_blocks:
            raise ValueError(f"sparse layout: num_sliding_window_blocks={self.num_sliding_window_blocks} "
                             f"exceeds the {num_blocks} blocks per row")
        w = self.num_sliding_window_blocks // 2
        for row in range(num_blocks):
            layout[h, row, max(0, row - w):min(row + w + 1, num_blocks)] = 1
        return layout

    def set_global_layout_itc(self, h, layout):
        num_blocks = layout.shape[1]
        if num_blocks < self.num_global_blocks:
            raise ValueError(f"sparse layout: num_global_blocks={self.num_global_blocks} "
                             f"exceeds the {num_blocks} blocks per row")
        layout[h, 0:self.num_global_blocks, :] = 1
        layout[h, :, 0:self.num_global_blocks] = 1
        return layout

    def make_layout(self, seq_len) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        rng = self.layout_rng()  # one seeded stream; heads draw sequentially
        for h in range(self.num_layout_heads):
            layout = self.set_random_layout(h, layout, rng)
            layout = self.set_sliding_window_layout(h, layout)
            layout = self.set_global_layout_itc(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


class BSLongformerSparsityConfig(SparsityConfig):
    """Block-sparse Longformer: sliding window + symmetric global block (ranges)."""

    def __init__(self,
                 num_heads,
                 block=16,
                 different_layout_per_head=False,
                 num_sliding_window_blocks=3,
                 global_block_indices: Optional[List[int]] = None,
                 global_block_end_indices: Optional[List[int]] = None):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = global_block_indices if global_block_indices is not None else [0]
        if global_block_end_indices is not None:
            if len(self.global_block_indices) != len(global_block_end_indices):
                raise ValueError("sparse layout: global_block_indices and "
                                 "global_block_end_indices differ in length")
            for start_idx, end_idx in zip(self.global_block_indices, global_block_end_indices):
                if start_idx >= end_idx:
                    raise ValueError(f"sparse layout: global block range [{start_idx}, {end_idx}) is empty")
        self.global_block_end_indices = global_block_end_indices

    def set_sliding_window_layout(self, h, layout):
        num_blocks = layout.shape[1]
        if num_blocks < self.num_sliding_window_blocks:
            raise ValueError(f"sparse layout: num_sliding_window_blocks={self.num_sliding_window_blocks} "
                             f"exceeds the {num_blocks} blocks per row")
        w = self.num_sliding_window_blocks // 2
        for row in range(num_blocks):
            layout[h, row, max(0, row - w):min(row + w + 1, num_blocks)] = 1
        return layout

    def set_global_layout(self, h, layout):
        num_blocks = layout.shape[1]
        if self.global_block_end_indices is None:
            for idx in self.global_block_indices:
                if idx < num_blocks:
                    layout[h, idx, :] = 1
                    layout[h, :, idx] = 1
        else:
            for start_idx, end_idx in zip(self.global_block_indices, self.global_block_end_indices):
                if start_idx < num_blocks:
                    end_idx = min(end_idx, num_blocks)
                    layout[h, start_idx:end_idx, :] = 1
                    layout[h, :, start_idx:end_idx] = 1
        return layout

    def make_layout(self, seq_len) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            layout = self.set_sliding_window_layout(h, layout)
            layout = self.set_global_layout(h, layout)
        return self.check_and_propagate_first_head_layout(layout)
