"""Sparse self-attention layer over SparsityConfig layouts.

Mirrors ``deepspeed/ops/sparse_attention/sparse_self_attention.py`` (SparseSelfAttention
l.18, forward l.83-142): computes softmax(QK^T * scale + masks) V under a block-sparse
layout. The Triton sdd→softmax→dsd pipeline is replaced by the single Pallas
block-sparse flash kernel; rpe / key-padding / attention masks take the dense-masked
path (they densify the score matrix anyway).
"""

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..pallas.block_sparse_attention import (DEFAULT_MASK_VALUE, block_sparse_attention,
                                             dense_blocksparse_attention)
from .sparsity_config import FixedSparsityConfig, SparsityConfig


class SparseSelfAttention:
    """q/k/v: [B, H, T, D] (already projected + split into heads)."""

    def __init__(self,
                 sparsity_config: SparsityConfig = None,
                 key_padding_mask_mode: str = "add",
                 attn_mask_mode: str = "mul",
                 max_seq_length: int = 2048):
        self.sparsity_config = sparsity_config or FixedSparsityConfig(num_heads=4)
        if key_padding_mask_mode not in ("add", "mul"):
            raise ValueError(f'only "add" or "mul" key_padding_mask_modes are supported, '
                             f'got {key_padding_mask_mode!r}')
        if attn_mask_mode not in ("add", "mul"):
            raise ValueError(f'only "add" or "mul" attn_mask_modes are supported, '
                             f'got {attn_mask_mode!r}')
        self.key_padding_mask_mode = key_padding_mask_mode
        self.attn_mask_mode = attn_mask_mode
        self._layout_cache = {}

    def get_layout(self, L: int) -> np.ndarray:
        if L not in self._layout_cache:
            self._layout_cache[L] = self.sparsity_config.make_layout(L)
        return self._layout_cache[L]

    def __call__(self, query, key, value, rpe=None, key_padding_mask=None, attn_mask=None):
        return self.forward(query, key, value, rpe, key_padding_mask, attn_mask)

    def forward(self, query, key, value, rpe=None, key_padding_mask=None, attn_mask=None):
        assert query.dtype == key.dtype == value.dtype, "only same-dtype q/k/v are supported"
        B, H, T, D = query.shape
        assert T % self.sparsity_config.block == 0, (
            f"sequence length {T} must be divisible by block size {self.sparsity_config.block}")
        layout = self.get_layout(T)
        causal = getattr(self.sparsity_config, "attention", "bidirectional") == "unidirectional"

        if rpe is None and key_padding_mask is None and attn_mask is None:
            return block_sparse_attention(query, key, value, layout,
                                          self.sparsity_config.block, causal=causal)
        return self._masked_dense(query, key, value, layout, causal, rpe, key_padding_mask,
                                  attn_mask)

    def _masked_dense(self, q, k, v, layout, causal, rpe, key_padding_mask, attn_mask):
        B, H, T, D = q.shape
        block = self.sparsity_config.block
        sm_scale = 1.0 / math.sqrt(D)
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * sm_scale
        if rpe is not None:
            scores = scores + rpe.astype(jnp.float32)
        if key_padding_mask is not None:
            m = key_padding_mask.astype(jnp.float32)[:, None, None, :]
            if self.key_padding_mask_mode == "add":
                scores = scores + m
            else:
                scores = jnp.where(m != 0, scores, DEFAULT_MASK_VALUE)
        if attn_mask is not None:
            m = attn_mask.astype(jnp.float32)
            while m.ndim < 4:
                m = m[None]
            if self.attn_mask_mode == "add":
                scores = scores + m
            else:
                scores = jnp.where(m != 0, scores, DEFAULT_MASK_VALUE)
        mask = np.kron(np.asarray(layout) != 0, np.ones((block, block), bool))
        if causal:
            mask = mask & np.tril(np.ones((T, T), bool))[None]
        scores = jnp.where(jnp.asarray(mask)[None], scores, DEFAULT_MASK_VALUE)
        probs = jax.nn.softmax(scores, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(q.dtype), v,
                          preferred_element_type=jnp.float32).astype(q.dtype)


class BertSparseSelfAttention:
    """BERT-style projected sparse attention (reference bert_sparse_self_attention.py):
    owns q/k/v projections; ``apply(params, hidden, attention_mask)`` -> context."""

    def __init__(self, hidden_size: int, num_attention_heads: int,
                 sparsity_config: SparsityConfig = None):
        if hidden_size % num_attention_heads != 0:
            raise ValueError(f"The hidden size ({hidden_size}) is not a multiple of "
                             f"the number of attention heads ({num_attention_heads})")
        self.hidden_size = hidden_size
        self.num_attention_heads = num_attention_heads
        self.head_dim = hidden_size // num_attention_heads
        self.sparse_self_attention = SparseSelfAttention(
            sparsity_config or FixedSparsityConfig(num_heads=num_attention_heads))

    def init(self, rng):
        H = self.hidden_size
        ks = jax.random.split(rng, 3)
        return {name: {"w": jax.random.normal(k, (H, H), jnp.float32) * 0.02,
                       "b": jnp.zeros((H,), jnp.float32)}
                for name, k in zip(("query", "key", "value"), ks)}

    def _split_heads(self, x):
        B, T, _ = x.shape
        return x.reshape(B, T, self.num_attention_heads, self.head_dim).transpose(0, 2, 1, 3)

    def apply(self, params, hidden_states, attention_mask=None):
        dt = hidden_states.dtype
        proj = {}
        for name in ("query", "key", "value"):
            p = params[name]
            proj[name] = self._split_heads(
                jnp.dot(hidden_states, p["w"].astype(dt),
                        preferred_element_type=jnp.float32).astype(dt) + p["b"].astype(dt))
        ctx = self.sparse_self_attention(proj["query"], proj["key"], proj["value"],
                                         key_padding_mask=attention_mask)
        B, H, T, D = ctx.shape
        return ctx.transpose(0, 2, 1, 3).reshape(B, T, H * D)
