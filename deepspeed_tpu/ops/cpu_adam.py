"""DeepSpeedCPUAdam: host-memory Adam for ZeRO-Offload.

TPU-native re-design of ``deepspeed/ops/adam/cpu_adam.py`` (DeepSpeedCPUAdam l.8) over
the native kernel in ``deepspeed_tpu/csrc/cpu_adam.cpp`` (analog of
``csrc/adam/cpu_adam.cpp``). The fp32 master weights and both Adam moments live in host
DRAM as one contiguous flat buffer each (the reference keeps them in pinned host memory,
stage2.py:333-349); ``step`` runs the OpenMP+SIMD native kernel in place, and
``step_and_cast_bf16`` fuses the fp32 -> bf16 conversion of the updated parameters into
the same pass — the analog of ``adam_update_copy`` fusing the fp16 device copy
(cpu_adam.py:69, cpu_adam.cpp:592).

If the native toolchain is unavailable the same math runs as vectorized numpy
(~3-10x slower but bit-compatible modulo fma ordering).
"""

from typing import Optional

import numpy as np

try:  # bf16 numpy dtype (ships with jax)
    import ml_dtypes
    _BF16 = ml_dtypes.bfloat16
except ImportError:  # pragma: no cover
    _BF16 = None

import jax

from .native import load_cpu_adam


def _ptr(arr, ctype=None):
    import ctypes
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float if ctype is None else ctype))


class DeepSpeedCPUAdam:
    """Adam over a flat host-resident fp32 parameter buffer with pytree views.

    Usage::

        opt = DeepSpeedCPUAdam(params_tree)          # copies params to host fp32
        opt.step(grads_flat, step=1, lr=1e-3, ...)   # in-place master update
        tree = opt.params_tree()                     # fp32 numpy views, zero-copy
    """

    def __init__(self, params_tree, adamw: bool = True, bias_correction: bool = True):
        leaves, self._treedef = jax.tree_util.tree_flatten(params_tree)
        host = [np.asarray(jax.device_get(l), dtype=np.float32) for l in leaves]
        self._shapes = [h.shape for h in host]
        self._sizes = [h.size for h in host]
        self._offsets = np.cumsum([0] + self._sizes)
        self.numel = int(self._offsets[-1])
        self.fp32 = np.ascontiguousarray(np.concatenate([h.reshape(-1) for h in host])
                                         if host else np.zeros(0, np.float32))
        self.exp_avg = np.zeros(self.numel, np.float32)
        self.exp_avg_sq = np.zeros(self.numel, np.float32)
        self._bf16 = None  # staging buffer (2 B/param), allocated on first bf16 step
        self._fp16 = None  # staging buffer for the fp16 compute-dtype path
        self._grad_buf = np.empty(self.numel, np.float32)  # D2H landing buffer
        self.adamw = adamw
        self.bias_correction = bias_correction
        self._lib = load_cpu_adam()

    # ------------------------------------------------------------- tree views (zero-copy)
    def tree_of(self, flat):
        return jax.tree_util.tree_unflatten(
            self._treedef,
            [flat[self._offsets[i]:self._offsets[i + 1]].reshape(self._shapes[i])
             for i in range(len(self._sizes))])

    def params_tree(self):
        return self.tree_of(self.fp32)

    def exp_avg_tree(self):
        return self.tree_of(self.exp_avg)

    def exp_avg_sq_tree(self):
        return self.tree_of(self.exp_avg_sq)

    def flatten_grads(self, grads_tree) -> np.ndarray:
        # One batched D2H transfer for all leaves, copied into a persistent flat
        # buffer: avoids per-leaf blocking transfers and a fresh numel-sized
        # allocation every step (this D2H is the hot cost of the offload path).
        leaves = jax.device_get(jax.tree_util.tree_leaves(grads_tree))
        offset = 0
        for l in leaves:
            flat = np.asarray(l, np.float32).reshape(-1)
            self._grad_buf[offset:offset + flat.size] = flat
            offset += flat.size
        assert offset == self.numel
        return self._grad_buf

    # ------------------------------------------------------------- update
    def step(self, grads_flat: np.ndarray, step: int, lr: float, beta1: float = 0.9,
             beta2: float = 0.999, eps: float = 1e-8, weight_decay: float = 0.0):
        """One in-place Adam step over the flat master buffer."""
        assert grads_flat.size == self.numel
        grads_flat = np.ascontiguousarray(grads_flat, np.float32)
        if self._lib is not None:
            self._lib.ds_adam_step(_ptr(self.fp32), _ptr(grads_flat), _ptr(self.exp_avg),
                                   _ptr(self.exp_avg_sq), self.numel, int(step), float(lr),
                                   float(beta1), float(beta2), float(eps), float(weight_decay),
                                   int(self.adamw), int(self.bias_correction))
        else:
            self._numpy_step(grads_flat, step, lr, beta1, beta2, eps, weight_decay)

    def step_and_cast_bf16(self, grads_flat: np.ndarray, step: int, lr: float,
                           beta1: float = 0.9, beta2: float = 0.999, eps: float = 1e-8,
                           weight_decay: float = 0.0) -> np.ndarray:
        """Fused step + bf16 cast; returns the (numel,) bf16 staging buffer (a view)."""
        assert grads_flat.size == self.numel
        if _BF16 is None:  # jax depends on ml_dtypes, so this is effectively unreachable
            raise RuntimeError("bf16 offload push requires ml_dtypes")
        grads_flat = np.ascontiguousarray(grads_flat, np.float32)
        if self._lib is not None:
            import ctypes
            if self._bf16 is None:
                self._bf16 = np.empty(self.numel, np.uint16)
            self._lib.ds_adam_step_copy(_ptr(self.fp32), _ptr(grads_flat), _ptr(self.exp_avg),
                                        _ptr(self.exp_avg_sq),
                                        self._bf16.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
                                        self.numel, int(step), float(lr), float(beta1),
                                        float(beta2), float(eps), float(weight_decay),
                                        int(self.adamw), int(self.bias_correction))
            return self._bf16.view(_BF16)
        self._numpy_step(grads_flat, step, lr, beta1, beta2, eps, weight_decay)
        return self.fp32.astype(_BF16)

    def _numpy_step(self, g, step, lr, beta1, beta2, eps, weight_decay):
        bc1 = 1.0 - beta1 ** step if self.bias_correction else 1.0
        bc2 = 1.0 - beta2 ** step if self.bias_correction else 1.0
        m, v, p = self.exp_avg, self.exp_avg_sq, self.fp32
        if not self.adamw:
            # classic L2 Adam: decay enters the gradient before the moments
            g = g + weight_decay * p
        np.multiply(m, beta1, out=m)
        m += (1.0 - beta1) * g
        np.multiply(v, beta2, out=v)
        v += (1.0 - beta2) * np.square(g)
        update = (m / bc1) / (np.sqrt(v / bc2) + eps)
        if self.adamw:
            p -= lr * update + lr * weight_decay * p
        else:
            p -= lr * update

    # ------------------------------------------------------------- checkpoint plumbing
    def load_flat(self, fp32: Optional[np.ndarray] = None, exp_avg: Optional[np.ndarray] = None,
                  exp_avg_sq: Optional[np.ndarray] = None):
        for dst, src in ((self.fp32, fp32), (self.exp_avg, exp_avg), (self.exp_avg_sq, exp_avg_sq)):
            if src is not None:
                np.copyto(dst, np.asarray(src, np.float32).reshape(-1))

    def cast_fp16(self) -> np.ndarray:
        """fp32 master → persistent fp16 staging buffer (no per-step allocation)."""
        if self._fp16 is None:
            self._fp16 = np.empty(self.numel, np.float16)
        np.copyto(self._fp16, self.fp32, casting="unsafe")
        return self._fp16

    def load_trees(self, master_tree=None, exp_avg_tree=None, exp_avg_sq_tree=None):
        def cat(tree):
            if tree is None:
                return None
            # one batched D2H for trees that still hold device arrays
            leaves = jax.device_get(jax.tree_util.tree_leaves(tree))
            return np.concatenate([np.asarray(l, np.float32).reshape(-1) for l in leaves])
        self.load_flat(cat(master_tree), cat(exp_avg_tree), cat(exp_avg_sq_tree))
