"""DeepSpeedCPUAdam: host-memory Adam for ZeRO-Offload.

TPU-native re-design of ``deepspeed/ops/adam/cpu_adam.py`` (DeepSpeedCPUAdam l.8) over
the native kernel in ``deepspeed_tpu/csrc/cpu_adam.cpp`` (analog of
``csrc/adam/cpu_adam.cpp``). The fp32 master weights and both Adam moments live in host
DRAM as one contiguous flat buffer each (the reference keeps them in pinned host memory,
stage2.py:333-349).

Partitioned (multi-rank) offload: when constructed with a ``shardings`` tree (the
engine's ZeRO master layout), the host buffers hold only the regions whose devices are
addressable from THIS process — the analog of the reference stepping each DP rank's own
``single_partition_of_fp32_groups`` (stage2.py:333-349, 750-907). Each distinct shard
index of a leaf is stored exactly once (replicated leaves are stepped once per host, not
once per device), so the per-host work and DRAM scale as 1/dp of the model under ZeRO-2.

Pipelined stepping (the reference's async D2H grad copies + ``ds_adam_step_plus_copy``
H2D param push, stage2.py:750-907, csrc/adam/custom_cuda_kernel.cu): ``begin_grad_fetch``
initiates ``copy_to_host_async`` on every local grad region up front — splitting regions
larger than the current element cap into fixed-width device-sliced chunks — and
``step_regions`` runs a K-deep software pipeline over the resulting work items:
a dedicated fetch worker lands chunk i+K into the flat grad buffer while the caller
thread runs host Adam on chunk i (loss-scale/clip factor fused in via ``grad_scale``)
and a dedicated push worker dispatches the H2D ``device_put`` of regions completed
earlier. numpy memcpy and the ctypes kernel release the GIL, so the three lanes
genuinely overlap and wall-clock ≈ max(Σfetch, Σadam, Σpush) instead of their sum.
The chunk cap is autotuned from the first step's measured fetch/Adam rates (about
50 ms of the slower lane per chunk) unless pinned via ``max_region_elements``, so a
single 400M-element region can no longer serialize the whole step.

If the native toolchain is unavailable the same math runs as vectorized numpy
(~3-10x slower but bit-compatible modulo fma ordering).
"""

import time
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional

import numpy as np

try:  # bf16 numpy dtype (ships with jax)
    import ml_dtypes
    _BF16 = ml_dtypes.bfloat16
except ImportError:  # pragma: no cover
    _BF16 = None

import jax

from ..runtime.zero.sharding import chunk_spans
from ..utils import logger
from .native import load_cpu_adam

#: pre-autotune pipeline chunk cap (elements): small enough that even the first
#: step of a 400M-element region pipelines, large enough to amortize dispatch
_DEFAULT_REGION_CAP = 8 << 20


def _ptr(arr, ctype=None):
    import ctypes
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float if ctype is None else ctype))


class _Region:
    """One distinct shard of one leaf: a host-buffer segment plus the devices holding it."""

    __slots__ = ("leaf", "slices", "shape", "size", "offset", "devices")

    def __init__(self, leaf, slices, shape, size, offset, devices):
        self.leaf = leaf          # leaf index in tree_flatten order
        self.slices = slices      # tuple of python slices into the full leaf
        self.shape = shape        # region shape
        self.size = size          # region element count
        self.offset = offset      # start offset in the flat host buffers
        self.devices = devices    # addressable devices holding this shard (None -> host-only)


def _normalize_index(idx, shape):
    """Sharding index (tuple of slices) -> ((start, stop), ...) covering every dim."""
    out = []
    for s, d in zip(idx, shape):
        start, stop, step = s.indices(d)
        assert step == 1, "strided shardings are not supported by the offload tier"
        out.append((start, stop))
    # shardings may omit trailing dims
    for d in shape[len(idx):]:
        out.append((0, d))
    return tuple(out)


class _LazyFuture:
    """Future-alike that runs its work on the caller thread at first ``result()``."""

    __slots__ = ("_fn", "_args", "_done", "_result", "_exc")

    def __init__(self, fn, args):
        self._fn, self._args = fn, args
        self._done = False
        self._result = self._exc = None

    def result(self, timeout=None):
        if not self._done:
            try:
                self._result = self._fn(*self._args)
            except BaseException as e:  # re-raised on every result() like a real Future
                self._exc = e
            self._done = True
        if self._exc is not None:
            raise self._exc
        return self._result


class SerialTransferExecutor:
    """Non-overlapped transfer execution: every fetch/push runs inline on the caller
    thread when its future is first waited on, reproducing the legacy serial step —
    wall-clock ≈ Σfetch + Σadam + Σpush. Used when the pipeline is disabled and as
    the reference path for bit-equality tests."""

    pipelined = False

    def submit_fetch(self, fn, *args):
        return _LazyFuture(fn, args)

    def submit_push(self, fn, *args):
        return _LazyFuture(fn, args)

    def shutdown(self):
        pass


class PipelinedTransferExecutor:
    """Dedicated single-worker fetch and push lanes — the TPU analog of the reference's
    separate D2H/H2D CUDA streams (stage2.py:750-907). numpy memcpy, ``jax.device_put``
    staging, and the ctypes Adam kernel all release the GIL, so fetch(i+K) / adam(i) /
    push(i-1) genuinely overlap across the three threads."""

    pipelined = True

    def __init__(self):
        self._fetch = ThreadPoolExecutor(1, thread_name_prefix="offload-fetch")
        self._push = ThreadPoolExecutor(1, thread_name_prefix="offload-push")

    def submit_fetch(self, fn, *args):
        return self._fetch.submit(fn, *args)

    def submit_push(self, fn, *args):
        return self._push.submit(fn, *args)

    def shutdown(self):
        self._fetch.shutdown(wait=False)
        self._push.shutdown(wait=False)


class DeepSpeedCPUAdam:
    """Adam over flat host-resident fp32 buffers with pytree views.

    Usage (whole-tree mode, ``shardings=None``)::

        opt = DeepSpeedCPUAdam(params_tree)          # copies params to host fp32
        opt.step(opt.flatten_grads(g), step=1, lr=1e-3)
        tree = opt.params_tree()                     # fp32 numpy leaves

    Engine mode passes ``shardings`` (the ZeRO master layout) and uses
    ``begin_grad_fetch`` + ``step_regions`` for the partitioned, pipelined step.

    Pipeline knobs (config block ``zero_optimization.offload_optimizer``):
    ``pipeline`` toggles the threaded fetch/push lanes (off -> legacy serial walk),
    ``pipeline_depth`` is K, the number of work items kept in flight ahead of the
    host Adam, and ``max_region_elements`` caps the per-chunk element count
    ("auto" -> autotuned after the first step from the measured fetch/Adam rates).
    Tests may inject a custom executor via the ``transfer_executor`` attribute
    (anything with ``submit_fetch``/``submit_push`` returning futures and a
    ``pipelined`` flag).
    """

    def __init__(self, params_tree, adamw: bool = True, bias_correction: bool = True,
                 shardings=None, pipeline: bool = True, pipeline_depth: int = 2,
                 max_region_elements="auto"):
        leaves, self._treedef = jax.tree_util.tree_flatten(params_tree)
        shard_leaves = (jax.tree_util.tree_leaves(shardings) if shardings is not None
                        else [None] * len(leaves))
        assert len(shard_leaves) == len(leaves), "shardings tree must mirror the param tree"
        host = [np.asarray(jax.device_get(l), dtype=np.float32) for l in leaves]
        self._shapes = [h.shape for h in host]
        self._shardings = shard_leaves

        # ---- region table: each distinct local shard of each leaf, in deterministic order
        self._regions: List[_Region] = []
        self._leaf_regions: List[List[_Region]] = []
        offset = 0
        for li, (h, sh) in enumerate(zip(host, shard_leaves)):
            regions = []
            if sh is None:
                r = _Region(li, tuple(slice(0, d) for d in h.shape), h.shape, h.size,
                            offset, None)
                offset += h.size
                regions.append(r)
            else:
                dmap = sh.addressable_devices_indices_map(tuple(h.shape))
                groups = {}
                for dev, idx in dmap.items():
                    key = _normalize_index(idx if idx is not None else (), h.shape)
                    groups.setdefault(key, []).append(dev)
                for key in sorted(groups):
                    slices = tuple(slice(a, b) for a, b in key)
                    shape = tuple(b - a for a, b in key)
                    size = int(np.prod(shape)) if shape else 1
                    devices = sorted(groups[key], key=lambda d: d.id)
                    regions.append(_Region(li, slices, shape, size, offset, devices))
                    offset += size
            self._leaf_regions.append(regions)
            self._regions.extend(regions)
        self.numel = offset  # local partition element count

        # leaf is a zero-copy view of the flat buffer iff its regions tile it
        # contiguously in row-major order (single full region, or axis-0 blocks in order)
        self._leaf_viewable = []
        for li, regions in enumerate(self._leaf_regions):
            shape = self._shapes[li]
            if not shape:  # scalar leaf: single one-element region
                self._leaf_viewable.append(True)
                continue
            ok = True
            expect_row = 0
            for r in regions:  # sorted by start offsets at construction
                if any(sl.start != 0 or sl.stop != d
                       for sl, d in zip(r.slices[1:], shape[1:])):
                    ok = False  # not a full block over the trailing dims
                    break
                if r.slices[0].start != expect_row:
                    ok = False
                    break
                expect_row = r.slices[0].stop
            self._leaf_viewable.append(bool(ok and expect_row == shape[0]))

        # ---- flat host buffers over the local partition
        self.fp32 = np.empty(self.numel, np.float32)
        for r in self._regions:
            self.fp32[r.offset:r.offset + r.size] = host[r.leaf][r.slices].reshape(-1)
        self.exp_avg = np.zeros(self.numel, np.float32)
        self.exp_avg_sq = np.zeros(self.numel, np.float32)
        self._grad_buf = np.empty(self.numel, np.float32)  # D2H landing buffer
        self._bf16 = None  # staging buffer for the bf16 path (flat mode)
        self.adamw = adamw
        self.bias_correction = bias_correction
        self._lib = load_cpu_adam()
        # aggregate + per-region breakdown; see step_regions for the full schema
        self.last_step_timing = None
        self.last_push_elements = 0   # elements crossing the host->device link last step
        self._warned_fallback = False

        # ---- pipeline configuration
        self.pipeline = bool(pipeline)
        self.pipeline_depth = max(1, int(pipeline_depth))
        if max_region_elements in (None, 0, "auto"):
            self._cap_fixed = None
        else:
            cap = int(max_region_elements)
            if cap <= 0:
                raise ValueError(
                    f"offload_optimizer.max_region_elements must be 'auto' or a positive "
                    f"integer, got {max_region_elements!r}")
            self._cap_fixed = cap
        self._auto_cap = _DEFAULT_REGION_CAP
        self._autotuned = False
        self.transfer_executor = None  # injectable; None -> built from `pipeline`
        self._default_ex = None
        self._slicers = {}  # cap -> jitted fixed-width device slicer

    # ------------------------------------------------------------- pipeline plumbing
    def _get_executor(self):
        if self.transfer_executor is not None:
            return self.transfer_executor
        if self._default_ex is None:
            self._default_ex = (PipelinedTransferExecutor() if self.pipeline
                                else SerialTransferExecutor())
        return self._default_ex

    def region_cap(self) -> Optional[int]:
        """Current per-chunk element cap, or None when stepping serially (unsplit)."""
        if not getattr(self._get_executor(), "pipelined", False):
            return None
        return self._cap_fixed if self._cap_fixed is not None else self._auto_cap

    def _chunk_slicer(self, cap):
        """Jitted fixed-width flat slice: one compiled program per (leaf shape, cap) —
        the dynamic start index keeps every chunk of a region on the same executable."""
        fn = self._slicers.get(cap)
        if fn is None:
            from jax import lax
            fn = jax.jit(lambda x, start: lax.dynamic_slice_in_dim(
                x.reshape(-1), start, cap))
            self._slicers[cap] = fn
        return fn

    def close(self):
        if self._default_ex is not None:
            self._default_ex.shutdown()
            self._default_ex = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    # ------------------------------------------------------------- tree views
    def _assemble(self, flat):
        """Leaves from the flat buffer: zero-copy views where the layout allows, else
        copies. Raises if this process doesn't hold every region of some leaf."""
        out = []
        for li, regions in enumerate(self._leaf_regions):
            shape = self._shapes[li]
            covered = sum(r.size for r in regions)
            if covered != int(np.prod(shape) if shape else 1):
                raise ValueError(
                    "host offload partition does not cover the full parameter tree on "
                    "this process (multi-host run); full-tree assembly is unavailable")
            if self._leaf_viewable[li]:
                start = regions[0].offset
                out.append(flat[start:start + covered].reshape(shape))
            else:
                arr = np.empty(shape, flat.dtype)
                for r in regions:
                    arr[r.slices] = flat[r.offset:r.offset + r.size].reshape(r.shape)
                out.append(arr)
        return jax.tree_util.tree_unflatten(self._treedef, out)

    def params_tree(self):
        return self._assemble(self.fp32)

    def exp_avg_tree(self):
        return self._assemble(self.exp_avg)

    def exp_avg_sq_tree(self):
        return self._assemble(self.exp_avg_sq)

    def flatten_grads(self, grads_tree) -> np.ndarray:
        """Synchronous whole-tree D2H into the persistent flat grad buffer."""
        leaves = jax.device_get(jax.tree_util.tree_leaves(grads_tree))
        for li, regions in enumerate(self._leaf_regions):
            g = np.asarray(leaves[li], np.float32)
            for r in regions:
                self._grad_buf[r.offset:r.offset + r.size] = g[r.slices].reshape(-1)
        return self._grad_buf

    # ------------------------------------------------------------- flat-buffer update
    def _kernel_step(self, lo: int, hi: int, grads_flat, step, lr, beta1, beta2, eps,
                     weight_decay, grad_scale=1.0, out_bf16=None):
        """One Adam step over buffer range [lo, hi) (native kernel or numpy)."""
        n = hi - lo
        if n <= 0:
            return
        if self._lib is not None:
            p = self.fp32[lo:hi]
            g = grads_flat[lo:hi] if grads_flat.size != n else grads_flat
            m = self.exp_avg[lo:hi]
            v = self.exp_avg_sq[lo:hi]
            if out_bf16 is not None:
                import ctypes
                self._lib.ds_adam_step_copy(
                    _ptr(p), _ptr(g), _ptr(m), _ptr(v),
                    out_bf16.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
                    n, int(step), float(lr), float(beta1), float(beta2), float(eps),
                    float(weight_decay), float(grad_scale), int(self.adamw),
                    int(self.bias_correction))
            else:
                self._lib.ds_adam_step(_ptr(p), _ptr(g), _ptr(m), _ptr(v), n, int(step),
                                       float(lr), float(beta1), float(beta2), float(eps),
                                       float(weight_decay), float(grad_scale),
                                       int(self.adamw), int(self.bias_correction))
        else:
            g = grads_flat[lo:hi] if grads_flat.size != n else grads_flat
            self._numpy_step(lo, hi, g, step, lr, beta1, beta2, eps, weight_decay,
                             grad_scale)
            if out_bf16 is not None:
                np.copyto(out_bf16.view(_BF16), self.fp32[lo:hi], casting="unsafe")

    def step(self, grads_flat: np.ndarray, step: int, lr: float, beta1: float = 0.9,
             beta2: float = 0.999, eps: float = 1e-8, weight_decay: float = 0.0,
             grad_scale: float = 1.0):
        """One in-place Adam step over the whole flat master buffer."""
        assert grads_flat.size == self.numel
        grads_flat = np.ascontiguousarray(grads_flat, np.float32)
        self._kernel_step(0, self.numel, grads_flat, step, lr, beta1, beta2, eps,
                          weight_decay, grad_scale)

    def step_and_cast_bf16(self, grads_flat: np.ndarray, step: int, lr: float,
                           beta1: float = 0.9, beta2: float = 0.999, eps: float = 1e-8,
                           weight_decay: float = 0.0, grad_scale: float = 1.0) -> np.ndarray:
        """Fused step + bf16 cast; returns the (numel,) bf16 staging buffer (a view)."""
        assert grads_flat.size == self.numel
        if _BF16 is None:  # jax depends on ml_dtypes, so this is effectively unreachable
            raise RuntimeError("bf16 offload push requires ml_dtypes")
        grads_flat = np.ascontiguousarray(grads_flat, np.float32)
        if self._bf16 is None:
            self._bf16 = np.empty(self.numel, np.uint16)
        self._kernel_step(0, self.numel, grads_flat, step, lr, beta1, beta2, eps,
                          weight_decay, grad_scale, out_bf16=self._bf16)
        return self._bf16.view(_BF16)

    def _numpy_step(self, lo, hi, g, step, lr, beta1, beta2, eps, weight_decay,
                    grad_scale=1.0):
        bc1 = 1.0 - beta1 ** step if self.bias_correction else 1.0
        bc2 = 1.0 - beta2 ** step if self.bias_correction else 1.0
        m, v, p = self.exp_avg[lo:hi], self.exp_avg_sq[lo:hi], self.fp32[lo:hi]
        g = np.asarray(g, np.float32)
        if grad_scale != 1.0:
            g = g * grad_scale
        if not self.adamw:
            # classic L2 Adam: decay enters the gradient before the moments
            g = g + weight_decay * p
        np.multiply(m, beta1, out=m)
        m += (1.0 - beta1) * g
        np.multiply(v, beta2, out=v)
        v += (1.0 - beta2) * np.square(g)
        update = (m / bc1) / (np.sqrt(v / bc2) + eps)
        if self.adamw:
            p -= lr * update + lr * weight_decay * p
        else:
            p -= lr * update

    # ------------------------------------------------------------- pipelined engine path
    def begin_grad_fetch(self, grads_tree):
        """Initiate async D2H of every local grad region; returns opaque work items for
        ``step_regions``. Transfers overlap whatever runs next (device compute, the
        norm/overflow stats jit, earlier items' host Adam).

        Regions larger than the current chunk cap are split into fixed-width
        device-sliced chunks, each with its own async copy, so the host Adam of a big
        region starts as soon as its first chunk lands instead of after the whole
        region. Work items are ``(kind, data, region, rel_lo, rel_hi, win)`` with
        [rel_lo, rel_hi) the covered flat sub-range of the region and ``win`` the
        start of the fetch window that carries it (see ``chunk_spans``)."""
        cap = self.region_cap()
        gleaves = jax.tree_util.tree_leaves(grads_tree)
        handles = []
        for li, regions in enumerate(self._leaf_regions):
            g = gleaves[li]
            shard_by_dev = None
            if isinstance(g, jax.Array) and regions[0].devices is not None:
                shard_by_dev = {s.device: s for s in g.addressable_shards}
            leaf_shape = self._shapes[li]
            for r in regions:
                if shard_by_dev is not None:
                    s = shard_by_dev.get(r.devices[0])
                    # index match, not just shape: a same-shaped shard of a DIFFERENT
                    # slice (grads sharded on another axis) must take the assembly path
                    if s is not None and _normalize_index(
                            s.index if s.index is not None else (), leaf_shape) == \
                            tuple((sl.start, sl.stop) for sl in r.slices):
                        if cap is not None and r.size > cap:
                            slicer = self._chunk_slicer(cap)
                            for lo, hi, win in chunk_spans(r.size, cap):
                                c = slicer(s.data, win)
                                c.copy_to_host_async()
                                handles.append(("shard_chunk", c, r, lo, hi, win))
                        else:
                            s.data.copy_to_host_async()
                            handles.append(("shard", s.data, r, 0, r.size, 0))
                        continue
                # Layout mismatch (e.g. XLA-chosen grad layouts under cpu-checkpointing):
                # reassemble the region from the ADDRESSABLE shards only. Never
                # device_get the whole leaf — on a multi-host run a cross-process
                # sharded leaf is not fully addressable and that would crash the step.
                if isinstance(g, jax.Array):
                    if not self._warned_fallback:
                        logger.warning(
                            "[deepspeed_tpu] offload grad fetch: device grad layout does "
                            "not match the master region layout; assembling regions from "
                            "addressable shards (slower, per-shard D2H). First leaf "
                            f"index: {li}")
                        self._warned_fallback = True
                    for s in g.addressable_shards:
                        s.data.copy_to_host_async()
                    handles.append(("region_shards", g, r, 0, r.size, 0))
                else:
                    for lo, hi, _ in chunk_spans(r.size, cap):
                        handles.append(("leaf", g, r, lo, hi, lo))
        return handles

    def _region_from_addressable(self, g, r) -> np.ndarray:
        """Assemble one master region from a jax.Array's addressable shards (the
        grad layout doesn't tile the region). Raises when the local shards cannot
        cover the region — e.g. a cross-process sharded leaf on a multi-host run."""
        shape = self._shapes[r.leaf]
        out = np.empty(r.shape, np.float32)
        region_box = [(sl.start, sl.stop) for sl in r.slices]
        covered = 0
        seen = set()  # distinct shard boxes only: replicated shards must not double-count
        for s in g.addressable_shards:
            box = _normalize_index(s.index if s.index is not None else (), shape)
            if box in seen:
                continue
            inter = []
            for (a0, a1), (b0, b1) in zip(region_box, box):
                lo, hi = max(a0, b0), min(a1, b1)
                if lo >= hi:
                    inter = None
                    break
                inter.append((lo, hi))
            if inter is None:
                continue
            seen.add(box)
            block = np.asarray(s.data)  # waits for this shard's async copy
            src = tuple(slice(lo - b0, hi - b0) for (lo, hi), (b0, _) in zip(inter, box))
            dst = tuple(slice(lo - a0, hi - a0) for (lo, hi), (a0, _) in zip(inter, region_box))
            out[dst] = np.asarray(block[src], np.float32)
            covered += int(np.prod([hi - lo for lo, hi in inter]))
        if covered < r.size:
            raise ValueError(
                f"offload grad leaf {r.leaf} (shape {shape}): region {region_box} is not "
                f"fully addressable from process {jax.process_index()} ({covered}/{r.size} "
                "elements) — the grad sharding does not match the master layout on a "
                "multi-host run; give the grads the engine's master/grad shardings")
        return out

    def _fetch_item(self, item, host_leaves):
        """Land one work item's grads into the flat buffer (fetch-lane work).
        Returns the busy seconds spent — the blocking D2H wait plus the memcpy."""
        kind, data, r, rel_lo, rel_hi, win = item
        t0 = time.perf_counter()
        # TraceAnnotation (not named_scope): this is host-thread work, invisible
        # to HLO — the annotation makes the fetch lane show up in profiler traces
        with jax.profiler.TraceAnnotation("ds_offload_fetch"):
            dst = self._grad_buf[r.offset + rel_lo:r.offset + rel_hi]
            if kind in ("shard", "shard_chunk"):
                h = np.asarray(data)  # blocks until this item's async copy lands
                np.copyto(dst, h.reshape(-1)[rel_lo - win:rel_hi - win], casting="unsafe")
            elif kind == "region_shards":
                np.copyto(dst, self._region_from_addressable(data, r).reshape(-1),
                          casting="unsafe")
            else:  # "leaf": host (or device_get-able) array, sliced region-relative
                if host_leaves[r.leaf] is None:
                    host_leaves[r.leaf] = np.asarray(jax.device_get(data), np.float32)
                np.copyto(dst, host_leaves[r.leaf][r.slices].reshape(-1)[rel_lo:rel_hi],
                          casting="unsafe")
        return time.perf_counter() - t0

    def _push_region(self, r, out_host):
        """Dispatch one completed region's H2D push (push-lane work). Returns
        ``(result, pushed_elems, busy_seconds)``; the result is merged into the
        global assembly on the caller thread."""
        t0 = time.perf_counter()
        pushed = 0
        with jax.profiler.TraceAnnotation("ds_offload_push"):
            return self._push_region_inner(r, out_host, pushed, t0)

    def _push_region_inner(self, r, out_host, pushed, t0):
        if r.devices is None:
            res = ("host", out_host)
        elif (len(r.devices) > 1 and len(self._leaf_regions[r.leaf]) == 1
              and len(self._shardings[r.leaf].device_set) == len(r.devices)):
            # A leaf ZeRO couldn't shard (replicated whole-leaf region), all of its
            # devices addressable here: push ONE copy over the host link and let a
            # jitted reshard broadcast it device-to-device (ICI) in step_regions —
            # host->device bytes stay proportional to the partition, not
            # x n_devices. (Multi-host replicated leaves keep per-device pushes:
            # a process-local single-device array cannot enter a cross-process jit.)
            res = ("repl", jax.device_put(out_host, r.devices[0]))
            pushed = r.size
        else:
            res = ("devs", {dev: jax.device_put(out_host, dev) for dev in r.devices})
            pushed = r.size * len(r.devices)
        return res, pushed, time.perf_counter() - t0

    def step_regions(self, handles, step: int, lr: float, beta1: float = 0.9,
                     beta2: float = 0.999, eps: float = 1e-8, weight_decay: float = 0.0,
                     grad_scale: float = 1.0, out_dtype=np.float32, leaf_hypers=None):
        """Partitioned, pipelined step: K work items in flight — the fetch lane lands
        chunk i+K while the caller thread runs Adam on chunk i and the push lane
        dispatches regions completed earlier. Returns the tree of GLOBAL jax arrays
        (one per leaf, carrying the construction sharding) in ``out_dtype``.

        ``leaf_hypers``: optional per-leaf {lr, beta1, beta2, eps, weight_decay} dicts
        (tree_flatten order) overriding the scalar args — the engine's per-group
        hyperparameters applied on the host tier.

        ``last_step_timing`` afterwards holds the aggregate lanes (``fetch_wait``
        caller-thread stall, ``host_adam``, ``push`` drain + global assembly,
        ``total``), the lane busy sums (``fetch_busy``, ``push_busy``) the overlap
        efficiency is computed from, the pipeline shape (``pipeline_depth``,
        ``region_cap``, ``n_work_items``), and ``regions`` — one
        {leaf, size, chunks, fetch_wait, fetch, adam, push} record per region."""
        out_np = np.dtype(out_dtype)
        use_fused_bf16 = (_BF16 is not None and out_np == np.dtype(_BF16))
        t0 = time.perf_counter()
        ex = self._get_executor()
        # Serial executors run fetches inline at result() time, so depth beyond 1 only
        # reorders identical work; pipelined lanes keep K items in flight.
        K = self.pipeline_depth if getattr(ex, "pipelined", False) else 1
        items = handles
        n = len(items)
        host_leaves = [None] * len(self._leaf_regions)
        remaining = {}  # region -> elements not yet stepped (push fires at zero)
        for it in items:
            remaining[it[2]] = remaining.get(it[2], 0) + (it[4] - it[3])
        staging = {}       # region -> flat compute-dtype output buffer
        region_order = []  # first-touch order, for the per-region timing records
        rec = {}
        t_fetch_wait = t_adam = 0.0
        fetch_busy = 0.0
        fetch_futs = [None] * n
        for j in range(min(K, n)):
            fetch_futs[j] = ex.submit_fetch(self._fetch_item, items[j], host_leaves)
        push_futs = []
        pieces = [dict() for _ in self._leaf_regions]  # leaf -> {device: jax.Array}
        repl_single = [None] * len(self._leaf_regions)  # whole-leaf replicated: 1 push/host
        for i, it in enumerate(items):
            kind, data, r, rel_lo, rel_hi, win = it
            t = time.perf_counter()
            busy = fetch_futs[i].result()
            fetch_futs[i] = None  # drop the chunk array as soon as it's consumed
            stall = time.perf_counter() - t
            if i + K < n:
                fetch_futs[i + K] = ex.submit_fetch(self._fetch_item, items[i + K],
                                                    host_leaves)
            rr = rec.get(r)
            if rr is None:
                region_order.append(r)
                rr = rec[r] = {"leaf": r.leaf, "size": r.size, "chunks": 0,
                               "fetch_wait": 0.0, "fetch": 0.0, "adam": 0.0, "push": 0.0}
            rr["chunks"] += 1
            rr["fetch_wait"] += stall
            rr["fetch"] += busy
            t_fetch_wait += stall
            fetch_busy += busy

            t = time.perf_counter()
            if leaf_hypers is not None:
                hy = leaf_hypers[r.leaf]
                r_lr, r_b1, r_b2 = hy["lr"], hy["beta1"], hy["beta2"]
                r_eps, r_wd = hy["eps"], hy["weight_decay"]
            else:
                r_lr, r_b1, r_b2, r_eps, r_wd = lr, beta1, beta2, eps, weight_decay
            lo, hi = r.offset + rel_lo, r.offset + rel_hi
            sbuf = staging.get(r)
            if sbuf is None:
                sbuf = staging[r] = np.empty(r.size,
                                             np.uint16 if use_fused_bf16 else out_np)
            with jax.profiler.TraceAnnotation("ds_offload_adam"):
                if use_fused_bf16:
                    self._kernel_step(lo, hi, self._grad_buf, step, r_lr, r_b1, r_b2,
                                      r_eps, r_wd, grad_scale,
                                      out_bf16=sbuf[rel_lo:rel_hi])
                else:
                    self._kernel_step(lo, hi, self._grad_buf, step, r_lr, r_b1, r_b2,
                                      r_eps, r_wd, grad_scale)
                    np.copyto(sbuf[rel_lo:rel_hi], self.fp32[lo:hi], casting="unsafe")
            dt = time.perf_counter() - t
            rr["adam"] += dt
            t_adam += dt

            remaining[r] -= rel_hi - rel_lo
            if remaining[r] == 0:  # region complete: hand the whole shard to the push lane
                out_host = (sbuf.view(_BF16) if use_fused_bf16 else sbuf).reshape(r.shape)
                push_futs.append((r, ex.submit_push(self._push_region, r, out_host)))

        t = time.perf_counter()
        pushed_elems = 0
        push_busy = 0.0
        for r, fut in push_futs:
            res, pushed, busy = fut.result()
            rec[r]["push"] = busy
            push_busy += busy
            pushed_elems += pushed
            tag, val = res
            if tag == "host":
                pieces[r.leaf][None] = val
            elif tag == "repl":
                repl_single[r.leaf] = val
            else:
                pieces[r.leaf].update(val)
        out = []
        reshard_idx = []
        for li, (shape, sh) in enumerate(zip(self._shapes, self._shardings)):
            if sh is None:
                out.append(pieces[li][None])
            elif repl_single[li] is not None:
                out.append(repl_single[li])  # placeholder; replaced by the reshard jit
                reshard_idx.append(li)
            else:
                dmap = sh.addressable_devices_indices_map(tuple(shape))
                arrs = [pieces[li][d] for d in dmap]
                out.append(jax.make_array_from_single_device_arrays(shape, sh, arrs))
        if reshard_idx:
            # device_put from a committed on-device array reshards device-to-device
            # (the broadcast rides ICI, not the host link)
            resharded = jax.device_put([out[li] for li in reshard_idx],
                                       [self._shardings[li] for li in reshard_idx])
            for li, arr in zip(reshard_idx, resharded):
                out[li] = arr
        t_push = time.perf_counter() - t  # drain stall + global assembly
        self.last_step_timing = {
            "fetch_wait": t_fetch_wait, "host_adam": t_adam, "push": t_push,
            "total": time.perf_counter() - t0,
            "fetch_busy": fetch_busy, "push_busy": push_busy,
            "pipeline_depth": K, "region_cap": self.region_cap() or 0,
            "n_work_items": n,
            "regions": [rec[r] for r in region_order],
        }
        self.last_push_elements = pushed_elems
        self._maybe_autotune_cap(ex, fetch_busy, t_adam)
        return jax.tree_util.tree_unflatten(self._treedef, out)

    def _maybe_autotune_cap(self, ex, fetch_busy: float, adam_busy: float):
        """Set the chunk cap from the first pipelined step's measured rates: about
        50 ms of the slower of the fetch/Adam lanes per chunk — deep enough that a
        hundreds-of-MB region pipelines, coarse enough to amortize per-chunk
        dispatch. A user-pinned ``max_region_elements`` disables this; the new cap
        takes effect at the next ``begin_grad_fetch``."""
        if (self._cap_fixed is not None or self._autotuned
                or not getattr(ex, "pipelined", False)
                or fetch_busy <= 0.0 or adam_busy <= 0.0 or self.numel <= 0):
            return
        slower_rate = self.numel / max(fetch_busy, adam_busy)
        cap = int(0.05 * slower_rate)
        self._auto_cap = max(1 << 20, min(cap, 64 << 20))
        self._autotuned = True

    # ------------------------------------------------------------- checkpoint plumbing
    def load_flat(self, fp32: Optional[np.ndarray] = None, exp_avg: Optional[np.ndarray] = None,
                  exp_avg_sq: Optional[np.ndarray] = None):
        for dst, src in ((self.fp32, fp32), (self.exp_avg, exp_avg), (self.exp_avg_sq, exp_avg_sq)):
            if src is not None:
                np.copyto(dst, np.asarray(src, np.float32).reshape(-1))

    def load_trees(self, master_tree=None, exp_avg_tree=None, exp_avg_sq_tree=None):
        """Scatter full trees into the local flat buffers (region-wise)."""
        for buf, tree in ((self.fp32, master_tree), (self.exp_avg, exp_avg_tree),
                          (self.exp_avg_sq, exp_avg_sq_tree)):
            if tree is None:
                continue
            leaves = jax.device_get(jax.tree_util.tree_leaves(tree))
            for li, regions in enumerate(self._leaf_regions):
                full = np.asarray(leaves[li], np.float32)
                for r in regions:
                    buf[r.offset:r.offset + r.size] = full[r.slices].reshape(-1)
