"""DeepSpeedCPUAdam: host-memory Adam for ZeRO-Offload.

TPU-native re-design of ``deepspeed/ops/adam/cpu_adam.py`` (DeepSpeedCPUAdam l.8) over
the native kernel in ``deepspeed_tpu/csrc/cpu_adam.cpp`` (analog of
``csrc/adam/cpu_adam.cpp``). The fp32 master weights and both Adam moments live in host
DRAM as one contiguous flat buffer each (the reference keeps them in pinned host memory,
stage2.py:333-349).

Partitioned (multi-rank) offload: when constructed with a ``shardings`` tree (the
engine's ZeRO master layout), the host buffers hold only the regions whose devices are
addressable from THIS process — the analog of the reference stepping each DP rank's own
``single_partition_of_fp32_groups`` (stage2.py:333-349, 750-907). Each distinct shard
index of a leaf is stored exactly once (replicated leaves are stepped once per host, not
once per device), so the per-host work and DRAM scale as 1/dp of the model under ZeRO-2.

Overlapped stepping (the reference's async D2H grad copies + ``ds_adam_step_plus_copy``
H2D param push, stage2.py:750-907, csrc/adam/custom_cuda_kernel.cu): ``begin_grad_fetch``
initiates ``copy_to_host_async`` on every local grad shard up front, then
``step_regions`` walks the regions in order — waiting only for that region's transfer,
stepping it with the native kernel (loss-scale/clip factor fused in via ``grad_scale``),
and immediately dispatching the async H2D ``device_put`` of the updated compute-dtype
slice. Transfers of later regions and device pushes of earlier ones proceed concurrently
with the host Adam of the current one, so wall-clock ≈ max(transfer, host-Adam) instead
of their sum.

If the native toolchain is unavailable the same math runs as vectorized numpy
(~3-10x slower but bit-compatible modulo fma ordering).
"""

import time
from typing import List, Optional

import numpy as np

try:  # bf16 numpy dtype (ships with jax)
    import ml_dtypes
    _BF16 = ml_dtypes.bfloat16
except ImportError:  # pragma: no cover
    _BF16 = None

import jax

from ..utils import logger
from .native import load_cpu_adam


def _ptr(arr, ctype=None):
    import ctypes
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float if ctype is None else ctype))


class _Region:
    """One distinct shard of one leaf: a host-buffer segment plus the devices holding it."""

    __slots__ = ("leaf", "slices", "shape", "size", "offset", "devices")

    def __init__(self, leaf, slices, shape, size, offset, devices):
        self.leaf = leaf          # leaf index in tree_flatten order
        self.slices = slices      # tuple of python slices into the full leaf
        self.shape = shape        # region shape
        self.size = size          # region element count
        self.offset = offset      # start offset in the flat host buffers
        self.devices = devices    # addressable devices holding this shard (None -> host-only)


def _normalize_index(idx, shape):
    """Sharding index (tuple of slices) -> ((start, stop), ...) covering every dim."""
    out = []
    for s, d in zip(idx, shape):
        start, stop, step = s.indices(d)
        assert step == 1, "strided shardings are not supported by the offload tier"
        out.append((start, stop))
    # shardings may omit trailing dims
    for d in shape[len(idx):]:
        out.append((0, d))
    return tuple(out)


class DeepSpeedCPUAdam:
    """Adam over flat host-resident fp32 buffers with pytree views.

    Usage (whole-tree mode, ``shardings=None``)::

        opt = DeepSpeedCPUAdam(params_tree)          # copies params to host fp32
        opt.step(opt.flatten_grads(g), step=1, lr=1e-3)
        tree = opt.params_tree()                     # fp32 numpy leaves

    Engine mode passes ``shardings`` (the ZeRO master layout) and uses
    ``begin_grad_fetch`` + ``step_regions`` for the partitioned, overlapped step.
    """

    def __init__(self, params_tree, adamw: bool = True, bias_correction: bool = True,
                 shardings=None):
        leaves, self._treedef = jax.tree_util.tree_flatten(params_tree)
        shard_leaves = (jax.tree_util.tree_leaves(shardings) if shardings is not None
                        else [None] * len(leaves))
        assert len(shard_leaves) == len(leaves), "shardings tree must mirror the param tree"
        host = [np.asarray(jax.device_get(l), dtype=np.float32) for l in leaves]
        self._shapes = [h.shape for h in host]
        self._shardings = shard_leaves

        # ---- region table: each distinct local shard of each leaf, in deterministic order
        self._regions: List[_Region] = []
        self._leaf_regions: List[List[_Region]] = []
        offset = 0
        for li, (h, sh) in enumerate(zip(host, shard_leaves)):
            regions = []
            if sh is None:
                r = _Region(li, tuple(slice(0, d) for d in h.shape), h.shape, h.size,
                            offset, None)
                offset += h.size
                regions.append(r)
            else:
                dmap = sh.addressable_devices_indices_map(tuple(h.shape))
                groups = {}
                for dev, idx in dmap.items():
                    key = _normalize_index(idx if idx is not None else (), h.shape)
                    groups.setdefault(key, []).append(dev)
                for key in sorted(groups):
                    slices = tuple(slice(a, b) for a, b in key)
                    shape = tuple(b - a for a, b in key)
                    size = int(np.prod(shape)) if shape else 1
                    devices = sorted(groups[key], key=lambda d: d.id)
                    regions.append(_Region(li, slices, shape, size, offset, devices))
                    offset += size
            self._leaf_regions.append(regions)
            self._regions.extend(regions)
        self.numel = offset  # local partition element count

        # leaf is a zero-copy view of the flat buffer iff its regions tile it
        # contiguously in row-major order (single full region, or axis-0 blocks in order)
        self._leaf_viewable = []
        for li, regions in enumerate(self._leaf_regions):
            shape = self._shapes[li]
            if not shape:  # scalar leaf: single one-element region
                self._leaf_viewable.append(True)
                continue
            ok = True
            expect_row = 0
            for r in regions:  # sorted by start offsets at construction
                if any(sl.start != 0 or sl.stop != d
                       for sl, d in zip(r.slices[1:], shape[1:])):
                    ok = False  # not a full block over the trailing dims
                    break
                if r.slices[0].start != expect_row:
                    ok = False
                    break
                expect_row = r.slices[0].stop
            self._leaf_viewable.append(bool(ok and expect_row == shape[0]))

        # ---- flat host buffers over the local partition
        self.fp32 = np.empty(self.numel, np.float32)
        for r in self._regions:
            self.fp32[r.offset:r.offset + r.size] = host[r.leaf][r.slices].reshape(-1)
        self.exp_avg = np.zeros(self.numel, np.float32)
        self.exp_avg_sq = np.zeros(self.numel, np.float32)
        self._grad_buf = np.empty(self.numel, np.float32)  # D2H landing buffer
        self._bf16 = None  # staging buffer for the bf16 path (flat mode)
        self.adamw = adamw
        self.bias_correction = bias_correction
        self._lib = load_cpu_adam()
        self.last_step_timing = None  # {"fetch_wait": s, "host_adam": s, "push": s, "total": s}
        self.last_push_elements = 0   # elements crossing the host->device link last step
        self._warned_fallback = False

    # ------------------------------------------------------------- tree views
    def _assemble(self, flat):
        """Leaves from the flat buffer: zero-copy views where the layout allows, else
        copies. Raises if this process doesn't hold every region of some leaf."""
        out = []
        for li, regions in enumerate(self._leaf_regions):
            shape = self._shapes[li]
            covered = sum(r.size for r in regions)
            if covered != int(np.prod(shape) if shape else 1):
                raise ValueError(
                    "host offload partition does not cover the full parameter tree on "
                    "this process (multi-host run); full-tree assembly is unavailable")
            if self._leaf_viewable[li]:
                start = regions[0].offset
                out.append(flat[start:start + covered].reshape(shape))
            else:
                arr = np.empty(shape, flat.dtype)
                for r in regions:
                    arr[r.slices] = flat[r.offset:r.offset + r.size].reshape(r.shape)
                out.append(arr)
        return jax.tree_util.tree_unflatten(self._treedef, out)

    def params_tree(self):
        return self._assemble(self.fp32)

    def exp_avg_tree(self):
        return self._assemble(self.exp_avg)

    def exp_avg_sq_tree(self):
        return self._assemble(self.exp_avg_sq)

    def flatten_grads(self, grads_tree) -> np.ndarray:
        """Synchronous whole-tree D2H into the persistent flat grad buffer."""
        leaves = jax.device_get(jax.tree_util.tree_leaves(grads_tree))
        for li, regions in enumerate(self._leaf_regions):
            g = np.asarray(leaves[li], np.float32)
            for r in regions:
                self._grad_buf[r.offset:r.offset + r.size] = g[r.slices].reshape(-1)
        return self._grad_buf

    # ------------------------------------------------------------- flat-buffer update
    def _kernel_step(self, lo: int, hi: int, grads_flat, step, lr, beta1, beta2, eps,
                     weight_decay, grad_scale=1.0, out_bf16=None):
        """One Adam step over buffer range [lo, hi) (native kernel or numpy)."""
        n = hi - lo
        if n <= 0:
            return
        if self._lib is not None:
            p = self.fp32[lo:hi]
            g = grads_flat[lo:hi] if grads_flat.size != n else grads_flat
            m = self.exp_avg[lo:hi]
            v = self.exp_avg_sq[lo:hi]
            if out_bf16 is not None:
                import ctypes
                self._lib.ds_adam_step_copy(
                    _ptr(p), _ptr(g), _ptr(m), _ptr(v),
                    out_bf16.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
                    n, int(step), float(lr), float(beta1), float(beta2), float(eps),
                    float(weight_decay), float(grad_scale), int(self.adamw),
                    int(self.bias_correction))
            else:
                self._lib.ds_adam_step(_ptr(p), _ptr(g), _ptr(m), _ptr(v), n, int(step),
                                       float(lr), float(beta1), float(beta2), float(eps),
                                       float(weight_decay), float(grad_scale),
                                       int(self.adamw), int(self.bias_correction))
        else:
            g = grads_flat[lo:hi] if grads_flat.size != n else grads_flat
            self._numpy_step(lo, hi, g, step, lr, beta1, beta2, eps, weight_decay,
                             grad_scale)
            if out_bf16 is not None:
                np.copyto(out_bf16.view(_BF16), self.fp32[lo:hi], casting="unsafe")

    def step(self, grads_flat: np.ndarray, step: int, lr: float, beta1: float = 0.9,
             beta2: float = 0.999, eps: float = 1e-8, weight_decay: float = 0.0,
             grad_scale: float = 1.0):
        """One in-place Adam step over the whole flat master buffer."""
        assert grads_flat.size == self.numel
        grads_flat = np.ascontiguousarray(grads_flat, np.float32)
        self._kernel_step(0, self.numel, grads_flat, step, lr, beta1, beta2, eps,
                          weight_decay, grad_scale)

    def step_and_cast_bf16(self, grads_flat: np.ndarray, step: int, lr: float,
                           beta1: float = 0.9, beta2: float = 0.999, eps: float = 1e-8,
                           weight_decay: float = 0.0, grad_scale: float = 1.0) -> np.ndarray:
        """Fused step + bf16 cast; returns the (numel,) bf16 staging buffer (a view)."""
        assert grads_flat.size == self.numel
        if _BF16 is None:  # jax depends on ml_dtypes, so this is effectively unreachable
            raise RuntimeError("bf16 offload push requires ml_dtypes")
        grads_flat = np.ascontiguousarray(grads_flat, np.float32)
        if self._bf16 is None:
            self._bf16 = np.empty(self.numel, np.uint16)
        self._kernel_step(0, self.numel, grads_flat, step, lr, beta1, beta2, eps,
                          weight_decay, grad_scale, out_bf16=self._bf16)
        return self._bf16.view(_BF16)

    def _numpy_step(self, lo, hi, g, step, lr, beta1, beta2, eps, weight_decay,
                    grad_scale=1.0):
        bc1 = 1.0 - beta1 ** step if self.bias_correction else 1.0
        bc2 = 1.0 - beta2 ** step if self.bias_correction else 1.0
        m, v, p = self.exp_avg[lo:hi], self.exp_avg_sq[lo:hi], self.fp32[lo:hi]
        g = np.asarray(g, np.float32)
        if grad_scale != 1.0:
            g = g * grad_scale
        if not self.adamw:
            # classic L2 Adam: decay enters the gradient before the moments
            g = g + weight_decay * p
        np.multiply(m, beta1, out=m)
        m += (1.0 - beta1) * g
        np.multiply(v, beta2, out=v)
        v += (1.0 - beta2) * np.square(g)
        update = (m / bc1) / (np.sqrt(v / bc2) + eps)
        if self.adamw:
            p -= lr * update + lr * weight_decay * p
        else:
            p -= lr * update

    # ------------------------------------------------------------- overlapped engine path
    def begin_grad_fetch(self, grads_tree):
        """Initiate async D2H of every local grad region; returns opaque handles for
        ``step_regions``. Transfers overlap whatever runs next (device compute, the
        norm/overflow stats jit, earlier regions' host Adam)."""
        gleaves = jax.tree_util.tree_leaves(grads_tree)
        handles = []
        for li, regions in enumerate(self._leaf_regions):
            g = gleaves[li]
            shard_by_dev = None
            if isinstance(g, jax.Array) and regions[0].devices is not None:
                shard_by_dev = {s.device: s for s in g.addressable_shards}
            leaf_shape = self._shapes[li]
            for r in regions:
                if shard_by_dev is not None:
                    s = shard_by_dev.get(r.devices[0])
                    # index match, not just shape: a same-shaped shard of a DIFFERENT
                    # slice (grads sharded on another axis) must take the assembly path
                    if s is not None and _normalize_index(
                            s.index if s.index is not None else (), leaf_shape) == \
                            tuple((sl.start, sl.stop) for sl in r.slices):
                        s.data.copy_to_host_async()
                        handles.append(("shard", s.data, r))
                        continue
                # Layout mismatch (e.g. XLA-chosen grad layouts under cpu-checkpointing):
                # reassemble the region from the ADDRESSABLE shards only. Never
                # device_get the whole leaf — on a multi-host run a cross-process
                # sharded leaf is not fully addressable and that would crash the step.
                if isinstance(g, jax.Array):
                    if not self._warned_fallback:
                        logger.warning(
                            "[deepspeed_tpu] offload grad fetch: device grad layout does "
                            "not match the master region layout; assembling regions from "
                            "addressable shards (slower, per-shard D2H). First leaf "
                            f"index: {li}")
                        self._warned_fallback = True
                    for s in g.addressable_shards:
                        s.data.copy_to_host_async()
                    handles.append(("region_shards", g, r))
                else:
                    handles.append(("leaf", g, r))
        return handles

    def _region_from_addressable(self, g, r) -> np.ndarray:
        """Assemble one master region from a jax.Array's addressable shards (the
        grad layout doesn't tile the region). Raises when the local shards cannot
        cover the region — e.g. a cross-process sharded leaf on a multi-host run."""
        shape = self._shapes[r.leaf]
        out = np.empty(r.shape, np.float32)
        region_box = [(sl.start, sl.stop) for sl in r.slices]
        covered = 0
        seen = set()  # distinct shard boxes only: replicated shards must not double-count
        for s in g.addressable_shards:
            box = _normalize_index(s.index if s.index is not None else (), shape)
            if box in seen:
                continue
            inter = []
            for (a0, a1), (b0, b1) in zip(region_box, box):
                lo, hi = max(a0, b0), min(a1, b1)
                if lo >= hi:
                    inter = None
                    break
                inter.append((lo, hi))
            if inter is None:
                continue
            seen.add(box)
            block = np.asarray(s.data)  # waits for this shard's async copy
            src = tuple(slice(lo - b0, hi - b0) for (lo, hi), (b0, _) in zip(inter, box))
            dst = tuple(slice(lo - a0, hi - a0) for (lo, hi), (a0, _) in zip(inter, region_box))
            out[dst] = np.asarray(block[src], np.float32)
            covered += int(np.prod([hi - lo for lo, hi in inter]))
        if covered < r.size:
            raise ValueError(
                f"offload grad leaf {r.leaf} (shape {shape}): region {region_box} is not "
                f"fully addressable from process {jax.process_index()} ({covered}/{r.size} "
                "elements) — the grad sharding does not match the master layout on a "
                "multi-host run; give the grads the engine's master/grad shardings")
        return out

    def step_regions(self, handles, step: int, lr: float, beta1: float = 0.9,
                     beta2: float = 0.999, eps: float = 1e-8, weight_decay: float = 0.0,
                     grad_scale: float = 1.0, out_dtype=np.float32, leaf_hypers=None):
        """Partitioned, overlapped step: wait-per-region D2H -> native Adam -> async H2D
        push of the updated compute-dtype slice. Returns the tree of GLOBAL jax arrays
        (one per leaf, carrying the construction sharding) in ``out_dtype``.

        ``leaf_hypers``: optional per-leaf {lr, beta1, beta2, eps, weight_decay} dicts
        (tree_flatten order) overriding the scalar args — the engine's per-group
        hyperparameters applied on the host tier."""
        out_np = np.dtype(out_dtype)
        use_fused_bf16 = (_BF16 is not None and out_np == np.dtype(_BF16))
        t_fetch = t_adam = t_push = 0.0
        t0 = time.perf_counter()
        pushed_elems = 0
        pieces = [dict() for _ in self._leaf_regions]  # leaf -> {device: jax.Array}
        repl_single = [None] * len(self._leaf_regions)  # whole-leaf replicated: 1 push/host
        host_leaves = [None] * len(self._leaf_regions)
        for kind, data, r in handles:
            t = time.perf_counter()
            if kind == "shard":
                h = np.asarray(data)  # blocks until this region's copy lands
            elif kind == "region_shards":
                h = self._region_from_addressable(data, r)
            else:
                if host_leaves[r.leaf] is None:
                    host_leaves[r.leaf] = np.asarray(jax.device_get(data), np.float32)
                h = host_leaves[r.leaf][r.slices]
            lo, hi = r.offset, r.offset + r.size
            self._grad_buf[lo:hi] = np.asarray(h, np.float32).reshape(-1)
            t_fetch += time.perf_counter() - t

            t = time.perf_counter()
            if leaf_hypers is not None:
                hy = leaf_hypers[r.leaf]
                r_lr, r_b1, r_b2 = hy["lr"], hy["beta1"], hy["beta2"]
                r_eps, r_wd = hy["eps"], hy["weight_decay"]
            else:
                r_lr, r_b1, r_b2, r_eps, r_wd = lr, beta1, beta2, eps, weight_decay
            if use_fused_bf16:
                out_seg = np.empty(r.size, np.uint16)
                self._kernel_step(lo, hi, self._grad_buf, step, r_lr, r_b1, r_b2, r_eps,
                                  r_wd, grad_scale, out_bf16=out_seg)
                out_host = out_seg.view(_BF16).reshape(r.shape)
            else:
                self._kernel_step(lo, hi, self._grad_buf, step, r_lr, r_b1, r_b2, r_eps,
                                  r_wd, grad_scale)
                out_host = self.fp32[lo:hi].astype(out_np).reshape(r.shape)
            t_adam += time.perf_counter() - t

            t = time.perf_counter()
            if r.devices is None:
                pieces[r.leaf][None] = out_host
            elif (len(r.devices) > 1 and len(self._leaf_regions[r.leaf]) == 1
                  and len(self._shardings[r.leaf].device_set) == len(r.devices)):
                # A leaf ZeRO couldn't shard (replicated whole-leaf region), all of its
                # devices addressable here: push ONE copy over the host link and let a
                # jitted reshard broadcast it device-to-device (ICI) below —
                # host->device bytes stay proportional to the partition, not
                # x n_devices. (Multi-host replicated leaves keep per-device pushes:
                # a process-local single-device array cannot enter a cross-process jit.)
                repl_single[r.leaf] = jax.device_put(out_host, r.devices[0])
                pushed_elems += r.size
            else:
                for dev in r.devices:
                    pieces[r.leaf][dev] = jax.device_put(out_host, dev)  # async H2D
                    pushed_elems += r.size
            t_push += time.perf_counter() - t

        t = time.perf_counter()
        out = []
        reshard_idx = []
        for li, (shape, sh) in enumerate(zip(self._shapes, self._shardings)):
            if sh is None:
                out.append(pieces[li][None])
            elif repl_single[li] is not None:
                out.append(repl_single[li])  # placeholder; replaced by the reshard jit
                reshard_idx.append(li)
            else:
                dmap = sh.addressable_devices_indices_map(tuple(shape))
                arrs = [pieces[li][d] for d in dmap]
                out.append(jax.make_array_from_single_device_arrays(shape, sh, arrs))
        if reshard_idx:
            # device_put from a committed on-device array reshards device-to-device
            # (the broadcast rides ICI, not the host link)
            resharded = jax.device_put([out[li] for li in reshard_idx],
                                       [self._shardings[li] for li in reshard_idx])
            for li, arr in zip(reshard_idx, resharded):
                out[li] = arr
        t_push += time.perf_counter() - t
        self.last_step_timing = {"fetch_wait": t_fetch, "host_adam": t_adam,
                                 "push": t_push, "total": time.perf_counter() - t0}
        self.last_push_elements = pushed_elems
        return jax.tree_util.tree_unflatten(self._treedef, out)

    # ------------------------------------------------------------- checkpoint plumbing
    def load_flat(self, fp32: Optional[np.ndarray] = None, exp_avg: Optional[np.ndarray] = None,
                  exp_avg_sq: Optional[np.ndarray] = None):
        for dst, src in ((self.fp32, fp32), (self.exp_avg, exp_avg), (self.exp_avg_sq, exp_avg_sq)):
            if src is not None:
                np.copyto(dst, np.asarray(src, np.float32).reshape(-1))

    def load_trees(self, master_tree=None, exp_avg_tree=None, exp_avg_sq_tree=None):
        """Scatter full trees into the local flat buffers (region-wise)."""
        for buf, tree in ((self.fp32, master_tree), (self.exp_avg, exp_avg_tree),
                          (self.exp_avg_sq, exp_avg_sq_tree)):
            if tree is None:
                continue
            leaves = jax.device_get(jax.tree_util.tree_leaves(tree))
            for li, regions in enumerate(self._leaf_regions):
                full = np.asarray(leaves[li], np.float32)
                for r in regions:
                    buf[r.offset:r.offset + r.size] = full[r.slices].reshape(-1)
