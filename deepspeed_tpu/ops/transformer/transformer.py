"""Fused transformer (BERT encoder) layer.

TPU-native equivalent of ``deepspeed/ops/transformer/transformer.py`` (N1:
DeepSpeedTransformerLayer l.419 over csrc/transformer/*, ~5.7k LoC of CUDA). The config
surface matches (``DeepSpeedTransformerConfig``, reference l.39-147); the execution model
is redesigned for XLA:

- GEMMs + bias + gelu + residual + layernorm fuse under jit — the hand-written
  ``gelu_kernels.cu`` / ``normalize_kernels.cu`` fusions are XLA's bread and butter, so
  only attention gets a hand kernel (``ops/pallas/flash_attention.py``), which also
  subsumes ``softmax_kernels.cu``'s fused scale+mask softmax.
- The memory knobs map to remat: ``normalize_invertible`` / ``gelu_checkpoint`` /
  ``attn_dropout_checkpoint`` → ``jax.checkpoint`` over the corresponding segment (the
  reference recomputes those activations in backward; jax.checkpoint expresses exactly
  that contract).
- Dropout uses stateless PRNG keys threaded per call (replaces the CUDA RNG state
  tracker + ``stochastic_mode``), so recompute-under-remat reproduces identical masks.

Layer contract: ``init(rng) -> params``; ``apply(params, hidden, attention_mask=None,
rng=None, deterministic=True) -> hidden`` with shapes [B, T, H].
"""

import json
import math
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name


class TransformerConfig:

    def __init__(self, batch_size=-1, max_seq_length=-1, hidden_size=-1, intermediate_size=-1,
                 heads=-1, attn_dropout_ratio=-1, hidden_dropout_ratio=-1,
                 num_hidden_layers=-1, initializer_range=-1):
        self.layer_id = -1
        self.batch_size = batch_size
        self.hidden_size = hidden_size
        self.intermediate_size = intermediate_size
        self.max_seq_length = max_seq_length
        self.heads = heads
        self.attn_dropout_ratio = attn_dropout_ratio
        self.hidden_dropout_ratio = hidden_dropout_ratio
        self.num_hidden_layers = num_hidden_layers
        self.initializer_range = initializer_range


class DeepSpeedTransformerConfig(TransformerConfig):
    """Config mirror of the reference (transformer.py:39). CUDA-only knobs are accepted;
    memory knobs become remat policies, ``fp16`` selects the compute dtype (bf16 default
    on TPU unless fp16 is explicitly requested)."""

    def __init__(self,
                 batch_size=-1,
                 max_seq_length=-1,
                 hidden_size=-1,
                 intermediate_size=-1,
                 heads=-1,
                 attn_dropout_ratio=-1,
                 hidden_dropout_ratio=-1,
                 num_hidden_layers=-1,
                 initializer_range=-1,
                 local_rank=-1,
                 seed=-1,
                 fp16=False,
                 bf16=True,
                 pre_layer_norm=True,
                 normalize_invertible=False,
                 gelu_checkpoint=False,
                 adjust_init_range=True,
                 attn_dropout_checkpoint=False,
                 stochastic_mode=False,
                 use_flash_attention=True):
        super().__init__(batch_size, max_seq_length, hidden_size,
                         (intermediate_size if intermediate_size > 0 else 4 * hidden_size),
                         heads, attn_dropout_ratio, hidden_dropout_ratio,
                         num_hidden_layers, initializer_range)
        self.fp16 = fp16
        self.bf16 = bf16
        self.pre_layer_norm = pre_layer_norm
        self.local_rank = local_rank
        self.seed = seed
        self.normalize_invertible = normalize_invertible
        self.gelu_checkpoint = gelu_checkpoint
        self.adjust_init_range = adjust_init_range
        self.test_gemm = False
        self.training = True
        self.is_grad_enabled = True
        self.attn_dropout_checkpoint = attn_dropout_checkpoint
        self.stochastic_mode = stochastic_mode
        self.use_flash_attention = use_flash_attention

    @property
    def compute_dtype(self):
        if self.fp16:
            return jnp.float16
        if self.bf16:
            return jnp.bfloat16
        return jnp.float32

    @classmethod
    def from_dict(cls, json_object):
        config = cls()
        for key, value in json_object.items():
            config.__dict__[key] = value
        return config

    @classmethod
    def from_json_file(cls, json_file):
        with open(json_file, "r", encoding="utf-8") as reader:
            return cls.from_dict(json.loads(reader.read()))


def _layer_norm(x, scale, bias, eps=1e-12):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    return (((xf - mean) * jax.lax.rsqrt(var + eps)) * scale + bias).astype(x.dtype)


def _dropout(x, rate, rng, deterministic):
    if deterministic or rate <= 0 or rng is None:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, 0).astype(x.dtype)


class DeepSpeedTransformerLayer:
    """One BERT encoder layer with the reference's parameter set (transformer.py:444-463):
    qkv (fused), attn out, attn LN, intermediate, output, output LN."""

    layer_id = 0

    def __init__(self, config: DeepSpeedTransformerConfig, initial_weights=None,
                 initial_biases=None):
        self.config = config
        self.config.layer_id = DeepSpeedTransformerLayer.layer_id
        DeepSpeedTransformerLayer.layer_id += 1
        self._initial_weights = initial_weights
        self._initial_biases = initial_biases

    # ---------------- parameters ----------------
    def init(self, rng, sample_input=None):
        c = self.config
        H, I = c.hidden_size, c.intermediate_size
        std = c.initializer_range if c.initializer_range > 0 else 0.02
        out_std = std / math.sqrt(2.0 * max(c.num_hidden_layers, 1)) if c.adjust_init_range else std
        ks = jax.random.split(rng, 4)
        params = {
            "attn_qkvw": jax.random.normal(ks[0], (H, 3 * H), jnp.float32) * std,
            "attn_qkvb": jnp.zeros((3 * H,), jnp.float32),
            "attn_ow": jax.random.normal(ks[1], (H, H), jnp.float32) * out_std,
            "attn_ob": jnp.zeros((H,), jnp.float32),
            "attn_nw": jnp.ones((H,), jnp.float32),
            "attn_nb": jnp.zeros((H,), jnp.float32),
            "inter_w": jax.random.normal(ks[2], (H, I), jnp.float32) * std,
            "inter_b": jnp.zeros((I,), jnp.float32),
            "output_w": jax.random.normal(ks[3], (I, H), jnp.float32) * out_std,
            "output_b": jnp.zeros((H,), jnp.float32),
            "norm_w": jnp.ones((H,), jnp.float32),
            "norm_b": jnp.zeros((H,), jnp.float32),
        }
        if self._initial_weights is not None:
            qkv = jnp.concatenate([jnp.asarray(w, jnp.float32).T for w in self._initial_weights[:3]],
                                  axis=1)
            params["attn_qkvw"] = qkv
            params["attn_ow"] = jnp.asarray(self._initial_weights[3], jnp.float32).T
            params["attn_nw"] = jnp.asarray(self._initial_weights[4], jnp.float32)
            params["inter_w"] = jnp.asarray(self._initial_weights[5], jnp.float32).T
            params["output_w"] = jnp.asarray(self._initial_weights[6], jnp.float32).T
            params["norm_w"] = jnp.asarray(self._initial_weights[7], jnp.float32)
        if self._initial_biases is not None:
            params["attn_qkvb"] = jnp.concatenate(
                [jnp.asarray(b, jnp.float32) for b in self._initial_biases[:3]])
            params["attn_ob"] = jnp.asarray(self._initial_biases[3], jnp.float32)
            params["attn_nb"] = jnp.asarray(self._initial_biases[4], jnp.float32)
            params["inter_b"] = jnp.asarray(self._initial_biases[5], jnp.float32)
            params["output_b"] = jnp.asarray(self._initial_biases[6], jnp.float32)
            params["norm_b"] = jnp.asarray(self._initial_biases[7], jnp.float32)
        return params

    def param_shapes(self):
        H, I = self.config.hidden_size, self.config.intermediate_size
        return [(H, 3 * H), (3 * H,), (H, H), (H,), (H,), (H,), (H, I), (I,), (I, H), (H,),
                (H,), (H,)]

    # ---------------- forward ----------------
    def _attention(self, params, x, attention_mask, rng, deterministic):
        c = self.config
        B, T, H = x.shape
        heads = c.heads
        d = H // heads
        dt = x.dtype
        # announce the fused-qkv dot to the flash remat policies (exact tag match
        # instead of the width-signature guess)
        x = checkpoint_name(x, "ds_dot:qkv")
        qkv = (jnp.dot(x, params["attn_qkvw"].astype(dt), preferred_element_type=jnp.float32)
               .astype(dt) + params["attn_qkvb"].astype(dt))
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, heads, d).transpose(0, 2, 1, 3)
        k = k.reshape(B, T, heads, d).transpose(0, 2, 1, 3)
        v = v.reshape(B, T, heads, d).transpose(0, 2, 1, 3)

        # flash handles the BERT-style additive key mask ([B,1,1,T] / [B,T]) and
        # train-mode attention dropout in-kernel; only a full [B,·,Tq,Tk] mask (rare:
        # per-query masking) falls back to the dense path.
        mask_ok = attention_mask is None or (
            (attention_mask.ndim == 2 and attention_mask.shape == (B, T)) or
            (attention_mask.ndim == 4 and attention_mask.shape[0] == B
             and attention_mask.shape[1] == 1 and attention_mask.shape[2] == 1
             and attention_mask.shape[3] == T))
        dropout_active = (not deterministic and c.attn_dropout_ratio > 0
                          and rng is not None)
        use_flash = c.use_flash_attention and mask_ok
        if use_flash:
            from ..pallas.flash_attention import flash_attention
            bias = None
            if attention_mask is not None:
                bias = attention_mask.astype(jnp.float32).reshape(B, 1, T)
            rate, seed = 0.0, None
            if dropout_active:
                rng, sub = jax.random.split(rng)
                seed = jax.random.randint(sub, (), 0, jnp.iinfo(jnp.int32).max,
                                          dtype=jnp.int32)
                rate = float(c.attn_dropout_ratio)
            ctx = flash_attention(q, k, v, False, bias=bias, dropout_rate=rate,
                                  dropout_seed=seed)
        else:
            scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                                preferred_element_type=jnp.float32) / math.sqrt(d)
            if attention_mask is not None:
                scores = scores + attention_mask.astype(jnp.float32)
            probs = jax.nn.softmax(scores, axis=-1)
            if rng is not None:
                rng, sub = jax.random.split(rng)
                probs = _dropout(probs.astype(dt), c.attn_dropout_ratio, sub, deterministic)
            ctx = jnp.einsum("bhqk,bhkd->bhqd", probs.astype(dt), v,
                             preferred_element_type=jnp.float32).astype(dt)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(B, T, H)
        # announce the square output projection (the 'dots+attn-lean' exclusion)
        ctx = checkpoint_name(ctx, "ds_dot:proj")
        out = (jnp.dot(ctx, params["attn_ow"].astype(dt), preferred_element_type=jnp.float32)
               .astype(dt) + params["attn_ob"].astype(dt))
        return out, rng

    def _ffn(self, params, x):
        dt = x.dtype
        h = (jnp.dot(x, params["inter_w"].astype(dt), preferred_element_type=jnp.float32)
             .astype(dt) + params["inter_b"].astype(dt))
        h = jax.nn.gelu(h, approximate=False)
        return (jnp.dot(h, params["output_w"].astype(dt), preferred_element_type=jnp.float32)
                .astype(dt) + params["output_b"].astype(dt))

    def apply(self, params, hidden_states, attention_mask=None, rng=None, deterministic=True):
        c = self.config
        x = hidden_states.astype(c.compute_dtype)

        def attn_segment(params, x, rng):
            if c.pre_layer_norm:
                normed = _layer_norm(x, params["attn_nw"], params["attn_nb"])
                attn, rng2 = self._attention(params, normed, attention_mask, rng, deterministic)
            else:
                attn, rng2 = self._attention(params, x, attention_mask, rng, deterministic)
            return attn, rng2

        if c.attn_dropout_checkpoint or c.normalize_invertible:
            attn_segment = jax.checkpoint(attn_segment, static_argnums=())
        attn_out, rng = attn_segment(params, x, rng)
        if rng is not None:
            rng, sub = jax.random.split(rng)
            attn_out = _dropout(attn_out, c.hidden_dropout_ratio, sub, deterministic)
        x = x + attn_out
        if not c.pre_layer_norm:
            x = _layer_norm(x, params["attn_nw"], params["attn_nb"])

        def ffn_segment(params, x):
            if c.pre_layer_norm:
                return self._ffn(params, _layer_norm(x, params["norm_w"], params["norm_b"]))
            return self._ffn(params, x)

        if c.gelu_checkpoint:
            ffn_segment = jax.checkpoint(ffn_segment)
        ffn_out = ffn_segment(params, x)
        if rng is not None:
            rng, sub = jax.random.split(rng)
            ffn_out = _dropout(ffn_out, c.hidden_dropout_ratio, sub, deterministic)
        x = x + ffn_out
        if not c.pre_layer_norm:
            x = _layer_norm(x, params["norm_w"], params["norm_b"])
        return x

    def __call__(self, params, hidden_states, **kw):
        return self.apply(params, hidden_states, **kw)
