"""1-bit Adam: error-compensated momentum compression for data-parallel training.

TPU-native re-design of ``deepspeed/runtime/fp16/onebit_adam.py`` (OnebitAdam l.18,
Compressed_Allreduce l.104-228, step l.229-374):

- **Warmup** (step < freeze_step): exact Adam-style moments over the mean gradient
  (the reference lets the engine allreduce grads; here the mean over the stacked worker
  axis is a GSPMD reduction over ``data``).
- **Frozen** (step >= freeze_step): each worker updates its momentum with its *local*
  gradient (onebit_adam.py:335-336), the momenta are averaged with the two-phase
  sign-compressed allreduce (int8 over ICI — see runtime/custom_collectives.py), and the
  variance term is frozen. The update is ``m / (sqrt(v) + eps) + wd * p`` with **no bias
  correction**, matching the reference update rule (onebit_adam.py:348-355).

Functional layout: the whole parameter tree is flattened into one fp32 vector (the
reference flattens per-param; one fused buffer is friendlier to the TPU's collective
granularity) padded so each of the dp server chunks is lane-aligned. State:

  exp_avg / exp_avg_sq : (n_pad,) replicated
  worker_error         : (dp, n_pad) sharded P(data, None) — row i lives on worker i
  server_error         : (dp, n_pad // dp) sharded P(data, None)

``apply`` expects **stacked unreduced gradients**: each leaf has a leading dp axis,
sharded over ``data``, produced by the engine's shard_map grad path. ZeRO stages >= 1 are
not supported (same as the reference, which pairs OnebitAdam with FP16_Optimizer only).
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.mesh import DATA_AXIS
from ..runtime.custom_collectives import compressed_allreduce, padded_size


class OneBitAdamState(NamedTuple):
    exp_avg: jnp.ndarray      # (n_pad,) fp32
    exp_avg_sq: jnp.ndarray   # (n_pad,) fp32
    worker_error: jnp.ndarray  # (dp, n_pad) fp32
    server_error: jnp.ndarray  # (dp, n_pad // dp) fp32


def _flatten_stacked(grads, dp: int):
    """Tree of (dp, *shape) leaves -> (dp, n) matrix plus the leaf restore recipe."""
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    sizes = [int(np.prod(l.shape[1:])) for l in leaves]
    flat = jnp.concatenate([l.reshape(dp, -1) for l in leaves], axis=1)
    return flat, (treedef, sizes, [l.shape[1:] for l in leaves])


def _flatten(tree):
    """Tree -> (n,) vector plus the leaf restore recipe (unstacked _flatten_stacked)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    sizes = [int(np.prod(l.shape)) for l in leaves]
    flat = jnp.concatenate([l.reshape(-1) for l in leaves])
    return flat, (treedef, sizes, [l.shape for l in leaves])


def _unflatten(vec, recipe):
    treedef, sizes, shapes = recipe
    offsets = np.cumsum([0] + sizes)
    leaves = [vec[offsets[i]:offsets[i + 1]].reshape(shapes[i]) for i in range(len(sizes))]
    return jax.tree_util.tree_unflatten(treedef, leaves)


class OneBitAdam:
    """(init, apply) optimizer pair with 1-bit compressed momentum averaging."""

    def __init__(self, freeze_step: int, dp_size: int, mesh: Mesh):
        assert mesh is not None, "OneBitAdam needs the device mesh for its compressed allreduce"
        self.freeze_step = int(freeze_step)
        self.dp_size = int(dp_size)
        self.mesh = mesh
        self._seg_ids = None   # per-leaf scale segments (built lazily from the param tree)
        self._seg_key = None   # (treedef, leaf shapes, n_pad) the cached map was built for

    def _segment_ids(self, master_params, n_pad: int):
        """Element -> parameter-leaf segment map: the reference compresses each tensor
        with its own scale (per-param state); the padded tail gets its own segment so
        its zeros never perturb a real tensor's RMS. Cached keyed on the tree structure
        and leaf shapes (not just n_pad): a differently-structured tree that happens to
        pad to the same length must not reuse a stale map."""
        leaves, treedef = jax.tree_util.tree_flatten(master_params)
        key = (treedef, tuple(l.shape for l in leaves), n_pad)
        if self._seg_ids is None or self._seg_key != key:
            sizes = [int(np.prod(s)) for s in key[1]]
            ids = np.repeat(np.arange(len(sizes), dtype=np.int32), sizes)
            if n_pad > ids.shape[0]:
                ids = np.concatenate([ids, np.full(n_pad - ids.shape[0], len(sizes),
                                                   np.int32)])
            self._seg_ids = ids
            self._seg_key = key
        return self._seg_ids

    # ---------------------------------------------------------------- state
    def init(self, master_params) -> OneBitAdamState:
        n = sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(master_params))
        n_pad = padded_size(n, self.dp_size)
        dp = self.dp_size
        return OneBitAdamState(
            exp_avg=jnp.zeros((n_pad,), jnp.float32),
            exp_avg_sq=jnp.zeros((n_pad,), jnp.float32),
            worker_error=jnp.zeros((dp, n_pad), jnp.float32),
            server_error=jnp.zeros((dp, n_pad // dp), jnp.float32))

    def state_shardings(self, mesh: Mesh):
        return OneBitAdamState(
            exp_avg=NamedSharding(mesh, P()),
            exp_avg_sq=NamedSharding(mesh, P()),
            worker_error=NamedSharding(mesh, P(DATA_AXIS, None)),
            server_error=NamedSharding(mesh, P(DATA_AXIS, None)))

    # ---------------------------------------------------------------- update
    def apply(self, grads, state: OneBitAdamState, master_params, step, hyper):
        """One optimizer step. ``grads`` leaves carry a leading stacked-worker dp axis."""
        dp = self.dp_size
        g_stacked, _ = _flatten_stacked(grads, dp)          # (dp, n)
        n = g_stacked.shape[1]
        n_pad = state.exp_avg.shape[0]
        if n_pad > n:
            g_stacked = jnp.pad(g_stacked, ((0, 0), (0, n_pad - n)))

        p_flat, p_recipe = _flatten(master_params)
        if n_pad > n:
            p_flat_pad = jnp.pad(p_flat, (0, n_pad - n))
        else:
            p_flat_pad = p_flat

        beta1, beta2 = hyper["beta1"], hyper["beta2"]
        m, v = state.exp_avg, state.exp_avg_sq
        frozen = step > self.freeze_step  # step is 1-based when called from the engine

        def warmup_branch(operand):
            m, v, g_stacked, we, se = operand
            g_mean = jnp.mean(g_stacked, axis=0)            # GSPMD fp32 allreduce over data
            new_m = beta1 * m + (1.0 - beta1) * g_mean
            new_v = beta2 * v + (1.0 - beta2) * jnp.square(g_mean)
            return new_m, new_v, we, se

        seg_ids = self._segment_ids(master_params, n_pad)

        def frozen_branch(operand):
            m, v, g_stacked, we, se = operand
            # Worker-local momentum update (onebit_adam.py:335-336), then 1-bit averaging
            # with per-tensor scales (reference compresses each param separately).
            m_local = beta1 * m[None, :] + (1.0 - beta1) * g_stacked
            new_m, new_we, new_se = compressed_allreduce(self.mesh, m_local, we, se,
                                                         seg_ids=seg_ids)
            return new_m, v, new_we, new_se

        m, v, we, se = jax.lax.cond(
            frozen, frozen_branch, warmup_branch,
            operand=(m, v, g_stacked, state.worker_error, state.server_error))

        update = m / (jnp.sqrt(v) + hyper["eps"]) + hyper["weight_decay"] * p_flat_pad
        new_p_flat = (p_flat_pad - hyper["lr"] * update)[:n]
        new_params = _unflatten(new_p_flat, p_recipe)
        return new_params, OneBitAdamState(m, v, we, se)

    # ---------------------------------------------------------------- elastic restore
    def elastic_adapt(self, loaded_flat: dict, template_flat: dict) -> dict:
        """Adapt a checkpointed state dict saved under a different DP world size.

        The moment vectors are truncated/zero-extended to the new lane-padded length
        (the padded tail never reaches parameters); the (dp, ...) error-feedback buffers
        are residuals, so on a topology change they reset to zero — costing one step of
        extra compression error, the same trade the reference makes when it lazily
        (re)allocates worker/server errors (onebit_adam.py:302-312).
        """
        out = {}
        for key, tmpl in template_flat.items():
            v = loaded_flat.get(key)
            tmpl_shape = tuple(tmpl.shape)
            if v is not None and tuple(v.shape) == tmpl_shape:
                out[key] = v
            elif v is not None and v.ndim == 1 and len(tmpl_shape) == 1:
                buf = np.zeros(tmpl_shape, np.float32)
                keep = min(v.size, int(tmpl_shape[0]))
                buf[:keep] = np.asarray(v)[:keep]
                out[key] = buf
            else:
                out[key] = np.zeros(tmpl_shape, np.float32)
        return out
