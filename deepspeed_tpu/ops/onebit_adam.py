"""1-bit Adam: error-compensated momentum compression for data-parallel training.

TPU-native re-design of ``deepspeed/runtime/fp16/onebit_adam.py`` (OnebitAdam l.18,
Compressed_Allreduce l.104-228, step l.229-374):

- **Warmup** (step < freeze_step): exact Adam-style moments over the mean gradient
  (the reference lets the engine allreduce grads; here the mean over the stacked worker
  axis is a GSPMD reduction over ``data``).
- **Frozen** (step >= freeze_step): each worker updates its momentum with its *local*
  gradient (onebit_adam.py:335-336), the momenta are averaged with the two-phase
  sign-compressed allreduce (int8 over ICI — see runtime/custom_collectives.py), and the
  variance term is frozen. The update is ``m / (sqrt(v) + eps) + wd * p`` with **no bias
  correction**, matching the reference update rule (onebit_adam.py:348-355).

Functional layout: the whole parameter tree is flattened into one fp32 vector (the
reference flattens per-param; one fused buffer is friendlier to the TPU's collective
granularity) padded so each of the dp server chunks is lane-aligned. State:

  exp_avg / exp_avg_sq : (n_pad,) replicated
  worker_error         : (dp, n_pad // slice_size) sharded P(data, None) — row i on worker i
  server_error         : (dp, n_pad // dp) sharded P(data, None)

With a hierarchical :class:`~..comm.topology.CommTopology` the frozen-phase momentum
averaging routes through the two-level ICI+DCN schedule (comm/hierarchical.py): the
worker residual then covers only the device's post-reduce-scatter ICI chunk. The flat
layout is the ``slice_size == 1`` special case, keeping the historical ``(dp, n_pad)``
worker shape.

``apply`` expects **stacked unreduced gradients**: each leaf has a leading dp axis,
sharded over ``data``, produced by the engine's shard_map grad path. ZeRO stages >= 1 are
not supported (same as the reference, which pairs OnebitAdam with FP16_Optimizer only).
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..comm.hierarchical import error_state_shapes, two_level_compressed_allreduce
from ..parallel.mesh import DATA_AXIS
from ..runtime.custom_collectives import compressed_allreduce, padded_size


class OneBitAdamState(NamedTuple):
    exp_avg: jnp.ndarray      # (n_pad,) fp32
    exp_avg_sq: jnp.ndarray   # (n_pad,) fp32
    worker_error: jnp.ndarray  # (dp, n_pad // slice_size) fp32
    server_error: jnp.ndarray  # (dp, n_pad // dp) fp32


def _flatten_stacked(grads, dp: int):
    """Tree of (dp, *shape) leaves -> (dp, n) matrix plus the leaf restore recipe."""
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    sizes = [int(np.prod(l.shape[1:])) for l in leaves]
    flat = jnp.concatenate([l.reshape(dp, -1) for l in leaves], axis=1)
    return flat, (treedef, sizes, [l.shape[1:] for l in leaves])


def _flatten(tree):
    """Tree -> (n,) vector plus the leaf restore recipe (unstacked _flatten_stacked)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    sizes = [int(np.prod(l.shape)) for l in leaves]
    flat = jnp.concatenate([l.reshape(-1) for l in leaves])
    return flat, (treedef, sizes, [l.shape for l in leaves])


def _unflatten(vec, recipe):
    treedef, sizes, shapes = recipe
    offsets = np.cumsum([0] + sizes)
    leaves = [vec[offsets[i]:offsets[i + 1]].reshape(shapes[i]) for i in range(len(sizes))]
    return jax.tree_util.tree_unflatten(treedef, leaves)


class OneBitAdam:
    """(init, apply) optimizer pair with 1-bit compressed momentum averaging."""

    def __init__(self, freeze_step: int, dp_size: int, mesh: Mesh, topology=None):
        assert mesh is not None, "OneBitAdam needs the device mesh for its compressed allreduce"
        self.freeze_step = int(freeze_step)
        self.dp_size = int(dp_size)
        self.mesh = mesh
        # Hierarchical CommTopology routes frozen-phase momentum averaging over the
        # two-level ICI+DCN schedule; None (or a single-slice topology) keeps the
        # historical flat compressed allreduce, HLO-for-HLO.
        self.topology = topology
        self._hier = topology is not None and topology.is_hierarchical
        if self._hier:
            assert topology.dp == self.dp_size, (
                f"topology dp={topology.dp} != optimizer dp={self.dp_size}")
        self._seg_ids = None   # per-leaf scale segments (built lazily from the param tree)
        self._seg_key = None   # (treedef, leaf shapes, n_pad) the cached map was built for

    def _segment_ids(self, master_params, n_pad: int):
        """Element -> parameter-leaf segment map: the reference compresses each tensor
        with its own scale (per-param state); the padded tail gets its own segment so
        its zeros never perturb a real tensor's RMS. Cached keyed on the tree structure
        and leaf shapes (not just n_pad): a differently-structured tree that happens to
        pad to the same length must not reuse a stale map."""
        leaves, treedef = jax.tree_util.tree_flatten(master_params)
        key = (treedef, tuple(l.shape for l in leaves), n_pad)
        if self._seg_ids is None or self._seg_key != key:
            sizes = [int(np.prod(s)) for s in key[1]]
            ids = np.repeat(np.arange(len(sizes), dtype=np.int32), sizes)
            if n_pad > ids.shape[0]:
                ids = np.concatenate([ids, np.full(n_pad - ids.shape[0], len(sizes),
                                                   np.int32)])
            self._seg_ids = ids
            self._seg_key = key
        return self._seg_ids

    # ---------------------------------------------------------------- state
    def init(self, master_params) -> OneBitAdamState:
        n = sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(master_params))
        n_pad = padded_size(n, self.dp_size)
        dp = self.dp_size
        if self._hier:
            we_shape, se_shape = error_state_shapes(n_pad, self.topology)
        else:
            we_shape, se_shape = (dp, n_pad), (dp, n_pad // dp)
        return OneBitAdamState(
            exp_avg=jnp.zeros((n_pad,), jnp.float32),
            exp_avg_sq=jnp.zeros((n_pad,), jnp.float32),
            worker_error=jnp.zeros(we_shape, jnp.float32),
            server_error=jnp.zeros(se_shape, jnp.float32))

    def state_shardings(self, mesh: Mesh):
        return OneBitAdamState(
            exp_avg=NamedSharding(mesh, P()),
            exp_avg_sq=NamedSharding(mesh, P()),
            worker_error=NamedSharding(mesh, P(DATA_AXIS, None)),
            server_error=NamedSharding(mesh, P(DATA_AXIS, None)))

    # ---------------------------------------------------------------- update
    def apply(self, grads, state: OneBitAdamState, master_params, step, hyper):
        """One optimizer step. ``grads`` leaves carry a leading stacked-worker dp axis."""
        dp = self.dp_size
        g_stacked, _ = _flatten_stacked(grads, dp)          # (dp, n)
        n = g_stacked.shape[1]
        n_pad = state.exp_avg.shape[0]
        if n_pad > n:
            g_stacked = jnp.pad(g_stacked, ((0, 0), (0, n_pad - n)))

        p_flat, p_recipe = _flatten(master_params)
        if n_pad > n:
            p_flat_pad = jnp.pad(p_flat, (0, n_pad - n))
        else:
            p_flat_pad = p_flat

        beta1, beta2 = hyper["beta1"], hyper["beta2"]
        m, v = state.exp_avg, state.exp_avg_sq
        frozen = step > self.freeze_step  # step is 1-based when called from the engine

        def warmup_branch(operand):
            m, v, g_stacked, we, se = operand
            g_mean = jnp.mean(g_stacked, axis=0)            # GSPMD fp32 allreduce over data
            new_m = beta1 * m + (1.0 - beta1) * g_mean
            new_v = beta2 * v + (1.0 - beta2) * jnp.square(g_mean)
            return new_m, new_v, we, se

        seg_ids = self._segment_ids(master_params, n_pad)

        def frozen_branch(operand):
            m, v, g_stacked, we, se = operand
            # Worker-local momentum update (onebit_adam.py:335-336), then 1-bit averaging
            # with per-tensor scales (reference compresses each param separately).
            m_local = beta1 * m[None, :] + (1.0 - beta1) * g_stacked
            if self._hier:
                new_m, new_we, new_se = two_level_compressed_allreduce(
                    self.mesh, m_local, we, se, self.topology, seg_ids=seg_ids)
            else:
                new_m, new_we, new_se = compressed_allreduce(self.mesh, m_local, we, se,
                                                             seg_ids=seg_ids)
            return new_m, v, new_we, new_se

        m, v, we, se = jax.lax.cond(
            frozen, frozen_branch, warmup_branch,
            operand=(m, v, g_stacked, state.worker_error, state.server_error))

        update = m / (jnp.sqrt(v) + hyper["eps"]) + hyper["weight_decay"] * p_flat_pad
        new_p_flat = (p_flat_pad - hyper["lr"] * update)[:n]
        new_params = _unflatten(new_p_flat, p_recipe)
        return new_params, OneBitAdamState(m, v, we, se)

    # ---------------------------------------------------------------- elastic restore
    @staticmethod
    def _ef_geometry(we_shape, se_shape):
        """(dp, slice_size, n_pad) implied by the two error-buffer shapes: the
        server rows give dp, its columns give n_pad = dp * csize, and the worker
        columns give slice_size = n_pad / worker_cols (flat layout -> 1)."""
        dp = int(se_shape[0])
        n_pad = dp * int(se_shape[1])
        L = n_pad // int(we_shape[1])
        assert (int(we_shape[0]) == dp and L >= 1 and dp % L == 0
                and L * int(we_shape[1]) == n_pad), (we_shape, se_shape)
        return dp, L, n_pad

    @staticmethod
    def _server_offsets(dp, L, n_pad):
        """Global start offset of each device's server sub-chunk: device d owns
        ``(d % L) * (n_pad // L) + (d // L) * (n_pad // dp)`` — the flat layout
        (L == 1) reduces to the historical ``d * csize`` tiling."""
        C, csize = n_pad // L, n_pad // dp
        return [(d % L) * C + (d // L) * csize for d in range(dp)]

    def elastic_adapt(self, loaded_flat: dict, template_flat: dict) -> dict:
        """Adapt a checkpointed state dict saved under a different DP world size.

        Moment vectors are truncated/zero-extended to the new lane-padded length
        (the padded tail never reaches parameters). The (dp, ...) error-feedback
        buffers are residuals of one fixed global vector chunked by
        topology-dependent global offsets, so instead of zeroing them on a
        world-size change (losing accumulated compression correction — the
        reference's lazy-reallocation trade, onebit_adam.py:302-312), the global
        residual is reconstructed from the old chunking and re-chunked under the
        new one:

        - ``server_error``: the dp sub-chunks tile the padded vector exactly, so
          re-chunking is a pure index permutation — every element of the
          real-data region survives BIT-IDENTICALLY; only the old padded tail
          (residual of structural zeros) is dropped or zero-filled when the
          lane padding changes with dp.
        - ``worker_error``: the ``num_slices`` devices sharing a chunk position
          hold independent residuals (each slice compressed its own partial
          mean), and only their mean enters the averaged output — so the f64
          mean is re-placed onto every new holder of the position:
          mean-preserving, the strongest invariant a topology change admits.
        """
        out = {}
        for key, tmpl in template_flat.items():
            v = loaded_flat.get(key)
            tshape = tuple(int(s) for s in tmpl.shape)
            kind = ("worker_error" if key.endswith("worker_error")
                    else "server_error" if key.endswith("server_error") else None)
            if v is None:
                out[key] = np.zeros(tshape, np.float32)
                continue
            if kind is None:
                if tuple(v.shape) == tshape:
                    out[key] = v  # geometry unchanged: carried over bit-identically
                elif v.ndim == 1 and len(tshape) == 1:
                    buf = np.zeros(tshape, np.float32)
                    keep = min(v.size, tshape[0])
                    buf[:keep] = np.asarray(v)[:keep]
                    out[key] = buf
                else:
                    out[key] = np.zeros(tshape, np.float32)
                continue
            # Pair the two error buffers sharing this key's prefix: both shapes
            # are needed to pin each side's (dp, slice_size, n_pad) geometry.
            # (A matching per-key shape alone is NOT enough to pass through —
            # the same dp with a different slice factorization permutes the
            # chunk -> global-offset map without changing the server shape.)
            prefix = key[:-len(kind)]
            quad = (loaded_flat.get(prefix + "worker_error"),
                    loaded_flat.get(prefix + "server_error"),
                    template_flat.get(prefix + "worker_error"),
                    template_flat.get(prefix + "server_error"))
            try:
                dp_o, L_o, np_o = self._ef_geometry(quad[0].shape, quad[1].shape)
                dp_n, L_n, np_n = self._ef_geometry(quad[2].shape, quad[3].shape)
            except (AssertionError, AttributeError, IndexError, ZeroDivisionError):
                out[key] = np.zeros(tshape, np.float32)  # unrecognizable layout
                continue
            if (dp_o, L_o, np_o) == (dp_n, L_n, np_n):
                out[key] = v  # full geometry unchanged: bit-identical passthrough
                continue
            keep = min(np_o, np_n)
            if kind == "server_error":
                g = np.zeros(np_o, np.float32)
                cs_o = np_o // dp_o
                for d, off in enumerate(self._server_offsets(dp_o, L_o, np_o)):
                    g[off:off + cs_o] = np.asarray(v)[d]
                g_new = np.zeros(np_n, np.float32)
                g_new[:keep] = g[:keep]
                cs_n = np_n // dp_n
                out[key] = np.stack(
                    [g_new[off:off + cs_n]
                     for off in self._server_offsets(dp_n, L_n, np_n)])
            else:
                C_o = np_o // L_o
                g = np.zeros(np_o, np.float64)
                v64 = np.asarray(v, np.float64)
                for l in range(L_o):
                    # rows holding chunk l are devices d with d % L_o == l
                    g[l * C_o:(l + 1) * C_o] = v64[l::L_o].mean(axis=0)
                g_new = np.zeros(np_n, np.float64)
                g_new[:keep] = g[:keep]
                C_n = np_n // L_n
                out[key] = np.stack(
                    [g_new[(d % L_n) * C_n:(d % L_n + 1) * C_n]
                     for d in range(dp_n)]).astype(np.float32)
        return out
