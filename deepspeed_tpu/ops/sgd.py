"""SGD with momentum (torch.optim.SGD parity for the engine's basic-optimizer path)."""

from typing import NamedTuple

import jax
import jax.numpy as jnp


class SgdState(NamedTuple):
    momentum_buf: object


def init(master_params) -> SgdState:
    return SgdState(momentum_buf=jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), master_params))


def apply(grads, state: SgdState, master_params, step, hyper, groups=None):
    from .adam import flat_group_ids, hyper_for_group

    def leaf(g, b, p, gi):
        h = hyper_for_group(hyper, gi)
        lr, wd = h["lr"], h["weight_decay"]
        mom = h.get("beta1", 0.0)  # momentum rides the beta1 slot
        g = g.astype(jnp.float32) + wd * p
        b = mom * b + g
        return p - lr * b, b

    flat_g, treedef = jax.tree_util.tree_flatten(grads)
    flat_b = jax.tree_util.tree_leaves(state.momentum_buf)
    flat_p = jax.tree_util.tree_leaves(master_params)
    flat_gi = flat_group_ids(groups, len(flat_g))
    new_p, new_b = [], []
    for g, b, p, gi in zip(flat_g, flat_b, flat_p, flat_gi):
        np_, nb = leaf(g, b, p, gi)
        new_p.append(np_)
        new_b.append(nb)
    unflat = jax.tree_util.tree_unflatten
    return unflat(treedef, new_p), SgdState(momentum_buf=unflat(treedef, new_b))
