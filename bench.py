"""Benchmark: the BASELINE.json metrics on the real TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra": {...}}.

Headline metric = BASELINE.json's "tokens/sec/chip at 1.5B (ZeRO-2)": a GPT-2 1.5B
(1600x48, 25 heads) training step on one v5e chip — fwd+bwd over the full 1.5B bf16
parameters plus the 1/32 fp32 optimizer-shard update a single v5e-32 ZeRO-2 rank
performs (collectives excluded: they need the other 31 chips). vs_baseline =
measured MFU / 0.40 (the north-star >=40% MFU). v5e-lite peak ~197 TFLOP/s bf16.

extra:
- gpt2_420m_*: the round-1 flagship config (real DeepSpeedEngine, ZeRO-2, dp=1) for
  round-over-round continuity.
- regression_vs_previous_round: this run's tok/s numbers vs the newest parseable
  BENCH_r*.json, >5% drops flagged by name (advisory).
- max_trainable_params_per_chip_zero_offload: largest GPT-2 (1600 wide, deepening
  n_layer) whose ZeRO-Offload HBM footprint — bf16 params + bf16 grads + remat
  activations; master/moments live in host DRAM — completes fwd+bwd on the chip
  (binary search over n_layer). The host Adam tier scales with host DRAM, so HBM is
  the binding constraint. (Full-model offload step timing rides the axon relay
  tunnel rather than a PCIe-class TPU-VM host link; a real small-scale engine step's
  fetch/adam/push breakdown is recorded in extra.offload_step_timing instead.)

Set DS_BENCH_FAST=1 to run only the 420M flagship (quick iteration).
"""

import gc
import json
import os
import sys
import time

import numpy as np

PEAK_TFLOPS = 197.0


def _fence(x):
    import jax
    return float(jax.device_get(x))


def _previous_round():
    """(round_file, bench_json) from the newest BENCH_r*.json whose driver tail
    still contains a parseable bench line — a truncated tail (r05) falls back to
    the next-newest round rather than killing the comparison."""
    import glob
    here = os.path.dirname(os.path.abspath(__file__))
    for path in sorted(glob.glob(os.path.join(here, "BENCH_r*.json")),
                       reverse=True):
        try:
            with open(path) as f:
                tail = json.load(f).get("tail", "")
        except (OSError, ValueError):
            continue
        for line in tail.splitlines():
            s = line.strip()
            if s.startswith('{"metric"'):
                try:
                    return os.path.basename(path), json.loads(s)
                except ValueError:
                    pass
    return None, None


def _dig(d, dotted):
    for k in dotted.split("."):
        if not isinstance(d, dict) or k not in d:
            return None
        d = d[k]
    return d if isinstance(d, (int, float)) and not isinstance(d, bool) else None


# round-over-round throughput ledger: headline + the per-block tok/s numbers
# a round may silently regress while the headline holds
REGRESSION_KEYS = (
    "value",
    "extra.gpt2_420m_tokens_per_sec_per_chip",
    "extra.gpt2_1p5b_engine_tokens_per_sec",
    "extra.decode_420m.greedy_tok_s",
    "extra.serving_420m.tok_s",
    "extra.serving_420m.goodput_tok_s",
    # serving latency ledger: TTFT percentiles regress independently of tok/s
    # (e.g. a scheduler change that favors decode over prefill admission) —
    # note lower-is-better keys flag on RISES via the inverted delta below
    "extra.serving_420m.ttft_ms_p50",
    "extra.serving_420m.ttft_ms_p95",
    # prefix-cache efficacy + sharded-decode throughput
    "extra.serving_420m_prefix_cache.prefix_cache_hit_rate",
    "extra.serving_420m_prefix_cache.ttft_ms_p50",
    "extra.serving_420m_sharded.tok_s",
    # speculative decoding (docs/serving.md): how often the draft is right,
    # and how many target program executions each emitted token costs —
    # target_steps_per_token is lower-is-better (PERF.md defines the metric)
    "extra.serving_speculative.spec_acceptance_rate",
    "extra.serving_speculative.target_steps_per_token",
    "extra.serving_1p5b_spec.spec_acceptance_rate",
    "extra.serving_1p5b_spec.target_steps_per_token",
    # fleet router (docs/serving.md): merged tail latency across replicas,
    # shed share under the seeded burst, and the merged goodput fraction
    # after the scripted warm failover — p99/shed lower-is-better
    "extra.serving_fleet.fleet_p99_ttft_ms",
    "extra.serving_fleet.shed_rate",
    "extra.serving_fleet.shed_rate_2x_saturation",
    "extra.serving_fleet.goodput_fleet_fraction",
    # HBM observatory (docs/hbm.md): the smoke engine's per-class resident
    # bytes (engine.memory_manifest -> utils/hbm) and the compile-reported
    # temp peak — a RISE is a memory regression (all lower-is-better)
    "extra.hbm.peak_by_class.params",
    "extra.hbm.peak_by_class.grads",
    "extra.hbm.peak_by_class.master",
    "extra.hbm.peak_by_class.optimizer",
    "extra.hbm.peak_by_class.compiled_temp_peak",
    # measured-time profile observatory (docs/profile.md): per-step exposed
    # collective time and host gap from the smoke trace window (all
    # lower-is-better — a RISE means overlap regressed), plus the measured
    # window MFU beside the rolling estimate
    "extra.profile.exposed_ici_ms",
    "extra.profile.exposed_dcn_ms",
    "extra.profile.host_gap_ms",
    "extra.profile.measured_mfu",
    # resilience ledger: caller-thread checkpoint stall and the warm/cold
    # restart TTFT ratio (docs/resilience.md) — both lower-is-better
    "extra.resilience.checkpoint_stall_ms",
    "extra.resilience.restore_warm_vs_cold_ttft",
    # run-lifecycle goodput (docs/goodput.md): productive share of run wall,
    # and the checkpoint-fence share of it (lower-is-better)
    "extra.goodput.goodput_fraction",
    "extra.goodput.badput_checkpoint_pct",
)

# Every regression key maps to its declared metric in the MetricCatalog
# (deepspeed_tpu/utils/metrics.py) — the catalog's direction decides which
# way is worse, so bench keeps NO private lower-is-better list. A key whose
# metric resolves neutral (or not at all) is a declaration bug:
# tests/unit/test_metrics_catalog.py pins full coverage.
REGRESSION_KEY_METRICS = {
    "value": "Telemetry/Samples/samples_per_sec",
    "extra.gpt2_420m_tokens_per_sec_per_chip":
        "Telemetry/Samples/samples_per_sec",
    "extra.gpt2_1p5b_engine_tokens_per_sec":
        "Telemetry/Samples/samples_per_sec",
    "extra.decode_420m.greedy_tok_s": "Serving/tok_s",
    "extra.serving_420m.tok_s": "Serving/tok_s",
    "extra.serving_420m.goodput_tok_s": "Serving/goodput_tok_s",
    "extra.serving_420m.ttft_ms_p50": "Serving/Latency/ttft_ms_p50",
    "extra.serving_420m.ttft_ms_p95": "Serving/Latency/ttft_ms_p95",
    "extra.serving_420m_prefix_cache.prefix_cache_hit_rate":
        "Serving/PrefixCache/hit_rate",
    "extra.serving_420m_prefix_cache.ttft_ms_p50":
        "Serving/Latency/ttft_ms_p50",
    "extra.serving_420m_sharded.tok_s": "Serving/tok_s",
    "extra.serving_speculative.spec_acceptance_rate":
        "Serving/Spec/acceptance_rate",
    "extra.serving_speculative.target_steps_per_token":
        "Serving/Spec/target_steps_per_token",
    "extra.serving_1p5b_spec.spec_acceptance_rate":
        "Serving/Spec/acceptance_rate",
    "extra.serving_1p5b_spec.target_steps_per_token":
        "Serving/Spec/target_steps_per_token",
    "extra.serving_fleet.fleet_p99_ttft_ms":
        "Serving/Fleet/Latency/ttft_ms_p99",
    "extra.serving_fleet.shed_rate": "Serving/Fleet/shed",
    "extra.serving_fleet.shed_rate_2x_saturation": "Serving/Fleet/shed",
    "extra.serving_fleet.goodput_fleet_fraction":
        "Serving/Fleet/Goodput/fraction",
    "extra.hbm.peak_by_class.params": "Memory/params_bytes",
    "extra.hbm.peak_by_class.grads": "Memory/grads_bytes",
    "extra.hbm.peak_by_class.master": "Memory/master_bytes",
    "extra.hbm.peak_by_class.optimizer": "Memory/optimizer_bytes",
    "extra.hbm.peak_by_class.compiled_temp_peak":
        "Memory/compiled_temp_peak_bytes",
    "extra.profile.exposed_ici_ms": "Profile/exposed_ici_ms",
    "extra.profile.exposed_dcn_ms": "Profile/exposed_dcn_ms",
    "extra.profile.host_gap_ms": "Profile/host_gap_ms",
    "extra.profile.measured_mfu": "Profile/mfu",
    "extra.resilience.checkpoint_stall_ms":
        "Run/Goodput/checkpoint_stall_seconds",
    "extra.resilience.restore_warm_vs_cold_ttft": "Serving/ttft_ms",
    "extra.goodput.goodput_fraction": "Run/Goodput/goodput_fraction",
    "extra.goodput.badput_checkpoint_pct":
        "Run/Goodput/checkpoint_stall_seconds",
}


def lower_is_better_keys():
    """Regression keys whose metric the catalog declares lower-is-better —
    their delta sign is inverted before the flag check (a regression is a
    RISE). Lazy import: the catalog costs nothing but bench's module import
    must stay dependency-light."""
    from deepspeed_tpu.utils.metrics import default_catalog
    catalog = default_catalog()
    return frozenset(k for k, metric in REGRESSION_KEY_METRICS.items()
                     if catalog.direction(metric) == "lower_is_better")


def regression_vs_previous_round(current, threshold_pct=5.0):
    """Compare this run's throughput numbers against the newest prior BENCH
    round; any metric more than ``threshold_pct`` below its predecessor is
    flagged by name. Purely advisory (the bench never fails on it) — the flags
    ride the JSON so the driver and PERF.md see the drop next to the number."""
    rnd, prev = _previous_round()
    if prev is None:
        return {"baseline_round": None,
                "note": "no parseable prior BENCH_r*.json"}
    if prev.get("metric") != current.get("metric"):
        return {"baseline_round": rnd, "note": "metric changed "
                f"({prev.get('metric')} -> {current.get('metric')}); skipped"}
    out = {"baseline_round": rnd, "threshold_pct": threshold_pct,
           "metrics": {}, "regressed": []}
    lower = lower_is_better_keys()
    for key in REGRESSION_KEYS:
        was, now = _dig(prev, key), _dig(current, key)
        if was is None or now is None or was <= 0:
            continue
        delta = 100.0 * (now - was) / was
        row = {"prev": was, "cur": now, "delta_pct": round(delta, 2)}
        worse = -delta if key in lower else delta
        if worse < -threshold_pct:
            row["regressed"] = True
            out["regressed"].append(key)
        out["metrics"][key] = row
    return out


def bench_420m():
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine
    from deepspeed_tpu.parallel.mesh import build_mesh

    # GPT-2-family ~420M flagship (tied LM head) shaped for one v5e chip: 1536-wide
    # matmuls keep the MXU fed; remat OFF — flash attention + seq-chunked fused CE keep
    # residuals small enough that batch 16 of full activations fits next to fp32 Adam.
    cfg = GPT2Config(vocab_size=50304, n_positions=1024, n_embd=1536, n_layer=12,
                     n_head=12, remat=False, use_flash_attention=True)
    batch, seq, steps = 16, 1024, 20  # 20: amortize the ~107 ms relay fence
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    n_params = model.param_count(params)
    mesh = build_mesh(model=1, pipe=1)
    engine = DeepSpeedEngine(model=model, model_parameters=params, mesh=mesh,
                             config_params={
                                 "train_batch_size": batch,
                                 "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
                                 "zero_optimization": {"stage": 2},
                             })
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, size=(batch, seq)).astype(np.int32)
    labels = np.roll(tokens, -1, axis=1)

    def step():
        loss = engine(tokens, labels)
        engine.backward(loss)
        engine.step()
        return loss

    # Two warmups: first compiles, second recompiles for donated-buffer layouts. NOTE:
    # on the axon relay block_until_ready does NOT fence — fence via device_get.
    step()
    _fence(step())
    # median-of-3 windows with the spread recorded: the shared tunnel chip shows
    # ~10% variance, and a best-of draw biases the round-over-round flagship
    # high (same rationale as the 1.5B engine headline's median-of-3)
    dts = []
    for _ in range(3):
        t0 = time.time()
        for _ in range(steps):
            loss = step()
        _fence(loss)
        dts.append(time.time() - t0)
    dts.sort()
    dt = dts[1]
    tps = batch * seq * steps / dt
    mfu = tps * 6.0 * n_params / 1e12 / PEAK_TFLOPS
    del engine, params
    gc.collect()
    out = {"gpt2_420m_tokens_per_sec_per_chip": round(tps, 1),
           "gpt2_420m_mfu": round(mfu, 4),
           "gpt2_420m_window_spread": round((dts[-1] - dts[0]) / dt, 4),
           "gpt2_420m_selection": f"median-of-3 {steps}-step windows"}
    try:
        out["gpt2_420m_telemetry"] = _telemetry_probe_420m(
            model, cfg, mesh, batch, tokens, labels)
    except Exception as e:
        out["gpt2_420m_telemetry"] = {"error": f"{type(e).__name__}: {e}"}
    return out


def _telemetry_probe_420m(model, cfg, mesh, batch, tokens, labels, steps=8):
    """Separate short instrumented run for the BENCH telemetry block. The timed
    headline windows above run UNtelemetered on purpose: telemetry's one block per
    step rides the loss fetch, and on the axon relay every device_get is a ~107 ms
    fence — fine for an observability probe, poison for a 20-step timed median."""
    import gc
    import tempfile

    import jax
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine

    tel_dir = tempfile.mkdtemp(prefix="ds_bench_telemetry_")
    probe = DeepSpeedEngine(model=model, model_parameters=model.init(jax.random.PRNGKey(0)),
                            mesh=mesh,
                            config_params={
                                "train_batch_size": batch,
                                "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
                                "zero_optimization": {"stage": 2},
                                "telemetry": {"enabled": True,
                                              "peak_tflops": PEAK_TFLOPS,
                                              "mfu_window": steps,
                                              "output_path": tel_dir,
                                              # one traced 2-step window mid-probe;
                                              # the profile observatory ingests it and
                                              # summary()["profile"] carries the
                                              # measured decomposition next to
                                              # anatomy's prediction (docs/profile.md)
                                              "trace_steps": [4, 6],
                                              "trace_dir": os.path.join(
                                                  tel_dir, "trace"),
                                              "profile": {"enabled": True},
                                              # chip auto-detected from device_kind;
                                              # summary()["anatomy"] then carries the
                                              # roofline floor + MFU ceiling beside
                                              # the measured MFU (docs/anatomy.md)
                                              "anatomy": {"enabled": True}},
                                "numerics": {"enabled": True,
                                             "audit_interval": 4},
                            })
    for _ in range(steps):
        loss = probe(tokens, labels)
        probe.backward(loss)
        probe.step()
    summary = probe.telemetry.summary()
    summary["note"] = (f"separate {steps}-step instrumented run; per-step loss "
                       "fetch fences the relay, so the timed windows above stay "
                       "untelemetered")
    if probe._numerics is not None:
        num = probe._numerics.summary()
        step_ms = summary.get("step_time_ms")
        try:
            total_s = float(step_ms) * steps / 1000.0
            num["audit_overhead_pct"] = round(100.0 * num["audit_seconds"] / total_s, 3) \
                if total_s > 0 else None
        except (TypeError, ValueError):
            num["audit_overhead_pct"] = None
        summary["numerics"] = num
    probe.telemetry.close()
    del probe
    gc.collect()
    return summary


def _shard_optimizer(dp):
    """Client (init, apply) pair for DeepSpeedEngine doing exactly one v5e-32 ZeRO-2
    rank's optimizer work: Adam over a 1/dp fp32 shard of the gradient stream. The
    apply is marked ``external_master``: the fp32 master shard it owns lives in
    opt_state, so the engine holds NO dp=1 full fp32 master at all (zero HBM — a
    real 1/32 rank never holds it) and skips the full-params re-cast
    (a real rank refreshes params from the 32-way all-gather, which needs the other
    31 chips and is excluded here like every cross-chip collective)."""
    import jax
    import jax.numpy as jnp

    def shard_of(tree):
        leaves = jax.tree_util.tree_leaves(tree)
        n = sum(l.size for l in leaves) // dp
        flat = jnp.concatenate(
            [l.reshape(-1)[: max(l.size // dp, 1)].astype(jnp.bfloat16) for l in leaves])
        if flat.shape[0] < n:
            flat = jnp.pad(flat, (0, n - flat.shape[0]))
        return flat[:n].astype(jnp.float32), n

    def init(master):
        n = sum(l.size for l in jax.tree_util.tree_leaves(master)) // dp
        return {"shard": jnp.zeros((n,), jnp.float32),
                "m1": jnp.zeros((n,), jnp.float32),
                "m2": jnp.zeros((n,), jnp.float32)}

    def apply(grads, state, master, step, hyper):
        gs, _ = shard_of(grads)
        m1 = hyper["beta1"] * state["m1"] + (1.0 - hyper["beta1"]) * gs
        m2 = hyper["beta2"] * state["m2"] + (1.0 - hyper["beta2"]) * gs * gs
        shard = state["shard"] - hyper["lr"] * m1 / (jnp.sqrt(m2) + hyper["eps"])
        return master, {"shard": shard, "m1": m1, "m2": m2}

    apply.external_master = True
    return init, apply


def bench_1p5b_engine(remat_policy="dots", batch=8, loss_chunk=128):
    """The 1.5B metric measured THROUGH DeepSpeedEngine: the real jitted
    value_and_grad, grad adoption, apply_update with donated buffers,
    monitor/report path — with the per-rank optimizer work supplied as an
    external-master client pair: the fp32 shard lives in opt_state, the engine
    holds NO dp=1 master at all, and at gas==1 the engine's fused single-jit step
    keeps the grad tree internal to the program — matching a real 1/32 rank's HBM
    footprint. The only remaining difference vs a real v5e-32 rank: cross-chip
    collectives are excluded (they need the other 31 chips)."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine
    from deepspeed_tpu.parallel.mesh import build_mesh

    cfg = GPT2Config(vocab_size=50304, n_positions=1024, n_embd=1600, n_layer=48,
                     n_head=25, remat=remat_policy != "none",
                     remat_policy=None if remat_policy in ("full", "none") else remat_policy,
                     use_flash_attention=True, loss_chunk=loss_chunk)
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    n_params = model.param_count(params)
    engine = DeepSpeedEngine(
        model=model, model_parameters=params, mesh=build_mesh(model=1, pipe=1),
        optimizer=_shard_optimizer(32),
        config_params={"train_batch_size": batch, "steps_per_print": 1000,
                       "bf16": {"enabled": True},
                       "zero_optimization": {"stage": 2},
                       # the external-master shard pair is a client optimizer
                       "zero_allow_untested_optimizer": True})
    del params
    gc.collect()
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, size=(batch, 1024)).astype(np.int32)
    labels = np.roll(tokens, -1, axis=1)

    def step():
        loss = engine(tokens, labels)
        engine.backward(loss)
        engine.step()
        return loss

    step()
    _fence(step())  # second warmup: donated-buffer layouts recompile
    # 15 steps/rep: the ~107 ms relay fence is a FIXED cost per timed window —
    # at 5 steps it inflated the 1.5B step time ~7%; 15 amortizes it to ~2%
    steps = 15
    dt = float("inf")
    for _ in range(2):
        t0 = time.time()
        for _ in range(steps):
            loss = step()
        _fence(loss)
        dt = min(dt, time.time() - t0)
    tps = batch * 1024 * steps / dt
    mfu = tps * 6.0 * n_params / 1e12 / PEAK_TFLOPS
    del engine
    gc.collect()
    return tps, mfu


# Round-5 sweep winner (PERF.md "Round-5 1.5B remat/batch sweep"): NO library
# remat at batch 3 with unchunked CE — XLA's own memory schedule beats every
# hand-chosen save set on this 15.75 GB chip (measured 0.5102 vs the round-4
# dots@8 pin's 0.4623). Triple = (remat_policy, batch, loss_chunk).
PINNED_ENGINE_CONFIG = ("none", 3, 1024)


def _engine_1p5b_subprocess():
    """Engine-driven 1.5B in a fresh process (an OOM must not poison the relay for
    the rest of the bench).

    Config discipline (VERDICT r3 #8): the PINNED config runs first and is the ONLY
    config whose number may become ``gpt2_1p5b_engine_mfu`` — if it fails
    deterministically the metric reports 0.0 (loud) with the failure log in extra,
    and any fallback measurement is reported separately as ``engine_fallback_*`` so
    the round-over-round headline stays config-stable. Transient relay failures
    ("response body closed", HTTP 500 without a resource signature) get up to two
    retries; resource exhaustion never retries."""
    import subprocess

    attempts = []

    def run_one(policy, batch, loss_chunk, retries, timeout=1500):
        for attempt in range(retries + 1):
            rec = {"config": f"remat={policy},batch={batch},chunk={loss_chunk}",
                   "attempt": attempt}
            try:
                r = subprocess.run([sys.executable, os.path.abspath(__file__),
                                    "--engine-1p5b", policy, str(batch),
                                    str(loss_chunk)],
                                   capture_output=True, text=True, timeout=timeout)
            except subprocess.TimeoutExpired:
                # a tunnel stall is transient — retry like any relay hiccup rather
                # than zeroing the headline on one slow attempt
                rec["outcome"] = "timeout"
                attempts.append(rec)
                sys.stderr.write(f"[bench] engine 1.5B ({policy}, B={batch}) timed out"
                                 f"{' (retrying)' if attempt < retries else ''}\n")
                continue
            for line in r.stdout.splitlines():
                if line.startswith("ENGINE_OK "):
                    _, tps, mfu = line.split()
                    rec["outcome"] = "ok"
                    rec["tps"], rec["mfu"] = float(tps), float(mfu)
                    attempts.append(rec)
                    return float(tps), float(mfu)
            deterministic = any(sig in r.stderr for sig in
                                ("RESOURCE_EXHAUSTED", "Ran out of memory",
                                 "exceeded scoped"))
            transient = not deterministic and any(
                sig in r.stderr for sig in
                ("response body", "remote_compile", "HTTP 500"))
            rec["outcome"] = "transient" if transient else "failed"
            rec["stderr_tail"] = r.stderr.splitlines()[-3:]
            attempts.append(rec)
            sys.stderr.write(f"[bench] engine 1.5B ({policy}, B={batch}) failed"
                             f"{' (transient, retrying)' if transient and attempt < retries else ''}:\n"
                             + "\n".join(r.stderr.splitlines()[-3:]) + "\n")
            if not transient:
                return None
        return None

    policy, batch, chunk = PINNED_ENGINE_CONFIG
    got = run_one(policy, batch, chunk, retries=2)
    if got is not None:
        # Run-to-run variance on the SAME pinned config measured ±4% on the
        # shared relay chip (0.491 in a post-offload-phase window vs 0.510
        # clean), so a single draw — and especially a best-of draw — biases the
        # round-over-round headline high. The headline is the MEDIAN of up to
        # three samples (VERDICT "What's weak" #1) with the observed spread
        # recorded alongside; every sample rides the attempts record (best-of
        # fields are retired — a reader wanting the max can take it from
        # attempts). Confirmation samples are optional — shorter timeout, no
        # retry — so a relay hiccup degrades to fewer samples, never to a dead
        # headline.
        samples = [got]
        for _ in range(2):
            extra = run_one(policy, batch, chunk, retries=0, timeout=900)
            if extra is not None:
                samples.append(extra)
        # median by mfu, keeping (tps, mfu) paired: lower-middle on even counts
        # so the headline is always a genuinely observed sample
        ranked = sorted(samples, key=lambda s: s[1])
        med = ranked[(len(ranked) - 1) // 2]
        spread = (ranked[-1][1] - ranked[0][1]) / med[1] if med[1] else 0.0
        return {"tps": med[0], "mfu": med[1],
                "mfu_spread": round(spread, 4),
                "config": f"remat={policy},batch={batch},chunk={chunk}",
                "selection": f"median-of-{len(samples)} subprocess samples "
                             f"(spread = (max-min)/median mfu; see attempts)",
                "attempts": attempts}
    sys.stderr.write("[bench] PINNED engine 1.5B config failed — headline engine "
                     "metric will read 0.0 (fallbacks reported separately)\n")
    out = {"tps": 0.0, "mfu": 0.0,
           "config": f"remat={policy},batch={batch},chunk={chunk}",
           "pinned_config_failed": True, "attempts": attempts}
    # memory-DECREASING ladder: the dominant pinned-failure mode is OOM, so each
    # fallback must use strictly less HBM than the last (none@2 < none@3; full
    # recompute @4 is the conservative floor)
    for fb_policy, fb_batch, fb_chunk in (("none", 2, 1024), ("full", 4, 128)):
        fb = run_one(fb_policy, fb_batch, fb_chunk, retries=1)
        if fb is not None:
            out["fallback"] = {"tps": fb[0], "mfu": fb[1],
                               "config": f"remat={fb_policy},batch={fb_batch},"
                                         f"chunk={fb_chunk}"}
            break
    return out


def _offload_step_once(n_embd, n_layer, vocab=8192):
    """One REAL ZeRO-Offload engine step at the given size; returns the
    DeepSpeedCPUAdam.last_step_timing breakdown plus derived rates."""
    import jax
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine
    from deepspeed_tpu.parallel.mesh import build_mesh

    cfg = GPT2Config(vocab_size=vocab, n_positions=512, n_embd=n_embd,
                     n_layer=n_layer, n_head=8, remat=True, use_flash_attention=True)
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    n_params = model.param_count(params)
    engine = DeepSpeedEngine(
        model=model, model_parameters=params, mesh=build_mesh(model=1, pipe=1),
        config_params={"train_batch_size": 4, "steps_per_print": 1000,
                       "bf16": {"enabled": True},
                       "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
                       "zero_optimization": {"stage": 2, "cpu_offload": True}})
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, size=(4, 512)).astype(np.int32)
    labels = np.roll(tokens, -1, axis=1)
    # TWO steps: the first pipelined step autotunes the region-element cap (it
    # takes effect at the next grad fetch), the second is the measured one
    for _ in range(2):
        loss = engine(tokens, labels)
        engine.backward(loss)
        engine.step()
        _fence(loss)
    t = dict(engine.offload_step_timing)
    numel = int(engine._offload.numel)
    # lane-busy seconds are the honest overlap denominator: fetch_wait is only
    # the stall the Adam loop actually SAW, so a well-overlapped step has tiny
    # fetch_wait while fetch_busy stays ~= the serial fetch time
    lanes = {"fetch": t.get("fetch_busy", t["fetch_wait"]),
             "adam": t["host_adam"], "push": t.get("push_busy", t["push"])}
    regions = t.get("regions", [])
    top = sorted(regions, key=lambda r: -(r["fetch"] + r["adam"] + r["push"]))[:5]
    out = {"params": int(n_params), "numel_local": numel,
           "fetch_wait_s": round(t["fetch_wait"], 3),
           "fetch_busy_s": round(lanes["fetch"], 3),
           "host_adam_s": round(t["host_adam"], 3),
           "push_s": round(t["push"], 3),
           "push_busy_s": round(lanes["push"], 3),
           "total_s": round(t["total"], 3),
           "pipeline_depth": t.get("pipeline_depth"),
           "region_cap_elements": t.get("region_cap"),
           "n_regions": len(regions), "n_work_items": t.get("n_work_items"),
           "elements_per_s": round(numel / max(t["total"], 1e-9)),
           # ideal overlapped pipeline -> total ~= max(lane busy) -> efficiency -> 1
           "overlap_efficiency": round(
               max(lanes.values()) / max(t["total"], 1e-9), 3),
           "regions_top": [
               {"leaf": r["leaf"], "size": r["size"], "chunks": r["chunks"],
                "fetch_wait_s": round(r["fetch_wait"], 3),
                "fetch_s": round(r["fetch"], 3), "adam_s": round(r["adam"], 3),
                "push_s": round(r["push"], 3)} for r in top]}
    del engine, params
    gc.collect()
    return out


def bench_offload_step_timing():
    """ZeRO-Offload step breakdown at THREE sizes (VERDICT r4 #5) + a modeled step
    at the advertised 4B max-params config.

    Transfers ride the axon relay tunnel (~80 MB/s D2H), so the absolute walls are
    tunnel-bound; the evidence is (a) the fetch/adam/push overlap STRUCTURE, (b)
    elements/s scaling ~linearly with size (the region pipeline has no
    super-linear term), and (c) the modeled 4B row extrapolated from the largest
    measured size's rates — on a TPU-VM's PCIe-class host link the same structure
    holds with transfer ~1000x faster, leaving host_adam dominant."""
    sizes = [
        (512, 8),     # ~30 M local elements (the round-4 measurement point)
        (1024, 10),   # ~130 M
        (1280, 20),   # ~400 M
    ]
    rows = [_offload_step_once(n_embd, n_layer) for n_embd, n_layer in sizes]

    big = rows[-1]
    max_numel = 4_016_950_400  # max_trainable_params_per_chip probe result
    scale = max_numel / big["numel_local"]
    modeled = {
        "numel_local": max_numel,
        "fetch_wait_s": round(big["fetch_wait_s"] * scale, 1),
        "host_adam_s": round(big["host_adam_s"] * scale, 1),
        "push_s": round(big["push_s"] * scale, 1),
        "total_s": round(big["total_s"] * scale, 1),
        "basis": f"linear scaling from the {big['numel_local']:,}-element measured row "
                 f"(elements/s {big['elements_per_s']:,}); tunnel-bound here — with a "
                 "PCIe-class host link the transfer terms shrink ~1000x and host_adam "
                 f"(~{round(big['host_adam_s'] * scale, 1)} s at 4B) dominates",
    }
    return {"sizes": rows, "modeled_step_at_max_params": modeled,
            "note": ("transfers ride the axon relay tunnel; the breakdown proves the "
                     "overlapped region pipeline, not production wall-clock")}


def bench_decode_420m():
    """KV-cache greedy decode tokens/s, GPT-2 420M batch 8 (VERDICT r4 #3 — the
    generation stack is beyond the v0.3.0 reference, so it carries its own
    number). Decode rate isolated from prefill by differencing a 128-token and a
    1-token generation; full table (1.5B, batch 1, beam-4) in PERF.md via
    tests/perf/decode_perf.py."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model

    T0, NEW, B = 1024, 128, 8
    cfg = GPT2Config(vocab_size=50304, n_positions=T0 + NEW + 8, n_embd=1024,
                     n_layer=24, n_head=16, use_flash_attention=True)
    model = GPT2Model(cfg)
    params = jax.tree_util.tree_map(
        lambda p: p.astype(jnp.bfloat16) if p.ndim >= 2 else p,
        model.init(jax.random.PRNGKey(0)))
    prompt = jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, size=(B, T0)), jnp.int32)

    def fence_tokens(x):
        # the generated [B, T] token array can't go through the scalar _fence
        return jax.tree_util.tree_leaves(jax.device_get(x))[0]

    def timed(fn):
        fence_tokens(fn())
        fence_tokens(fn())
        best = float("inf")
        for _ in range(2):
            t0 = time.time()
            fence_tokens(fn())
            best = min(best, time.time() - t0)
        return best

    t1 = timed(lambda: model.generate(params, prompt, 1))
    t_long = timed(lambda: model.generate(params, prompt, NEW))
    out = {"greedy_tok_s": round((NEW - 1) * B / max(t_long - t1, 1e-9), 1),
           "prefill_s": round(t1, 3), "batch": B, "prompt": T0}
    del params
    gc.collect()
    return out


def bench_serving_summary(cfg_kwargs, *, n_requests, num_slots, block_size,
                          num_blocks, max_model_len, prefill_chunk,
                          param_dtype=None, seed=11, prefix_cache=False,
                          sharding=1, shared_prefix=0, speculate=0,
                          draft_cfg_kwargs=None):
    """Continuous-batching serving summary (docs/serving.md): replay a seeded
    mixed greedy/beam trace through the InferenceEngine and report tok/s,
    TTFT/TPOT latency percentiles (request-trace ledger), preemption-waste
    fraction, mean slot occupancy, and goodput — plus the compile-watchdog
    recompile count, which must be 0 after warmup (the fixed-shape contract
    ds-tpu serve-sim gates on). Runs OUTSIDE the headline measurement windows
    (PERF.md): the ledger is host-side bookkeeping, but the headline numbers
    stay untraced on principle."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
    from deepspeed_tpu.serve.sim import synth_trace
    from deepspeed_tpu.utils.monitor import SummaryMonitor
    from deepspeed_tpu.utils.telemetry import TelemetrySession

    cfg = GPT2Config(**cfg_kwargs)
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    if param_dtype is not None:
        params = jax.tree_util.tree_map(
            lambda p: p.astype(param_dtype) if p.ndim >= 2 else p, params)
    # speculation: self-draft (same model+params, acceptance ~1) unless a
    # separate draft config is given — then the real small-drafts-big shape
    draft_model = draft_params = None
    if speculate:
        if draft_cfg_kwargs is None:
            draft_model, draft_params = model, params
        else:
            draft_model = GPT2Model(GPT2Config(**draft_cfg_kwargs))
            draft_params = draft_model.init(jax.random.PRNGKey(1))
            if param_dtype is not None:
                draft_params = jax.tree_util.tree_map(
                    lambda p: p.astype(param_dtype) if p.ndim >= 2 else p,
                    draft_params)
    # disabled monitor: the watchdog is wanted, the scalar files are not
    session = TelemetrySession(monitor=SummaryMonitor(enabled=False))
    import deepspeed_tpu
    eng = deepspeed_tpu.init_inference(
        model=model, model_parameters=params, telemetry=session,
        draft_model=draft_model, draft_parameters=draft_params,
        config_params={"serving": {
            "enabled": True, "max_seqs": num_slots, "block_size": block_size,
            "num_blocks": num_blocks, "max_model_len": max_model_len,
            "prefill_chunk": prefill_chunk,
            "prefix_cache": {"enabled": prefix_cache},
            "sharding": {"model": sharding},
            "speculation": {"enabled": bool(speculate),
                            "max_draft_tokens": max(int(speculate), 1)},
            "request_trace": {"enabled": True,
                              "capacity": max(n_requests + 1, 256)}}})
    reqs = synth_trace(n_requests, vocab_size=cfg.vocab_size,
                       max_model_len=max_model_len, seed=seed,
                       shared_prefix_len=shared_prefix)
    t0 = time.time()
    outs, logs = eng.run(reqs)
    wall = max(time.time() - t0, 1e-9)
    fin = [o for o in outs if o.status == "finished"]
    new_tokens = sum(len(o.tokens) for o in fin)
    occ = [len(log["decode"]) / num_slots for log in logs]
    recompiles = sum(session.watchdog.recompiles(n)
                     for n in session.watchdog.records
                     if n.startswith("serve:"))
    spec_extra = {}
    if speculate:
        ss = eng.spec_summary()
        spec_extra = {
            "spec_k": int(speculate),
            "spec_acceptance_rate": round(ss["spec_acceptance_rate"], 4),
            "target_steps_per_token": round(ss["target_steps_per_token"], 4),
            "drafted_tokens": ss["drafted_tokens"],
            "accepted_draft_tokens": ss["accepted_tokens"],
            "wasted_draft_tokens": ss["wasted_draft_tokens"]}
    cache_extra = {}
    if eng.prefix_cache is not None:
        cs = eng.prefix_cache.stats()
        cache_extra = {
            "prefix_cache_hit_rate": round(cs["hit_rate"], 4),
            "cached_token_fraction": round(cs["cached_token_fraction"], 4),
            "cached_prefix_tokens": cs["hit_tokens"],
            "prefix_cache_evictions": cs["evictions"]}
    return {"requests": len(reqs), "finished": len(fin),
            "iterations": len(logs), "wall_s": round(wall, 2),
            **({"sharding_model_ways": sharding} if sharding > 1 else {}),
            **cache_extra, **spec_extra,
            # tok_s counts every sampled token (all beam lanes, preempted
            # work included); goodput only tokens of finished requests
            "tok_s": round(eng._tokens_sampled / wall, 1),
            "goodput_tok_s": round(new_tokens / wall, 1),
            "ttft_ms_mean": round(float(np.mean([o.ttft_ms for o in fin])), 2),
            "ttft_iters_mean": round(float(np.mean([o.ttft_iters
                                                    for o in fin])), 2),
            **{f"{m}_{p}": round(v, 2)
               for m in ("ttft_ms", "tpot_ms")
               for p, v in eng.tracer.percentiles(m, ps=(50, 95, 99)).items()
               if v is not None},
            "waste_fraction": round(
                eng.tracer.waste_summary()["waste_fraction"], 4),
            "occupancy_mean": round(float(np.mean(occ)) if occ else 0.0, 3),
            "preemptions": sum(o.preemptions for o in fin),
            "decode_recompiles_after_warmup": recompiles}


def bench_serving_smoke():
    """CPU smoke shape of the serving summary (tiny model, 16 requests)."""
    return bench_serving_summary(
        dict(vocab_size=256, n_positions=64, n_embd=32, n_layer=2, n_head=2,
             loss_chunk=0),
        n_requests=16, num_slots=4, block_size=8, num_blocks=33,
        max_model_len=64, prefill_chunk=16)


def bench_serving_prefix_cache_smoke():
    """Prefix-cache smoke: shared-system-prompt trace, cache on — reports
    hit-rate / cached-token fraction next to the same tok/s columns."""
    return bench_serving_summary(
        dict(vocab_size=256, n_positions=64, n_embd=32, n_layer=2, n_head=2,
             loss_chunk=0),
        n_requests=16, num_slots=4, block_size=8, num_blocks=33,
        max_model_len=64, prefill_chunk=16, prefix_cache=True,
        shared_prefix=24)


def bench_serving_sharded_smoke():
    """Model-axis sharded smoke (2-way head shard over the CPU mesh) — the
    sharded-decode tok/s column of the regression ledger."""
    return bench_serving_summary(
        dict(vocab_size=256, n_positions=64, n_embd=32, n_layer=2, n_head=2,
             loss_chunk=0),
        n_requests=16, num_slots=4, block_size=8, num_blocks=33,
        max_model_len=64, prefill_chunk=16, sharding=2)


def bench_serving_speculative_smoke():
    """Speculative-decoding smoke: the shared-prefix trace with self-draft
    K=4 speculation — acceptance rate (~1 by construction for self-draft) and
    target-steps-per-token for the regression ledger (PERF.md)."""
    return bench_serving_summary(
        dict(vocab_size=256, n_positions=64, n_embd=32, n_layer=2, n_head=2,
             loss_chunk=0),
        n_requests=16, num_slots=4, block_size=8, num_blocks=33,
        max_model_len=64, prefill_chunk=16, shared_prefix=24, speculate=4)


def bench_serving_fleet_summary(cfg_kwargs, *, replicas, n_requests, num_slots,
                                block_size, num_blocks, max_model_len,
                                prefill_chunk, param_dtype=None, seed=11,
                                shared_prefix=0, max_queue_depth=0, kills=(),
                                shed_probe_rate=0.0,
                                shed_probe_queue_depth=0):
    """Fleet-router serving summary (docs/serving.md): N replicas sharing one
    model/params object behind the prefix-affinity FleetRouter, a seeded
    shared-prefix trace routed through it, and a scripted warm failover —
    reports the fleet-MERGED TTFT/TPOT percentiles (exact sketch fold), the
    shed rate under the queue-depth bound, and the merged goodput_fleet
    fraction after the kills bill their restart_replay badput. Runs OUTSIDE
    the headline windows like the single-replica serving smokes."""
    import shutil
    import tempfile

    import jax
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
    from deepspeed_tpu.serve.engine import InferenceEngine
    from deepspeed_tpu.serve.router import FleetRouter
    from deepspeed_tpu.serve.sim import synth_trace
    from deepspeed_tpu.utils.monitor import SummaryMonitor
    from deepspeed_tpu.utils.telemetry import TelemetrySession

    cfg = GPT2Config(**cfg_kwargs)
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    if param_dtype is not None:
        params = jax.tree_util.tree_map(
            lambda p: p.astype(param_dtype) if p.ndim >= 2 else p, params)
    # disabled monitor: the recompile watchdog is wanted, scalar files are not
    session = TelemetrySession(monitor=SummaryMonitor(enabled=False))

    def build(slot, telemetry=None):
        return InferenceEngine(
            model, params, num_slots=num_slots, block_size=block_size,
            num_blocks=num_blocks, max_model_len=max_model_len,
            prefill_chunk=prefill_chunk, prefix_cache=True,
            telemetry=telemetry,
            request_trace={"enabled": True,
                           "capacity": max(n_requests + 1, 256),
                           "host_id": slot})

    engines = [build(s, session if s == 0 else None) for s in range(replicas)]
    snap = tempfile.mkdtemp(prefix="ds_bench_fleet_") if kills else None
    router = FleetRouter(
        engines, max_queue_depth=max_queue_depth,
        kill_schedule=list(kills), snapshot_dir=snap,
        build_replacement=(lambda slot: build(slot)) if kills else None,
        telemetry=session, run_id=f"bench_fleet{replicas}")
    reqs = synth_trace(n_requests, vocab_size=cfg.vocab_size,
                       max_model_len=max_model_len, seed=seed,
                       shared_prefix_len=shared_prefix)
    t0 = time.time()
    outs, _ = router.run(reqs)
    wall = max(time.time() - t0, 1e-9)
    if snap:
        shutil.rmtree(snap, ignore_errors=True)
    summary = router.fleet_summary()
    lat = summary["latency"]
    fin = [o for o in outs if o.status == "finished"]
    recompiles = sum(session.watchdog.recompiles(n)
                     for n in session.watchdog.records
                     if n.startswith("serve:"))
    # load-shedding probe: the same seeded trace re-drawn as a Poisson
    # process at ~2x the fleet's service capacity, routed through fresh
    # replicas (same model/params — no new compiles) behind a queue-depth
    # bound tight enough that the overload actually crosses it
    # (shed_probe_queue_depth; the main trace's bound is sized NOT to).
    # shed_rate under that overload is the admission-control ledger: a rise
    # means the fleet sheds MORE of an identical overload than last round
    # (regression key, lower-is-better).
    probe = None
    probe_depth = shed_probe_queue_depth or max_queue_depth
    if shed_probe_rate and probe_depth:
        probe_engines = [build(s) for s in range(replicas)]
        probe_router = FleetRouter(
            probe_engines, max_queue_depth=probe_depth,
            run_id=f"bench_fleet{replicas}_shed_probe")
        probe_reqs = synth_trace(
            n_requests, vocab_size=cfg.vocab_size,
            max_model_len=max_model_len, seed=seed,
            shared_prefix_len=shared_prefix,
            arrival_process=("poisson", shed_probe_rate))
        pouts, _ = probe_router.run(probe_reqs)
        pshed = sum(1 for o in pouts if o.status == "shed")
        probe = {"arrival_rate": shed_probe_rate, "requests": len(probe_reqs),
                 "queue_depth": probe_depth, "shed": pshed,
                 "shed_rate_2x_saturation": round(
                     pshed / max(len(probe_reqs), 1), 4)}
    return {"replicas": replicas, "requests": len(reqs),
            **({"shed_probe": probe,
                "shed_rate_2x_saturation":
                    probe["shed_rate_2x_saturation"]} if probe else {}),
            "finished": len(fin), "shed": summary["shed"],
            "kills": summary["kills"], "wall_s": round(wall, 2),
            "goodput_tok_s": round(sum(len(o.tokens) for o in fin) / wall, 1),
            **{f"fleet_{k}": round(v, 2) for k, v in lat.items()},
            "fleet_p99_ttft_ms": round(lat.get("ttft_ms_p99", 0.0), 2),
            "shed_rate": round(summary["shed"] / max(len(reqs), 1), 4),
            "goodput_fleet_fraction": round(
                summary["goodput_fleet"]["goodput_fraction"], 4),
            "prefill_chunks": summary["prefill_chunks"],
            "total_prefill_chunks": summary["total_prefill_chunks"],
            "decode_recompiles_after_warmup": recompiles}


def bench_serving_fleet_smoke():
    """CPU smoke of the fleet summary: 3 tiny replicas, a shared-prefix
    trace, one scripted warm kill, and a queue-depth bound tight enough to
    exercise (but not saturate) the shed path."""
    return bench_serving_fleet_summary(
        dict(vocab_size=256, n_positions=64, n_embd=32, n_layer=2, n_head=2,
             loss_chunk=0),
        replicas=3, n_requests=16, num_slots=4, block_size=8, num_blocks=33,
        max_model_len=64, prefill_chunk=16, shared_prefix=24,
        max_queue_depth=8, kills=((6, 0),),
        # service capacity on this trace ~ replicas*slots/mean-request-iters
        # = 3*4/~10 ~ 1.2 req/iteration; probe the shed path at ~2x that,
        # behind a depth-1 bound (the 12 decode slots absorb the burst at
        # this toy scale behind anything looser and the probe reads 0.0)
        shed_probe_rate=2.4, shed_probe_queue_depth=1)


def bench_resilience_smoke():
    """Resilience smoke (docs/resilience.md): measures what the async
    checkpointer actually costs the step — median step wall time with a
    background commit in flight vs no saves at all, plus the caller-thread
    snapshot stall — and what a warm serving restart actually buys: mean TTFT
    of requests drained after a warm restore vs a cold restart of the same
    pending work (plus the deterministic prefill-chunk counts behind it).
    Runs OUTSIDE the headline window like the serving smokes."""
    import shutil
    import tempfile

    from deepspeed_tpu.resilience.async_ckpt import AsyncCheckpointer
    from deepspeed_tpu.resilience.crash_sim import (_drain, _make_server,
                                                    _make_trainer,
                                                    _prefill_chunks,
                                                    _serve_trace,
                                                    _train_batches)
    from deepspeed_tpu.resilience.serve_restart import (restore_server,
                                                        save_server)
    from deepspeed_tpu.serve.scheduler import pack_request, unpack_request

    workdir = tempfile.mkdtemp(prefix="ds_bench_resilience_")
    try:
        engine = _make_trainer(0)
        batches = _train_batches(12, 0)

        def timed_step(x, y):
            t0 = time.perf_counter()
            loss = engine(x, y)
            engine.backward(loss)
            engine.step()
            _fence(loss)
            return (time.perf_counter() - t0) * 1e3

        for x, y in batches[:2]:  # pay the compiles outside both windows
            timed_step(x, y)
        base = [timed_step(x, y) for x, y in batches[2:7]]
        ck = AsyncCheckpointer(engine, os.path.join(workdir, "train"))
        stalls, with_save = [], []
        for i, (x, y) in enumerate(batches[7:12]):
            # issue the save BEFORE the timed step: the commit thread then
            # overlaps the step, which is exactly the fencing claim under test
            ck.save(tag=f"s{i}")
            stalls.append(ck.last_stall_ms)
            with_save.append(timed_step(x, y))
        ck.wait()

        trace = _serve_trace(1)
        victim = _make_server(1, 129)
        for r in trace:
            victim.submit(unpack_request(pack_request(r)))
        for _ in range(6):  # partial progress, then the replica dies
            if victim.scheduler.idle:
                break
            victim.step()
        finished_at_kill = set(victim.outputs)
        snap = save_server(victim, os.path.join(workdir, "serve"))

        warm = _make_server(1, 129)
        restore_server(warm, snap)
        warm_logs = _drain(warm)
        warm_ttft = [o.ttft_ms for rid, o in warm.outputs.items()
                     if rid not in finished_at_kill and o.status == "finished"]
        cold = _make_server(1, 129)
        pending = [r for r in trace if r.req_id not in finished_at_kill]
        cold_out, cold_logs = cold.run([unpack_request(pack_request(r))
                                        for r in pending])
        cold_ttft = [o.ttft_ms for o in cold_out if o.status == "finished"]
        warm_ms = float(np.mean(warm_ttft)) if warm_ttft else 0.0
        cold_ms = float(np.mean(cold_ttft)) if cold_ttft else 0.0
        return {"checkpoint_stall_ms": round(float(np.median(stalls)), 2),
                "step_ms_no_save": round(float(np.median(base)), 2),
                "step_ms_with_async_save": round(float(np.median(with_save)), 2),
                "saves_committed": int(ck.saves_committed),
                "restore_warm_ttft_ms_mean": round(warm_ms, 2),
                "restore_cold_ttft_ms_mean": round(cold_ms, 2),
                # warm/cold TTFT ratio (lower is better; < 1.0 = warm wins)
                "restore_warm_vs_cold_ttft": round(warm_ms / cold_ms, 3)
                if cold_ms > 0 else 0.0,
                "warm_prefill_chunks": int(_prefill_chunks(warm_logs)),
                "cold_prefill_chunks": int(_prefill_chunks(cold_logs))}
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def bench_goodput_smoke():
    """Run-lifecycle goodput smoke (docs/goodput.md): a short engine run with
    the badput ledger on and periodic async saves, reporting the goodput
    fraction and the checkpoint-fence share of run wall — the two
    run-efficiency numbers the round ledger tracks (the checkpoint share is
    lower-is-better). Runs OUTSIDE the headline window like the other
    smokes."""
    import shutil
    import tempfile

    from deepspeed_tpu.resilience.crash_sim import (_goodput_trainer,
                                                    _train_batches)

    workdir = tempfile.mkdtemp(prefix="ds_bench_goodput_")
    try:
        engine = _goodput_trainer(0, os.path.join(workdir, "led"),
                                  {"enabled": True,
                                   "save_dir": os.path.join(workdir, "ckpt"),
                                   "save_interval": 3})
        for x, y in _train_batches(9, 0):
            loss = engine(x, y)
            engine.backward(loss)
            engine.step()
        engine._resilience.wait()
        summary = engine._goodput.finalize()
        wall = summary["wall_s"] or 1.0
        cs = summary["class_seconds"]
        return {"goodput_fraction": round(summary["goodput_fraction"], 4),
                "badput_checkpoint_pct":
                    round(100.0 * cs["checkpoint_stall"] / wall, 3),
                "badput_init_pct": round(100.0 * cs["init"] / wall, 3),
                "badput_compile_pct": round(100.0 * cs["compile"] / wall, 3),
                "steps": int(summary["steps"]),
                "checkpoint_stalls": int(summary["checkpoint_stalls"])}
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def bench_serving_420m():
    """TPU serving path: GPT-2 420M bf16, 32-request mixed trace."""
    import jax.numpy as jnp
    out = bench_serving_summary(
        dict(vocab_size=50304, n_positions=1024, n_embd=1024, n_layer=24,
             n_head=16, use_flash_attention=True),
        n_requests=32, num_slots=8, block_size=16, num_blocks=513,
        max_model_len=1024, prefill_chunk=128, param_dtype=jnp.bfloat16)
    gc.collect()
    return out


def bench_serving_420m_prefix_cache():
    """420M shared-system-prompt trace with the prefix cache on: the TTFT
    delta vs ``serving_420m`` prices what cross-request reuse buys at size."""
    import jax.numpy as jnp
    out = bench_serving_summary(
        dict(vocab_size=50304, n_positions=1024, n_embd=1024, n_layer=24,
             n_head=16, use_flash_attention=True),
        n_requests=32, num_slots=8, block_size=16, num_blocks=513,
        max_model_len=1024, prefill_chunk=128, param_dtype=jnp.bfloat16,
        prefix_cache=True, shared_prefix=256)
    gc.collect()
    return out


def bench_serving_420m_sharded():
    """420M decode sharded 2 ways over the model axis by attention head."""
    import jax.numpy as jnp
    out = bench_serving_summary(
        dict(vocab_size=50304, n_positions=1024, n_embd=1024, n_layer=24,
             n_head=16, use_flash_attention=True),
        n_requests=32, num_slots=8, block_size=16, num_blocks=513,
        max_model_len=1024, prefill_chunk=128, param_dtype=jnp.bfloat16,
        sharding=2)
    gc.collect()
    return out


def bench_serving_1p5b_spec():
    """GPT-2 420M drafts for a 1.5B target (both bf16) — the real-deployment
    shape of speculative decoding. Acceptance rate prices how often the small
    model predicts the big one's greedy choice; target_steps_per_token is what
    the K+1-wide verify amortization actually buys at size."""
    import jax.numpy as jnp
    out = bench_serving_summary(
        dict(vocab_size=50304, n_positions=1024, n_embd=1600, n_layer=48,
             n_head=25, use_flash_attention=True),
        n_requests=32, num_slots=8, block_size=16, num_blocks=513,
        max_model_len=1024, prefill_chunk=128, param_dtype=jnp.bfloat16,
        shared_prefix=256, speculate=4,
        draft_cfg_kwargs=dict(vocab_size=50304, n_positions=1024, n_embd=1024,
                              n_layer=24, n_head=16, use_flash_attention=True))
    gc.collect()
    return out


def bench_serving_420m_fleet():
    """420M bf16 fleet: 3 replicas behind the prefix-affinity router, a
    shared-system-prompt trace, and one scripted warm failover — the fleet
    tail-latency / shed-rate / goodput_fleet row of the regression ledger."""
    import jax.numpy as jnp
    out = bench_serving_fleet_summary(
        dict(vocab_size=50304, n_positions=1024, n_embd=1024, n_layer=24,
             n_head=16, use_flash_attention=True),
        replicas=3, n_requests=32, num_slots=8, block_size=16, num_blocks=513,
        max_model_len=1024, prefill_chunk=128, param_dtype=jnp.bfloat16,
        shared_prefix=256, max_queue_depth=16, kills=((8, 0),))
    gc.collect()
    return out


def _zero2_step_fn(model, dp_shard):
    """jitted fwd+bwd + the 1/dp fp32 Adam-shard update of one ZeRO-2 rank."""
    import jax
    import jax.numpy as jnp

    def step(params, master, m1, m2, tokens, labels):
        loss, grads = jax.value_and_grad(lambda p: model.apply(p, tokens, labels))(params)
        # bf16 grads (the reference keeps fp16 grads under ZeRO-2); this rank's
        # 1/dp partition updates in fp32, exactly the per-chip ZeRO-2 optimizer work.
        # Per-leaf floor(size/dp) slices can sum short of total//dp when leaf sizes
        # aren't dp-divisible — pad to the master shard length.
        gflat = jnp.concatenate(
            [g.astype(jnp.bfloat16).reshape(-1)[: max(g.size // dp_shard, 1)]
             for g in jax.tree_util.tree_leaves(grads)])
        short = master.shape[0] - gflat.shape[0]
        if short > 0:
            gflat = jnp.pad(gflat, (0, short))
        gs = gflat[: master.shape[0]].astype(jnp.float32)
        m1n = 0.9 * m1 + 0.1 * gs
        m2n = 0.999 * m2 + 0.001 * gs * gs
        mastern = master - 1e-4 * m1n / (jnp.sqrt(m2n) + 1e-8)
        return loss, mastern, m1n, m2n

    return jax.jit(step, donate_argnums=(1, 2, 3))


def bench_1p5b():
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model

    DP = 32  # the target platform: v5e-32, ZeRO-2 shards the optimizer 32 ways
    # remat_policy="dots" (save matmul outputs, replay only elementwise ops in
    # backward): measured 0.46 MFU vs 0.39 under full recompute — the saved dots fit
    # HBM at batch 8 next to bf16 params+grads
    cfg = GPT2Config(vocab_size=50304, n_positions=1024, n_embd=1600, n_layer=48,
                     n_head=25, remat=True, remat_policy="dots",
                     use_flash_attention=True)
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    n_params = model.param_count(params)
    params = jax.device_put(
        jax.tree_util.tree_map(lambda p: p.astype(jnp.bfloat16), params))
    shard_n = sum(l.size for l in jax.tree_util.tree_leaves(params)) // DP
    master = jnp.zeros((shard_n,), jnp.float32)
    m1 = jnp.zeros((shard_n,), jnp.float32)
    m2 = jnp.zeros((shard_n,), jnp.float32)
    jstep = _zero2_step_fn(model, DP)

    rng = np.random.default_rng(0)
    B, T, steps = 8, 1024, 15  # 15: amortize the ~107 ms relay fence
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(B, T)), jnp.int32)
    labels = jnp.roll(tokens, -1, axis=1)
    loss, master, m1, m2 = jstep(params, master, m1, m2, tokens, labels)
    loss_v = _fence(loss)
    loss, master, m1, m2 = jstep(params, master, m1, m2, tokens, labels)
    _fence(loss)
    dt = float("inf")
    for _ in range(2):
        t0 = time.time()
        for _ in range(steps):
            loss, master, m1, m2 = jstep(params, master, m1, m2, tokens, labels)
        _fence(loss)
        dt = min(dt, time.time() - t0)
    tps = B * T * steps / dt
    mfu = tps * 6.0 * n_params / 1e12 / PEAK_TFLOPS
    del params, master, m1, m2
    gc.collect()
    return tps, mfu, n_params, loss_v


def probe_offload_footprint(n_layer):
    """Does a GPT-2(1600-wide, n_layer) ZeRO-Offload HBM footprint fit on this chip?
    bf16 params + bf16 grads + remat activations (master/moments are host-resident)."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
    cfg = GPT2Config(vocab_size=50304, n_positions=1024, n_embd=1600, n_layer=n_layer,
                     n_head=25, remat=True, use_flash_attention=True)
    model = GPT2Model(cfg)
    try:
        # allocate bf16 directly from abstract shapes: a real fp32 init would
        # transiently DOUBLE the param footprint and mask the true capacity
        shapes = jax.eval_shape(model.init, jax.random.PRNGKey(0))
        n_params = int(sum(int(np.prod(s.shape)) for s in jax.tree_util.tree_leaves(shapes)))
        params = jax.jit(lambda: jax.tree_util.tree_map(
            lambda s: jnp.full(s.shape, 0.01, jnp.bfloat16), shapes))()

        @jax.jit
        def fwd_bwd(p, tokens, labels):
            loss, grads = jax.value_and_grad(lambda pp: model.apply(pp, tokens, labels))(p)
            # bf16 grads, exactly what the offload engine materializes in HBM (the
            # host tier upcasts to fp32 in its landing buffer)
            return loss, jax.tree_util.tree_map(lambda g: g.astype(jnp.bfloat16), grads)

        tokens = jnp.zeros((4, 1024), jnp.int32)
        loss, grads = fwd_bwd(params, tokens, tokens)
        ok = bool(np.isfinite(_fence(loss)))
        del params, grads, loss
        gc.collect()
        return ok, n_params
    except Exception as e:  # XLA RESOURCE_EXHAUSTED (OOM) or similar
        gc.collect()
        sys.stderr.write(f"[bench] offload probe n_layer={n_layer}: {type(e).__name__}\n")
        return False, 0


def _probe_subprocess(n_layer):
    """Run one footprint probe in a FRESH process: an OOM'd probe leaves the relay
    backend unable to satisfy later (smaller) allocations in the same process."""
    import subprocess
    try:
        r = subprocess.run([sys.executable, os.path.abspath(__file__), "--probe",
                            str(n_layer)], capture_output=True, text=True, timeout=900)
        for line in r.stdout.splitlines():
            if line.startswith("PROBE_OK "):
                return True, int(line.split()[1])
    except subprocess.TimeoutExpired:
        # a hung probe must not lose the already-measured 420M/1.5B numbers
        sys.stderr.write(f"[bench] offload probe n_layer={n_layer}: timed out\n")
        return False, 0
    sys.stderr.write(f"[bench] offload probe n_layer={n_layer}: does not fit\n")
    return False, 0


def max_params_offload():
    """Binary-search the deepest 1600-wide GPT-2 whose offload footprint fits.

    Seeded at the round-2 measured boundary (128 layers fit, 132 did not) so the
    steady-state cost is two probes; falls back to the full search if the boundary
    moved (allocator/runtime changes)."""
    ok128, n128 = _probe_subprocess(128)
    if ok128:
        ok132, n132 = _probe_subprocess(132)
        if not ok132:
            return n128
        lo, best = 132, n132
    else:
        lo = 48
        ok, best = _probe_subprocess(lo)
        if not ok:
            return 0
    hi = 160  # analytic ceiling ~ (16GB - act) / (4 B/param * 30.7M/layer)
    ok_hi, hi_params = _probe_subprocess(hi)
    if ok_hi:
        return hi_params
    while hi - lo > 8:  # invariant: lo fits, hi does not
        mid = (lo + hi) // 2 // 4 * 4
        if mid <= lo:
            break
        ok, n = _probe_subprocess(mid)
        if ok:
            lo, best = mid, n
        else:
            hi = mid
    return best


def collect_workload_evidence():
    """Driver-visible workload/parity evidence (VERDICT r2 next #8): run the
    tests/model functional suite (8-virtual-device CPU mesh) and tests/tpu_parity.py
    (compiled-TPU kernel numerics) as subprocesses and fold pass/fail into the bench
    JSON, so rounds can't silently regress them. DS_BENCH_SKIP_WORKLOADS=1 skips."""
    import re
    import subprocess
    here = os.path.dirname(os.path.abspath(__file__))
    out = {}

    try:
        r = subprocess.run([sys.executable, os.path.join(here, "tests", "tpu_parity.py")],
                           capture_output=True, text=True, timeout=900, cwd=here)
        passed = r.returncode == 0 and "all TPU parity checks passed" in r.stdout
        out["tpu_parity"] = {"passed": bool(passed), "returncode": r.returncode,
                             "checks": r.stdout.count("PASS "),
                             "failures": r.stdout.count("FAIL ")}
    except subprocess.TimeoutExpired:
        out["tpu_parity"] = {"passed": False, "error": "timeout"}

    try:
        # 3600 s: the real-corpus convergence gate adds ~5 min of byte-level
        # training idle, ~3x that under concurrent compiles
        r = subprocess.run([sys.executable, "-m", "pytest", "tests/model", "-q"],
                           capture_output=True, text=True, timeout=3600, cwd=here)
        m = re.search(r"(\d+) passed", r.stdout)
        f = re.search(r"(\d+) failed", r.stdout)
        out["model_suite"] = {"passed": int(m.group(1)) if m else 0,
                              "failed": int(f.group(1)) if f else
                              (0 if r.returncode == 0 else -1),
                              "returncode": r.returncode}
    except subprocess.TimeoutExpired:
        out["model_suite"] = {"passed": 0, "failed": -1, "error": "timeout"}

    try:
        with open(os.path.join(here, "WORKLOADS.json"), "w") as fh:
            json.dump(out, fh)
    except OSError:
        pass
    return out


def _pipeline_goodput_probe(stages=4, micro=8, steps=2):
    """Post-window pipeline goodput probe (docs/pipeline-trace.md): build a tiny
    instruction-mode pipeline with span tracing on, run a couple of
    train_batches after a compile warmup, and report the measured bubble
    fraction next to the analytic simulator replayed at the measured mean
    fwd/bwd costs. Runs AFTER the headline timed window — the smoke tokens/s
    number is never measured with tracing enabled."""
    import jax
    import jax.numpy as jnp
    import deepspeed_tpu
    from deepspeed_tpu.parallel.pipe import LayerSpec, PipelineModule
    from deepspeed_tpu.utils.pipeline_trace import measured_costs, simulate_schedule

    hidden = 16

    class _Lin:
        def init(self, rng, x):
            return {"w": jax.random.normal(rng, (x.shape[-1], hidden), jnp.float32) * 0.3}

        def apply(self, params, x):
            return jnp.tanh(x @ params["w"].astype(x.dtype))

    def _mse(out, target):
        return jnp.mean(jnp.square(out.astype(jnp.float32) - target.astype(jnp.float32)))

    module = PipelineModule(layers=[LayerSpec(_Lin) for _ in range(stages)],
                            num_stages=stages, loss_fn=_mse)
    params = module.init_params(jax.random.PRNGKey(0), jnp.zeros((2, hidden), jnp.float32))
    world = jax.device_count()
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=module, model_parameters=params,
        config_params={"train_batch_size": 2 * micro * world,
                       "gradient_accumulation_steps": micro,
                       "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                       "pipeline": {"spmd": False},
                       "telemetry": {"pipeline_trace": {"enabled": True}}})
    rng = np.random.default_rng(0)

    def it():
        while True:
            x = rng.normal(size=(2 * world, hidden)).astype(np.float32)
            yield x, np.tanh(x)

    gen = it()
    for _ in range(steps + 1):  # first batch carries the stage-fn compiles
        eng.train_batch(gen)
    g = eng.pipe_trace.last_schedule_goodput
    t_fwd, t_bwd = measured_costs(eng.pipe_trace.steps[-1])
    sim = simulate_schedule(micro, stages, "train", t_fwd=t_fwd, t_bwd=t_bwd)
    return {"stages": stages, "micro_batches": micro,
            "measured_bubble_fraction": round(g["bubble_fraction"], 4),
            "simulated_bubble_fraction": round(sim["bubble_fraction"], 4),
            "analytic_uniform_bubble_fraction": round(
                (stages - 1) / (micro + stages - 1), 4),
            "per_stage_busy_seconds": [round(b, 6) for b in g["per_stage_busy_seconds"]],
            "fwd_seconds": round(g["fwd_seconds"], 6),
            "bwd_seconds": round(g["bwd_seconds"], 6),
            "p2p_seconds": round(g["p2p_seconds"], 6),
            "opt_seconds": round(g["opt_seconds"], 6),
            "straggler": g["straggler"]}


def main():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)) or ".")
    # Persistent compilation cache (works over the axon relay: measured 13.0s ->
    # 1.4s for a warm cross-process compile): the capacity probes and the engine
    # subprocess recompile the same 1.5B programs several times per bench run.
    import jax
    import tempfile
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(tempfile.gettempdir(),
                                   f"deepspeed_tpu_jax_cache_{os.getuid()}"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    if len(sys.argv) >= 3 and sys.argv[1] == "--probe":
        ok, n = probe_offload_footprint(int(sys.argv[2]))
        if ok:
            print(f"PROBE_OK {n}")
        return
    if len(sys.argv) >= 4 and sys.argv[1] == "--engine-1p5b":
        lc = int(sys.argv[4]) if len(sys.argv) >= 5 else 128
        tps, mfu = bench_1p5b_engine(remat_policy=sys.argv[2], batch=int(sys.argv[3]),
                                     loss_chunk=lc)
        print(f"ENGINE_OK {tps:.1f} {mfu:.4f}")
        return
    import jax
    on_tpu = jax.devices()[0].platform != "cpu"
    fast = os.environ.get("DS_BENCH_FAST", "0") == "1"

    if not on_tpu:  # CPU smoke mode: engine path only, tiny shapes
        import tempfile
        from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
        from deepspeed_tpu.runtime.engine import DeepSpeedEngine
        from deepspeed_tpu.parallel.mesh import build_mesh
        cfg = GPT2Config(vocab_size=512, n_positions=128, n_embd=128, n_layer=2, n_head=4)
        model = GPT2Model(cfg)
        params = model.init(jax.random.PRNGKey(0))
        B = max(4, jax.device_count())
        # the smoke engine carries telemetry directly: on CPU the per-step loss
        # fetch is cheap, and the smoke JSON doubles as a telemetry demo
        smoke_tel_dir = tempfile.mkdtemp(prefix="ds_bench_telemetry_")
        engine = DeepSpeedEngine(model=model, model_parameters=params,
                                 mesh=build_mesh(model=1, pipe=1),
                                 config_params={"train_batch_size": B,
                                                "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
                                                "zero_optimization": {"stage": 2},
                                                "telemetry": {"enabled": True,
                                                              "peak_tflops": PEAK_TFLOPS,
                                                              "output_path": smoke_tel_dir,
                                                              # trace window over the two
                                                              # clean post-window steps;
                                                              # the profile observatory
                                                              # ingests it so extra.profile
                                                              # carries the MEASURED
                                                              # decomposition beside
                                                              # anatomy's predicted one
                                                              "trace_steps": [3, 5],
                                                              "trace_dir": os.path.join(
                                                                  smoke_tel_dir, "trace"),
                                                              "profile": {"enabled": True},
                                                              # anatomy prices the same
                                                              # PEAK_TFLOPS so the MFU
                                                              # ceiling is comparable to
                                                              # the measured MFU below
                                                              "anatomy": {"enabled": True,
                                                                          "chip": "cpu-test",
                                                                          "peak_tflops": PEAK_TFLOPS}},
                                                "numerics": {"enabled": True,
                                                             "audit_interval": 2}})
        rng = np.random.default_rng(0)
        tokens = rng.integers(0, 512, size=(B, 64)).astype(np.int32)
        t0 = time.time()
        for _ in range(3):
            loss = engine(tokens, np.roll(tokens, -1, axis=1))
            engine.backward(loss)
            engine.step()
        _fence(loss)
        tps = B * 64 * 3 / (time.time() - t0)
        # two post-window steps: the timed window above pays the compiles
        # (warmup + donated-layout recompile + the audit program), so the
        # rolling MFU and the anatomy attribution — both of which only record
        # compile-free steps — need clean steps to have anything to report
        for _ in range(2):
            loss = engine(tokens, np.roll(tokens, -1, axis=1))
            engine.backward(loss)
            engine.step()
        _fence(loss)
        telemetry = engine.telemetry.summary()
        numerics = engine._numerics.summary() if engine._numerics is not None else None
        try:  # HBM ledger: per-class resident bytes + compile-reported temp peak
            from deepspeed_tpu.utils import hbm as _hbm
            _, class_bytes = _hbm.manifest_signatures(engine.memory_manifest())
            hbm_block = {"peak_by_class": {
                **{k: int(v) for k, v in class_bytes.items()},
                "compiled_temp_peak":
                    int(engine.telemetry.watchdog.peak_temp_bytes())}}
        except Exception as e:
            hbm_block = {"error": f"{type(e).__name__}: {e}"}
        engine.telemetry.close()
        try:  # instrumented post-window probe; headline window above stays untraced
            pipeline_goodput = _pipeline_goodput_probe()
        except Exception as e:
            pipeline_goodput = {"error": f"{type(e).__name__}: {e}"}
        try:  # serving summary rides after the training window, never inside it
            serving = bench_serving_smoke()
        except Exception as e:
            serving = {"error": f"{type(e).__name__}: {e}"}
        try:
            serving_prefix = bench_serving_prefix_cache_smoke()
        except Exception as e:
            serving_prefix = {"error": f"{type(e).__name__}: {e}"}
        try:
            serving_sharded = bench_serving_sharded_smoke()
        except Exception as e:
            serving_sharded = {"error": f"{type(e).__name__}: {e}"}
        try:
            serving_spec = bench_serving_speculative_smoke()
        except Exception as e:
            serving_spec = {"error": f"{type(e).__name__}: {e}"}
        try:
            serving_fleet = bench_serving_fleet_smoke()
        except Exception as e:
            serving_fleet = {"error": f"{type(e).__name__}: {e}"}
        try:
            resilience = bench_resilience_smoke()
        except Exception as e:
            resilience = {"error": f"{type(e).__name__}: {e}"}
        try:
            goodput = bench_goodput_smoke()
        except Exception as e:
            goodput = {"error": f"{type(e).__name__}: {e}"}
        anatomy = telemetry.get("anatomy") or {}
        result = {"metric": "gpt2_tokens_per_sec_per_chip_cpu_smoke",
                  "value": round(tps, 1), "unit": "tokens/s", "vs_baseline": 0.0,
                  "extra": {"telemetry": telemetry, "numerics": numerics,
                            # measured MFU and its roofline ceiling side by side
                            # (both priced at PEAK_TFLOPS; docs/anatomy.md)
                            "mfu_measured": telemetry.get("mfu"),
                            "mfu_ceiling": anatomy.get("mfu_ceiling"),
                            "anatomy_predicted_floor_ms":
                                anatomy.get("predicted_floor_ms"),
                            # measured-time decomposition of the traced window
                            # (None when the profiler backend is unavailable —
                            # telemetry.trace.failed above says why)
                            "profile": telemetry.get("profile"),
                            "pipeline_goodput": pipeline_goodput,
                            "serving": serving,
                            "serving_prefix_cache": serving_prefix,
                            "serving_sharded": serving_sharded,
                            "serving_speculative": serving_spec,
                            "serving_fleet": serving_fleet,
                            "resilience": resilience,
                            "goodput": goodput,
                            "hbm": hbm_block}}
        result["extra"]["regression_vs_previous_round"] = \
            regression_vs_previous_round(result)
        print(json.dumps(result))
        return

    extra = bench_420m()
    if fast:
        print(json.dumps({"metric": "gpt2_420m_tokens_per_sec_per_chip",
                          "value": extra["gpt2_420m_tokens_per_sec_per_chip"],
                          "unit": "tokens/s",
                          "vs_baseline": round(extra["gpt2_420m_mfu"] / 0.40, 4),
                          "extra": extra}))
        return

    tps, mfu, n_params, loss_v = bench_1p5b()
    extra.update({"gpt2_1p5b_mfu": round(mfu, 4),
                  "gpt2_1p5b_params": int(n_params),
                  "gpt2_1p5b_first_loss": round(loss_v, 3),
                  "gpt2_1p5b_note": ("fwd+bwd on full 1.5B bf16 params + 1/32 fp32 "
                                     "optimizer-shard update (one v5e-32 ZeRO-2 rank's "
                                     "per-chip work; cross-chip collectives excluded)")})
    # the same metric measured THROUGH DeepSpeedEngine (jitted engine paths +
    # donated-buffer update; the external-master shard optimizer keeps the dp=1
    # fp32 master off-HBM, matching a real rank's 1/32 footprint)
    e = _engine_1p5b_subprocess()
    extra.update({"gpt2_1p5b_engine_tokens_per_sec": round(e["tps"], 1),
                  "gpt2_1p5b_engine_mfu": round(e["mfu"], 4),
                  "gpt2_1p5b_engine_config": e["config"],
                  "gpt2_1p5b_engine_attempts": e["attempts"]})
    if "selection" in e:
        extra["gpt2_1p5b_engine_selection"] = e["selection"]
    if "mfu_spread" in e:
        extra["gpt2_1p5b_engine_mfu_spread"] = e["mfu_spread"]
    if e.get("pinned_config_failed"):
        extra["gpt2_1p5b_engine_pinned_config_failed"] = True
        if "fallback" in e:
            extra["gpt2_1p5b_engine_fallback"] = e["fallback"]
    try:
        extra["offload_step_timing"] = bench_offload_step_timing()
    except Exception as e:
        extra["offload_step_timing"] = {"error": f"{type(e).__name__}: {e}"}
    try:
        extra["decode_420m"] = bench_decode_420m()
    except Exception as e:
        extra["decode_420m"] = {"error": f"{type(e).__name__}: {e}"}
    try:  # continuous-batching serving summary (after the headline windows)
        extra["serving_420m"] = bench_serving_420m()
    except Exception as e:
        extra["serving_420m"] = {"error": f"{type(e).__name__}: {e}"}
    try:  # prefix-cache TTFT delta + hit-rate on a shared-prompt trace
        extra["serving_420m_prefix_cache"] = bench_serving_420m_prefix_cache()
    except Exception as e:
        extra["serving_420m_prefix_cache"] = {"error": f"{type(e).__name__}: {e}"}
    try:  # model-axis sharded decode tok/s
        extra["serving_420m_sharded"] = bench_serving_420m_sharded()
    except Exception as e:
        extra["serving_420m_sharded"] = {"error": f"{type(e).__name__}: {e}"}
    try:  # 420M-drafts-1.5B speculative serving (docs/serving.md)
        extra["serving_1p5b_spec"] = bench_serving_1p5b_spec()
    except Exception as e:
        extra["serving_1p5b_spec"] = {"error": f"{type(e).__name__}: {e}"}
    try:  # 3-replica fleet router: merged tails, shed rate, goodput_fleet
        extra["serving_fleet"] = bench_serving_420m_fleet()
    except Exception as e:
        extra["serving_fleet"] = {"error": f"{type(e).__name__}: {e}"}
    try:  # run-lifecycle goodput fraction + checkpoint badput share
        extra["goodput"] = bench_goodput_smoke()
    except Exception as e:
        extra["goodput"] = {"error": f"{type(e).__name__}: {e}"}
    mp = max_params_offload()
    extra["max_trainable_params_per_chip_zero_offload"] = int(mp)
    if os.environ.get("DS_BENCH_SKIP_WORKLOADS", "0") != "1":
        extra["workloads"] = collect_workload_evidence()
    result = {"metric": "gpt2_1p5b_zero2_tokens_per_sec_per_chip",
              "value": round(tps, 1), "unit": "tokens/s",
              "vs_baseline": round(mfu / 0.40, 4),
              "extra": extra}
    # round-over-round tok/s ledger vs the newest parseable BENCH_r*.json;
    # >5% drops are flagged by metric name (advisory — see the JSON block)
    extra["regression_vs_previous_round"] = regression_vs_previous_round(result)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
