"""Benchmark: GPT-2 training throughput on the real TPU chip.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

The metric is tokens/sec/chip for a ZeRO-2 GPT-2 train step at the largest config that
fits one v5e chip; vs_baseline is measured MFU / 0.40 (the BASELINE.json north-star of
>=40% MFU). v5e-lite peak is ~197 TFLOP/s bf16.
"""

import json
import sys
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    sys.path.insert(0, ".")
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine
    from deepspeed_tpu.parallel.mesh import build_mesh

    on_tpu = jax.devices()[0].platform != "cpu"
    if on_tpu:
        # GPT-2-family ~420M flagship (tied LM head) shaped for one v5e chip:
        # wider-shallower than the classic 1024x24 medium — 1536-wide matmuls keep the
        # MXU fed (measured 0.55 vs 0.41 MFU at 1024x24). remat OFF: flash attention +
        # seq-chunked fused CE keep residuals small enough that batch 16 of full
        # activations fits in HBM next to the fp32 Adam state.
        cfg = GPT2Config(vocab_size=50304, n_positions=1024, n_embd=1536, n_layer=12,
                         n_head=12, remat=False, use_flash_attention=True)
        batch, seq, steps = 16, 1024, 10
    else:  # CPU smoke mode
        cfg = GPT2Config(vocab_size=512, n_positions=128, n_embd=128, n_layer=2, n_head=4)
        batch, seq, steps = max(4, jax.device_count()), 64, 3

    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    n_params = model.param_count(params)

    mesh = build_mesh(model=1, pipe=1)
    ds_cfg = {
        "train_batch_size": batch,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "zero_optimization": {"stage": 2},
    }
    engine = DeepSpeedEngine(model=model, model_parameters=params, config_params=ds_cfg, mesh=mesh)

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, size=(batch, seq)).astype(np.int32)
    labels = np.roll(tokens, -1, axis=1)

    def step():
        loss = engine(tokens, labels)
        engine.backward(loss)
        engine.step()
        return loss

    # Two warmup steps: the first compiles, the second recompiles for donated-buffer
    # layouts. NOTE: on the axon relay platform block_until_ready/effects_barrier do NOT
    # fence execution — only device_get does, so we fence by pulling the loss scalar.
    step()
    loss = step()
    float(jax.device_get(loss))
    # Best of two timed loops: the shared tunnel chip shows ~10% run-to-run variance.
    dt = float("inf")
    for _ in range(2):
        t0 = time.time()
        for _ in range(steps):
            loss = step()
        float(jax.device_get(loss))
        dt = min(dt, time.time() - t0)

    tokens_per_sec = batch * seq * steps / dt
    # 6*N FLOPs per token (fwd+bwd) is the standard decoder estimate
    flops_per_token = 6.0 * n_params
    achieved_tflops = tokens_per_sec * flops_per_token / 1e12
    peak_tflops = 197.0 if on_tpu else 0.1
    mfu = achieved_tflops / peak_tflops

    print(json.dumps({
        "metric": "gpt2_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.40, 4),
    }))


if __name__ == "__main__":
    main()
