"""Install deepspeed_tpu.

Mirrors the reference's install-time provenance discipline (setup.py:19 version,
setup.py:300-324 git hash + ``git_version_info_installed.py`` with ``installed_ops``)
without its torch/CUDA extension builds: TPU kernels are Pallas/XLA (compiled by jax
at runtime) and the one C++ host op (cpu_adam) builds lazily on first use, so install
only records WHAT this host can serve, it does not compile anything.

    pip install -e .          # editable dev install
    pip install .             # regular install
"""

import os
import shutil
import subprocess

from setuptools import setup

HERE = os.path.dirname(os.path.abspath(__file__))


def read_version():
    with open(os.path.join(HERE, "version.txt")) as fd:
        return fd.read().strip()


def fetch_requirements(path):
    with open(os.path.join(HERE, path)) as fd:
        return [r.strip() for r in fd if r.strip() and not r.startswith(("#", "-r"))]


def git_info():
    def run(args):
        try:
            return subprocess.check_output(["git", *args], cwd=HERE,
                                           stderr=subprocess.DEVNULL).decode().strip()
        except (OSError, subprocess.CalledProcessError):
            return "unknown"

    return run(["rev-parse", "--short", "HEAD"]), run(["rev-parse", "--abbrev-ref", "HEAD"])


VERSION = read_version()
git_hash, git_branch = git_info()
version = f"{VERSION}+{git_hash}" if git_hash != "unknown" else VERSION

# What this host can serve (reference setup.py records which CUDA ops compiled;
# here the Pallas kernels always ship and cpu_adam needs a C++ toolchain at runtime)
installed_ops = {
    "cpu_adam": shutil.which("g++") is not None,
    "flash_attention": True,
    "block_sparse_attention": True,
    "transformer": True,
}

print(f"version={version}, git_hash={git_hash}, git_branch={git_branch}")
print(f"installed_ops={installed_ops}")
with open(os.path.join(HERE, "deepspeed_tpu", "git_version_info_installed.py"), "w") as fd:
    fd.write(f"version='{version}'\n")
    fd.write(f"git_hash='{git_hash}'\n")
    fd.write(f"git_branch='{git_branch}'\n")
    fd.write(f"installed_ops={installed_ops}\n")

setup(
    version=version,
    install_requires=fetch_requirements("requirements.txt"),
    extras_require={"dev": fetch_requirements("requirements-dev.txt")},
)
