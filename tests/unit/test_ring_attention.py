"""Ring attention (sequence parallelism) vs dense attention on the 8-device mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.ops.pallas.flash_attention import (dense_attention,
                                                      flash_attention_with_lse)
from deepspeed_tpu.parallel.mesh import build_mesh
from deepspeed_tpu.parallel.ring_attention import ring_attention_sharded

B, H, T, D = 2, 4, 256, 32


@pytest.fixture(scope="module")
def mesh():
    return build_mesh(data=8, model=1, pipe=1)


def qkv(seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (B, H, T, D), jnp.float32) for k in ks)


def test_flash_lse_matches_dense_logsumexp():
    q, k, v = qkv()
    out, lse = flash_attention_with_lse(q, k, v, interpret=True)
    import math
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(D)
    ref_lse = jax.scipy.special.logsumexp(scores, axis=-1)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse), rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense_attention(q, k, v)),
                               rtol=1e-5, atol=1e-5)


def test_flash_lse_cotangent_matches_autodiff():
    """grad through BOTH outputs (out and lse) must match dense autodiff — the lse
    cotangent is what makes the pure-JAX ring backward correct."""
    q, k, v = qkv(1)
    w = jax.random.normal(jax.random.PRNGKey(9), (B, H, T), jnp.float32)

    def loss_flash(q, k, v):
        out, lse = flash_attention_with_lse(q, k, v, interpret=True)
        return jnp.sum(out ** 2) + jnp.sum(w * lse)

    def loss_dense(q, k, v):
        import math
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(D)
        lse = jax.scipy.special.logsumexp(scores, axis=-1)
        out = dense_attention(q, k, v)
        return jnp.sum(out ** 2) + jnp.sum(w * lse)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gd, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5,
                                   err_msg=f"d{name}")


@pytest.mark.parametrize("causal", [False, pytest.param(True, marks=pytest.mark.slow)])
def test_ring_matches_dense(mesh, causal):
    q, k, v = qkv(2)
    out = ring_attention_sharded(q, k, v, mesh, causal=causal, interpret=True)
    ref = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)
    # really sequence-sharded over the ring axis
    assert not out.sharding.is_fully_replicated


# The causal-grads, dropout, and GPT-2 sequence-parallel integration tests below
# are the slow tail of this file (15-80s each on the 8-rank interpret mesh,
# compile-bound): marked `slow` so tier-1 finishes under the ROADMAP 870s cap
# instead of truncating. The fast parity tests above them keep ring attention
# exercised in every tier-1 run; the slow set runs via `-m slow` standalone.
@pytest.mark.parametrize("causal", [False, pytest.param(True, marks=pytest.mark.slow)])
def test_ring_grads_match_dense(mesh, causal):
    q, k, v = qkv(3)
    g = jax.random.normal(jax.random.PRNGKey(7), (B, H, T, D), jnp.float32)
    spec = NamedSharding(mesh, P(None, None, "data", None))
    g = jax.device_put(g, spec)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention_sharded(q, k, v, mesh, causal=causal,
                                              interpret=True) * g)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=causal) * g)

    gr = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    gd = jax.jit(jax.grad(loss_dense, argnums=(0, 1, 2)))(q, k, v)
    for a, b, name in zip(gr, gd, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5,
                                   err_msg=f"d{name} (causal={causal})")


@pytest.mark.slow
def test_ring_memory_is_chunked(mesh):
    """The per-chunk flash only ever sees [T/n]-sized operands: a sequence whose
    FULL [T, T] score matrix would be enormous still runs (no O(T^2) anywhere)."""
    T_big = 1024  # scores would be [1024, 1024] per (b, h) — chunk kernel sees 128
    ks = jax.random.split(jax.random.PRNGKey(4), 3)
    q, k, v = (jax.random.normal(kk, (1, 2, T_big, D), jnp.float32) for kk in ks)
    out = ring_attention_sharded(q, k, v, mesh, interpret=True)
    ref = dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_gpt2_sequence_parallel_matches_dense(mesh):
    """GPT-2 with with_sequence_parallel over 8 ranks: loss AND grads equal the
    plain dense model on the full sequence (positions offset per rank, ring
    attention, pmean'd token loss)."""
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model

    cfg = GPT2Config(vocab_size=128, n_positions=128, n_embd=32, n_layer=2, n_head=2,
                     compute_dtype=jnp.float32)
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    toks = rng.integers(0, cfg.vocab_size, size=(2, 128)).astype(np.int32)
    labels = np.roll(toks, -1, axis=1)  # global shift BEFORE sharding

    sp_loss = model.sequence_parallel_loss_fn(mesh, "data")
    l_sp = jax.jit(sp_loss)(params, jnp.asarray(toks), jnp.asarray(labels))
    l_ref = model.apply(params, jnp.asarray(toks), jnp.asarray(labels))
    np.testing.assert_allclose(float(l_sp), float(l_ref), rtol=2e-5)

    g_sp = jax.jit(jax.grad(sp_loss))(params, jnp.asarray(toks), jnp.asarray(labels))
    g_ref = jax.grad(model.apply)(params, jnp.asarray(toks), jnp.asarray(labels))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                rtol=1e-3, atol=1e-5),
        g_sp, g_ref)


@pytest.mark.slow
def test_gpt2_sequence_parallel_trains_through_engine(mesh):
    """The packaged model_fn drives DeepSpeedEngine end to end (seq sharded over
    the data axis; params replicated; loss decreases)."""
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model

    cfg = GPT2Config(vocab_size=64, n_positions=64, n_embd=32, n_layer=2, n_head=2,
                     compute_dtype=jnp.float32)
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine

    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(1))
    model_fn = model.sequence_parallel_loss_fn(mesh, "data")
    engine = DeepSpeedEngine(
        model=model_fn, model_parameters=params, mesh=mesh,
        config_params={"train_batch_size": 8, "train_micro_batch_size_per_gpu": 1,
                       "gradient_accumulation_steps": 1, "steps_per_print": 100,
                       "optimizer": {"type": "Adam", "params": {"lr": 3e-3}}})
    rng = np.random.default_rng(2)
    losses = []
    toks = rng.integers(0, 64, size=(2, 64)).astype(np.int32)
    labels = np.roll(toks, -1, axis=1)
    # the 'data' axis carries the SEQUENCE here: pre-shard inputs on dim 1 (the
    # engine's shard_batch default of dim-0-over-data doesn't apply)
    spec = NamedSharding(mesh, P(None, "data"))
    toks_d = jax.device_put(jnp.asarray(toks), spec)
    labels_d = jax.device_put(jnp.asarray(labels), spec)
    for _ in range(30):
        loss = engine(toks_d, labels_d)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])


@pytest.mark.slow
@pytest.mark.parametrize("causal", [False, True])
def test_ring_dropout_matches_global_oracle(mesh, causal):
    """Attention dropout under the ring: every rank hashes GLOBAL coordinates, so
    the 8-shard ring must equal dense attention with the whole-sequence oracle
    mask — fwd and grads (VERDICT r3 #4)."""
    from deepspeed_tpu.ops.pallas.flash_attention import dropout_keep_reference
    rate, seed = 0.2, 1234
    q, k, v = qkv(5)
    keep = dropout_keep_reference(seed, B, H, T, T, rate)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention_sharded(q, k, v, mesh, causal=causal,
                                              interpret=True, dropout_rate=rate,
                                              dropout_seed=seed) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=causal,
                                       dropout_keep=keep) ** 2)

    np.testing.assert_allclose(float(jax.jit(loss_ring)(q, k, v)),
                               float(loss_dense(q, k, v)), rtol=2e-5)
    gr = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gr, gd, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5,
                                   err_msg=f"d{name} (causal={causal})")


@pytest.mark.slow
def test_gpt2_sequence_parallel_dropout_trains(mesh):
    """Dropout under sequence parallelism (round 4): the ring threads a shared seed
    (global-coordinate attention masks) and hidden dropout folds the rank into its
    key. Same rng -> identical loss; different rng -> different loss; grads finite;
    no-rng path stays the deterministic one."""
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model

    cfg = GPT2Config(vocab_size=64, n_positions=64, n_embd=32, n_layer=2, n_head=2,
                     compute_dtype=jnp.float32, dropout=0.2)
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(4)
    toks = jnp.asarray(rng.integers(0, 64, size=(2, 64)).astype(np.int32))
    labels = jnp.roll(toks, -1, axis=1)
    loss_fn = model.sequence_parallel_loss_fn(mesh, "data")

    l1 = float(jax.jit(loss_fn)(params, toks, labels, jax.random.PRNGKey(5)))
    l1b = float(jax.jit(loss_fn)(params, toks, labels, jax.random.PRNGKey(5)))
    l2 = float(jax.jit(loss_fn)(params, toks, labels, jax.random.PRNGKey(6)))
    assert l1 == l1b, "same rng must reproduce the same masks"
    assert l1 != l2, "different rng must sample different masks"
    l_det = float(jax.jit(loss_fn)(params, toks, labels))
    ref = float(model.apply(params, toks, labels))
    np.testing.assert_allclose(l_det, ref, rtol=2e-5)

    g = jax.jit(jax.grad(lambda p: loss_fn(p, toks, labels, jax.random.PRNGKey(7))))(params)
    assert all(bool(jnp.isfinite(x).all()) for x in jax.tree_util.tree_leaves(g))
