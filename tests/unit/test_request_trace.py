"""Serving request observatory tests: per-request lifecycle ledger coverage,
TTFT single-sourcing (RequestOutput == ledger record), exact preemption-waste
decomposition, SLO classification + serve-sim gate, Serving/Latency/* scalars
through TelemetrySession, the HLO-identity/zero-recompile guarantee when the
trace block toggles, flight-recorder embedding, and the Perfetto exporter
(64-request golden byte stability + CLI round trips).
"""

import ast
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
from deepspeed_tpu.serve.engine import InferenceEngine
from deepspeed_tpu.serve.request_trace import (RequestTracer,
                                               StreamingHistogram,
                                               serve_timeline_main,
                                               to_serve_trace_events)
from deepspeed_tpu.serve.scheduler import Request
from deepspeed_tpu.serve.sim import main as sim_main
from deepspeed_tpu.utils.hlo import instruction_count, optimized_hlo
from deepspeed_tpu.utils.pipeline_trace import serialize_trace
from deepspeed_tpu.utils.telemetry import TelemetrySession

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
GOLDEN = os.path.join(os.path.dirname(os.path.abspath(__file__)), "golden",
                      "serve_timeline_64.trace.json")

ML = 32


@pytest.fixture(scope="module")
def model_and_params():
    cfg = GPT2Config(vocab_size=64, n_positions=ML, n_embd=16, n_layer=2,
                     n_head=2, compute_dtype=jnp.float32, loss_chunk=0)
    model = GPT2Model(cfg)
    return model, model.init(jax.random.PRNGKey(0))


def _engine(model_and_params, trace=True, **kw):
    model, params = model_and_params
    kw.setdefault("num_slots", 4)
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 33)
    kw.setdefault("max_model_len", ML)
    kw.setdefault("prefill_chunk", 8)
    if trace is True:
        trace = {"enabled": True}
    return InferenceEngine(model, params, request_trace=trace, **kw)


def _prompt(seed, n):
    return np.random.RandomState(seed).randint(0, 64, size=n).astype(np.int32).tolist()


def _starved(model_and_params, **kw):
    """Short prompts, long generations, a 12-page pool: every group reaches
    decode and then the pool starves -> decode-phase preempt-by-recompute."""
    reqs = [Request(f"r{i}", _prompt(10 + i, 8), 20) for i in range(4)]
    eng = _engine(model_and_params, num_blocks=13, **kw)
    return eng, reqs


# ------------------------------------------------------------------ histogram


def test_streaming_histogram_percentiles():
    h = StreamingHistogram()
    assert h.percentile(50) is None and h.mean is None
    values = [float(v) for v in range(1, 1001)]
    for v in values:
        h.add(v)
    h.add(None)                         # ignored, not counted
    assert h.count == 1000
    for p in (50, 90, 95, 99):
        exact = values[int(p / 100 * len(values)) - 1]
        got = h.percentile(p)
        assert got >= exact, (p, got, exact)      # never understates a tail
        assert got <= exact * 1.07, (p, got, exact)
    assert h.mean == pytest.approx(sum(values) / len(values))
    assert set(h.percentiles((50, 99))) == {"p50", "p99"}


# ----------------------------------------------------------- ledger lifecycle


def test_tracer_disabled_by_default(model_and_params):
    eng = _engine(model_and_params, trace=None)
    assert eng.tracer is None
    outs, _ = eng.run([Request("r0", _prompt(0, 11), 5)])
    assert outs[0].status == "finished"         # untraced path still serves


def test_ledger_covers_the_lifecycle(model_and_params):
    reqs = [Request("g0", _prompt(1, 11), 5),
            Request("g1", _prompt(2, 6), 4, arrival=2),
            Request("b0", _prompt(3, 9), 4, num_beams=4)]
    eng = _engine(model_and_params)
    outs, _ = eng.run(reqs)
    tr = eng.tracer
    assert not tr.live and tr.finished == 3
    recs = {r["req_id"]: r for r in tr.requests}
    for req in reqs:
        rec = recs[req.req_id]
        names = [e[0] for e in rec["events"]]
        assert names[0] == "submit" and names[-1] == "finish"
        assert "admit" in names and "first_token" in names
        # prefill chunks tile the prompt exactly, in order
        chunks = [(e[3], e[4]) for e in rec["events"] if e[0] == "prefill"]
        covered = 0
        for pos, n in chunks:
            assert pos == covered
            covered += n
        assert covered == len(req.prompt)
        # one decode membership event per generated token after the first
        n_decodes = sum(1 for e in rec["events"] if e[0] == "decode")
        assert n_decodes == req.max_new_tokens - 1
        assert rec["n_tokens"] == req.max_new_tokens
        assert rec["e2e_iters"] == rec["finished_it"] - req.arrival
        assert rec["queue_delay_iters"] >= 0
        assert rec["ttft_ms"] > 0 and rec["e2e_ms"] >= rec["ttft_ms"]
        assert rec["tpot_ms"] > 0
    # the beam group records its CoW table fork with its lane count
    forks = [e for e in recs["b0"]["events"] if e[0] == "fork"]
    assert [e[3] for e in forks] == [4]
    assert not [e for e in recs["g0"]["events"] if e[0] == "fork"]
    # latency percentile API exposes every populated metric
    pcts = tr.percentiles(ps=(50, 95, 99))
    for metric in ("ttft_ms", "tpot_ms", "queue_delay_ms", "e2e_ms"):
        assert set(pcts[metric]) == {"p50", "p95", "p99"}, metric


def test_capacity_bounds_the_rings(model_and_params):
    reqs = [Request(f"r{i}", _prompt(i, 5), 2) for i in range(5)]
    eng = _engine(model_and_params, trace={"enabled": True, "capacity": 2,
                                           "iteration_capacity": 3})
    eng.run(reqs)
    tr = eng.tracer
    assert len(tr.requests) == 2 and tr.finished == 5   # ring bounded, counts not
    assert len(tr.iterations) == 3


def test_refusal_recorded(model_and_params):
    eng = _engine(model_and_params)
    out = eng.submit(Request("huge", _prompt(0, ML), ML))
    assert out.status == "refused"
    rec = eng.tracer.requests[-1]
    assert rec["req_id"] == "huge" and rec["status"] == "refused"
    ev = [e for e in rec["events"] if e[0] == "refused"]
    assert len(ev) == 1 and "max_model_len" in ev[0][3]
    assert eng.tracer.refused == 1


# ------------------------------------------------------- TTFT single-sourcing


def test_ttft_single_source_regression(model_and_params):
    """Satellite: RequestOutput's ttft fields and the ledger record must be
    THE SAME numbers (both read one on_first_token computation), and the
    iteration-domain values must match an untraced engine's independent
    bookkeeping on the same seeded trace."""
    def mk():
        return [Request(f"r{i}", _prompt(20 + i, 7 + i), 4 + i,
                        arrival=i) for i in range(4)]
    eng = _engine(model_and_params)
    outs, _ = eng.run(mk())
    recs = {r["req_id"]: r for r in eng.tracer.requests}
    for o in outs:
        rec = recs[o.req_id]
        assert o.ttft_ms == rec["ttft_ms"]
        assert o.ttft_iters == rec["ttft_iters"]
        assert o.finished_it == rec["finished_it"]
        assert o.preemptions == rec["preemptions"]
    eng_off = _engine(model_and_params, trace=None)
    outs_off, _ = eng_off.run(mk())
    assert [o.ttft_iters for o in outs] == [o.ttft_iters for o in outs_off]
    assert [o.finished_it for o in outs] == [o.finished_it for o in outs_off]


# ------------------------------------------------------------ waste accounting


def test_preemption_waste_sums_exactly(model_and_params):
    """Acceptance: the useful/replayed split covers every scheduled token with
    no residue, decode-phase preemptions bill their recompute as replay, and
    the evicted-block counts ride the preempt events."""
    eng, reqs = _starved(model_and_params)
    outs, logs = eng.run(reqs)
    tr = eng.tracer
    assert sum(o.preemptions for o in outs) > 0
    ws = tr.waste_summary()
    sched_prefill = sum(l["prefill"][2] for l in logs if l["prefill"])
    sched_decode = sum(len(l["decode"]) for l in logs)
    assert ws["prefill_tokens"] == sched_prefill
    assert ws["decode_tokens"] == sched_decode
    assert ws["useful_tokens"] + ws["replayed_tokens"] == ws["scheduled_tokens"]
    assert ws["scheduled_tokens"] == sched_prefill + sched_decode
    assert ws["replayed_tokens"] > 0 and 0.0 < ws["waste_fraction"] < 1.0
    # useful decode work = every kept token except the prefill-sampled first
    assert (ws["decode_tokens"] - ws["decode_replayed"]
            == sum(len(o.tokens) - 1 for o in outs))
    # useful prefill work = each prompt exactly once
    assert (ws["prefill_tokens"] - ws["prefill_replayed"]
            == sum(len(r.prompt) for r in reqs))
    evicted = [e[3] for r in tr.requests for e in r["events"]
               if e[0] == "preempt"]
    assert evicted and all(n > 0 for n in evicted)
    # per-iteration timeline agrees with the global totals
    its = list(tr.iterations)
    assert sum(i["prefill"][0] + i["prefill"][1] for i in its) == sched_prefill
    assert sum(i["decode"][0] + i["decode"][1] for i in its) == sched_decode
    for i in its:
        pool = i["pool"]
        assert pool["free"] + pool["used"] == eng.num_blocks - 1
        assert 0.0 <= pool["frag"] <= 1.0


def test_pool_timeline_tracks_allocator_counters(model_and_params):
    eng = _engine(model_and_params)
    eng.run([Request("b0", _prompt(5, 9), 6, num_beams=4)])
    alloc = eng.scheduler.allocator
    assert alloc.fork_count > 0                 # beam table forks happened
    assert alloc.alloc_count >= alloc.free_count
    st = alloc.stats()
    assert st["cow_copies"] == alloc.cow_copies
    last_pool = list(eng.tracer.iterations)[-1]["pool"]
    assert last_pool["cow_copies"] == alloc.cow_copies


# -------------------------------------------------------------------- the SLO


def test_slo_classification(model_and_params):
    reqs = [Request(f"r{i}", _prompt(i, 6), 3) for i in range(3)]
    eng = _engine(model_and_params,
                  trace={"enabled": True, "slo": {"ttft_ms": 1e-6}})
    eng.run(reqs)
    s = eng.tracer.slo_summary()
    assert s["configured"] == {"ttft_ms": 1e-6}
    assert s["violated"] == 3 and s["met"] == 0 and s["attainment"] == 0.0
    assert all(r["slo_violations"] == ["ttft_ms"]
               for r in eng.tracer.requests)

    eng2 = _engine(model_and_params,
                   trace={"enabled": True, "slo": {"ttft_ms": 1e9,
                                                   "tpot_ms": 1e9}})
    eng2.run([Request(f"r{i}", _prompt(i, 6), 3) for i in range(3)])
    s2 = eng2.tracer.slo_summary()
    assert s2["met"] == 3 and s2["violated"] == 0 and s2["attainment"] == 1.0

    # 0-valued thresholds mean "not gated", not "always violated"
    eng3 = _engine(model_and_params,
                   trace={"enabled": True, "slo": {"ttft_ms": 0.0}})
    eng3.run([Request("r0", _prompt(0, 6), 3)])
    assert eng3.tracer.slo_summary()["configured"] == {}
    assert eng3.tracer.slo_summary()["attainment"] is None


# -------------------------------------------------------- telemetry + scalars


def test_latency_scalars_flow_through_telemetry(tmp_path, model_and_params):
    session = TelemetrySession(output_path=str(tmp_path), job_name="rt_test")
    model, params = model_and_params
    eng = InferenceEngine(model, params, num_slots=4, block_size=4,
                          num_blocks=33, max_model_len=ML, prefill_chunk=8,
                          telemetry=session, request_trace={"enabled": True})
    eng.run([Request(f"r{i}", _prompt(i, 7), 4) for i in range(3)])
    session.close()
    scalars = open(os.path.join(str(tmp_path), "rt_test",
                                "scalars.jsonl")).read()
    for name in ("Serving/Latency/ttft_ms_p50", "Serving/Latency/ttft_ms_p99",
                 "Serving/Latency/tpot_ms_p90",
                 "Serving/Latency/queue_delay_ms_p50",
                 "Serving/Latency/e2e_ms_p50",
                 "Serving/Waste/replayed_tokens", "Serving/Waste/fraction",
                 "Serving/Pool/fragmentation"):
        assert name in scalars, name


# --------------------------------------------------------------- HLO identity


def test_hlo_identical_and_zero_recompiles_when_toggled(tmp_path,
                                                        model_and_params):
    """Acceptance: the trace block changes NOTHING on device — decode/prefill/
    beam programs of a traced engine are instruction-identical to an untraced
    one, and a traced run recompiles nothing after warmup (watchdog)."""
    model, params = model_and_params
    eng_off = _engine(model_and_params, trace=None)
    eng_on = _engine(model_and_params)
    S, MB, C = eng_off.num_slots, eng_off.max_blocks, eng_off.prefill_chunk
    zs = jnp.zeros(S, jnp.int32)
    decode_args = (params, zs, zs, jnp.zeros((S, MB), jnp.int32),
                   jnp.zeros(S, bool), eng_off.k_pool, eng_off.v_pool)
    prefill_args = (params, jnp.zeros((1, C), jnp.int32), jnp.int32(0),
                    jnp.int32(1), jnp.zeros(MB, jnp.int32),
                    eng_off.k_pool, eng_off.v_pool)
    for name, a_fn, b_fn, fargs in (
            ("decode", eng_off._raw["decode_step"],
             eng_on._raw["decode_step"], decode_args),
            ("prefill", eng_off._raw["prefill_chunk"],
             eng_on._raw["prefill_chunk"], prefill_args)):
        h_off = optimized_hlo(a_fn, *fargs)
        h_on = optimized_hlo(b_fn, *fargs)
        assert instruction_count(h_off) > 0
        assert instruction_count(h_off) == instruction_count(h_on), name
    beam_off = eng_off._raw["beam_init"](4, -1)
    beam_on = eng_on._raw["beam_init"](4, -1)
    logits = jnp.zeros((1, model.config.vocab_size), jnp.float32)
    assert (instruction_count(optimized_hlo(beam_off, logits))
            == instruction_count(optimized_hlo(beam_on, logits))), "beam"

    session = TelemetrySession(output_path=str(tmp_path), job_name="rt_watch")
    eng_w = _engine(model_and_params, telemetry=session)
    eng_w.run([Request(f"r{i}", _prompt(i, 9), 5) for i in range(4)]
              + [Request("b0", _prompt(9, 9), 4, num_beams=2)])
    for prog in session.watchdog.records:
        if prog.startswith("serve:"):
            assert session.watchdog.recompiles(prog) == 0, prog
    session.close()


def test_request_trace_module_is_stdlib_pure():
    """The ledger must never be able to block the device: no numpy, no jax —
    only stdlib — so the HostSyncPass sweep (test_no_sync_guard) covers every
    primitive it could possibly call."""
    path = os.path.join(REPO, "deepspeed_tpu", "serve", "request_trace.py")
    tree = ast.parse(open(path).read())
    mods = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            mods.update(a.name.split(".")[0] for a in node.names)
        elif isinstance(node, ast.ImportFrom) and node.level == 0:
            mods.add((node.module or "").split(".")[0])
    assert "numpy" not in mods and "jax" not in mods, sorted(mods)


# ------------------------------------------------- flight recorder embedding


def test_flight_recorder_embeds_ledger(tmp_path, model_and_params):
    from deepspeed_tpu.utils.numerics import FlightRecorder

    eng = _engine(model_and_params)
    eng.run([Request("r0", _prompt(0, 9), 4)])
    rec = FlightRecorder(dump_dir=str(tmp_path), request_trace=eng.tracer)
    path = rec.trigger("manual_test")
    bundle = json.load(open(path))
    embedded = bundle["serving_request_trace"]
    assert embedded["kind"] == "serving_request_trace"
    assert embedded["counts"]["finished"] == 1
    # serve-timeline resolves the flight-recorder dump directly
    out = os.path.join(str(tmp_path), "dump.trace.json")
    assert serve_timeline_main([path, "-o", out]) == 0
    trace = json.load(open(out))
    assert any(e["ph"] == "X" for e in trace["traceEvents"])


# ------------------------------------------------------------ Perfetto export


@pytest.fixture(scope="module")
def seeded_64_artifacts(tmp_path_factory):
    """One seeded default 64-request serve-sim run shared by the golden and
    report tests (the acceptance trace; ~10 s with the oracle off)."""
    d = tmp_path_factory.mktemp("serve64")
    ledger = os.path.join(str(d), "ledger.json")
    report = os.path.join(str(d), "report.json")
    rc = sim_main(["--no-mirror", "--dump-ledger", ledger,
                   "--json", report, "--output", os.path.join(str(d), "tel")])
    assert rc == 0
    return ledger, report


def test_perfetto_export_matches_golden(seeded_64_artifacts):
    """Acceptance: the seeded 64-request serve-sim trace exports to Perfetto
    JSON byte-for-byte equal to the committed golden file."""
    ledger, _ = seeded_64_artifacts
    bundle = json.load(open(ledger))
    data = serialize_trace(to_serve_trace_events(bundle))
    assert data == serialize_trace(to_serve_trace_events(bundle))  # stable
    golden = open(GOLDEN).read()
    assert data == golden
    trace = json.loads(data)
    tids = {e["tid"] for e in trace["traceEvents"]
            if e["ph"] == "M" and e["name"] == "thread_name"}
    assert len(tids) == 64                       # one track per request
    counters = {e["name"] for e in trace["traceEvents"] if e["ph"] == "C"}
    assert {"pool occupancy", "waiting queue", "waste fraction",
            "free blocks", "pool fragmentation"} <= counters
    cats = {e.get("cat") for e in trace["traceEvents"] if e["ph"] == "X"}
    assert {"queued", "prefill", "decode"} <= cats


def test_serve_sim_json_report(seeded_64_artifacts):
    ledger, report = seeded_64_artifacts
    rep = json.load(open(report))
    assert rep["kind"] == "serve_sim_report" and not rep["failures"]
    det = rep["deterministic"]
    assert det["n_finished"] == 64 and len(det["requests"]) == 64
    w = det["waste"]
    assert w["useful_tokens"] + w["replayed_tokens"] == w["scheduled_tokens"]
    for row in det["requests"]:
        assert row["status"] == "finished"
        assert row["ttft_iters"] >= 0 and row["e2e_iters"] >= row["ttft_iters"]
    assert "percentiles" in rep["wall"] and "slo" in rep["wall"]


def test_serve_sim_json_deterministic_subtree(tmp_path):
    """The report's `deterministic` subtree is byte-stable across fresh runs
    (CI diffs it, mirroring `ds-tpu lint --json`)."""
    blobs = []
    for i in range(2):
        p = os.path.join(str(tmp_path), f"rep{i}.json")
        rc = sim_main(["--requests", "12", "--max-model-len", "64",
                       "--block-size", "8", "--num-blocks", "33",
                       "--slots", "4", "--prefill-chunk", "16", "--no-mirror",
                       "--json", p,
                       "--output", os.path.join(str(tmp_path), f"tel{i}")])
        assert rc == 0
        blobs.append(json.dumps(json.load(open(p))["deterministic"],
                                sort_keys=True))
    assert blobs[0] == blobs[1]


def test_serve_sim_slo_gate_fails_nonzero(tmp_path, capsys):
    """Acceptance: a configured-but-violated SLO exits serve-sim nonzero."""
    rc = sim_main(["--requests", "6", "--max-model-len", "64",
                   "--block-size", "8", "--num-blocks", "33", "--slots", "4",
                   "--prefill-chunk", "16", "--no-mirror",
                   "--slo-ttft-ms", "1e-6",
                   "--output", os.path.join(str(tmp_path), "tel")])
    assert rc == 1
    assert "SLO violated" in capsys.readouterr().err


def test_serve_timeline_cli_subprocess(tmp_path, model_and_params):
    """The shipped `ds-tpu serve-timeline` entry converts a dumped ledger end
    to end (pure-host dispatch — no accelerator pinning needed)."""
    eng = _engine(model_and_params)
    eng.run([Request(f"r{i}", _prompt(i, 7), 4) for i in range(3)])
    path = os.path.join(str(tmp_path), "ledger.json")
    eng.tracer.dump(path)
    env = dict(os.environ,
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "ds-tpu"),
         "serve-timeline", path],
        capture_output=True, text=True, env=env, timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "trace events" in proc.stdout
    trace = json.load(open(path[:-5] + ".trace.json"))
    assert trace["otherData"]["generator"] == "ds-tpu serve-timeline"
    assert trace["traceEvents"]


def test_serve_timeline_rejects_traceless_input(tmp_path, capsys):
    path = os.path.join(str(tmp_path), "not_a_bundle.json")
    json.dump({"steps": [], "kind": "something_else"}, open(path, "w"))
    assert serve_timeline_main([path]) == 2
    assert "no serving_request_trace bundle" in capsys.readouterr().out


def test_dump_and_atexit_path(tmp_path, model_and_params):
    eng = _engine(model_and_params,
                  trace={"enabled": True, "dump_dir": str(tmp_path)})
    eng.run([Request("r0", _prompt(0, 9), 4)])
    path = eng.tracer.dump()
    assert path == os.path.join(str(tmp_path), "request_trace_host0.json")
    bundle = json.load(open(path))
    assert bundle["kind"] == "serving_request_trace"
    assert len(bundle["requests"]) == 1
