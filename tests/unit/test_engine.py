"""End-to-end engine tests on the 8-device virtual CPU mesh (reference test_fp16.py style)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from simple_model import SimpleModel, random_dataset, simple_config

HIDDEN = 16


def run_training(config, steps=10, hidden=HIDDEN, seed=0):
    model = SimpleModel(hidden)
    params = model.init(jax.random.PRNGKey(seed))
    data = random_dataset(256, hidden, seed=seed)
    engine, optimizer, loader, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, training_data=data, config_params=config)
    losses = []
    it = iter(loader)
    for _ in range(steps * engine.gradient_accumulation_steps()):
        x, y = next(it)
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    return engine, losses


@pytest.mark.parametrize("zero_stage", [0, 1, 2])
def test_zero_stage_training_loss_decreases(zero_stage):
    cfg = simple_config(zero_optimization={"stage": zero_stage})
    engine, losses = run_training(cfg, steps=20)
    assert losses[-1] < losses[0] * 0.9, f"loss did not decrease: {losses[0]} -> {losses[-1]}"
    assert engine.global_steps == 20


def test_zero_stages_agree():
    """Stages 0/1/2 are different layouts of the same math: losses must match closely."""
    results = {}
    for stage in [0, 1, 2]:
        cfg = simple_config(zero_optimization={"stage": stage})
        _, losses = run_training(cfg, steps=5, seed=3)
        results[stage] = losses
    for stage in [1, 2]:
        np.testing.assert_allclose(results[0], results[stage], rtol=2e-2)


def test_gradient_accumulation():
    cfg = simple_config(batch=16, gradient_accumulation_steps=2)
    engine, losses = run_training(cfg, steps=5)
    assert engine.gradient_accumulation_steps() == 2
    assert engine.global_steps == 5
    assert engine.micro_steps == 10


def test_grad_accum_equivalence():
    """grad_acc=2 at micro-batch 8 must match grad_acc=1 at batch 16 (same total batch)."""
    model = SimpleModel(HIDDEN)
    params = model.init(jax.random.PRNGKey(0))
    data = random_dataset(64, HIDDEN, seed=1)

    def run(cfg):
        p = jax.tree_util.tree_map(jnp.array, params)
        engine, _, loader, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=p, training_data=data, config_params=cfg)
        xs = np.stack([data[i][0] for i in range(16)])
        ys = np.stack([data[i][1] for i in range(16)])
        if engine.gradient_accumulation_steps() == 1:
            loss = engine(xs, ys)
            engine.backward(loss)
            engine.step()
        else:
            for half in range(2):
                x = xs[half * 8:(half + 1) * 8]
                y = ys[half * 8:(half + 1) * 8]
                loss = engine(x, y)
                engine.backward(loss)
                engine.step()
        return jax.device_get(engine.master_params)

    p_full = run(simple_config(batch=16))
    p_acc = run(simple_config(batch=16, gradient_accumulation_steps=2))
    jax.tree_util.tree_map(lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5),
                           p_full, p_acc)


def test_fp16_dynamic_loss_scale_init():
    cfg = simple_config(fp16={"enabled": True, "initial_scale_power": 8})
    engine, losses = run_training(cfg, steps=25)
    assert engine.fp16_enabled()
    assert engine.dynamic_loss_scale()
    assert losses[-1] < losses[0]


def test_fp16_static_loss_scale():
    cfg = simple_config(fp16={"enabled": True, "loss_scale": 128.0})
    engine, losses = run_training(cfg, steps=25)
    assert engine.loss_scale() == 128.0
    assert losses[-1] < losses[0]


def test_lamb_optimizer():
    """LAMB's trust ratio shrinks small-model updates; like the reference's lamb tests we
    check stable execution + that parameters actually move, not convergence speed."""
    cfg = simple_config(optimizer={"type": "Lamb", "params": {"lr": 2e-3}})
    model = SimpleModel(HIDDEN)
    params = model.init(jax.random.PRNGKey(0))
    data = random_dataset(256, HIDDEN)
    engine, _, loader, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                                    training_data=data, config_params=cfg)
    before = jax.device_get(engine.master_params)
    it = iter(loader)
    losses = []
    for _ in range(10):
        x, y = next(it)
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    after = jax.device_get(engine.master_params)
    assert all(np.isfinite(l) for l in losses)
    assert engine.optimizer.name == "lamb"
    moved = any(not np.allclose(a, b) for a, b in zip(jax.tree_util.tree_leaves(before),
                                                      jax.tree_util.tree_leaves(after)))
    assert moved, "LAMB step did not change parameters"


def test_scheduler_integration():
    cfg = simple_config(scheduler={"type": "WarmupLR",
                                   "params": {"warmup_min_lr": 0.0, "warmup_max_lr": 0.01,
                                              "warmup_num_steps": 10}})
    engine, _ = run_training(cfg, steps=5)
    lr_now = engine.get_lr()[0]
    assert 0 < lr_now <= 0.01


def test_gradient_clipping_runs():
    cfg = simple_config(gradient_clipping=0.1)
    engine, losses = run_training(cfg, steps=5)
    assert losses[-1] <= losses[0] * 1.5  # just needs to run stably


def test_eval_mode_no_grads():
    model = SimpleModel(HIDDEN)
    params = model.init(jax.random.PRNGKey(0))
    cfg = simple_config()
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                               training_data=random_dataset(16, HIDDEN),
                                               config_params=cfg)
    engine.eval()
    x, y = np.zeros((8, HIDDEN), np.float32), np.zeros((8, HIDDEN), np.float32)
    loss = engine(x, y)
    assert np.isfinite(float(jax.device_get(loss)))
    with pytest.raises(AssertionError):
        engine.backward(loss)


def test_zero_sharded_state_layout(eight_devices):
    """Stage >=1 must actually shard the optimizer state over the data axis."""
    hidden = 64  # 64x64 weights are above the min-shard size and divisible by dp=8
    cfg = simple_config(zero_optimization={"stage": 2})
    model = SimpleModel(hidden)
    params = model.init(jax.random.PRNGKey(0))
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                               training_data=random_dataset(16, hidden),
                                               config_params=cfg)
    sharded = engine.master_params["w1"].sharding
    assert not sharded.is_fully_replicated, "ZeRO>=1 master weights should be dp-sharded"
    opt_sharded = engine.opt_state.exp_avg["w1"].sharding
    assert not opt_sharded.is_fully_replicated, "ZeRO>=1 optimizer state should be dp-sharded"


def test_eval_forward_is_jitted_and_compiles_once():
    """eval() forwards must go through one cached jit (VERDICT r2 weak #3): op-by-op
    dispatch of a large model would make eval pathologically slow."""
    model = SimpleModel(HIDDEN)
    params = model.init(jax.random.PRNGKey(0))
    traces = []

    def model_fn(p, x, y):
        traces.append(1)
        return model.apply(p, x, y)

    engine, _, _, _ = deepspeed_tpu.initialize(model=model_fn, model_parameters=params,
                                               config_params=simple_config())
    engine.eval()
    x = np.random.default_rng(0).normal(size=(8, HIDDEN)).astype(np.float32)
    y = np.zeros((8, HIDDEN), np.float32)
    l1 = float(jax.device_get(engine(x, y)))
    l2 = float(jax.device_get(engine(x, y)))
    assert len(traces) == 1, f"eval forward retraced: {len(traces)} traces for 2 calls"
    assert abs(l1 - l2) < 1e-12
    # numerics match the un-jitted model
    ref = float(model.apply(params, jnp.asarray(x), jnp.asarray(y)))
    assert abs(l1 - ref) < 1e-5
