"""End-to-end engine tests on the 8-device virtual CPU mesh (reference test_fp16.py style)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from simple_model import SimpleModel, random_dataset, simple_config

HIDDEN = 16


def run_training(config, steps=10, hidden=HIDDEN, seed=0):
    model = SimpleModel(hidden)
    params = model.init(jax.random.PRNGKey(seed))
    data = random_dataset(256, hidden, seed=seed)
    engine, optimizer, loader, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, training_data=data, config_params=config)
    losses = []
    it = iter(loader)
    for _ in range(steps * engine.gradient_accumulation_steps()):
        x, y = next(it)
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    return engine, losses


@pytest.mark.parametrize("zero_stage", [0, 1, 2])
def test_zero_stage_training_loss_decreases(zero_stage):
    cfg = simple_config(zero_optimization={"stage": zero_stage})
    engine, losses = run_training(cfg, steps=20)
    assert losses[-1] < losses[0] * 0.9, f"loss did not decrease: {losses[0]} -> {losses[-1]}"
    assert engine.global_steps == 20


def test_zero_stages_agree():
    """Stages 0/1/2 are different layouts of the same math: losses must match closely."""
    results = {}
    for stage in [0, 1, 2]:
        cfg = simple_config(zero_optimization={"stage": stage})
        _, losses = run_training(cfg, steps=5, seed=3)
        results[stage] = losses
    for stage in [1, 2]:
        np.testing.assert_allclose(results[0], results[stage], rtol=2e-2)


def test_gradient_accumulation():
    cfg = simple_config(batch=16, gradient_accumulation_steps=2)
    engine, losses = run_training(cfg, steps=5)
    assert engine.gradient_accumulation_steps() == 2
    assert engine.global_steps == 5
    assert engine.micro_steps == 10


def test_grad_accum_equivalence():
    """grad_acc=2 at micro-batch 8 must match grad_acc=1 at batch 16 (same total batch)."""
    model = SimpleModel(HIDDEN)
    params = model.init(jax.random.PRNGKey(0))
    data = random_dataset(64, HIDDEN, seed=1)

    def run(cfg):
        p = jax.tree_util.tree_map(jnp.array, params)
        engine, _, loader, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=p, training_data=data, config_params=cfg)
        xs = np.stack([data[i][0] for i in range(16)])
        ys = np.stack([data[i][1] for i in range(16)])
        if engine.gradient_accumulation_steps() == 1:
            loss = engine(xs, ys)
            engine.backward(loss)
            engine.step()
        else:
            for half in range(2):
                x = xs[half * 8:(half + 1) * 8]
                y = ys[half * 8:(half + 1) * 8]
                loss = engine(x, y)
                engine.backward(loss)
                engine.step()
        return jax.device_get(engine.master_params)

    p_full = run(simple_config(batch=16))
    p_acc = run(simple_config(batch=16, gradient_accumulation_steps=2))
    jax.tree_util.tree_map(lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5),
                           p_full, p_acc)


def test_fp16_dynamic_loss_scale_init():
    cfg = simple_config(fp16={"enabled": True, "initial_scale_power": 8})
    engine, losses = run_training(cfg, steps=25)
    assert engine.fp16_enabled()
    assert engine.dynamic_loss_scale()
    assert losses[-1] < losses[0]


def test_fp16_static_loss_scale():
    cfg = simple_config(fp16={"enabled": True, "loss_scale": 128.0})
    engine, losses = run_training(cfg, steps=25)
    assert engine.loss_scale() == 128.0
    assert losses[-1] < losses[0]


def test_lamb_optimizer():
    """LAMB's trust ratio shrinks small-model updates; like the reference's lamb tests we
    check stable execution + that parameters actually move, not convergence speed."""
    cfg = simple_config(optimizer={"type": "Lamb", "params": {"lr": 2e-3}})
    model = SimpleModel(HIDDEN)
    params = model.init(jax.random.PRNGKey(0))
    data = random_dataset(256, HIDDEN)
    engine, _, loader, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                                    training_data=data, config_params=cfg)
    before = jax.device_get(engine.master_params)
    it = iter(loader)
    losses = []
    for _ in range(10):
        x, y = next(it)
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    after = jax.device_get(engine.master_params)
    assert all(np.isfinite(l) for l in losses)
    assert engine.optimizer.name == "lamb"
    moved = any(not np.allclose(a, b) for a, b in zip(jax.tree_util.tree_leaves(before),
                                                      jax.tree_util.tree_leaves(after)))
    assert moved, "LAMB step did not change parameters"


def test_scheduler_integration():
    cfg = simple_config(scheduler={"type": "WarmupLR",
                                   "params": {"warmup_min_lr": 0.0, "warmup_max_lr": 0.01,
                                              "warmup_num_steps": 10}})
    engine, _ = run_training(cfg, steps=5)
    lr_now = engine.get_lr()[0]
    assert 0 < lr_now <= 0.01


def test_gradient_clipping_runs():
    cfg = simple_config(gradient_clipping=0.1)
    engine, losses = run_training(cfg, steps=5)
    assert losses[-1] <= losses[0] * 1.5  # just needs to run stably


def test_eval_mode_no_grads():
    model = SimpleModel(HIDDEN)
    params = model.init(jax.random.PRNGKey(0))
    cfg = simple_config()
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                               training_data=random_dataset(16, HIDDEN),
                                               config_params=cfg)
    engine.eval()
    x, y = np.zeros((8, HIDDEN), np.float32), np.zeros((8, HIDDEN), np.float32)
    loss = engine(x, y)
    assert np.isfinite(float(jax.device_get(loss)))
    with pytest.raises(AssertionError):
        engine.backward(loss)


def test_zero_sharded_state_layout(eight_devices):
    """Stage >=1 must actually shard the optimizer state over the data axis."""
    hidden = 64  # 64x64 weights are above the min-shard size and divisible by dp=8
    cfg = simple_config(zero_optimization={"stage": 2})
    model = SimpleModel(hidden)
    params = model.init(jax.random.PRNGKey(0))
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                               training_data=random_dataset(16, hidden),
                                               config_params=cfg)
    sharded = engine.master_params["w1"].sharding
    assert not sharded.is_fully_replicated, "ZeRO>=1 master weights should be dp-sharded"
    opt_sharded = engine.opt_state.exp_avg["w1"].sharding
    assert not opt_sharded.is_fully_replicated, "ZeRO>=1 optimizer state should be dp-sharded"


def test_zero_sharded_fraction_reported(eight_devices):
    """VERDICT r3 #9: the engine must account what fraction of master/optimizer bytes
    actually sharded, and flagship-shaped configs must exceed 90% (GPT-2-like dims
    divisible by dp; a user should never silently run 'ZeRO-2' mostly replicated)."""
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
    import jax.numpy as jnp

    cfg = GPT2Config(vocab_size=512, n_layer=2, n_head=4, n_embd=128, n_positions=128,
                     compute_dtype=jnp.float32)
    model = GPT2Model(cfg)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.init(jax.random.PRNGKey(0)),
        config_params=simple_config(zero_optimization={"stage": 2}))
    assert engine._zero_sharded_fraction is not None
    assert engine._zero_sharded_fraction > 0.9, engine._zero_sharded_fraction

    # tiny awkward shapes (all leaves under min_size): fraction reported, clearly low
    small = SimpleModel(8)
    engine2, _, _, _ = deepspeed_tpu.initialize(
        model=small, model_parameters=small.init(jax.random.PRNGKey(1)),
        config_params=simple_config(zero_optimization={"stage": 2}))
    assert engine2._zero_sharded_fraction is not None
    assert engine2._zero_sharded_fraction < 0.5


def test_eval_forward_is_jitted_and_compiles_once():
    """eval() forwards must go through one cached jit (VERDICT r2 weak #3): op-by-op
    dispatch of a large model would make eval pathologically slow."""
    model = SimpleModel(HIDDEN)
    params = model.init(jax.random.PRNGKey(0))
    traces = []

    def model_fn(p, x, y):
        traces.append(1)
        return model.apply(p, x, y)

    engine, _, _, _ = deepspeed_tpu.initialize(model=model_fn, model_parameters=params,
                                               config_params=simple_config())
    engine.eval()
    x = np.random.default_rng(0).normal(size=(8, HIDDEN)).astype(np.float32)
    y = np.zeros((8, HIDDEN), np.float32)
    l1 = float(jax.device_get(engine(x, y)))
    l2 = float(jax.device_get(engine(x, y)))
    assert len(traces) == 1, f"eval forward retraced: {len(traces)} traces for 2 calls"
    assert abs(l1 - l2) < 1e-12
    # numerics match the un-jitted model
    ref = float(model.apply(params, jnp.asarray(x), jnp.asarray(y)))
    assert abs(l1 - ref) < 1e-5


def test_external_master_optimizer(tmp_path):
    """A client (init, apply) pair marked external_master owns its parameter state:
    the engine keeps the fp32 master as host numpy (zero HBM), the update touches
    only opt_state, and compute params are NOT re-derived (VERDICT r3 #2 — this is
    how the 1.5B bench emulates one ZeRO-2 rank without the dp=1 master burden)."""
    import jax.numpy as jnp

    def init(master):
        n = sum(l.size for l in jax.tree_util.tree_leaves(master))
        return {"shard": jnp.zeros((n // 4,), jnp.float32)}

    def apply(grads, state, master, step, hyper):
        g = jnp.concatenate([x.reshape(-1) for x in jax.tree_util.tree_leaves(grads)])
        return master, {"shard": state["shard"] - hyper["lr"] * g[: state["shard"].size]}

    apply.external_master = True

    model = SimpleModel(HIDDEN)
    params = model.init(jax.random.PRNGKey(0))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, optimizer=(init, apply),
        config_params=simple_config(zero_optimization={"stage": 2},
                                    zero_allow_untested_optimizer=True))
    assert engine._external_master
    # no separate master storage exists: master_params is a derived fp32 view of
    # the compute params (zero extra HBM — the whole point at dp=1/1.5B)
    assert not hasattr(engine, "_master_params_store")
    jax.tree_util.tree_map(
        lambda m, p: np.testing.assert_allclose(np.asarray(jax.device_get(m)),
                                                np.asarray(jax.device_get(p), np.float32),
                                                rtol=1e-6),
        engine.master_params, engine.params)
    before_master = jax.device_get(engine.master_params)
    before_params = jax.device_get(engine.params)
    shard0 = np.asarray(jax.device_get(engine.opt_state["shard"]))

    x = np.random.default_rng(0).normal(size=(8, HIDDEN)).astype(np.float32)
    for _ in range(2):
        loss = engine(x, np.tanh(x))
        engine.backward(loss)
        engine.step()
    assert engine.global_steps == 2
    # opt state moved; master view and compute params did not (the optimizer owns them)
    assert np.abs(np.asarray(jax.device_get(engine.opt_state["shard"])) - shard0).max() > 0
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        jax.device_get(engine.master_params), before_master)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        jax.device_get(engine.params), before_params)

    # checkpoint roundtrip: the optimizer-owned shard survives; no master storage
    shard_now = np.asarray(jax.device_get(engine.opt_state["shard"]))
    engine.save_checkpoint(str(tmp_path))
    engine.load_checkpoint(str(tmp_path))
    np.testing.assert_allclose(np.asarray(jax.device_get(engine.opt_state["shard"])),
                               shard_now, rtol=1e-6)
    assert not hasattr(engine, "_master_params_store")


def test_external_master_unfused_accumulation_and_rotation_contract():
    """gas>1 external-master engines use the two-jit path (accumulated grads ->
    apply_update_ext); at gas==1 the fused step enforces strict
    forward/backward/step rotation."""
    import jax.numpy as jnp

    def init(master):
        n = sum(l.size for l in jax.tree_util.tree_leaves(master))
        return {"shard": jnp.zeros((n // 4,), jnp.float32)}

    def apply(grads, state, master, step, hyper):
        g = jnp.concatenate([x.reshape(-1) for x in jax.tree_util.tree_leaves(grads)])
        return master, {"shard": state["shard"] - hyper["lr"] * g[: state["shard"].size]}

    apply.external_master = True
    model = SimpleModel(HIDDEN)
    x = np.random.default_rng(1).normal(size=(8, HIDDEN)).astype(np.float32)

    # gas = 2: unfused (grad accumulation needs materialized grads)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.init(jax.random.PRNGKey(0)),
        optimizer=(init, apply),
        config_params=simple_config(batch=16, gradient_accumulation_steps=2))
    assert engine._run_fused_step is None
    shard0 = np.asarray(jax.device_get(engine.opt_state["shard"]))
    for _ in range(2):
        loss = engine(x, np.tanh(x))
        engine.backward(loss)
        engine.step()
    assert engine.global_steps == 1
    assert np.abs(np.asarray(jax.device_get(engine.opt_state["shard"])) - shard0).max() > 0

    # gas = 1: fused; a second forward before step() must fail loudly
    engine2, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.init(jax.random.PRNGKey(0)),
        optimizer=(init, apply), config_params=simple_config())
    assert engine2._run_fused_step is not None
    engine2(x, np.tanh(x))
    with pytest.raises(RuntimeError, match="rotation"):
        engine2(x, np.tanh(x))


def test_fused_step_config_matches_two_jit_path():
    """{"fused_step": true}: the standard engine's single-jit step must produce the
    SAME losses and master weights as the two-jit step — including fp16 overflow
    skip behavior — and enforce the rotation contract."""
    model = SimpleModel(HIDDEN)
    data = random_dataset(64, HIDDEN, seed=5)
    results = {}
    for fused in (False, True):
        params = model.init(jax.random.PRNGKey(2))
        cfg = simple_config(fused_step=fused)
        engine, _, loader, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=params, training_data=data,
            config_params=cfg)
        assert (engine._run_fused_step is not None) == fused
        it = iter(loader)
        losses = []
        for _ in range(6):
            x, y = next(it)
            loss = engine(x, y)
            engine.backward(loss)
            engine.step()
            losses.append(float(jax.device_get(loss)))
        results[fused] = (losses, jax.device_get(engine.master_params))
    np.testing.assert_allclose(results[True][0], results[False][0], rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7),
        results[True][1], results[False][1])


def test_fused_step_fp16_overflow_parity():
    """Overflow under the fused step must skip the master update, halve the scale,
    and count a skipped step — exactly like the two-jit path."""
    model = SimpleModel(HIDDEN)
    params = model.init(jax.random.PRNGKey(0))
    cfg = simple_config(fused_step=True,
                        fp16={"enabled": True, "loss_scale": 0,
                              "initial_scale_power": 4, "hysteresis": 1})
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                               config_params=cfg)
    assert engine._run_fused_step is not None
    s0 = float(engine.loss_scale())
    before = jax.device_get(engine.master_params)
    x = np.ones((8, HIDDEN), np.float32)
    y = np.full((8, HIDDEN), 1e30, np.float32)  # cotangents overflow fp16
    loss = engine(x, y)
    engine.backward(loss)
    engine.step()
    assert engine.skipped_steps == 1
    assert float(engine.loss_scale()) == s0 / 2
    jax.tree_util.tree_map(lambda a, b: np.testing.assert_array_equal(a, b),
                           jax.device_get(engine.master_params), before)
