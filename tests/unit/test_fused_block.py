"""Parity tests for the fused Pallas transformer-block kernel
(ops/pallas/fused_block.py): interpret-mode forward vs the jnp reference,
gradient equality (the custom_vjp backward IS the reference vjp), and
model-level equivalence of GPT2Config(fused_block=True) against the unfused
block."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.pallas.fused_block import (
    fused_block_reference, fused_transformer_block)

B, T, E, H = 2, 64, 32, 4


@pytest.fixture(scope="module")
def operands():
    rng = np.random.RandomState(0)

    def mk(shape, scale=0.05):
        return jnp.asarray(rng.randn(*shape) * scale, jnp.float32)

    return {
        "x": mk((B, T, E), 1.0),
        "ln_scale": jnp.ones((E,), jnp.float32) + mk((E,)),
        "ln_bias": mk((E,)),
        "w_qkv": mk((E, 3 * E)),
        "b_qkv": mk((3 * E,)),
        "w_proj": mk((E, E)),
        "b_proj": mk((E,)),
    }


def _args(ops):
    return (ops["x"], ops["ln_scale"], ops["ln_bias"], ops["w_qkv"],
            ops["b_qkv"], ops["w_proj"], ops["b_proj"])


@pytest.mark.parametrize("causal", [True, False])
def test_forward_matches_reference(operands, causal):
    out = fused_transformer_block(*_args(operands), H, causal=causal,
                                  block_q=16)
    ref = fused_block_reference(*_args(operands), H, causal=causal)
    assert out.shape == ref.shape and out.dtype == ref.dtype
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_forward_matches_reference_under_jit(operands):
    fn = jax.jit(lambda x: fused_transformer_block(
        x, *_args(operands)[1:], H, block_q=16))
    ref = fused_block_reference(*_args(operands), H)
    np.testing.assert_allclose(np.asarray(fn(operands["x"])),
                               np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_block_q_is_fit_to_sequence(operands):
    # T=64 is not divisible by the 256 default: the wrapper must shrink it
    out = fused_transformer_block(*_args(operands), H)  # block_q=None
    ref = fused_block_reference(*_args(operands), H)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_gradients_equal_reference_gradients(operands):
    # the custom_vjp backward differentiates the reference at the saved
    # primals, so grads must match the unfused block's almost exactly
    def loss_fused(x, w_qkv, w_proj):
        ops = dict(operands, x=x, w_qkv=w_qkv, w_proj=w_proj)
        return jnp.sum(fused_transformer_block(*_args(ops), H, block_q=16) ** 2)

    def loss_ref(x, w_qkv, w_proj):
        ops = dict(operands, x=x, w_qkv=w_qkv, w_proj=w_proj)
        return jnp.sum(fused_block_reference(*_args(ops), H) ** 2)

    g = jax.grad(loss_fused, argnums=(0, 1, 2))(
        operands["x"], operands["w_qkv"], operands["w_proj"])
    r = jax.grad(loss_ref, argnums=(0, 1, 2))(
        operands["x"], operands["w_qkv"], operands["w_proj"])
    for gi, ri in zip(g, r):
        np.testing.assert_allclose(np.asarray(gi), np.asarray(ri),
                                   rtol=1e-4, atol=1e-5)


def test_bf16_forward(operands):
    xb = operands["x"].astype(jnp.bfloat16)
    ops = dict(operands, x=xb)
    out = fused_transformer_block(*_args(ops), H, block_q=16)
    ref = fused_block_reference(*_args(ops), H)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=0.05, atol=0.05)


# ---------------------------------------------------------------------------
# model-level parity: GPT2Config(fused_block=True) vs the unfused block
# ---------------------------------------------------------------------------

def _tiny_model(fused):
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
    cfg = GPT2Config(vocab_size=97, n_positions=T, n_embd=E, n_layer=2,
                     n_head=H, loss_chunk=0, compute_dtype=jnp.float32,
                     fused_block=fused)
    return GPT2Model(cfg)


def test_gpt2_fused_block_matches_unfused():
    fused = _tiny_model(True)
    plain = _tiny_model(False)
    params = plain.init(jax.random.PRNGKey(0))
    tokens = jnp.asarray(
        np.random.RandomState(1).randint(0, 97, (2, T)), jnp.int32)
    lf = fused.logits(params, tokens)
    lp = plain.logits(params, tokens)
    np.testing.assert_allclose(np.asarray(lf), np.asarray(lp),
                               rtol=2e-4, atol=2e-4)


def test_gpt2_fused_block_loss_grads_match_unfused():
    fused = _tiny_model(True)
    plain = _tiny_model(False)
    params = plain.init(jax.random.PRNGKey(0))
    rs = np.random.RandomState(2)
    tokens = jnp.asarray(rs.randint(0, 97, (2, T)), jnp.int32)
    labels = jnp.asarray(rs.randint(0, 97, (2, T)), jnp.int32)
    gf = jax.grad(lambda p: fused.apply(p, tokens, labels))(params)
    gp = jax.grad(lambda p: plain.apply(p, tokens, labels))(params)
    flat_f, _ = jax.tree_util.tree_flatten(gf)
    flat_p, _ = jax.tree_util.tree_flatten(gp)
    for a, b in zip(flat_f, flat_p):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-5)


def test_fused_block_rejects_dropout_config():
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
    with pytest.raises(AssertionError, match="fused_block"):
        GPT2Model(GPT2Config(n_embd=E, n_layer=1, n_head=H, dropout=0.1,
                             fused_block=True))
